"""CT802 positive: a flag declared but never read anywhere, and a
namespace attribute read but never declared."""
import argparse


def build_parser():
    parser = argparse.ArgumentParser()
    parser.add_argument("--log-steps", type=int, default=10)
    parser.add_argument("--dead-knob", type=float, default=0.5)
    return parser


def main():
    args = build_parser().parse_args()
    print(args.log_steps, args.warmup_steps)
