"""DN701 negative: the rebind idiom (the call's own assignment replaces
the donated name), a Store before any later read, and donated arguments
that are not bare names."""
import jax


def train_step(state, batch):
    return state, {"loss": 0.0}


step = jax.jit(train_step, donate_argnums=(0,))


def run(state, batches):
    metrics = None
    for batch in batches:
        state, metrics = step(state, batch)
    return state, metrics


def run_reset(state, batch, fresh):
    out, metrics = step(state, batch)
    state = fresh  # re-assigned before any read: hazard cleared
    return out, metrics, state


def run_attr(holder, batch):
    out, metrics = step(holder.state, batch)
    return out, metrics
