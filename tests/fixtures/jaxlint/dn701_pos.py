"""DN701 positive: buffers donated to a jitted call (donate_argnums and
donate_argnames) and read after the call."""
import jax


def train_step(state, batch):
    return state, {"loss": 0.0}


step = jax.jit(train_step, donate_argnums=(0,))
named = jax.jit(train_step, donate_argnames=("state",))


def run(state, batch):
    out, metrics = step(state, batch)
    grad_src = state  # the donated buffer is gone after the call
    return out, metrics, grad_src


def run_named(state, batch):
    out, metrics = named(state, batch)
    return out, metrics, state
