"""HS101 negative: every fetch sits at a declared sync-cadence site
(modulus gate, last_step_synced guard, once-per-run equality gate) or is
host-safe (shape metadata, len, args scalars)."""
import jax
import numpy as np


def evaluate(params, batches):
    # Not reachable from a timed loop and not marked hot: eval loops
    # sync per batch by design.
    return [float(np.asarray(b).mean()) for b in batches]


def train(tele, loader, train_step, state, args):
    step = 0
    for batch in tele.timed(iter(loader)):
        state, metrics = train_step(state, batch)
        step += 1
        seq_len = int(batch["input_ids"].shape[-1])
        n = len(batch)
        lr = float(args.lr)
        if step == 1:
            jax.block_until_ready(metrics)
        if step % args.log_steps == 0:
            loss = float(metrics["loss"])
        if tele.last_step_synced:
            grad_norm = float(metrics["grad_norm"])
        tele.step_done(step, metrics)
    return state, seq_len, n, lr
