"""HS101 positive: blocking host fetches inside a tele.timed step loop,
including one reached through same-module call propagation and one in a
# jaxlint: hot marked function."""
import jax
import numpy as np


def fetch_norm(metrics):
    # Reached from the hot loop below by bare-name call: hot by
    # propagation.
    return metrics["grad_norm"].item()


# jaxlint: hot
def consume_outputs(outputs):
    return np.asarray(outputs)


def train(tele, loader, train_step, state):
    losses = []
    for batch in tele.timed(iter(loader)):
        state, metrics = train_step(state, batch)
        tele.step_done(1, metrics)
        losses.append(float(metrics["loss"]))
        grad_norm = fetch_norm(metrics)
        host = jax.device_get(metrics)
    return state, losses, grad_norm, host
