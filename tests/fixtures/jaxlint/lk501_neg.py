"""LK501 negative: every access outside __init__ holds the declared
lock (and __init__ itself is implicitly allowed — no second thread can
hold a reference yet)."""
import threading


class Gauges:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def read(self):
        with self._lock:
            return self.count
