"""LK501 positive (with the test registry): `count` is declared guarded
by `_lock`, but read() touches it bare — exactly the lock-free gauge
read the serve stack shipped twice."""
import threading


class Gauges:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def read(self):
        return self.count
