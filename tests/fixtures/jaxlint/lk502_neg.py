"""LK502 negative: the frozen binding is assigned once in __init__;
reads from any thread are fine."""


class Emitter:
    def __init__(self, sink):
        self.sink = sink

    def emit(self, record):
        self.sink.write(record)
