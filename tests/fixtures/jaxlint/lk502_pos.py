"""LK502 positive (with the test registry): `sink` is declared frozen —
shared across threads through a stable binding — but reset() rebinds
it, racing every reader."""


class Emitter:
    def __init__(self, sink):
        self.sink = sink

    def reset(self, sink):
        self.sink = sink

    def emit(self, record):
        self.sink.write(record)
