"""LK503 negative: the producer communicates only through the Queue
(internally synchronized); the confined gauges stay consumer-side."""
import queue
import threading


class Prefetcher:
    def __init__(self):
        self._queue = queue.Queue(2)
        self._stats = {"batches": 0}
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def _worker(self):
        while True:
            self._queue.put(object())

    def __next__(self):
        item = self._queue.get()
        self._stats["batches"] += 1
        return item

    def snapshot(self):
        return dict(self._stats)
