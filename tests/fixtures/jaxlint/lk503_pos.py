"""LK503 positive (with the test registry): `_stats` is confined to the
consumer thread, but the producer thread target `_worker` mutates it."""
import queue
import threading


class Prefetcher:
    def __init__(self):
        self._queue = queue.Queue(2)
        self._stats = {"batches": 0}
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def _worker(self):
        while True:
            self._queue.put(object())
            self._stats["batches"] += 1

    def snapshot(self):
        return dict(self._stats)
