"""RC201 negative: hashable statics (tuple literal, module constant,
plain name) and collections at DYNAMIC positions are fine."""
import jax

MODES = ("train", "eval")


def forward(x, cfg):
    return x


g = jax.jit(forward, static_argnames=("cfg",))
plain = jax.jit(forward)


def call(x, cfg_obj):
    a = g(x, cfg=(1, 2, 3))
    b = g(x, cfg=MODES)
    c = g(x, cfg=cfg_obj)
    d = plain(x, [1, 2, 3])  # dynamic position: a list is just a pytree
    return a, b, c, d
