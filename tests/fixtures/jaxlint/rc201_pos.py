"""RC201 positive: collection literals passed at jit static positions
(by static_argnames keyword and by static_argnums position)."""
import jax


def forward(x, cfg):
    return x


def forward2(x, dims):
    return x


g = jax.jit(forward, static_argnames=("cfg",))
h = jax.jit(forward2, static_argnums=(1,))


def call(x):
    a = g(x, cfg=[1, 2, 3])
    b = h(x, {"hidden": 4})
    return a, b
