"""RC202 negative: ALL_CAPS module constants are declared immutable by
convention; locals shadowing the global name are fine; non-jitted
functions may read module state freely."""
import jax

SCALE_TABLE = {"s": 2.0}
_mutable_cache = {}


@jax.jit
def apply_scale(x):
    return x * SCALE_TABLE["s"]


@jax.jit
def shadowed(x):
    _mutable_cache = {"local": True}
    return x, _mutable_cache


def host_side(x):
    _mutable_cache["x"] = x
    return _mutable_cache
