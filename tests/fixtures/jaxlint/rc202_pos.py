"""RC202 positive: jitted functions (decorated, wrapped, lambda) closing
over lowercase module-level mutable state."""
import jax

_scale_table = {}
_history = []


@jax.jit
def apply_scale(x):
    return x * _scale_table["s"]


def step(x):
    return x + len(_history)


step_jit = jax.jit(step)

identity = jax.jit(lambda xs: (xs, _scale_table))
