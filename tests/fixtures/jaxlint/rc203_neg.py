"""RC203 negative: numeric literals at DYNAMIC positions trace as
weak-typed arrays (no per-value recompile); string/bool statics are
small-cardinality mode flags."""
import jax


def scaled(x, factor, mode="train"):
    return x * factor


g = jax.jit(scaled, static_argnames=("mode",))
plain = jax.jit(scaled)


def call(x, n):
    a = plain(x, 0.5)
    b = g(x, 0.5, mode="eval")
    c = g(x, n)
    return a, b, c
