"""RC203 positive: numeric Python literals at jit static positions —
each distinct value compiles a fresh executable."""
import jax


def scaled(x, factor):
    return x * factor


g = jax.jit(scaled, static_argnums=(1,))


def call(x):
    return g(x, 0.5)
