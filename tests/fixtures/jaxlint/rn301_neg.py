"""RN301 negative: proper key hygiene — split before every draw,
fold_in for derived streams (non-consuming), per-branch single use."""
import jax


def sample(shape):
    key = jax.random.PRNGKey(0)
    key, a_key, b_key = jax.random.split(key, 3)
    a = jax.random.normal(a_key, shape)
    b = jax.random.uniform(b_key, shape)
    return a, b


def loop(n):
    key = jax.random.PRNGKey(1)
    out = []
    for i in range(n):
        key, sub = jax.random.split(key)
        out.append(jax.random.normal(sub, ()))
    return out


def folded(base_key, steps):
    # fold_in derives an independent stream per step without consuming
    # the base key.
    return [jax.random.normal(jax.random.fold_in(base_key, i), ())
            for i in range(steps)]


def branches(flag, shape):
    key = jax.random.PRNGKey(2)
    if flag:
        return jax.random.normal(key, shape)
    else:
        return jax.random.uniform(key, shape)
