"""RN301 positive: the same key drawn from twice (identical randomness),
and a key created outside a loop consumed inside it (same dropout mask
every iteration)."""
import jax


def sample(shape):
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, shape)
    b = jax.random.uniform(key, shape)
    return a, b


def loop(n):
    key = jax.random.PRNGKey(1)
    out = []
    for _ in range(n):
        out.append(jax.random.normal(key, ()))
    return out
