"""RN302 negative: seeds from config/arguments, with fold_in for derived
per-step streams; clock calls used for TIMING are not seeds."""
import time

import jax
import numpy as np


def make_key(args):
    return jax.random.PRNGKey(args.seed)


def make_rng(seed):
    return np.random.default_rng(seed)


def timed_draw(key, shape):
    t0 = time.perf_counter()
    out = jax.random.normal(key, shape)
    return out, time.perf_counter() - t0
