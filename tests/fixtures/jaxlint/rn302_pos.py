"""RN302 positive: seeds derived from wall-clock time — two processes
started in the same second share a stream, and no run can be replayed."""
import time

import jax
import numpy as np


def make_key():
    return jax.random.PRNGKey(int(time.time()))


def make_rng():
    return np.random.default_rng(time.time_ns())
