"""SD601 negative: registered mesh axes (through a local constant),
shard_map-declared manual axes (wrapped by name, decorator spelling,
inline lambda), and dynamic axis names are all allowed."""
from functools import partial

import jax
from jax.experimental.shard_map import shard_map

AXIS_DATA = "data"


def global_mean(x):
    return jax.lax.pmean(x, AXIS_DATA)


def stage_body(x):
    # Declared by the shard_map in build() below, which wraps this
    # function by name.
    return jax.lax.psum(x, "stage")


def build(mesh, specs):
    return shard_map(stage_body, mesh=mesh, axis_names={"stage"},
                     in_specs=specs, out_specs=specs)


@partial(shard_map, mesh=None, axis_names={"ring"}, in_specs=None,
         out_specs=None)
def rotate(x):
    return jax.lax.ppermute(x, "ring", perm=[(0, 1)])


def build_inline(mesh, specs):
    return shard_map(lambda x: jax.lax.psum(x, "stage"), mesh=mesh,
                     axis_names={"stage"}, in_specs=specs, out_specs=specs)


def dynamic(x, axis):
    # A computed axis name is out of this tier's reach: proven statically
    # knowable or skipped, never guessed.
    return jax.lax.psum(x, axis)


# A lambda PARAMETER is dynamic too — it must shadow the module-level
# constant of the same name, not resolve through it (regression: the
# parameter check used to skip lambdas).
axis = "typo"
dynamic_lambda = lambda x, axis: jax.lax.psum(x, axis)
