"""SD601 positive: collectives over axis names that are neither
registered mesh axes (analysis/axes.py) nor declared by any enclosing
shard_map/pmap scope."""
import jax


def logical_mean(x):
    # 'batch' is a LOGICAL axis name, not a mesh axis: pmean over it
    # traces fine and fails only under a mesh that exercises the path.
    return jax.lax.pmean(x, "batch")


def typo_sum(x):
    total = jax.lax.psum(x, axis_name="dta")
    return total
