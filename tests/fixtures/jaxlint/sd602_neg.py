"""SD602 negative: fully covered logical names (direct, via the axes
keywords, via a module constant) and mesh-axis PartitionSpecs; dynamic
specs are skipped."""
import flax.linen as nn
from jax.sharding import PartitionSpec

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
EMBED_AXES = ("embed",)


def make_param(dense, kernel_init):
    init = nn.with_logical_partitioning(kernel_init, ("batch", "heads"))
    layer = dense(kernel_axes=EMBED_AXES, bias_axes=("mlp",))
    return init, layer


def make_spec():
    return PartitionSpec((AXIS_DATA, AXIS_FSDP), None)


def dynamic_spec(names):
    return PartitionSpec(*names)
