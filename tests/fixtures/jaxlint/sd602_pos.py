"""SD602 positive: a logical name with no rule under every declared
strategy (it would silently replicate), and a PartitionSpec axis that is
no mesh axis."""
import flax.linen as nn
from jax.sharding import PartitionSpec


def make_param(kernel_init):
    # 'hidden_bad' has no rule in any strategy's table: under fsdp it
    # silently replicates instead of sharding — the ZeRO bug class.
    init = nn.with_logical_partitioning(kernel_init, ("hidden_bad", "mlp"))
    return init


def make_spec():
    # 'dta' (typo'd 'data') only raises once a mesh is attached.
    return PartitionSpec("dta", None)
