"""SD603 negative: the same sites spelled through the parallel/mesh
AXIS_* constants, plus a non-axis string in an ordinary position."""
import jax
from jax.sharding import PartitionSpec

from bert_pytorch_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_PIPE


def global_sum(x):
    return jax.lax.psum(x, AXIS_DATA)


def batch_spec():
    return PartitionSpec((AXIS_DATA, AXIS_FSDP))


def stage_count(mesh):
    return mesh.shape[AXIS_PIPE]


def tag(kind="data_loader"):
    # An arbitrary string that merely CONTAINS an axis spelling in a
    # non-axis position is not a site.
    return kind
