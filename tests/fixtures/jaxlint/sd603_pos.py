"""SD603 positive: raw mesh-axis string literals outside parallel/ —
collective axis args, PartitionSpec entries, mesh.shape lookups, and
axis-named parameter defaults (5 sites)."""
import jax
from jax.sharding import PartitionSpec


def global_sum(x):
    return jax.lax.psum(x, "data")


def batch_spec():
    return PartitionSpec(("data", "fsdp"))


def stage_count(mesh):
    return mesh.shape["pipe"]


def rotate(x, seq_axis="seq"):
    return x, seq_axis
