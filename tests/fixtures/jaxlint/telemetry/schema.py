"""Mini telemetry schema for the CT801 fixtures. This is a CONTEXT
module (tests pass it through ``run_files(context_paths=...)``): CT801
reads ``KIND_REQUIRED_KEYS`` by parsing whatever ``telemetry/schema.py``
the program holds — never by importing it — so the fixtures bring their
own registry instead of coupling to the real one."""

KIND_REQUIRED_KEYS = {
    "train_window": ("step", "loss"),
    "fault": ("kind",),
}
