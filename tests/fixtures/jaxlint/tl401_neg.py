"""TL401 negative: state leaves jit through return values; non-jitted
methods may cache on self; constant flag assignments are config, not
tracer leaks."""
import jax


class Model:
    @jax.jit
    def step(self, x):
        y = x * 2
        self.compiled = True  # constant: a flag, not a traced value
        return y

    def cache_result(self, x):
        # Host-side method, not traced: caching is fine here.
        self.cache = self.step(x)
        return self.cache


@jax.jit
def accum(x, total):
    return x, total + x
