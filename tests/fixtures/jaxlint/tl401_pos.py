"""TL401 positive: traced values stored on self / a global inside jitted
functions — the stored tracer is stale after the first trace."""
import jax

_last_loss = None


class Model:
    @jax.jit
    def step(self, x):
        y = x * 2
        self.cache = y
        return y


@jax.jit
def accum(x):
    global _last_loss
    _last_loss = x * 0.5
    return x
