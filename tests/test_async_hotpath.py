"""Async hot-path suite (ISSUE 6; docs/telemetry.md "async the hot path").

Covers the three overlapped phases end to end on CPU:

* async checkpointing — per-directory pending-save keying, device-snapshot
  donation safety, and the acceptance comparison: with a deliberately
  large injected state, checkpoint-step p95 collapses from a multiple of
  the steady-state step p95 (blocking writes) to within 20% of it (async
  writes), gated through the telemetry-report regression path by name;
* double-buffered device prefetch — a fast producer drives data_wait p50
  to ~0, a slow producer still attributes the stall to data_wait, and a
  slow staging function reports as the h2d_wait sub-phase (always <= the
  data_wait it is part of — the schema lint invariant);
* overlapped data-parallel gradients — the bucketed explicit-psum step
  (pretrain.make_train_step(overlap_grad_buckets=True)) is numerically
  identical to the implicit-reduction step at fp32 tolerance.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from bert_pytorch_tpu.data.device_prefetch import DevicePrefetcher
from bert_pytorch_tpu.telemetry import schema as tschema
from bert_pytorch_tpu.telemetry import report as treport
from bert_pytorch_tpu.telemetry.runner import TrainTelemetry
from bert_pytorch_tpu.telemetry.step_timer import StepTimer
from bert_pytorch_tpu.utils import checkpoint as ckpt
from bert_pytorch_tpu.utils.logging import JSONLHandler


# ---------------------------------------------------------------------------
# async checkpointing: pending-save registry + device snapshot


def test_pending_saves_keyed_per_directory(tmp_path, monkeypatch):
    """Two save targets in one process must not share a pending slot: a
    wait on one directory leaves the other's write untouched, and a
    failure surfaces for its own directory only."""
    import threading

    dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
    release_b = threading.Event()
    real_write = ckpt._write_and_prune

    def gated_write(state, output_dir, step, keep):
        if output_dir == dir_b:
            assert release_b.wait(10.0)
        real_write(state, output_dir, step, keep)

    monkeypatch.setattr(ckpt, "_write_and_prune", gated_write)
    state = {"model": {"w": np.ones((8,), np.float32)}}
    ckpt.save_checkpoint(dir_a, 1, state, async_write=True)
    ckpt.save_checkpoint(dir_b, 2, state, async_write=True)
    # Joining A must complete without B's gate ever opening.
    ckpt.wait_for_pending_save(dir_a)
    assert ckpt.find_resume_step(dir_a) == 1
    assert ckpt.find_resume_step(dir_b) is None  # still gated
    release_b.set()
    ckpt.wait_for_pending_save()  # joins ALL remaining
    assert ckpt.find_resume_step(dir_b) == 2


def test_pending_save_error_stays_with_its_directory(tmp_path, monkeypatch):
    dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
    real_write = ckpt._write_and_prune

    def failing_for_a(state, output_dir, step, keep):
        if output_dir == dir_a:
            raise OSError("disk full")
        real_write(state, output_dir, step, keep)

    monkeypatch.setattr(ckpt, "_write_and_prune", failing_for_a)
    state = {"model": {"w": np.ones((8,), np.float32)}}
    ckpt.save_checkpoint(dir_a, 1, state, async_write=True)
    ckpt.save_checkpoint(dir_b, 1, state, async_write=True)
    ckpt.wait_for_pending_save(dir_b)  # B is healthy: no raise
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        ckpt.wait_for_pending_save(dir_a)
    ckpt.wait_for_pending_save()  # error consumed; all joined


def test_failed_async_write_does_not_block_emergency_save(tmp_path,
                                                          monkeypatch):
    """A stale periodic-write failure must not cost the CURRENT state:
    the next (emergency) sync save writes its checkpoint FIRST, then
    re-raises the background failure — durability before diagnostics
    (docs/fault_tolerance.md)."""
    real_write = ckpt._write_and_prune

    def failing_once(state, output_dir, step, keep):
        if step == 1:
            raise OSError("disk full")
        real_write(state, output_dir, step, keep)

    monkeypatch.setattr(ckpt, "_write_and_prune", failing_once)
    state = {"model": {"w": np.ones((8,), np.float32)}}
    ckpt.save_checkpoint(str(tmp_path), 1, state, async_write=True)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        ckpt.save_checkpoint(str(tmp_path), 2, state)  # emergency: sync
    # The raise reported the OLD failure; the NEW state landed anyway.
    assert ckpt.find_resume_step(str(tmp_path), verify=True) == 2


def test_async_snapshot_survives_donated_device_buffers(tmp_path):
    """The tentpole invariant: save_checkpoint(async_write=True) returns
    after a DEVICE-side snapshot, so the train loop may immediately donate
    the live buffers to the next step without corrupting the write."""
    import jax
    import jax.numpy as jnp

    state = {"model": {"w": jnp.full((64, 64), 3.0)}, "epoch": 5}
    ckpt.save_checkpoint(str(tmp_path), 7, state, async_write=True)
    # Donate-and-overwrite the source buffer, as the next train step does.
    bump = jax.jit(lambda t: jax.tree_util.tree_map(lambda x: x * -1.0, t),
                   donate_argnums=0)
    mutated = bump(state["model"])
    jax.block_until_ready(mutated)
    ckpt.wait_for_pending_save(str(tmp_path))
    loaded = ckpt.load_checkpoint(ckpt.checkpoint_path(str(tmp_path), 7))
    np.testing.assert_array_equal(loaded["model"]["w"],
                                  np.full((64, 64), 3.0))
    assert int(loaded["epoch"]) == 5


# ---------------------------------------------------------------------------
# device prefetch: data_wait attribution


def _drive_loop(tmp_path, producer_sleep_s, stage_sleep_s, consumer_sleep_s,
                n_items=12, window=10, depth=2):
    """Run a synthetic loop through TrainTelemetry.timed with an attached
    DevicePrefetcher; return the step_window records (schema-validated)."""
    jsonl = str(tmp_path / "telemetry.jsonl")

    def source():
        for i in range(n_items):
            if producer_sleep_s:
                time.sleep(producer_sleep_s)
            yield {"x": np.full((4,), i)}

    def stage(item):
        if stage_sleep_s:
            time.sleep(stage_sleep_s)
        return item

    tele = TrainTelemetry(jsonl_path=jsonl, window=window, sync_every=0)
    prefetcher = DevicePrefetcher(source(), stage=stage, depth=depth)
    tele.attach_prefetcher(prefetcher)
    step = 0
    for _ in tele.timed(iter(prefetcher)):
        if consumer_sleep_s:
            time.sleep(consumer_sleep_s)
        tele.dispatch_done()
        step += 1
        tele.step_done(step, None)
    tele.finish(step)
    tele.close()
    assert tschema.validate_file(jsonl) == []
    return [rec for rec in map(json.loads, open(jsonl))
            if rec.get("kind") == "step_window"]


def test_prefetch_fast_producer_drives_data_wait_to_zero(tmp_path):
    """With the producer ahead of the loop, the consumer never waits:
    data_wait p50 ~ 0 even though featurization takes real time per item
    (it hides behind the consumer's step)."""
    windows = _drive_loop(tmp_path, producer_sleep_s=0.004,
                          stage_sleep_s=0.0, consumer_sleep_s=0.02)
    assert windows, "no window record emitted"
    assert windows[0]["data_wait_p50_s"] < 0.004
    # h2d fields ride along (prefetcher attached), bounded by data_wait.
    assert windows[0]["h2d_wait_p50_s"] <= windows[0]["data_wait_p50_s"]


def test_prefetch_slow_producer_still_attributes_data_wait(tmp_path):
    """A producer slower than the loop is a real stall and must stay
    attributed to data_wait (not hidden), with only a small h2d share."""
    windows = _drive_loop(tmp_path, producer_sleep_s=0.03,
                          stage_sleep_s=0.0, consumer_sleep_s=0.0)
    w = windows[0]
    assert w["data_wait_p50_s"] >= 0.015
    assert w["h2d_wait_p50_s"] <= 0.5 * w["data_wait_p50_s"]


def test_prefetch_slow_staging_reports_as_h2d_subphase(tmp_path):
    """When the H2D staging call is the bottleneck, the wait lands in
    data_wait AND is attributed to the h2d_wait sub-phase."""
    windows = _drive_loop(tmp_path, producer_sleep_s=0.0,
                          stage_sleep_s=0.02, consumer_sleep_s=0.0)
    w = windows[0]
    assert w["data_wait_p50_s"] >= 0.01
    assert w["h2d_wait_p50_s"] >= 0.5 * w["data_wait_p50_s"]
    assert w["h2d_wait_p95_s"] <= w["data_wait_p95_s"]


def test_prefetch_inline_depth_zero_same_contract(tmp_path):
    windows = _drive_loop(tmp_path, producer_sleep_s=0.0,
                          stage_sleep_s=0.01, consumer_sleep_s=0.0,
                          depth=0)
    w = windows[0]
    assert w["h2d_wait_p50_s"] >= 0.005
    assert w["h2d_wait_p50_s"] <= w["data_wait_p50_s"]


def test_prefetch_propagates_producer_error():
    def source():
        yield 1
        raise RuntimeError("shard exploded")

    p = DevicePrefetcher(source(), stage=lambda x: x, depth=2)
    it = iter(p)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="shard exploded"):
        next(it)


# ---------------------------------------------------------------------------
# acceptance: checkpoint-step p95 collapses under async writes


def _ckpt_run(jsonl_path, async_write, state, n_steps=12, step_s=0.45,
              every=4):
    """Paced synthetic training loop with periodic saves of a large
    state, emitting real step_window records (the bench BENCH_ASYNC leg's
    shape, through the same StepTimer + ckpt_step accounting)."""
    import shutil
    import tempfile

    sink = JSONLHandler(jsonl_path, overwrite=False)
    timer = StepTimer(window=8, sync_every=0)
    out_dir = tempfile.mkdtemp(prefix="ckpt_accept_")
    try:
        # Un-measured warmup save: first-call effects (allocator growth,
        # directory creation, thread spawn) must not land in the measured
        # p95 — with a handful of saves, p95 is the max.
        warm_dir = tempfile.mkdtemp(prefix="ckpt_accept_warm_")
        ckpt.save_checkpoint(warm_dir, 0, state, async_write=async_write)
        ckpt.wait_for_pending_save(warm_dir)
        shutil.rmtree(warm_dir, ignore_errors=True)
        for step in range(1, n_steps + 1):
            timer.data_start()
            timer.data_end()
            time.sleep(step_s)
            timer.dispatch_end()
            rec = timer.step_done(step)
            if rec:
                sink.write_record(rec)
            if step % every == 0:
                t0 = time.perf_counter()
                ckpt.save_checkpoint(out_dir, step, state, keep=2,
                                     async_write=async_write)
                timer.note_ckpt_stall(time.perf_counter() - t0)
        ckpt.wait_for_pending_save(out_dir)
        rec = timer.flush(n_steps)
        if rec:
            sink.write_record(rec)
        sink.write_record({"kind": "run_summary", "tag": "telemetry",
                           "step": n_steps, "steps": n_steps})
    finally:
        ckpt.wait_for_pending_save()
        shutil.rmtree(out_dir, ignore_errors=True)
        sink.close()


def test_checkpoint_step_p95_collapses_and_report_gates(tmp_path):
    """ISSUE 6 acceptance: with async checkpointing and a deliberately
    large state, checkpoint-step p95 lands within 20% of steady-state p95
    while blocking writes hold it at >= 2x — and diffing the blocking run
    against the async baseline trips the telemetry-report regression gate
    BY NAME (the same path the bench gate uses)."""
    # ~96 MB of DEVICE state, like a real runner's: the async foreground
    # cost is the jitted-identity snapshot DISPATCH (enqueued, ms-scale —
    # the copy itself executes on the backend while the step sleeps),
    # while a blocking save pays the full device_get + serialize + hash +
    # write (~7-10 ms/MB on this box) — both ratio thresholds keep a
    # wide margin.
    import jax.numpy as jnp

    state = {"model": {f"w{i}": jnp.ones((4_000_000,), jnp.float32)
                       for i in range(6)}, "epoch": 1}
    sync_jsonl = str(tmp_path / "sync_telemetry.jsonl")
    async_jsonl = str(tmp_path / "async_telemetry.jsonl")
    _ckpt_run(sync_jsonl, async_write=False, state=state)
    _ckpt_run(async_jsonl, async_write=True, state=state)
    for path in (sync_jsonl, async_jsonl):
        assert tschema.validate_file(path) == []

    def ratios(summary):
        return (summary["ckpt_step_p95_s"] / summary["step_p95_s"], summary)

    sync_ratio, sync_sum = ratios(treport.summarize_file(sync_jsonl))
    async_ratio, async_sum = ratios(treport.summarize_file(async_jsonl))
    assert sync_sum["ckpt_steps"] == async_sum["ckpt_steps"] == 3
    assert sync_ratio >= 2.0, (sync_sum, "blocking saves should stall")
    if async_ratio > 1.2:
        # p95 over 3 saves is the max: one background-load spike on this
        # throttled 2-core box (another test's teardown, a page-cache
        # flush) can poison a single snapshot memcpy. Re-measure once —
        # a real regression (a blocking write on the async path) fails
        # both times by a wide margin, noise doesn't.
        async_jsonl = str(tmp_path / "async_retry_telemetry.jsonl")
        _ckpt_run(async_jsonl, async_write=True, state=state)
        assert tschema.validate_file(async_jsonl) == []
        async_ratio, async_sum = ratios(treport.summarize_file(async_jsonl))
    assert async_ratio <= 1.2, (async_sum, "async saves should overlap")

    # Injected-regression gating path: blocking run vs async baseline
    # must exit nonzero and NAME the checkpoint-step regression.
    regressions, _ = treport.compare(async_sum, sync_sum)
    assert any(r["metric"] == "ckpt_step_p95_s" for r in regressions), (
        regressions)
    rc = treport.main([sync_jsonl, async_jsonl])
    assert rc == 1
    # And the async run against itself is clean.
    assert treport.main([async_jsonl, async_jsonl]) == 0


# ---------------------------------------------------------------------------
# overlapped data-parallel gradients: bucketed == unbucketed


def test_bucketed_overlap_gradients_match_unbucketed():
    """Acceptance: the explicit availability-ordered per-bucket psum path
    produces gradients (observed through one optimizer step: params,
    loss, grad_norm) numerically identical to the implicit-reduction path
    at fp32 tolerance (1e-6)."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu import optim, pretrain
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.parallel import (MeshConfig, create_mesh,
                                           logical_axis_rules)

    # A fresh config (never the shared session fixture — it would leak
    # the dropout override into later tests). Dropout off: the bucketed
    # path folds the shard index into the dropout stream (valid draws,
    # different from the unbucketed path), so exact parity is defined on
    # the deterministic graph.
    config = BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2, next_sentence=True,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    model = BertForPreTraining(config, dtype=jnp.float32)
    mesh = create_mesh(MeshConfig(data=-1))
    rules = logical_axis_rules("dp")
    seq = 32
    sample = (jnp.zeros((1, seq), jnp.int32),) * 3
    tx = optim.lamb(optim.make_schedule("poly", 1e-3, 0.1, 10),
                    weight_decay=0.01, weight_decay_mask=optim.no_decay_mask,
                    max_grad_norm=1.0)
    rng = np.random.default_rng(0)
    accum, rows = 2, 16
    batch = {
        "input_ids": rng.integers(
            0, config.vocab_size, (accum, rows, seq)).astype(np.int32),
        "segment_ids": rng.integers(0, 2, (accum, rows, seq)).astype(np.int32),
        "input_mask": np.ones((accum, rows, seq), np.int32),
        "masked_lm_labels": np.where(
            rng.random((accum, rows, seq)) < 0.15,
            rng.integers(0, config.vocab_size, (accum, rows, seq)),
            -1).astype(np.int32),
        "next_sentence_labels": rng.integers(
            0, 2, (accum, rows)).astype(np.int32),
    }
    spec = {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
            "masked_lm_labels": 3, "next_sentence_labels": 2}
    with mesh:
        shardings = pretrain.state_shardings(mesh, model, rules, sample)
        b_sh = pretrain.batch_shardings(mesh, spec)
        init_fn = pretrain.make_init_fn(model, tx, sample, shardings)
        kwargs = dict(schedule=None, next_sentence=True, shardings=shardings,
                      batch_shardings_=b_sh, max_pred_per_seq=8)
        step_ref = pretrain.make_train_step(model, tx, **kwargs)
        step_ovl = pretrain.make_train_step(
            model, tx, mesh=mesh, overlap_grad_buckets=True, **kwargs)
        s_ref, m_ref = step_ref(init_fn(jax.random.PRNGKey(0)),
                                pretrain.put_batch(batch, b_sh))
        s_ovl, m_ovl = step_ovl(init_fn(jax.random.PRNGKey(0)),
                                pretrain.put_batch(batch, b_sh))
    for key in ("loss", "mlm_accuracy", "grad_norm", "real_tokens"):
        np.testing.assert_allclose(float(m_ref[key]), float(m_ovl[key]),
                                   rtol=1e-6, atol=1e-7, err_msg=key)
    assert float(m_ovl["finite"]) == 1.0
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), s_ref.params,
        s_ovl.params)
    assert max(jax.tree_util.tree_leaves(diffs)) < 1e-6


def test_overlap_rejects_unsupported_compositions(tiny_config):
    import jax.numpy as jnp

    from bert_pytorch_tpu import optim, pretrain
    from bert_pytorch_tpu.models import BertForPreTraining

    model = BertForPreTraining(tiny_config, dtype=jnp.float32)
    tx = optim.adamw(optim.make_schedule("poly", 1e-3, 0.1, 10))
    with pytest.raises(ValueError, match="requires mesh"):
        pretrain.make_train_step(model, tx, overlap_grad_buckets=True)


def test_gradient_buckets_cover_tree_in_availability_order():
    from bert_pytorch_tpu.parallel import overlap

    grads = {"bert": {"embeddings": {"w": 1}, "encoder": {"layers": {"k": 2}},
                      "pooler": {"d": 3}},
             "predictions": {"b": 4}, "seq_relationship": {"k": 5}}
    flat, _ = __import__("jax").tree_util.tree_flatten_with_path(grads)
    buckets = {}
    for path, leaf in flat:
        buckets.setdefault(overlap._bucket_of(path), []).append(leaf)
    assert buckets[overlap._BUCKET_EMBEDDINGS] == [1]
    assert buckets[overlap._BUCKET_ENCODER] == [2]
    assert sorted(buckets[overlap._BUCKET_HEADS]) == [3, 4, 5]
