"""Elasticity-plane unit tests (PR 20, docs/serving.md "Elastic
fleet"): the autoscaler control loop under a fake clock and scripted
fleets/signals (evidence windows, both cooldowns, the replica band,
every hard scale-down hold), dynamic supervisor/router membership with
fake processes and transports, the scale_event schema fixtures + the
membership chain lint, the two zero-tolerance report gates tripping by
name, the collector's event-stream fleet membership, and the
in-process fake-fleet surge pass that carries the surge invariants at
tier-1 (PR 14 budget rule — the live subprocess proof is
``tools/chaos_serve.py --surge``, tests/test_fleet_chaos.py)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from bert_pytorch_tpu.serve.autoscaler import (HOLD, SCALE_DOWN, SCALE_UP,
                                               AutoscalerController,
                                               AutoscalerError,
                                               ElasticFleet, RouterSignals)
from bert_pytorch_tpu.serve.router import Router
from bert_pytorch_tpu.serve.supervisor import (BACKOFF, RUNNING, STOPPED,
                                               ReplicaTemplate, Supervisor)
from bert_pytorch_tpu.telemetry import report, schema
from bert_pytorch_tpu.telemetry.collector import (FleetCollector,
                                                  FleetMembership,
                                                  JsonlTailer, Target)
from bert_pytorch_tpu.utils.preemption import EXIT_PREEMPTED
from bert_pytorch_tpu.utils.retry import RetryPolicy

HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(HERE)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


class FakeProc:
    _pids = iter(range(6000, 7000))

    def __init__(self):
        self.pid = next(FakeProc._pids)
        self.rc = None
        self.signals = []

    def poll(self):
        return self.rc

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)
        self.rc = EXIT_PREEMPTED   # a well-behaved replica drains


# ---------------------------------------------------------------------------
# scripted collaborators for the controller units


class ScriptedFleet:
    """Minimal :class:`ElasticFleet` surface with scriptable status
    rows — the controller's decisions are pure functions of what this
    reports, so every branch is reachable without a process tree."""

    def __init__(self, replicas: int = 1):
        self.rows = [self._row(i) for i in range(replicas)]
        self.split = False
        self.pending_drain = False
        self.scale_up_calls = 0
        self.drain_calls = 0
        self.scale_up_exc = None
        self.refuse_drain = False

    @staticmethod
    def _row(i, state=RUNNING, draining=False):
        return {"replica": i, "port": 9000 + i,
                "url": f"http://127.0.0.1:{9000 + i}",
                "state": state, "draining": draining}

    def status(self):
        return [dict(r) for r in self.rows]

    def split_active(self):
        return self.split

    def draining(self):
        return self.pending_drain or any(
            r["draining"] and r["state"] != STOPPED for r in self.rows)

    def scale_up(self):
        if self.scale_up_exc is not None:
            raise self.scale_up_exc
        self.scale_up_calls += 1
        i = max((r["replica"] for r in self.rows), default=-1) + 1
        self.rows.append(self._row(i))
        return {"replica": i, "url": self.rows[-1]["url"],
                "port": 9000 + i}

    def begin_drain(self):
        self.drain_calls += 1
        if self.refuse_drain:
            return None
        victims = [r for r in self.rows
                   if not r["draining"] and r["state"] not in (STOPPED,)]
        victim = max(victims, key=lambda r: r["replica"])
        victim["draining"] = True
        victim["state"] = STOPPED   # the fake drains instantly
        return {"replica": victim["replica"], "url": victim["url"]}

    def reap_drained(self):
        return []


RED = {"window_requests": 40, "window_errors": 0, "window_sheds": 9}
GREEN = {"window_requests": 2, "window_errors": 0, "window_sheds": 0}
# Hot reading over a thin window: a red trigger WITHOUT the traffic
# evidence floor — neither red nor green, resets both streaks.
NEUTRAL = {"window_requests": 2, "window_errors": 0, "window_sheds": 0,
           "queue_wait_share": 0.9}


def _controller(fleet, events=None, clock=None, **kw):
    clock = clock or FakeClock()
    sig = {"value": dict(GREEN)}
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("red_windows_to_scale_up", 2)
    kw.setdefault("green_windows_to_scale_down", 2)
    kw.setdefault("up_cooldown_s", 5.0)
    kw.setdefault("down_cooldown_s", 20.0)
    kw.setdefault("min_window_requests", 8)
    ctrl = AutoscalerController(
        fleet, lambda: dict(sig["value"]),
        emit=events.append if events is not None else None,
        clock=clock, **kw)

    def tick_with(window):
        sig["value"] = dict(window)
        return ctrl.tick()

    return ctrl, clock, tick_with


# ---------------------------------------------------------------------------
# controller: configuration validation


def test_controller_validation_errors():
    fleet = ScriptedFleet()
    sig = dict
    with pytest.raises(AutoscalerError, match="min_replicas"):
        AutoscalerController(fleet, sig, min_replicas=3, max_replicas=2)
    with pytest.raises(AutoscalerError, match="evidence windows"):
        AutoscalerController(fleet, sig, red_windows_to_scale_up=0)
    with pytest.raises(AutoscalerError, match="cooldowns"):
        AutoscalerController(fleet, sig, up_cooldown_s=-1.0)
    with pytest.raises(AutoscalerError, match="queue_wait_share"):
        AutoscalerController(fleet, sig, queue_wait_share_low=0.5,
                             queue_wait_share_high=0.25)


# ---------------------------------------------------------------------------
# controller: evidence windows


def test_scale_up_needs_consecutive_red_windows():
    fleet = ScriptedFleet(replicas=1)
    events: list = []
    ctrl, _, tick = _controller(fleet, events)
    rec = tick(RED)
    assert rec["decision"] == HOLD and fleet.scale_up_calls == 0
    rec = tick(RED)
    assert rec["decision"] == SCALE_UP
    assert rec["reason"].startswith("red_windows:")
    assert "sheds=9" in rec["reason"]
    assert rec["replicas_before"] == 1 and rec["replicas_after"] == 2
    assert rec["exogenous"] == 0 and rec["replica"] == 1
    assert fleet.scale_up_calls == 1


def test_red_streak_broken_by_neutral_window():
    fleet = ScriptedFleet(replicas=1)
    ctrl, _, tick = _controller(fleet)
    tick(RED)
    rec = tick(NEUTRAL)         # hot share over a thin window: noise
    assert rec["decision"] == HOLD
    assert ctrl.status()["reds"] == 0
    tick(RED)
    assert fleet.scale_up_calls == 0   # the streak restarted


def test_red_evidence_floor_sheds_bypass_thin_window():
    """min_window_requests gates hot readings — but an actual shed IS
    the evidence, however thin the window."""
    fleet = ScriptedFleet(replicas=1)
    ctrl, _, tick = _controller(fleet)
    thin_shed = {"window_requests": 1, "window_errors": 0,
                 "window_sheds": 3}
    tick(thin_shed)
    rec = tick(thin_shed)
    assert rec["decision"] == SCALE_UP
    assert "sheds=3" in rec["reason"]


# ---------------------------------------------------------------------------
# controller: cooldowns


def test_up_cooldown_blocks_back_to_back_growth():
    fleet = ScriptedFleet(replicas=1)
    ctrl, clock, tick = _controller(fleet)
    tick(RED)
    assert tick(RED)["decision"] == SCALE_UP
    tick(RED)                              # streak restarted post-scale
    rec = tick(RED)
    assert rec["decision"] == HOLD and rec["reason"] == "hold:up_cooldown"
    assert fleet.scale_up_calls == 1
    clock.advance(5.1)
    rec = tick(RED)
    assert rec["decision"] == SCALE_UP and fleet.scale_up_calls == 2


def test_down_cooldown_is_the_slower_direction():
    fleet = ScriptedFleet(replicas=3)
    ctrl, clock, tick = _controller(fleet)
    tick(GREEN)
    rec = tick(GREEN)
    assert rec["decision"] == SCALE_DOWN
    assert rec["replicas_before"] == 3 and rec["replicas_after"] == 2
    tick(GREEN)
    rec = tick(GREEN)
    assert rec["reason"] == "hold:down_cooldown"
    assert rec["cooldown_s"] == 20.0       # the cooldown it answers to
    clock.advance(21.0)
    rec = tick(GREEN)
    assert rec["decision"] == SCALE_DOWN
    assert fleet.drain_calls == 2
    assert ctrl.status()["thrash"] == 0


# ---------------------------------------------------------------------------
# controller: the replica band + every hard scale-down hold


def test_band_max_holds_growth():
    fleet = ScriptedFleet(replicas=1)
    ctrl, _, tick = _controller(fleet, max_replicas=1)
    tick(RED)
    rec = tick(RED)
    assert rec["reason"] == "hold:band_max"
    assert fleet.scale_up_calls == 0


def test_band_min_holds_shrink():
    fleet = ScriptedFleet(replicas=1)
    ctrl, _, tick = _controller(fleet)
    tick(GREEN)
    rec = tick(GREEN)
    assert rec["reason"] == "hold:band_min"
    assert fleet.drain_calls == 0


def test_hard_hold_canary_split():
    fleet = ScriptedFleet(replicas=2)
    fleet.split = True
    ctrl, _, tick = _controller(fleet)
    tick(GREEN)
    rec = tick(GREEN)
    assert rec["reason"] == "hold:canary_split"
    assert fleet.drain_calls == 0


def test_hard_hold_drain_in_flight():
    fleet = ScriptedFleet(replicas=2)
    fleet.pending_drain = True
    ctrl, _, tick = _controller(fleet)
    tick(GREEN)
    rec = tick(GREEN)
    assert rec["reason"] == "hold:draining"
    assert fleet.drain_calls == 0


def test_hard_hold_restarting_replica_is_not_spare_capacity():
    fleet = ScriptedFleet(replicas=2)
    fleet.rows[1]["state"] = BACKOFF   # SIGKILLed; respawn owed
    ctrl, _, tick = _controller(fleet)
    tick(GREEN)
    rec = tick(GREEN)
    assert rec["reason"] == "hold:restarting"
    assert rec["replicas_before"] == 2   # ...and still counted as capacity
    assert fleet.drain_calls == 0


def test_hard_hold_min_healthy():
    """Defense in depth: a replica active but not ready under some
    FUTURE state would slip past the restarting hold — the healthy
    floor still refuses to shrink below min_replicas healthy."""
    fleet = ScriptedFleet(replicas=3)
    fleet.rows[2]["state"] = "degraded"
    ctrl, _, tick = _controller(fleet, min_replicas=2)
    tick(GREEN)
    rec = tick(GREEN)
    assert rec["reason"] == "hold:min_healthy"
    assert fleet.drain_calls == 0


def test_scale_up_failure_is_a_named_hold():
    fleet = ScriptedFleet(replicas=1)
    fleet.scale_up_exc = RuntimeError("spawn blew up")
    ctrl, _, tick = _controller(fleet)
    tick(RED)
    rec = tick(RED)
    assert rec["decision"] == HOLD
    assert rec["reason"] == "hold:scale_up_failed:RuntimeError"
    assert "spawn blew up" in ctrl.status()["last_error"]


def test_scale_down_without_candidate_is_a_named_hold():
    fleet = ScriptedFleet(replicas=2)
    fleet.refuse_drain = True
    ctrl, _, tick = _controller(fleet)
    tick(GREEN)
    rec = tick(GREEN)
    assert rec["decision"] == HOLD and rec["reason"] == "hold:no_candidate"


# ---------------------------------------------------------------------------
# controller: emission discipline


def test_hold_dedup_and_reemission_on_change():
    fleet = ScriptedFleet(replicas=1)
    events: list = []
    ctrl, _, tick = _controller(fleet, events)
    for _ in range(4):
        tick(GREEN)
    # hold:evidence once, hold:band_min once — the repeats are dropped.
    assert [e["reason"] for e in events] == ["hold:evidence",
                                             "hold:band_min"]
    fleet.rows.append(fleet._row(1))   # membership changed exogenously
    tick(GREEN)
    assert events[-1]["decision"] == SCALE_DOWN   # actions always emit


def test_exogenous_drift_keeps_membership_chain_reconstructible(tmp_path):
    fleet = ScriptedFleet(replicas=1)
    events: list = []
    ctrl, _, tick = _controller(fleet, events)
    tick(RED)
    tick(RED)                                      # scale_up: 1 -> 2
    fleet.rows.pop()          # operator/gave-up drift outside the loop
    tick(GREEN)
    rec = events[-1]
    assert rec["decision"] == HOLD
    assert rec["replicas_before"] == 1 and rec["exogenous"] == -1
    # The full emitted stream passes the cross-record chain lint.
    path = tmp_path / "scale.jsonl"
    with open(path, "w", encoding="utf-8") as f:
        for e in events:
            f.write(json.dumps(dict(
                e, schema=schema.SCHEMA_VERSION, ts=0.0)) + "\n")
    assert schema.validate_file(str(path)) == []


def test_controller_records_are_schema_clean():
    fleet = ScriptedFleet(replicas=1)
    events: list = []
    ctrl, clock, tick = _controller(fleet, events)
    tick(RED), tick(RED)
    clock.advance(30.0)
    tick(GREEN)
    assert tick(GREEN)["decision"] == SCALE_DOWN
    for e in events:
        rec = dict(e, schema=schema.SCHEMA_VERSION, ts=0.0)
        assert schema.validate_record(rec) == [], rec
    assert ctrl.status()["thrash"] == 0
    assert ctrl.status()["scale_ups"] == 1
    assert ctrl.status()["scale_downs"] == 1


def test_controller_loop_thread_start_stop():
    fleet = ScriptedFleet(replicas=1)
    ctrl, _, _ = _controller(fleet)
    ctrl.start(interval_s=0.001)
    with pytest.raises(AutoscalerError, match="already started"):
        ctrl.start()
    deadline = time.monotonic() + 5.0
    while ctrl.status()["ticks"] < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    ctrl.stop()
    st = ctrl.status()
    assert st["ticks"] >= 3 and st["last_error"] is None


# ---------------------------------------------------------------------------
# ElasticFleet over a real Supervisor + Router (fake procs/transports)


def _healthy_scrape(url):
    return {"dispatch_alive": True, "draining": False, "queue_depth": 0}


def _live_fleet(tmp_path, events=None):
    clock = FakeClock()
    procs = []

    def spawn(spec):
        procs.append(FakeProc())
        return procs[-1]

    template = ReplicaTemplate(["--task", "classify"], str(tmp_path),
                               script="run_server.py")
    specs = [template.make_spec(0, port=9001)]
    sup = Supervisor(specs,
                     emit=events.append if events is not None else None,
                     spawn=spawn,
                     policy=RetryPolicy(attempts=3, base_delay_s=1.0,
                                        jitter=0.0),
                     clock=clock, sleep=lambda s: None)
    sup.start(monitor=False)
    router = Router([specs[0].url], transport=lambda *a: (200, {}),
                    scrape=_healthy_scrape, sleep=lambda s: None)
    router.scrape_once()
    fleet = ElasticFleet(sup, router, template)
    return fleet, sup, router, procs, clock


def test_elastic_fleet_scale_up_mints_fresh_identity(tmp_path):
    fleet, sup, router, procs, _ = _live_fleet(tmp_path)
    info = fleet.scale_up()
    assert info["replica"] == 1 and info["port"] != 9001
    assert len(procs) == 2 and router.replica_count() == 2
    # The new target is unhealthy until its first clean scrape.
    assert router.healthy_count() == 1
    router.scrape_once()
    assert router.healthy_count() == 2
    # Fresh per-replica output dir from the template recipe.
    assert os.path.isdir(os.path.join(str(tmp_path), "replica_1"))


def test_elastic_fleet_drain_confirm_then_remove(tmp_path):
    events: list = []
    fleet, sup, router, procs, _ = _live_fleet(tmp_path, events)
    fleet.scale_up()
    router.scrape_once()
    item = fleet.begin_drain()
    assert item["replica"] == 1         # the elastic replica goes first
    assert procs[1].signals == [15]     # SIGTERM drain
    assert fleet.draining() is True
    # The router keeps the target until the supervisor CONFIRMS.
    assert fleet.reap_drained() == []
    assert router.replica_count() == 2
    sup.poll_once()                     # the rc-75 exit lands
    st = [s for s in sup.status() if s["replica"] == 1][0]
    assert st["state"] == STOPPED and st["last_rc"] == EXIT_PREEMPTED
    done = fleet.reap_drained()
    assert [d["replica"] for d in done] == [1]
    assert router.replica_count() == 1
    assert fleet.draining() is False
    # Reaped WITHOUT respawn, and the index is never reused.
    sup.poll_once()
    assert len(procs) == 2
    spec = sup.add_replica(ReplicaTemplate(
        ["--task", "classify"], str(tmp_path), script="run_server.py"))
    assert spec.index == 2
    names = [e["event"] for e in events]
    assert "scale_drain" in names
    drain_done = [e for e in events if e["event"] == "drain_complete"][-1]
    assert drain_done["rc"] == EXIT_PREEMPTED and drain_done["graceful"]


def test_router_membership_under_live_traffic():
    calls = []

    def transport(url, task, payload, timeout_s):
        calls.append(url)
        return 200, {"ok": True}

    # The seed replicas report deep queues; the elastic one is empty —
    # once (and only once) a scrape proves it up, it takes the traffic.
    def scrape(url):
        return {"dispatch_alive": True, "draining": False,
                "queue_depth": 0 if url == "http://c:3" else 5}

    router = Router(["http://a:1", "http://b:2"], transport=transport,
                    scrape=scrape, sleep=lambda s: None)
    router.scrape_once()
    router.add_target("http://c:3")
    with pytest.raises(ValueError, match="already routed"):
        router.add_target("http://c:3")
    for _ in range(6):
        assert router.handle("classify", {"text": "hi"})[0] == 200
    assert "http://c:3" not in calls    # unhealthy until proven
    router.scrape_once()
    calls.clear()
    for _ in range(9):
        router.handle("classify", {"text": "hi"})
    assert "http://c:3" in calls        # ...then absorbs traffic
    assert router.remove_target("http://b:2") is True
    assert router.remove_target("http://b:2") is False
    calls.clear()
    for _ in range(6):
        assert router.handle("classify", {"text": "hi"})[0] == 200
    assert "http://b:2" not in calls
    router.remove_target("http://c:3")
    with pytest.raises(ValueError, match="last target"):
        router.remove_target("http://a:1")


# ---------------------------------------------------------------------------
# RouterSignals: per-tick windows from the router's run counters


class _SnapRouter:
    def __init__(self):
        self.snap = {"requests": 0, "errors": 0, "sheds": 0,
                     "replica_states": []}

    def snapshot(self):
        return dict(self.snap)


def test_router_signals_are_window_deltas():
    router = _SnapRouter()
    signals = RouterSignals(router)
    assert signals() == {"window_requests": 0, "window_errors": 0,
                         "window_sheds": 0, "unfinished": 0}
    router.snap.update(requests=10, errors=1, sheds=2, replica_states=[
        {"url": "http://a:1", "unfinished": 3},
        {"url": "http://b:2", "unfinished": 4}])
    sig = signals()
    assert sig["window_requests"] == 10 and sig["window_errors"] == 1
    assert sig["window_sheds"] == 2 and sig["unfinished"] == 7
    router.snap.update(requests=14)
    sig = signals()
    assert sig["window_requests"] == 4     # delta, not the running total
    assert sig["window_errors"] == 0 and sig["window_sheds"] == 0


def test_router_signals_probe_takes_worst_replica():
    router = _SnapRouter()
    router.snap["replica_states"] = [{"url": "http://a:1"},
                                     {"url": "http://b:2"},
                                     {"url": "http://c:3"}]

    def probe(url):
        if url == "http://a:1":
            return {"phases": {"queue_wait_share": 0.1,
                               "slo_budget_burn": 0.2}}
        if url == "http://b:2":
            return {"phases": {"queue_wait_share": 0.3,
                               "slo_budget_burn": 1.2}}
        raise OSError("replica c is warming")   # skipped, not fatal

    sig = RouterSignals(router, probe=probe)()
    assert sig["queue_wait_share"] == 0.3   # max over replicas
    assert sig["budget_burn"] == 1.2


# ---------------------------------------------------------------------------
# scale_event schema fixtures + the membership chain lint


def test_scale_schema_fixtures_lint():
    good = os.path.join(HERE, "fixtures", "telemetry", "scale_good.jsonl")
    bad = os.path.join(HERE, "fixtures", "telemetry", "scale_bad.jsonl")
    assert schema.validate_file(good) == []
    errors = schema.validate_file(bad)
    text = " | ".join(err for _, err in errors)
    assert "decision must be one of" in text
    assert "reason must be a non-empty string" in text
    assert "must move replicas by +1" in text
    assert "replicas_before must be a non-negative integer" in text
    assert "queue_wait_share must be in [0, 1]" in text
    assert "exogenous must be an integer" in text
    assert "fleet membership not reconstructible" in text
    # And the repo tool (jax-free, file-path bootstrap) agrees.
    proc = subprocess.run(
        [sys.executable, "tools/check_telemetry_schema.py", good, bad],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "scale_good.jsonl: ok" in proc.stdout
    assert "scale_bad" in proc.stdout


# ---------------------------------------------------------------------------
# telemetry-report: the elasticity section + both zero-tolerance gates


def _scale_records(flip_inside_cooldown=False, window_errors=0):
    records = [
        {"kind": "scale_event", "tag": "autoscale",
         "decision": "scale_up", "reason": "red_windows:sheds=5",
         "replicas_before": 1, "replicas_after": 2, "exogenous": 0,
         "window_requests": 40, "window_errors": window_errors,
         "window_sheds": 5, "cooldown_s": 5.0},
        {"kind": "scale_event", "tag": "autoscale",
         "decision": "scale_down", "reason": "green_windows",
         "replicas_before": 2, "replicas_after": 1, "exogenous": 0,
         "window_requests": 4, "window_errors": 0, "window_sheds": 0,
         "cooldown_s": 20.0, "since_last_scale_s": 25.0},
    ]
    if flip_inside_cooldown:
        records.append(
            {"kind": "scale_event", "tag": "autoscale",
             "decision": "scale_up", "reason": "red_windows:sheds=2",
             "replicas_before": 1, "replicas_after": 2, "exogenous": 0,
             "window_requests": 30, "window_errors": 0,
             "window_sheds": 2, "cooldown_s": 5.0,
             "since_last_scale_s": 0.5})
    return [dict(r, schema=schema.SCHEMA_VERSION, ts=0.0)
            for r in records]


def test_report_summarizes_scale_events():
    summary = report.summarize_records(_scale_records())
    assert summary["scale_events"] == 2
    assert summary["autoscaler_scale_ups"] == 1
    assert summary["autoscaler_scale_downs"] == 1
    assert summary["autoscaler_replicas_max"] == 2
    assert summary["autoscaler_replicas_last"] == 1
    assert summary["autoscaler_thrash"] == 0
    assert summary["surge_client_errors"] == 0
    text = report.format_summary(summary)
    assert "autoscaler_thrash" in text and "scale_events" in text


def test_report_autoscaler_thrash_gate_trips_by_name():
    base = report.summarize_records(_scale_records())
    bad = report.summarize_records(
        _scale_records(flip_inside_cooldown=True))
    assert bad["autoscaler_thrash"] == 1
    regressions, _ = report.compare(base, bad)
    assert "autoscaler thrash" in [r["label"] for r in regressions]


def test_report_surge_error_gate_trips_by_name():
    base = report.summarize_records(_scale_records())
    bad = report.summarize_records(_scale_records(window_errors=3))
    regressions, _ = report.compare(base, bad)
    assert "surge client-visible errors" in [r["label"]
                                             for r in regressions]
    # A clean self-diff stays clean.
    assert report.compare(base, base)[0] == []


# ---------------------------------------------------------------------------
# collector: event-stream fleet membership (tools/obs_collect.py --fleet)


def test_fleet_membership_follows_supervisor_events(tmp_path):
    fleet_log = tmp_path / "fleet.jsonl"

    def emit(event, replica, port):
        with open(fleet_log, "a", encoding="utf-8") as f:
            f.write(json.dumps({"kind": "fleet_event", "tag": "fleet",
                                "event": event, "replica": replica,
                                "port": port}) + "\n")

    records: list = []
    coll = FleetCollector([], emit=records.append)
    mem = FleetMembership(coll, JsonlTailer(str(fleet_log), "fleet"),
                          scrape=lambda url: {"healthy": True})
    emit("spawn", 0, 8001)
    emit("spawn", 1, 8002)
    assert mem.sync() == {"joined": ["replica-0", "replica-1"],
                          "left": []}
    coll.collect_once()
    scraped = [r["target"] for r in records if r["kind"] == "obs_scrape"]
    assert scraped == ["replica-0", "replica-1"]
    # A crash-respawn of a known replica is a no-op; the drain REQUEST
    # alone removes nothing — confirmation does.
    emit("spawn", 1, 8002)
    emit("scale_drain", 1, 8002)
    assert mem.sync() == {"joined": [], "left": []}
    emit("drain_complete", 1, 8002)
    assert mem.sync() == {"joined": [], "left": ["replica-1"]}
    assert coll.target_names() == ["replica-0"]
    coll.close()


def test_dynamic_target_ages_from_join_not_collector_start():
    clock = FakeClock()
    records: list = []
    coll = FleetCollector(
        [Target("seed", "replica", "http://a:1",
                scrape=lambda url: None)],
        emit=records.append, clock=clock)
    clock.advance(100.0)
    coll.add_target(Target("late", "replica", "http://b:2",
                           scrape=lambda url: None))
    coll.collect_once()
    by_name = {r["target"]: r for r in records
               if r["kind"] == "obs_scrape"}
    # The seed target was never up for 100s; the late joiner was only
    # born this instant — staleness must say so.
    assert by_name["seed"]["staleness_s"] == pytest.approx(100.0)
    assert by_name["late"]["staleness_s"] == pytest.approx(0.0)
    coll.close()


# ---------------------------------------------------------------------------
# the tier-1 surge carrier (PR 14 budget rule): the full surge story on
# an in-process fake fleet — warm scale-up, hysteresis under sustained
# load, cooldown-gated scale-down, a reconstructible event stream, and
# both gates green. The live subprocess version is `--surge` (slow).


def test_in_process_surge_pass_carries_the_invariants(tmp_path):
    fleet = ScriptedFleet(replicas=1)
    events: list = []
    ctrl, clock, tick = _controller(fleet, events, max_replicas=2,
                                    green_windows_to_scale_down=3)
    # Idle: holds at band_min, nothing thrashes.
    for _ in range(4):
        tick(GREEN)
        clock.advance(1.0)
    # Surge: brownout sheds force growth after the evidence windows.
    tick(RED)
    clock.advance(1.0)
    assert tick(RED)["decision"] == SCALE_UP
    # Sustained surge at the band edge holds, it does not oscillate.
    for _ in range(3):
        clock.advance(1.0)
        rec = tick(RED)
        assert rec["decision"] == HOLD
    # Recovery: greens accumulate, the down cooldown gates the shrink.
    clock.advance(30.0)
    for _ in range(2):
        tick(GREEN)
        clock.advance(1.0)
    rec = tick(GREEN)
    assert rec["decision"] == SCALE_DOWN
    assert rec["replicas_before"] == 2 and rec["replicas_after"] == 1

    st = ctrl.status()
    assert st["scale_ups"] == 1 and st["scale_downs"] == 1
    assert st["thrash"] == 0
    assert all(e["replicas_after"] <= 2 for e in events)
    assert all(e["exogenous"] == 0 for e in events)

    # The emitted stream is schema-clean (chain included) and both
    # zero-tolerance gates stay green on a self-diff.
    path = tmp_path / "surge_scale.jsonl"
    with open(path, "w", encoding="utf-8") as f:
        for e in events:
            f.write(json.dumps(dict(
                e, schema=schema.SCHEMA_VERSION, ts=0.0)) + "\n")
    assert schema.validate_file(str(path)) == []
    summary = report.summarize_records([
        dict(e, schema=schema.SCHEMA_VERSION, ts=0.0) for e in events])
    assert summary["autoscaler_thrash"] == 0
    assert summary["surge_client_errors"] == 0
    assert report.compare(summary, summary)[0] == []
