"""bench.py capture-hardening + MFU accounting tests.

Round 1 lost its entire perf capture to a transient TPU-backend init
failure (BENCH_r01.json rc=1, parsed: null). These tests pin the property
that prevents a repeat: the parent ALWAYS prints exactly one parseable
JSON line with the metric contract keys — even when the backend is
completely unavailable (where it exits 1 so ``set -e`` shell callers
still see the failure, but the driver's parse gets the error record).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(extra_env, timeout=120, capture_stderr=False):
    env = dict(os.environ)
    # Neutralize any TPU plugin sitecustomize so the probe fails fast
    # (unknown backend) instead of hanging on a dead tunnel.
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, BENCH], env=env, timeout=timeout,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE if capture_stderr else subprocess.DEVNULL,
        text=True)


def test_unavailable_backend_still_prints_parseable_json():
    proc = _run_bench({
        "JAX_PLATFORMS": "nonexistent_backend",
        "BENCH_ATTEMPTS": "2",
        "BENCH_BACKOFF_S": "1",
        "BENCH_PROBE_TIMEOUT_S": "30",
        "BENCH_BUDGET_S": "90",
    })
    # Total failure: parseable JSON on stdout, but non-zero exit so
    # set -e shell callers still see the failure.
    assert proc.returncode == 1
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert out["metric"] == "bert_large_phase1_seq_per_sec"
    assert out["value"] == 0.0
    assert out["unit"] == "seq/s/chip"
    assert out["vs_baseline"] == 0.0
    assert "error" in out and "probe failed" in out["error"]


def test_budget_exhaustion_prints_parseable_json():
    proc = _run_bench({
        "JAX_PLATFORMS": "nonexistent_backend",
        "BENCH_BUDGET_S": "1",
    }, timeout=60)
    assert proc.returncode == 1
    out = json.loads(proc.stdout.strip())
    assert out["value"] == 0.0
    assert "error" in out


def test_degraded_metric_name_and_note():
    proc = _run_bench({
        "JAX_PLATFORMS": "nonexistent_backend",
        "BENCH_DEGRADED": "1",
        "BENCH_BUDGET_S": "1",
    }, timeout=60)
    out = json.loads(proc.stdout.strip())
    assert out["metric"] == "bert_base_phase1_seq_per_sec"
    assert out["degraded"] is True


def _config_digest(env):
    """The per-config warm-marker digest bench.py would compute under
    ``env`` (module constants are env-derived, so ask a subprocess)."""
    full = dict(os.environ)
    full.update(env)
    return subprocess.run(
        [sys.executable, "-c",
         "import bench; print(bench.CONFIG_DIGEST)"],
        env=full, cwd=REPO, capture_output=True, text=True,
        check=True).stdout.strip()


def test_cold_cache_defaults_to_one_long_attempt(tmp_path):
    # A cache without THIS config's warm marker => the parent must not
    # split its budget into several short attempts (a killed compile
    # caches nothing; only one long window can make progress). Entries
    # for other shapes don't count as warm.
    cache = tmp_path / "other_shapes"
    cache.mkdir()
    (cache / "warm_0000deadbeef").write_text("ok")  # some OTHER config
    proc = _run_bench({
        "JAX_PLATFORMS": "nonexistent_backend",
        "BENCH_COMPILE_CACHE_DIR": str(cache),
        "BENCH_DEGRADE": "0",
        "BENCH_BUDGET_S": "60",
    }, timeout=120, capture_stderr=True)
    assert proc.returncode == 1
    assert "attempt 1" in proc.stderr
    assert "attempt 2" not in proc.stderr


def _degraded_digest(env):
    full = dict(os.environ)
    full.update(env)
    return subprocess.run(
        [sys.executable, "-c",
         "import bench; print(bench._degraded_digest())"],
        env=full, cwd=REPO, capture_output=True, text=True,
        check=True).stdout.strip()


def test_cold_cache_still_attempts_degraded_fallback(tmp_path):
    # Round-3 verdict weak#1: the degraded rung must NOT be gated on the
    # normal config's cache warmth — on a cold cache with a live tunnel,
    # a cold BERT-base compile plausibly fits the tail window while a
    # cold BERT-large attempt cannot, so the fallback must still be
    # probed/attempted after the one long cold attempt fails.
    cache = tmp_path / "cold"
    cache.mkdir()
    proc = _run_bench({
        "JAX_PLATFORMS": "nonexistent_backend",
        "BENCH_COMPILE_CACHE_DIR": str(cache),
        "BENCH_PROBE_TIMEOUT_S": "30",
        # Generous budget + a short attempt timeout: the point is the
        # STRATEGY (fallback attempted after the cold attempt fails), so
        # don't let a slow host's jax-import time race the entry gate.
        "BENCH_ATTEMPT_TIMEOUT_S": "30",
        "BENCH_BUDGET_S": "300",
    }, timeout=200, capture_stderr=True)
    assert proc.returncode == 1
    assert "degrade_ok=True" in proc.stderr
    assert "degraded_warm=False" in proc.stderr
    # The backend is dead, so the rung's probe runs and fails — but it
    # must have been attempted at all (the old strategy skipped it cold).
    assert "degraded fallback: probing backend" in proc.stderr
    assert "degraded fallback: backend probe failed" in proc.stderr


def test_degraded_reserve_keyed_on_degraded_marker(tmp_path):
    # ADVICE r3 #2: the reserve is sized by the DEGRADED config's own
    # warm marker (DEGRADED=True, LOCAL_BATCH=64 are part of the digest),
    # not the normal config's — a warm degraded entry means a short tail
    # suffices even when the normal config is cold.
    cache = tmp_path / "degwarm"
    cache.mkdir()
    env = {
        "JAX_PLATFORMS": "nonexistent_backend",
        "BENCH_COMPILE_CACHE_DIR": str(cache),
        "BENCH_PROBE_TIMEOUT_S": "30",
        "BENCH_BUDGET_S": "90",
    }
    (cache / f"warm_{_degraded_digest(env)}").write_text("ok")
    proc = _run_bench(env, timeout=150, capture_stderr=True)
    assert proc.returncode == 1
    assert "warm=False degraded_warm=True" in proc.stderr
    # warm reserve rung: min(240, 0.25*90) = 22s (vs cold's 0.45*90=40)
    assert "reserve=22s" in proc.stderr


def test_warm_cache_defaults_to_retries(tmp_path):
    cache = tmp_path / "warm"
    cache.mkdir()
    env = {
        "JAX_PLATFORMS": "nonexistent_backend",
        "BENCH_COMPILE_CACHE_DIR": str(cache),
        "BENCH_DEGRADE": "0",
        "BENCH_BACKOFF_S": "1",
        "BENCH_PROBE_TIMEOUT_S": "30",
        "BENCH_BUDGET_S": "90",
    }
    (cache / f"warm_{_config_digest(env)}").write_text("ok")
    proc = _run_bench(env, timeout=150, capture_stderr=True)
    assert proc.returncode == 1
    assert "attempt 2" in proc.stderr


def test_metric_name_tracks_phase_env():
    proc = _run_bench({
        "JAX_PLATFORMS": "nonexistent_backend",
        "BENCH_PHASE": "2",
        "BENCH_KFAC": "1",
        "BENCH_BUDGET_S": "1",
    }, timeout=60)
    out = json.loads(proc.stdout.strip())
    assert out["metric"] == "bert_large_phase2_kfac_seq_per_sec"


class TestFlops:
    def _config(self):
        from bert_pytorch_tpu.config import BertConfig
        return BertConfig(
            vocab_size=30528, hidden_size=1024, num_hidden_layers=24,
            num_attention_heads=16, intermediate_size=4096)

    def test_bert_large_phase1_flops(self):
        from bert_pytorch_tpu.utils import flops
        got = flops.bert_train_flops_per_seq(
            self._config(), seq_len=128, max_pred_per_seq=20)
        # Hand-derived: encoder 24*(8*128*1024^2 + 4*128^2*1024 +
        # 4*128*1024*4096) + heads 20*(2*1024^2 + 2*1024*30528) + pooler
        # + NSP, all x3 for fwd+bwd.
        enc = 24 * (8 * 128 * 1024**2 + 4 * 128**2 * 1024
                    + 4 * 128 * 1024 * 4096)
        heads = 20 * (2 * 1024**2 + 2 * 1024 * 30528)
        heads += 2 * 1024**2 + 2 * 1024 * 2
        assert got == pytest.approx(3.0 * (enc + heads), rel=1e-12)
        # Sanity: BERT-large phase-1 is ~0.24 TFLOPs/seq.
        assert 0.2e12 < got < 0.3e12

    def test_phase2_flops_larger_than_phase1(self):
        from bert_pytorch_tpu.utils import flops
        p1 = flops.bert_train_flops_per_seq(self._config(), 128, 20)
        p2 = flops.bert_train_flops_per_seq(self._config(), 512, 80)
        # Phase 2 is ~4-5x the FLOPs (seq 4x + quadratic attention term).
        assert 4.0 < p2 / p1 < 5.5

    def test_peak_lookup_and_mfu(self):
        from bert_pytorch_tpu.utils import flops
        assert flops.peak_tflops("TPU v5e") == 197.0
        assert flops.peak_tflops("TPU v4") == 275.0
        assert flops.peak_tflops("cpu") == 0.0
        c = self._config()
        per_seq = flops.bert_train_flops_per_seq(c, 128, 20)
        # The round-1 claimed 396 seq/s/chip on v5e must land near 0.5 MFU.
        assert 0.4 < flops.mfu(396.0, per_seq, "TPU v5e") < 0.55
        assert flops.mfu(396.0, per_seq, "unknown-device") == 0.0


class TestCompileCache:
    """enable_compile_cache validates the directory up front (a failure at
    compile time would only surface as a buried JAX warning)."""

    def test_enables_and_creates_dir(self, tmp_path):
        import jax

        from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache

        target = tmp_path / "nested" / "cache"
        before_dir = jax.config.jax_compilation_cache_dir
        before_min = jax.config.jax_persistent_cache_min_compile_time_secs
        try:
            assert enable_compile_cache(str(target)) is True
            assert target.is_dir()
            assert jax.config.jax_compilation_cache_dir == str(target)
        finally:
            jax.config.update("jax_compilation_cache_dir", before_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", before_min)

    def test_empty_disables(self):
        from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache

        assert enable_compile_cache("") is False

    def test_unwritable_dir_reports_and_degrades(self, capsys):
        from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache

        assert enable_compile_cache("/proc/1/nonexistent/cache") is False
        assert "compile cache disabled" in capsys.readouterr().out


class TestPallasBhBlockOverride:
    def test_env_override_raises_cap(self, monkeypatch):
        from bert_pytorch_tpu.ops.pallas.attention import _pick_bh_block

        # default heuristic caps at 16 (the 4096 VMEM budget)
        monkeypatch.delenv("PALLAS_ATTN_BH_BLOCK", raising=False)
        assert _pick_bh_block(128, 896) == 16
        # the sweep's override probes past the cap...
        monkeypatch.setenv("PALLAS_ATTN_BH_BLOCK", "32")
        assert _pick_bh_block(128, 896) == 32
        # ...but the divisibility walk still rules: bh % g == 0
        assert _pick_bh_block(128, 48) == 16
