"""Checkpoint subsystem unit tests: atomic writes, retention, async mode."""

import numpy as np

from bert_pytorch_tpu.utils import checkpoint as ckpt


def _contents(step):
    return {"model": {"w": np.full((4, 4), float(step))}, "epoch": step}


def test_save_load_roundtrip(tmp_path):
    path = ckpt.save_checkpoint(str(tmp_path), 3, _contents(3))
    state = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(state["model"]["w"], np.full((4, 4), 3.0))
    assert state["epoch"] == 3
    assert ckpt.find_resume_step(str(tmp_path)) == 3


def test_retention_keeps_newest(tmp_path):
    for step in (1, 2, 3, 4, 5):
        ckpt.save_checkpoint(str(tmp_path), step, _contents(step), keep=3)
    assert ckpt.find_resume_step(str(tmp_path)) == 5
    steps = sorted(
        int(m.group(1)) for name in tmp_path.iterdir()
        if (m := ckpt.CKPT_RE.search(name.name)))
    assert steps == [3, 4, 5]


def test_async_write_lands_and_orders(tmp_path):
    """Async saves must serialize in order and be visible after the wait."""
    for step in (1, 2, 3, 4):
        ckpt.save_checkpoint(str(tmp_path), step, _contents(step), keep=2,
                             async_write=True)
    ckpt.wait_for_pending_save()
    assert ckpt.find_resume_step(str(tmp_path)) == 4
    state = ckpt.load_checkpoint(ckpt.checkpoint_path(str(tmp_path), 4))
    np.testing.assert_array_equal(state["model"]["w"], np.full((4, 4), 4.0))
    steps = sorted(
        int(m.group(1)) for name in tmp_path.iterdir()
        if (m := ckpt.CKPT_RE.search(name.name)))
    assert steps == [3, 4]
    # no stray tmp files
    assert not [p for p in tmp_path.iterdir() if p.suffix == ".tmp"]


def test_async_snapshot_immune_to_mutation(tmp_path):
    """The state must be snapshotted before save_checkpoint returns: mutating
    the source buffers afterwards (what donated train-state buffers do on the
    next step) cannot corrupt the written checkpoint."""
    contents = _contents(7)
    ckpt.save_checkpoint(str(tmp_path), 7, contents, async_write=True)
    contents["model"]["w"][:] = -1.0  # simulate buffer reuse
    ckpt.wait_for_pending_save()
    state = ckpt.load_checkpoint(ckpt.checkpoint_path(str(tmp_path), 7))
    np.testing.assert_array_equal(state["model"]["w"], np.full((4, 4), 7.0))


def test_wait_without_pending_is_noop():
    ckpt.wait_for_pending_save()


def test_async_write_failure_raises_at_wait(tmp_path, monkeypatch):
    """A failed background write must surface, not let training run on."""
    import pytest

    def boom(*args, **kwargs):
        raise OSError("disk full")

    monkeypatch.setattr(ckpt, "_write_and_prune", boom)
    ckpt.save_checkpoint(str(tmp_path), 1, _contents(1), async_write=True)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        ckpt.wait_for_pending_save()
    # error is consumed; subsequent waits are clean
    ckpt.wait_for_pending_save()
