"""Checkpoint subsystem unit tests: atomic writes, retention, async mode."""

import numpy as np

from bert_pytorch_tpu.utils import checkpoint as ckpt


def _contents(step):
    return {"model": {"w": np.full((4, 4), float(step))}, "epoch": step}


def test_save_load_roundtrip(tmp_path):
    path = ckpt.save_checkpoint(str(tmp_path), 3, _contents(3))
    state = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(state["model"]["w"], np.full((4, 4), 3.0))
    assert state["epoch"] == 3
    assert ckpt.find_resume_step(str(tmp_path)) == 3


def test_retention_keeps_newest(tmp_path):
    for step in (1, 2, 3, 4, 5):
        ckpt.save_checkpoint(str(tmp_path), step, _contents(step), keep=3)
    assert ckpt.find_resume_step(str(tmp_path)) == 5
    steps = sorted(
        int(m.group(1)) for name in tmp_path.iterdir()
        if (m := ckpt.CKPT_RE.search(name.name)))
    assert steps == [3, 4, 5]


def test_async_write_lands_and_orders(tmp_path):
    """Async saves must serialize in order and be visible after the wait."""
    for step in (1, 2, 3, 4):
        ckpt.save_checkpoint(str(tmp_path), step, _contents(step), keep=2,
                             async_write=True)
    ckpt.wait_for_pending_save()
    assert ckpt.find_resume_step(str(tmp_path)) == 4
    state = ckpt.load_checkpoint(ckpt.checkpoint_path(str(tmp_path), 4))
    np.testing.assert_array_equal(state["model"]["w"], np.full((4, 4), 4.0))
    steps = sorted(
        int(m.group(1)) for name in tmp_path.iterdir()
        if (m := ckpt.CKPT_RE.search(name.name)))
    assert steps == [3, 4]
    # no stray tmp files
    assert not [p for p in tmp_path.iterdir() if p.suffix == ".tmp"]


def test_async_snapshot_immune_to_mutation(tmp_path):
    """The state must be snapshotted before save_checkpoint returns: mutating
    the source buffers afterwards (what donated train-state buffers do on the
    next step) cannot corrupt the written checkpoint."""
    contents = _contents(7)
    ckpt.save_checkpoint(str(tmp_path), 7, contents, async_write=True)
    contents["model"]["w"][:] = -1.0  # simulate buffer reuse
    ckpt.wait_for_pending_save()
    state = ckpt.load_checkpoint(ckpt.checkpoint_path(str(tmp_path), 7))
    np.testing.assert_array_equal(state["model"]["w"], np.full((4, 4), 7.0))


def test_wait_without_pending_is_noop():
    ckpt.wait_for_pending_save()


def test_async_write_failure_raises_at_wait(tmp_path, monkeypatch):
    """A failed background write must surface, not let training run on."""
    import pytest

    def boom(*args, **kwargs):
        raise OSError("disk full")

    monkeypatch.setattr(ckpt, "_write_and_prune", boom)
    ckpt.save_checkpoint(str(tmp_path), 1, _contents(1), async_write=True)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        ckpt.wait_for_pending_save()
    # error is consumed; subsequent waits are clean
    ckpt.wait_for_pending_save()


def test_load_latest_skips_corrupt_newest(tmp_path):
    """A corrupt newest checkpoint must not kill resume: the loader warns
    and falls back to the previous retained one."""
    import pytest

    ckpt.save_checkpoint(str(tmp_path), 1, _contents(1))
    ckpt.save_checkpoint(str(tmp_path), 2, _contents(2))
    # truncate the newest file mid-blob
    p2 = ckpt.checkpoint_path(str(tmp_path), 2)
    blob = open(p2, "rb").read()
    open(p2, "wb").write(blob[: len(blob) // 2])
    with pytest.warns(UserWarning, match="Skipping unreadable checkpoint"):
        step, state = ckpt.load_latest_checkpoint(str(tmp_path))
    assert step == 1
    assert state["epoch"] == 1


def test_load_latest_none_when_all_corrupt(tmp_path):
    import pytest

    ckpt.save_checkpoint(str(tmp_path), 1, _contents(1))
    p = ckpt.checkpoint_path(str(tmp_path), 1)
    open(p, "wb").write(b"not msgpack")
    with pytest.warns(UserWarning):
        assert ckpt.load_latest_checkpoint(str(tmp_path)) is None
    assert ckpt.load_latest_checkpoint(str(tmp_path / "missing")) is None


def test_agree_on_resume_step_policies(monkeypatch):
    """Multi-host resume agreement (utils/dist.py): same -> keep, differing
    loadable steps -> minimum, any-missing-while-others-have -> fail fast."""
    import pytest

    from bert_pytorch_tpu.utils import dist

    monkeypatch.setattr(dist.jax, "process_count", lambda: 2)

    class FakeMH:
        def __init__(self, values):
            self.values = values

        def process_allgather(self, _x):
            return np.asarray(self.values, np.int32)

    def run(values, step):
        # Patch dist's own accessor seam, not sys.modules: once the real
        # multihost_utils has been imported anywhere in the process, a
        # 'from jax.experimental import ...' binds the package attribute
        # and a sys.modules patch is silently ignored (order-dependent
        # failure in the full suite).
        monkeypatch.setattr(
            dist, "_multihost_utils", lambda: FakeMH(values))
        return dist.agree_on_resume_step(step)

    assert run([7, 7], 7) == 7
    assert run([5, 7], 7) == 5          # lagging host wins: everyone at 5
    assert run([-1, -1], None) is None  # nobody has one: fresh start
    with pytest.raises(RuntimeError, match="inconsistent across hosts"):
        run([-1, 7], 7)


def test_latest_checkpoint_public_and_missing_dir_safe(tmp_path):
    assert ckpt.latest_checkpoint(str(tmp_path / "not_there")) is None
    assert ckpt.latest_checkpoint(str(tmp_path)) is None  # empty dir
    for step in (2, 9):
        ckpt.save_checkpoint(str(tmp_path), step, _contents(step))
    assert ckpt.latest_checkpoint(str(tmp_path)) == ckpt.checkpoint_path(
        str(tmp_path), 9)


def test_load_params_only_skips_optimizer_subtree(tmp_path):
    """Serving restores just the model subtree: the optimizer bytes are
    skipped by the streaming unpacker, never decoded into arrays."""
    import pytest

    params = {"dense": {"kernel": np.arange(6, dtype=np.float32).reshape(2, 3),
                        "bias": np.full(3, 0.5, np.float32)}}
    heavy_opt = {"mu": {"dense": {"kernel": np.ones((2, 3), np.float32)}},
                 "nu": {"dense": {"kernel": np.ones((2, 3), np.float32)}}}
    path = ckpt.save_checkpoint(
        str(tmp_path), 4,
        {"model": params, "optimizer": heavy_opt, "epoch": 1})

    # The streaming extractor finds the subtree without a full decode.
    blob = open(path, "rb").read()
    sub = ckpt._extract_toplevel_subtree(blob, "model")
    assert sub is not None
    np.testing.assert_array_equal(
        np.asarray(sub["dense"]["kernel"]), params["dense"]["kernel"])

    target = {"dense": {"kernel": np.zeros((2, 3), np.float32),
                        "bias": np.zeros(3, np.float32)}}
    out = ckpt.load_params_only(path, target)
    np.testing.assert_array_equal(out["dense"]["kernel"],
                                  params["dense"]["kernel"])
    np.testing.assert_array_equal(out["dense"]["bias"],
                                  params["dense"]["bias"])
    with pytest.raises(KeyError, match="no top-level"):
        ckpt.load_params_only(path, target, key="preconditioner")
