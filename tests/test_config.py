"""Config-system unit tests: BertConfig merge semantics and the three-level
CLI > JSON-config-file > argparse-defaults precedence (SURVEY §5.6;
reference run_pretraining.py:75-177, src/modeling.py:188-295)."""

import argparse
import json

import pytest

from bert_pytorch_tpu.config import (
    BertConfig,
    parse_args_with_config_file,
    require_args,
)


def _parser():
    p = argparse.ArgumentParser()
    p.add_argument("--config_file", type=str, default=None)
    p.add_argument("--learning_rate", type=float, default=1e-3)
    p.add_argument("--max_steps", type=int, default=None)
    p.add_argument("--optimizer", type=str, default="lamb")
    p.add_argument("--kfac", action="store_true")
    return p


class TestPrecedence:
    def test_defaults_when_no_config(self):
        args = parse_args_with_config_file(_parser(), [])
        assert args.learning_rate == 1e-3 and args.optimizer == "lamb"

    def test_json_overrides_defaults(self, tmp_path):
        cfg = tmp_path / "t.json"
        cfg.write_text(json.dumps(
            {"learning_rate": 6e-3, "max_steps": 7038, "kfac": True}))
        args = parse_args_with_config_file(
            _parser(), ["--config_file", str(cfg)])
        assert args.learning_rate == 6e-3
        assert args.max_steps == 7038
        assert args.kfac is True  # store_true flag set from JSON
        assert args.optimizer == "lamb"  # untouched default

    def test_explicit_cli_beats_json(self, tmp_path):
        cfg = tmp_path / "t.json"
        cfg.write_text(json.dumps({"learning_rate": 6e-3, "max_steps": 7038}))
        args = parse_args_with_config_file(
            _parser(),
            ["--config_file", str(cfg), "--learning_rate", "4e-3"])
        # CLI wins over JSON; JSON still beats the default for other keys.
        assert args.learning_rate == 4e-3
        assert args.max_steps == 7038

    def test_unknown_json_key_rejected(self, tmp_path):
        cfg = tmp_path / "t.json"
        cfg.write_text(json.dumps({"not_a_flag": 1}))
        with pytest.raises(ValueError, match="not_a_flag"):
            parse_args_with_config_file(_parser(), ["--config_file", str(cfg)])

    def test_require_args_from_either_source(self, tmp_path):
        cfg = tmp_path / "t.json"
        cfg.write_text(json.dumps({"max_steps": 10}))
        args = parse_args_with_config_file(
            _parser(), ["--config_file", str(cfg)])
        require_args(args, ["max_steps"])  # satisfied via JSON
        args2 = parse_args_with_config_file(_parser(), [])
        with pytest.raises(ValueError, match="max_steps"):
            require_args(args2, ["max_steps"])


class TestBertConfig:
    def test_from_dict_merges_onto_defaults(self):
        cfg = BertConfig.from_dict({"hidden_size": 1024, "vocab_file": "/v"})
        assert cfg.hidden_size == 1024
        assert cfg.num_hidden_layers == 12  # default survives
        assert cfg.vocab_file == "/v"  # extra key rides along

    def test_json_roundtrip(self, tmp_path):
        path = tmp_path / "c.json"
        BertConfig(hidden_size=256, tokenizer="wordpiece").to_json_file(
            str(path))
        cfg = BertConfig.from_json_file(str(path))
        assert cfg.hidden_size == 256
        assert cfg.tokenizer == "wordpiece"
