"""Smoke tests for the repo-root convergence tools (summarizer + plotter) —
the artifact post-processing behind scripts/convergence_r02.sh."""

import csv
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_csv(path, legs=("lamb", "kfac"), steps=30, sps=None):
    """sps: optional {leg: samples_per_second} for wallclock columns."""
    with open(path, "w", newline="") as f:
        wr = csv.writer(f)
        cols = ["optimizer", "step", "loss", "mlm_accuracy", "learning_rate"]
        if sps:
            cols.append("samples_per_second")
        wr.writerow(cols)
        for leg in legs:
            for s in range(1, steps + 1):
                loss = 7.0 - 0.05 * s - (0.1 if leg.startswith("kfac") else 0.0)
                row = [leg, s, loss, 0.01 * s, 1e-3]
                if sps:
                    # the runner logs 0 on the first row (timer not yet
                    # started); the summarizer must skip it, not crash
                    row.append(0 if s == 1 else sps[leg])
                wr.writerow(row)


def _summarize(path):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "summarize_convergence.py"), str(path)],
        capture_output=True, text=True, check=True)
    return json.loads(out.stdout)


def test_summarizer_two_legs(tmp_path):
    path = tmp_path / "conv.csv"
    _write_csv(path)
    rec = _summarize(path)
    assert set(rec["legs"]) == {"lamb", "kfac"}
    assert rec["legs"]["lamb"]["steps"] == 30
    # kfac runs 0.1 LOWER than lamb at every step in this fixture, so the
    # advantage (lamb - kfac, positive = K-FAC ahead) is +0.1
    cmp = rec["kfac_vs_lamb"]["kfac"]
    assert cmp["equal_step"] == 30
    assert abs(cmp["kfac_advantage"] - 0.1) < 1e-6
    assert "equal_wallclock" not in cmp  # no samples_per_second column


def test_summarizer_equal_wallclock(tmp_path):
    # K-FAC leads by 0.1 at equal steps but runs at HALF the throughput:
    # at LAMB's 30-step horizon K-FAC has only reached step 15, where its
    # loss (7 - .05*15 - .1 = 6.15) trails LAMB's step-30 loss (5.5).
    path = tmp_path / "conv.csv"
    _write_csv(path, legs=("lamb", "kfac_ref"),
               sps={"lamb": 100.0, "kfac_ref": 50.0})
    cmp = _summarize(path)["kfac_vs_lamb"]["kfac_ref"]
    assert abs(cmp["kfac_advantage"] - 0.1) < 1e-6
    wc = cmp["equal_wallclock"]
    assert wc["lamb_step"] == 30 and wc["kfac_step"] == 15
    assert abs(wc["step_cost_ratio"] - 2.0) < 1e-6
    assert abs(wc["kfac_advantage"] - (5.5 - 6.15)) < 1e-6  # negative


def test_plotter_writes_png(tmp_path):
    one = tmp_path / "one.csv"
    _write_csv(one, legs=("lamb",))
    two = tmp_path / "two.csv"
    _write_csv(two)
    for src, name in ((one, "one.png"), (two, "two.png")):
        out_png = tmp_path / name
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "plot_convergence.py"),
             str(src), str(out_png), "test title"],
            capture_output=True, text=True, check=True)
        assert out_png.stat().st_size > 10_000  # a real rendered figure
        assert out_png.read_bytes()[:8] == b"\x89PNG\r\n\x1a\n"
