"""Smoke tests for the repo-root convergence tools (summarizer + plotter) —
the artifact post-processing behind scripts/convergence_r02.sh."""

import csv
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_csv(path, legs=("lamb", "kfac"), steps=30):
    with open(path, "w", newline="") as f:
        wr = csv.writer(f)
        wr.writerow(["optimizer", "step", "loss", "mlm_accuracy",
                     "learning_rate"])
        for leg in legs:
            for s in range(1, steps + 1):
                loss = 7.0 - 0.05 * s - (0.1 if leg == "kfac" else 0.0)
                wr.writerow([leg, s, loss, 0.01 * s, 1e-3])


def test_summarizer_two_legs(tmp_path):
    path = tmp_path / "conv.csv"
    _write_csv(path)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "summarize_convergence.py"), str(path)],
        capture_output=True, text=True, check=True)
    rec = json.loads(out.stdout)
    assert set(rec["legs"]) == {"lamb", "kfac"}
    assert rec["legs"]["lamb"]["steps"] == 30
    # kfac runs 0.1 LOWER than lamb at every step in this fixture, so the
    # advantage (lamb - kfac, positive = K-FAC ahead) is +0.1
    cmp = rec["kfac_vs_lamb"]
    assert cmp["equal_step"] == 30
    assert abs(cmp["kfac_advantage"] - 0.1) < 1e-6


def test_plotter_writes_png(tmp_path):
    one = tmp_path / "one.csv"
    _write_csv(one, legs=("lamb",))
    two = tmp_path / "two.csv"
    _write_csv(two)
    for src, name in ((one, "one.png"), (two, "two.png")):
        out_png = tmp_path / name
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "plot_convergence.py"),
             str(src), str(out_png), "test title"],
            capture_output=True, text=True, check=True)
        assert out_png.stat().st_size > 10_000  # a real rendered figure
        assert out_png.read_bytes()[:8] == b"\x89PNG\r\n\x1a\n"
