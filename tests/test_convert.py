"""Pretrained-weight import/export tests (models/convert.py).

Oracles: HF transformers' torch BertForPreTraining (same lineage as the
reference's modeling.py) for numerical agreement, and a synthetic Google-
style TF checkpoint for the load_tf_weights_in_bert path
(reference modeling.py:58-116, from_pretrained :659-799).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import (
    BertForPreTraining,
    convert_torch_state_dict,
    export_torch_state_dict,
    from_pretrained,
    load_tf_checkpoint,
    merge_params,
)

HIDDEN, LAYERS, HEADS, INTER, VOCAB, TYPES = 32, 2, 4, 64, 100, 2


@pytest.fixture(scope="module")
def hf_model():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    hf_config = transformers.BertConfig(
        vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=LAYERS,
        num_attention_heads=HEADS, intermediate_size=INTER,
        max_position_embeddings=64, type_vocab_size=TYPES,
        hidden_act="gelu", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, layer_norm_eps=1e-12)
    torch.manual_seed(0)
    model = transformers.BertForPreTraining(hf_config).eval()
    return model


@pytest.fixture(scope="module")
def our_config():
    return BertConfig(
        vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=LAYERS,
        num_attention_heads=HEADS, intermediate_size=INTER,
        max_position_embeddings=64, type_vocab_size=TYPES,
        next_sentence=True)


def test_hf_forward_agreement(hf_model, our_config):
    """Imported HF weights reproduce the HF forward pass bit-for-bit-ish."""
    import torch

    params = convert_torch_state_dict(hf_model.state_dict(), our_config)
    model = BertForPreTraining(our_config, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    B, S = 3, 16
    ids = rng.integers(0, VOCAB, (B, S)).astype(np.int32)
    types = rng.integers(0, TYPES, (B, S)).astype(np.int32)
    mask = np.ones((B, S), np.int32)
    mask[:, -3:] = 0

    with torch.no_grad():
        out = hf_model(
            input_ids=torch.from_numpy(ids.astype(np.int64)),
            token_type_ids=torch.from_numpy(types.astype(np.int64)),
            attention_mask=torch.from_numpy(mask.astype(np.int64)))
    mlm, nsp = model.apply(
        {"params": params}, jnp.asarray(ids), jnp.asarray(types),
        jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(mlm), out.prediction_logits.numpy(), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(nsp), out.seq_relationship_logits.numpy(),
        rtol=1e-4, atol=1e-4)


def test_export_roundtrip(our_config):
    """params -> torch naming -> params is the identity."""
    import flax.linen as nn

    model = BertForPreTraining(our_config, dtype=jnp.float32)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(1), *(jnp.zeros((1, 8), jnp.int32),) * 3))["params"]
    sd = export_torch_state_dict(params, our_config)
    back = convert_torch_state_dict(sd, our_config)
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(back))
    for path, leaf in flat_a:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_b[path]), rtol=1e-6,
            err_msg=str(path))


def test_vocab_padding(our_config, hf_model):
    """MXU %8 vocab padding (run_pretraining.py:157): checkpoint vocab 100
    loads into a config padded to 104 with zero rows."""
    padded = BertConfig.from_dict({**our_config.to_dict(), "vocab_size": 104})
    params = convert_torch_state_dict(hf_model.state_dict(), padded)
    emb = params["bert"]["embeddings"]["word_embeddings"]["embedding"]
    assert emb.shape == (104, HIDDEN)
    assert np.all(emb[100:] == 0)
    assert params["predictions"]["bias"].shape == (104,)


def test_partial_load_merges_over_init(our_config, hf_model):
    """Backbone-only checkpoints merge over fresh heads — the strict=False
    load of reference run_squad.py:957-961."""
    import flax.linen as nn

    sd = {k: v for k, v in hf_model.state_dict().items()
          if k.startswith("bert.")}
    loaded = convert_torch_state_dict(sd, our_config)
    assert "predictions" not in loaded
    model = BertForPreTraining(our_config, dtype=jnp.float32)
    init = nn.unbox(model.init(
        jax.random.PRNGKey(0), *(jnp.zeros((1, 8), jnp.int32),) * 3))["params"]
    merged = merge_params(init, loaded)
    assert "predictions" in merged  # head kept from init
    np.testing.assert_allclose(
        np.asarray(merged["bert"]["embeddings"]["word_embeddings"]["embedding"]),
        hf_model.state_dict()["bert.embeddings.word_embeddings.weight"].numpy())


def test_tf_checkpoint_loading(tmp_path, our_config, hf_model):
    """Google-style TF checkpoint (v1 names: layer_N, kernel/gamma/beta,
    output_bias/output_weights) loads identically to the torch path."""
    tf = pytest.importorskip("tensorflow")

    sd = {k: v.numpy() for k, v in hf_model.state_dict().items()}
    tf_vars = {}
    for name, arr in sd.items():
        if name == "cls.predictions.decoder.weight" or name.endswith(
                "position_ids"):
            continue
        parts = []
        for piece in name.split("."):
            parts.append(piece)
        tf_name = "/".join(parts)
        tf_name = tf_name.replace("LayerNorm/weight", "LayerNorm/gamma")
        tf_name = tf_name.replace("LayerNorm/bias", "LayerNorm/beta")
        import re
        tf_name = re.sub(r"layer/(\d+)", r"layer_\1", tf_name)
        if tf_name == "cls/seq_relationship/weight":
            tf_name, arr = "cls/seq_relationship/output_weights", arr
        elif tf_name == "cls/seq_relationship/bias":
            tf_name = "cls/seq_relationship/output_bias"
        elif tf_name == "cls/predictions/bias":
            tf_name = "cls/predictions/output_bias"
        elif tf_name.endswith("/weight"):
            tf_name, arr = tf_name[:-len("/weight")] + "/kernel", arr.T
        elif tf_name.endswith("/bias"):
            pass
        tf_vars[tf_name] = arr

    ckpt_prefix = str(tmp_path / "bert_model.ckpt")
    with tf.compat.v1.Graph().as_default():
        variables = [
            tf.compat.v1.get_variable(
                name, initializer=tf.constant(value))
            for name, value in tf_vars.items()
        ]
        saver = tf.compat.v1.train.Saver(variables)
        with tf.compat.v1.Session() as sess:
            sess.run(tf.compat.v1.global_variables_initializer())
            saver.save(sess, ckpt_prefix)

    sd_tf = load_tf_checkpoint(ckpt_prefix)
    params_tf = convert_torch_state_dict(sd_tf, our_config)
    params_torch = convert_torch_state_dict(hf_model.state_dict(), our_config)
    flat_torch = dict(jax.tree_util.tree_leaves_with_path(params_torch))
    for path, leaf in jax.tree_util.tree_leaves_with_path(params_tf):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_torch[path]), rtol=1e-6,
            err_msg=str(path))


def test_from_pretrained_directory(tmp_path, our_config, hf_model):
    """Archive-directory loading: config.json + pytorch_model.bin
    (reference from_pretrained, modeling.py:659-799)."""
    import json

    import torch

    archive = tmp_path / "archive"
    archive.mkdir()
    (archive / "config.json").write_text(json.dumps({
        "vocab_size": VOCAB, "hidden_size": HIDDEN,
        "num_hidden_layers": LAYERS, "num_attention_heads": HEADS,
        "intermediate_size": INTER, "max_position_embeddings": 64,
        "type_vocab_size": TYPES, "next_sentence": True}))
    torch.save(hf_model.state_dict(), archive / "pytorch_model.bin")
    config, params = from_pretrained(str(archive))
    assert config.hidden_size == HIDDEN
    model = BertForPreTraining(config, dtype=jnp.float32)
    ids = jnp.zeros((1, 8), jnp.int32)
    mlm, nsp = model.apply({"params": params}, ids, ids, jnp.ones((1, 8), jnp.int32))
    assert mlm.shape == (1, 8, VOCAB)


def test_squad_runner_accepts_torch_init(tmp_path, our_config, hf_model):
    """run_squad.load_init_params loads a torch .bin archive (the reference
    --init_checkpoint from_pretrained path) and keeps the fresh QA head."""
    import argparse

    import flax.linen as nn
    import torch

    import run_squad
    from bert_pytorch_tpu.models import BertForQuestionAnswering

    weights = tmp_path / "pytorch_model.bin"
    torch.save(hf_model.state_dict(), weights)
    model = BertForQuestionAnswering(our_config, dtype=jnp.float32)
    init = nn.unbox(model.init(
        jax.random.PRNGKey(0), *(jnp.zeros((1, 8), jnp.int32),) * 3))["params"]
    args = argparse.Namespace(init_checkpoint=str(weights))
    params = run_squad.load_init_params(args, init, our_config)
    np.testing.assert_allclose(
        np.asarray(params["bert"]["embeddings"]["word_embeddings"]["embedding"]),
        hf_model.state_dict()["bert.embeddings.word_embeddings.weight"].numpy())
    assert "qa_outputs" in params


def test_from_pretrained_url(tmp_path, our_config, hf_model, monkeypatch):
    """URL weights resolve through the cached_path download cache
    (reference from_pretrained's cached_path step, file_utils.py:97-125)."""
    import http.server
    import threading

    import torch

    weights = tmp_path / "w.bin"
    torch.save(hf_model.state_dict(), weights)
    blob = weights.read_bytes()

    class Handler(http.server.BaseHTTPRequestHandler):
        def _respond(self):
            self.send_response(200)
            self.send_header("ETag", '"w1"')
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()

        def do_HEAD(self):
            self._respond()

        def do_GET(self):
            self._respond()
            self.wfile.write(blob)

        def log_message(self, *a):
            pass

    server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    monkeypatch.setenv("BERT_TPU_CACHE", str(tmp_path / "cache"))
    import bert_pytorch_tpu.utils.file_utils as fu
    monkeypatch.setattr(fu, "CACHE_DIR", str(tmp_path / "cache"))
    try:
        url = f"http://127.0.0.1:{server.server_port}/pytorch_model.bin"
        config, params = from_pretrained(url, config=our_config)
        assert "predictions" in params
        np.testing.assert_allclose(
            np.asarray(params["bert"]["embeddings"]["word_embeddings"]["embedding"]),
            hf_model.state_dict()["bert.embeddings.word_embeddings.weight"].numpy())
    finally:
        server.shutdown()


def test_training_trajectory_parity_vs_torch(hf_model, our_config):
    """Lockstep TRAINING parity against torch: same init (HF weights
    imported), same batch, same SGD learning rate, five full
    forward/backward/update steps — the per-step losses must track within
    fp32 tolerance. This anchors the whole training trajectory (loss,
    gradients through every layer incl. the tied decoder, parameter
    update) to an external implementation, not just the forward pass
    (VERDICT r2 'no loss-vs-step curve is anchored to anything
    external')."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    import optax

    from bert_pytorch_tpu.models.losses import pretraining_loss

    rng = np.random.default_rng(7)
    B, S = 4, 32
    input_ids = rng.integers(0, VOCAB, (B, S)).astype(np.int64)
    token_type = rng.integers(0, TYPES, (B, S)).astype(np.int64)
    attention = np.ones((B, S), np.int64)
    mask = rng.random((B, S)) < 0.2
    mlm_torch = np.where(mask, input_ids, -100)
    mlm_ours = np.where(mask, input_ids, -1).astype(np.int32)
    nsp = rng.integers(0, 2, (B,)).astype(np.int64)

    # -- torch side: fresh copy of the HF model, SGD lr 0.1
    import copy

    tmodel = copy.deepcopy(hf_model).train()
    opt = torch.optim.SGD(tmodel.parameters(), lr=0.1)
    t_in = {
        "input_ids": torch.tensor(input_ids),
        "token_type_ids": torch.tensor(token_type),
        "attention_mask": torch.tensor(attention),
    }
    torch_losses = []
    for _ in range(5):
        opt.zero_grad()
        out = tmodel(**t_in)
        mlm_loss = F.cross_entropy(
            out.prediction_logits.reshape(-1, VOCAB),
            torch.tensor(mlm_torch.reshape(-1)), ignore_index=-100)
        nsp_loss = F.cross_entropy(
            out.seq_relationship_logits, torch.tensor(nsp))
        loss = mlm_loss + nsp_loss
        loss.backward()
        opt.step()
        torch_losses.append(float(loss))

    # -- our side: import the SAME initial weights, optax SGD lr 0.1
    model = BertForPreTraining(our_config, dtype=jnp.float32)
    params = convert_torch_state_dict(hf_model.state_dict(), our_config)
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            mlm_logits, nsp_logits = model.apply(
                {"params": p}, jnp.asarray(input_ids, jnp.int32),
                jnp.asarray(token_type, jnp.int32),
                jnp.asarray(attention, jnp.int32))
            return pretraining_loss(
                mlm_logits, nsp_logits, jnp.asarray(mlm_ours),
                jnp.asarray(nsp, jnp.int32))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    our_losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
        our_losses.append(float(loss))

    # Identical math on both sides; fp32 accumulation-order differences
    # grow slowly over steps at this scale.
    np.testing.assert_allclose(our_losses, torch_losses, rtol=2e-4)
    # and training actually moved the loss
    assert our_losses[-1] < our_losses[0]
