"""Data runtime tests: shard streaming, masking semantics, sampler resume.

Encodes the documented behaviors of reference src/dataset.py (segment/mask
derivation examples at dataset.py:224-252, masking at :277-296, sampler
resume at :401-425).
"""

import numpy as np
import pytest

from bert_pytorch_tpu.data import (
    DataLoader,
    DistributedSampler,
    ShardedPretrainingDataset,
)
from bert_pytorch_tpu.tools.make_synthetic_data import make_shard

VOCAB = 1000
MASK_ID = 4


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    d = tmp_path_factory.mktemp("shards")
    paths = [
        make_shard(str(d / f"shard_{i}.hdf5"), 32, 64, VOCAB, seed=i)
        for i in range(3)
    ]
    return paths


@pytest.fixture(scope="module")
def legacy_shard(tmp_path_factory):
    d = tmp_path_factory.mktemp("legacy")
    return make_shard(str(d / "legacy.hdf5"), 16, 64, VOCAB, seed=9, legacy=True)


def _dataset(shards, cls=ShardedPretrainingDataset, **kw):
    return cls(
        shards, MASK_ID, max_pred_per_seq=20, masked_lm_prob=0.15,
        vocab_size=VOCAB, seed=0, **kw,
    )


def test_sequential_iteration_crosses_files(shards):
    ds = _dataset(shards)
    assert len(ds) == 96
    seen = 0
    for i in range(len(ds)):
        sample = ds[i]
        assert len(sample) == 5
        seen += 1
    assert seen == 96


def test_index_past_dataset_end_raises(shards):
    ds = _dataset(shards)
    ds[0]
    with pytest.raises(ValueError, match="exceeds dataset size"):
        ds[96]


def test_forward_skip_across_files_allowed(shards):
    """Strided readers (multi-process DataLoader workers take every Nth
    batch) may skip whole shards going forward."""
    ds = _dataset(shards)
    first = ds[0]
    third_file = ds[70]  # skips the entire second file
    assert len(first) == len(third_file) == 5
    last = ds[95]
    assert len(last) == 5


def test_epoch_wrap_mid_dataset_chunk(shards):
    """A rank whose contiguous chunk starts mid-dataset must be able to
    restart its chunk after an epoch (chunk end -> chunk start walks the
    cyclic file sequence through the wrap). The reference's one-swap
    invariant check rejected exactly this legal restart."""
    ds = _dataset(shards)
    sampler = DistributedSampler(ds, 2, 1)  # chunk = indices 48..95
    epoch1 = [ds[i] for i in sampler]
    epoch2 = [ds[i] for i in sampler]  # restart at 48 from file 2
    assert len(epoch1) == len(epoch2) == 48


def test_segment_and_mask_derivation():
    ids = np.zeros(16, np.int32)
    special = np.asarray([0, 5, 10], np.int32)
    seg = ShardedPretrainingDataset._get_segment_ids(ids, special)
    # positions 6..10 inclusive are segment 1 (dataset.py:224-238)
    assert seg[:6].sum() == 0 and (seg[6:11] == 1).all() and seg[11:].sum() == 0
    mask = ShardedPretrainingDataset._get_input_mask(ids, special)
    assert (mask[:11] == 1).all() and mask[11:].sum() == 0


def test_masking_statistics(shards):
    ds = _dataset(shards)
    n_masked, n_masktok, n_kept, n_total = 0, 0, 0, 0
    for i in range(32):
        input_ids, seg, mask, labels, nsp = ds[i]
        positions = np.nonzero(labels != -1)[0]
        assert 1 <= len(positions) <= 20
        # labels hold original ids; inputs are [MASK] / random / original
        n_masked += len(positions)
        n_masktok += int((input_ids[positions] == MASK_ID).sum())
        n_kept += int((input_ids[positions] == labels[positions]).sum())
        n_total += 1
        # special positions are never masked
        assert labels[0] == -1
    # roughly 80% [MASK], 10% kept (random replacement can collide with orig)
    assert 0.6 < n_masktok / n_masked < 0.95
    assert n_kept / n_masked < 0.3


def test_no_duplicate_mask_positions(shards):
    ds = _dataset(shards)
    for i in range(16):
        _, _, _, labels, _ = ds[i]
        pos = np.nonzero(labels != -1)[0]
        assert len(pos) == len(set(pos.tolist()))


def test_legacy_format(legacy_shard):
    ds = ShardedPretrainingDataset(
        [legacy_shard], None, 20, 0.15, vocab_size=VOCAB, seed=0
    )
    input_ids, seg, mask, labels, nsp = ds[0]
    pos = np.nonzero(labels != -1)[0]
    # pre-masked: labels reproduce the stored masked_lm ids
    assert (labels[pos] == input_ids[pos]).all()  # synthetic shard stores originals
    assert mask.sum() > 0


def test_sampler_contiguous_chunks(shards):
    ds = _dataset(shards)
    samplers = [DistributedSampler(ds, 4, r) for r in range(4)]
    chunks = [list(s) for s in samplers]
    assert all(len(c) == 24 for c in chunks)
    # contiguous, rank-ordered, covering 0..95
    flat = sum(chunks, [])
    assert flat == list(range(96))


def test_sampler_padding_non_divisible(shards):
    ds = _dataset(shards)  # 96 samples
    samplers = [DistributedSampler(ds, 5, r) for r in range(5)]
    total = sum(len(list(s)) for s in samplers)
    assert total == samplers[0].total_size == 100  # padded with wrap-around


def test_sampler_state_roundtrip(shards):
    ds = _dataset(shards)
    s = DistributedSampler(ds, 2, 0)
    for _ in range(10):
        next(s)
    state = s.state_dict()
    s2 = DistributedSampler(ds, 2, 0)
    s2.load_state_dict(state)
    assert next(s2) == next(s)


def test_sampler_state_skipped_on_mismatch(shards):
    ds = _dataset(shards)
    s = DistributedSampler(ds, 2, 0)
    state = s.state_dict()
    state["num_replicas"] = 4
    s2 = DistributedSampler(ds, 2, 0)
    with pytest.warns(UserWarning, match="replicas has changed"):
        s2.load_state_dict(state)
    assert s2.index == 0


def test_loader_batches_and_shapes(shards):
    ds = _dataset(shards)
    sampler = DistributedSampler(ds, 1, 0)
    loader = DataLoader(ds, sampler, batch_size=8)
    batches = list(loader)
    assert len(batches) == 12
    b = batches[0]
    assert b["input_ids"].shape == (8, 64)
    assert b["next_sentence_labels"].shape == (8,)
    assert b["input_ids"].dtype == np.int32


def test_loader_propagates_worker_errors(shards):
    ds = _dataset(shards)

    class BadSampler:
        def __iter__(self):
            yield 0
            raise RuntimeError("boom")

        def __len__(self):
            return 8

    loader = DataLoader(ds, BadSampler(), batch_size=1)
    with pytest.raises(RuntimeError, match="boom"):
        list(loader)


def test_loader_producer_exits_on_abandoned_iteration(tmp_path):
    """Breaking out of a DataLoader iteration must not strand the producer
    thread blocked in q.put (one leak per abandoned pass — e.g. every
    early-stopped validation pass — grows threads/memory for the run)."""
    import threading
    import time

    from bert_pytorch_tpu.data.dataset import ShardedPretrainingDataset
    from bert_pytorch_tpu.data.loader import DataLoader
    from bert_pytorch_tpu.data.sampler import DistributedSampler
    from bert_pytorch_tpu.tools.make_synthetic_data import make_shard

    path = tmp_path / "s.hdf5"
    make_shard(str(path), 64, 16, 100, seed=0)
    ds = ShardedPretrainingDataset([str(path)], 4, 4, 0.15, vocab_size=100)
    sampler = DistributedSampler(ds, num_replicas=1, rank=0)
    loader = DataLoader(ds, sampler, batch_size=4, drop_last=True)

    before = {t.ident for t in threading.enumerate()}
    for i, _ in enumerate(loader):
        if i == 1:
            break  # abandon with the queue full
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.ident not in before and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, [t.name for t in leaked]


# ---------------------------------------------------------------------------
# Multi-process DataLoader (num_workers > 0): order-exact vs the thread path
# (reference run_pretraining.py:394-395 num_workers=4 parity)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def legacy_shards(tmp_path_factory):
    """Legacy pre-masked shards: __getitem__ is fully DETERMINISTIC (no
    masking RNG), so thread and process paths must be byte-identical."""
    d = tmp_path_factory.mktemp("legacy_mp")
    return [
        make_shard(str(d / f"l{i}.hdf5"), 24, 32, VOCAB, seed=50 + i,
                   legacy=True)
        for i in range(3)
    ]


def test_loader_multiprocess_matches_thread_order(legacy_shards):
    ds = _dataset(legacy_shards)
    sampler = DistributedSampler(ds, 1, 0)
    expect = list(DataLoader(ds, sampler, batch_size=8, num_workers=0))
    ds2 = _dataset(legacy_shards)
    sampler2 = DistributedSampler(ds2, 1, 0)
    got = list(DataLoader(ds2, sampler2, batch_size=8, num_workers=2))
    assert len(got) == len(expect) == 9
    for b_t, b_p in zip(expect, got):
        for key in b_t:
            np.testing.assert_array_equal(b_t[key], b_p[key], err_msg=key)
    # epoch completed: live index reset exactly like the thread path
    assert sampler2.index == 0


def test_loader_multiprocess_sampler_index_tracks_delivery(legacy_shards):
    ds = _dataset(legacy_shards)
    sampler = DistributedSampler(ds, 1, 0)
    loader = DataLoader(ds, sampler, batch_size=8, num_workers=2)
    it = iter(loader)
    next(it)
    next(it)
    assert sampler.index == 16  # 2 delivered batches x 8
    it.close()  # abandon mid-epoch; workers must shut down


def test_loader_multiprocess_propagates_worker_errors(legacy_shards):
    ds = _dataset(legacy_shards)

    class OutOfRangeSampler:
        def __init__(self, n):
            self.n = n
            self.index = 0

        def __iter__(self):
            # dataset has 72 samples; index 10_000 explodes in the worker
            yield from list(range(8)) + [10_000] * 8

        def __len__(self):
            return self.n

    loader = DataLoader(ds, OutOfRangeSampler(16), batch_size=8,
                        num_workers=2)
    with pytest.raises(RuntimeError, match="worker"):
        list(loader)


class _DyingDataset(ShardedPretrainingDataset):
    """Worker-death fixture: exits the PROCESS (no exception to catch) when
    asked for an index past the first batch — the OOM-kill shape."""

    def __getitem__(self, idx):
        if idx >= 8:
            import os

            os._exit(3)
        return super().__getitem__(idx)


def test_loader_multiprocess_detects_silent_worker_death(legacy_shards):
    ds = _dataset(legacy_shards, cls=_DyingDataset)
    sampler = DistributedSampler(ds, 1, 0)
    loader = DataLoader(ds, sampler, batch_size=8, num_workers=1)
    # os._exit can fire before the queue's feeder thread flushes batch 0,
    # so the death may surface on the first OR second get — either way the
    # loader must raise (exit code in message), never hang.
    with pytest.raises(RuntimeError, match="died .exit code 3."):
        list(loader)


def test_loader_multiprocess_epoch_changes_masking(shards):
    """Respawned workers must fold the EPOCH into their masking RNG seed:
    without it every epoch replays identical masking draws (silently static
    masking — defeating dynamic masking's purpose)."""
    ds = _dataset(shards)
    sampler = DistributedSampler(ds, 1, 0)
    loader = DataLoader(ds, sampler, batch_size=8, num_workers=2)
    sampler.set_epoch(0)
    epoch0 = list(loader)
    sampler.set_epoch(1)
    epoch1 = list(loader)
    # same underlying samples, different masked positions/replacements
    assert len(epoch0) == len(epoch1)
    same = all(
        np.array_equal(a["masked_lm_labels"], b["masked_lm_labels"])
        for a, b in zip(epoch0, epoch1))
    assert not same, "masking draws repeated across epochs"
