"""Deployment-plane unit tests (PR 19, docs/serving.md "Model registry
& canary rollouts"): the versioned registry's state machine /
torn-write discipline / digest verification (plus its jax-free CLI),
the engine's atomic hot-swap, the router's deterministic request-hash
canary split and per-version counters, the SLO-gated RolloutController
against fake windows, the registry_event / rollout_window schema
fixtures, and the zero-tolerance report gates.

The end-to-end proof — a real 2-replica fleet rolling a published
version 1% -> 50% -> 100% and auto-rolling a degraded one back — is
``tools/chaos_serve.py --canary`` (tests/test_fleet_chaos.py, slow
tier); the SIGKILL-mid-swap torn-model proof is ``--smoke`` phase D.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import threading

import pytest

from bert_pytorch_tpu.serve.registry import (GEOMETRY_KEYS, ModelRegistry,
                                             RegistryError,
                                             geometry_from_config)
from bert_pytorch_tpu.serve.rollout import RolloutController, RolloutError
from bert_pytorch_tpu.serve.router import Router, _split_hash
from bert_pytorch_tpu.telemetry import report, schema
from bert_pytorch_tpu.utils.retry import RetryPolicy

HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(HERE)


def _ckpt(tmp_path, name="ckpt_0.msgpack", payload=b"model-bytes" * 64):
    path = str(tmp_path / name)
    with open(path, "wb") as f:
        f.write(payload)
    return path


def _emitter():
    records: list = []

    def emit(rec):
        records.append(dict(rec))

    return records, emit


def _lint_records(records, tmp_path, name):
    """Stamp the sink envelope and run BOTH the per-record and the
    cross-record (file) lint — what a real artifact stream faces."""
    path = str(tmp_path / name)
    with open(path, "w") as f:
        for i, rec in enumerate(records):
            rec = dict(rec, schema=schema.SCHEMA_VERSION,
                       ts=1754300000.0 + i)
            assert schema.validate_record(rec) == [], rec
            f.write(json.dumps(rec) + "\n")
    assert schema.validate_file(path) == []


# ---------------------------------------------------------------------------
# serve/registry.py: publish, state machine, verification


def test_registry_publish_digests_and_is_immutable(tmp_path):
    records, emit = _emitter()
    reg = ModelRegistry(str(tmp_path / "reg"), emit=emit)
    ckpt = _ckpt(tmp_path)
    manifest = reg.publish("v1", task="classify", checkpoint=ckpt,
                           quantize="int8",
                           geometry={"hidden_size": 32})
    assert manifest["state"] == "staged"
    assert manifest["sha256"] and manifest["size_bytes"] == \
        os.path.getsize(ckpt)
    assert manifest["quantize"] == "int8"
    # Versions are immutable: republishing the name refuses.
    with pytest.raises(RegistryError, match="already published"):
        reg.publish("v1", task="classify", checkpoint=ckpt)
    # A fresh instance reads the same manifest back off disk.
    again = ModelRegistry(str(tmp_path / "reg"))
    assert again.get("v1")["sha256"] == manifest["sha256"]
    assert [m["version"] for m in again.list_versions()] == ["v1"]
    assert records[0]["kind"] == "registry_event"
    assert records[0]["event"] == "published"


def test_registry_refuses_missing_checkpoint(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    with pytest.raises(RegistryError, match="checkpoint missing"):
        reg.publish("v1", task="classify",
                    checkpoint=str(tmp_path / "nope.msgpack"))


def test_registry_state_machine_edges(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    ckpt = _ckpt(tmp_path)
    reg.publish("v1", task="classify", checkpoint=ckpt)
    # The only legal first move is staged -> canary (or retire).
    with pytest.raises(RegistryError, match="illegal transition"):
        reg.set_state("v1", "live")
    reg.begin_canary("v1")
    # A rollback must carry its breach reason.
    with pytest.raises(RegistryError, match="requires a reason"):
        reg.set_state("v1", "staged")
    reg.rollback("v1", "canary p95 breach")
    assert reg.get("v1")["state"] == "staged"
    assert reg.get("v1")["history"][-1]["reason"] == "canary p95 breach"
    # Re-canary and promote; a second promoted version retires the first.
    reg.begin_canary("v1")
    reg.promote("v1")
    assert reg.live_version("classify")["version"] == "v1"
    reg.publish("v2", task="classify", checkpoint=ckpt)
    reg.begin_canary("v2")
    reg.promote("v2")
    assert reg.get("v1")["state"] == "retired"
    assert reg.live_version("classify")["version"] == "v2"


def test_registry_manifest_written_tmp_rename(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish("v1", task="classify", checkpoint=_ckpt(tmp_path))
    entries = os.listdir(str(tmp_path / "reg" / "v1"))
    # tmp+rename: the version dir holds exactly the manifest — no
    # .tmp stragglers a torn writer could leave half-written.
    assert entries == ["manifest.json"]


def test_registry_verify_catches_tamper_and_size_change(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    ckpt = _ckpt(tmp_path)
    reg.publish("v1", task="classify", checkpoint=ckpt)
    ok, detail = reg.verify("v1")
    assert ok, detail
    # Same size, different bytes: only the digest catches it.
    size = os.path.getsize(ckpt)
    with open(ckpt, "r+b") as f:
        f.seek(size // 2)
        f.write(b"X")
    ok, detail = reg.verify("v1")
    assert not ok and "sha256 mismatch" in detail
    with open(ckpt, "ab") as f:
        f.write(b"tail")
    ok, detail = reg.verify("v1")
    assert not ok and "size mismatch" in detail
    os.unlink(ckpt)
    ok, detail = reg.verify("v1")
    assert not ok and "missing" in detail


def test_registry_geometry_drift(tmp_path):
    config = {"hidden_size": 32, "num_hidden_layers": 2,
              "num_attention_heads": 4, "intermediate_size": 64,
              "vocab_size": 48, "max_position_embeddings": 64,
              "hidden_act": "gelu"}
    geometry = geometry_from_config(config)
    assert set(geometry) == set(GEOMETRY_KEYS)
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish("v1", task="classify", checkpoint=_ckpt(tmp_path),
                geometry=geometry)
    ok, detail = reg.verify_geometry("v1", config)
    assert ok and "matches" in detail
    ok, detail = reg.verify_geometry("v1", dict(config, hidden_size=64))
    assert not ok and "hidden_size" in detail
    # A version published without geometry has nothing to check.
    reg.publish("v2", task="classify", checkpoint=_ckpt(tmp_path))
    ok, detail = reg.verify_geometry("v2", config)
    assert ok and "no geometry" in detail


def test_registry_lifecycle_events_are_schema_clean(tmp_path):
    records, emit = _emitter()
    reg = ModelRegistry(str(tmp_path / "reg"), emit=emit)
    ckpt = _ckpt(tmp_path)
    reg.publish("v1", task="classify", checkpoint=ckpt)
    reg.begin_canary("v1")
    reg.rollback("v1", "error budget burned")
    reg.publish("v2", task="classify", checkpoint=ckpt)
    reg.begin_canary("v2")
    reg.promote("v2")
    assert [r["event"] for r in records] == [
        "published", "state_change", "state_change",
        "published", "state_change", "state_change"]
    _lint_records(records, tmp_path, "registry_events.jsonl")


def test_registry_cli_full_lifecycle(tmp_path):
    """The jax-free operator surface: publish with geometry, list,
    verify, canary/promote/rollback — exit codes and the audit JSONL."""
    ckpt = _ckpt(tmp_path)
    config_path = str(tmp_path / "config.json")
    with open(config_path, "w") as f:
        json.dump({"hidden_size": 32, "num_hidden_layers": 2,
                   "vocab_size": 48}, f)
    root = str(tmp_path / "reg")
    audit = str(tmp_path / "audit.jsonl")
    tool = os.path.join(REPO_ROOT, "tools", "model_registry.py")

    def cli(*argv):
        return subprocess.run(
            [sys.executable, tool, "--root", root,
             "--telemetry_jsonl", audit, *argv],
            capture_output=True, text=True, cwd=REPO_ROOT)

    out = cli("publish", "v1", "--task", "classify",
              "--checkpoint", ckpt, "--config", config_path)
    assert out.returncode == 0 and "published v1" in out.stdout
    out = cli("list")
    assert out.returncode == 0
    assert "v1" in out.stdout and "L2/H32" in out.stdout
    out = cli("verify")
    assert out.returncode == 0 and "v1: OK" in out.stdout
    assert cli("canary", "v1").returncode == 0
    assert cli("promote", "v1").returncode == 0
    out = cli("promote", "v1")   # live -> live is not an edge
    assert out.returncode == 1 and "illegal transition" in out.stderr
    out = cli("publish", "v2", "--task", "classify", "--checkpoint", ckpt)
    assert out.returncode == 0
    assert cli("canary", "v2").returncode == 0
    out = cli("rollback", "v2", "--reason", "p95 breach")
    assert out.returncode == 0 and "p95 breach" in out.stdout
    # Tampering fails verify with exit 1, scoped to the bad version.
    with open(ckpt, "r+b") as f:
        f.write(b"Z")
    out = cli("verify", "v1")
    assert out.returncode == 1 and "FAIL" in out.stdout
    assert schema.validate_file(audit) == []


def test_verify_checkpoint_registry_mode(tmp_path):
    """tools/verify_checkpoint.py --registry sweeps every version of
    every named root offline: exit 0 clean, 1 on a digest mismatch."""
    reg = ModelRegistry(str(tmp_path / "reg"))
    ckpt = _ckpt(tmp_path)
    reg.publish("v1", task="classify", checkpoint=ckpt)
    tool = os.path.join(REPO_ROOT, "tools", "verify_checkpoint.py")
    out = subprocess.run(
        [sys.executable, tool, "--registry", str(tmp_path / "reg")],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "v1: verified" in out.stdout
    with open(ckpt, "r+b") as f:
        f.write(b"Z")
    out = subprocess.run(
        [sys.executable, tool, "--registry", str(tmp_path / "reg")],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert out.returncode == 1
    assert "v1: corrupt" in out.stdout


# ---------------------------------------------------------------------------
# serve/engine.py: the atomic hot-swap


@pytest.fixture(scope="module")
def swap_engine():
    """Tiny single-task engine. No warmup — these tests never run a
    forward, so construction is just a CPU param init."""
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.data.tokenization import BertTokenizer
    from bert_pytorch_tpu.serve import InferenceEngine
    from bert_pytorch_tpu.tools.make_synthetic_data import (TRACE_WORDS,
                                                            write_trace_vocab)

    import tempfile

    d = tempfile.mkdtemp(prefix="deploy_engine_")
    vocab = 5 + len(TRACE_WORDS)
    vocab += (8 - vocab % 8) % 8
    config = BertConfig(
        vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    tokenizer = BertTokenizer(write_trace_vocab(os.path.join(
        d, "vocab.txt")), do_lower_case=True)
    return InferenceEngine(
        config, tokenizer, tasks={"classify": {"labels": ["neg", "pos"]}},
        buckets=(16,), max_batch_size=2, dtype=jnp.float32, seed=7,
        version="v1")


def test_swap_params_flips_version_and_params_atomically(
        swap_engine, tmp_path):
    import jax

    from bert_pytorch_tpu.utils import checkpoint as ckpt_util

    eng = swap_engine
    assert eng.version() == "v1"
    spec = eng.tasks["classify"]
    old_leaf = jax.tree_util.tree_leaves(spec.params)[0]
    nudged = jax.tree_util.tree_map(lambda x: x + 1.0, spec.params)
    ckpt = ckpt_util.save_checkpoint(
        str(tmp_path / "swap_ckpt"), 0, {"model": nudged, "epoch": 0})
    epoch_before = eng._swap_epoch
    info = eng.swap_params("classify", ckpt, "v2")
    assert info["version"] == "v2" and info["from_version"] == "v1"
    # Same geometry, stable forward names: the swap compiles NOTHING
    # (the already-jitted forwards keep running against the new tree).
    assert info["compiles"] == 0 and info["compiles_cold"] == 0
    assert eng.version() == "v2"
    assert eng._swap_epoch == epoch_before + 1
    stats = eng.swap_stats()
    assert stats["swaps"] >= 1 and stats["torn_serves"] == 0
    new_leaf = jax.tree_util.tree_leaves(spec.params)[0]
    assert float(abs((new_leaf - old_leaf) - 1.0).max()) < 1e-6


def test_swap_params_rejects_bad_inputs(swap_engine, tmp_path):
    from bert_pytorch_tpu.serve.engine import SwapBusy

    eng = swap_engine
    with pytest.raises(ValueError, match="unknown task"):
        eng.swap_params("fill_mask", str(tmp_path / "x"), "v9")
    with pytest.raises(FileNotFoundError):
        eng.swap_params("classify", str(tmp_path / "missing.msgpack"),
                        "v9")
    # One swap in flight at a time: the second caller gets SwapBusy
    # (serve/http.py maps it to 409; the supervisor retries later).
    # The probe needs a real file — the existence check runs first.
    busy_ckpt = _ckpt(tmp_path, "busy.msgpack")
    with eng._swap_lock:
        eng._swap_inflight = True
    try:
        with pytest.raises(SwapBusy):
            eng.swap_params("classify", busy_ckpt, "v9")
    finally:
        with eng._swap_lock:
            eng._swap_inflight = False


# ---------------------------------------------------------------------------
# serve/router.py: the deterministic canary split + per-version counters


def test_split_hash_is_deterministic_and_nested():
    first = [_split_hash(seq) for seq in range(512)]
    assert first == [_split_hash(seq) for seq in range(512)]
    assert all(0.0 <= h < 1.0 for h in first)
    # Widening the share only ADDS members: the 1% cohort is a subset
    # of the 50% cohort — a request never flaps out of the canary as
    # the rollout advances.
    tiny = {s for s in range(4096) if _split_hash(s) < 0.01}
    half = {s for s in range(4096) if _split_hash(s) < 0.50}
    assert tiny <= half
    # And the share is honored to first order.
    assert 0.35 < len(half) / 4096 < 0.65


def _versioned_router(versions, events=None, **kwargs):
    def transport(url, task, payload, timeout_s):
        return 200, {"url": url}

    def scrape(url):
        return {"dispatch_alive": True, "draining": False,
                "queue_depth": 0, "version": versions[url]}

    kwargs.setdefault("retry_policy", RetryPolicy(
        attempts=3, base_delay_s=0.0, jitter=0.0))
    kwargs.setdefault("hedge_pctl", 0.0)
    r = Router(sorted(versions), emit=events.append
               if events is not None else None, transport=transport,
               scrape=scrape, sleep=lambda s: None, **kwargs)
    r.scrape_once()
    return r


def test_router_split_routes_cohort_to_canary_version():
    r = _versioned_router({"http://a:1": "v1", "http://b:2": "v2"})
    r.set_split("classify", "v2", 1.0)
    for _ in range(8):
        status, body, _ = r.handle("classify", {"text": "hi"})
        assert status == 200 and body["url"] == "http://b:2"
    window = r.split_window(reset=False)
    assert window["canary"]["requests"] == 8
    assert window["canary"]["ok"] == 8
    assert window["control"]["requests"] == 0
    snap = r.snapshot()
    assert snap["version_requests"] == {"v2": 8}
    r.stop()


def test_router_split_share_matches_hash_prediction():
    """The harness-side planner (tools/chaos_serve.py plan_burst) and
    the router must agree on cohort membership seq by seq."""
    r = _versioned_router({"http://a:1": "v1", "http://b:2": "v2"})
    r.set_split("classify", "v2", 0.5)
    n = 64
    expected = sum(1 for seq in range(n) if _split_hash(seq) < 0.5)
    for _ in range(n):
        r.handle("classify", {"text": "hi"})
    window = r.split_window(reset=True)
    assert window["canary"]["requests"] == expected
    assert window["control"]["requests"] == n - expected
    # reset=True zeroed the accumulators but kept the split installed.
    window = r.split_window(reset=True)
    assert window["canary"]["requests"] == 0
    r.clear_split()
    assert r.split_window() is None
    r.stop()


def test_router_version_counters_match_metrics_export():
    r = _versioned_router({"http://a:1": "v1", "http://b:2": "v2"})
    r.set_split("classify", "v2", 0.5)
    for _ in range(32):
        r.handle("classify", {"text": "hi"})
    snap = r.snapshot()
    counts = snap["version_requests"]
    assert sum(counts.values()) == 32 and set(counts) == {"v1", "v2"}
    text = r.metrics_text()
    for version, count in counts.items():
        assert (f'bert_router_version_requests{{version="{version}"}} '
                f"{count}") in text
    r.stop()


def test_router_rejects_overlapping_splits():
    r = _versioned_router({"http://a:1": "v1", "http://b:2": "v2"})
    r.set_split("classify", "v2", 0.01)
    r.set_split("classify", "v2", 0.5)   # widening the SAME split is fine
    with pytest.raises(RuntimeError, match="different split"):
        r.set_split("classify", "v3", 0.01)
    r.stop()


# ---------------------------------------------------------------------------
# serve/rollout.py: the SLO-gated controller against fake windows


class FakeSplitRouter:
    """Records the split calls the controller makes; split_window
    replays whatever the test staged."""

    def __init__(self):
        self.calls: list = []
        self.window = None

    def set_split(self, task, version, share):
        self.calls.append(("set", task, version, share))

    def clear_split(self):
        self.calls.append(("clear",))

    def split_window(self, reset=True):
        return self.window


def _window(requests, errors=0, p95=None, fallbacks=0):
    canary = {"requests": requests, "ok": requests - errors,
              "errors": errors, "sheds": 0}
    if p95 is not None:
        canary.update(latency_p50_ms=p95 / 2, latency_p95_ms=p95,
                      latency_p99_ms=p95 * 1.2)
    return {"task": "classify", "version": "v2", "share": 0.01,
            "fallbacks": fallbacks, "canary": canary,
            "control": {"requests": requests * 10, "ok": requests * 10,
                        "errors": 0, "sheds": 0}}


def _controller(tmp_path, records=None, **kwargs):
    router = FakeSplitRouter()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish("v2", task="classify", checkpoint=_ckpt(tmp_path))
    kwargs.setdefault("min_window_requests", 10)
    kwargs.setdefault("green_windows_to_advance", 1)
    ctrl = RolloutController(
        router, reg, "classify", "v2",
        emit=records.append if records is not None else None, **kwargs)
    return ctrl, router, reg


def test_rollout_advances_through_stages_and_promotes(tmp_path):
    records: list = []
    promoted = []
    ctrl, router, reg = _controller(
        tmp_path, records, stages=(0.01, 0.5, 1.0),
        on_promote=lambda: promoted.append(True))
    ctrl.start()
    assert reg.get("v2")["state"] == "canary"
    assert router.calls[-1] == ("set", "classify", "v2", 0.01)
    actions = []
    for _ in range(3):
        actions.append(ctrl.observe(window=_window(12))["action"])
    assert actions == ["advance", "advance", "promote"]
    assert ctrl.status()["state"] == "promoted"
    assert promoted == [True]
    assert reg.get("v2")["state"] == "live"
    # The split widened through every stage, then cleared on promote.
    shares = [c[3] for c in router.calls if c[0] == "set"]
    assert shares == [0.01, 0.5, 1.0]
    assert router.calls[-1] == ("clear",)
    # The emitted share is the share DURING each window (pre-advance):
    # monotone per version, so the file-level cross-record lint passes.
    assert [r["canary_share"] for r in records] == [0.01, 0.5, 1.0]
    _lint_records(records, tmp_path, "rollout_happy.jsonl")


def test_rollout_holds_on_thin_evidence(tmp_path):
    ctrl, router, _ = _controller(tmp_path, min_window_requests=20)
    ctrl.start()
    rec = ctrl.observe(window=_window(3))
    assert rec["action"] == "hold" and rec["slo_ok"] is True
    assert ctrl.status()["state"] == "canary"
    assert ctrl.status()["greens"] == 0


def test_rollout_requires_consecutive_greens(tmp_path):
    ctrl, _, _ = _controller(tmp_path, green_windows_to_advance=2)
    ctrl.start()
    assert ctrl.observe(window=_window(12))["action"] == "hold"
    assert ctrl.observe(window=_window(12))["action"] == "advance"


def test_rollout_error_budget_breach_rolls_back(tmp_path):
    records: list = []
    order: list = []
    router = FakeSplitRouter()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish("v2", task="classify", checkpoint=_ckpt(tmp_path))
    ctrl = RolloutController(
        router, reg, "classify", "v2", min_window_requests=10,
        error_budget=0.01, emit=records.append,
        on_rollback=lambda reason: order.append(
            ("callback", reason, router.calls[-1])))
    ctrl.start()
    rec = ctrl.observe(window=_window(20, errors=5))
    assert rec["action"] == "rollback" and rec["slo_ok"] is False
    assert "error share" in rec["reason"]
    assert ctrl.status()["state"] == "rolled_back"
    assert reg.get("v2")["state"] == "staged"
    assert reg.get("v2")["history"][-1]["reason"] == rec["reason"]
    # Ordering: the split cleared BEFORE the fleet unwound — traffic
    # snaps back to the old version before any replica re-swaps.
    assert order == [("callback", rec["reason"], ("clear",))]
    _lint_records(records, tmp_path, "rollout_breach.jsonl")


def test_rollout_p95_gate(tmp_path):
    ctrl, _, reg = _controller(tmp_path, slo_p95_ms=100.0)
    ctrl.start()
    assert ctrl.observe(window=_window(12, p95=50.0))["action"] == \
        "advance"
    ctrl2, _, _ = _controller(tmp_path / "b", slo_p95_ms=100.0)
    ctrl2.start()
    rec = ctrl2.observe(window=_window(12, p95=250.0))
    assert rec["action"] == "rollback" and "p95" in rec["reason"]


def test_rollout_torn_serve_rolls_back_even_on_thin_evidence(tmp_path):
    ctrl, _, reg = _controller(tmp_path, min_window_requests=50,
                               scrape_torn=lambda: 1)
    ctrl.start()
    # One request of evidence would normally hold — but a torn serve
    # is the zero-tolerance structural breach; nothing excuses it.
    rec = ctrl.observe(window=_window(1))
    assert rec["action"] == "rollback"
    assert "torn" in rec["reason"]
    assert rec["torn_serves"] == 1
    assert reg.get("v2")["state"] == "staged"


def test_rollout_controller_is_single_use(tmp_path):
    ctrl, _, _ = _controller(tmp_path)
    ctrl.start()
    with pytest.raises(RolloutError, match="single-use"):
        ctrl.start()
    ctrl.observe(window=_window(20, errors=20))
    with pytest.raises(RolloutError, match="cannot observe"):
        ctrl.observe(window=_window(20))


def test_rollout_rejects_bad_stage_lists(tmp_path):
    router = FakeSplitRouter()
    reg = ModelRegistry(str(tmp_path / "reg"))
    with pytest.raises(RolloutError, match="ascend"):
        RolloutController(router, reg, "classify", "v2",
                          stages=(0.5, 0.01, 1.0))
    with pytest.raises(RolloutError, match="final stage"):
        RolloutController(router, reg, "classify", "v2",
                          stages=(0.01, 0.5))
    with pytest.raises(RolloutError, match="shares"):
        RolloutController(router, reg, "classify", "v2",
                          stages=(0.0, 1.0))


# ---------------------------------------------------------------------------
# the chaos harness's deterministic burst planner


def _load_chaos_serve():
    tools_dir = os.path.join(REPO_ROOT, "tools")
    spec = importlib.util.spec_from_file_location(
        "_deploy_chaos_serve", os.path.join(tools_dir, "chaos_serve.py"))
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, tools_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.remove(tools_dir)
    return module


def test_plan_burst_fills_the_canary_window_exactly():
    """plan_burst sizes a burst so the deterministic cohort hash yields
    at least ``need`` canary requests from a known starting seq — the
    1% stage of the --canary acceptance cannot stall on luck."""
    chaos = _load_chaos_serve()
    for share, need, start in ((0.01, 3, 0), (0.5, 5, 17), (1.0, 4, 3)):
        n = chaos.plan_burst(share, need, start, minimum=2)
        hits = sum(1 for seq in range(start, start + n)
                   if _split_hash(seq) < share)
        assert hits >= need
        assert n >= 2


# ---------------------------------------------------------------------------
# schema fixtures + the zero-tolerance report gates


def test_registry_schema_fixtures_lint():
    good = os.path.join(HERE, "fixtures", "telemetry",
                        "registry_good.jsonl")
    bad = os.path.join(HERE, "fixtures", "telemetry",
                       "registry_bad.jsonl")
    assert schema.validate_file(good) == []
    text = " | ".join(err for _, err in schema.validate_file(bad))
    assert "version must be a non-empty string" in text
    assert "state must be one of" in text
    assert "illegal registry transition" in text
    assert "must carry a non-empty 'reason'" in text
    assert "'state_change' requires from_state" in text


def test_rollout_schema_fixtures_lint():
    good = os.path.join(HERE, "fixtures", "telemetry",
                        "rollout_good.jsonl")
    bad = os.path.join(HERE, "fixtures", "telemetry",
                       "rollout_bad.jsonl")
    assert schema.validate_file(good) == []
    text = " | ".join(err for _, err in schema.validate_file(bad))
    assert "canary_share must be in [0, 1]" in text
    assert "ok + errors exceeds window_requests" in text
    assert "action must be one of" in text
    assert "action 'rollback' must carry a non-empty 'reason'" in text
    assert "latency percentiles not ordered" in text
    assert "torn_serves must be a non-negative integer" in text
    assert "canary_share regressed without a rollback" in text
    # And the jax-free repo tool agrees.
    proc = subprocess.run(
        [sys.executable, "tools/check_telemetry_schema.py", good, bad],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "rollout_good.jsonl: ok" in proc.stdout


def _rollout_records(breaches=0, torn=0):
    records = [
        {"kind": "registry_event", "version": "v2", "event": "published",
         "state": "staged"},
        {"kind": "rollout_window", "task": "classify", "version": "v2",
         "stage": 0, "canary_share": 0.01, "window_requests": 40,
         "ok": 40, "errors": 0, "slo_ok": True, "action": "advance",
         "torn_serves": 0},
    ]
    for _ in range(breaches):
        records.append(
            {"kind": "rollout_window", "task": "classify",
             "version": "v2", "stage": 1, "canary_share": 0.5,
             "window_requests": 40, "ok": 30, "errors": 10,
             "slo_ok": False, "action": "rollback",
             "reason": "error budget", "torn_serves": torn})
    return records


def test_report_summarizes_rollout_counters():
    summary = report.summarize_records(_rollout_records(breaches=1,
                                                        torn=2))
    assert summary["registry_events"] == 1
    assert summary["rollout_windows"] == 2
    assert summary["rollout_slo_breaches"] == 1
    assert summary["rollout_rollbacks"] == 1
    assert summary["rollout_torn_serves"] == 2
    assert summary["rollout_max_share"] == 0.5
    assert summary["rollout_final_action"] == "rollback"


def test_report_gate_fires_on_canary_breach_and_torn_serves():
    clean = report.summarize_records(_rollout_records())
    breached = report.summarize_records(_rollout_records(breaches=1))
    torn = report.summarize_records(_rollout_records(breaches=1, torn=1))
    regressions, _ = report.compare(clean, breached)
    assert any(r["label"] == "rollout canary SLO" for r in regressions), \
        regressions
    regressions, _ = report.compare(clean, torn)
    assert any(r["label"] == "rollout torn-model serves"
               for r in regressions), regressions
    # Zero-tolerance gates stay quiet when both sides are at zero.
    regressions, _ = report.compare(clean, clean)
    assert not any("rollout" in r["label"] for r in regressions), \
        regressions
