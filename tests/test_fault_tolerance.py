"""Fault-tolerance suite (ISSUE 5; docs/fault_tolerance.md).

Covers the recovery machinery unit-by-unit — retry/backoff timing with a
fake clock, checkpoint integrity manifests and the multi-checkpoint
resume walk-back, deterministic fault injection, the hung-step watchdog,
graceful preemption, serve drain — and end to end: an in-process
pretraining run stopped by an injected SIGTERM, and the subprocess chaos
acceptance (`tools/chaos_run.py --smoke`: SIGKILL a child mid-run,
corrupt the newest checkpoint, resume, assert the loss trajectory
matches an uninterrupted reference exactly, with schema-clean
``fault``/``resume`` records).
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from bert_pytorch_tpu.telemetry import schema as tschema
from bert_pytorch_tpu.telemetry.report import summarize_records
from bert_pytorch_tpu.telemetry.sentinels import HeartbeatWatchdog
from bert_pytorch_tpu.testing import faults
from bert_pytorch_tpu.utils import checkpoint as ckpt
from bert_pytorch_tpu.utils import integrity, preemption
from bert_pytorch_tpu.utils.retry import RetryError, RetryPolicy, retry_call

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No fault plan (or BERT_FAULTS leak) may outlive a test."""
    yield
    faults.arm("")


# ---------------------------------------------------------------------------
# utils/retry.py


def test_retry_policy_delays_deterministic():
    p = RetryPolicy(attempts=4, base_delay_s=1.0, max_delay_s=5.0,
                    jitter=0.0, sleep=lambda s: None)
    assert list(p.delays()) == [1.0, 2.0, 4.0]
    assert RetryPolicy(attempts=6, base_delay_s=1.0, max_delay_s=5.0,
                       jitter=0.0).backoff_s(4) == 5.0  # capped


def test_retry_jitter_stays_in_band():
    p = RetryPolicy(base_delay_s=10.0, jitter=0.5, rng=random.Random(0))
    draws = [p.backoff_s(0) for _ in range(200)]
    assert all(5.0 <= d < 10.0 for d in draws)
    assert len(set(draws)) > 100  # actually jittered


def test_retry_call_recovers_and_reports_timing():
    slept, seen = [], []
    p = RetryPolicy(attempts=3, base_delay_s=0.5, jitter=0.0,
                    sleep=slept.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(f"transient {calls['n']}")
        return "ok"

    out = retry_call(flaky, policy=p,
                     on_retry=lambda n, e, d: seen.append((n, str(e), d)))
    assert out == "ok" and calls["n"] == 3
    assert slept == [0.5, 1.0]  # exact backoff sequence, no real sleeping
    assert seen == [(1, "transient 1", 0.5), (2, "transient 2", 1.0)]


def test_retry_exhausted_raises_with_cause():
    p = RetryPolicy(attempts=2, base_delay_s=0.0, sleep=lambda s: None)
    with pytest.raises(RetryError, match="2 attempt") as err:
        retry_call(lambda: (_ for _ in ()).throw(OSError("disk gone")),
                   policy=p, description="shard read")
    assert isinstance(err.value.__cause__, OSError)


def test_retry_non_retryable_propagates_immediately():
    calls = {"n": 0}

    def bug():
        calls["n"] += 1
        raise ValueError("a real bug")

    with pytest.raises(ValueError):
        retry_call(bug, policy=RetryPolicy(attempts=5, sleep=lambda s: None))
    assert calls["n"] == 1  # no retry budget burned on a non-IO error


# ---------------------------------------------------------------------------
# checkpoint integrity manifests + resume walk-back


def _contents(step):
    return {"model": {"w": np.full((4, 4), float(step), np.float32)},
            "epoch": step}


def test_save_checkpoint_writes_verified_manifest_and_prunes(tmp_path):
    for step in (1, 2, 3, 4):
        ckpt.save_checkpoint(str(tmp_path), step, _contents(step), keep=3)
    assert ckpt._ckpt_steps(str(tmp_path)) == [2, 3, 4]
    for step in (2, 3, 4):
        path = ckpt.checkpoint_path(str(tmp_path), step)
        status, detail = integrity.verify_checkpoint(path)
        assert status == integrity.VERIFIED, (step, detail)
        manifest = integrity.read_manifest(path)
        assert manifest["step"] == step
        assert "model" in manifest["keys"]
    # pruning removed the step-1 blob AND its sidecar
    gone = ckpt.checkpoint_path(str(tmp_path), 1)
    assert not os.path.exists(gone)
    assert not os.path.exists(integrity.manifest_path(gone))


@pytest.mark.parametrize("mode,expect", [("truncate", "size mismatch"),
                                         ("flip", "sha256 mismatch")])
def test_corruption_detected(tmp_path, mode, expect):
    ckpt.save_checkpoint(str(tmp_path), 1, _contents(1))
    path = ckpt.checkpoint_path(str(tmp_path), 1)
    faults.corrupt_checkpoint(path, mode)
    status, detail = integrity.verify_checkpoint(path)
    assert status == integrity.CORRUPT and expect in detail
    with pytest.raises(ckpt.CheckpointCorruptError, match=expect):
        ckpt.load_checkpoint(path)
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.load_params_only(path, _contents(1)["model"])


def test_walk_back_skips_all_corrupt_retained(tmp_path):
    """Both newer retained checkpoints corrupt (one truncated, one
    bit-flipped — the size-preserving case only sha256 can catch): the
    walk-back lands on the oldest, reporting every skip."""
    for step in (2, 4, 6):
        ckpt.save_checkpoint(str(tmp_path), step, _contents(step))
    faults.corrupt_checkpoint(ckpt.checkpoint_path(str(tmp_path), 6),
                              "truncate")
    faults.corrupt_checkpoint(ckpt.checkpoint_path(str(tmp_path), 4),
                              "flip")
    skipped = []
    with pytest.warns(UserWarning, match="Skipping unreadable checkpoint"):
        step, state = ckpt.load_latest_checkpoint(
            str(tmp_path), on_skip=skipped.append)
    assert step == 2 and state["epoch"] == 2
    assert [s["step"] for s in skipped] == [6, 4]
    assert all("integrity" in s["reason"] for s in skipped)
    assert ckpt.find_resume_step(str(tmp_path), verify=True) == 2
    assert ckpt.find_resume_step(str(tmp_path)) == 6  # unverified view


def test_legacy_checkpoint_without_manifest_still_loads(tmp_path):
    ckpt.save_checkpoint(str(tmp_path), 3, _contents(3))
    path = ckpt.checkpoint_path(str(tmp_path), 3)
    os.unlink(integrity.manifest_path(path))
    assert integrity.verify_checkpoint(path)[0] == integrity.NO_MANIFEST
    step, state = ckpt.load_latest_checkpoint(str(tmp_path))
    assert step == 3 and state["epoch"] == 3  # unverifiable != corrupt


def test_verify_checkpoint_tool(tmp_path):
    ckpt.save_checkpoint(str(tmp_path), 1, _contents(1))
    ckpt.save_checkpoint(str(tmp_path), 2, _contents(2))
    faults.corrupt_checkpoint(ckpt.checkpoint_path(str(tmp_path), 2),
                              "truncate")
    tool = os.path.join(REPO_ROOT, "tools", "verify_checkpoint.py")
    proc = subprocess.run([sys.executable, tool, str(tmp_path)],
                          capture_output=True, text=True)
    assert proc.returncode == 1
    assert "ckpt_1.msgpack: verified" in proc.stdout
    assert "ckpt_2.msgpack: corrupt" in proc.stdout
    # strict mode also rejects manifestless checkpoints
    os.unlink(integrity.manifest_path(
        ckpt.checkpoint_path(str(tmp_path), 2)))
    os.unlink(ckpt.checkpoint_path(str(tmp_path), 2))
    proc = subprocess.run([sys.executable, tool, str(tmp_path)],
                          capture_output=True, text=True)
    assert proc.returncode == 0
    os.unlink(integrity.manifest_path(
        ckpt.checkpoint_path(str(tmp_path), 1)))
    proc = subprocess.run([sys.executable, tool, "--strict", str(tmp_path)],
                          capture_output=True, text=True)
    assert proc.returncode == 1 and "no_manifest" in proc.stdout


# ---------------------------------------------------------------------------
# testing/faults.py


def test_fault_spec_parsing_and_rejection():
    plan = faults.FaultPlan("die@7,shard_errorx2,nonfinite@5x2,hang@3x1")
    assert plan.active
    assert not faults.FaultPlan("").active
    for bad in ("bogus@3", "die", "nonfinite", "die@x"):
        with pytest.raises(faults.FaultSpecError):
            faults.FaultPlan(bad)


def test_poison_metrics_window():
    plan = faults.FaultPlan("nonfinite@5x2")
    recs = []
    healthy = {"loss": 1.25, "finite": 1.0}
    assert plan.poison_metrics(4, healthy) is healthy  # untouched
    for step in (5, 6):
        poisoned = plan.poison_metrics(step, healthy, emit=recs.append)
        assert np.isnan(poisoned["loss"]) and poisoned["finite"] == 0.0
    assert plan.poison_metrics(7, healthy) is healthy
    assert healthy["loss"] == 1.25  # original never mutated
    assert all(r["fault"] == "injected_nonfinite" and r["injected"]
               for r in recs)


def test_shard_error_countdown_then_healthy():
    plan = faults.FaultPlan("shard_errorx2")
    for _ in range(2):
        with pytest.raises(OSError, match="injected transient"):
            plan.shard_read_check("/data/shard_0.hdf5")
    plan.shard_read_check("/data/shard_0.hdf5")  # exhausted -> healthy


def test_arm_roundtrips_through_env():
    faults.arm("shard_errorx1")
    assert os.environ[faults.FAULTS_ENV] == "shard_errorx1"
    faults.arm("")
    assert faults.FAULTS_ENV not in os.environ
    os.environ[faults.FAULTS_ENV] = "die@9"  # a worker process's view
    assert faults.get_plan().active


# ---------------------------------------------------------------------------
# data-path resilience (retry around HDF5 shard reads)


@pytest.fixture()
def shards(tmp_path):
    from bert_pytorch_tpu.tools.make_synthetic_data import make_shard

    paths = []
    for i in range(2):
        path = str(tmp_path / f"shard_{i}.hdf5")
        make_shard(path, 16, 32, 100, seed=i)
        paths.append(path)
    return paths


def _dataset(paths, **kw):
    from bert_pytorch_tpu.data.dataset import ShardedPretrainingDataset

    kw.setdefault("retry_base_delay_s", 0.01)
    return ShardedPretrainingDataset(
        paths, 4, max_pred_per_seq=20, masked_lm_prob=0.15, vocab_size=100,
        seed=0, **kw)


def test_dataset_retries_transient_shard_errors(shards):
    emitted = []
    ds = _dataset(shards, read_retries=2, on_fault=emitted.append)
    faults.arm("shard_errorx2")  # after construction: streaming reads only
    with pytest.warns(UserWarning, match="retrying"):
        sample = ds[0]
    assert sample[0].shape == (32,)
    kinds = [r["fault"] for r in emitted]
    assert "injected_shard_error" in kinds and "shard_read_retry" in kinds
    faults.arm("")
    # the retried read returned EXACTLY what an unfaulted reader gets
    clean = _dataset(shards)[0]
    for a, b in zip(sample, clean):
        np.testing.assert_array_equal(a, b)


def test_dataset_read_error_after_retry_budget(shards):
    from bert_pytorch_tpu.data.dataset import DataReadError

    ds = _dataset(shards, read_retries=1)
    faults.arm("shard_errorx10")
    with pytest.warns(UserWarning, match="retrying"):
        with pytest.raises(DataReadError, match="2 attempt"):
            ds[0]


def test_shard_error_policy_abort_vs_skip(tmp_path, shards):
    garbage = str(tmp_path / "shard_zz.hdf5")
    with open(garbage, "wb") as f:
        f.write(b"not an hdf5 file")
    from bert_pytorch_tpu.data.dataset import DataReadError

    with pytest.warns(UserWarning, match="Skipping File"):
        ds = _dataset(shards + [garbage], read_retries=0)  # default: skip
    assert len(ds) == 32
    with pytest.raises(DataReadError, match="abort"):
        _dataset(shards + [garbage], read_retries=0,
                 shard_error_policy="abort")


def test_masking_deterministic_per_sample_index(shards):
    """Draws for sample i depend only on (seed, epoch, i) — the property
    resume-exactness rests on: a reader that arrives at i via a
    different history gets identical masking."""
    a, b = _dataset(shards), _dataset(shards)
    for i in (0, 3, 7):  # warm `a` along a different access history
        a[i]
    for x, y in zip(a[8], b[8]):
        np.testing.assert_array_equal(x, y)
    c = _dataset(shards)
    c.set_epoch(1)  # ...but epochs still re-draw (dynamic masking)
    assert any(not np.array_equal(x, y) for x, y in zip(b[8], c[8]))


# ---------------------------------------------------------------------------
# hung-step watchdog


def test_watchdog_flags_stall_once_with_fake_clock():
    clock = {"t": 0.0}
    records = []
    dog = HeartbeatWatchdog(max_age_s=10.0, emit=records.append,
                            clock=lambda: clock["t"])
    assert dog.check() is None  # unarmed before the first note
    dog.note(3)
    clock["t"] = 9.0
    assert dog.check() is None  # healthy
    clock["t"] = 11.0
    rec = dog.check()
    assert rec["fault"] == "hung_step" and rec["step"] == 3
    assert rec["age_s"] == 11.0 and rec["injected"] is False
    assert dog.check() is None  # one flag per stall, never a storm
    dog.note(4)  # progress re-arms
    clock["t"] = 30.0
    assert dog.check()["step"] == 4
    assert dog.stalls_flagged == 2
    assert tschema.validate_record({"schema": 1, "ts": 0.0, **rec}) == []


def test_watchdog_thread_emits_on_real_stall():
    records = []
    dog = HeartbeatWatchdog(max_age_s=0.1, emit=records.append,
                            poll_s=0.02)
    dog.start().note(1)
    deadline = time.monotonic() + 2.0
    with pytest.warns(UserWarning, match="may be hung"):
        while not records and time.monotonic() < deadline:
            time.sleep(0.02)
    dog.stop()
    assert records and records[0]["fault"] == "hung_step"


# ---------------------------------------------------------------------------
# graceful preemption


def test_graceful_stop_catches_sigterm_and_restores():
    before = signal.getsignal(signal.SIGTERM)
    with preemption.GracefulStop() as stop:
        assert not stop.requested
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 2.0
        while not stop.requested and time.monotonic() < deadline:
            time.sleep(0.01)
        assert stop.requested and stop.signal_name == "SIGTERM"
        os.kill(os.getpid(), signal.SIGTERM)  # grace-period repeat absorbed
    assert signal.getsignal(signal.SIGTERM) is before
    assert preemption.EXIT_PREEMPTED == 75
    rec = preemption.preemption_record(12, stop)
    assert rec["fault"] == "preemption" and rec["signal"] == "SIGTERM"
    assert tschema.validate_record({"schema": 1, "ts": 0.0, **rec}) == []


# ---------------------------------------------------------------------------
# schema + report for the fault/resume record family


def _rec(**kw):
    return {"schema": 1, "ts": 0.0, **kw}


def test_schema_lints_fault_and_resume_kinds():
    good_fault = _rec(kind="fault", fault="preemption", injected=False)
    assert tschema.validate_record(good_fault) == []
    assert tschema.validate_record(
        _rec(kind="fault", fault="", injected=False))
    assert tschema.validate_record(
        _rec(kind="fault", fault="hung_step", injected="yes"))
    good_resume = _rec(kind="resume", step=4, skipped=[
        {"step": 6, "path": "x/ckpt_6.msgpack", "reason": "integrity"}])
    assert tschema.validate_record(good_resume) == []
    assert tschema.validate_record(_rec(kind="resume", step=4,
                                        skipped="ckpt_6"))
    assert tschema.validate_record(
        _rec(kind="resume", step=4, skipped=[{"step": 6}]))


def test_report_recovery_section():
    records = [
        _rec(kind="fault", fault="injected_die", injected=True, step=7),
        _rec(kind="fault", fault="shard_read_retry", injected=False),
        _rec(kind="resume", step=4, skipped=[
            {"step": 6, "path": "p", "reason": "integrity: size"}]),
    ]
    out = summarize_records(records)
    assert out["faults"] == 2 and out["faults_injected"] == 1
    assert out["fault_kinds"] == ["injected_die", "shard_read_retry"]
    assert out["resumes"] == 1 and out["resume_last_step"] == 4
    assert out["resume_skipped_checkpoints"] == 1
    assert out["resume_skipped_steps"] == [6]
    from bert_pytorch_tpu.telemetry.report import format_summary

    text = format_summary(out)
    assert "fault_kinds" in text and "resume_skipped_steps" in text


# ---------------------------------------------------------------------------
# serve graceful drain


class _EchoHandler:
    def prepare(self, payload, max_len):
        return {"input_ids": [1, 2, 3]}

    def postprocess(self, features, out, payload):
        return {"echo": out}


class _EchoSpec:
    handler = _EchoHandler()


class _FakeEngine:
    """Just enough engine for ServingService/healthz — no jax, no model."""
    tasks = {"echo": _EchoSpec()}
    buckets = (8,)
    warmed = True
    max_requests_per_pack = 1

    def max_len(self):
        return 8

    def plan_batch(self, batch):
        from types import SimpleNamespace

        return SimpleNamespace(requests=batch, leftover=[])

    def execute(self, task, plan):
        return (["ok"] * len(plan.requests),
                {"device_s": 0.001, "rows": len(plan.requests), "bucket": 8,
                 "real_tokens": 3, "compiles": 0})

    # The pipelined dispatch plane (docs/serving.md "Continuous
    # batching") drives the staged split; compose it from execute.
    def stage(self, task, plan):
        from types import SimpleNamespace

        return SimpleNamespace(task=task, plan=plan, pack_s=0.0,
                               staged_at=None)

    def execute_staged(self, staged):
        return self.execute(staged.task, staged.plan)

    def demux(self, staged, out):
        return out


def test_serve_drain_sheds_then_flushes_then_stops():
    from bert_pytorch_tpu.serve import Batcher, ServiceDraining
    from bert_pytorch_tpu.serve.service import ServingService

    service = ServingService(_FakeEngine(),
                             Batcher(max_batch_size=2, max_wait_ms=1.0))
    assert service.health()["status"] == "not_serving"  # dispatch not up
    service.start()
    assert service.health()["status"] == "ok"
    assert service.submit("echo", {"x": 1}, timeout=5.0) == {"echo": "ok"}
    service.begin_drain()
    health = service.health()
    assert health["status"] == "draining" and health["draining"]
    with pytest.raises(ServiceDraining):
        service.submit("echo", {"x": 2}, timeout=5.0)
    service.stop(drain_s=1.0)
    assert not service.dispatch_alive
    assert service.health()["status"] == "draining"


def test_healthz_reflects_dispatch_liveness_and_drain():
    import http.client
    import threading

    from bert_pytorch_tpu.serve import Batcher, make_server
    from bert_pytorch_tpu.serve.service import ServingService

    service = ServingService(_FakeEngine(),
                             Batcher(max_batch_size=2, max_wait_ms=1.0))
    service.start()
    server = make_server(service, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    def healthz():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read())
        finally:
            conn.close()

    try:
        status, body = healthz()
        assert status == 200 and body["status"] == "ok"
        assert body["dispatch_alive"] is True
        service.begin_drain()
        status, body = healthz()
        assert status == 503 and body["status"] == "draining"
        service.stop(drain_s=0.5)
        status, body = healthz()
        assert status == 503 and body["dispatch_alive"] is False
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# end to end: in-process preemption + sentinel injection, subprocess chaos


@pytest.fixture()
def pretrain_workdir(tmp_path):
    from bert_pytorch_tpu.tools.make_synthetic_data import make_shard

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    for i in range(2):
        make_shard(str(data_dir / f"shard_{i}.hdf5"), 64, 32, 1000, seed=i)
    model_config = {
        "vocab_size": 1000, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 32, "type_vocab_size": 2,
        "next_sentence": True, "mask_token_id": 4,
    }
    config_path = tmp_path / "model.json"
    config_path.write_text(json.dumps(model_config))
    return {"data": str(data_dir), "out": str(tmp_path / "out"),
            "model": str(config_path)}


def _pretrain_args(workdir, *extra):
    import run_pretraining

    return run_pretraining.parse_arguments([
        "--input_dir", workdir["data"], "--output_dir", workdir["out"],
        "--model_config_file", workdir["model"],
        "--global_batch_size", "16", "--local_batch_size", "2",
        "--max_steps", "8", "--steps", "8", "--dtype", "float32",
        "--seed", "7", "--num_steps_per_checkpoint", "100",
        "--telemetry_sync_every", "1", *extra])


def _kinds(workdir):
    jsonl = os.path.join(workdir["out"], "pretraining_telemetry.jsonl")
    assert tschema.validate_file(jsonl) == []
    kinds = {}
    for line in open(jsonl):
        rec = json.loads(line)
        kinds.setdefault(rec.get("kind", "metric"), []).append(rec)
    return kinds


@pytest.mark.slow  # ~43-100s: full runner compile+run (ISSUE 14 budget
# fix). The SIGTERM->graceful-stop->restore invariant is carried tier-1
# by test_graceful_stop_catches_sigterm_and_restores (in-process, no
# jit); the verified-emergency-checkpoint half by
# test_save_checkpoint_writes_verified_manifest_and_prunes.
def test_pretraining_term_injection_stops_and_checkpoints(
        pretrain_workdir):
    """Injected SIGTERM at step 3: the run must stop at the next
    term-check boundary, write a VERIFIED emergency checkpoint, and emit
    injected_term + preemption fault records. (That the checkpoint then
    resumes — with a resume record — is the chaos harness's subprocess
    assertion; re-proving it in-process would just re-pay the compile.)
    """
    import run_pretraining

    result = run_pretraining.main(_pretrain_args(
        pretrain_workdir, "--fault_spec", "term@3",
        "--term_check_steps", "1"))
    assert result["terminated_by_signal"] is True
    stopped_at = result["global_step"]
    assert 3 <= stopped_at < 8
    out_ckpts = os.path.join(pretrain_workdir["out"], "pretrain_ckpts")
    assert ckpt.find_resume_step(out_ckpts, verify=True) == stopped_at
    kinds = _kinds(pretrain_workdir)
    fault_names = {r["fault"] for r in kinds["fault"]}
    assert {"injected_term", "preemption"} <= fault_names
    preempt = next(r for r in kinds["fault"] if r["fault"] == "preemption")
    assert preempt["signal"] == "SIGTERM" and preempt["injected"] is False
    assert kinds["run_summary"][0]["terminated_by_signal"] is True


@pytest.mark.slow  # ~50s: full runner startup + deliberately slowed
# writes. The join-ordering invariant it exercises end-to-end is carried
# in tier-1 by test_async_hotpath.py's per-directory pending-save units
# and test_sync_save_joins_inflight_async_write_first below.
def test_preemption_joins_inflight_async_save(pretrain_workdir, monkeypatch):
    """ISSUE 6 satellite: GracefulStop fires while a periodic ASYNC
    checkpoint write is still in flight. The emergency checkpoint must
    join it first (saves land in order — the step-2 write can never
    clobber or outlive the step-3 emergency state), and the manifest
    walk-back must still see a VERIFIED newest checkpoint."""
    import run_pretraining

    real_write = ckpt._write_and_prune

    def slow_write(state, output_dir, step, keep):
        # Stretch every background write past a step time, so the step-2
        # periodic save is guaranteed still in flight when term@3 stops
        # the run at the next boundary.
        time.sleep(1.0)
        real_write(state, output_dir, step, keep)

    monkeypatch.setattr(ckpt, "_write_and_prune", slow_write)
    result = run_pretraining.main(_pretrain_args(
        pretrain_workdir, "--fault_spec", "term@3",
        "--term_check_steps", "1", "--num_steps_per_checkpoint", "2"))
    assert result["terminated_by_signal"] is True
    stopped_at = result["global_step"]
    out_ckpts = os.path.join(pretrain_workdir["out"], "pretrain_ckpts")
    # Newest VERIFIED checkpoint is the emergency one; the async periodic
    # write it joined landed verified too (blob-then-manifest held).
    assert ckpt.find_resume_step(out_ckpts, verify=True) == stopped_at
    for step in ckpt._ckpt_steps(out_ckpts):
        path = ckpt.checkpoint_path(out_ckpts, step)
        status, detail = integrity.verify_checkpoint(path)
        assert status == integrity.VERIFIED, (step, detail)
    assert set(ckpt._ckpt_steps(out_ckpts)) == {2, stopped_at}
    # The walk-back story survives async saves: corrupt the newest and
    # resume must land on the verified periodic checkpoint below it.
    faults.corrupt_checkpoint(
        ckpt.checkpoint_path(out_ckpts, stopped_at), "flip")
    assert ckpt.find_resume_step(out_ckpts, verify=True) == 2


def test_sync_save_joins_inflight_async_write_first(tmp_path, monkeypatch):
    """The emergency-checkpoint invariant, unit-level: a SYNCHRONOUS save
    to a directory with an async write in flight joins that write before
    writing its own state — checkpoints land in order, and the sync
    save's (newer) step ends up the verified newest."""
    order = []
    real_write = ckpt._write_and_prune

    def slow_logged_write(state, output_dir, step, keep):
        if step == 1:
            time.sleep(0.3)  # keep the async write in flight
        order.append(step)
        real_write(state, output_dir, step, keep)

    monkeypatch.setattr(ckpt, "_write_and_prune", slow_logged_write)
    ckpt.save_checkpoint(str(tmp_path), 1, _contents(1), async_write=True)
    ckpt.save_checkpoint(str(tmp_path), 2, _contents(2))  # emergency: sync
    assert order == [1, 2]
    assert ckpt.find_resume_step(str(tmp_path), verify=True) == 2


@pytest.mark.slow  # ~15s compile; the poison hook and the sentinel
# policy are each unit-tested above / in tests/test_telemetry.py
def test_pretraining_nonfinite_injection_trips_abort_sentinel(
        pretrain_workdir):
    """Injected NaN metrics must flow through the host sentinel exactly
    like a real divergence: records per bad step, NonFiniteError under
    the abort policy."""
    import run_pretraining
    from bert_pytorch_tpu.telemetry.sentinels import NonFiniteError

    with pytest.raises(NonFiniteError, match="2 consecutive"):
        run_pretraining.main(_pretrain_args(
            pretrain_workdir, "--fault_spec", "nonfinite@2x3",
            "--sentinel_policy", "abort", "--sentinel_patience", "2"))
    jsonl = os.path.join(pretrain_workdir["out"],
                         "pretraining_telemetry.jsonl")
    assert tschema.validate_file(jsonl) == []
    records = [json.loads(line) for line in open(jsonl)]
    injected = [r for r in records if r.get("fault") == "injected_nonfinite"]
    sentinels = [r for r in records if r.get("kind") == "sentinel"]
    assert len(injected) >= 2
    assert [r["step"] for r in sentinels] == [2, 3]


@pytest.mark.slow  # ~62-100s: three pretraining subprocesses (ISSUE 14
# budget fix). The key invariant — resume walks back past a corrupt
# newest checkpoint to the last VERIFIED one, recording what it skipped
# — is carried tier-1 by test_walk_back_skips_all_corrupt_retained and
# test_corruption_detected above (in-process, no jit); this acceptance
# additionally proves the loss trajectory across the kill and runs
# under ``-m slow``.
def test_chaos_kill_corrupt_resume_acceptance():
    """ISSUE 5 acceptance: the chaos harness SIGKILLs a CPU pretraining
    child mid-run AND corrupts the newest checkpoint; the rerun
    auto-resumes from the previous verified checkpoint and its per-step
    loss trajectory matches an uninterrupted reference run from that
    step (fp32, same seed), with schema-clean fault/resume records."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "chaos_run.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=540,
        cwd=os.path.join(REPO_ROOT, "tools"))
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True
    assert verdict["resume_step"] < verdict["corrupted_step"]
    assert [e["step"] for e in verdict["skipped"]] == [
        verdict["corrupted_step"]]
    assert verdict["compared_steps"] >= 3
