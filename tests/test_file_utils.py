"""Cached-download tests (utils/file_utils.py; reference src/file_utils.py).

A loopback http.server stands in for the network (zero-egress environment).
"""

import http.server
import json
import os
import threading

import pytest

from bert_pytorch_tpu.utils import file_utils


@pytest.fixture()
def http_srv(tmp_path):
    content = b"pretrained weights blob"

    class Handler(http.server.BaseHTTPRequestHandler):
        etag = '"v1"'
        hits = {"GET": 0, "HEAD": 0}

        def _respond(self, body):
            self.send_response(200)
            self.send_header("ETag", Handler.etag)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            return body

        def do_HEAD(self):
            Handler.hits["HEAD"] += 1
            self._respond(b"")

        def do_GET(self):
            Handler.hits["GET"] += 1
            self.wfile.write(self._respond(content))

        def log_message(self, *args):
            pass

    server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_port}/weights.bin", Handler, content
    server.shutdown()


def test_local_path_passthrough(tmp_path):
    path = tmp_path / "f.txt"
    path.write_text("x")
    assert file_utils.cached_path(str(path)) == str(path)
    with pytest.raises(EnvironmentError):
        file_utils.cached_path(str(tmp_path / "missing.txt"))


def test_url_to_filename_etag():
    base = file_utils.url_to_filename("http://x/y")
    with_tag = file_utils.url_to_filename("http://x/y", '"abc"')
    assert with_tag.startswith(base + ".")
    assert base != with_tag


def test_download_once_and_meta(http_srv, tmp_path):
    url, handler, content = http_srv
    cache = str(tmp_path / "cache")
    path1 = file_utils.cached_path(url, cache_dir=cache)
    assert open(path1, "rb").read() == content
    meta = json.load(open(path1 + ".json"))
    assert meta["url"] == url and meta["etag"] == '"v1"'
    # second call: HEAD only, no new GET
    gets = handler.hits["GET"]
    path2 = file_utils.cached_path(url, cache_dir=cache)
    assert path2 == path1
    assert handler.hits["GET"] == gets


def test_etag_change_redownloads(http_srv, tmp_path):
    url, handler, _ = http_srv
    cache = str(tmp_path / "cache")
    path1 = file_utils.cached_path(url, cache_dir=cache)
    handler.etag = '"v2"'
    path2 = file_utils.cached_path(url, cache_dir=cache)
    assert path1 != path2  # new etag -> new cache entry
    url_back, etag = file_utils.filename_to_url(
        os.path.basename(path2), cache)
    assert url_back == url and etag == '"v2"'


def test_offline_serves_cached_copy(http_srv, tmp_path):
    url, handler, content = http_srv
    cache = str(tmp_path / "cache")
    path1 = file_utils.cached_path(url, cache_dir=cache)
    # unreachable host, same cache prefix? -> different url misses
    with pytest.raises(OSError):
        file_utils.cached_path(
            "http://127.0.0.1:1/never-cached.bin", cache_dir=cache)
    # simulate the probe failing for a cached url: point at a dead server
    # after renaming the cache entry to that url's hash
    dead_url = "http://127.0.0.1:1/weights.bin"
    prefix = file_utils.url_to_filename(dead_url)
    os.replace(path1, os.path.join(cache, prefix + ".deadbeef"))
    assert file_utils.cached_path(dead_url, cache_dir=cache).startswith(
        os.path.join(cache, prefix))
