"""SQuAD + NER finetuning tests: featurization, decoding, tiny e2e runs."""

import json
import os

import numpy as np
import pytest

VOCAB_TOKENS = (
    ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    + ["the", "capital", "of", "france", "is", "paris", "what", "who",
       "wrote", "hamlet", "shakespeare", "william", "city", "big", "a",
       "in", "was", "by", "play", "##s", "##ing", "london", "england"]
)


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("vocab")
    path = d / "vocab.txt"
    path.write_text("\n".join(VOCAB_TOKENS) + "\n")
    return str(path)


@pytest.fixture(scope="module")
def tokenizer(vocab_file):
    from bert_pytorch_tpu.data.tokenization import BertTokenizer

    return BertTokenizer(vocab_file, do_lower_case=True)


@pytest.fixture(scope="module")
def squad_json(tmp_path_factory):
    d = tmp_path_factory.mktemp("squad")
    context = "The capital of France is Paris"
    data = {
        "version": "1.1",
        "data": [{
            "title": "t",
            "paragraphs": [{
                "context": context,
                "qas": [
                    {"id": "q1",
                     "question": "What is the capital of France",
                     "answers": [{"text": "Paris",
                                  "answer_start": context.index("Paris")}]},
                    {"id": "q2",
                     "question": "The capital of France is what city",
                     "answers": [{"text": "Paris",
                                  "answer_start": context.index("Paris")}]},
                ],
            }],
        }],
    }
    path = d / "train.json"
    path.write_text(json.dumps(data))
    return str(path)


def test_read_squad_examples(squad_json):
    from bert_pytorch_tpu import squad

    examples = squad.read_squad_examples(squad_json, True, False)
    assert len(examples) == 2
    ex = examples[0]
    assert ex.doc_tokens == ["The", "capital", "of", "France", "is", "Paris"]
    assert ex.start_position == 5 and ex.end_position == 5


def test_convert_examples_to_features(squad_json, tokenizer):
    from bert_pytorch_tpu import squad

    examples = squad.read_squad_examples(squad_json, True, False)
    features = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=32, doc_stride=8,
        max_query_length=16, is_training=True)
    f = features[0]
    assert len(f.input_ids) == 32
    assert f.tokens[0] == "[CLS]" and "[SEP]" in f.tokens
    # answer position points at 'paris' inside the doc segment
    assert f.tokens[f.start_position] == "paris"
    assert f.segment_ids[f.start_position] == 1
    assert f.input_mask[: len(f.tokens)] == [1] * len(f.tokens)


def test_sliding_window_and_max_context(tokenizer):
    from bert_pytorch_tpu import squad

    # long synthetic doc forces multiple windows
    doc = " ".join(["the", "big", "city"] * 20)
    context = doc
    data = {"data": [{"paragraphs": [{
        "context": context,
        "qas": [{"id": "q", "question": "what city",
                 "answers": [{"text": "city", "answer_start": context.index("city")}]}],
    }]}]}
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(data, f)
        path = f.name
    examples = squad.read_squad_examples(path, True, False)
    features = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=24, doc_stride=8,
        max_query_length=8, is_training=True)
    os.unlink(path)
    assert len(features) > 1  # window slid
    # every doc token position is max-context in exactly one window
    for pos_key in features[0].token_is_max_context:
        flags = [f.token_is_max_context.get(pos_key, False) for f in features]
    # at least first window has some max-context tokens
    assert any(features[0].token_is_max_context.values())


def test_get_final_text_realignment():
    from bert_pytorch_tpu.squad import get_final_text

    # normalized prediction -> original casing/punctuation restored
    assert get_final_text("steve smith", "Steve Smith's", True) == "Steve Smith"
    # failure falls back to orig_text
    assert get_final_text("zzz", "Steve Smith's", True) == "Steve Smith's"


def _decode_args(**overrides):
    """Answer-decoding knobs shared by the get_answers tests."""

    class Args:
        n_best_size = 5
        max_answer_length = 10
        version_2_with_negative = False
        null_score_diff_threshold = 0.0
        do_lower_case = True

    for key, value in overrides.items():
        setattr(Args, key, value)
    return Args()


def test_get_answers_decodes_correct_span(squad_json, tokenizer):
    from bert_pytorch_tpu import squad

    examples = squad.read_squad_examples(squad_json, False, False)
    features = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=32, doc_stride=8,
        max_query_length=16, is_training=False)

    results = []
    for f in features:
        start = np.full(32, -5.0)
        end = np.full(32, -5.0)
        # boost the position of 'paris' in the doc segment
        paris_pos = f.tokens.index("paris", f.tokens.index("[SEP]"))
        start[paris_pos] = 5.0
        end[paris_pos] = 5.0
        results.append(squad.RawResult(f.unique_id, start.tolist(), end.tolist()))
    answers, nbest, _ = squad.get_answers(
        examples, features, results, _decode_args())
    assert answers["q1"] == "Paris"
    assert answers["q2"] == "Paris"
    assert nbest["q1"][0]["probability"] > 0.3


def test_squad_end_to_end_tiny(tmp_path, squad_json, vocab_file):
    import run_squad

    model_config = {
        "vocab_size": len(VOCAB_TOKENS), "hidden_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 64, "max_position_embeddings": 64,
        "type_vocab_size": 2, "next_sentence": True,
        "vocab_file": vocab_file, "tokenizer": "wordpiece",
        "lowercase": True,
    }
    config_path = tmp_path / "model.json"
    config_path.write_text(json.dumps(model_config))
    args = run_squad.parse_args([
        "--output_dir", str(tmp_path / "out"),
        "--config_file", str(config_path),
        "--train_file", squad_json,
        "--predict_file", squad_json,
        "--do_train", "--do_predict", "--do_lower_case",
        "--train_batch_size", "2", "--predict_batch_size", "2",
        "--max_steps", "2", "--max_seq_length", "32",
        "--doc_stride", "8", "--max_query_length", "16",
        "--dtype", "float32", "--skip_cache", "--mesh_data", "2",
    ])
    summary = run_squad.main(args)
    assert np.isfinite(summary["final_loss"])
    assert summary["training_sequences_per_second"] > 0
    pred_file = tmp_path / "out" / "predictions.json"
    assert pred_file.exists()
    answers = json.loads(pred_file.read_text())
    assert set(answers.keys()) == {"q1", "q2"}
    # Grad-health must land at the DEFAULT sampled sync cadence (4): the
    # in-jit due gate counts from the PRE-update optimizer count, which is
    # the same 0-base the host's sync cadence uses — a post-update count
    # would be off by one and never coincide with a synced step.
    tele = [json.loads(line) for line in
            open(tmp_path / "out" / "squad_telemetry.jsonl")]
    health = [r for r in tele if r.get("kind") == "grad_health"]
    assert health, "no grad_health record at the default sync cadence"
    assert "bert/encoder" in health[0]["groups"]


@pytest.mark.slow
def test_squad_fp16_loss_scaled_tiny(tmp_path, squad_json, vocab_file):
    """--dtype float16: the reference-parity AMP mode (apex O2 + scaler,
    reference run_squad.py:980-996) on the SQuAD runner.

    Slow-gated (~33s): the fp32 SQuAD E2E below stays tier-1 and the
    loss-scaling math is tier-1-covered by tests/test_fp16.py's step
    tests (scaling-transparency, overflow skip/recover); runs under
    ``-m slow``."""
    import run_squad

    model_config = {
        "vocab_size": len(VOCAB_TOKENS), "hidden_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 64, "max_position_embeddings": 64,
        "type_vocab_size": 2, "next_sentence": True,
        "vocab_file": vocab_file, "tokenizer": "wordpiece",
        "lowercase": True,
    }
    config_path = tmp_path / "model.json"
    config_path.write_text(json.dumps(model_config))
    args = run_squad.parse_args([
        "--output_dir", str(tmp_path / "out"),
        "--config_file", str(config_path),
        "--train_file", squad_json,
        "--predict_file", squad_json,
        "--do_train", "--do_predict", "--do_lower_case",
        "--train_batch_size", "2", "--predict_batch_size", "2",
        "--max_steps", "2", "--max_seq_length", "32",
        "--doc_stride", "8", "--max_query_length", "16",
        "--dtype", "float16", "--skip_cache", "--mesh_data", "2",
    ])
    summary = run_squad.main(args)
    assert np.isfinite(summary["final_loss"])
    assert (tmp_path / "out" / "predictions.json").exists()


@pytest.fixture(scope="module")
def conll_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("ner")
    lines = []
    for _ in range(8):
        lines += [
            "-DOCSTART- X X O", "",
            "paris X X B-LOC", "is X X O", "big X X O", "",
            "william X X B-PER", "shakespeare X X I-PER",
            "wrote X X O", "hamlet X X O", "",
        ]
    path = d / "train.txt"
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_ner_dataset_parsing_and_encoding(conll_file, tokenizer):
    from bert_pytorch_tpu.data.ner_dataset import NERDataset

    labels = ["O", "B-PER", "I-PER", "B-LOC", "I-LOC"]
    ds = NERDataset(conll_file, tokenizer, labels, max_seq_len=16)
    assert len(ds) == 16  # 2 sentences x 8 repeats
    seq, lab, mask = ds[0]
    assert seq.shape == (16,)
    assert lab[0] == -100  # [CLS]
    # 'paris' gets B-LOC id (4 in 1-based ordering)
    assert lab[1] == labels.index("B-LOC") + 1
    assert mask.sum() == 5  # [CLS] paris is big [SEP]


def test_ner_end_to_end_tiny(tmp_path, conll_file, vocab_file):
    import run_ner

    model_config = {
        "vocab_size": len(VOCAB_TOKENS), "hidden_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 64, "max_position_embeddings": 32,
        "type_vocab_size": 2, "next_sentence": True,
        "vocab_file": vocab_file, "tokenizer": "wordpiece",
    }
    config_path = tmp_path / "model.json"
    config_path.write_text(json.dumps(model_config))
    args = run_ner.parse_arguments([
        "--train_file", conll_file,
        "--val_file", conll_file,
        "--test_file", conll_file,
        "--labels", "O", "B-PER", "I-PER", "B-LOC", "I-LOC",
        "--model_config_file", str(config_path),
        "--epochs", "2", "--batch_size", "8", "--max_seq_len", "16",
        "--lr", "1e-3", "--dtype", "float32",
    ])
    results = run_ner.main(args)
    assert 0.0 <= results["val_f1"] <= 1.0
    assert "test_f1" in results


def test_macro_f1_perfect_and_zero():
    from run_ner import macro_f1

    logits = np.zeros((1, 4, 3))
    labels = np.asarray([[1, 2, 1, -100]])
    logits[0, 0, 1] = 5; logits[0, 1, 2] = 5; logits[0, 2, 1] = 5
    assert macro_f1(logits, labels) == 1.0
    logits2 = np.zeros((1, 4, 3))
    logits2[0, :, 0] = 5  # predict reserved class everywhere
    assert macro_f1(logits2, labels) == 0.0


def test_squad_v2_null_answers(tokenizer, tmp_path):
    """SQuAD v2.0: unanswerable questions decode to the empty string when
    the null score beats the best span by more than the threshold
    (reference run_squad.py's version_2_with_negative path)."""
    from bert_pytorch_tpu import squad

    context = "The capital of France is Paris"
    data = {"version": "v2.0", "data": [{"title": "t", "paragraphs": [{
        "context": context, "qas": [
            {"id": "a1", "question": "What is the capital of France",
             "is_impossible": False,
             "answers": [{"text": "Paris",
                          "answer_start": context.index("Paris")}]},
            {"id": "na1", "question": "Who wrote Hamlet",
             "is_impossible": True, "answers": []},
        ]}]}]}
    path = tmp_path / "v2.json"
    path.write_text(json.dumps(data))

    examples = squad.read_squad_examples(str(path), True, True)
    assert [e.is_impossible for e in examples] == [False, True]

    examples = squad.read_squad_examples(str(path), False, True)
    features = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=32, doc_stride=8,
        max_query_length=16, is_training=False)

    results = []
    for f in features:
        start = np.full(32, -5.0)
        end = np.full(32, -5.0)
        qid = examples[f.example_index].qas_id
        if qid == "a1":
            pos = f.tokens.index("paris", f.tokens.index("[SEP]"))
            start[pos] = 5.0
            end[pos] = 5.0
        else:
            # A REAL candidate span must exist and LOSE to the null score
            # through the threshold comparison (squad.py's score_diff path)
            # — with no surviving span at all, get_answers short-circuits
            # and the threshold logic would be dead to this test.
            pos = f.tokens.index("paris", f.tokens.index("[SEP]"))
            start[pos] = 2.0
            end[pos] = 2.0
            start[0] = 8.0  # null score = start[0] + end[0] ([CLS])
            end[0] = 8.0
        results.append(
            squad.RawResult(f.unique_id, start.tolist(), end.tolist()))
    answers, nbest, null_odds = squad.get_answers(
        examples, features, results, _decode_args(
            version_2_with_negative=True))
    assert answers["a1"] == "Paris"
    assert answers["na1"] == ""
    # null_odds carries the decode's null-vs-span score diff for the
    # official v2.0 best-threshold search: negative (span wins) for the
    # answerable question, positive for the unanswerable one
    assert null_odds["a1"] < 0 < null_odds["na1"]
    # the competing span is present in the n-best list — the null verdict
    # came from the threshold comparison, not from an empty candidate set
    assert any(e["text"] == "Paris" for e in nbest["na1"])
    # and with a huge threshold the span wins instead
    answers_hi, _, _ = squad.get_answers(
        examples, features, results, _decode_args(
            version_2_with_negative=True, null_score_diff_threshold=50.0))
    assert answers_hi["na1"] == "Paris"
