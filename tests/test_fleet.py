"""Fleet-tier resilience unit tests (PR 11, docs/serving.md "Fleet
tier"): the supervisor's restart-storm backoff under a fake clock, the
router's failover/hedge/brownout behaviors with fake transports, the
retry-policy extensions the router rides on (with the byte-identical
pin for every pre-existing call site), the requeue-during-drain batcher
regression, and the fleet_event/router_window schema + report gates.

The end-to-end proof — real replica subprocesses SIGKILLed/wedged under
a live burst — is ``tools/chaos_serve.py --smoke``
(tests/test_fleet_chaos.py)."""

from __future__ import annotations

import os
import random
import subprocess
import sys
import threading

import pytest

from bert_pytorch_tpu.serve.batcher import Batcher, Request
from bert_pytorch_tpu.serve.router import Router
from bert_pytorch_tpu.serve.supervisor import (BACKOFF, FAILED, RUNNING,
                                               STARTING, ReplicaSpec,
                                               Supervisor)
from bert_pytorch_tpu.telemetry import report, schema
from bert_pytorch_tpu.utils.preemption import EXIT_PREEMPTED
from bert_pytorch_tpu.utils.retry import RetryError, RetryPolicy, retry_call

HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(HERE)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# ---------------------------------------------------------------------------
# utils/retry.py: the PR-11 extensions + the byte-identical default pin


def test_retry_defaults_byte_identical_to_pre_fleet_formula():
    """The router's new modes are opt-in: under the DEFAULT flags every
    existing call site (dataset shard reads, bench loops) must draw the
    exact scaled-jitter sequence the pre-fleet formula produced."""
    seed = 20250803
    p = RetryPolicy(attempts=6, base_delay_s=0.8, max_delay_s=7.0,
                    jitter=0.5, rng=random.Random(seed))
    assert p.full_jitter is False and p.max_elapsed_s is None
    rng = random.Random(seed)
    for i in range(5):
        raw = min(7.0, 0.8 * 2 ** i)
        assert p.backoff_s(i) == raw * (1.0 - 0.5 + 0.5 * rng.random())


def test_retry_defaults_never_touch_the_clock():
    """max_elapsed_s=None must not even READ the clock — the cheapest
    possible proof that default-path behavior is unchanged."""
    def explode() -> float:
        raise AssertionError("clock read on the default path")

    p = RetryPolicy(attempts=3, base_delay_s=0.0, jitter=0.0,
                    sleep=lambda s: None, clock=explode)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, policy=p) == "ok"


def test_retry_full_jitter_band():
    p = RetryPolicy(base_delay_s=10.0, max_delay_s=10.0, full_jitter=True,
                    rng=random.Random(0))
    draws = [p.backoff_s(0) for _ in range(200)]
    assert all(0.0 <= d < 10.0 for d in draws)
    assert min(draws) < 2.0  # genuinely reaches the low band
    assert len(set(draws)) > 100


def test_retry_max_elapsed_budget_stops_the_loop():
    clock = FakeClock()
    p = RetryPolicy(attempts=10, base_delay_s=1.0, jitter=0.0,
                    max_elapsed_s=2.5, clock=clock,
                    sleep=lambda s: clock.advance(s))
    calls = {"n": 0}

    def always_down():
        calls["n"] += 1
        raise OSError("replica down")

    with pytest.raises(RetryError, match="elapsed budget"):
        retry_call(always_down, policy=p)
    # attempt 1 fails -> 1s backoff fits the 2.5s budget; attempt 2
    # fails -> the next 2s backoff would land at 3s > 2.5 -> abandon.
    assert calls["n"] == 2


# ---------------------------------------------------------------------------
# serve/batcher.py: the requeue-during-drain regression


def _req(task="classify", n=6):
    return Request(task, {"input_ids": list(range(2, 2 + n)),
                          "segment_ids": [0] * n}, {})


def test_batcher_unfinished_covers_popped_requests():
    """depth() reads 0 the instant a batch is popped; unfinished() —
    what stop()'s drain loop now waits on — must not, or a drain racing
    the dispatch window closes the batcher under requests whose plan
    leftovers are about to requeue (the PR-11 bug)."""
    b = Batcher(max_batch_size=4, max_wait_ms=0.0)
    reqs = [_req() for _ in range(4)]
    for r in reqs:
        b.submit(r)
    batch = b.next_batch(timeout=0.01)
    assert len(batch) == 4
    assert b.depth() == 0              # the lying gauge the bug raced
    assert b.unfinished() == 4         # the honest one

    # A partial dispatch requeues 2 as plan leftovers: they move from
    # in-flight back to pending with no dip in between.
    b.requeue_front(batch[2:])
    assert b.depth() == 2
    assert b.unfinished() == 4
    b.done(2)                          # the dispatched pair finished
    assert b.unfinished() == 2

    # Drain flush: whatever dispatch never got to is handed back for a
    # deterministic error instead of stranding blocked submitters.
    stranded = b.drain_remaining()
    assert [r.id for r in stranded] == [r.id for r in batch[2:]]
    assert b.depth() == 0


def test_batcher_done_and_requeue_never_go_negative():
    b = Batcher(max_batch_size=4, max_wait_ms=0.0)
    b.done(3)                          # nothing popped: clamps at 0
    assert b.unfinished() == 0
    b.requeue_front([_req()])          # never-popped requeue (tests do)
    assert b.depth() == 1
    assert b.unfinished() == 1         # pending only, not negative


def test_batcher_requeue_during_drain_ordering():
    """The full race, single-threaded: stop() must observe unfinished()
    > 0 across the pop -> requeue window, so leftovers re-enter the
    queue BEFORE the close, in FIFO order."""
    b = Batcher(max_batch_size=8, max_wait_ms=0.0)
    reqs = [_req() for _ in range(6)]
    for r in reqs:
        b.submit(r)
    batch = b.next_batch(timeout=0.01)
    assert len(batch) == 6 and b.unfinished() == 6
    # drain begins here; depth()==0 would have let stop() close now
    b.requeue_front(batch[4:])
    b.done(4)
    b.close()
    leftovers = b.drain_remaining()
    assert [r.id for r in leftovers] == [batch[4].id, batch[5].id]
    assert b.unfinished() == 0


# ---------------------------------------------------------------------------
# serve/supervisor.py: restart-storm backoff with a fake clock


class FakeProc:
    _pids = iter(range(4000, 5000))

    def __init__(self):
        self.pid = next(FakeProc._pids)
        self.rc = None
        self.signals = []

    def poll(self):
        return self.rc

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)
        self.rc = EXIT_PREEMPTED   # a well-behaved replica drains


def _supervisor(clock, *, attempts=4, heartbeat=None, events=None,
                **kwargs):
    procs = []

    def spawn(spec):
        procs.append(FakeProc())
        return procs[-1]

    sup = Supervisor(
        [ReplicaSpec(0, 9001, ["run_server"],
                     heartbeat_file="hb.json" if heartbeat else None)],
        emit=events.append if events is not None else None,
        spawn=spawn,
        policy=RetryPolicy(attempts=attempts, base_delay_s=1.0,
                           max_delay_s=8.0, jitter=0.0),
        read_heartbeat=heartbeat, clock=clock, sleep=lambda s: None,
        **kwargs)
    return sup, procs


def test_supervisor_backoff_schedule_and_give_up():
    clock = FakeClock()
    events: list = []
    sup, procs = _supervisor(clock, attempts=4, events=events)
    sup.start(monitor=False)
    assert len(procs) == 1

    backoffs = []
    for expected in (1.0, 2.0, 4.0):   # base 1.0 x2, capped at 8, jitter 0
        procs[-1].rc = 1               # crash
        sup.poll_once()
        st = sup.status()[0]
        assert st["state"] == BACKOFF
        sched = [e for e in events if e["event"] == "restart_scheduled"]
        backoffs.append(sched[-1]["backoff_s"])
        # Not a second early: just before the deadline nothing respawns.
        clock.advance(expected - 0.01)
        sup.poll_once()
        assert len(procs) == len(backoffs)
        clock.advance(0.02)
        sup.poll_once()
        assert len(procs) == len(backoffs) + 1   # respawned on schedule
    assert backoffs == [1.0, 2.0, 4.0]

    procs[-1].rc = 1                   # 4th consecutive crash: give up
    sup.poll_once()
    st = sup.status()[0]
    assert st["state"] == FAILED
    assert [e["event"] for e in events].count("gave_up") == 1
    clock.advance(60.0)
    sup.poll_once()
    assert len(procs) == 4             # FAILED stays down


def test_supervisor_graceful_exit_respawns_without_burning_budget():
    clock = FakeClock()
    events: list = []
    sup, procs = _supervisor(clock, events=events)
    sup.start(monitor=False)
    procs[-1].rc = EXIT_PREEMPTED      # asked to drain, not a crash
    sup.poll_once()
    sched = [e for e in events if e["event"] == "restart_scheduled"][-1]
    assert sched["crash"] is False and sched["backoff_s"] == 0.0
    sup.poll_once()                    # immediate respawn
    assert len(procs) == 2
    assert sup.status()[0]["consecutive_crashes"] == 0


def test_supervisor_graceful_churn_escalates_to_backoff():
    """ONE free graceful respawn per stable stretch: a replica that
    keeps exiting 0/75 within stable_reset_s of each spawn is a crash
    loop wearing a polite exit code (a config that drains instantly, an
    agent SIGTERMing every startup) and must walk the restart-storm
    schedule — a zero-backoff respawn every poll tick is exactly the
    storm the backoff exists to prevent."""
    clock = FakeClock()
    events: list = []
    sup, procs = _supervisor(clock, events=events)
    sup.start(monitor=False)
    procs[-1].rc = EXIT_PREEMPTED
    sup.poll_once()                    # first graceful exit: free
    sched = [e for e in events if e["event"] == "restart_scheduled"][-1]
    assert sched["crash"] is False and sched["backoff_s"] == 0.0
    sup.poll_once()                    # immediate respawn
    assert len(procs) == 2
    procs[-1].rc = EXIT_PREEMPTED      # "drains" again, instantly
    sup.poll_once()
    sched = [e for e in events if e["event"] == "restart_scheduled"][-1]
    assert sched["crash"] is True
    assert sched["reason"] == "graceful_churn"
    assert sched["backoff_s"] > 0.0
    assert sup.status()[0]["consecutive_crashes"] == 1


def test_supervisor_stable_run_pays_backoff_debt_back():
    clock = FakeClock()
    hb = {"counter": 0}
    sup, procs = _supervisor(clock, heartbeat=lambda spec: hb["counter"],
                             stable_reset_s=30.0)
    sup.start(monitor=False)
    procs[-1].rc = 1
    sup.poll_once()                    # crash -> consecutive = 1
    clock.advance(1.5)
    sup.poll_once()                    # respawn
    assert sup.status()[0]["consecutive_crashes"] == 1
    hb["counter"] += 1
    sup.poll_once()                    # heartbeat advance -> RUNNING
    assert sup.status()[0]["state"] == RUNNING
    clock.advance(31.0)
    hb["counter"] += 1
    sup.poll_once()                    # stable past stable_reset_s
    assert sup.status()[0]["consecutive_crashes"] == 0


def test_supervisor_watchdog_kills_wedged_replica():
    """A wedged dispatch thread keeps /healthz 200 — only the heartbeat
    counter going stale can catch it. The stale-counter age must be
    measured against the startup grace while STARTING (a warming
    replica is not wedged) and the tight timeout once RUNNING."""
    clock = FakeClock()
    events: list = []
    hb = {"counter": 0}
    sup, procs = _supervisor(
        clock, events=events, heartbeat=lambda spec: hb["counter"],
        heartbeat_timeout_s=5.0, startup_grace_s=60.0)
    sup.start(monitor=False)
    # Warming: counter stale at its pre-spawn baseline, 20s in — still
    # inside the startup grace, must NOT be killed.
    clock.advance(20.0)
    sup.poll_once()
    assert sup.status()[0]["state"] == STARTING
    assert procs[-1].rc is None
    hb["counter"] += 1
    sup.poll_once()                    # first beat -> RUNNING
    assert sup.status()[0]["state"] == RUNNING
    hb["counter"] += 1
    clock.advance(1.0)
    sup.poll_once()                    # advancing: healthy
    assert procs[-1].rc is None
    clock.advance(5.5)                 # counter frozen past the timeout
    sup.poll_once()
    assert [e["event"] for e in events].count("wedged_kill") == 1
    assert procs[-1].rc == -9          # SIGKILLed
    assert sup.status()[0]["state"] == BACKOFF


def test_supervisor_restart_baselines_stale_heartbeat():
    """The heartbeat file SURVIVES a replica crash (the counter resumes
    from it). The predecessor's last value must not read as an advance
    for the fresh process — that would flip a warming replica straight
    to RUNNING and arm the tight wedge timeout against its startup."""
    clock = FakeClock()
    hb = {"counter": 57}               # the dead replica's last beat
    sup, procs = _supervisor(
        clock, heartbeat=lambda spec: hb["counter"],
        heartbeat_timeout_s=5.0, startup_grace_s=60.0)
    sup.start(monitor=False)
    clock.advance(10.0)                # warming, stale counter visible
    sup.poll_once()
    assert sup.status()[0]["state"] == STARTING
    assert procs[-1].rc is None        # grace applies — no false kill
    hb["counter"] = 58                 # the NEW process's first beat
    sup.poll_once()
    assert sup.status()[0]["state"] == RUNNING


def test_supervisor_stop_reports_preemption_contract_exits():
    clock = FakeClock()
    sup, procs = _supervisor(clock)
    sup.start(monitor=False)
    summary = sup.stop()
    assert procs[-1].signals == [15]   # SIGTERM drain
    assert summary["rcs"] == {0: EXIT_PREEMPTED}
    assert summary["all_graceful"] is True and summary["drain_killed"] == 0


# ---------------------------------------------------------------------------
# serve/router.py: failover, hedging, brownout


def _healthy_scrape(url):
    return {"dispatch_alive": True, "draining": False, "queue_depth": 0}


def _router(transport, scrape=_healthy_scrape, urls=("http://a:1",
                                                     "http://b:2"),
            events=None, **kwargs):
    kwargs.setdefault("retry_policy", RetryPolicy(
        attempts=3, base_delay_s=0.0, jitter=0.0))
    kwargs.setdefault("hedge_pctl", 0.0)   # hedging off unless the test
    r = Router(list(urls), emit=events.append if events is not None
               else None, transport=transport, scrape=scrape,
               sleep=lambda s: None, **kwargs)
    r.scrape_once()
    return r


def test_router_retry_excludes_failed_replica():
    calls = []

    def transport(url, task, payload, timeout_s):
        calls.append(url)
        if url == "http://a:1":
            raise ConnectionRefusedError("replica a is dead")
        return 200, {"answer": 42}

    r = _router(transport)
    status, body, headers = r.handle("classify", {"text": "hi"})
    assert status == 200 and body == {"answer": 42}
    # index tie-break routed to a first; the retry went ELSEWHERE.
    assert calls == ["http://a:1", "http://b:2"]
    snap = r.snapshot()
    assert snap["failovers"] == 1 and snap["retries"] == 1
    assert snap["errors"] == 0
    # Fast feedback: the failed replica is out of rotation until a
    # scrape proves it back.
    assert [s for s in snap["replica_states"]
            if s["url"] == "http://a:1"][0]["healthy"] is False
    r.scrape_once()
    assert r.healthy_count() == 2      # ...and the scrape re-heals it


def test_router_retryable_5xx_fails_over_but_4xx_is_final():
    calls = []

    def transport(url, task, payload, timeout_s):
        calls.append(url)
        if url == "http://a:1":
            return 500, {"error": "execute blew up"}
        return 200, {"ok": True}

    r = _router(transport)
    status, _, _ = r.handle("classify", {"text": "hi"})
    assert status == 200 and calls == ["http://a:1", "http://b:2"]

    calls.clear()

    def bad_payload(url, task, payload, timeout_s):
        calls.append(url)
        return 400, {"error": "bad JSON"}

    r2 = _router(bad_payload)
    status, _, _ = r2.handle("classify", {"text": None})
    # A client error is the same on every replica: answered as-is, once.
    assert status == 400 and len(calls) == 1
    snap = r2.snapshot()
    assert snap["retries"] == 0
    # A relayed 4xx is the router WORKING (counted ok, not error): the
    # zero-tolerance "router client-visible errors" report gate must
    # not trip because one client mistyped a task name.
    assert snap["ok"] == 1 and snap["errors"] == 0


def test_router_exhausted_retries_yield_502():
    def transport(url, task, payload, timeout_s):
        raise ConnectionRefusedError("everything is down")

    r = _router(transport)
    status, body, _ = r.handle("classify", {"text": "hi"})
    # Both replicas burned -> no candidates -> the outage shed answer.
    assert status == 503
    assert r.snapshot()["sheds"] == 1


def test_router_hedge_fires_only_past_percentile():
    slow_started = threading.Event()
    release_slow = threading.Event()
    calls = []
    lock = threading.Lock()

    def transport(url, task, payload, timeout_s):
        with lock:
            calls.append(url)
        if url == "http://a:1":
            slow_started.set()
            release_slow.wait(timeout=10.0)   # the slow tail
            return 200, {"from": "a"}
        return 200, {"from": "b"}

    r = _router(transport, hedge_pctl=0.95, hedge_min_ms=10.0,
                hedge_min_samples=8)
    # Below min_samples: no hedge threshold exists yet.
    assert r._hedge_delay_s() is None
    for _ in range(16):
        r.note_latency(0.005)
    delay = r._hedge_delay_s()
    assert delay == pytest.approx(0.010)   # floored at hedge_min_ms

    status, body, _ = r.handle("classify", {"text": "hi"})
    release_slow.set()
    assert status == 200 and body == {"from": "b"}   # the hedge won
    snap = r.snapshot()
    assert snap["hedges"] == 1 and snap["hedge_wins"] == 1
    assert snap["errors"] == 0

    # A fast primary never hedges: budgeted tail-cutting, not 2x load.
    calls.clear()
    fast = _router(lambda u, t, p, s: (200, {"from": u}),
                   hedge_pctl=0.95, hedge_min_ms=10.0, hedge_min_samples=8)
    for _ in range(16):
        fast.note_latency(0.005)
    fast.handle("classify", {"text": "hi"})
    assert fast.snapshot()["hedges"] == 0


def test_router_brownout_503_carries_retry_after():
    def saturated(url):
        return {"dispatch_alive": True, "draining": False,
                "queue_depth": 128}

    r = _router(lambda *a: (200, {}), scrape=saturated,
                brownout_queue_depth=64, shed_retry_after_s=1.5)
    status, body, headers = r.handle("classify", {"text": "hi"})
    assert status == 503
    assert headers["Retry-After"] == "1.5"
    assert "brownout" in body["error"]
    assert r.snapshot()["sheds"] == 1


def test_router_skips_draining_and_dead_dispatch_replicas():
    calls = []

    def transport(url, task, payload, timeout_s):
        calls.append(url)
        return 200, {}

    def scrape(url):
        if url == "http://a:1":
            return {"dispatch_alive": True, "draining": True,
                    "queue_depth": 0}
        return {"dispatch_alive": True, "draining": False,
                "queue_depth": 5}

    r = _router(transport, scrape=scrape)
    status, _, _ = r.handle("classify", {"text": "hi"})
    # a is draining: even with the deeper queue, b takes the request.
    assert status == 200 and calls == ["http://b:2"]


def test_router_window_and_summary_records_are_schema_clean():
    events: list = []

    def transport(url, task, payload, timeout_s):
        if url == "http://a:1":
            raise ConnectionRefusedError("down")
        return 200, {}

    r = _router(transport, events=events, window=4)
    for _ in range(5):
        r.handle("classify", {"text": "hi"})
    r.stop()
    kinds = [e.get("kind") for e in events]
    assert "router_window" in kinds and "router_summary" in kinds
    for rec in events:
        rec = dict(rec, schema=schema.SCHEMA_VERSION, ts=0.0)
        assert schema.validate_record(rec) == [], rec
    summary = [e for e in events if e["kind"] == "router_summary"][-1]
    assert summary["requests"] == 5
    assert summary["failovers"] >= 1
    assert summary["failover_p95_ms"] >= 0


# ---------------------------------------------------------------------------
# schema lint fixtures + the telemetry-report "router failover" gate


def test_fleet_schema_fixtures_lint():
    good = os.path.join(HERE, "fixtures", "telemetry", "fleet_good.jsonl")
    bad = os.path.join(HERE, "fixtures", "telemetry", "fleet_bad.jsonl")
    assert schema.validate_file(good) == []
    errors = schema.validate_file(bad)
    text = " | ".join(err for _, err in errors)
    assert "event must be a non-empty string" in text
    assert "ok + sheds + errors must equal window_requests" in text
    assert "hedge_wins (3) exceeds hedges (1)" in text
    assert "healthy_replicas (4) exceeds replicas (2)" in text
    assert "failover percentiles not ordered" in text
    assert "backoff_s must be a non-negative number" in text
    # And the repo tool (jax-free, file-path bootstrap) agrees.
    proc = subprocess.run(
        [sys.executable, "tools/check_telemetry_schema.py", good, bad],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "fleet_good.jsonl: ok" in proc.stdout
    assert "fleet_bad" in proc.stdout


def _fleet_records(failover_p95_ms=120.0, errors=0, gave_up=0):
    records = [
        {"kind": "fleet_event", "event": "spawn", "replica": i, "port": p}
        for i, p in ((0, 8001), (1, 8002))]
    records += [{"kind": "fleet_event", "event": "restart_scheduled",
                 "replica": 0, "port": 8001, "crash": True,
                 "backoff_s": 0.4, "reason": "exit"}]
    records += [{"kind": "fleet_event", "event": "gave_up", "replica": 1,
                 "port": 8002}] * gave_up
    records.append({
        "kind": "router_window", "window_requests": 64, "ok": 62 - errors,
        "sheds": 2, "errors": errors, "retries": 3, "hedges": 2,
        "hedge_wins": 1, "failovers": 3, "healthy_replicas": 2,
        "replicas": 2, "latency_p50_ms": 8.0, "latency_p95_ms": 40.0,
        "latency_p99_ms": 80.0, "failover_p50_ms": 60.0,
        "failover_p95_ms": failover_p95_ms})
    return [dict(r, schema=schema.SCHEMA_VERSION, ts=0.0) for r in records]


def test_report_summarizes_fleet_records():
    summary = report.summarize_records(_fleet_records())
    assert summary["router_requests"] == 64
    assert summary["router_failovers"] == 3
    assert summary["router_failover_p95_ms"] == 120.0
    assert summary["fleet_spawns"] == 2
    assert summary["fleet_crash_restarts"] == 1
    assert summary["fleet_gave_up"] == 0
    text = report.format_summary(summary)
    assert "router_failover_p95_ms" in text and "fleet_event_kinds" in text


def test_report_router_failover_gate_trips():
    """The named resilience gate: injected failover latency drifting
    past tolerance must be CALLED OUT, not averaged away."""
    base = report.summarize_records(_fleet_records(failover_p95_ms=120.0))
    ok_run = report.summarize_records(_fleet_records(failover_p95_ms=130.0))
    slow = report.summarize_records(_fleet_records(failover_p95_ms=400.0))
    regressions, _ = report.compare(base, ok_run)
    assert regressions == []
    regressions, _ = report.compare(base, slow)
    assert "router failover p95" in [r["label"] for r in regressions]


def test_report_router_errors_and_gave_up_are_zero_tolerance():
    base = report.summarize_records(_fleet_records())
    bad = report.summarize_records(_fleet_records(errors=1, gave_up=1))
    regressions, _ = report.compare(base, bad)
    labels = [r["label"] for r in regressions]
    assert "router client-visible errors" in labels
    assert "fleet replicas given up" in labels
