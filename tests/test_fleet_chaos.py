"""ISSUE 11 acceptance: the fleet chaos harness (tools/chaos_serve.py
--smoke) SIGKILLs a serving replica under a concurrent client burst,
wedges another's dispatch thread (the failure only the supervisor's
heartbeat watchdog can catch), and cuts a graceful drain short with a
second kill — and no client ever sees it.

Kept in its own module so the heavyweight subprocess gate (the
supervisor spawns real ``run_server.py`` replicas; ~90s on a throttled
2-core box) never slows collection of the in-process fleet tests
(tests/test_fleet.py)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_chaos_serve_fleet_failover_acceptance():
    """Zero client-visible failures beyond explicit 503 sheds; failover
    inside the retry budget (p95 under the tolerance the
    telemetry-report "router failover" gate regresses on); the killed
    replica respawned from the shared AOT cache with compiles_cold==0
    (cache counter events, the PR-8 authority); replica 0 drained with
    the training runners' EXIT_PREEMPTED contract at stop."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "chaos_serve.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=540,
        cwd=os.path.join(REPO_ROOT, "tools"))
    assert proc.returncode == 0, (proc.stdout[-3000:], proc.stderr[-2000:])
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True
    for phase in ("phase_a", "phase_b", "phase_c"):
        assert verdict[phase]["failures"] == 0, verdict[phase]
    # Phase A's SIGKILL landed inside the admission window: the armed
    # admit_hold fault reported the assembler holding a forming batch
    # open (pipelined dispatch) before the kill fired.
    assert verdict["phase_a"]["admit_hold_observed"] is True
    assert verdict["restart_compiles_cold"] == 0
    assert verdict["router"]["errors"] == 0
    assert verdict["router"]["failovers"] >= 1
    assert verdict["router"]["failover_p95_ms"] <= 8000.0
    assert verdict["drain"]["rcs"]["0"] == 75  # EXIT_PREEMPTED
