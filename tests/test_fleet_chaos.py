"""ISSUE 11 acceptance: the fleet chaos harness (tools/chaos_serve.py
--smoke) SIGKILLs a serving replica under a concurrent client burst,
wedges another's dispatch thread (the failure only the supervisor's
heartbeat watchdog can catch), and cuts a graceful drain short with a
second kill — and no client ever sees it. Since ISSUE 19 the smoke run
also SIGKILLs a replica INSIDE an armed hot-swap window (phase D) to
prove the torn-model count stays zero, and ``--canary`` drives the full
deployment plane: registry publish, SLO-gated 1% -> 50% -> 100% canary
rollout, and auto-rollback of a degraded version.

Kept in its own module so the heavyweight subprocess gate (the
supervisor spawns real ``run_server.py`` replicas; ~90s on a throttled
2-core box) never slows collection of the in-process fleet tests
(tests/test_fleet.py). Since ISSUE 14 the subprocess acceptance itself
is second-tier (``-m slow``); the harness's VERDICT ARITHMETIC — the
ok/shed/failure decomposition and the cold-start record scan every
chaos assertion trusts — is carried tier-1 by the cheap in-process
tests below (chaos_serve.py is stdlib-only and loads by file path, so
they cost milliseconds)."""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_chaos_serve():
    # chaos_serve.py resolves its siblings through tools/_bootstrap.py
    # (the harness runs with cwd=tools/), so the loader mirrors that.
    tools_dir = os.path.join(REPO_ROOT, "tools")
    spec = importlib.util.spec_from_file_location(
        "_test_chaos_serve", os.path.join(tools_dir, "chaos_serve.py"))
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, tools_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.remove(tools_dir)
    return module


def test_classify_outcomes_decomposition():
    """The burst verdict the acceptance trusts: 2xx is ok, a 503 WITH
    Retry-After is an explicit shed, everything else — including the
    router's own deadline 503, which carries no Retry-After — is a
    client-visible failure."""
    chaos = _load_chaos_serve()
    outcomes = [
        {"status": 200},
        {"status": 201},
        {"status": 503, "retry_after": "1"},   # admission-control shed
        {"status": 503},                        # deadline 503: FAILURE
        {"status": 500},
        {"status": None},                       # transport error
    ]
    verdict = chaos.classify_outcomes(outcomes)
    assert verdict["requests"] == 6
    assert verdict["ok"] == 2
    assert verdict["sheds"] == 1
    assert verdict["failures"] == 3
    assert len(verdict["failure_samples"]) == 3


def test_cold_start_record_scan(tmp_path):
    """The warm-restart assertion reads serve_cold_start records from
    the replica's telemetry artifact; the scan must pick exactly that
    kind and preserve order (the RESPAWNED replica's record is the one
    the compiles_cold==0 check targets)."""
    chaos = _load_chaos_serve()
    out_dir = str(tmp_path)
    path = os.path.join(out_dir, "serve_telemetry.jsonl")
    records = [
        {"kind": "serve_cold_start", "compiles_cold": 4,
         "compiles_warm": 0, "compiles": 4, "cold_start_s": 2.0},
        {"kind": "serve_window", "window_requests": 8},
        {"kind": "serve_cold_start", "compiles_cold": 0,
         "compiles_warm": 4, "compiles": 4, "cold_start_s": 0.5},
    ]
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    found = chaos.cold_start_records(out_dir)
    assert [r["compiles_cold"] for r in found] == [4, 0]
    assert chaos.cold_start_records(str(tmp_path / "missing")) == []


@pytest.mark.slow  # ~47-90s: supervisor + real run_server.py replica
# subprocesses (ISSUE 14 budget fix); the in-process supervisor/router
# behavior is tier-1 in tests/test_fleet.py and the verdict arithmetic
# in the tests above.
def test_chaos_serve_fleet_failover_acceptance():
    """Zero client-visible failures beyond explicit 503 sheds; failover
    inside the retry budget (p95 under the tolerance the
    telemetry-report "router failover" gate regresses on); the killed
    replica respawned from the shared AOT cache with compiles_cold==0
    (cache counter events, the PR-8 authority); replica 0 drained with
    the training runners' EXIT_PREEMPTED contract at stop."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "chaos_serve.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=540,
        cwd=os.path.join(REPO_ROOT, "tools"))
    assert proc.returncode == 0, (proc.stdout[-3000:], proc.stderr[-2000:])
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True
    for phase in ("phase_a", "phase_b", "phase_c"):
        assert verdict[phase]["failures"] == 0, verdict[phase]
    # Phase A's SIGKILL landed inside the admission window: the armed
    # admit_hold fault reported the assembler holding a forming batch
    # open (pipelined dispatch) before the kill fired.
    assert verdict["phase_a"]["admit_hold_observed"] is True
    assert verdict["restart_compiles_cold"] == 0
    assert verdict["router"]["errors"] == 0
    assert verdict["router"]["failovers"] >= 1
    assert verdict["router"]["failover_p95_ms"] <= 8000.0
    assert verdict["drain"]["rcs"]["0"] == 75  # EXIT_PREEMPTED
    # End-to-end tracing acceptance (docs/observability.md "Trace
    # propagation"): every sampled client request stitched into exactly
    # one trace tree with zero orphans, and the SIGKILL-mid-flight
    # retried request yields ONE stitched trace whose attempt-1 span
    # names the killed replica (transport_error) and whose winning
    # attempt 2+ chains to the surviving replica's serve_trace.
    trace = verdict["trace"]
    assert trace["stitches"] == trace["router_traces"]
    assert trace["orphans"] == 0
    assert trace["complete"] >= 1
    fo = verdict["failover_trace"]
    assert fo["winning_attempt"] >= 2
    assert fo["attempt_1_replica"] != fo["winning_replica"]
    assert fo["winning_trace_id"]          # chains to a serve_trace
    assert fo["winning_source"]            # ... from a named replica sink
    # Every answered request echoed the router's trace id (satellite-2
    # correlation contract), and the report gates fired live: doctored
    # router delay -> rc 1 naming "router overhead share"; clean
    # self-diff -> rc 0.
    for phase in ("phase_a", "phase_b", "phase_c"):
        assert verdict[phase]["traced"] >= verdict[phase]["ok"], \
            verdict[phase]
    assert verdict["report_gate"] == {"doctored_rc": 1, "clean_rc": 0}
    # Phase D (ISSUE 19): SIGKILL landed inside the armed swap_hold
    # window — between checkpoint load and the atomic flip — and the
    # fleet never served a torn model; the completed swap_all after the
    # respawn hit the shared AOT cache (zero cold compiles).
    d = verdict["phase_d"]
    assert d["failures"] == 0 and d["swap_hold_observed"] is True, d
    assert d["torn_serves"] == 0
    assert d["swap_compiles_cold"] == 0


@pytest.mark.slow  # ~40-120s: live burst + elastic replica subprocesses
def test_chaos_serve_surge_elasticity_acceptance():
    """ISSUE 20 acceptance (tools/chaos_serve.py --surge): a burst past
    one replica's capacity makes the autoscaler scale up WARM (the
    elastic replica boots from the shared AOT cache, compiles_cold==0),
    sheds stop and p99 recovers at the same offered load; a SIGKILL
    mid-surge is respawned capacity, never double-counted growth; the
    load dropping to a trickle drains the elastic replica through the
    SIGTERM -> rc-75 contract with zero stranded requests; and the
    seeded-violation artifact trips BOTH zero-tolerance elasticity
    gates by name while the real artifact self-diffs green."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "chaos_serve.py"),
         "--surge"],
        capture_output=True, text=True, timeout=540,
        cwd=os.path.join(REPO_ROOT, "tools"))
    assert proc.returncode == 0, (proc.stdout[-3000:], proc.stderr[-2000:])
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True
    # Zero client-visible failures in EVERY phase; the surge genuinely
    # ramped past capacity (explicit sheds) and stopped shedding once
    # capacity doubled.
    for phase in ("phase_surge", "phase_post", "phase_trickle"):
        assert verdict[phase]["failures"] == 0, verdict[phase]
    assert verdict["phase_surge"]["sheds"] > 0
    assert verdict["phase_post"]["sheds"] == 0
    # Warm elasticity: the cache counter events are the authority.
    assert verdict["elastic_compiles_cold"] == 0
    assert verdict["p99_post_s"] < verdict["p99_surge_s"]
    # Hysteresis held: one up, one down, zero thrash, and the event
    # stream never books capacity past the band or unexplained drift.
    assert verdict["controller"]["scale_ups"] == 1
    assert verdict["controller"]["scale_downs"] == 1
    assert verdict["controller"]["thrash"] == 0
    assert verdict["report_gate"] == {"breach_rc": 1, "clean_rc": 0}


@pytest.mark.slow  # ~15-40s: 2 real replicas + registry + full rollout
def test_chaos_serve_canary_rollout_acceptance():
    """ISSUE 19 acceptance (tools/chaos_serve.py --canary): a version
    published from the fleet's own init checkpoint rolls out
    1% -> 50% -> 100% behind the router's deterministic request-hash
    split with zero client-visible failures and zero cold compiles on
    every same-geometry swap; the per-version router counters export
    consistently on /statsz and /metricsz; a degraded version breaches
    its (unmeetable) p95 SLO on its FIRST full canary window and
    auto-rolls back — and the breach artifact trips the zero-tolerance
    "rollout canary SLO" report gate against the pre-breach baseline."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "chaos_serve.py"),
         "--canary"],
        capture_output=True, text=True, timeout=540,
        cwd=os.path.join(REPO_ROOT, "tools"))
    assert proc.returncode == 0, (proc.stdout[-3000:], proc.stderr[-2000:])
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True
    # The staircase ran to promotion, every window green.
    windows = verdict["happy_windows"]
    assert windows[-1]["action"] == "promote"
    assert all(w["errors"] == 0 and w["slo_ok"] for w in windows)
    shares = [w["canary_share"] for w in windows]
    assert shares == sorted(shares) and shares[-1] == 1.0
    # The degraded leg rolled back naming the breached SLO.
    degraded = verdict["degraded_window"]
    assert degraded["action"] == "rollback"
    assert degraded["slo_ok"] is False and "p95" in degraded["reason"]
    assert verdict["torn_serves"] == 0
    assert verdict["version_requests"].get("v2", 0) > 0
    assert verdict["report_gate"] == {"breach_rc": 1, "clean_rc": 0}
