"""End-to-end fleet tracing (ISSUE 16, docs/observability.md "Trace
propagation"): the router's trace-context minting + ``X-Bert-Trace``
propagation, its admission/attempt/backoff span taxonomy and hedge-waste
accounting, the replica tracer's adoption of the router's sampling
decision, the fleet collector's stitcher (complete trees, orphan grace,
slow-forced exclusion), the ``trace_stitch`` schema rules, the
telemetry-report trace section with its two named gates, and the
``obs_collect.py --trace`` drill-down.

Everything here is in-process and engine-free (the router, collector,
schema, and report layers are deliberately jax-light); the live
2-replica SIGKILL acceptance that exercises the same surfaces over real
HTTP is tools/chaos_serve.py, gated slow in tests/test_fleet_chaos.py.
The replica HTTP half (header echo + adoption through a real service)
is tests/test_serve_tracing.py."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import pytest

from bert_pytorch_tpu.serve import router as router_mod
from bert_pytorch_tpu.serve.router import Router
from bert_pytorch_tpu.serve.tracing import (TRACE_HEADER,
                                            TRACE_ID_RESPONSE_HEADER,
                                            TraceCollector,
                                            format_trace_header,
                                            parse_trace_header)
from bert_pytorch_tpu.telemetry import report
from bert_pytorch_tpu.telemetry.collector import (STITCH_GRACE_PASSES,
                                                  FleetCollector,
                                                  JsonlTailer, stitch_tree)
from bert_pytorch_tpu.telemetry.schema import validate_file, validate_record
from bert_pytorch_tpu.utils.retry import RetryPolicy

HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(HERE)


def _valid(rec: dict) -> list:
    """Schema errors for one record (stamped with the envelope the
    emitters add)."""
    return validate_record(dict({"schema": 1, "ts": 0.0}, **rec))


# ---------------------------------------------------------------------------
# the wire format: both tiers speak the SAME header


def test_trace_header_round_trip_cross_module():
    """router.py duplicates the wire format on purpose (stdlib-only,
    dual-loadable by file path); this pins the two copies together."""
    assert router_mod.TRACE_HEADER == TRACE_HEADER
    assert router_mod.TRACE_ID_RESPONSE_HEADER == TRACE_ID_RESPONSE_HEADER
    for attempt, sampled in ((1, True), (3, False)):
        wire = router_mod.format_trace_header("rt-abc123-7", attempt,
                                              sampled)
        assert wire == format_trace_header("rt-abc123-7", attempt, sampled)
        ctx = parse_trace_header(wire)
        assert ctx == {"trace_id": "rt-abc123-7", "attempt": attempt,
                       "sampled": sampled}
    # Malformed/absent headers parse to None — never an exception on
    # the request path.
    for junk in (None, "", ";;;", "id;attempt=x;sampled=2",
                 ";attempt=1;sampled=1"):
        assert parse_trace_header(junk) is None
    # Sampling hashes agree too: the router's fleet-wide decision and a
    # replica replay of the same sequence must see the SAME coin.
    from bert_pytorch_tpu.serve.tracing import _sample_hash as serve_hash
    assert all(router_mod._sample_hash(i) == serve_hash(i)
               for i in range(64))


# ---------------------------------------------------------------------------
# router tier: minting, propagation, span taxonomy


def _healthy_scrape(url):
    return {"dispatch_alive": True, "draining": False, "queue_depth": 0}


def _router(transport, urls=("http://a:1", "http://b:2"), events=None,
            **kwargs):
    kwargs.setdefault("retry_policy", RetryPolicy(
        attempts=3, base_delay_s=0.0, jitter=0.0))
    kwargs.setdefault("hedge_pctl", 0.0)
    r = Router(list(urls), emit=events.append if events is not None
               else None, transport=transport, scrape=_healthy_scrape,
               sleep=lambda s: None, **kwargs)
    r.scrape_once()
    return r


def test_router_mints_propagates_and_echoes():
    seen_headers = []

    def transport(url, task, payload, timeout_s, headers=None):
        seen_headers.append(dict(headers or {}))
        return 200, {"ok": True}

    events = []
    r = _router(transport, events=events, trace_sample_rate=1.0)
    status, _, headers = r.handle("classify", {"text": "hi"})
    assert status == 200
    # Satellite 2: the response echoes the trace id on EVERY request.
    tid = headers[TRACE_ID_RESPONSE_HEADER]
    assert tid.startswith("rt-") and len(tid.split("-")) == 3
    # The attempt carried the full context on the wire.
    ctx = parse_trace_header(seen_headers[0][TRACE_HEADER])
    assert ctx == {"trace_id": tid, "attempt": 1, "sampled": True}
    # Rate 1.0: exactly one schema-clean router_trace for the request.
    traces = [e for e in events if e["kind"] == "router_trace"]
    assert len(traces) == 1
    t = traces[0]
    assert _valid(t) == []
    assert t["trace_id"] == tid and t["sampled"] is True
    assert t["attempts"] == 1 and t["winning_attempt"] == 1
    names = [s["name"] for s in t["spans"]]
    assert names == ["admission", "attempt"]
    att = t["spans"][1]
    assert att["replica"] == "http://a:1" and att["outcome"] == "final"
    assert att["status"] == 200 and att["hedge"] is False

    # Rate 0: NOT sampled — the header still rides (sampled=0, so the
    # replica's local head hash is overridden OFF fleet-wide) and the
    # echo still lands, but no router_trace is emitted.
    seen_headers.clear()
    events2 = []
    r0 = _router(transport, events=events2, trace_sample_rate=0.0)
    status, _, headers = r0.handle("classify", {"text": "hi"})
    assert status == 200 and TRACE_ID_RESPONSE_HEADER in headers
    ctx = parse_trace_header(seen_headers[0][TRACE_HEADER])
    assert ctx["sampled"] is False
    assert not [e for e in events2 if e["kind"] == "router_trace"]

    # Minting is deterministic per sequence: a fresh router at rate 0.5
    # makes the same decisions for the same sequence numbers (replayed
    # bursts sample the same requests).
    a = [Router(["http://a:1"], trace_sample_rate=0.5)._mint_trace()[1]
         for _ in range(1)] + \
        [Router(["http://a:1"], trace_sample_rate=0.5)._mint_trace()[1]]
    assert a[0] == a[1] == (router_mod._sample_hash(0) < 0.5)


def test_router_legacy_4arg_transport_still_works():
    """PR-11 test transports take (url, task, payload, timeout_s);
    tracing must degrade to not-forwarded, never to a TypeError."""
    calls = []

    def transport(url, task, payload, timeout_s):
        calls.append(url)
        return 200, {"ok": True}

    events = []
    r = _router(transport, events=events, trace_sample_rate=1.0)
    status, _, headers = r.handle("classify", {"text": "hi"})
    assert status == 200 and calls == ["http://a:1"]
    assert TRACE_ID_RESPONSE_HEADER in headers
    # The router-side trace is still whole; only the wire hop is lost.
    (t,) = [e for e in events if e["kind"] == "router_trace"]
    assert _valid(t) == [] and t["attempts"] == 1


def test_router_failover_attempt_spans():
    """A SIGKILL-shaped failover in miniature: attempt 1 dies in
    transport, the backoff wait is its own span, attempt 2 wins on the
    other replica — the exact tree the chaos acceptance asserts on."""
    def transport(url, task, payload, timeout_s, headers=None):
        if url == "http://a:1":
            raise ConnectionRefusedError("replica a is dead")
        return 200, {"ok": True}

    events = []
    r = _router(transport, events=events, trace_sample_rate=1.0)
    status, _, _ = r.handle("classify", {"text": "hi"})
    assert status == 200
    (t,) = [e for e in events if e["kind"] == "router_trace"]
    assert _valid(t) == []
    assert t["attempts"] == 2 and t["winning_attempt"] == 2
    atts = [s for s in t["spans"] if s["name"] == "attempt"]
    assert [a["attempt"] for a in atts] == [1, 2]
    assert atts[0]["replica"] == "http://a:1"
    assert atts[0]["outcome"] == "transport_error"
    assert "status" not in atts[0]      # it never answered
    assert atts[1]["replica"] == "http://b:2"
    assert atts[1]["outcome"] == "final" and atts[1]["status"] == 200
    # The retry wait is visible, not folded into overhead anonymously.
    assert "backoff" in [s["name"] for s in t["spans"]]
    # Two admissions (one per round) bracket the attempts.
    assert [s["name"] for s in t["spans"]].count("admission") == 2


def test_router_hedge_waste_accounting():
    """Satellite 1: a hedged race's losing attempt is wasted work —
    summed into the trace AND the window in the same _observe lock
    acquisition as hedge_wins, so a window flush can never land between
    the two and emit waste with no hedge (the schema forbids it)."""
    slow_started = threading.Event()
    release_slow = threading.Event()

    def transport(url, task, payload, timeout_s, headers=None):
        if url == "http://a:1":
            slow_started.set()
            release_slow.wait(timeout=10.0)
            return 200, {"who": "slow"}
        return 200, {"who": "hedge"}

    events = []
    r = _router(transport, events=events, trace_sample_rate=1.0,
                hedge_pctl=0.5, hedge_min_ms=1.0, hedge_min_samples=4)
    for _ in range(8):                  # seed the latency history
        r.note_latency(0.002)
    try:
        status, body, _ = r.handle("classify", {"text": "hi"})
    finally:
        release_slow.set()
    assert status == 200 and body == {"who": "hedge"}
    (t,) = [e for e in events if e["kind"] == "router_trace"]
    assert _valid(t) == []
    assert t["hedges"] == 1 and t["hedge_won"] is True
    assert t["hedge_wasted_ms"] > 0.0
    atts = {a["replica"]: a for a in t["spans"]
            if a["name"] == "attempt"}
    assert atts["http://a:1"]["outcome"] == "lost"
    assert atts["http://b:2"]["hedge"] is True
    assert atts["http://b:2"]["outcome"] == "final"
    # Loser measured at the decision instant: the waste is what the
    # race cost, not the latency nobody waited for.
    assert t["hedge_wasted_ms"] == pytest.approx(
        atts["http://a:1"]["dur_ms"], abs=0.01)
    win = r.flush_window()
    assert _valid(win) == []
    assert win["hedges"] == 1 and win["hedge_wins"] == 1
    assert win["hedge_wasted_ms"] == pytest.approx(
        t["hedge_wasted_ms"], abs=0.5)
    assert "bert_router_hedge_wasted_ms_total" in r.metrics_text()


# ---------------------------------------------------------------------------
# replica tier: the router's sampling decision wins both ways


def _phases():
    return {"queue": 0.002, "assembly": 0.001, "execute": 0.010,
            "postprocess": 0.001}


def test_tracer_adopts_router_decision_both_ways():
    # Local rate 0, router says SAMPLED: traced, chained to the parent.
    records = []
    tc = TraceCollector(emit=records.append, sample_rate=0.0, window=64)
    rec = tc.observe("classify", 1, _phases(), total_s=0.02,
                     trace_ctx={"trace_id": "rt-x-1", "attempt": 2,
                                "sampled": True})
    assert rec is not None and _valid(rec) == []
    assert rec["parent_trace_id"] == "rt-x-1" and rec["attempt"] == 2
    assert rec["sampled"] is True and rec["sample_reason"] == "head"
    # Local rate 1.0, router says NOT sampled: the router wins that way
    # too — one fleet-wide coin, not two.
    tc2 = TraceCollector(emit=records.append, sample_rate=1.0, window=64)
    assert tc2.observe("classify", 1, _phases(), total_s=0.02,
                       trace_ctx={"trace_id": "rt-x-2", "attempt": 1,
                                  "sampled": False}) is None
    # ...except the always-sample-slow rule, which is LOCAL: an over-SLO
    # request is exported regardless, marked sampled=false (so the
    # stitcher knows it has no router counterpart) but still chained.
    tc3 = TraceCollector(emit=records.append, sample_rate=0.0,
                         slo_p99_ms=5.0, window=64)
    slow = tc3.observe("classify", 1, _phases(), total_s=0.5,
                       trace_ctx={"trace_id": "rt-x-3", "attempt": 1,
                                  "sampled": False})
    assert slow is not None and _valid(slow) == []
    assert slow["sampled"] is False and slow["sample_reason"] == "slow"
    assert slow["parent_trace_id"] == "rt-x-3"


# ---------------------------------------------------------------------------
# schema: the router_trace / trace_stitch rules


def test_schema_rules_for_router_trace_and_stitch():
    good = {"kind": "router_trace", "tag": "router", "trace_id": "rt-1",
            "task": "classify", "status": 200, "total_ms": 10.0,
            "sampled": True, "attempts": 2, "hedges": 1,
            "hedge_wasted_ms": 4.0, "winning_attempt": 2, "spans": [
                {"name": "admission", "start_ms": 0.0, "dur_ms": 0.1},
                # OVERLAPPING attempts: legal (a hedged race) — only the
                # per-span sub-interval bound applies, not the
                # serve_trace additive-sum rule.
                {"name": "attempt", "start_ms": 0.2, "dur_ms": 9.0,
                 "attempt": 1, "replica": "http://a:1",
                 "outcome": "lost"},
                {"name": "attempt", "start_ms": 4.0, "dur_ms": 5.5,
                 "attempt": 2, "replica": "http://b:2",
                 "outcome": "final"}]}
    assert _valid(good) == []

    def err(**over):
        return " | ".join(_valid(dict(good, **over)))

    assert "spans[1].name must be one of" in err(spans=[
        good["spans"][0], dict(good["spans"][1], name="retry"),
        good["spans"][2]], attempts=1)
    assert "must equal the number of attempt spans" in err(attempts=3)
    assert "ends past total_ms" in err(total_ms=5.0)
    assert "winning_attempt (9) exceeds attempts" in err(winning_attempt=9)
    assert "must be a non-negative number" in err(hedge_wasted_ms=-1.0)

    # Satellite 1's window rule: waste with no hedge fired means the
    # counters were folded in different lock acquisitions (the PR-11
    # race all over again) — the schema rejects the record outright.
    window = {"kind": "router_window", "tag": "router",
              "window_requests": 8, "ok": 8, "sheds": 0, "errors": 0,
              "retries": 0, "hedges": 1, "hedge_wins": 1,
              "hedge_wasted_ms": 3.0, "failovers": 0,
              "healthy_replicas": 2, "replicas": 2}
    assert _valid(window) == []
    assert any("positive with zero hedges" in e
               for e in _valid(dict(window, hedges=0, hedge_wins=0)))

    stitch = {"kind": "trace_stitch", "tag": "obs", "trace_id": "rt-1",
              "orphan": False, "router_spans": 3, "replica_spans": 1,
              "status": 200, "client_total_ms": 10.0,
              "router_overhead_ms": 4.5, "network_gap_ms": 0.5,
              "replica_ms": 5.0, "consistent": True,
              "winning_attempt": 2}
    assert _valid(stitch) == []

    def serr(**over):
        return " | ".join(_valid(dict(stitch, **over)))

    assert "must be marked orphan" in serr(router_spans=0)
    assert "decomposition must sum" in serr(replica_ms=9.0)
    assert "non-negative network_gap_ms" in serr(
        network_gap_ms=-5.0, router_overhead_ms=10.0)
    # Orphans carry no decomposition and that is fine.
    assert _valid({"kind": "trace_stitch", "tag": "obs",
                   "trace_id": "rt-2", "orphan": True,
                   "orphan_side": "router", "router_spans": 0,
                   "replica_spans": 1}) == []


def test_trace_stitch_fixtures_lint():
    good = os.path.join(HERE, "fixtures", "telemetry",
                        "trace_stitch_good.jsonl")
    bad = os.path.join(HERE, "fixtures", "telemetry",
                       "trace_stitch_bad.jsonl")
    assert validate_file(good) == []
    errors = validate_file(bad)
    assert len(errors) == 9             # one named violation per line
    text = " | ".join(err for _, err in errors)
    assert "spans[0].name must be one of" in text
    assert "attempts (2) must equal the number of attempt spans" in text
    assert "ends past total_ms" in text
    assert "winning_attempt (3) exceeds attempts" in text
    assert "hedge_wasted_ms (3.0) positive with zero hedges" in text
    assert "must be marked orphan" in text
    assert "decomposition must sum to client_total_ms" in text
    assert "non-negative network_gap_ms" in text
    assert "'attempt' must be a positive integer" in text
    # And the repo tool agrees end to end (jax-free file-path load).
    proc = subprocess.run(
        [sys.executable, "tools/check_telemetry_schema.py", good, bad],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "trace_stitch_good.jsonl: ok" in proc.stdout
    assert proc.stdout.count("trace_stitch_bad.jsonl:") == 9


# ---------------------------------------------------------------------------
# the stitcher (telemetry/collector.py)


def _router_trace(tid, status=200, total=18.4, winning=2):
    spans = [
        {"name": "admission", "start_ms": 0.0, "dur_ms": 0.2},
        {"name": "attempt", "start_ms": 0.3, "dur_ms": 2.1, "attempt": 1,
         "replica": "http://a:1", "outcome": "transport_error",
         "hedge": False},
        {"name": "backoff", "start_ms": 2.5, "dur_ms": 1.0},
        {"name": "attempt", "start_ms": 3.6, "dur_ms": 14.6, "attempt": 2,
         "replica": "http://b:2", "outcome": "final", "hedge": False,
         "status": 200}]
    rec = {"schema": 1, "ts": 100.0, "kind": "router_trace",
           "tag": "router", "trace_id": tid, "task": "classify",
           "status": status, "total_ms": total, "sampled": True,
           "attempts": 2, "hedges": 0, "hedge_wasted_ms": 0.0,
           "spans": spans}
    if winning is not None:
        rec["winning_attempt"] = winning
    return rec


def _serve_trace(parent, attempt=2, total=12.8, sampled=True,
                 tid="beefcafe-1"):
    return {"schema": 1, "ts": 100.1, "kind": "serve_trace",
            "tag": "serve", "trace_id": tid, "task": "classify",
            "total_ms": total, "queue_wait_ms": 2.0, "sampled": sampled,
            "sample_reason": "head" if sampled else "slow",
            "parent_trace_id": parent, "attempt": attempt,
            "spans": [
                {"name": "queue", "start_ms": 0.0, "dur_ms": 2.0},
                {"name": "assembly", "start_ms": 2.0, "dur_ms": 1.5},
                {"name": "execute", "start_ms": 3.5, "dur_ms": 8.0},
                {"name": "postprocess", "start_ms": 11.5, "dur_ms": 1.3}]}


class _Sink:
    """A JSONL file the collector tails, appendable between passes."""

    def __init__(self, tmp_path, name):
        self.path = str(tmp_path / f"{name}.jsonl")
        open(self.path, "w").close()
        self.tailer = JsonlTailer(self.path, name)

    def append(self, rec):
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")


def test_stitcher_complete_orphan_grace_and_slow_exclusion(tmp_path):
    router_sink = _Sink(tmp_path, "router")
    replica_sink = _Sink(tmp_path, "replica-1")
    timeline = []
    coll = FleetCollector([], tails=[router_sink.tailer,
                                     replica_sink.tailer],
                          emit=timeline.append)

    def stitches():
        return [r for r in timeline if r["kind"] == "trace_stitch"]

    # -- complete: both halves land in the same pass -> joined at once.
    router_sink.append(_router_trace("rt-ok-1"))
    replica_sink.append(_serve_trace("rt-ok-1"))
    coll.collect_once()
    (s,) = stitches()
    assert _valid(s) == []
    assert s["trace_id"] == "rt-ok-1" and s["orphan"] is False
    # Decomposition sums EXACTLY at record precision (the gap is the
    # residual), and the winning join carries provenance.
    assert s["router_overhead_ms"] + s["network_gap_ms"] \
        + s["replica_ms"] == pytest.approx(s["client_total_ms"], abs=1e-9)
    assert s["router_overhead_ms"] == pytest.approx(18.4 - 14.6)
    assert s["consistent"] is True and s["winning_attempt"] == 2
    assert s["winning_trace_id"] == "beefcafe-1"
    assert s["winning_source"] == "replica-1"
    assert s["replica_critical_phase"] == "execute"

    # -- a replica span with NO router parent ages through the grace,
    # then orphans (router side missing). A slow-forced record
    # (sampled=false) never enters at all.
    replica_sink.append(_serve_trace("rt-gone-1", tid="beefcafe-2"))
    replica_sink.append(_serve_trace("rt-slow-1", sampled=False,
                                     tid="beefcafe-3"))
    coll.collect_once()
    assert len(stitches()) == 1          # inside the grace: pending
    for _ in range(STITCH_GRACE_PASSES):
        coll.collect_once()
    orphans = [s for s in stitches() if s.get("orphan")]
    (o,) = orphans
    assert _valid(o) == []
    assert o["trace_id"] == "rt-gone-1" and o["orphan_side"] == "router"
    assert o["replica_spans"] == 1 and o["router_spans"] == 0
    assert not any(s["trace_id"] == "rt-slow-1" for s in stitches())

    # -- a router non-2xx is a complete singleton immediately (no
    # replica span is ever expected for a shed/deadline answer).
    router_sink.append(_router_trace("rt-shed-1", status=503,
                                     winning=None))
    coll.collect_once()
    (shed,) = [s for s in stitches() if s["trace_id"] == "rt-shed-1"]
    assert _valid(shed) == []
    assert shed["orphan"] is False and shed["replica_spans"] == 0
    assert "router_overhead_ms" not in shed

    # -- a router 2xx whose winning serve_trace never shows up is
    # force-drained as a REPLICA-side orphan at close, not dropped.
    router_sink.append(_router_trace("rt-lost-1"))
    coll.collect_once()
    coll.close()
    (lost,) = [s for s in stitches() if s["trace_id"] == "rt-lost-1"]
    assert _valid(lost) == []
    assert lost["orphan"] is True and lost["orphan_side"] == "replica"
    # Close is idempotent about the drain: nothing doubles.
    coll.close()
    assert len([s for s in stitches()
                if s["trace_id"] == "rt-lost-1"]) == 1


def test_stitch_tree_rendering():
    records = [_router_trace("rt-tree-1"),
               dict(_serve_trace("rt-tree-1"), obs_source="replica-1"),
               {"kind": "trace_stitch", "trace_id": "rt-tree-1",
                "orphan": False, "router_spans": 4, "replica_spans": 1,
                "client_total_ms": 18.4, "router_overhead_ms": 3.8,
                "network_gap_ms": 1.8, "replica_ms": 12.8,
                "consistent": True, "replica_critical_phase": "execute"}]
    tree = stitch_tree(records, "rt-tree-1")
    assert "trace rt-tree-1" in tree
    assert "outcome=transport_error" in tree
    assert "#2 -> http://b:2" in tree and "[win]" in tree
    # The winning replica's phases nest under its attempt with source
    # attribution.
    assert "serve_trace beefcafe-1 (replica-1)" in tree
    assert "execute" in tree
    assert "stitch: overhead=3.8ms" in tree
    assert "consistent=True" in tree
    # Orphan rendering names the missing side.
    orphan_tree = stitch_tree(
        [dict(_serve_trace("rt-tree-2"), obs_source="replica-1"),
         {"kind": "trace_stitch", "trace_id": "rt-tree-2",
          "orphan": True, "orphan_side": "router", "router_spans": 0,
          "replica_spans": 1}], "rt-tree-2")
    assert "no router_trace span — orphan" in orphan_tree
    assert "ORPHAN (router side missing)" in orphan_tree
    assert "not found in timeline" in stitch_tree(records, "rt-nope")


def test_obs_collect_trace_drilldown_subprocess(tmp_path):
    timeline = str(tmp_path / "fleet_timeline.jsonl")
    with open(timeline, "w") as f:
        f.write(json.dumps(_router_trace("rt-cli-1")) + "\n")
        f.write(json.dumps(dict(_serve_trace("rt-cli-1"),
                                obs_source="replica-1")) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "obs_collect.py"),
         "--trace", "rt-cli-1", "--out", timeline],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "trace rt-cli-1" in proc.stdout
    assert "http://b:2" in proc.stdout
    assert "stitch: (pending" in proc.stdout   # no stitch record yet
    missing = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "obs_collect.py"),
         "--trace", "rt-nope", "--out", timeline],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert missing.returncode == 1
    assert "not found in timeline" in missing.stdout


# ---------------------------------------------------------------------------
# telemetry-report: the trace section + the two named gates


def _stitch_rec(tid, total=20.0, overhead=2.0, gap=1.0, orphan=False):
    rec = {"schema": 1, "ts": 0.0, "kind": "trace_stitch", "tag": "obs",
           "trace_id": tid, "orphan": orphan, "router_spans": 2,
           "replica_spans": 0 if orphan else 1}
    if orphan:
        rec.update({"orphan_side": "replica", "router_spans": 2,
                    "status": 200, "client_total_ms": total})
    else:
        rec.update({"status": 200, "client_total_ms": total,
                    "router_overhead_ms": overhead, "network_gap_ms": gap,
                    "replica_ms": round(total - overhead - gap, 3),
                    "consistent": True, "winning_attempt": 1,
                    "replica_critical_phase": "execute"})
    return rec


def test_report_trace_section_aggregates_shares():
    recs = [_router_trace(f"rt-{i}") for i in range(4)]
    # Aggregate-ratio property: a tiny request with a huge overhead
    # SHARE must not dominate — the share is sum/sum, not mean-of-ratios.
    recs += [_stitch_rec("rt-0", total=100.0, overhead=5.0, gap=5.0),
             _stitch_rec("rt-1", total=1.0, overhead=0.9, gap=0.05),
             _stitch_rec("rt-2", total=99.0, overhead=4.1, gap=4.95),
             _stitch_rec("rt-3", orphan=True)]
    summary = report.summarize_records(recs, name="t")
    assert summary["router_traces"] == 4
    assert summary["trace_stitches"] == 4
    assert summary["trace_orphans"] == 1
    assert summary["trace_orphan_share"] == pytest.approx(0.25)
    assert summary["trace_router_overhead_share"] == pytest.approx(
        10.0 / 200.0)
    assert summary["trace_replica_share"] == pytest.approx(0.9)
    assert "trace_critical_path" in summary
    text = report.format_summary(summary)
    assert "trace_router_overhead_share" in text
    assert "dominant tier, slowest decile" in text


def test_trace_gates_trip_by_name():
    base = report.summarize_records(
        [_stitch_rec(f"rt-{i}", total=20.0, overhead=1.0, gap=0.5)
         for i in range(8)])
    # Gate 1 ("router overhead share", ratio check): time moving INTO
    # the routing tier trips it even when replicas got no slower.
    bloated = report.summarize_records(
        [_stitch_rec(f"rt-{i}", total=30.0, overhead=11.0, gap=0.5)
         for i in range(8)])
    regressions, _ = report.compare(base, bloated)
    assert "router overhead share" in [r["label"] for r in regressions]
    # Gate 2 ("orphan span share", zero-tolerance): a clean baseline has
    # ZERO orphans (the ratio path would n/a it) — ONE new orphan fires.
    with_orphan = report.summarize_records(
        [_stitch_rec(f"rt-{i}", total=20.0, overhead=1.0, gap=0.5)
         for i in range(7)] + [_stitch_rec("rt-7", orphan=True)])
    regressions, _ = report.compare(base, with_orphan)
    assert "orphan span share" in [r["label"] for r in regressions]
    # Self-compare stays green.
    regressions, _ = report.compare(base, base)
    assert regressions == []
