"""fp16 + dynamic loss scaling — the reference-parity AMP mode.

SURVEY.md §2.3 planned "keep optional fp16+scaler for parity testing"
(reference GradScaler at run_pretraining.py:314-318, its state in
checkpoints at :519-523). bf16 stays the TPU default; these tests pin the
GradScaler-equivalent semantics: scaled-gradient unscaling, skip+backoff
on inf/nan, growth after an interval, checkpointable wrapper state, and
phase-surgery compatibility.
"""

import json

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bert_pytorch_tpu import optim
from bert_pytorch_tpu.tools.make_synthetic_data import make_shard

VOCAB = 128


@pytest.fixture()
def workdir(tmp_path):
    data_dir = tmp_path / "data"
    out_dir = tmp_path / "out"
    data_dir.mkdir()
    for i in range(2):
        make_shard(str(data_dir / f"shard_{i}.hdf5"), 64, 32, VOCAB, seed=i)
    model_config = {
        "vocab_size": VOCAB, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 32, "type_vocab_size": 2,
        "next_sentence": True, "mask_token_id": 4,
    }
    config_path = tmp_path / "model.json"
    config_path.write_text(json.dumps(model_config))
    return {"data": str(data_dir), "out": str(out_dir),
            "model": str(config_path)}


def _argv(workdir, *extra):
    return [
        "--input_dir", workdir["data"],
        "--output_dir", workdir["out"],
        "--model_config_file", workdir["model"],
        "--global_batch_size", "32",
        "--local_batch_size", "2",
        "--max_steps", "8",
        "--steps", "3",
        "--learning_rate", "1e-3",
        "--warmup_proportion", "0.25",
        "--num_steps_per_checkpoint", "100",
        "--dtype", "float16",
        "--seed", "7",
        *extra,
    ]


def _tree(x):
    return {"a": jnp.asarray([x, 2.0 * x]), "b": {"c": jnp.asarray([3.0 * x])}}


class TestDynamicLossScale:
    def _tx(self, **kw):
        return optim.dynamic_loss_scale(optax.sgd(0.1), **kw)

    def test_finite_step_matches_inner_on_unscaled_grads(self):
        tx = self._tx(init_scale=1024.0)
        params = _tree(1.0)
        state = tx.init(params)
        grads = _tree(0.5)
        scaled = jax.tree_util.tree_map(lambda g: g * state.scale, grads)
        updates, new_state = tx.update(scaled, state, params)
        ref = optax.sgd(0.1)
        ref_updates, _ = ref.update(grads, ref.init(params), params)
        for u, r in zip(jax.tree_util.tree_leaves(updates),
                        jax.tree_util.tree_leaves(ref_updates)):
            np.testing.assert_allclose(u, r, rtol=1e-6)
        assert float(new_state.scale) == 1024.0
        assert int(new_state.growth_count) == 1

    def test_nonfinite_skips_and_backs_off(self):
        tx = optim.dynamic_loss_scale(
            optim.lamb(1e-2), init_scale=2.0 ** 10)
        params = _tree(1.0)
        state = tx.init(params)
        bad = _tree(1.0)
        bad["b"]["c"] = jnp.asarray([jnp.inf])
        updates, new_state = tx.update(bad, state, params)
        for u in jax.tree_util.tree_leaves(updates):
            np.testing.assert_array_equal(u, np.zeros_like(u))
        # inner optimizer state untouched: count not incremented
        assert int(new_state.inner.count) == int(state.inner.count)
        assert float(new_state.scale) == 2.0 ** 9
        assert int(new_state.growth_count) == 0

    def test_growth_after_interval(self):
        tx = self._tx(init_scale=8.0, growth_interval=3)
        params = _tree(1.0)
        state = tx.init(params)
        for i in range(3):
            scaled = jax.tree_util.tree_map(
                lambda g: g * state.scale, _tree(0.1))
            _, state = tx.update(scaled, state, params)
        assert float(state.scale) == 16.0
        assert int(state.growth_count) == 0  # reset on growth

    def test_reset_count_keeps_scale(self):
        tx = optim.dynamic_loss_scale(optim.lamb(1e-2), init_scale=4096.0)
        state = tx.init(_tree(1.0))
        _, state = tx.update(_tree(1.0), state, _tree(1.0))
        reset = optim.reset_count(state, 17)
        assert int(reset.inner.count) == 17
        assert float(reset.scale) == float(state.scale)

    def test_opt_step_count_both_layouts(self):
        plain = optim.lamb(1e-2).init(_tree(1.0))
        wrapped = optim.dynamic_loss_scale(optim.lamb(1e-2)).init(_tree(1.0))
        assert int(optim.opt_step_count(plain)) == 0
        assert int(optim.opt_step_count(wrapped)) == 0


class TestTrainStepFp16:
    def _setup(self, loss_scale, dtype=jnp.float16, init_scale=2.0 ** 12):
        from bert_pytorch_tpu import pretrain
        from bert_pytorch_tpu.config import BertConfig
        from bert_pytorch_tpu.models import BertForPreTraining

        config = BertConfig(
            vocab_size=256, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=32, next_sentence=True,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
        model = BertForPreTraining(config, dtype=dtype)
        tx = optim.lamb(1e-3)
        if loss_scale:
            tx = optim.dynamic_loss_scale(tx, init_scale=init_scale)
        rng = np.random.default_rng(0)
        b, s = 4, 32
        host = {
            "input_ids": rng.integers(0, 256, (b, s)).astype(np.int32),
            "segment_ids": rng.integers(0, 2, (b, s)).astype(np.int32),
            "input_mask": np.ones((b, s), np.int32),
            "masked_lm_labels": np.where(
                rng.random((b, s)) < 0.15,
                rng.integers(0, 256, (b, s)), -1).astype(np.int32),
            "next_sentence_labels": rng.integers(0, 2, (b,)).astype(np.int32),
        }
        sample = (jnp.zeros((1, s), jnp.int32),) * 3
        params = nn.unbox(
            model.init(jax.random.PRNGKey(0), *sample))["params"]
        state = pretrain.TrainState(
            params=params, opt_state=tx.init(params),
            rng=jax.random.PRNGKey(1))
        step = pretrain.make_train_step(model, tx, loss_scale=loss_scale)
        batch = pretrain.stack_microbatches(host, 2)
        return step, state, batch

    def test_fp16_step_runs_and_reports_scale(self):
        step, state, batch = self._setup(loss_scale=True)
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["loss_scale"]) == 2.0 ** 12
        assert np.isfinite(float(metrics["grad_norm"]))
        state, metrics = step(state, batch)
        assert int(optim.opt_step_count(state.opt_state)) == 2

    def test_scaling_is_transparent_in_f32(self):
        # Same model/dtype (f32), with and without the scaler: identical
        # parameters after a step — scaling must be numerically neutral
        # when nothing overflows.
        step_a, state_a, batch = self._setup(loss_scale=False,
                                             dtype=jnp.float32)
        step_b, state_b, _ = self._setup(loss_scale=True, dtype=jnp.float32)
        state_a, ma = step_a(state_a, batch)
        state_b, mb = step_b(state_b, batch)
        np.testing.assert_allclose(
            float(ma["loss"]), float(mb["loss"]), rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(state_a.params),
                        jax.tree_util.tree_leaves(state_b.params)):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-7)

    def test_fp16_overflow_skips_then_recovers(self):
        # A loss scale far beyond fp16 range overflows the backward pass;
        # the step must be skipped (count stays 0) with the scale halved,
        # not produce NaN parameters.
        step, state, batch = self._setup(loss_scale=True, init_scale=2.0 ** 60)
        before = jax.tree_util.tree_map(np.asarray, state.params)
        state, metrics = step(state, batch)
        assert int(optim.opt_step_count(state.opt_state)) == 0
        assert float(state.opt_state.scale) == 2.0 ** 59
        for b, a in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(b, np.asarray(a))


class TestRunnerFp16:
    @pytest.mark.slow
    def test_runner_fp16_smoke_checkpoint_roundtrip(self, workdir):
        """Slow-gated (~46s: two full runner invocations): the fp16 step
        math is tier-1-covered by TestTrainStepFp16 and checkpoint
        resume by tests/test_checkpoint.py; this E2E proves the runner
        WIRING (scaler state riding in 'optimizer' across a resume) and
        runs under ``-m slow``."""
        import run_pretraining

        result = run_pretraining.main(
            run_pretraining.parse_arguments(_argv(workdir)))
        assert result["global_step"] == 3
        assert np.isfinite(result["loss"])
        # resume from the checkpoint (scaler state rides in 'optimizer'):
        # 5 more steps on top of the 3 already run
        result = run_pretraining.main(run_pretraining.parse_arguments(
            _argv(workdir, "--steps", "5")))
        assert result["global_step"] == 8

    def test_runner_rejects_fp16_with_kfac(self, workdir):
        import run_pretraining

        with pytest.raises(ValueError, match="float16"):
            run_pretraining.main(run_pretraining.parse_arguments(
                _argv(workdir, "--kfac")))
