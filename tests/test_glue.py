"""GLUE processors, featurization, metrics, and a tiny end-to-end finetune."""

import json

import numpy as np
import pytest

VOCAB_TOKENS = (
    ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    + ["the", "movie", "was", "great", "terrible", "a", "film", "good",
       "bad", "very", "it", "is", "same", "different", "paris", "london"]
)


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    path.write_text("\n".join(VOCAB_TOKENS) + "\n")
    return str(path)


@pytest.fixture(scope="module")
def tokenizer(vocab_file):
    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer

    return get_wordpiece_tokenizer(vocab_file)


def _write_tsv(path, rows, header=None):
    lines = (["\t".join(header)] if header else []) + [
        "\t".join(str(c) for c in row) for row in rows
    ]
    path.write_text("\n".join(lines) + "\n")


@pytest.fixture(scope="module")
def sst2_dir(tmp_path_factory):
    """SST-2-shaped data where sentiment is decidable from one word."""
    d = tmp_path_factory.mktemp("SST-2")
    rows = []
    for i in range(24):
        good = i % 2 == 0
        text = f"the movie was {'great' if good else 'terrible'}"
        rows.append((text, int(good)))
    _write_tsv(d / "train.tsv", rows, header=("sentence", "label"))
    _write_tsv(d / "dev.tsv", rows[:8], header=("sentence", "label"))
    return str(d)


@pytest.fixture(scope="module")
def mrpc_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("MRPC")
    header = ("Quality", "#1 ID", "#2 ID", "#1 String", "#2 String")
    rows = [
        (1, i, i, "the movie was great", "the film was good")
        if i % 2 == 0
        else (0, i, i, "the movie was great", "paris is different")
        for i in range(12)
    ]
    _write_tsv(d / "train.tsv", rows, header=header)
    _write_tsv(d / "dev.tsv", rows[:6], header=header)
    return str(d)


@pytest.fixture(scope="module")
def stsb_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("STS-B")
    header = tuple(f"c{i}" for i in range(7)) + ("sentence1", "sentence2", "score")
    rows = [
        ("x",) * 7 + ("the movie was great", "the film was good", "4.2")
        if i % 2 == 0
        else ("x",) * 7 + ("the movie was great", "paris is different", "0.5")
        for i in range(12)
    ]
    _write_tsv(d / "train.tsv", rows, header=header)
    _write_tsv(d / "dev.tsv", rows[:6], header=header)
    return str(d)


def test_sst2_processor_reads_rows(sst2_dir):
    from bert_pytorch_tpu.data import glue

    proc = glue.PROCESSORS["sst-2"]()
    train = proc.get_train_examples(sst2_dir)
    dev = proc.get_dev_examples(sst2_dir)
    assert len(train) == 24 and len(dev) == 8
    assert train[0].text_a == "the movie was great"
    assert train[0].text_b is None
    assert train[0].label == "1" and train[1].label == "0"


def test_mrpc_processor_pairs(mrpc_dir):
    from bert_pytorch_tpu.data import glue

    ex = glue.PROCESSORS["mrpc"]().get_train_examples(mrpc_dir)[0]
    assert ex.text_a == "the movie was great"
    assert ex.text_b == "the film was good"
    assert ex.label == "1"


def test_stsb_processor_regression(stsb_dir):
    from bert_pytorch_tpu.data import glue

    proc = glue.PROCESSORS["sts-b"]()
    assert proc.regression
    ex = proc.get_train_examples(stsb_dir)[0]
    assert float(ex.label) == pytest.approx(4.2)


def test_features_pair_layout(mrpc_dir, tokenizer):
    from bert_pytorch_tpu.data import glue

    proc = glue.PROCESSORS["mrpc"]()
    examples = proc.get_train_examples(mrpc_dir)
    feats = glue.convert_examples_to_features(
        examples, tokenizer, 16, proc.labels)
    f = feats[0]
    cls_id = tokenizer.token_to_id("[CLS]")
    sep_id = tokenizer.token_to_id("[SEP]")
    assert f.input_ids[0] == cls_id
    sep_positions = np.flatnonzero(f.input_ids == sep_id)
    assert len(sep_positions) == 2
    # segment 0 through the first [SEP], segment 1 for the b side
    assert f.segment_ids[sep_positions[0]] == 0
    assert f.segment_ids[sep_positions[0] + 1] == 1
    assert f.segment_ids[sep_positions[1]] == 1
    # padding after the second [SEP]
    assert f.input_mask[sep_positions[1]] == 1
    assert np.all(f.input_ids[len(np.flatnonzero(f.input_mask)):] == 0)


def test_truncate_pair_budget(tokenizer):
    from bert_pytorch_tpu.data import glue

    examples = [glue.InputExample(
        "t-0", " ".join(["movie"] * 30), " ".join(["film"] * 3), "0")]
    feats = glue.convert_examples_to_features(examples, tokenizer, 16, ("0", "1"))
    # longest-first truncation keeps the short b side intact
    ids = feats[0].input_ids[feats[0].input_mask.astype(bool)]
    film = tokenizer.token_to_id("film")
    assert int(np.sum(ids == film)) == 3
    assert len(ids) == 16


def test_metrics_matthews_and_correlation():
    from bert_pytorch_tpu.data import glue

    preds = np.array([1, 1, 0, 0])
    labels = np.array([1, 1, 0, 0])
    assert glue.matthews(preds, labels)["matthews"] == pytest.approx(1.0)
    assert glue.matthews(1 - preds, labels)["matthews"] == pytest.approx(-1.0)

    x = np.array([1.0, 2.0, 3.0, 4.0])
    m = glue.pearson_and_spearman(x, 2 * x + 1)
    assert m["pearson"] == pytest.approx(1.0)
    assert m["spearman"] == pytest.approx(1.0)
    m = glue.pearson_and_spearman(x, np.array([1.0, 4.0, 9.0, 16.0]))
    assert m["spearman"] == pytest.approx(1.0)  # monotone, nonlinear
    assert m["pearson"] < 1.0

    m = glue.acc_and_f1(np.array([1, 0, 1, 0]), np.array([1, 1, 1, 0]))
    assert m["accuracy"] == pytest.approx(0.75)
    assert m["f1"] == pytest.approx(0.8)


def _model_config(tmp_path, vocab_file):
    config = {
        "vocab_size": len(VOCAB_TOKENS), "hidden_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 64, "max_position_embeddings": 32,
        "type_vocab_size": 2, "next_sentence": True,
        "vocab_file": vocab_file, "tokenizer": "wordpiece",
    }
    path = tmp_path / "model.json"
    path.write_text(json.dumps(config))
    return str(path)


def test_glue_end_to_end_sst2(tmp_path, sst2_dir, vocab_file):
    import run_glue

    args = run_glue.parse_arguments([
        "--task", "sst-2", "--data_dir", sst2_dir,
        "--model_config_file", _model_config(tmp_path, vocab_file),
        "--output_dir", str(tmp_path / "out"),
        "--epochs", "10", "--batch_size", "8", "--max_seq_len", "16",
        "--lr", "3e-3", "--dtype", "float32",
    ])
    results = run_glue.main(args)
    # single-word sentiment on a 2-layer model must be learnable
    assert results["accuracy"] >= 0.75
    assert (tmp_path / "out" / "eval_results_sst-2.json").exists()


def test_glue_end_to_end_stsb_regression(tmp_path, stsb_dir, vocab_file):
    import run_glue

    args = run_glue.parse_arguments([
        "--task", "sts-b", "--data_dir", stsb_dir,
        "--model_config_file", _model_config(tmp_path, vocab_file),
        "--epochs", "4", "--batch_size", "4", "--max_seq_len", "16",
        "--lr", "1e-3", "--dtype", "float32",
    ])
    results = run_glue.main(args)
    assert "pearson" in results and np.isfinite(results["pearson"])


def test_glue_partial_batch_padding():
    from run_glue import batches

    arrays = {"labels": np.arange(10, dtype=np.int32),
              "input_ids": np.arange(10, dtype=np.int32)[:, None]}
    out = list(batches(arrays, 4, False, np.random.default_rng(0)))
    assert len(out) == 3
    last_batch, valid = out[-1]
    assert last_batch["labels"].shape == (4,)
    assert valid.tolist() == [True, True, False, False]


@pytest.mark.parametrize("task,header,row,expect", [
    ("cola", None,
     ["gj04", "1", "", "They drank the pub dry."],
     ("They drank the pub dry.", None, "1")),
    ("qqp", ["id", "qid1", "qid2", "question1", "question2", "is_duplicate"],
     ["1", "10", "11", "Is this a question?", "Is that a question?", "1"],
     ("Is this a question?", "Is that a question?", "1")),
    ("mnli", ["index"] + ["c"] * 7 + ["sentence1", "sentence2", "x",
                                     "gold_label"],
     ["0"] + ["?"] * 7 + ["A premise.", "A hypothesis.", "x", "entailment"],
     ("A premise.", "A hypothesis.", "entailment")),
    ("qnli", ["index", "question", "sentence", "label"],
     ["0", "What is it?", "It is a thing.", "entailment"],
     ("What is it?", "It is a thing.", "entailment")),
    ("rte", ["index", "sentence1", "sentence2", "label"],
     ["0", "A statement.", "Another statement.", "not_entailment"],
     ("A statement.", "Another statement.", "not_entailment")),
    ("wnli", ["index", "sentence1", "sentence2", "label"],
     ["0", "The trophy fits.", "It fits.", "1"],
     ("The trophy fits.", "It fits.", "1")),
])
def test_remaining_processors_column_layouts(tmp_path, task, header, row,
                                             expect):
    """Column-index regression net for the GLUE tasks without dedicated
    fixtures (the dumps' layouts are easy to silently mis-index)."""
    from bert_pytorch_tpu.data import glue

    d = tmp_path / task
    d.mkdir()
    _write_tsv(d / "train.tsv", [row, row], header=header)
    ex = glue.PROCESSORS[task]().get_train_examples(str(d))
    assert len(ex) == 2
    assert (ex[0].text_a, ex[0].text_b, ex[0].label) == expect
