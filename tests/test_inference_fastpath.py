"""Inference fast path tests (ISSUE 8; docs/serving.md "Inference fast
path"): weight quantization, the forward-only Pallas attention kernel,
and warm-in-seconds cold starts.

Covers, on CPU:

* the quantization rules (ops/quant.py): per-tensor symmetric int8 with
  per-layer scales for the encoder's scan stacks, bf16 storage, the
  EXCLUDE_MODULES downgrade, embeddings/LayerNorm untouched;
* the STREAMING quantized checkpoint load (utils/checkpoint.py): the
  per-leaf decode produces bit-identical trees to the host-side
  transform, casts to the target dtype with no quantization, and fails
  loudly on shape mismatches;
* per-task parity bounds quantized-vs-fp32 on all four served heads —
  the documented levels: |Δlogit| <= 2e-2 for bf16, <= 1e-1 for int8
  (tiny seeded config; real BERT-base measurements in docs/serving.md);
* packed == unpacked parity of ``flash_attention_infer`` in interpret
  mode, and model-level pallas_infer == xla parity;
* the warm cold-start acceptance: a SECOND engine start in a fresh
  process against the persisted AOT compile cache performs ZERO cold
  compiles, with the persistent-cache counter events (not wall clock)
  as the authority;
* the serve_cold_start schema kind and the telemetry-report gates on
  "serve p50 latency" / "serve cold start" / "serve cold compiles".
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bert_pytorch_tpu.config import BertConfig

BF16_LOGIT_ATOL = 2e-2
INT8_LOGIT_ATOL = 1e-1

NER_LABELS = ["O", "B-LOC", "B-PER"]
CLS_LABELS = ["neg", "pos"]
TASKS = {"fill_mask": {}, "classify": {"labels": CLS_LABELS},
         "squad": {}, "ner": {"labels": NER_LABELS}}
BUCKET = 16
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# fixtures


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    from bert_pytorch_tpu.tools.make_synthetic_data import write_trace_vocab

    d = tmp_path_factory.mktemp("fastpath_vocab")
    return write_trace_vocab(str(d / "vocab.txt"))


@pytest.fixture(scope="module")
def tokenizer(vocab_file):
    from bert_pytorch_tpu.data.tokenization import BertTokenizer

    return BertTokenizer(vocab_file, do_lower_case=True)


@pytest.fixture(scope="module")
def config():
    from bert_pytorch_tpu.tools.make_synthetic_data import TRACE_WORDS

    vocab = 5 + len(TRACE_WORDS)
    vocab += (8 - vocab % 8) % 8
    return BertConfig(
        vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2, next_sentence=True,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)


def _engine(config, tokenizer, quantize=None, **kw):
    import jax.numpy as jnp

    from bert_pytorch_tpu.serve import InferenceEngine

    eng = InferenceEngine(
        config, tokenizer, TASKS, buckets=(BUCKET,), max_batch_size=2,
        dtype=jnp.float32, seed=7, quantize=quantize, **kw)
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def engine_fp32(config, tokenizer):
    return _engine(config, tokenizer)


@pytest.fixture(scope="module")
def engine_int8(config, tokenizer):
    return _engine(config, tokenizer, quantize="int8")


@pytest.fixture(scope="module")
def engine_bf16(config, tokenizer):
    return _engine(config, tokenizer, quantize="bf16")


@pytest.fixture(scope="module")
def tiny_params(config):
    """A seeded fp32 params tree (the MLM head's — it exercises the
    encoder, pooler path, and tied decoder)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu import models

    model = models.BertForMaskedLM(config, dtype=jnp.float32)
    ids = jnp.zeros((1, BUCKET), jnp.int32)
    return model, nn.unbox(
        model.init(jax.random.PRNGKey(0), ids, ids, ids))["params"]


# ---------------------------------------------------------------------------
# ops/quant.py units


def test_quantize_array_roundtrip():
    from bert_pytorch_tpu.ops import quant

    rng = np.random.default_rng(0)
    w = rng.normal(size=(24, 48)).astype(np.float32)
    q, scale = quant.quantize_array(w)
    assert q.dtype == np.int8 and scale.shape == ()
    err = np.max(np.abs(quant.dequantize_array(q, scale) - w))
    # Round-to-nearest on a symmetric grid: error <= scale / 2.
    assert err <= float(scale) / 2 + 1e-9

    # Stacked (scan) mode: one scale per leading slice, so a quiet layer
    # is not forced onto a loud layer's grid.
    w2 = np.stack([w, 100.0 * w])
    q2, scale2 = quant.quantize_array(w2, per_axis0=True)
    assert scale2.shape == (2,)
    assert np.isclose(scale2[1], 100.0 * scale2[0], rtol=1e-5)
    np.testing.assert_array_equal(q2[0], q2[1])


def test_int8_matmul_error_bound():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops import quant

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 10, 32)).astype(np.float32))
    w = rng.normal(size=(32, 64)).astype(np.float32)
    q, scale = quant.quantize_array(w)
    ref = x @ jnp.asarray(w)
    out = quant.int8_matmul(x, jnp.asarray(q), jnp.asarray(scale))
    rel = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert rel < 0.05, rel
    assert jax.jit(quant.int8_matmul)(x, jnp.asarray(q),
                                      jnp.asarray(scale)).shape == ref.shape


def test_quantize_params_rules(tiny_params):
    import jax

    from bert_pytorch_tpu.ops import quant

    _, p32 = tiny_params
    qp = quant.quantize_params(p32, "int8")
    flat = {jax.tree_util.keystr(k): v
            for k, v in jax.tree_util.tree_leaves_with_path(qp)}

    enc_q = [k for k in flat if "encoder" in k and k.endswith("'kernel_q']")]
    assert enc_q, sorted(flat)[:5]
    for k in enc_q:
        assert flat[k].dtype == np.int8
        scale = flat[k.replace("kernel_q", "kernel_scale")]
        # scan-stacked kernels carry one scale per layer
        assert scale.shape == (flat[k].shape[0],)
    # embeddings and LayerNorm stay fp32
    emb = [k for k in flat if "word_embeddings" in k]
    assert emb and all(flat[k].dtype == np.float32 for k in emb)
    ln = [k for k in flat if "layer_norm" in k and "'scale']" in k]
    assert ln and all(flat[k].dtype == np.float32 for k in ln)
    # dense biases ride bf16
    import jax.numpy as jnp

    bias = [k for k in flat if "intermediate" in k and k.endswith("'bias']")]
    assert bias and all(flat[k].dtype == jnp.bfloat16 for k in bias)


def test_exclude_modules_downgrade(config, tokenizer, engine_int8):
    """The task-head output layers skip int8: their kernels store bf16."""
    import jax
    import jax.numpy as jnp

    flat = {jax.tree_util.keystr(k): v
            for k, v in jax.tree_util.tree_leaves_with_path(
                engine_int8.tasks["classify"].params)}
    cls_kernel = [k for k in flat if "classifier" in k and "kernel" in k]
    assert cls_kernel
    for k in cls_kernel:
        assert "kernel_q" not in k
        assert flat[k].dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# streaming checkpoint load


def test_streaming_quantized_load_matches_host_transform(
        tmp_path, tiny_params):
    import jax

    from bert_pytorch_tpu.ops import quant
    from bert_pytorch_tpu.utils import checkpoint as ckpt

    _, p32 = tiny_params
    # A realistic checkpoint: optimizer subtree present and byte-skipped.
    ckpt.save_checkpoint(str(tmp_path), 5, {
        "model": p32,
        "optimizer": {"m": np.ones((64,), np.float32)},
        "epoch": 0})
    path = ckpt.checkpoint_path(str(tmp_path), 5)

    for mode in ("bf16", "int8"):
        streamed = ckpt.load_params_only(path, p32, quantize=mode)
        host = quant.quantize_params(p32, mode)
        s = jax.tree_util.tree_leaves_with_path(streamed)
        h = jax.tree_util.tree_leaves_with_path(host)
        assert len(s) == len(h)
        for (pk, a), (hk, b) in zip(s, h):
            assert str(pk) == str(hk)
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_streaming_cast_happens_inside_decode(tmp_path, tiny_params):
    """quantize=None: leaves cast to the TARGET's dtype during the
    streaming decode (the no-quantization host-memory fix)."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.utils import checkpoint as ckpt

    _, p32 = tiny_params
    ckpt.save_checkpoint(str(tmp_path), 1, {"model": p32})
    path = ckpt.checkpoint_path(str(tmp_path), 1)
    target = jax.tree_util.tree_map(
        lambda x: np.asarray(x).astype(jnp.bfloat16)
        if x.dtype == np.float32 else x, p32)
    restored = ckpt.load_params_only(path, target)
    for leaf in jax.tree_util.tree_leaves(restored):
        assert leaf.dtype == jnp.bfloat16


def test_streaming_load_shape_mismatch_raises(tmp_path, config, tiny_params):
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu import models
    from bert_pytorch_tpu.utils import checkpoint as ckpt

    _, p32 = tiny_params
    ckpt.save_checkpoint(str(tmp_path), 1, {"model": p32})
    path = ckpt.checkpoint_path(str(tmp_path), 1)
    wrong_cfg = BertConfig(
        vocab_size=config.vocab_size, hidden_size=16, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=32,
        max_position_embeddings=64, type_vocab_size=2, next_sentence=True)
    wrong = models.BertForMaskedLM(wrong_cfg, dtype=jnp.float32)
    ids = jnp.zeros((1, BUCKET), jnp.int32)
    pw = nn.unbox(wrong.init(jax.random.PRNGKey(0), ids, ids, ids))["params"]
    with pytest.raises(ckpt.CheckpointShapeError):
        ckpt.load_params_only(path, pw, quantize="int8")


# ---------------------------------------------------------------------------
# per-head parity bounds (the documented quant levels)


_PARITY_PAYLOADS = {
    "fill_mask": {"text": "the capital of [MASK] is paris"},
    "classify": {"text": "the river runs through london",
                 "text_pair": "england is old"},
    "squad": {"question": "what is the capital of france",
              "context": "the capital of france is paris"},
    "ner": {"text": "william shakespeare wrote hamlet"},
}


def _head_outputs(engine, task):
    """Raw per-request logit slices through the real batched path."""
    from bert_pytorch_tpu.serve.batcher import Request

    spec = engine.tasks[task]
    payload = _PARITY_PAYLOADS[task]
    features = spec.handler.prepare(payload, engine.max_len())
    plan = engine.plan_batch([Request(task, features, payload)],
                            packed=False)
    outputs, info = engine.execute(task, plan)
    assert info["compiles"] == 0  # warmup covered this shape
    out = outputs[0]
    return out if isinstance(out, tuple) else (out,)


@pytest.mark.parametrize("task", sorted(TASKS))
def test_quantized_parity_bounds(task, engine_fp32, engine_bf16,
                                 engine_int8):
    """Served bf16/int8 logits match fp32 within the documented per-level
    bounds, per task head (docs/serving.md "Inference fast path")."""
    ref = _head_outputs(engine_fp32, task)
    for engine, atol in ((engine_bf16, BF16_LOGIT_ATOL),
                        (engine_int8, INT8_LOGIT_ATOL)):
        got = _head_outputs(engine, task)
        assert len(got) == len(ref)
        for a, b in zip(ref, got):
            diff = float(np.max(np.abs(
                np.asarray(a, np.float32) - np.asarray(b, np.float32))))
            assert diff <= atol, (task, engine.quantize, diff)


def test_run_direct_quantized_end_to_end(engine_int8):
    """Postprocessing works over quantized outputs (argmax-stable on the
    seeded tiny config)."""
    result = engine_int8.run_direct(
        "classify", {"text": "paris is big"})
    assert set(result) >= {"label", "scores"}


# ---------------------------------------------------------------------------
# forward-only Pallas kernel (interpret mode on CPU)


def test_infer_kernel_packed_equals_unpacked():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops.pallas.attention import flash_attention_infer

    B, S, H, D = 1, 32, 4, 8
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in keys)
    # Two sequences packed into one row: 12 + 8 tokens, rest pad (id 0).
    sids = np.zeros((B, S), np.int32)
    sids[0, :12], sids[0, 12:20] = 1, 2
    packed = flash_attention_infer(q, k, v,
                                   sequence_ids=jnp.asarray(sids))

    def solo(lo, hi):
        pad = S - (hi - lo)
        sl = lambda t: jnp.pad(t[:, lo:hi], ((0, 0), (0, pad),
                                             (0, 0), (0, 0)))
        mask = np.zeros((B, S), np.int32)
        mask[0, :hi - lo] = 1
        from bert_pytorch_tpu.ops.attention import make_attention_bias

        out = flash_attention_infer(
            sl(q), sl(k), sl(v),
            bias=make_attention_bias(jnp.asarray(mask)))
        return out[0, :hi - lo]

    np.testing.assert_allclose(np.asarray(packed[0, :12]),
                               np.asarray(solo(0, 12)), atol=1e-5)
    np.testing.assert_allclose(np.asarray(packed[0, 12:20]),
                               np.asarray(solo(12, 20)), atol=1e-5)


def test_infer_kernel_matches_xla_reference():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops import attention as att

    B, S, H, D = 2, 32, 4, 8
    keys = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in keys)
    mask = np.ones((B, S), np.int32)
    mask[0, 20:] = 0
    bias = att.make_attention_bias(jnp.asarray(mask))
    ref = att.dot_product_attention(q, k, v, bias=bias, backend="xla")
    out = att.dot_product_attention(q, k, v, bias=bias,
                                    backend="pallas_infer")
    np.testing.assert_allclose(np.asarray(out[:, :20]),
                               np.asarray(ref[:, :20]), atol=1e-5)


def test_infer_backend_rejects_training_dropout():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops import attention as att

    x = jnp.zeros((1, 16, 2, 4), jnp.float32)
    with pytest.raises(ValueError, match="forward-only"):
        att.dot_product_attention(
            x, x, x, backend="pallas_infer", deterministic=False,
            dropout_rate=0.1, dropout_rng=jax.random.PRNGKey(0))


def test_model_level_pallas_infer_parity(config, tiny_params):
    """The serve heads produce identical logits under the inference
    kernel (interpret mode) and the XLA path — the parity pattern the
    packed training kernel established (tests/test_packing.py)."""
    import jax.numpy as jnp

    from bert_pytorch_tpu import models

    model_xla, p32 = tiny_params
    model_inf = models.BertForMaskedLM(config, dtype=jnp.float32,
                                       attention_backend="pallas_infer")
    ids = jnp.arange(BUCKET, dtype=jnp.int32)[None, :] % 7 + 1
    mask = jnp.ones_like(ids)
    ref = model_xla.apply({"params": p32}, ids, ids * 0, mask)
    out = model_inf.apply({"params": p32}, ids, ids * 0, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# ---------------------------------------------------------------------------
# stable forward names + cold-start stats


def test_forward_names_per_spec(engine_int8):
    """Every (task, bucket, quant) compiles under its own stable fn name
    — compile-cache keys derive from the fn-name-derived HLO module
    name, so this is what makes warm restarts deterministic and the
    CompileMonitor attribution unambiguous."""
    names = {e["fn"] for e in engine_int8.monitor.events
             if e.get("kind") == "compile"}
    expected = {f"serve_{task}_b{BUCKET}_int8" for task in TASKS}
    assert expected <= names, names


def test_cold_start_stats_shape(engine_fp32):
    s = engine_fp32.startup
    assert s["compiles"] == s["compiles_cold"] + s["compiles_warm"] \
        + sum(1 for e in engine_fp32.monitor.events
              if e.get("kind") == "compile" and e.get("cache") == "jit")
    assert s["cold_start_s"] > 0
    assert s["quantize"] == "none"
    assert s["weight_bytes"] > 0


def test_statsz_carries_cold_start_and_quant_mode(engine_int8):
    from bert_pytorch_tpu.serve.stats import ServeTelemetry
    from bert_pytorch_tpu.telemetry.schema import validate_record

    records = []
    tele = ServeTelemetry(emit=records.append, window=4)
    rec = tele.observe_cold_start(engine_int8.startup)
    assert rec["kind"] == "serve_cold_start"
    assert validate_record({"schema": 1, "ts": 0.0, **rec}) == []
    # A stop()/start() cycle re-observes the same engine start: no
    # second record (the report SUMS cold compiles across records — a
    # duplicate would double-count the warm-restart gate).
    assert tele.observe_cold_start(engine_int8.startup) is None
    assert len(records) == 1
    snap = tele.snapshot()
    assert snap["quantize"] == "int8"
    assert snap["cold_start_s"] == engine_int8.startup["cold_start_s"]
    assert snap["warmup_compiles"] == engine_int8.startup["compiles"]
    # steady-state compiles stays the serve acceptance counter (zero).
    assert snap["compiles"] == 0


# ---------------------------------------------------------------------------
# schema + report gating by name


def test_serve_cold_start_schema_lint():
    from bert_pytorch_tpu.telemetry.schema import validate_record

    good = {"schema": 1, "ts": 0.0, "kind": "serve_cold_start",
            "cold_start_s": 1.5, "compiles": 4, "compiles_cold": 4,
            "compiles_warm": 0}
    assert validate_record(good) == []
    bad = dict(good, compiles_cold=3, compiles_warm=2)
    assert any("exceeds compiles" in e for e in validate_record(bad))
    bad2 = dict(good, cold_start_s=-1)
    assert any("non-negative" in e for e in validate_record(bad2))


def test_report_gates_serve_p50_and_cold_start_by_name():
    from bert_pytorch_tpu.telemetry.report import compare, summarize_records

    def summary(p50, cold_s, cold_compiles):
        return summarize_records([
            {"kind": "serve_summary", "requests": 64, "batches": 8,
             "requests_per_sec": 10.0, "latency_p50_ms": p50,
             "latency_p95_ms": p50 * 2, "latency_p99_ms": p50 * 3},
            {"kind": "serve_cold_start", "cold_start_s": cold_s,
             "compiles": 4, "compiles_cold": cold_compiles,
             "compiles_warm": 4 - cold_compiles, "quantize": "int8"},
        ])

    base = summary(10.0, 2.0, 0)
    assert base["serve_cold_start_s"] == 2.0
    assert base["serve_quantize"] == "int8"

    regs, _ = compare(base, summary(10.0, 2.0, 0))
    assert not regs
    # p50 regression is caught BY NAME
    regs, _ = compare(base, summary(20.0, 2.0, 0))
    assert any(r["label"] == "serve p50 latency" for r in regs)
    # cold-start regression by name
    regs, _ = compare(base, summary(10.0, 8.0, 0))
    assert any(r["label"] == "serve cold start" for r in regs)
    # NEW cold compiles against a warm baseline regress regardless of tol
    regs, _ = compare(base, summary(10.0, 2.0, 3))
    assert any(r["label"] == "serve cold compiles" for r in regs)


# ---------------------------------------------------------------------------
# the two-process warm-cache acceptance


_CHILD_SCRIPT = """
import json, sys
import jax
# Match the parent's conftest config: both feed the compile-cache key
# (matmul precision changes the HLO; the XLA_FLAGS device count rides the
# inherited environment).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
import jax.numpy as jnp
from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache
assert enable_compile_cache(sys.argv[1], min_compile_secs=0.0)
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.serve import InferenceEngine
from bert_pytorch_tpu.data.tokenization import BertTokenizer
from bert_pytorch_tpu.tools.make_synthetic_data import TRACE_WORDS

vocab = 5 + len(TRACE_WORDS); vocab += (8 - vocab %% 8) %% 8
cfg = BertConfig(vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
                 num_attention_heads=4, intermediate_size=64,
                 max_position_embeddings=64, type_vocab_size=2,
                 next_sentence=True, hidden_dropout_prob=0.0,
                 attention_probs_dropout_prob=0.0)
tok = BertTokenizer(sys.argv[2], do_lower_case=True)
eng = InferenceEngine(cfg, tok, {"classify": {"labels": ["a", "b"]}},
                      buckets=(%(bucket)d,), max_batch_size=2,
                      dtype=jnp.float32, seed=11, quantize="int8")
eng.warmup()
print("STARTUP " + json.dumps(eng.startup))
"""


def test_second_process_start_zero_cold_compiles(tmp_path, vocab_file):
    """THE cold-start acceptance (docs/serving.md): engine start in this
    process populates the persistent AOT cache; a SECOND, fresh process
    warms entirely from it — zero cold compiles, proven by the
    persistent-cache counter events the startup stats split on."""
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig as BC
    from bert_pytorch_tpu.data.tokenization import BertTokenizer
    from bert_pytorch_tpu.serve import InferenceEngine
    from bert_pytorch_tpu.tools.make_synthetic_data import TRACE_WORDS
    from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache

    cache_dir = str(tmp_path / "aot_cache")
    assert enable_compile_cache(cache_dir, min_compile_secs=0.0)
    try:
        vocab = 5 + len(TRACE_WORDS)
        vocab += (8 - vocab % 8) % 8
        cfg = BC(vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
                 num_attention_heads=4, intermediate_size=64,
                 max_position_embeddings=64, type_vocab_size=2,
                 next_sentence=True, hidden_dropout_prob=0.0,
                 attention_probs_dropout_prob=0.0)
        tok = BertTokenizer(vocab_file, do_lower_case=True)
        eng = InferenceEngine(
            cfg, tok, {"classify": {"labels": ["a", "b"]}},
            buckets=(BUCKET,), max_batch_size=2, dtype=jnp.float32,
            seed=11, quantize="int8")
        eng.warmup()
        first = eng.startup
        assert first["compiles_cold"] >= 1  # this process paid the compile
    finally:
        # Restore process-global jax config: later tests must not
        # silently run against this tmp cache.
        import jax
        from jax._src import compilation_cache as _cc

        jax.config.update("jax_compilation_cache_dir", None)
        _cc.reset_cache()

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD_SCRIPT % {"bucket": BUCKET},
         cache_dir, vocab_file],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines()
            if l.startswith("STARTUP ")][-1]
    second = json.loads(line[len("STARTUP "):])
    # Cache counter events are the authority: every forward the fresh
    # process compiled was served from the persisted AOT cache.
    assert second["compiles_cold"] == 0, second
    assert second["compiles_warm"] >= 1, second
