"""jaxlint: the tier-1 repo gate + unit coverage for every check ID
(bert_pytorch_tpu/analysis/, docs/static_analysis.md).

The gate contract (ISSUE 7): running the analyzer over the whole
package, the five runners, serve, and tools must produce ZERO findings
beyond the committed baseline — and the analyzer itself must run
without importing jax (asserted by poisoning sys.modules['jax'] in the
CLI subprocess) and complete fast enough to live un-slow-gated in
tier-1.

Fixture coverage: one positive and one negative fixture per check ID
under tests/fixtures/jaxlint/, plus inline suppression, the
unknown-ID-in-disable error, and the baseline round-trip (line-shift
stability + fixed-line staleness).
"""

from __future__ import annotations

import ast
import os
import subprocess
import sys
import time

import pytest

from bert_pytorch_tpu.analysis import baseline as baseline_mod
from bert_pytorch_tpu.analysis import check_all, core
from bert_pytorch_tpu.analysis.concurrency import Entry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "jaxlint")
BASELINE = os.path.join(REPO_ROOT, "jaxlint_baseline.json")

# The lock-discipline fixtures are not part of the real codebase, so
# their registry entries live here, injected through run_files(registry=).
FIXTURE_REGISTRY = (
    Entry("lk501_pos.py", "count", kind="lock", cls="Gauges",
          locks=("_lock",)),
    Entry("lk501_neg.py", "count", kind="lock", cls="Gauges",
          locks=("_lock",)),
    Entry("lk502_pos.py", "sink", kind="frozen", cls="Emitter"),
    Entry("lk502_neg.py", "sink", kind="frozen", cls="Emitter"),
    Entry("lk503_pos.py", "_stats", kind="confined", cls="Prefetcher",
          forbidden_in=("_worker",)),
    Entry("lk503_neg.py", "_stats", kind="confined", cls="Prefetcher",
          forbidden_in=("_worker",)),
)


# The CT801 fixtures judge their emitted kinds against this mini schema
# module (parsed as program CONTEXT, so it produces no findings of its
# own); every other fixture simply ignores it.
FIXTURE_SCHEMA = os.path.join(FIXTURES, "telemetry", "schema.py")


def run_fixture(name):
    return core.run_files([os.path.join(FIXTURES, name)],
                          repo_root=REPO_ROOT, registry=FIXTURE_REGISTRY,
                          context_paths=[FIXTURE_SCHEMA])


# -- the tier-1 gate -----------------------------------------------------

def test_repo_gate_no_unsuppressed_findings():
    """The acceptance invariant: package + runners + tools lint clean
    against the committed (near-empty) baseline, fast enough to live in
    tier-1 (the bound started at 10s; each PR grows the parsed corpus —
    PR 10 added the whole-program tier, PR 11 ~120KB of fleet code —
    and the throttled 2-core box's clock varies, so the bound tracks
    "an order of magnitude under the tier-1 budget", not the original
    measurement)."""
    t0 = time.perf_counter()
    findings = core.run_paths(list(check_all.JAXLINT_TARGETS),
                              repo_root=REPO_ROOT)
    elapsed = time.perf_counter() - t0
    entries = baseline_mod.load_baseline(BASELINE)
    new, matched, stale = baseline_mod.apply_baseline(findings, entries)
    assert not new, "unsuppressed jaxlint findings:\n" + "\n".join(
        f.format() for f in new)
    assert not stale, (
        "stale baseline entries (the flagged lines no longer exist — "
        "prune with --write-baseline): " + repr(stale))
    assert elapsed < 25.0, f"jaxlint took {elapsed:.1f}s (budget 25s)"


def test_cli_repo_gate_runs_without_jax():
    """The exact acceptance command (ISSUE 10: the UNIFIED gate —
    jaxlint incl. the whole-program shardlint tier, plus the telemetry
    schema leg) with jax imports POISONED: the analyzer, the
    bert_pytorch_tpu __init__ chain it rides in on, AND the file-path-
    loaded schema engine must all be stdlib-only, and the repo must lint
    clean (exit 0) against the EMPTY committed baseline."""
    script = os.path.join(REPO_ROOT, "tools", "check_all.py")
    code = (
        "import sys, runpy\n"
        "sys.modules['jax'] = None\n"  # any 'import jax' now raises
        "sys.argv = ['check_all']\n"
        f"runpy.run_path({script!r}, run_name='__main__')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_ROOT,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"check_all gate failed (rc {proc.returncode}):\n"
        f"{proc.stdout}\n{proc.stderr}")


# One seeded violation per check FAMILY (ISSUE 10 acceptance: the CLI
# must exit 1 naming the check ID). hs101 keeps the legacy per-file
# tier covered; the rest are the shardlint tier.
SEEDED = ["hs101_pos.py", "sd601_pos.py", "sd602_pos.py", "dn701_pos.py",
          "ct801_pos.py", "ct802_pos.py"]


@pytest.mark.parametrize("fixture", SEEDED,
                         ids=[f.split("_")[0].upper() for f in SEEDED])
def test_cli_seeded_violation_exits_nonzero_naming_the_id(fixture):
    check_id = fixture.split("_")[0].upper()
    # No --no-context: the fixture is judged against the REAL program
    # (ct801's kinds against the real telemetry/schema.py registry,
    # ct802's flags against the real runners' parsers).
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "jaxlint.py"),
         os.path.join(FIXTURES, fixture), "--no-baseline"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert check_id in proc.stdout


# -- per-ID fixtures -----------------------------------------------------

POSITIVE = [
    ("hs101_pos.py", "HS101", 4),
    ("rc201_pos.py", "RC201", 2),
    ("rc202_pos.py", "RC202", 3),
    ("rc203_pos.py", "RC203", 1),
    ("rn301_pos.py", "RN301", 2),
    ("rn302_pos.py", "RN302", 2),
    ("tl401_pos.py", "TL401", 2),
    ("lk501_pos.py", "LK501", 1),
    ("lk502_pos.py", "LK502", 1),
    ("lk503_pos.py", "LK503", 1),
    # The shardlint (whole-program) tier.
    ("sd601_pos.py", "SD601", 2),
    ("sd602_pos.py", "SD602", 2),
    ("sd603_pos.py", "SD603", 5),
    ("dn701_pos.py", "DN701", 2),
    ("ct801_pos.py", "CT801", 2),
    ("ct802_pos.py", "CT802", 2),
]


@pytest.mark.parametrize("name,check_id,count", POSITIVE,
                         ids=[p[1] for p in POSITIVE])
def test_positive_fixture(name, check_id, count):
    findings = run_fixture(name)
    ids = [f.check for f in findings]
    assert ids == [check_id] * count, (
        f"{name}: expected {count}x {check_id}, got:\n"
        + "\n".join(f.format() for f in findings))


@pytest.mark.parametrize(
    "name", sorted(n for n in os.listdir(FIXTURES) if n.endswith("_neg.py")))
def test_negative_fixture(name):
    findings = run_fixture(name)
    assert findings == [], (
        f"{name}: expected clean, got:\n"
        + "\n".join(f.format() for f in findings))


def test_every_check_id_has_both_fixtures():
    jl = {core.JL_BAD_ID, core.JL_PARSE}
    for check_id in sorted(set(core.ALL_CHECK_IDS) - jl):
        for suffix in ("pos", "neg"):
            path = os.path.join(FIXTURES,
                                f"{check_id.lower()}_{suffix}.py")
            assert os.path.exists(path), f"missing fixture {path}"


# -- suppression ---------------------------------------------------------

HOT_LOOP = """import jax

def train(tele, loader, step_fn, state):
    for batch in tele.timed(loader):
        state, m = step_fn(state, batch)
        x = float(m["loss"]){comment}
    return state, x
"""


def _lint_source(tmp_path, source):
    path = tmp_path / "mod.py"
    path.write_text(source)
    return core.run_files([str(path)], repo_root=str(tmp_path))


def test_inline_suppression_same_line(tmp_path):
    findings = _lint_source(
        tmp_path, HOT_LOOP.format(comment="  # jaxlint: disable=HS101"))
    assert findings == []


def test_inline_suppression_line_above(tmp_path):
    source = HOT_LOOP.format(comment="").replace(
        "        x = float(",
        "        # jaxlint: disable=HS101\n        x = float(")
    assert _lint_source(tmp_path, source) == []


def test_suppression_inside_docstring_is_inert(tmp_path):
    source = ('"""Docs quoting # jaxlint: disable=HS101 must not '
              'suppress."""\n') + HOT_LOOP.format(comment="")
    findings = _lint_source(tmp_path, source)
    assert [f.check for f in findings] == ["HS101"]


def test_unknown_check_id_in_disable_comment_errors(tmp_path):
    findings = _lint_source(
        tmp_path, HOT_LOOP.format(comment="  # jaxlint: disable=HS999"))
    checks = sorted(f.check for f in findings)
    # The typo'd suppression is an error AND does not suppress.
    assert checks == sorted(["HS101", core.JL_BAD_ID]), checks
    jl = [f for f in findings if f.check == core.JL_BAD_ID][0]
    assert "HS999" in jl.message


def test_parse_error_is_a_finding(tmp_path):
    path = tmp_path / "broken.py"
    path.write_text("def train(:\n")
    findings = core.run_files([str(path)], repo_root=str(tmp_path))
    assert [f.check for f in findings] == [core.JL_PARSE]


# -- baseline ------------------------------------------------------------

def test_baseline_round_trip_line_shift_and_fix(tmp_path):
    source = HOT_LOOP.format(comment="")
    path = tmp_path / "mod.py"
    path.write_text(source)
    lint = lambda: core.run_files([str(path)], repo_root=str(tmp_path))
    findings = lint()
    assert [f.check for f in findings] == ["HS101"]

    bpath = str(tmp_path / "baseline.json")
    assert baseline_mod.write_baseline(bpath, findings) == 1
    entries = baseline_mod.load_baseline(bpath)

    # Round trip: the same findings are fully covered.
    new, matched, stale = baseline_mod.apply_baseline(lint(), entries)
    assert (len(new), len(matched), len(stale)) == (0, 1, 0)

    # Unrelated edits shift lines: matching is by source text, so the
    # baseline still covers the finding.
    path.write_text("# a new header comment\n" + source)
    new, matched, stale = baseline_mod.apply_baseline(lint(), entries)
    assert (len(new), len(matched), len(stale)) == (0, 1, 0)

    # Fixing the flagged line removes the finding AND strands the entry
    # (reported stale so --write-baseline prunes it).
    path.write_text(source.replace('float(m["loss"])', 'm["loss"]'))
    assert lint() == []
    new, matched, stale = baseline_mod.apply_baseline(lint(), entries)
    assert (len(new), len(matched), len(stale)) == (0, 0, 1)


def test_write_baseline_subset_run_preserves_other_entries(tmp_path):
    """--write-baseline after linting a SUBSET of the repo must keep
    entries for unlinted files (and still-matching entries' hand-written
    justifications), pruning only stale entries of linted files."""
    source = HOT_LOOP.format(comment="")
    path = tmp_path / "mod.py"
    path.write_text(source)
    findings = core.run_files([str(path)], repo_root=str(tmp_path))
    assert len(findings) == 1

    other = {"check": "LK501", "path": "other/module.py",
             "source": "self.count += 1",
             "justification": "hand-written: lock held by caller"}
    covered = {"check": findings[0].check, "path": findings[0].path,
               "source": findings[0].source,
               "justification": "hand-written: host-resident value"}
    gone = {"check": "HS101", "path": findings[0].path,
            "source": "float(old_line_since_fixed)",
            "justification": "stale"}
    merged = baseline_mod.merge_entries(
        [other, covered, gone], findings, linted_paths={findings[0].path})
    assert other in merged              # unlinted file: untouched
    assert covered in merged            # justification preserved
    assert gone not in merged           # stale entry of a linted file
    assert len(merged) == 2


def test_malformed_baseline_fails_loudly(tmp_path):
    bpath = tmp_path / "baseline.json"
    bpath.write_text('{"version": 99}')
    with pytest.raises(ValueError):
        baseline_mod.load_baseline(str(bpath))
    bpath.write_text('{"version": 1, "entries": [{"check": "HS101"}]}')
    with pytest.raises(ValueError):
        baseline_mod.load_baseline(str(bpath))


def test_committed_baseline_loads_and_is_near_empty():
    entries = baseline_mod.load_baseline(BASELINE)
    # ISSUE 7: fix findings, don't grandfather them. Tolerate a handful
    # of justified entries, never a dumping ground.
    assert len(entries) <= 5
    for entry in entries:
        assert entry.get("justification"), (
            "every baseline entry needs a justification: " + repr(entry))


# -- the axes-registry mirror --------------------------------------------

def test_axes_registry_mirrors_mesh_py():
    """analysis/axes.py restates parallel/mesh.py's axis tables because
    the analysis package must stay stdlib-only (it cannot import the
    real ones). This pins the two copies together by PARSING mesh.py —
    a one-mesh-refactor edit to MESH_AXES / _BASE_RULES /
    _RULE_TEMPLATE / _STRATEGY_AXES that forgets the mirror fails
    tier-1 here, not a sharding bug three PRs later. The derived
    _STRATEGY_RULES dicts (both sides regenerate them from these
    literals) are pinned equal by tests/test_mesh.py, which may import
    jax."""
    from bert_pytorch_tpu.analysis import axes as axes_registry

    mesh_py = os.path.join(REPO_ROOT, "bert_pytorch_tpu", "parallel",
                           "mesh.py")
    with open(mesh_py) as fh:
        tree = ast.parse(fh.read())

    env = {}

    def ev(node):
        # The axis tables are literals plus references to the AXIS_*
        # constants; anything richer (function calls, imports) aborts
        # the evaluation of that assignment, which is then skipped.
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            return env[node.id]
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(ev(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return {ev(k): ev(v) for k, v in zip(node.keys, node.values)}
        raise KeyError(ast.dump(node))

    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            try:
                env[stmt.targets[0].id] = ev(stmt.value)
            except KeyError:
                pass

    assert {k: env[k] for k in axes_registry.AXIS_CONSTANTS} \
        == axes_registry.AXIS_CONSTANTS
    assert env["MESH_AXES"] == axes_registry.MESH_AXES
    assert env["_BASE_RULES"] == axes_registry.BASE_RULES
    assert env["_RULE_TEMPLATE"] == axes_registry.RULE_TEMPLATE
    assert env["_STRATEGY_AXES"] == axes_registry.STRATEGY_AXES
    # The registry's regenerated alias rules must agree with a
    # re-derivation from mesh.py's parsed literals (same first-wins
    # semantics as mesh.derive_rules).
    for name, active in env["_STRATEGY_AXES"].items():
        assert axes_registry.STRATEGY_RULES[name] == \
            axes_registry.derive_rules(active)


# -- the unified gate ----------------------------------------------------

def test_check_all_schema_leg(tmp_path, capsys):
    good = tmp_path / "good.jsonl"
    # A legacy (schema-less) record is held to the universal rules only.
    good.write_text('{"tag": "t", "step": 1, "loss": 2.5}\n')
    assert check_all.main(["--skip-jaxlint", str(good)]) == 0
    bad = tmp_path / "bad.jsonl"
    bad.write_text("not json\n")
    assert check_all.main(["--skip-jaxlint", str(bad)]) == 1
    capsys.readouterr()


def test_cli_list_checks(capsys):
    from bert_pytorch_tpu.analysis import cli
    assert cli.main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for check_id in core.ALL_CHECK_IDS:
        assert check_id in out
