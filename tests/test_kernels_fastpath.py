"""Raw-speed kernel program tests (ISSUE 14; docs/serving.md
"Raw-speed kernels"): fused task-head epilogues, int8 attention, and
the measured Pallas autotune with persisted winners.

Covers, on CPU:

* per-head numerical parity of FUSED EPILOGUES — fill_mask's gathered
  [B, P, V] logits are BIT-EQUAL to the unfused plane's rows at the
  mask positions for fp32 (the one-hot gather multiplies by exact 1.0
  and sums exact zeros before the linear projection), squad's stacked
  span output re-splits bit-equal, and quantized fused engines hold the
  existing int8 bound; the slot-overflow fallback stays correct;
* the output-bytes reduction the fusion exists for, asserted from the
  joined ``compile_cost`` records (the acceptance: fused engines move
  measurably fewer device->host bytes);
* int8-attention parity: kernel-level vs the XLA reference and packed
  == solo, plus MODEL-LEVEL parity on all four serve heads (the XLA
  engine vs the interpret-mode Pallas int8 engine);
* the autotune pass: candidates/measure/persist/load round trips, the
  winners-file format lint (bert-lint integration), the ``autotune``
  record schema kind, the winner digest riding the stable forward
  names, and THE warm-restart acceptance — a fresh subprocess with a
  populated AOT cache + winners file reports ``compiles_cold == 0``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bert_pytorch_tpu.config import BertConfig

# Documented parity bounds (docs/serving.md "Raw-speed kernels").
INT8_LOGIT_ATOL = 1e-1          # quantized-weights engines (PR 8 bound)
INT8_ATTN_KERNEL_ATOL = 5e-2    # kernel out, N(0,1) q/k (worst case)
INT8_ATTN_MODEL_ATOL = 2e-2     # served logits, tiny seeded config

NER_LABELS = ["O", "B-LOC", "B-PER"]
TASKS = {"fill_mask": {}, "classify": {"labels": ["neg", "pos"]},
         "squad": {}, "ner": {"labels": NER_LABELS}}
BUCKET = 16
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PARITY_PAYLOADS = {
    "fill_mask": {"text": "the capital of [MASK] is paris"},
    "classify": {"text": "the river runs through london",
                 "text_pair": "england is old"},
    "squad": {"question": "what is the capital of france",
              "context": "the capital of france is paris"},
    "ner": {"text": "william shakespeare wrote hamlet"},
}


# ---------------------------------------------------------------------------
# fixtures


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    from bert_pytorch_tpu.tools.make_synthetic_data import write_trace_vocab

    d = tmp_path_factory.mktemp("kernels_vocab")
    return write_trace_vocab(str(d / "vocab.txt"))


@pytest.fixture(scope="module")
def tokenizer(vocab_file):
    from bert_pytorch_tpu.data.tokenization import BertTokenizer

    return BertTokenizer(vocab_file, do_lower_case=True)


@pytest.fixture(scope="module")
def config():
    from bert_pytorch_tpu.tools.make_synthetic_data import TRACE_WORDS

    vocab = 5 + len(TRACE_WORDS)
    vocab += (8 - vocab % 8) % 8
    return BertConfig(
        vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2, next_sentence=True,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)


def _engine(config, tokenizer, tasks=TASKS, cost="off", **kw):
    import jax.numpy as jnp

    from bert_pytorch_tpu.serve import InferenceEngine
    from bert_pytorch_tpu.telemetry.compile_events import CompileMonitor

    eng = InferenceEngine(
        config, tokenizer, tasks, buckets=(BUCKET,), max_batch_size=2,
        max_requests_per_pack=2, dtype=jnp.float32, seed=7,
        monitor=CompileMonitor(emit=lambda rec: None, cost_analysis=cost),
        **kw)
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def engine_base(config, tokenizer):
    """Unfused fp32 XLA engine — the reference, with cost attribution
    on for the output-bytes comparison."""
    return _engine(config, tokenizer, cost="auto")


@pytest.fixture(scope="module")
def engine_fused(config, tokenizer):
    return _engine(config, tokenizer, cost="auto", fuse_epilogues=True)


@pytest.fixture(scope="module")
def engine_int8_attn(config, tokenizer):
    """fp32 weights + int8-QK^T interpret-mode Pallas attention."""
    return _engine(config, tokenizer,
                   attention_backend="pallas_infer_int8")


def _head_outputs(engine, task, payload=None, packed=False):
    """Raw per-request output slices through the real batched path."""
    from bert_pytorch_tpu.serve.batcher import Request

    spec = engine.tasks[task]
    payload = payload or _PARITY_PAYLOADS[task]
    features = spec.handler.prepare(payload, engine.max_len())
    plan = engine.plan_batch([Request(task, features, payload)],
                             packed=packed)
    outputs, info = engine.execute(task, plan)
    return outputs[0], features, info


# ---------------------------------------------------------------------------
# fused epilogues: parity


def test_fill_mask_fused_gather_bit_equal_fp32(engine_base, engine_fused):
    """The gathered [P, V] rows are BIT-EQUAL to the unfused plane's
    rows at the mask positions — gather-then-project == project-then-
    gather exactly, because the one-hot matmul multiplies by 1.0 and
    sums exact zeros and the projection is linear and row-independent."""
    from bert_pytorch_tpu.serve.tasks import GatheredTokens

    ref, feats, info_b = _head_outputs(engine_base, "fill_mask")
    got, _, info_f = _head_outputs(engine_fused, "fill_mask")
    assert not info_b["fused"] and info_f["fused"]
    assert isinstance(got, GatheredTokens)
    expected = np.asarray(ref, np.float32)[feats["mask_positions"]]
    np.testing.assert_array_equal(np.asarray(got.logits), expected)


def test_squad_fused_stack_bit_equal_fp32(engine_base, engine_fused):
    (ref_s, ref_e), _, _ = _head_outputs(engine_base, "squad")
    (got_s, got_e), _, info = _head_outputs(engine_fused, "squad")
    assert info["fused"]
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(ref_s))
    np.testing.assert_array_equal(np.asarray(got_e), np.asarray(ref_e))


@pytest.mark.parametrize("task", ["classify", "ner"])
def test_unfusable_heads_identical(task, engine_base, engine_fused):
    """Heads with nothing to fuse (pooled already extracts in-model;
    ner's per-word rows are unbounded) compile the same program —
    outputs are bit-equal and the fn names match the unfused engine's,
    so they share its persistent-cache entries."""
    ref, _, _ = _head_outputs(engine_base, task)
    got, _, info = _head_outputs(engine_fused, task)
    assert not info["fused"]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    base_names = {e["fn"] for e in engine_base.monitor.events
                  if e.get("kind") == "compile"
                  and e["fn"].startswith(f"serve_{task}_")}
    fused_names = {e["fn"] for e in engine_fused.monitor.events
                   if e.get("kind") == "compile"
                   and e["fn"].startswith(f"serve_{task}_")}
    assert base_names == fused_names


def test_fill_mask_fused_packed_bit_equal(engine_base, engine_fused):
    """Packed rows: each request's gathered rows match the unfused
    packed plane at its own (offset + mask) positions, bit-equal."""
    from bert_pytorch_tpu.serve.batcher import Request
    from bert_pytorch_tpu.serve.tasks import GatheredTokens

    payloads = [{"text": "paris is [MASK]"},
                {"text": "the capital of [MASK] is paris"},
                {"text": "[MASK] wrote hamlet"}]

    def run(engine):
        spec = engine.tasks["fill_mask"]
        reqs = [Request("fill_mask",
                        spec.handler.prepare(p, engine.max_len()), p)
                for p in payloads]
        outs = {}
        todo = list(reqs)
        shared = False
        while todo:
            plan = engine.plan_batch(todo, packed=True)
            shared = shared or any(len(row) > 1 for row in plan.rows)
            outputs, info = engine.execute("fill_mask", plan)
            for r, o in zip(plan.requests, outputs):
                outs[r.id] = (o, r.features)
            todo = plan.leftover
        assert shared, "payloads must actually share rows"
        return [outs[r.id] for r in reqs]

    for (ref, ref_f), (got, got_f) in zip(run(engine_base),
                                          run(engine_fused)):
        assert isinstance(got, GatheredTokens)
        expected = np.asarray(ref, np.float32)[ref_f["mask_positions"]]
        np.testing.assert_array_equal(np.asarray(got.logits), expected)


def test_fused_run_direct_results_identical(engine_base, engine_fused):
    """End to end through postprocess: the fused engine's JSON results
    equal the unfused engine's for every head."""
    for task, payload in _PARITY_PAYLOADS.items():
        a = engine_base.run_direct(task, dict(payload))
        b = engine_fused.run_direct(task, dict(payload))
        assert a == b, (task, a, b)


def test_fused_overflow_falls_back(config, tokenizer):
    """A batch whose gather positions exceed the slot quota runs the
    unfused fallback forward — same results, no error."""
    eng = _engine(config, tokenizer, tasks={"fill_mask": {}},
                  fuse_epilogues=True, epilogue_slots=1)
    over = {"text": "[MASK] is [MASK]"}  # 2 masks > 1 slot
    out, feats, info = _head_outputs(eng, "fill_mask", payload=over)
    assert not info["fused"]  # fell back
    assert np.asarray(out).shape[0] == len(feats["input_ids"])
    under = {"text": "paris is [MASK]"}
    _, _, info = _head_outputs(eng, "fill_mask", payload=under)
    assert info["fused"]


def test_int8_quantized_fused_within_bound(config, tokenizer,
                                           engine_base):
    """Quantized fused engines hold the PR-8 int8 logit bound against
    the fp32 reference — the epilogue commutes with the per-token
    activation quantization (row-independent), so fusing adds no new
    error on top of the documented quantization level."""
    eng = _engine(config, tokenizer, quantize="int8",
                  fuse_epilogues=True)
    got, feats, info = _head_outputs(eng, "fill_mask")
    assert info["fused"]
    ref, _, _ = _head_outputs(engine_base, "fill_mask")
    expected = np.asarray(ref, np.float32)[feats["mask_positions"]]
    diff = float(np.max(np.abs(np.asarray(got.logits) - expected)))
    assert diff <= INT8_LOGIT_ATOL, diff


# ---------------------------------------------------------------------------
# fused epilogues: the bytes win (the acceptance)


def _fill_mask_output_bytes(engine, fused):
    costs = {e["fn"]: e for e in engine.monitor.events
             if e.get("kind") == "compile_cost"
             and e["fn"].startswith("serve_fill_mask_b")
             and ("_fused" in e["fn"]) == fused
             and "_packed" not in e["fn"]}
    assert costs, [e.get("fn") for e in engine.monitor.events
                   if e.get("kind") == "compile_cost"]
    return sum(int(e.get("output_bytes", 0)) for e in costs.values())


def test_fused_epilogue_reduces_output_bytes(engine_base, engine_fused):
    """THE acceptance: the fused fill_mask forward's executable moves
    measurably fewer output bytes than the unfused one — [B, P, V]
    instead of [B, S, V], asserted from the compile_cost records the
    CompileMonitor joined at warmup (P=8 slots vs S=16 here: 2x; at
    production geometry S=128 the same fusion is 16x)."""
    base = _fill_mask_output_bytes(engine_base, fused=False)
    fused = _fill_mask_output_bytes(engine_fused, fused=True)
    assert base > 0 and fused > 0
    assert fused < base, (base, fused)
    # The exact shape arithmetic: V * 4 bytes per row position.
    assert base / fused == pytest.approx(
        BUCKET / engine_fused.epilogue_slots, rel=0.01)


# ---------------------------------------------------------------------------
# int8 attention


def test_int8_attention_kernel_parity_vs_xla():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops import attention as att

    B, S, H, D = 2, 32, 4, 8
    keys = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in keys)
    mask = np.ones((B, S), np.int32)
    mask[0, 20:] = 0
    bias = att.make_attention_bias(jnp.asarray(mask))
    ref = att.dot_product_attention(q, k, v, bias=bias, backend="xla")
    out = att.dot_product_attention(q, k, v, bias=bias,
                                    backend="pallas_infer_int8")
    diff = float(jnp.max(jnp.abs(out[:, :20] - ref[:, :20])))
    assert diff <= INT8_ATTN_KERNEL_ATOL, diff


def test_int8_attention_packed_equals_solo():
    """The packed block-diagonal mask survives quantization: a packed
    row's per-sequence outputs match each sequence run alone (same int8
    path both sides, so the only difference is the packing)."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops.attention import make_attention_bias
    from bert_pytorch_tpu.ops.pallas.attention import (
        flash_attention_infer_int8)

    B, S, H, D = 1, 32, 4, 8
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in keys)
    sids = np.zeros((B, S), np.int32)
    sids[0, :12], sids[0, 12:20] = 1, 2
    packed = flash_attention_infer_int8(q, k, v,
                                        sequence_ids=jnp.asarray(sids))

    def solo(lo, hi):
        pad = S - (hi - lo)
        sl = lambda t: jnp.pad(t[:, lo:hi], ((0, 0), (0, pad),
                                             (0, 0), (0, 0)))
        mask = np.zeros((B, S), np.int32)
        mask[0, :hi - lo] = 1
        out = flash_attention_infer_int8(
            sl(q), sl(k), sl(v),
            bias=make_attention_bias(jnp.asarray(mask)))
        return out[0, :hi - lo]

    # Packing changes the per-head amax (more rows share one scale), so
    # solo-vs-packed holds to the quantization grain, not exactly.
    np.testing.assert_allclose(np.asarray(packed[0, :12]),
                               np.asarray(solo(0, 12)),
                               atol=INT8_ATTN_KERNEL_ATOL)
    np.testing.assert_allclose(np.asarray(packed[0, 12:20]),
                               np.asarray(solo(12, 20)),
                               atol=INT8_ATTN_KERNEL_ATOL)


@pytest.mark.parametrize("task", sorted(TASKS))
def test_int8_attention_model_parity_all_heads(task, engine_base,
                                               engine_int8_attn):
    """Model-level parity on every served head: the interpret-mode
    Pallas int8 engine's logits vs the XLA engine's, within the
    documented bound (docs/serving.md 'Raw-speed kernels')."""
    ref, _, _ = _head_outputs(engine_base, task)
    got, _, _ = _head_outputs(engine_int8_attn, task)
    ref = ref if isinstance(ref, tuple) else (ref,)
    got = got if isinstance(got, tuple) else (got,)
    for a, b in zip(ref, got):
        diff = float(np.max(np.abs(
            np.asarray(a, np.float32) - np.asarray(b, np.float32))))
        assert diff <= INT8_ATTN_MODEL_ATOL, (task, diff)


def test_int8_infer_backend_rejects_training_dropout():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops import attention as att

    x = jnp.zeros((1, 16, 2, 4), jnp.float32)
    with pytest.raises(ValueError, match="forward-only"):
        att.dot_product_attention(
            x, x, x, backend="pallas_infer_int8", deterministic=False,
            dropout_rate=0.1, dropout_rng=jax.random.PRNGKey(0))


# ---------------------------------------------------------------------------
# autotune: registry, persistence, lint


@pytest.fixture()
def clean_registry():
    from bert_pytorch_tpu.ops.pallas import autotune

    autotune.clear_winners()
    yield autotune
    autotune.clear_winners()


def test_autotune_measure_persist_load_roundtrip(clean_registry,
                                                 tmp_path):
    autotune = clean_registry
    assert autotune.lookup("infer", 32, 8) is None
    rec = autotune.measure("infer", 32, 8, 8, repeats=1)
    assert rec["winner"]["block_q"] in (8, 16, 32)
    assert autotune.lookup("infer", 32, 8) == tuple(
        rec["winner"][k] for k in ("block_q", "block_k", "bh_block"))
    digest = autotune.name_digest("infer", 32, 8)
    assert len(digest) == 6

    path = str(tmp_path / "winners.json")
    assert autotune.save_winners(path) == 1
    autotune.clear_winners()
    assert autotune.name_digest("infer", 32, 8) == ""
    assert autotune.load_winners(path) == 1
    # Same winners -> same digest -> same forward names on restart: the
    # property the zero-cold warm start stands on.
    assert autotune.name_digest("infer", 32, 8) == digest


def test_autotune_candidates_tile_the_shape(clean_registry):
    autotune = clean_registry
    for bq, bk, g in autotune.candidates(64, 24):
        assert 64 % bq == 0 and 64 % bk == 0 and 24 % g == 0


def test_winners_file_lint_rules(clean_registry, tmp_path):
    autotune = clean_registry
    good = {"version": 1, "platform": "cpu", "interpret": True,
            "winners": {"infer:s32:bh8": {"block_q": 16, "block_k": 16,
                                          "bh_block": 2}}}
    assert autotune.validate_winners(good) == []
    bad_divide = json.loads(json.dumps(good))
    bad_divide["winners"]["infer:s32:bh8"]["block_q"] = 12
    assert any("does not divide" in e
               for e in autotune.validate_winners(bad_divide))
    bad_kernel = {"version": 1, "platform": "cpu", "interpret": True,
                  "winners": {"bogus:s32:bh8": {"block_q": 16,
                                                "block_k": 16,
                                                "bh_block": 2}}}
    assert any("unknown kernel" in e
               for e in autotune.validate_winners(bad_kernel))
    # a corrupt file fails LOUD on load, never silently detunes
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        json.dump(bad_divide, f)
    with pytest.raises(ValueError, match="malformed"):
        autotune.load_winners(path)


def test_winners_lint_via_check_all(clean_registry, tmp_path, capsys):
    """bert-lint validates winners JSONs alongside the telemetry
    artifacts (the CI/tooling satellite)."""
    from bert_pytorch_tpu.analysis import check_all

    autotune = clean_registry
    autotune.record_winner("infer", 32, 8, 16, 16, 2, measured_ms=0.5)
    good = str(tmp_path / "pallas_autotune.json")
    autotune.save_winners(good)
    assert check_all.main(["--skip-jaxlint", good]) == 0
    out = capsys.readouterr().out
    assert "autotune winners" in out
    bad = str(tmp_path / "bad_autotune.json")
    with open(bad, "w") as f:
        json.dump({"version": 99}, f)
    assert check_all.main(["--skip-jaxlint", bad]) == 1


def test_autotune_schema_kind_lint():
    from bert_pytorch_tpu.telemetry.schema import validate_record

    good = {"schema": 1, "ts": 0.0, "kind": "autotune", "kernel": "infer",
            "seq": 32, "bh": 8, "source": "measured",
            "winner": {"block_q": 16, "block_k": 16, "bh_block": 2}}
    assert validate_record(good) == []
    assert any("does not divide" in e for e in validate_record(
        dict(good, winner={"block_q": 12, "block_k": 16, "bh_block": 2})))
    assert any("source" in e for e in validate_record(
        dict(good, source="guessed")))
    # measured/cached provenance must carry the winner it claims
    bad = dict(good)
    del bad["winner"]
    assert any("requires a winner" in e for e in validate_record(bad))
    ok_heuristic = dict(bad, source="heuristic")
    assert validate_record(ok_heuristic) == []


# ---------------------------------------------------------------------------
# autotune: engine integration


def test_autotune_misconfiguration_fails_loud(config, tokenizer,
                                              tmp_path):
    """autotune without a winners path would silently degrade to the
    heuristic on restart, and autotune under a non-Pallas backend has
    nothing to tune — both pairings fail loud at construction instead
    of quietly serving an untuned engine."""
    import jax.numpy as jnp

    from bert_pytorch_tpu.serve import InferenceEngine

    def build(**kw):
        return InferenceEngine(config, tokenizer, {"classify": {}},
                               buckets=(BUCKET,), max_batch_size=2,
                               dtype=jnp.float32, **kw)

    with pytest.raises(ValueError, match="requires autotune_cache"):
        build(autotune="load", attention_backend="pallas_infer")
    with pytest.raises(ValueError, match="no geometry to tune"):
        build(autotune="measure",
              autotune_cache=str(tmp_path / "w.json"))  # default xla


def test_autotune_engine_records_names_and_cache(clean_registry, config,
                                                 tokenizer, tmp_path):
    """An autotune="measure" engine measures each bucket once, persists
    the winners, folds the digest into its forward names, and emits
    schema-valid autotune records; a second engine with
    autotune="load" reuses the winners (source="cached") and builds
    THE SAME names — the restart property."""
    from bert_pytorch_tpu.telemetry.schema import validate_record

    cache = str(tmp_path / "winners.json")
    eng = _engine(config, tokenizer, tasks={"classify": TASKS["classify"]},
                  attention_backend="pallas_infer",
                  autotune="measure", autotune_cache=cache)
    records = [e for e in eng.monitor.events
               if e.get("kind") == "autotune"]
    assert [r["source"] for r in records] == ["measured"]
    for rec in records:
        assert validate_record({"schema": 1, "ts": 0.0, **rec}) == []
    assert os.path.exists(cache)
    names = {e["fn"] for e in eng.monitor.events
             if e.get("kind") == "compile"}
    assert all("_g" in n for n in names), names

    eng2 = _engine(config, tokenizer,
                   tasks={"classify": TASKS["classify"]},
                   attention_backend="pallas_infer",
                   autotune="load", autotune_cache=cache)
    records2 = [e for e in eng2.monitor.events
                if e.get("kind") == "autotune"]
    assert [r["source"] for r in records2] == ["cached"]
    names2 = {e["fn"] for e in eng2.monitor.events
              if e.get("kind") == "compile"}
    assert names == names2
    r = eng2.run_direct("classify", {"text": "paris is big"})
    assert r["label"] in ("neg", "pos")


_CHILD_SCRIPT = """
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
import jax.numpy as jnp
from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache
assert enable_compile_cache(sys.argv[1], min_compile_secs=0.0)
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.serve import InferenceEngine
from bert_pytorch_tpu.data.tokenization import BertTokenizer
from bert_pytorch_tpu.tools.make_synthetic_data import TRACE_WORDS

vocab = 5 + len(TRACE_WORDS); vocab += (8 - vocab %% 8) %% 8
cfg = BertConfig(vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
                 num_attention_heads=4, intermediate_size=64,
                 max_position_embeddings=64, type_vocab_size=2,
                 next_sentence=True, hidden_dropout_prob=0.0,
                 attention_probs_dropout_prob=0.0)
tok = BertTokenizer(sys.argv[2], do_lower_case=True)
eng = InferenceEngine(cfg, tok, {"classify": {"labels": ["a", "b"]}},
                      buckets=(%(bucket)d,), max_batch_size=2,
                      dtype=jnp.float32, seed=11,
                      attention_backend="pallas_infer",
                      fuse_epilogues=True,
                      autotune=sys.argv[4], autotune_cache=sys.argv[3])
eng.warmup()
print("STARTUP " + json.dumps(eng.startup))
"""


def test_second_process_autotuned_start_zero_cold(clean_registry,
                                                  tmp_path, vocab_file):
    """THE warm-restart acceptance with autotune in the loop
    (ISSUE 14): process one MEASURES geometry, persists winners, and
    populates the AOT compile cache under digest-suffixed names; a
    fresh process LOADS the winners file and must warm entirely from
    the persistent cache — zero cold compiles by the cache counter
    events. This is what the same-keying discipline (winner digest in
    the fn-name-derived HLO module name) exists to guarantee."""
    cache_dir = str(tmp_path / "aot_cache")
    winners = str(tmp_path / "pallas_autotune.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))

    def start(mode):
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD_SCRIPT % {"bucket": BUCKET},
             cache_dir, vocab_file, winners, mode],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=REPO_ROOT)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("STARTUP ")][-1]
        return json.loads(line[len("STARTUP "):])

    first = start("measure")
    assert first["compiles_cold"] >= 1  # this process paid the compiles
    assert os.path.exists(winners)
    second = start("load")
    assert second["compiles_cold"] == 0, second
    assert second["compiles_warm"] >= 1, second
