"""K-FAC preconditioner tests (optim/kfac.py).

The reference has no tests; behaviors tested here come from the kfac_pytorch
semantics the reference drives (run_pretraining.py:320-355): factor EMA,
interval eigendecompositions, eigenbasis preconditioning with damping,
kl_clip trust scaling, checkpointable state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu import optim, pretrain
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.optim.kfac import KFACState, kfac_state_shardings
from bert_pytorch_tpu.parallel import MeshConfig, create_mesh, logical_axis_rules

# Heavyweight (module-scope model + many jit compiles on the virtual 8-device
# mesh): outside the tier-1 wallclock budget on a throttled CPU host. Run
# explicitly with `-m slow`.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def setup():
    config = BertConfig(
        vocab_size=64, hidden_size=16, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=32,
        max_position_embeddings=32, next_sentence=True)
    model = BertForPreTraining(config, dtype=jnp.float32)
    tapped = BertForPreTraining(config, dtype=jnp.float32, kfac_tap=True)
    variables = model.init(
        jax.random.PRNGKey(0), *(jnp.zeros((1, 16), jnp.int32),) * 3)
    import flax.linen as nn
    params = nn.unbox(variables)["params"]

    rng = np.random.default_rng(0)
    B, S = 8, 16
    mb = {
        "input_ids": rng.integers(0, 64, (B, S)).astype(np.int32),
        "segment_ids": np.zeros((B, S), np.int32),
        "input_mask": np.ones((B, S), np.int32),
        "masked_lm_labels": np.where(
            rng.random((B, S)) < 0.2,
            rng.integers(0, 64, (B, S)), -1).astype(np.int32),
        "next_sentence_labels": rng.integers(0, 2, (B,)).astype(np.int32),
    }
    apply_loss, tap_shape_fn = pretrain.make_kfac_fns(tapped, True)
    kfac = optim.KFAC(apply_loss, tap_shape_fn)
    kstate = kfac.init(params, mb)
    return config, model, params, mb, kfac, kstate


def test_spec_discovery(setup):
    """Tap set matches the reference's registered nn.Linear modules: q/k/v
    (shared input factor), attention output, MLP output — per scanned layer."""
    _, _, _, _, kfac, _ = setup
    g_keys = {s.g_key.rsplit("/", 1)[-1] for s in kfac.specs}
    assert g_keys == {"query__attn_in", "key__attn_in", "value__attn_in",
                      "output__attn_ctx", "output__mlp_in"}
    # q/k/v share one A factor
    a_of = {s.g_key.rsplit("/", 1)[-1]: s.a_key for s in kfac.specs}
    assert a_of["query__attn_in"] == a_of["key__attn_in"] == a_of["value__attn_in"]
    for s in kfac.specs:
        assert s.stacked  # encoder layers are scanned -> (L, d, d)


def test_factor_shapes_and_symmetry(setup):
    config, _, params, mb, kfac, kstate = setup
    kstate = kfac.update_factors(kstate, params, mb, jax.random.PRNGKey(1))
    L, H, I = (config.num_hidden_layers, config.hidden_size,
               config.intermediate_size)
    shapes = {k.rsplit("/", 1)[-1]: v.shape for k, v in kstate.a.items()}
    assert shapes["attn_in_a"] == (L, H + 1, H + 1)
    assert shapes["mlp_in_a"] == (L, I + 1, I + 1)
    for fac in list(kstate.a.values()) + list(kstate.g.values()):
        fac = np.asarray(jax.device_get(fac))
        assert np.allclose(fac, np.swapaxes(fac, -1, -2), atol=1e-4)
        # PSD: eigenvalues >= -tol
        w = np.linalg.eigvalsh(fac)
        assert w.min() > -1e-3
    assert int(kstate.count) == 1


def test_factor_ema(setup):
    """Second update blends with decay; first update overwrites zeros."""
    _, _, params, mb, kfac, kstate = setup
    s1 = kfac.update_factors(kstate, params, mb, jax.random.PRNGKey(1))
    s2 = kfac.update_factors(s1, params, mb, jax.random.PRNGKey(1))
    key = list(s1.a)[0]
    a1 = np.asarray(jax.device_get(s1.a[key]))
    a2 = np.asarray(jax.device_get(s2.a[key]))
    # same rng + same batch -> same new factor, so EMA is a no-op blend
    np.testing.assert_allclose(a2, a1, rtol=1e-4, atol=1e-5)
    assert int(s2.count) == 2


def test_precondition_identity_state(setup):
    """With Q=I, lambda=1 (the init state) preconditioning divides tapped
    grads by (1 + damping) then applies the kl_clip scale; untapped grads
    pass through untouched."""
    _, _, params, mb, kfac, kstate = setup
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    lr = 0.01
    out = jax.jit(kfac.precondition)(kstate, grads, lr)

    import flax.traverse_util as tu
    flat_in = tu.flatten_dict(grads)
    flat_out = tu.flatten_dict(out)

    tapped = set()
    vg_sum = 0.0
    for s in kfac.specs:
        tapped |= {s.kernel_path, s.bias_path}
        n = np.prod(flat_in[s.kernel_path].shape) + np.prod(
            flat_in[s.bias_path].shape)
        vg_sum += n / (1.0 + kfac.damping) * lr * lr
    nu = min(1.0, np.sqrt(kfac.kl_clip / vg_sum))
    expected = nu / (1.0 + kfac.damping)

    for path, g in flat_out.items():
        g = np.asarray(jax.device_get(g))
        if path in tapped:
            np.testing.assert_allclose(g, expected, rtol=1e-2)
        else:
            np.testing.assert_allclose(g, 1.0, rtol=1e-6)


def test_train_step_with_kfac(setup, devices):
    """Full sharded train step with preconditioning on the 8-device mesh."""
    config, model, _, mb, kfac, kstate = setup
    mesh = create_mesh(MeshConfig(data=-1))
    rules = logical_axis_rules("dp")
    schedule = optim.warmup_poly_schedule(1e-3, 0.1, 100)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    sample = (jnp.zeros((1, 16), jnp.int32),) * 3
    with mesh:
        shardings = pretrain.state_shardings(mesh, model, rules, sample)
        b_shardings = pretrain.batch_shardings(
            mesh, {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
                   "masked_lm_labels": 3, "next_sentence_labels": 2})
        state = pretrain.make_init_fn(model, tx, sample, shardings)(
            jax.random.PRNGKey(0))
        kshard = kfac_state_shardings(mesh, kstate)
        kstate_sh = jax.device_put(kstate, kshard)
        step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            shardings=shardings, batch_shardings_=b_shardings,
            kfac=kfac, kfac_shardings=kshard)
        batch = pretrain.put_batch(
            pretrain.stack_microbatches(mb, 1), b_shardings)
        mb0 = {k: v[0] for k, v in batch.items()}
        losses = []
        for i in range(4):
            kstate_sh = kfac.update_factors(
                kstate_sh, state.params, mb0, jax.random.PRNGKey(i))
            kstate_sh = kfac.update_inverses(kstate_sh)
            state, metrics = step(state, batch, kstate_sh)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


def test_kfac_requires_schedule(setup):
    _, model, _, _, kfac, _ = setup
    tx = optim.lamb(1e-3)
    with pytest.raises(ValueError, match="schedule"):
        pretrain.make_train_step(model, tx, schedule=None, kfac=kfac)


class TestFusedCapture:
    """In-train factor capture (the structural fix for the reference's
    free hook harvest, run_pretraining.py:320-355): the training step's
    own backward yields the factors — no separate stats forward/backward
    at factor_interval=1."""

    def _build(self, dropout=0.0):
        config = BertConfig(
            vocab_size=64, hidden_size=16, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=32,
            max_position_embeddings=32, next_sentence=True,
            hidden_dropout_prob=dropout, attention_probs_dropout_prob=dropout)
        model = BertForPreTraining(config, dtype=jnp.float32)
        tapped = BertForPreTraining(config, dtype=jnp.float32, kfac_tap=True)
        import flax.linen as nn
        params = nn.unbox(model.init(
            jax.random.PRNGKey(0), *(jnp.zeros((1, 16), jnp.int32),) * 3)
        )["params"]
        rng = np.random.default_rng(1)
        A, B, S = 2, 4, 16
        batch = {
            "input_ids": rng.integers(0, 64, (A, B, S)).astype(np.int32),
            "segment_ids": np.zeros((A, B, S), np.int32),
            "input_mask": np.ones((A, B, S), np.int32),
            "masked_lm_labels": np.where(
                rng.random((A, B, S)) < 0.2,
                rng.integers(0, 64, (A, B, S)), -1).astype(np.int32),
            "next_sentence_labels": rng.integers(
                0, 2, (A, B)).astype(np.int32),
        }
        apply_loss, tap_shape_fn = pretrain.make_kfac_fns(tapped, True)
        kfac = optim.KFAC(apply_loss, tap_shape_fn)
        mb0 = {k: v[0] for k, v in batch.items()}
        kstate = kfac.init(params, mb0)
        schedule = optim.warmup_poly_schedule(1e-3, 0.1, 100)
        tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
        state = pretrain.TrainState(
            params=params, opt_state=tx.init(params),
            rng=jax.random.PRNGKey(7))
        return model, tapped, tx, schedule, kfac, kstate, state, batch, mb0

    def test_fused_matches_stats_pass(self):
        """One fused step == stats-pass update_factors on mb0 (with the
        step's mb0 dropout rng) + the plain preconditioned step: same
        factors, same params, same loss."""
        (model, tapped, tx, schedule, kfac, kstate, state, batch, mb0
         ) = self._build(dropout=0.0)
        fused_step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            kfac=kfac, kfac_capture_model=tapped, kfac_factor_interval=1)
        plain_step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True, kfac=kfac)

        # Stats-pass reference first, on COPIES: both steps donate their
        # state (and the fused one its kfac_state), so the originals must
        # reach the fused call undeleted.
        copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
        kstate_s = kfac.update_factors(
            kstate, state.params, mb0, jax.random.PRNGKey(0))
        state_s, metrics_s = plain_step(copy(state), batch, kstate)
        state_f, metrics_f, kstate_f = fused_step(state, batch, kstate)

        assert float(metrics_f["loss"]) == pytest.approx(
            float(metrics_s["loss"]), rel=1e-5)
        assert int(kstate_f.count) == 1
        for key in kstate_f.g:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(kstate_f.g[key])),
                np.asarray(jax.device_get(kstate_s.g[key])),
                rtol=2e-4, atol=1e-5)
        for key in kstate_f.a:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(kstate_f.a[key])),
                np.asarray(jax.device_get(kstate_s.a[key])),
                rtol=2e-4, atol=1e-5)
        for pf, ps in zip(jax.tree_util.tree_leaves(state_f.params),
                          jax.tree_util.tree_leaves(state_s.params)):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(pf)),
                np.asarray(jax.device_get(ps)), rtol=1e-4, atol=1e-6)

    def test_interval_gates_capture(self):
        """factor_interval=2: steps at even opt counts capture, odd skip
        — and the skipped step still trains (params move)."""
        (model, tapped, tx, schedule, kfac, kstate, state, batch, _
         ) = self._build(dropout=0.0)
        step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            kfac=kfac, kfac_capture_model=tapped, kfac_factor_interval=2)
        state, _, kstate = step(state, batch, kstate)   # count 0: due
        assert int(kstate.count) == 1
        p_before = jax.device_get(state.params)
        state, _, kstate = step(state, batch, kstate)   # count 1: skip
        assert int(kstate.count) == 1
        state, _, kstate = step(state, batch, kstate)   # count 2: due
        assert int(kstate.count) == 2
        moved = jax.tree_util.tree_map(
            lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
            p_before, jax.device_get(state.params))
        assert max(jax.tree_util.tree_leaves(moved)) > 0

    def test_fused_requires_kfac(self):
        model, tapped, tx, schedule, *_ = self._build()
        with pytest.raises(ValueError, match="kfac_capture_model"):
            pretrain.make_train_step(
                model, tx, schedule=schedule, kfac_capture_model=tapped)

    def test_fused_in_jit_inverses_match_stats_flow(self):
        """kfac_inv_interval: an inverse-due fused step must equal the
        stats flow 'factors on full mb0 -> update_inverses -> step' —
        the kfac_pytorch optimizer.step() ordering, now with zero
        staleness and no host round trip."""
        (model, tapped, tx, schedule, kfac, kstate, state, batch, mb0
         ) = self._build(dropout=0.0)
        fused_step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            kfac=kfac, kfac_capture_model=tapped,
            kfac_factor_interval=1, kfac_inv_interval=1)
        plain_step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True, kfac=kfac)
        copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
        ks = kfac.update_factors(
            kstate, state.params, mb0, jax.random.PRNGKey(0))
        ks = kfac.update_inverses(ks)
        state_s, _ = plain_step(copy(state), batch, ks)
        state_f, _, ks_f = fused_step(state, batch, kstate)
        for key in ks_f.qa:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(ks_f.qa[key]), np.float32),
                np.asarray(jax.device_get(ks.qa[key]), np.float32),
                rtol=2e-2, atol=1e-4)
        for pf, ps in zip(jax.tree_util.tree_leaves(state_f.params),
                          jax.tree_util.tree_leaves(state_s.params)):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(pf)),
                np.asarray(jax.device_get(ps)), rtol=1e-4, atol=1e-6)

    def test_in_jit_inverses_require_fused(self):
        model, _, tx, schedule, kfac, *_ = self._build()
        with pytest.raises(ValueError, match="kfac_inv_interval"):
            pretrain.make_train_step(
                model, tx, schedule=schedule, kfac=kfac,
                kfac_inv_interval=10)

    def test_capture_all_microbatches(self):
        """kfac_capture_microbatches='all' (kfac_pytorch's accumulation
        semantics): with A=2 IDENTICAL microbatches and dropout off, the
        all-microbatch factors must equal the first-microbatch factors
        (both average the same rows), and the training trajectory must
        match the plain step's."""
        (model, tapped, tx, schedule, kfac, kstate, state, batch, mb0
         ) = self._build(dropout=0.0)
        dup = {k: np.stack([v[0], v[0]]) for k, v in batch.items()}
        first_step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            kfac=kfac, kfac_capture_model=tapped, kfac_factor_interval=1)
        all_step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            kfac=kfac, kfac_capture_model=tapped, kfac_factor_interval=1,
            kfac_capture_microbatches="all")
        plain_step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True, kfac=kfac)
        copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
        state_p, metrics_p = plain_step(copy(state), dup, kstate)
        _, _, ks_first = first_step(copy(state), dup, copy(kstate))
        state_a, metrics_a, ks_all = all_step(state, dup, kstate)
        assert int(ks_all.count) == 1
        for key in ks_all.g:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(ks_all.g[key])),
                np.asarray(jax.device_get(ks_first.g[key])),
                rtol=2e-4, atol=1e-5)
        for key in ks_all.a:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(ks_all.a[key])),
                np.asarray(jax.device_get(ks_first.a[key])),
                rtol=2e-4, atol=1e-5)
        assert float(metrics_a["loss"]) == pytest.approx(
            float(metrics_p["loss"]), rel=1e-6)
        for pa, pp in zip(jax.tree_util.tree_leaves(state_a.params),
                          jax.tree_util.tree_leaves(state_p.params)):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(pa)),
                np.asarray(jax.device_get(pp)), rtol=1e-5, atol=1e-7)

    def test_fused_matches_plain_step_with_dropout(self):
        """WITH dropout on, the fused step must train identically to the
        plain kfac step: the mb0 unroll's rng split chain
        (rng_rest, sub0 = split(step_rng)) mirrors the scan body's, so
        every microbatch sees the same dropout mask either way. Pins the
        parity claim in pretrain.py's fused branch."""
        (model, tapped, tx, schedule, kfac, kstate, state, batch, _
         ) = self._build(dropout=0.1)
        fused_step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            kfac=kfac, kfac_capture_model=tapped, kfac_factor_interval=1)
        plain_step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True, kfac=kfac)
        copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
        state_p, metrics_p = plain_step(copy(state), batch, kstate)
        state_f, metrics_f, _ = fused_step(state, batch, kstate)
        assert float(metrics_f["loss"]) == pytest.approx(
            float(metrics_p["loss"]), rel=1e-6)
        for pf, pp in zip(jax.tree_util.tree_leaves(state_f.params),
                          jax.tree_util.tree_leaves(state_p.params)):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(pf)),
                np.asarray(jax.device_get(pp)), rtol=1e-5, atol=1e-7)


def test_checkpoint_roundtrip(setup, tmp_path):
    """KFACState serializes through the checkpoint subsystem (reference
    'preconditioner' checkpoint entry, run_pretraining.py:519-520)."""
    _, _, params, mb, kfac, kstate = setup
    from bert_pytorch_tpu.utils import checkpoint as ckpt
    kstate = kfac.update_factors(kstate, params, mb, jax.random.PRNGKey(3))
    kstate = kfac.update_inverses(kstate)
    ckpt.save_checkpoint(str(tmp_path), 7, {"preconditioner": kstate})
    loaded = ckpt.load_checkpoint(ckpt.checkpoint_path(str(tmp_path), 7))
    fresh = kfac.init(params, mb)
    restored = ckpt.restore_tree(fresh, loaded["preconditioner"])
    for orig, back in zip(jax.tree_util.tree_leaves(kstate),
                          jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(orig)), np.asarray(jax.device_get(back)))


def test_cholesky_inverses_are_damped_factor_inverses(setup):
    """inv_method='cholesky' (default): after update_inverses, qa/qg hold
    (F + sqrt(damping) I)^-1 — check F_damped @ qa ≈ I."""
    _, _, params, mb, kfac, kstate = setup
    assert kfac.inv_method == "cholesky"
    kstate2 = kfac.update_factors(kstate, params, mb, jax.random.PRNGKey(3))
    kstate2 = kfac.update_inverses(kstate2)
    for key, fac in kstate2.a.items():
        fac = np.asarray(jax.device_get(fac), np.float64)
        inv = np.asarray(jax.device_get(kstate2.qa[key]), np.float64)
        eye = np.eye(fac.shape[-1])
        damped = fac + np.sqrt(kfac.damping) * eye
        prod = damped @ inv
        # bf16 storage of the inverse bounds the accuracy
        assert np.abs(prod - eye).max() < 0.1, key
        np.testing.assert_allclose(
            np.asarray(jax.device_get(kstate2.la[key])), 1.0)


def test_eigen_and_cholesky_agree_on_direction(setup):
    """Both inverse methods must produce similar preconditioned gradients
    (they differ only in how damping enters)."""
    config, model, params, mb, kfac, kstate = setup
    from bert_pytorch_tpu import pretrain
    tapped = BertForPreTraining(config, dtype=jnp.float32, kfac_tap=True)
    apply_loss, tap_shape_fn = pretrain.make_kfac_fns(tapped, True)
    kfac_e = optim.KFAC(apply_loss, tap_shape_fn, inv_method="eigen",
                        damping=kfac.damping, kl_clip=kfac.kl_clip)
    ke = kfac_e.init(params, mb)

    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    kc = kfac.update_inverses(kfac.update_factors(
        kstate, params, mb, jax.random.PRNGKey(5)))
    ke = kfac_e.update_inverses(kfac_e.update_factors(
        ke, params, mb, jax.random.PRNGKey(5)))
    pc = jax.jit(kfac.precondition)(kc, grads, 0.01)
    pe = jax.jit(kfac_e.precondition)(ke, grads, 0.01)
    import flax.traverse_util as tu
    fc, fe = tu.flatten_dict(pc), tu.flatten_dict(pe)
    for spec in kfac.specs:
        a = np.asarray(jax.device_get(fc[spec.kernel_path])).ravel()
        b = np.asarray(jax.device_get(fe[spec.kernel_path])).ravel()
        cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-30))
        # The damping enters differently (sqrt(damping) per factor vs
        # damping on the eigenvalue product), so directions drift on
        # ill-conditioned factors — this guards against sign flips and
        # garbage, not exact agreement.
        assert cos > 0.7, (spec.kernel_path, cos)


def test_pp_train_step_with_kfac_matches_dp(setup, devices):
    """K-FAC x pipeline: the preconditioned pp step must produce the same
    loss and updated params as the preconditioned dp step from identical
    initial state, factors, and data. Dropout is disabled for the
    comparison (the two paths fold step PRNGs differently). Closes the
    K-FAC composition asterisk (PARITY §2.2)."""
    config, _, _, mb, _, _ = setup
    cfg_dict = config.to_dict()
    cfg_dict["hidden_dropout_prob"] = 0.0
    cfg_dict["attention_probs_dropout_prob"] = 0.0
    cfg = BertConfig.from_dict(cfg_dict)
    model = BertForPreTraining(cfg, dtype=jnp.float32)
    tapped = BertForPreTraining(cfg, dtype=jnp.float32, kfac_tap=True)
    apply_loss, tap_shape_fn = pretrain.make_kfac_fns(tapped, True)
    schedule = optim.warmup_poly_schedule(1e-3, 0.1, 100)
    sample = (jnp.zeros((1, 16), jnp.int32),) * 3
    n_mb = 2
    host = pretrain.stack_microbatches(mb, n_mb)  # [2, 4, S] microbatches

    results = {}
    for name, meshcfg, strategy, seq_sharded in [
        ("dp", MeshConfig(data=4), "dp", False),
        ("pp", MeshConfig(data=2, pipe=2), "pp", False),
        ("pp_tp", MeshConfig(data=1, pipe=2, model=2), "pp_tp", False),
        # K-FAC x pp x sp: the preconditioner solve is a pure per-layer
        # function over the stacked factors, so it composes with the
        # {pipe, seq} manual region's gradients the same way it does with
        # pipe-only (the factor/inverse cadence runs outside the region).
        ("pp_sp", MeshConfig(data=1, pipe=2, seq=2), "pp", True),
    ]:
        mesh = create_mesh(meshcfg, devices=jax.devices()[:4])
        rules = logical_axis_rules(strategy)
        kfac = optim.KFAC(apply_loss, tap_shape_fn)
        tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
        with mesh:
            shardings = pretrain.state_shardings(mesh, model, rules, sample)
            b_shardings = pretrain.batch_shardings(
                mesh, {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
                       "masked_lm_labels": 3, "next_sentence_labels": 2},
                seq_sharded=seq_sharded)
            state = pretrain.make_init_fn(model, tx, sample, shardings)(
                jax.random.PRNGKey(7))
            kstate = kfac.init(jax.device_get(state.params), mb)
            kshard = kfac_state_shardings(mesh, kstate)
            kstate = jax.device_put(kstate, kshard)
            kstate = kfac.update_factors(
                kstate, state.params, mb, jax.random.PRNGKey(13))
            kstate = kfac.update_inverses(kstate)
            if name.startswith("pp"):
                step = pretrain.make_pp_train_step(
                    model, tx, mesh, schedule=schedule, next_sentence=True,
                    shardings=shardings, batch_shardings_=b_shardings,
                    max_pred_per_seq=8, kfac=kfac, kfac_shardings=kshard)
            else:
                step = pretrain.make_train_step(
                    model, tx, schedule=schedule, next_sentence=True,
                    shardings=shardings, batch_shardings_=b_shardings,
                    max_pred_per_seq=8, kfac=kfac, kfac_shardings=kshard)
            batch = pretrain.put_batch(host, b_shardings)
            new_state, metrics = step(state, batch, kstate)
            results[name] = (float(metrics["loss"]),
                             jax.device_get(new_state.params))

    loss_dp, params_dp = results["dp"]
    flat_dp = jax.tree_util.tree_leaves_with_path(params_dp)
    for name in ("pp", "pp_tp", "pp_sp"):
        loss_x, params_x = results[name]
        np.testing.assert_allclose(loss_x, loss_dp, rtol=1e-5, err_msg=name)
        flat_x = dict(
            (jax.tree_util.keystr(kp), leaf)
            for kp, leaf in jax.tree_util.tree_leaves_with_path(params_x))
        for kp, leaf in flat_dp:
            np.testing.assert_allclose(
                np.asarray(flat_x[jax.tree_util.keystr(kp)]),
                np.asarray(leaf),
                atol=2e-5, err_msg=f"{name} {jax.tree_util.keystr(kp)}")
