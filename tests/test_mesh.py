"""MeshConfig / create_mesh unit coverage (the multi-process integration
legs live in tests/test_multihost.py)."""

import pytest

from bert_pytorch_tpu.parallel import MeshConfig, create_mesh


def test_resolve_dcn_divides_data_axis():
    # 16 devices, dcn_data=2: the ICI granule holds 8-way data parallelism.
    assert MeshConfig(dcn_data=2).resolve(16) == (8, 1, 1, 1, 1)
    # explicit data size is the PER-GRANULE size
    assert MeshConfig(data=4, dcn_data=2, model=2).resolve(16) == \
        (4, 1, 1, 1, 2)


def test_resolve_dcn_divisibility_errors():
    with pytest.raises(ValueError, match="dcn_data"):
        MeshConfig(dcn_data=3).resolve(16)
    with pytest.raises(ValueError, match="dcn"):
        MeshConfig(data=8, dcn_data=2).resolve(8)


def test_create_mesh_dcn_needs_granules(devices):
    # Single-process CPU: one process granule cannot satisfy dcn_data=2.
    with pytest.raises(ValueError, match="[Nn]umber of slices"):
        create_mesh(MeshConfig(dcn_data=2, dcn_process_granule=True))


def test_create_mesh_plain_shapes(devices):
    import jax

    mesh = create_mesh(MeshConfig(data=2, seq=2, model=2),
                       devices=jax.devices()[:8])
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 2, "fsdp": 1, "pipe": 1, "seq": 2, "model": 2}
