"""Model library tests: shapes, scan param layout, determinism, head parity.

The reference has no test suite (SURVEY.md §4); these tests encode the
documented behaviors of src/modeling.py instead.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu import models


def _batch(cfg, batch=2, seq=16, rng=0):
    r = np.random.default_rng(rng)
    input_ids = r.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    token_type_ids = r.integers(0, 2, (batch, seq), dtype=np.int32)
    mask = np.ones((batch, seq), dtype=np.int32)
    mask[:, seq - 3 :] = 0
    return jnp.asarray(input_ids), jnp.asarray(token_type_ids), jnp.asarray(mask)


def test_pretraining_forward_shapes(tiny_config):
    cfg = tiny_config
    model = models.BertForPreTraining(cfg, dtype=jnp.float32)
    ids, types, mask = _batch(cfg)
    variables = model.init(jax.random.PRNGKey(0), ids, types, mask)
    mlm_logits, nsp_logits = model.apply(variables, ids, types, mask)
    assert mlm_logits.shape == (2, 16, cfg.vocab_size)
    assert nsp_logits.shape == (2, 2)


def test_encoder_params_are_stacked_by_scan(tiny_config):
    cfg = tiny_config
    model = models.BertForPreTraining(cfg, dtype=jnp.float32)
    ids, types, mask = _batch(cfg)
    variables = model.init(jax.random.PRNGKey(0), ids, types, mask)
    params = nn.unbox(variables)["params"]
    layer_params = params["bert"]["encoder"]["layers"]
    q_kernel = layer_params["attention"]["query"]["kernel"]
    # nn.scan stacks per-layer params on a leading 'layers' axis.
    assert q_kernel.shape == (
        cfg.num_hidden_layers,
        cfg.hidden_size,
        cfg.num_attention_heads,
        cfg.head_dim,
    )


def test_tied_decoder_has_no_duplicate_weight(tiny_config):
    """The MLM decoder weight IS the embedding matrix (modeling.py:570-574):
    only a bias param may exist in the prediction head."""
    cfg = tiny_config
    model = models.BertForPreTraining(cfg, dtype=jnp.float32)
    ids, types, mask = _batch(cfg)
    variables = model.init(jax.random.PRNGKey(0), ids, types, mask)
    pred = nn.unbox(variables)["params"]["predictions"]
    assert set(pred.keys()) == {"transform", "bias"}
    assert pred["bias"].shape == (cfg.vocab_size,)


def test_next_sentence_false_drops_nsp_and_pooler(tiny_config):
    cfg = BertConfig.from_dict({**tiny_config.to_dict(), "next_sentence": False})
    model = models.BertForPreTraining(cfg, dtype=jnp.float32)
    ids, _, mask = _batch(cfg)
    variables = model.init(jax.random.PRNGKey(0), ids, None, mask)
    mlm_logits, nsp_logits = model.apply(variables, ids, None, mask)
    assert nsp_logits is None
    params = variables["params"]
    assert "seq_relationship" not in params
    assert "pooler" not in params["bert"]
    assert "token_type_embeddings" not in params["bert"]["embeddings"]


def test_attention_mask_blocks_padding(tiny_config):
    """Changing tokens at masked-out positions must not change outputs at
    attended positions (extended_attention_mask semantics,
    modeling.py:862-870)."""
    cfg = tiny_config
    model = models.BertModel(cfg, dtype=jnp.float32)
    ids, types, mask = _batch(cfg)
    variables = model.init(jax.random.PRNGKey(0), ids, types, mask)
    seq_out, _ = model.apply(variables, ids, types, mask)
    ids2 = ids.at[:, -1].set((ids[:, -1] + 7) % cfg.vocab_size)
    seq_out2, _ = model.apply(variables, ids2, types, mask)
    np.testing.assert_allclose(
        np.asarray(seq_out[:, :13]), np.asarray(seq_out2[:, :13]), atol=1e-5
    )


def test_dropout_determinism(tiny_config):
    cfg = tiny_config
    model = models.BertForPreTraining(cfg, dtype=jnp.float32)
    ids, types, mask = _batch(cfg)
    variables = model.init(jax.random.PRNGKey(0), ids, types, mask)
    out1, _ = model.apply(
        variables, ids, types, mask, False,
        rngs={"dropout": jax.random.PRNGKey(1)},
    )
    out2, _ = model.apply(
        variables, ids, types, mask, False,
        rngs={"dropout": jax.random.PRNGKey(1)},
    )
    out3, _ = model.apply(
        variables, ids, types, mask, False,
        rngs={"dropout": jax.random.PRNGKey(2)},
    )
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))
    assert not np.allclose(np.asarray(out1), np.asarray(out3))


def test_remat_matches_no_remat(tiny_config):
    cfg = tiny_config
    ids, types, mask = _batch(cfg)
    m1 = models.BertForPreTraining(cfg, dtype=jnp.float32, remat="none")
    m2 = models.BertForPreTraining(cfg, dtype=jnp.float32, remat="full")
    v = m1.init(jax.random.PRNGKey(0), ids, types, mask)
    o1, _ = m1.apply(v, ids, types, mask)
    o2, _ = m2.apply(v, ids, types, mask)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


@pytest.mark.parametrize(
    "head_cls,kwargs,out_check",
    [
        (models.BertForMaskedLM, {}, lambda o, cfg: o.shape == (2, 16, cfg.vocab_size)),
        (models.BertForNextSentencePrediction, {}, lambda o, cfg: o.shape == (2, 2)),
        (
            models.BertForSequenceClassification,
            {"num_labels": 3},
            lambda o, cfg: o.shape == (2, 3),
        ),
        (
            models.BertForTokenClassification,
            {"num_labels": 5},
            lambda o, cfg: o.shape == (2, 16, 5),
        ),
    ],
)
def test_task_heads(tiny_config, head_cls, kwargs, out_check):
    cfg = tiny_config
    model = head_cls(cfg, dtype=jnp.float32, **kwargs)
    ids, types, mask = _batch(cfg)
    variables = model.init(jax.random.PRNGKey(0), ids, types, mask)
    out = model.apply(variables, ids, types, mask)
    assert out_check(out, cfg)


def test_question_answering_head(tiny_config):
    cfg = tiny_config
    model = models.BertForQuestionAnswering(cfg, dtype=jnp.float32)
    ids, types, mask = _batch(cfg)
    variables = model.init(jax.random.PRNGKey(0), ids, types, mask)
    start, end = model.apply(variables, ids, types, mask)
    assert start.shape == (2, 16) and end.shape == (2, 16)


def test_multiple_choice_head(tiny_config):
    cfg = tiny_config
    model = models.BertForMultipleChoice(cfg, num_choices=4, dtype=jnp.float32)
    r = np.random.default_rng(0)
    ids = jnp.asarray(r.integers(0, cfg.vocab_size, (2, 4, 16), dtype=np.int32))
    types = jnp.zeros_like(ids)
    mask = jnp.ones_like(ids)
    variables = model.init(jax.random.PRNGKey(0), ids, types, mask)
    out = model.apply(variables, ids, types, mask)
    assert out.shape == (2, 4)


def test_losses():
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 32)), jnp.float32)
    labels = np.full((2, 8), -1, np.int32)
    labels[0, 2] = 5
    labels[1, 7] = 9
    loss = models.masked_lm_loss(logits, jnp.asarray(labels))
    assert loss.shape == () and float(loss) > 0
    # all-ignored -> zero loss, no NaN
    loss0 = models.masked_lm_loss(logits, jnp.full((2, 8), -1, jnp.int32))
    assert float(loss0) == 0.0

    nsp_logits = jnp.asarray([[2.0, -1.0], [0.5, 0.5]], jnp.float32)
    nsp = models.next_sentence_loss(nsp_logits, jnp.asarray([0, 1]))
    assert float(nsp) > 0

    start = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8)), jnp.float32)
    end = jnp.asarray(np.random.default_rng(2).normal(size=(2, 8)), jnp.float32)
    sl = models.span_loss(start, end, jnp.asarray([1, 20]), jnp.asarray([2, 20]))
    assert np.isfinite(float(sl))
