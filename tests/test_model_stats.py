"""Model-internals & memory observability units (ISSUE 2,
docs/telemetry.md): in-jit grad-health reduction + cadence gating, the
divergence early-warning policy, the memory sampler's supported/
unsupported paths, and the static per-executable cost attribution."""

import numpy as np
import pytest

from bert_pytorch_tpu.telemetry import memory as memory_mod
from bert_pytorch_tpu.telemetry import model_stats
from bert_pytorch_tpu.telemetry import schema as tschema
from bert_pytorch_tpu.telemetry.model_stats import (DivergenceError,
                                                    DivergenceMonitor)


def _tree(scale=1.0):
    import jax.numpy as jnp

    return {
        "bert": {
            "embeddings": {"word_embeddings": jnp.full((4, 2), scale)},
            "encoder": {"layers": {
                "kernel": jnp.full((3, 2, 2), scale),  # stacked [L, ...]
                "bias": jnp.full((3, 2), scale),
            }},
        },
        "qa_outputs": {"kernel": jnp.full((2, 2), scale)},
    }


# -- grad_health reduction ----------------------------------------------


def test_grad_health_groups_and_per_layer():
    health = model_stats.grad_health(
        _tree(2.0), _tree(1.0), _tree(0.5))
    assert set(health["groups"]) == {
        "bert/embeddings", "bert/encoder", "qa_outputs"}
    # bert/embeddings: 8 grad entries of 1.0 -> norm sqrt(8); params 2.0
    emb = health["groups"]["bert/embeddings"]
    assert float(emb["grad_norm"]) == pytest.approx(np.sqrt(8))
    assert float(emb["param_norm"]) == pytest.approx(np.sqrt(8 * 4))
    # update_ratio = ||0.5 * ones|| / ||2.0 * ones|| = 0.25 per group
    assert float(emb["update_ratio"]) == pytest.approx(0.25, rel=1e-5)
    assert float(health["update_ratio"]) == pytest.approx(0.25, rel=1e-5)
    # global norm = sqrt(total leaves) over 8+6+3+4=... every leaf is 1.0
    n_entries = 8 + 12 + 6 + 4
    assert float(health["grad_norm"]) == pytest.approx(np.sqrt(n_entries))
    # stacked encoder: per-layer vector of length L=3, each layer holds
    # 4 kernel + 2 bias unit entries -> norm sqrt(6)
    per_layer = np.asarray(health["per_layer_grad_norm"])
    assert per_layer.shape == (3,)
    np.testing.assert_allclose(per_layer, np.sqrt(6.0), rtol=1e-5)


def test_grad_health_grad_scale_divides_grad_norms_only():
    plain = model_stats.grad_health(_tree(2.0), _tree(1.0), _tree(0.5))
    scaled = model_stats.grad_health(
        _tree(2.0), _tree(1.0), _tree(0.5), grad_scale=4.0)
    assert float(scaled["grad_norm"]) == pytest.approx(
        float(plain["grad_norm"]) / 4.0)
    assert float(scaled["param_norm"]) == pytest.approx(
        float(plain["param_norm"]))
    assert float(scaled["update_ratio"]) == pytest.approx(
        float(plain["update_ratio"]))


def test_gated_grad_health_cadence_inside_jit():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(count):
        return model_stats.gated_grad_health(
            _tree(2.0), _tree(1.0), _tree(0.5), count, every=3)

    due = step(jnp.int32(0))
    off = step(jnp.int32(1))
    assert float(due["due"]) == 1.0 and float(due["grad_norm"]) > 0
    # Off-cadence: the cond's zero branch — values are zeros, flag says so.
    assert float(off["due"]) == 0.0 and float(off["grad_norm"]) == 0.0
    assert model_stats.gated_grad_health(
        _tree(1.0), _tree(1.0), _tree(1.0), 0, every=0) is None

    # Resumed runs: `phase` (the count at run start) rebases the gate onto
    # the host's run-local 0-based cadence — count 250 with phase 250 is
    # due, count 252 is not.
    @jax.jit
    def resumed(count):
        return model_stats.gated_grad_health(
            _tree(2.0), _tree(1.0), _tree(0.5), count, every=4, phase=250)

    assert float(resumed(jnp.int32(250))["due"]) == 1.0
    assert float(resumed(jnp.int32(252))["due"]) == 0.0
    assert float(resumed(jnp.int32(254))["due"]) == 1.0


def test_health_record_is_schema_valid():
    health = model_stats.grad_health(_tree(2.0), _tree(1.0), _tree(0.5))
    record = model_stats.health_record(7, health)
    assert record["step"] == 7
    full = {"schema": tschema.SCHEMA_VERSION, "ts": 0.0, **record}
    assert tschema.validate_record(full) == []
    # everything JSON-serializable (floats/lists, no device arrays)
    import json

    json.dumps(record)


# -- divergence monitor -------------------------------------------------


def test_divergence_spike_and_abort():
    emitted = []
    mon = DivergenceMonitor(emit=emitted.append, policy="abort",
                            patience=2, spike_factor=5.0, ratio_max=0.0,
                            warmup=3)
    for step in range(5):
        assert mon.observe(step, 1.0, 0.001)
    assert mon.observe(5, 2.0, 0.001)   # 2x EMA: under the 5x bar
    assert not mon.observe(6, 50.0, 0.001)  # spike
    assert emitted[-1]["reason"] == "grad_norm_spike"
    with pytest.raises(DivergenceError):
        mon.observe(7, 500.0, 0.001)    # second consecutive -> abort
    assert all(r["kind"] == "divergence" for r in emitted)
    for rec in emitted:
        full = {"schema": tschema.SCHEMA_VERSION, "ts": 0.0, **rec}
        assert tschema.validate_record(full) == []


def test_divergence_plateau_still_aborts():
    """The EMA must not absorb warned observations: a diverged-but-
    plateaued grad norm has to keep warning until patience aborts,
    not warn once and then normalize its own threshold."""
    mon = DivergenceMonitor(policy="abort", patience=3, spike_factor=5.0,
                            ratio_max=0.0, warmup=2)
    for step in range(3):
        assert mon.observe(step, 1.0)
    assert not mon.observe(3, 50.0)
    assert not mon.observe(4, 50.0)  # same plateau: EMA frozen, still warns
    with pytest.raises(DivergenceError):
        mon.observe(5, 50.0)


def test_divergence_warmup_suppresses_early_spikes():
    emitted = []
    mon = DivergenceMonitor(emit=emitted.append, spike_factor=2.0,
                            ratio_max=0.0, warmup=10)
    # step-0 norms are legitimately wild; no warning inside the warmup
    assert mon.observe(0, 100.0)
    assert mon.observe(1, 1.0)
    assert emitted == []


def test_divergence_update_ratio_and_recovery():
    emitted = []
    mon = DivergenceMonitor(emit=emitted.append, policy="continue",
                            spike_factor=0.0, ratio_max=0.5)
    assert not mon.observe(1, 1.0, update_ratio=0.9)
    assert emitted[0]["reason"] == "update_ratio_high"
    assert mon.observe(2, 1.0, update_ratio=0.1)  # recovery resets
    assert mon.consecutive == 0
    # non-finite norms are the sentinel's signal, not a spike
    assert mon.observe(3, float("nan"), update_ratio=0.1)


def test_divergence_rejects_unknown_policy():
    with pytest.raises(ValueError):
        DivergenceMonitor(policy="explode")


# -- memory sampler -----------------------------------------------------


class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_memory_sampler_unsupported_emits_single_note(monkeypatch):
    import jax

    monkeypatch.setattr(jax, "local_devices",
                        lambda: [_FakeDevice(None)])
    emitted = []
    sampler = memory_mod.MemorySampler(emit=emitted.append)
    for step in range(5):
        sampler.sample(step)
    sampler.flush(5)
    assert len(emitted) == 1  # ONE note, not a warning storm
    assert emitted[0]["memory_supported"] is False
    full = {"schema": tschema.SCHEMA_VERSION, "ts": 0.0, **emitted[0]}
    assert tschema.validate_record(full) == []


def test_memory_sampler_window_aggregation(monkeypatch):
    import jax

    readings = iter([
        {"bytes_in_use": 100, "peak_bytes_in_use": 150, "bytes_limit": 1000},
        {"bytes_in_use": 300, "peak_bytes_in_use": 400, "bytes_limit": 1000},
        {"bytes_in_use": 200, "peak_bytes_in_use": 400, "bytes_limit": 1000},
    ])
    monkeypatch.setattr(
        jax, "local_devices", lambda: [_FakeDevice(next(readings))])
    emitted = []
    sampler = memory_mod.MemorySampler(emit=emitted.append)
    for step in (1, 2, 3):
        sampler.sample(step)
    record = sampler.flush(3)
    assert record is emitted[0] is not None
    assert record["memory_supported"] is True
    assert record["samples"] == 3
    assert record["bytes_in_use"] == 200       # last
    assert record["bytes_in_use_max"] == 300   # max live
    assert record["peak_bytes_in_use"] == 400  # allocator high-water
    assert record["bytes_limit"] == 1000
    full = {"schema": tschema.SCHEMA_VERSION, "ts": 0.0, **record}
    assert tschema.validate_record(full) == []
    # window reset: nothing left to flush
    assert sampler.flush(4) is None
    # non-primary ranks never emit
    quiet = memory_mod.MemorySampler(emit=emitted.append, enabled=False)
    quiet.sample(1)
    assert len(emitted) == 1


# -- static cost attribution --------------------------------------------


def test_analyze_executable_full_and_off():
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.ones((16, 16))
    fn(x)
    fields = memory_mod.analyze_executable(fn, (x,), {}, mode="full")
    assert fields["analysis"] == "compiled"
    assert fields["flops"] > 0 and fields["bytes_accessed"] > 0
    assert fields["argument_bytes"] == x.size * 4
    assert "temp_bytes" in fields
    assert memory_mod.analyze_executable(fn, (x,), {}, mode="off") is None
    # Not an AOT-capable callable: attribution declines, never raises.
    assert memory_mod.analyze_executable(
        lambda x: x, (x,), {}, mode="full") is None
    with pytest.raises(ValueError):
        memory_mod.analyze_executable(fn, (x,), {}, mode="bogus")


def test_analyze_executable_after_donation():
    """Attribution runs after the instrumented call, when donated args
    are already deleted — lowering needs only aval metadata."""
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda s, b: s + b.sum(), donate_argnums=(0,))
    s, b = jnp.ones((4,)), jnp.ones((3,))
    fn(s, b)  # s is deleted now
    fields = memory_mod.analyze_executable(fn, (s, b), {}, mode="auto")
    assert fields is not None and fields["flops"] >= 0


def test_compile_monitor_emits_cost_records():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.telemetry.compile_events import CompileMonitor

    emitted = []
    monitor = CompileMonitor(emit=emitted.append, cost_analysis="auto")
    fn = monitor.instrument(jax.jit(lambda x: x * 2.0 + 1.0), "probe")
    fn(jnp.arange(5, dtype=jnp.float32))
    kinds = [r["kind"] for r in emitted]
    assert kinds.count("compile") == 1
    assert kinds.count("compile_cost") == 1
    cost = next(r for r in emitted if r["kind"] == "compile_cost")
    compile_rec = next(r for r in emitted if r["kind"] == "compile")
    assert cost["shapes_digest"] == compile_rec["shapes_digest"]
    assert cost["fn"] == "probe"
    # steady-state call: no new records of either kind
    fn(jnp.arange(5, dtype=jnp.float32))
    assert len(emitted) == 2
    # new shapes: one more of each, attribution stays one-shot per digest
    fn(jnp.arange(7, dtype=jnp.float32))
    assert [r["kind"] for r in emitted].count("compile_cost") == 2
