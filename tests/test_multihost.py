"""Real multi-process distributed integration (2 processes x 4 CPU devices).

Goes beyond the virtual-mesh tests: an actual jax.distributed rendezvous,
a mesh spanning both processes, and put_batch's
make_array_from_process_local_data path (each process contributes its local
slice of the global batch) — the TPU analog of the reference's multi-process
Gloo harness (src/dataset.py:431-505), but running the full train step.
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "_mh_worker.py")

_JAX_VERSION = tuple(int(x) for x in jax.__version__.split(".")[:2])
# Every test in this module spawns a real 2-process jax.distributed job on
# CPU devices; on jax 0.4.x the legacy shard_map path those collectives
# lower through hits XLA's "PartitionId unsupported for SPMD" (the same
# gate as test_pipeline's gpipe tests — see CHANGES.md PR 1). Skipping with
# an explicit version gate keeps tier-1 red meaning NEW regression only.
pytestmark = pytest.mark.skipif(
    _JAX_VERSION < (0, 5),
    reason="multi-process CPU collectives need jax>=0.5 "
           f"(running {jax.__version__}: legacy shard_map lowers to XLA "
           "'PartitionId unsupported for SPMD')",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(mode, extra_args=()):
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS",)}  # worker sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, "2", str(rank), mode,
             *extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, out[-2000:]
    # both ranks computed the same global losses (the allreduce worked)
    lines = [next(l for l in out.splitlines() if "OK losses" in l)
             for out in outs]
    assert lines[0].split("losses=")[1] == lines[1].split("losses=")[1], lines
    return outs


def test_two_process_training():
    _run_workers("dp")


def test_two_process_fsdp_checkpoint_roundtrip(tmp_path):
    """Multi-host fsdp: params sharded ACROSS processes, checkpoint saved
    via the process_allgather collective, restored, and step-equivalent
    (VERDICT r1 missing #4 / SURVEY §5.4)."""
    outs = _run_workers("fsdp", (str(tmp_path),))
    for out in outs:
        assert "CKPT OK" in out, out[-2000:]


def test_two_process_pipeline():
    """GPipe 'pipe' axis spanning two real processes: the worker lays the
    mesh out so stage 0 is process 0 and stage 1 is process 1, making the
    stage-to-stage ppermute cross the process boundary."""
    _run_workers("pp")


def test_two_process_pipeline_tensor_parallel():
    """pp_tp with the cross-process pipe layout: the pipe ppermute crosses
    the process boundary while each stage's compiler-inserted
    tensor-parallel collectives run intra-process."""
    _run_workers("pp_tp")


def test_two_process_sequence_parallel():
    """Multi-host long context, production layout: per-rank loader slices
    over the host-splitting 'data' axis, ring attention over the
    intra-host 'seq' axis, locality check green."""
    _run_workers("sp")


def test_two_process_pipeline_sequence_parallel():
    """pp x sp across two real processes: the {pipe, seq} manual region's
    stage-to-stage ppermute crosses the process boundary while the ring
    K/V rotation stays intra-process (the ICI-friendly layout)."""
    _run_workers("pp_sp")


def test_two_process_dcn_hybrid_mesh():
    """Multi-slice recipe on the CPU analog (process = slice granule):
    MeshConfig(dcn_data=2) builds the hybrid device mesh, data parallelism
    spans the DCN granule boundary, and both ranks agree on losses."""
    _run_workers("dcn")


def test_two_process_kfac():
    """Distributed K-FAC across two real processes: factor statistics,
    batched inverses, and preconditioned steps all agree across ranks."""
    _run_workers("kfac")


def test_two_process_kfac_fused():
    """Fused in-train factor capture + in-jit inverse rebuilds across two
    real processes — the complete K-FAC flow as one compiled step with
    process-spanning factor-stack shardings."""
    _run_workers("kfac_fused")
