"""Real multi-process distributed integration (2 processes x 4 CPU devices).

Goes beyond the virtual-mesh tests: an actual jax.distributed rendezvous,
a mesh spanning both processes, and put_batch's
make_array_from_process_local_data path (each process contributes its local
slice of the global batch) — the TPU analog of the reference's multi-process
Gloo harness (src/dataset.py:431-505), but running the full train step.
"""

import os
import socket
import subprocess
import sys

WORKER = os.path.join(os.path.dirname(__file__), "_mh_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_training():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS",)}  # worker sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, "2", str(rank)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, out[-2000:]
    # both ranks computed the same global losses (the allreduce worked)
    lines = [next(l for l in out.splitlines() if "OK losses" in l)
             for out in outs]
    assert lines[0].split("losses=")[1] == lines[1].split("losses=")[1], lines
