"""Fleet observatory unit tests (ISSUE 12, docs/observability.md):
the training introspection plane (hub state machine, /metricsz vs JSONL
agreement), the flight recorder (byte bound, incident/periodic/crash
flush semantics, torn-write safety), the fleet collector (deterministic
merge under out-of-order timestamps, black-holed-target concurrency and
staleness, fleet-window aggregation), the supervisor's heartbeat +
postmortem harvest, the router's /metricsz, and the telemetry-report
fleet section with its two named gates.

The end-to-end proof — real replicas + router + a live trainer plane,
SIGKILL mid-burst, harvested postmortem in the one fleet timeline — is
tests/test_observatory_e2e.py."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from bert_pytorch_tpu.serve.router import Router
from bert_pytorch_tpu.serve.supervisor import ReplicaSpec, Supervisor
from bert_pytorch_tpu.telemetry import report, schema
from bert_pytorch_tpu.telemetry.collector import (FleetCollector,
                                                  JsonlTailer, Target,
                                                  parse_prometheus)
from bert_pytorch_tpu.telemetry.flightrec import (FlightRecorder,
                                                  read_postmortem)
from bert_pytorch_tpu.telemetry.introspect import (IntrospectionHub,
                                                   start_debug_server)
from bert_pytorch_tpu.telemetry.runner import TrainTelemetry
from bert_pytorch_tpu.utils.retry import RetryPolicy

HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(HERE)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode("utf-8")


# ---------------------------------------------------------------------------
# telemetry/introspect.py: the hub + the debug plane


def test_hub_healthz_warming_ok_stale():
    clock = FakeClock()
    hub = IntrospectionHub(process="pretrain", stale_after_s=10.0,
                           clock=clock)
    code, body = hub.healthz()
    assert (code, body["status"]) == (200, "warming")
    hub.note_step(3, loss=2.0)
    clock.advance(5.0)
    code, body = hub.healthz()
    assert (code, body["status"]) == (200, "ok")
    assert body["step"] == 3 and body["last_loss"] == 2.0
    clock.advance(10.1)
    code, body = hub.healthz()
    assert (code, body["status"]) == (503, "stale")
    assert body["step_age_s"] > 10.0
    # A new step re-arms liveness (the re-heal path).
    hub.note_step(4)
    assert hub.healthz()[0] == 200


def test_hub_counters_fold_record_kinds():
    hub = IntrospectionHub()
    hub.observe_record({"kind": "compile", "fn": "f", "compile_s": 1.5,
                        "cache": "miss"})
    hub.observe_record({"kind": "compile", "fn": "f", "compile_s": 0.0,
                        "cache": "hit"})
    hub.observe_record({"kind": "sentinel", "step": 4})
    hub.observe_record({"kind": "divergence", "step": 5})
    hub.observe_record({"kind": "fault", "fault": "hung_step"})
    stats = hub.statsz()
    assert stats["compiles"] == 2
    assert stats["compile_cache"] == {"miss": 1, "hit": 1}
    assert stats["nonfinite_steps"] == 1
    assert stats["divergence_warnings"] == 1
    assert stats["faults"] == 1
    assert stats["records"] == 5


def test_debug_plane_metricsz_agrees_with_jsonl_window(tmp_path):
    """THE tentpole consistency property: every numeric field of the
    last step_window record in the JSONL artifact appears on /metricsz
    as bert_train_window_<field> with the IDENTICAL value (nested
    loader gauges as bert_train_loader_<field>) — the scrape surface
    and the offline artifact cannot drift."""
    jsonl = tmp_path / "train_telemetry.jsonl"
    hub = IntrospectionHub(process="unit")
    tele = TrainTelemetry(jsonl_path=str(jsonl), window=10, sync_every=1,
                          introspect=hub)
    tele.attach_loader(type("L", (), {"snapshot": staticmethod(
        lambda: {"batches": 7, "wait_s_total": 0.25, "stalls": 1,
                 "depth_max": 3})})())
    server = start_debug_server(hub, port=0)
    try:
        for step in range(1, 24):
            tele.timer.data_start()
            tele.timer.data_end()
            tele.dispatch_done()
            tele.step_done(step, {"loss": 2.0 + 0.01 * step})
        host, port = server.server_address[:2]
        code, text = _get(f"http://{host}:{port}/metricsz")
        assert code == 200
        gauges = {name: value
                  for name, labels, value in parse_prometheus(text)}
        windows = [rec for rec in report.iter_records(str(jsonl))
                   if rec.get("kind") == "step_window"]
        assert len(windows) == 2  # 23 steps, window 10
        last = windows[-1]
        checked = 0
        for key, value in last.items():
            if key in ("kind", "tag", "schema", "ts"):
                continue
            if isinstance(value, (int, float)) and \
                    not isinstance(value, bool):
                assert gauges[f"bert_train_window_{key}"] == \
                    pytest.approx(value, abs=0.0), key
                checked += 1
            elif isinstance(value, dict):
                for sub, sv in value.items():
                    if isinstance(sv, (int, float)):
                        assert gauges[f"bert_train_{key}_{sub}"] == \
                            pytest.approx(sv, abs=0.0), (key, sub)
                        checked += 1
        assert checked >= 10  # the window genuinely exports its fields
        # Liveness + route sanity on the same server.
        code, body = _get(f"http://{host}:{port}/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        code, body = _get(f"http://{host}:{port}/statsz")
        assert json.loads(body)["last_window"]["step"] == last["step"]
    finally:
        server.shutdown()
        server.server_close()
        tele.close()


def test_from_args_wires_debug_plane_and_recorder(tmp_path):
    """The runner wiring (telemetry/cli.py): --debug_port stands up the
    live plane, output_dir anchors the flight recorder, and finish()
    tears both down (port released, clean run leaves no postmortem)."""
    import argparse
    import socket

    from bert_pytorch_tpu.telemetry import cli as tcli

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    parser = argparse.ArgumentParser()
    tcli.add_cli_args(parser)
    args = parser.parse_args(["--debug_port", str(port)])
    tele = tcli.from_args(args, output_dir=str(tmp_path), process="unit")
    try:
        assert tele.debug_server is not None
        assert tele.flight_recorder is not None
        assert tele.flight_recorder.path == \
            str(tmp_path / "postmortem.json")
        code, body = _get(f"http://127.0.0.1:{port}/healthz")
        payload = json.loads(body)
        assert code == 200 and payload["status"] == "warming"
        assert payload["process"] == "unit"
    finally:
        tele.finish(0)
        tele.close()
    with pytest.raises(OSError):
        _get(f"http://127.0.0.1:{port}/healthz")
    assert not os.path.exists(tmp_path / "postmortem.json")


def test_from_args_debug_port_zero_disables(tmp_path):
    import argparse

    from bert_pytorch_tpu.telemetry import cli as tcli

    parser = argparse.ArgumentParser()
    tcli.add_cli_args(parser)
    tele = tcli.from_args(parser.parse_args([]))
    assert tele.debug_server is None
    assert tele.introspect is None
    assert tele.flight_recorder is None  # no output_dir, no flag
    tele.close()


# ---------------------------------------------------------------------------
# telemetry/flightrec.py: the ring + flush semantics


def test_flightrec_ring_never_exceeds_byte_bound(tmp_path):
    rec = FlightRecorder(str(tmp_path / "pm.json"), max_bytes=4096,
                         flush_interval_s=1e9)
    for i in range(500):
        rec.note_record({"kind": "step_window", "step": i,
                         "pad": "x" * (i % 97)})
        assert rec.ring_bytes() <= 4096
    rec.note_line("y" * 100000)  # oversized entries are stubbed
    assert rec.ring_bytes() <= 4096
    pm_path = rec.flush("unit")
    pm = read_postmortem(pm_path)
    assert pm["ring_bytes"] <= 4096
    assert pm["dropped"] > 0 and pm["records"]
    # Newest records survive eviction, oldest go first.
    assert pm["records"][-1]["step"] == 499


def test_flightrec_incident_flush_and_clean_close(tmp_path):
    path = str(tmp_path / "pm.json")
    rec = FlightRecorder(path, flush_interval_s=float("inf"))
    rec.note_record({"kind": "step_window", "step": 1})
    assert not os.path.exists(path)  # periodic flushing disabled
    rec.note_record({"kind": "fault", "fault": "preemption",
                     "injected": False})
    pm = read_postmortem(path)
    assert pm["reason"] == "fault:preemption"
    assert [r["kind"] for r in pm["records"]] == ["step_window", "fault"]
    rec.close(clean=True)
    assert os.path.exists(path)  # incident forensics survive clean close

    clean = FlightRecorder(str(tmp_path / "pm2.json"),
                           flush_interval_s=0.0)
    clean.note_record({"kind": "step_window", "step": 1})
    assert os.path.exists(clean.path)  # periodic flush
    clean.close(clean=True)
    assert not os.path.exists(clean.path)  # clean run leaves no stale file


def test_flightrec_periodic_flush_survives_sigkill_semantics(tmp_path):
    """The SIGKILL story: no atexit, no excepthook — the last periodic
    flush IS the postmortem. Fake clock drives the cadence."""
    clock = FakeClock()
    rec = FlightRecorder(str(tmp_path / "pm.json"), flush_interval_s=2.0,
                         clock=clock)
    rec.note_record({"kind": "serve_window", "window_requests": 8})
    first = read_postmortem(rec.path)
    assert first["reason"] == "periodic"  # first note flushes immediately
    clock.advance(1.0)
    rec.note_record({"kind": "serve_window", "window_requests": 9})
    assert read_postmortem(rec.path) == first  # cadence not due: no write
    clock.advance(1.5)
    rec.note_record({"kind": "serve_window", "window_requests": 10})
    assert len(read_postmortem(rec.path)["records"]) == 3


def test_flightrec_torn_write_safe(tmp_path, monkeypatch):
    """tmp + rename: a failed replace leaves the previous postmortem
    intact, and the on-disk file is ALWAYS complete JSON."""
    from bert_pytorch_tpu.telemetry import flightrec as mod

    path = str(tmp_path / "pm.json")
    rec = FlightRecorder(path, flush_interval_s=1e9)
    rec.note_record({"kind": "step_window", "step": 1})
    rec.flush("first")
    before = read_postmortem(path)

    real_replace = os.replace

    def broken_replace(src, dst):
        raise OSError("disk pulled mid-rename")

    monkeypatch.setattr(mod.os, "replace", broken_replace)
    rec.note_record({"kind": "step_window", "step": 2})
    rec.flush("second")  # swallowed: forensics never crash the process
    assert read_postmortem(path) == before  # target untouched
    monkeypatch.setattr(mod.os, "replace", real_replace)
    rec.flush("third")
    assert read_postmortem(path)["reason"] == "third"


def test_flightrec_excepthook_keeps_traceback_over_atexit(tmp_path):
    rec = FlightRecorder(str(tmp_path / "pm.json"), flush_interval_s=1e9)
    rec.note_record({"kind": "step_window", "step": 7})
    try:
        raise RuntimeError("boom at step 7")
    except RuntimeError as exc:
        rec.flush("crash", exc=exc)
    pm = read_postmortem(rec.path)
    assert "boom at step 7" in pm["exception"]
    # The atexit pass after an excepthook flush must NOT overwrite the
    # traceback-carrying payload with a contextless one.
    rec._atexit_flush()
    assert read_postmortem(rec.path)["reason"] == "crash"


def test_flightrec_stale_flush_never_clobbers_newer_payload(tmp_path):
    """The build-under-lock/write-after-release window (review
    finding): a descheduled periodic flush must not overwrite a newer
    crash payload already on disk — _write is ordered by sequence."""
    rec = FlightRecorder(str(tmp_path / "pm.json"),
                         flush_interval_s=float("inf"))
    rec.note_record({"kind": "step_window", "step": 1})
    stale = rec._payload_locked("periodic")
    rec.note_record({"kind": "step_window", "step": 2})
    try:
        raise RuntimeError("boom")
    except RuntimeError as exc:
        rec.flush("crash", exc=exc)  # seq 1, written
    rec._write(stale, seq=0)  # the descheduled older writer resumes
    pm = read_postmortem(rec.path)
    assert pm["reason"] == "crash" and "boom" in pm["exception"]


def test_from_args_survives_debug_port_conflict(tmp_path):
    """A held port costs the debug plane, never the training run
    (review finding: the bind error used to crash the runner)."""
    import argparse
    import socket

    from bert_pytorch_tpu.telemetry import cli as tcli

    holder = socket.socket()
    holder.bind(("127.0.0.1", 0))
    holder.listen(1)
    port = holder.getsockname()[1]
    try:
        parser = argparse.ArgumentParser()
        tcli.add_cli_args(parser)
        tele = tcli.from_args(parser.parse_args(["--debug_port",
                                                 str(port)]))
        assert tele.debug_server is None  # plane disabled, run alive
        assert tele.introspect is not None
        tele.close()
    finally:
        holder.close()


def test_flightrec_tee_and_log_handler(tmp_path):
    rec = FlightRecorder(str(tmp_path / "pm.json"), flush_interval_s=1e9)
    seen = []
    teed = rec.tee(seen.append)
    teed({"kind": "serve_window", "window_requests": 4})
    assert seen == [{"kind": "serve_window", "window_requests": 4}]
    handler = rec.log_handler()
    handler.write_message("[ts] warming 1 task heads")
    handler.write_record({"tag": "train", "step": 3, "loss": float("nan")})
    pm = read_postmortem(rec.flush("unit"))
    assert pm["lines"] == ["[ts] warming 1 task heads"]
    assert pm["records"][-1]["loss"] is None  # NaN sanitized, not raw


# ---------------------------------------------------------------------------
# telemetry/collector.py: merge, staleness, aggregation


def _mk_tail(tmp_path, name, records):
    path = tmp_path / f"{name}.jsonl"
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return JsonlTailer(str(path), name)


def test_collector_merge_deterministic_under_out_of_order_ts(tmp_path):
    """Two identical runs over shuffled-timestamp sources produce the
    SAME timeline, in timestamp order within the pass."""
    recs_a = [{"schema": 1, "ts": 100.0 + t, "kind": "fleet_event",
               "tag": "fleet", "event": "spawn", "replica": 0, "port": 1}
              for t in (5, 1, 3)]
    recs_b = [{"schema": 1, "ts": 100.0 + t, "kind": "fleet_event",
               "tag": "fleet", "event": "exit", "replica": 1, "port": 2}
              for t in (4, 2)]

    tail_a = _mk_tail(tmp_path, "a", recs_a)
    tail_b = _mk_tail(tmp_path, "b", recs_b)

    def run(out_name):
        out = tmp_path / out_name
        coll = FleetCollector(
            [], tails=[JsonlTailer(tail_a.path, "a"),
                       JsonlTailer(tail_b.path, "b")],
            out_path=str(out), wall=lambda: 200.0)
        coll.collect_once()
        coll.stop()
        return out.read_bytes()

    one, two = run("one"), run("two")
    assert one == two
    timeline = [json.loads(line) for line in one.decode().splitlines()]
    tailed = [r for r in timeline if r.get("kind") == "fleet_event"]
    assert [r["ts"] for r in tailed] == sorted(r["ts"] for r in tailed)
    assert all(r["obs_source"] for r in tailed)
    # Tailers are incremental: a second pass re-reads nothing.
    errors = schema.validate_file(str(tmp_path / "one"))
    assert errors == []


def test_collector_tailer_incremental_and_partial_lines(tmp_path):
    path = tmp_path / "sink.jsonl"
    path.write_text('{"a": 1}\n{"b": 2')
    tail = JsonlTailer(str(path), "s")
    assert tail.poll() == [{"a": 1}]
    assert tail.poll() == []  # the partial line stays buffered
    with open(path, "a") as f:
        f.write("}\n")
    assert tail.poll() == [{"b": 2}]


def test_collector_blackholed_target_concurrent_and_stale():
    """One dead target cannot stall the pass (concurrent probes, the
    scrape_once discipline) and its staleness is RECORDED per pass."""
    clock = FakeClock()
    stall = threading.Event()

    def dead(url):
        stall.wait(timeout=0.5)  # a black-holed transport timing out
        return None

    fast_called = []

    def fast(url):
        fast_called.append(time.monotonic())
        return {"healthy": True, "requests": 10.0}

    emitted = []
    coll = FleetCollector(
        [Target("dead", "replica", "http://x", scrape=dead),
         Target("fast", "replica", "http://y", scrape=fast)],
        emit=emitted.append, clock=clock, wall=lambda: 500.0)
    t0 = time.monotonic()
    clock.advance(1.0)
    coll.collect_once()
    wall = time.monotonic() - t0
    assert wall < 1.5  # one stalled probe, not two serialized
    scrapes = {r["target"]: r for r in emitted
               if r.get("kind") == "obs_scrape"}
    assert scrapes["fast"]["ok"] is True
    assert scrapes["fast"]["staleness_s"] == 0.0
    assert scrapes["dead"]["ok"] is False
    assert scrapes["dead"]["staleness_s"] > 0
    first_stale = scrapes["dead"]["staleness_s"]
    clock.advance(3.0)
    emitted.clear()
    coll.collect_once()
    dead_rec = [r for r in emitted if r.get("target") == "dead"][0]
    assert dead_rec["staleness_s"] >= first_stale + 3.0  # grows per pass
    window = [r for r in emitted
              if r.get("kind") == "obs_fleet_window"][0]
    assert window["targets_total"] == 2
    assert window["targets_healthy"] == 1
    assert window["max_staleness_s"] == dead_rec["staleness_s"]


def test_collector_fleet_window_aggregates():
    clock = FakeClock()
    replica_state = {"r0": 100.0, "r1": 200.0}

    def mk_scrape(name, p99):
        def scrape(url):
            return {"healthy": True, "requests": replica_state[name],
                    "over_slo": 4.0, "latency_p99_ms": p99}
        return scrape

    def trainer(url):
        return {"healthy": True, "steps_per_sec": 3.5}

    emitted = []
    coll = FleetCollector(
        [Target("r0", "replica", "http://a", scrape=mk_scrape("r0", 40.0)),
         Target("r1", "replica", "http://b", scrape=mk_scrape("r1", 90.0)),
         Target("t0", "trainer", "http://c", scrape=trainer)],
        emit=emitted.append, clock=clock, slo_error_budget=0.1)
    coll.collect_once()
    clock.advance(2.0)
    replica_state["r0"] += 50.0   # 25 req/s
    replica_state["r1"] += 10.0   # 5 req/s
    emitted.clear()
    coll.collect_once()
    window = [r for r in emitted
              if r.get("kind") == "obs_fleet_window"][0]
    assert window["replicas_total"] == 2
    assert window["replicas_healthy"] == 2
    assert window["worst_replica_p99_ms"] == 90.0
    assert window["fleet_rps"] == pytest.approx(30.0)
    assert window["trainer_steps_per_sec"] == pytest.approx(3.5)
    # 8 over-SLO of 360 requests at 10% budget: burn well under 1.
    assert 0 < window["error_budget_burn"] < 1
    for rec in emitted:
        assert schema.validate_record(rec) == []


def test_replica_p99_counts_overflow_bucket(monkeypatch):
    """The worst-replica p99 must see observations past the largest
    finite histogram bound (they live only in the +Inf bucket / _count
    series): a 5%-of-requests tail blowup is exactly the incident the
    'fleet worst-replica p99' gate exists to catch (review finding)."""
    from bert_pytorch_tpu.telemetry import collector as mod

    text = "\n".join([
        "bert_serve_dispatch_alive 1",
        "bert_serve_draining 0",
        "bert_serve_queue_depth 0",
        'bert_serve_requests_total{task="classify"} 100',
        'bert_serve_phase_latency_ms_bucket{task="classify",'
        'phase="total",le="10"} 95',
        'bert_serve_phase_latency_ms_bucket{task="classify",'
        'phase="total",le="2500"} 95',
        'bert_serve_phase_latency_ms_bucket{task="classify",'
        'phase="total",le="+Inf"} 100',
        'bert_serve_phase_latency_ms_count{task="classify",'
        'phase="total"} 100',
    ]) + "\n"
    monkeypatch.setattr(mod, "_http_get", lambda url, path, t: (200, text))
    sample = mod.scrape_replica("http://x")
    # 99th of 100 sits among the 5 overflow observations: the estimate
    # floors at the largest finite bound, never the fast-path 10ms.
    assert sample["latency_p99_ms"] == 2500.0


def test_scrape_trainer_counts_wedged_trainer_unhealthy():
    """A trainer wedged in a hung collective keeps answering /metricsz
    (the HTTP threads are fine) — the scraper must read the step age
    against the exported staleness bound, not just 'the port answered'
    (the review finding: bert_train_up alone is always 1)."""
    from bert_pytorch_tpu.telemetry.collector import scrape_trainer

    clock = FakeClock()
    hub = IntrospectionHub(process="t", stale_after_s=10.0, clock=clock)
    hub.note_step(5)
    server = start_debug_server(hub, port=0)
    try:
        url = "http://%s:%d" % server.server_address[:2]
        assert scrape_trainer(url)["healthy"] is True
        clock.advance(11.0)  # past the bound: /healthz would say 503
        sample = scrape_trainer(url)
        assert sample["healthy"] is False
        assert sample["step_age_s"] > 10.0
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# serve/supervisor.py: heartbeat + postmortem harvest


class FakeProc:
    _pids = iter(range(6000, 7000))

    def __init__(self):
        self.pid = next(FakeProc._pids)
        self.rc = None

    def poll(self):
        return self.rc

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc

    def send_signal(self, sig):
        self.rc = 0


def _harvest_supervisor(tmp_path, clock, events):
    pm_path = str(tmp_path / "postmortem.json")
    procs = []

    def spawn(spec):
        procs.append(FakeProc())
        return procs[-1]

    sup = Supervisor(
        [ReplicaSpec(0, 9001, ["run_server"], postmortem_file=pm_path)],
        emit=events.append, spawn=spawn,
        policy=RetryPolicy(attempts=5, base_delay_s=1.0, jitter=0.0),
        heartbeat_file=str(tmp_path / "sup_heartbeat.json"),
        clock=clock, sleep=lambda s: None)
    return sup, procs, pm_path


def test_supervisor_writes_its_own_heartbeat(tmp_path):
    clock = FakeClock()
    events: list = []
    sup, procs, _ = _harvest_supervisor(tmp_path, clock, events)
    sup.start(monitor=False)
    sup.poll_once()
    hb = json.load(open(tmp_path / "sup_heartbeat.json"))
    assert (hb["step"], hb["counter"]) == (1, 1)
    sup.poll_once()
    hb = json.load(open(tmp_path / "sup_heartbeat.json"))
    assert (hb["step"], hb["counter"]) == (2, 2)
    # Resumable like every heartbeat: a new supervisor continues the
    # counter instead of restarting it (liveness = "did it advance").
    sup2 = Supervisor([ReplicaSpec(0, 9001, ["x"])],
                      spawn=lambda spec: FakeProc(),
                      heartbeat_file=str(tmp_path / "sup_heartbeat.json"),
                      clock=clock, sleep=lambda s: None)
    sup2.start(monitor=False)
    sup2.poll_once()
    assert json.load(open(tmp_path / "sup_heartbeat.json"))["counter"] == 3


def test_supervisor_harvests_postmortem_on_crash(tmp_path):
    clock = FakeClock()
    events: list = []
    sup, procs, pm_path = _harvest_supervisor(tmp_path, clock, events)
    sup.start(monitor=False)
    # The replica's flight recorder flushed before it died (periodic).
    json.dump({"process": "serve", "reason": "periodic",
               "flushed_at": 123.0, "ring_entries": 9, "ring_bytes": 512,
               "dropped": 0,
               "records": [{"kind": "serve_window", "window_requests": i}
                           for i in range(8)],
               "lines": ["serving on :9001"]},
              open(pm_path, "w"))
    procs[-1].rc = -9  # SIGKILL
    sup.poll_once()
    harvests = [e for e in events if e["event"] == "postmortem"]
    assert len(harvests) == 1
    h = harvests[0]
    assert h["found"] is True and h["context"] == "exit"
    assert h["reason"] == "periodic" and h["ring_entries"] == 9
    assert len(h["records"]) == 5  # bounded tail, newest kept
    assert h["records"][-1]["window_requests"] == 7
    assert h["lines"] == ["serving on :9001"]
    assert schema.validate_record(
        dict(h, schema=1, ts=1.0)) == []
    # The respawn wipes the dead incarnation's file: fresh forensics.
    clock.advance(1.01)
    sup.poll_once()
    assert len(procs) == 2
    assert not os.path.exists(pm_path)


def test_supervisor_graceful_exit_does_not_harvest(tmp_path):
    clock = FakeClock()
    events: list = []
    sup, procs, pm_path = _harvest_supervisor(tmp_path, clock, events)
    sup.start(monitor=False)
    json.dump({"reason": "periodic", "records": [], "lines": []},
              open(pm_path, "w"))
    procs[-1].rc = 0  # operator stop, not a crash
    sup.poll_once()
    assert not any(e["event"] == "postmortem" for e in events)


def test_supervisor_harvest_names_missing_postmortem(tmp_path):
    """A crash before the first flush is itself diagnostic — the event
    says found=false instead of silently skipping."""
    clock = FakeClock()
    events: list = []
    sup, procs, pm_path = _harvest_supervisor(tmp_path, clock, events)
    sup.start(monitor=False)
    procs[-1].rc = 1
    sup.poll_once()
    harvests = [e for e in events if e["event"] == "postmortem"]
    assert harvests and harvests[0]["found"] is False


# ---------------------------------------------------------------------------
# serve/router.py: the Prometheus export


def test_router_metricsz_matches_statsz():
    router = Router(["http://127.0.0.1:1"],
                    scrape=lambda url: {"dispatch_alive": True,
                                        "draining": False,
                                        "queue_depth": 2},
                    transport=lambda url, task, payload, t: (200, {}),
                    sleep=lambda s: None)
    router.scrape_once()
    for _ in range(3):
        status, _, _ = router.handle("classify", {"text": "x"})
        assert status == 200
    text = router.metrics_text()
    series = {name: value for name, labels, value
              in parse_prometheus(text) if not labels}
    snap = router.snapshot()
    assert series["bert_router_requests_total"] == snap["requests"] == 3
    assert series["bert_router_ok_total"] == snap["ok"] == 3
    assert series["bert_router_healthy_replicas"] == 1
    labeled = {(name, labels.get("replica"), labels.get("field")): value
               for name, labels, value in parse_prometheus(text) if labels}
    assert labeled[("bert_router_replica_state", "0", "healthy")] == 1
    assert labeled[("bert_router_replica_state", "0", "queue_depth")] == 2


# ---------------------------------------------------------------------------
# report: the fleet observatory section + its two named gates


def _timeline_records(stale=0.4, p99=45.0):
    return [
        {"kind": "obs_scrape", "target": "r0", "target_kind": "replica",
         "ok": True, "staleness_s": 0.0},
        {"kind": "obs_scrape", "target": "r1", "target_kind": "replica",
         "ok": False, "staleness_s": stale},
        {"kind": "obs_fleet_window", "targets_total": 3,
         "targets_healthy": 2, "max_staleness_s": stale,
         "replicas_total": 2, "replicas_healthy": 1,
         "worst_replica_p99_ms": p99, "fleet_rps": 40.0,
         "trainer_steps_per_sec": 3.0, "error_budget_burn": 0.5},
        {"kind": "obs_fleet_window", "targets_total": 3,
         "targets_healthy": 3, "max_staleness_s": 0.0},
    ]


def test_report_summarizes_fleet_observatory_section():
    summary = report.summarize_records(_timeline_records(), name="t")
    assert summary["obs_scrapes"] == 2
    assert summary["obs_targets"] == 2
    assert summary["obs_scrape_failures"] == 1
    assert summary["fleet_scrape_staleness_s"] == 0.4
    assert summary["fleet_windows"] == 2
    assert summary["fleet_targets"] == 3
    assert summary["fleet_healthy_min"] == 2
    assert summary["fleet_worst_replica_p99_ms"] == 45.0
    assert summary["fleet_error_budget_burn"] == 0.5


def test_report_gates_fleet_staleness_and_worst_p99_by_name(tmp_path):
    """An injected staleness/latency regression exits nonzero NAMING
    the fleet gate — through the real CLI shim, the ISSUE acceptance."""
    base = report.summarize_records(_timeline_records(), name="base")
    worse = report.summarize_records(
        _timeline_records(stale=5.0, p99=200.0), name="new")
    regressions, checks = report.compare(base, worse)
    names = {r["label"] for r in regressions}
    assert "fleet scrape staleness" in names
    assert "fleet worst-replica p99" in names
    # And via the CLI: rc 1, gate named in stdout.
    base_path = tmp_path / "base.jsonl"
    new_path = tmp_path / "new.jsonl"
    for path, stale, p99 in ((base_path, 0.4, 45.0),
                             (new_path, 5.0, 200.0)):
        with open(path, "w") as f:
            for rec in _timeline_records(stale=stale, p99=p99):
                f.write(json.dumps(dict(rec, schema=1, ts=1.0)) + "\n")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "telemetry_report.py"),
         str(new_path), str(base_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "fleet scrape staleness" in proc.stdout
    assert "fleet worst-replica p99" in proc.stdout


# ---------------------------------------------------------------------------
# fixtures + the obs_collect CLI (jax-free parent)


def test_obs_schema_fixtures_lint_as_expected():
    good = os.path.join(HERE, "fixtures", "telemetry", "obs_good.jsonl")
    bad = os.path.join(HERE, "fixtures", "telemetry", "obs_bad.jsonl")
    assert schema.validate_file(good) == []
    errors = schema.validate_file(bad)
    assert len(errors) >= 6
    text = " ".join(err for _, err in errors)
    assert "target_kind" in text
    assert "targets_healthy" in text
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "check_telemetry_schema.py"),
         good, bad],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "obs_good.jsonl: ok" in proc.stdout
    assert "obs_bad" in proc.stdout


def test_obs_collect_cli_tails_and_self_lints(tmp_path):
    sink = tmp_path / "fleet.jsonl"
    with open(sink, "w") as f:
        f.write(json.dumps({"schema": 1, "ts": 1.0, "kind": "fleet_event",
                            "tag": "fleet", "event": "spawn",
                            "replica": 0, "port": 9001}) + "\n")
    out = tmp_path / "timeline.jsonl"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "obs_collect.py"),
         "--tail", f"fleet={sink}", "--out", str(out),
         "--passes", "2", "--interval_s", "0.05"],
        capture_output=True, text=True, timeout=60,
        cwd=os.path.join(REPO_ROOT, "tools"))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "ok" in proc.stdout
    timeline = [json.loads(line) for line in open(out)]
    assert any(r.get("kind") == "fleet_event" for r in timeline)
    assert schema.validate_file(str(out)) == []
