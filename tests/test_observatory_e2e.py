"""ISSUE 12 E2E acceptance (docs/observability.md): the fleet
observatory over a REAL fleet on the chaos-tiny config.

One test stands up 2 ``run_server.py`` replicas (supervised, warming
from one shared persistent AOT cache) behind the router, plus a live
in-process training loop exporting the ``--debug_port`` introspection
plane, and runs the fleet collector over all of it while a client burst
flows and replica 0 is SIGKILLed mid-burst. Asserted on the ONE merged
timeline the collector writes:

* schema-clean end to end (``obs_scrape``/``obs_fleet_window`` +
  every tailed fleet/trainer record);
* the trainer's /metricsz agrees with its JSONL step_window artifact
  per metric name (the introspection plane's consistency contract);
* the SIGKILLed replica's harvested postmortem is IN the timeline
  (fleet_event ``postmortem`` with a non-empty ring tail — the flight
  recorder's periodic flush survived the kill);
* an ``obs_fleet_window`` shows the healthy-count dip AND a later
  window shows recovery (supervised respawn, warm restart);
* an injected staleness regression makes ``telemetry-report`` exit
  nonzero NAMING the fleet gate.

Kept in its own module (like tests/test_fleet_chaos.py) so the
subprocess fleet never slows collection of the in-process observatory
tests. Budgeted for the throttled 2-core tier-1 box: one fleet
spin-up, one small burst, one kill/recover cycle.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.parse
import urllib.request

import pytest

from bert_pytorch_tpu.serve import router as router_mod
from bert_pytorch_tpu.serve import supervisor as supervisor_mod
from bert_pytorch_tpu.telemetry import report, schema
from bert_pytorch_tpu.telemetry.collector import (FleetCollector,
                                                  JsonlTailer, Target,
                                                  parse_prometheus)
from bert_pytorch_tpu.telemetry.introspect import (IntrospectionHub,
                                                   start_debug_server)
from bert_pytorch_tpu.telemetry.runner import TrainTelemetry
from bert_pytorch_tpu.tools import make_synthetic_data as synth

HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(HERE)

PHRASES = (
    "paris is big", "the river runs through london",
    "william shakespeare wrote hamlet", "england is old",
    "the capital of france is paris", "hamlet was wrote in london",
)


def model_config() -> dict:
    vocab = 5 + len(synth.TRACE_WORDS)
    vocab += (8 - vocab % 8) % 8
    return {
        "vocab_size": vocab, "hidden_size": 16, "num_hidden_layers": 1,
        "num_attention_heads": 2, "intermediate_size": 32,
        "max_position_embeddings": 32, "type_vocab_size": 2,
        "next_sentence": True, "mask_token_id": 4,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
    }


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_spawn(log_dir: str):
    """Replica Popen factory: pin CPU jax, strip the test harness's
    virtual-device flag (the replicas must not build an 8-device mesh),
    tee output per replica (tools/chaos_serve.py discipline)."""

    def spawn(spec):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("BERT_FAULTS", None)
        xla = " ".join(
            flag for flag in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in flag)
        if xla:
            env["XLA_FLAGS"] = xla
        else:
            env.pop("XLA_FLAGS", None)
        if spec.env:
            env.update(spec.env)
        log = open(os.path.join(log_dir, f"replica_{spec.index}.log"), "ab")
        return subprocess.Popen(spec.cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT)

    return spawn


class Sink:
    """Thread-safe schema-v1 JSONL sink + in-memory index (the chaos
    harness's Sink, trimmed): supervisor + router emit through it, the
    collector tails the file, the test asserts on the index."""

    def __init__(self, path: str):
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")
        self.records = []

    def write(self, record: dict) -> None:
        rec = {"schema": schema.SCHEMA_VERSION,
               "ts": round(time.time(), 3)}
        rec.update(record)
        with self._lock:
            self.records.append(rec)
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()

    def count(self, event: str) -> int:
        with self._lock:
            return sum(1 for r in self.records
                       if r.get("event") == event)

    def close(self) -> None:
        with self._lock:
            self._f.close()


def post(url: str, task: str, payload: dict, timeout_s: float):
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=timeout_s)
    try:
        conn.request("POST", f"/v1/{task}",
                     body=json.dumps(payload).encode("utf-8"),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        return resp.status, dict(resp.getheaders())
    finally:
        conn.close()


def wait_until(pred, timeout_s: float, what: str, poll_s: float = 0.25):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise AssertionError(f"timed out after {timeout_s:g}s waiting for "
                         f"{what}")


class TrainerPlane:
    """A live in-process training loop on the real TrainTelemetry
    facade with the real debug server — the 'short training run with
    --debug_port' of the acceptance, without a third jax subprocess on
    the throttled box (the subprocess runners wire the identical path
    through telemetry/cli.from_args)."""

    def __init__(self, workdir: str):
        self.jsonl = os.path.join(workdir, "trainer_telemetry.jsonl")
        self.hub = IntrospectionHub(process="pretrain",
                                    stale_after_s=30.0)
        self.tele = TrainTelemetry(
            jsonl_path=self.jsonl, window=20, sync_every=1,
            introspect=self.hub)
        self.server = start_debug_server(self.hub, port=0)
        self.url = "http://%s:%d" % self.server.server_address[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="e2e-trainer")

    def start(self):
        self._thread.start()

    def _loop(self):
        step = 0
        while not self._stop.is_set():
            step += 1
            self.tele.timer.data_start()
            self.tele.timer.data_end()
            self.tele.dispatch_done()
            self.tele.step_done(step, {"loss": 2.0 + 0.001 * step})
            time.sleep(0.02)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10.0)
        self.server.shutdown()
        self.server.server_close()
        self.tele.close()


def test_trainer_plane_collector_pass_in_process(tmp_path):
    """The acceptance's key invariant, carried tier-1 in-process
    (ISSUE 14 budget fix): a live trainer debug plane scrapes healthy
    through the collector, the scraped /metricsz gauges agree with the
    trainer's own JSONL window, and the merged timeline the collector
    writes is schema-clean — no subprocess fleet, one TrainerPlane
    thread, one collector pass."""
    workdir = str(tmp_path)
    trainer = TrainerPlane(workdir)
    trainer.start()
    timeline_path = os.path.join(workdir, "timeline.jsonl")
    try:
        wait_until(lambda: os.path.exists(trainer.jsonl), 10.0,
                   "trainer telemetry artifact")
        collector = FleetCollector(
            targets=[Target(name="pretrain", kind="trainer",
                            url=trainer.url)],
            tails=[JsonlTailer(trainer.jsonl, "trainer")],
            out_path=timeline_path, emit=lambda rec: None)
        wait_until(lambda: collector.collect_once()["targets_healthy"]
                   == 1, 15.0, "healthy trainer scrape")
    finally:
        trainer.stop()
    records = [json.loads(line) for line in open(timeline_path)]
    scrapes = [r for r in records if r.get("kind") == "obs_scrape"]
    assert scrapes and scrapes[-1]["ok"] is True
    assert scrapes[-1]["target_kind"] == "trainer"
    fleet = [r for r in records if r.get("kind") == "obs_fleet_window"]
    assert fleet and fleet[-1]["targets_total"] == 1
    assert schema.validate_file(timeline_path) == []


@pytest.mark.slow  # ~30-50s: supervised run_server.py replicas + kill/
# recover cycle (ISSUE 14 budget fix); the collector/introspection
# behavior is tier-1 above and in tests/test_observatory.py.
def test_fleet_observatory_acceptance(tmp_path):
    workdir = str(tmp_path)
    cache_dir = os.path.join(workdir, "compile_cache")
    vocab_path = synth.write_trace_vocab(os.path.join(workdir, "vocab.txt"))
    config_path = os.path.join(workdir, "model.json")
    with open(config_path, "w") as f:
        json.dump(model_config(), f)

    shared_args = [
        "--model_config_file", config_path, "--vocab_file", vocab_path,
        "--tasks", "classify", "--classify_labels", "neg,pos",
        "--buckets", "16", "--max_batch_size", "4", "--max_wait_ms", "5",
        "--dtype", "float32", "--compile_cache_dir", cache_dir,
        "--trace_sample_rate", "0", "--telemetry_window", "16",
        "--slo_p99_ms", "2000", "--request_timeout_s", "10",
    ]
    specs = []
    for i in range(2):
        out_dir = os.path.join(workdir, f"replica_{i}")
        os.makedirs(out_dir, exist_ok=True)
        port = free_port()
        specs.append(supervisor_mod.ReplicaSpec(
            index=i, port=port,
            cmd=supervisor_mod.run_server_command(port, out_dir,
                                                  shared_args),
            heartbeat_file=os.path.join(out_dir, "heartbeat.json"),
            postmortem_file=os.path.join(out_dir, "postmortem.json")))

    sink = Sink(os.path.join(workdir, "fleet_telemetry.jsonl"))
    sup = supervisor_mod.Supervisor(
        specs, emit=sink.write, spawn=make_spawn(workdir),
        policy=supervisor_mod.RetryPolicy(
            attempts=5, base_delay_s=0.4, max_delay_s=3.0,
            full_jitter=True),
        heartbeat_timeout_s=10.0, startup_grace_s=240.0,
        stable_reset_s=15.0, poll_interval_s=0.25, drain_grace_s=15.0,
        heartbeat_file=os.path.join(workdir, "supervisor_heartbeat.json"))
    router = router_mod.Router(
        [s.url for s in specs], emit=sink.write, window=16,
        scrape_interval_s=0.25, deadline_s=8.0,
        retry_policy=router_mod.RetryPolicy(
            attempts=3, base_delay_s=0.05, max_delay_s=0.5,
            full_jitter=True),
        hedge_pctl=0.95, hedge_min_ms=30.0, hedge_min_samples=24,
        brownout_queue_depth=64, shed_retry_after_s=0.5)
    router_server = router_mod.make_router_server(router, port=0)
    router_url = "http://%s:%d" % router_server.server_address[:2]

    trainer = TrainerPlane(workdir)
    timeline_path = os.path.join(workdir, "fleet_timeline.jsonl")
    collected = []
    collector = FleetCollector(
        targets=[
            Target("pretrain", "trainer", trainer.url),
            Target("r0", "replica", specs[0].url),
            Target("r1", "replica", specs[1].url),
            Target("front", "router", router_url),
        ],
        tails=[
            JsonlTailer(os.path.join(workdir, "fleet_telemetry.jsonl"),
                        "fleet"),
            JsonlTailer(trainer.jsonl, "trainer"),
            JsonlTailer(os.path.join(workdir, "replica_0",
                                     "serve_telemetry.jsonl"), "r0"),
            JsonlTailer(os.path.join(workdir, "replica_1",
                                     "serve_telemetry.jsonl"), "r1"),
        ],
        out_path=timeline_path, emit=collected.append, interval_s=0.5)

    try:
        trainer.start()
        sup.start()
        router.start()
        threading.Thread(target=router_server.serve_forever,
                         daemon=True).start()
        collector.start()
        wait_until(lambda: router.healthy_count() == 2, 240.0,
                   "both replicas healthy")

        # -- the burst, with a SIGKILL landing mid-flight ----------------
        outcomes = []
        kill_at = {"t": None, "wall": None}

        def kill_replica_0():
            pid = sup.status()[0]["pid"]
            kill_at["t"] = time.monotonic()
            kill_at["wall"] = time.time()
            if pid:
                os.kill(pid, signal.SIGKILL)

        for seq in range(40):
            if seq == 10:
                kill_replica_0()
            status, headers = post(
                router_url, "classify",
                {"text": PHRASES[seq % len(PHRASES)]}, timeout_s=15.0)
            outcomes.append((status, headers.get("Retry-After")))
        assert kill_at["t"] is not None
        failures = [o for o in outcomes
                    if not (o[0] == 200 or (o[0] == 503 and o[1]))]
        assert failures == [], failures  # the PR-11 resilience story holds

        # The supervisor harvested the dead replica's postmortem...
        wait_until(lambda: sink.count("postmortem") >= 1, 60.0,
                   "postmortem harvest fleet_event")
        # ...and the fleet healed (respawn + warm restart).
        wait_until(lambda: router.healthy_count() == 2, 120.0,
                   "killed replica respawned and healthy")
        # Let the collector observe the healed fleet in its OWN windows
        # (the recovery half of the dip-and-recovery assertion).
        def dip_then_recovery() -> bool:
            snap = [r for r in list(collected)
                    if r.get("kind") == "obs_fleet_window"]
            dips = [r["ts"] for r in snap
                    if r.get("replicas_healthy", 2) < 2
                    and r["ts"] > kill_at["wall"]]
            return bool(dips) and any(
                r.get("replicas_healthy") == 2 and r["ts"] > dips[0]
                for r in snap)

        wait_until(dip_then_recovery, 60.0,
                   "an obs_fleet_window dip (post-kill) then recovery")

        # -- trainer /metricsz vs its JSONL windows, per metric name -----
        with urllib.request.urlopen(f"{trainer.url}/metricsz",
                                    timeout=5) as resp:
            gauges = {name: value for name, labels, value
                      in parse_prometheus(resp.read().decode())}
        windows = [r for r in report.iter_records(trainer.jsonl)
                   if r.get("kind") == "step_window"]
        assert windows, "the trainer emitted no step_window records"
        # The scrape races the live loop: the exported window is SOME
        # recently emitted one — find it by step, then compare every
        # numeric field verbatim.
        exported_step = gauges.get("bert_train_window_step")
        match = [w for w in windows if w.get("step") == exported_step]
        assert match, (exported_step, [w["step"] for w in windows])
        checked = 0
        for key, value in match[0].items():
            if key in ("kind", "tag", "schema", "ts"):
                continue
            if isinstance(value, (int, float)) and \
                    not isinstance(value, bool):
                assert gauges[f"bert_train_window_{key}"] == \
                    pytest.approx(value, abs=0.0), key
                checked += 1
        assert checked >= 10
    finally:
        try:
            collector.stop()
        except Exception:
            pass
        try:
            trainer.stop()
        except Exception:
            pass
        drain = sup.stop()
        router_server.shutdown()
        router.stop()
        sink.close()

    # -- the one timeline: schema-clean, postmortem present, dip+recover -
    assert schema.validate_file(timeline_path) == []
    timeline = [json.loads(line) for line in open(timeline_path)]
    harvests = [r for r in timeline
                if r.get("kind") == "fleet_event"
                and r.get("event") == "postmortem"]
    assert harvests, "harvested postmortem never reached the timeline"
    pm = harvests[0]
    assert pm["found"] is True
    assert pm["records"], "harvested ring is empty"
    # The ring's last records are the replica's final telemetry — the
    # serve records it emitted before dying (cold start at minimum).
    kinds = {r.get("kind") for r in pm["records"]}
    assert kinds & {"serve_cold_start", "serve_window", "serve_trace",
                    "serve_phase", "compile", "compile_cost"}, kinds
    dips = [r for r in timeline if r.get("kind") == "obs_fleet_window"
            and r.get("replicas_healthy", 99) < r.get("replicas_total", 0)
            and r["ts"] > kill_at["wall"]]
    assert dips, "no obs_fleet_window recorded the post-kill dip"
    recoveries = [r for r in timeline
                  if r.get("kind") == "obs_fleet_window"
                  and r.get("replicas_healthy") == 2
                  and r["ts"] > dips[0]["ts"]]
    assert recoveries, "no obs_fleet_window recorded the recovery"
    scraped_kinds = {r.get("target_kind") for r in timeline
                     if r.get("kind") == "obs_scrape"}
    assert scraped_kinds == {"trainer", "replica", "router"}

    # -- the report gate: injected staleness exits nonzero, by name ------
    doctored = os.path.join(workdir, "doctored_timeline.jsonl")
    with open(timeline_path) as src, open(doctored, "w") as dst:
        dst.write(src.read())
        dst.write(json.dumps({
            "schema": 1, "ts": time.time(), "kind": "obs_scrape",
            "tag": "obs", "target": "r1", "target_kind": "replica",
            "ok": False, "staleness_s": 900.0}) + "\n")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "telemetry_report.py"),
         doctored, timeline_path],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout[-2000:]
    assert "fleet scrape staleness" in proc.stdout
    # And the clean timeline against itself stays green.
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "telemetry_report.py"),
         timeline_path, timeline_path],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout[-2000:]
