"""One-mesh composition (ISSUE 18): MeshSpec parsing, spec-derived rules,
legacy-alias byte-identity, composed-strategy parity, and elastic
sharded-checkpoint resume.

The tentpole invariant is that parallelism composition is a SPEC, not a
menu: any ``dp=A,fsdp=B,pipe=C,seq=D`` product derives its logical-axis
rules from one template (``parallel/mesh.py derive_rules``), the legacy
strategy names are aliases that lower onto specs with byte-identical
rules, and a checkpoint saved sharded under one topology resumes under
another (save on 8 ways, resume on 4) with an exact loss trajectory.
Runs tier-1 on the virtual 8-device CPU mesh (conftest.py); cells whose
engine cannot run on this jax (the gpipe shard_map typing needs
jax>=0.5 on CPU — see tests/test_pipeline.py) skip with the reason
rather than fail.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu import optim, pretrain
from bert_pytorch_tpu.analysis import axes as axes_registry
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.parallel import (
    MeshSpec,
    MeshSpecError,
    create_mesh,
    derive_rules,
    logical_axis_rules,
    parse_mesh_spec,
)
from bert_pytorch_tpu.parallel import mesh as mesh_mod
from bert_pytorch_tpu.utils import checkpoint as ckpt
from bert_pytorch_tpu.utils import integrity

# -- spec grammar ---------------------------------------------------------


def test_spec_parse_roundtrip():
    spec = MeshSpec.parse("dp=4,fsdp=2,pipe=1,seq=1")
    assert (spec.data, spec.fsdp, spec.pipe, spec.seq) == (4, 2, 1, 1)
    assert spec.canonical() == "dp=4,fsdp=2"
    assert MeshSpec.parse(spec.canonical()) == spec
    # aliases: pp->pipe, sp/ring->seq, tp->model, data->data
    assert MeshSpec.parse("data=2,pp=2,tp=2") == MeshSpec(
        data=2, pipe=2, model=2)
    assert MeshSpec.parse("dp=2,ring=4").seq == 4
    # data=-1 (fill the mesh) survives the round trip
    spec = MeshSpec.parse("dp=-1,fsdp=2")
    assert spec.data == -1
    assert MeshSpec.parse(spec.canonical()) == spec
    # as_dict/from_dict round-trips through plain ints (manifest format)
    d = MeshSpec.parse("dp=2,fsdp=2,seq=2").as_dict()
    assert all(isinstance(v, int) for v in d.values())
    assert MeshSpec.from_dict(d) == MeshSpec.parse("dp=2,fsdp=2,seq=2")
    # module-level convenience wrapper
    assert parse_mesh_spec("dp=8") == MeshSpec(data=8)


@pytest.mark.parametrize(
    "text, match",
    [
        ("dp=4,bogus=2", "unknown mesh-spec key"),
        ("dp=4,dp=2", "given twice"),
        ("dp=two", "integer"),
        ("dp", "KEY=SIZE"),
        ("dp=4,fsdp=0", ">= 1"),
    ],
)
def test_spec_parse_rejections(text, match):
    with pytest.raises(MeshSpecError, match=match):
        MeshSpec.parse(text)


def test_spec_validate_rejections():
    # impossible combos are spec-validation errors WITH REASONS
    with pytest.raises(MeshSpecError, match="packed"):
        MeshSpec.parse("dp=2,seq=2").validate(packed=True)
    with pytest.raises(MeshSpecError, match="devices"):
        MeshSpec.parse("dp=3,fsdp=3").validate(n_devices=8)
    # sound combos pass, packing included
    MeshSpec.parse("dp=4,fsdp=2").validate(n_devices=8, packed=True)
    MeshSpec.parse("dp=2,pipe=2,seq=2").validate(n_devices=8)


def test_save_checkpoint_rejects_unknown_layout(tmp_path):
    with pytest.raises(ValueError, match="unknown checkpoint layout"):
        ckpt.save_checkpoint(str(tmp_path), 0, {"x": jnp.zeros(2)},
                             layout="banana")


# -- rule derivation ------------------------------------------------------

# The seed's named-strategy table, verbatim (pre-one-mesh
# parallel/mesh.py). The refactor's contract is byte-identity: legacy
# aliases must lower onto specs producing EXACTLY these rules.
_SEED_STRATEGY_RULES = {
    "pp": [("layers", "pipe"), ("embed", None), ("embed_out", None),
           ("vocab", None), ("heads", None), ("kv", None), ("mlp", None)],
    "sp": [("embed", None), ("embed_out", None), ("vocab", None),
           ("heads", None), ("kv", None), ("mlp", None)],
    "dp": [("embed", None), ("embed_out", None), ("vocab", None),
           ("heads", None), ("kv", None), ("mlp", None)],
    "fsdp": [("embed", "fsdp"), ("embed_out", None), ("vocab", None),
             ("heads", None), ("kv", None), ("mlp", None)],
    "tp": [("embed", None), ("embed_out", "model"), ("vocab", "model"),
           ("heads", "model"), ("kv", None), ("mlp", "model")],
    "tp_fsdp": [("embed", "fsdp"), ("embed_out", "model"),
                ("vocab", "model"), ("heads", "model"), ("kv", None),
                ("mlp", "model")],
    "pp_tp": [("layers", "pipe"), ("embed", None), ("embed_out", "model"),
              ("vocab", "model"), ("heads", "model"), ("kv", None),
              ("mlp", "model")],
}

# Representative sizes that activate each legacy strategy's axes.
_ALIAS_SIZES = {
    "dp": {},
    "sp": {"seq": 2},
    "fsdp": {"fsdp": 2},
    "tp": {"model": 2},
    "tp_fsdp": {"fsdp": 2, "model": 2},
    "pp": {"pipe": 2},
    "pp_tp": {"pipe": 2, "model": 2},
}


def test_legacy_alias_rules_byte_identical():
    for name, seed_rules in _SEED_STRATEGY_RULES.items():
        assert mesh_mod._STRATEGY_RULES[name] == seed_rules, name
        assert logical_axis_rules(name) == seed_rules + list(
            mesh_mod._BASE_RULES), name
        # the alias lowered onto a spec derives the same bytes
        spec = MeshSpec.from_strategy(name, **_ALIAS_SIZES[name])
        assert logical_axis_rules(spec) == logical_axis_rules(name), name


def test_from_strategy_rejects_unknown():
    with pytest.raises(MeshSpecError, match="unknown strategy"):
        MeshSpec.from_strategy("zz")
    with pytest.raises(ValueError, match="unknown strategy"):
        logical_axis_rules("zz")


def test_derived_rules_mirror_axes_registry():
    """The jax-free shardlint mirror (analysis/axes.py) regenerates the
    SAME rules from the same template — for the legacy names AND for
    every generated dp*{fsdp,pipe,seq,model} product (SD602 coverage
    iterates these)."""
    for name, rules in mesh_mod._STRATEGY_RULES.items():
        assert tuple(tuple(r) for r in rules) == \
            axes_registry.STRATEGY_RULES[name], name
    for name, rules in axes_registry.PRODUCT_RULES.items():
        active = frozenset(
            a for a in name.split("*")[1:])  # "dp*fsdp*pipe" -> axes
        assert rules == tuple(tuple(r) for r in derive_rules(active)), name
    # the generated products are visible to SD602's coverage iteration
    assert "dp*fsdp*pipe" in axes_registry.strategies()


# -- composed-strategy parity --------------------------------------------

_PRODUCTS = ["dp=8", "dp=4,fsdp=2", "dp=4,pipe=2"]


def _nodrop_config(tiny_config):
    cfg = tiny_config.to_dict()
    cfg["hidden_dropout_prob"] = 0.0
    cfg["attention_probs_dropout_prob"] = 0.0
    return BertConfig.from_dict(cfg)


def _unpacked_batch(rng, b, seq, vocab):
    return {
        "input_ids": rng.integers(0, vocab, (b, seq)).astype(np.int32),
        "segment_ids": rng.integers(0, 2, (b, seq)).astype(np.int32),
        "input_mask": np.ones((b, seq), np.int32),
        "masked_lm_labels": np.where(
            rng.random((b, seq)) < 0.2,
            rng.integers(0, vocab, (b, seq)), -1).astype(np.int32),
        "next_sentence_labels": rng.integers(0, 2, (b,)).astype(np.int32),
    }


def _packed_batch(rng, b, seq, vocab, k=2):
    """Each row holds two back-to-back sequences (block-diagonal mask via
    sequence_ids) plus a padded tail; NSP labels/cls positions are [B, K]
    with -1 padding, the packed collation layout (data/packing.py)."""
    batch = {
        "input_ids": rng.integers(0, vocab, (b, seq)).astype(np.int32),
        "segment_ids": rng.integers(0, 2, (b, seq)).astype(np.int32),
        "input_mask": np.zeros((b, seq), np.int32),
        "masked_lm_labels": np.full((b, seq), -1, np.int32),
        "next_sentence_labels": np.full((b, k), -1, np.int32),
        "sequence_ids": np.zeros((b, seq), np.int32),
        "cls_positions": np.zeros((b, k), np.int32),
    }
    for i in range(b):
        n1 = int(rng.integers(seq // 4, seq // 2))
        n2 = int(rng.integers(seq // 4, seq // 2))
        batch["input_mask"][i, :n1 + n2] = 1
        batch["sequence_ids"][i, :n1] = 1
        batch["sequence_ids"][i, n1:n1 + n2] = 2
        batch["cls_positions"][i] = [0, n1]
        batch["next_sentence_labels"][i] = rng.integers(0, 2, 2)
        lab = np.where(rng.random(n1 + n2) < 0.2,
                       rng.integers(0, vocab, n1 + n2), -1)
        batch["masked_lm_labels"][i, :n1 + n2] = lab
    return batch


def _step_once(model, spec_text, host, packed, n_mb, seq, host_params):
    spec = MeshSpec.parse(spec_text)
    spec.validate(n_devices=8, packed=packed)
    mesh = create_mesh(spec.mesh_config())
    rules = logical_axis_rules(spec)
    schedule = optim.warmup_poly_schedule(1e-3, 0.25, 100)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    sample = (jnp.zeros((1, seq), jnp.int32),) * 3
    dims = {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
            "masked_lm_labels": 3,
            "next_sentence_labels": 3 if packed else 2}
    if packed:
        dims.update({"sequence_ids": 3, "cls_positions": 3})
    pipe = spec.pipe > 1
    accum = n_mb if pipe else 1
    with mesh:
        shardings = pretrain.state_shardings(mesh, model, rules, sample)
        b_shardings = pretrain.batch_shardings(
            mesh, dims, seq_sharded=spec.seq > 1)
        state = pretrain.make_init_fn(model, tx, sample, shardings)(
            jax.random.PRNGKey(5))
        # Same host-side init for every cell: with non-partitionable
        # threefry (this jax's default) a jitted init's DRAWS depend on
        # the param sharding, so parity must start from shared weights —
        # exactly what elastic resume does. LAMB's opt state is zeros,
        # value-independent, so the per-mesh init's is reusable.
        state = dataclasses.replace(
            state, params=jax.device_put(host_params, shardings.params))
        if pipe:
            step = pretrain.make_pp_train_step(
                model, tx, mesh, schedule=schedule, next_sentence=True,
                shardings=shardings, batch_shardings_=b_shardings,
                max_pred_per_seq=8)
        else:
            step = pretrain.make_train_step(
                model, tx, schedule=schedule, next_sentence=True,
                shardings=shardings, batch_shardings_=b_shardings,
                max_pred_per_seq=8)
        batch = pretrain.put_batch(
            pretrain.stack_microbatches(host, accum), b_shardings)
        state, metrics = step(state, batch)
        return float(metrics["loss"]), jax.device_get(state.params)


@pytest.mark.parametrize("packed", [False, True], ids=["unpacked", "packed"])
def test_composed_strategy_parity(tiny_config, devices, packed):
    """The parity matrix: (packed|unpacked) x {dp, dp*fsdp, dp*pipe} —
    one fp32 optimizer step from the same init and batch must agree with
    plain dp to 1e-6 (composition is a layout, never a different model).
    Dropout off: the paths fold the step PRNG differently."""
    cfg = _nodrop_config(tiny_config)
    model = BertForPreTraining(cfg, dtype=jnp.float32)
    # n_mb=2 keeps the pipe cell's microbatch (b/n_mb = 4) divisible by
    # its data axis (dp=4).
    b, seq, n_mb = 8, 32, 2
    rng = np.random.default_rng(11)
    host = (_packed_batch(rng, b, seq, cfg.vocab_size) if packed
            else _unpacked_batch(rng, b, seq, cfg.vocab_size))
    sample = (jnp.zeros((1, seq), jnp.int32),) * 3
    host_params = jax.device_get(nn.unbox(
        model.init(jax.random.PRNGKey(5), *sample))["params"])

    results, skipped = {}, {}
    for text in _PRODUCTS:
        try:
            results[text] = _step_once(
                model, text, host, packed, n_mb, seq, host_params)
        except Exception as e:  # jax-version limitation, not a parity bug
            if "PartitionId" in str(e) or "shard_map" in str(e):
                skipped[text] = str(e)
            else:
                raise
    assert "dp=8" in results and "dp=4,fsdp=2" in results, skipped
    loss_dp, params_dp = results["dp=8"]
    flat_dp = jax.tree_util.tree_leaves_with_path(params_dp)
    for text, (loss_x, params_x) in results.items():
        if text == "dp=8":
            continue
        np.testing.assert_allclose(loss_x, loss_dp, rtol=1e-6, err_msg=text)
        flat_x = {jax.tree_util.keystr(kp): leaf for kp, leaf in
                  jax.tree_util.tree_leaves_with_path(params_x)}
        for kp, leaf in flat_dp:
            np.testing.assert_allclose(
                np.asarray(flat_x[jax.tree_util.keystr(kp)]),
                np.asarray(leaf), atol=1e-6,
                err_msg=f"{text} {jax.tree_util.keystr(kp)}")
    if skipped:
        pytest.skip(
            "parity held for {}; pipe cells need the jax>=0.5 shard_map "
            "typing (tests/test_pipeline.py): {}".format(
                sorted(results), sorted(skipped)))


# -- elastic sharded resume ----------------------------------------------


def _make_step_fn(model, mesh, rules, seq):
    schedule = optim.warmup_poly_schedule(1e-3, 0.25, 100)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    sample = (jnp.zeros((1, seq), jnp.int32),) * 3
    dims = {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
            "masked_lm_labels": 3, "next_sentence_labels": 2}
    shardings = pretrain.state_shardings(mesh, model, rules, sample)
    b_shardings = pretrain.batch_shardings(mesh, dims)
    init_fn = pretrain.make_init_fn(model, tx, sample, shardings)
    step = pretrain.make_train_step(
        model, tx, schedule=schedule, next_sentence=True,
        shardings=shardings, batch_shardings_=b_shardings,
        max_pred_per_seq=8)
    return init_fn, step, shardings, b_shardings


def test_elastic_sharded_resume_8_to_4(tiny_config, devices, tmp_path):
    """Save 8-way sharded mid-run, resume on a 4-device mesh: the
    per-step loss trajectory must be EXACT (rtol 1e-6) vs the
    uninterrupted 8-way run — the sharded layout stores topology-free
    slice records, and restore re-shards under the resuming mesh."""
    cfg = _nodrop_config(tiny_config)
    model = BertForPreTraining(cfg, dtype=jnp.float32)
    b, seq, n_steps, cut = 8, 32, 4, 2
    rng = np.random.default_rng(3)
    hosts = [_unpacked_batch(rng, b, seq, cfg.vocab_size)
             for _ in range(n_steps)]

    spec8 = MeshSpec.parse("dp=8")
    mesh8 = create_mesh(spec8.mesh_config())
    with mesh8:
        init8, step8, _, bsh8 = _make_step_fn(
            model, mesh8, logical_axis_rules(spec8), seq)
        state = init8(jax.random.PRNGKey(7))
        ref_losses = []
        for i in range(n_steps):
            batch = pretrain.put_batch(
                pretrain.stack_microbatches(hosts[i], 1), bsh8)
            if i == cut:
                ckpt.save_checkpoint(
                    str(tmp_path), i,
                    {"model": state.params, "optimizer": state.opt_state},
                    layout="sharded", mesh_spec=spec8.as_dict())
            state, metrics = step8(state, batch)
            ref_losses.append(float(metrics["loss"]))

    # the index records the saving topology for --strict audits
    path = ckpt.checkpoint_path(str(tmp_path), cut)
    manifest = integrity.read_manifest(path)
    assert manifest["mesh_spec"] == {k: int(v) for k, v in
                                     spec8.as_dict().items()}
    assert manifest["layout"] == "sharded"
    ok, reason = integrity.validate_mesh_spec(manifest)
    assert ok, reason

    # resume on HALF the devices: a 4-way dp mesh
    spec4 = MeshSpec.parse("dp=4")
    mesh4 = create_mesh(spec4.mesh_config(), devices=jax.devices()[:4])
    with mesh4:
        init4, step4, sh4, bsh4 = _make_step_fn(
            model, mesh4, logical_axis_rules(spec4), seq)
        loaded = ckpt.load_checkpoint(path)
        abstract = jax.eval_shape(init4, jax.random.PRNGKey(7))
        state4 = pretrain.TrainState(
            params=jax.device_put(
                ckpt.restore_tree(abstract.params, loaded["model"]),
                sh4.params),
            opt_state=jax.device_put(
                ckpt.restore_tree(abstract.opt_state, loaded["optimizer"]),
                sh4.opt_state),
            rng=init4(jax.random.PRNGKey(7)).rng)
        for i in range(cut, n_steps):
            batch = pretrain.put_batch(
                pretrain.stack_microbatches(hosts[i], 1), bsh4)
            state4, metrics = step4(state4, batch)
            np.testing.assert_allclose(
                float(metrics["loss"]), ref_losses[i], rtol=1e-6,
                err_msg=f"resumed step {i}")


# -- async sharded save: donation safety ----------------------------------


def test_async_sharded_save_donation_safe_dp_fsdp(devices, tmp_path):
    """save_checkpoint(async_write=True, layout='sharded') under a
    dp x fsdp mesh must snapshot before returning: donating (and thereby
    invalidating) the live buffers right after the call cannot corrupt
    the written checkpoint — the PR 6 gap (sharded async saves falling
    back to a synchronous gather) is closed."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = MeshSpec.parse("dp=2,fsdp=4")
    mesh = create_mesh(spec.mesh_config())
    sharding = NamedSharding(mesh, P(("data", "fsdp")))
    value = np.arange(64, dtype=np.float32)
    live = {"model": {"w": jax.device_put(value, sharding)},
            "epoch": 1}

    ckpt.save_checkpoint(str(tmp_path), 3, live, async_write=True,
                         layout="sharded", mesh_spec=spec.as_dict())
    # Donate the live buffer immediately — training's next step does
    # exactly this. A save that aliased it would now serialize garbage.
    bump = jax.jit(lambda x: x + 1.0, donate_argnums=0)
    live["model"]["w"] = bump(live["model"]["w"])
    ckpt.wait_for_pending_save(str(tmp_path))

    loaded = ckpt.load_checkpoint(ckpt.checkpoint_path(str(tmp_path), 3))
    np.testing.assert_array_equal(loaded["model"]["w"], value)
    assert loaded["epoch"] == 1
    # shard files carry their own verifiable sidecars
    status, detail = integrity.verify_checkpoint(
        ckpt.checkpoint_path(str(tmp_path), 3))
    assert status == integrity.VERIFIED, detail


def test_sharded_load_detects_missing_shard(devices, tmp_path):
    """A sharded index whose shard file disappeared must fail loudly
    (CORRUPT via the manifest chase; CheckpointCorruptError on load),
    never restore zeros."""
    import os

    spec = MeshSpec.parse("dp=8")
    mesh = create_mesh(spec.mesh_config())
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(np.ones((8, 4), np.float32),
                       NamedSharding(mesh, P("data")))
    ckpt.save_checkpoint(str(tmp_path), 1, {"model": {"w": x}},
                         layout="sharded", mesh_spec=spec.as_dict())
    path = ckpt.checkpoint_path(str(tmp_path), 1)
    shard = str(tmp_path / "ckpt_1.shard0of1.msgpack")
    os.unlink(shard)
    status, detail = integrity.verify_checkpoint(path)
    assert status == integrity.CORRUPT and "shard" in detail
    with pytest.raises(Exception):
        ckpt.load_checkpoint(path)
