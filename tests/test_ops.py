"""Op-library tests: Pallas kernels vs the XLA reference paths.

Kernels run in interpret mode on CPU (ops/pallas/common.py), so numerical
agreement here carries to the compiled TPU path.
"""

import jax
import jax.numpy as jnp
import numpy as np

from bert_pytorch_tpu import ops


def test_layer_norm_pallas_matches_xla():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(6, 16, 128)), jnp.float32)
    scale = jnp.asarray(rng.normal(size=(128,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(128,)), jnp.float32)
    ref = ops.layer_norm(x, scale, bias, eps=1e-12, backend="xla")
    out = ops.layer_norm(x, scale, bias, eps=1e-12, backend="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_layer_norm_pallas_grads_match():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 128)), jnp.float32)
    scale = jnp.asarray(rng.normal(size=(128,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(128,)), jnp.float32)

    def loss(backend):
        def f(x, s, b):
            return jnp.sum(jnp.sin(ops.layer_norm(x, s, b, backend=backend)))

        return jax.grad(f, argnums=(0, 1, 2))(x, scale, bias)

    gx_ref, gs_ref, gb_ref = loss("xla")
    gx, gs, gb = loss("pallas")
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_ref), atol=1e-4)


def _qkv(batch=2, seq=64, heads=2, depth=32, seed=0):
    rng = np.random.default_rng(seed)
    shp = (batch, seq, heads, depth)
    q = jnp.asarray(rng.normal(size=shp), jnp.float32)
    k = jnp.asarray(rng.normal(size=shp), jnp.float32)
    v = jnp.asarray(rng.normal(size=shp), jnp.float32)
    mask = np.ones((batch, seq), np.int32)
    mask[:, seq - 5 :] = 0
    bias = ops.attention.make_attention_bias(jnp.asarray(mask))
    return q, k, v, bias


def test_flash_attention_matches_xla():
    q, k, v, bias = _qkv()
    ref = ops.dot_product_attention(q, k, v, bias=bias, backend="xla")
    out = ops.dot_product_attention(q, k, v, bias=bias, backend="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_grads_match():
    q, k, v, bias = _qkv(batch=1, seq=32, heads=2, depth=16)

    def make_loss(backend):
        def f(q, k, v):
            out = ops.dot_product_attention(q, k, v, bias=bias, backend=backend)
            return jnp.sum(jnp.tanh(out))

        return jax.grad(f, argnums=(0, 1, 2))

    ref = make_loss("xla")(q, k, v)
    got = make_loss("pallas")(q, k, v)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=2e-4)


def test_flash_attention_bf16_matches_xla():
    """Exercise the mixed-precision path: bf16 operands with fp32 softmax and
    accumulation (the training dtype). The fp32 tests above collapse the
    kernel's .astype(v.dtype) operand casts to no-ops; this one doesn't."""
    q, k, v, bias = _qkv(batch=1, seq=64, heads=2, depth=32)
    q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    ref = ops.dot_product_attention(q, k, v, bias=bias, backend="xla")
    out = ops.dot_product_attention(q, k, v, bias=bias, backend="pallas")
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )

    def make_loss(backend):
        def f(q, k, v):
            o = ops.dot_product_attention(q, k, v, bias=bias, backend=backend)
            return jnp.sum(jnp.tanh(o.astype(jnp.float32)))

        return jax.grad(f, argnums=(0, 1, 2))

    ref_g = make_loss("xla")(q, k, v)
    got_g = make_loss("pallas")(q, k, v)
    for r, g in zip(ref_g, got_g):
        assert g.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32), atol=5e-2
        )


def test_global_norm_and_clip():
    tree = {"a": jnp.asarray([3.0, 4.0]), "b": jnp.zeros((2, 2))}
    assert np.isclose(float(ops.global_norm(tree)), 5.0)
    clipped, norm = ops.clip_by_global_norm(tree, 1.0)
    assert np.isclose(float(norm), 5.0)
    assert np.isclose(float(ops.global_norm(clipped)), 1.0, atol=1e-4)
    # already within bounds -> unchanged
    same, _ = ops.clip_by_global_norm(tree, 10.0)
    np.testing.assert_allclose(np.asarray(same["a"]), np.asarray(tree["a"]))


def test_act2fn_bias_variants():
    x = jnp.asarray([[0.5, -0.3]], jnp.float32)
    b = jnp.asarray([0.1, 0.2], jnp.float32)
    np.testing.assert_allclose(
        np.asarray(ops.bias_gelu(b, x)), np.asarray(ops.gelu(x + b)), atol=1e-6
    )


def test_flash_attention_bias_grad_matches():
    """dbias comes out of the fused dkv kernel — check it against autodiff."""
    q, k, v, bias = _qkv(batch=1, seq=32, heads=2, depth=16)

    def make_loss(backend):
        def f(bias):
            out = ops.dot_product_attention(q, k, v, bias=bias, backend=backend)
            return jnp.sum(jnp.tanh(out))

        return jax.grad(f)

    ref = make_loss("xla")(bias)
    got = make_loss("pallas")(bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4)


def test_pallas_dropout_falls_back_on_cpu():
    """Interpret mode has no TPU PRNG; active dropout must route to XLA and
    still produce a stochastic, correctly-scaled result."""
    q, k, v, bias = _qkv(batch=1, seq=32, heads=2, depth=16)
    out = ops.dot_product_attention(
        q, k, v, bias=bias, backend="pallas",
        dropout_rng=jax.random.PRNGKey(0), dropout_rate=0.5,
        deterministic=False)
    ref = ops.dot_product_attention(q, k, v, bias=bias, backend="xla")
    assert out.shape == ref.shape
    assert not np.allclose(np.asarray(out), np.asarray(ref))


def test_pallas_dropout_on_tpu():
    """In-kernel dropout statistics + determinism (real chip only)."""
    import pytest

    if jax.default_backend() != "tpu":
        pytest.skip("TPU hardware PRNG has no interpret-mode lowering")
    from bert_pytorch_tpu.ops.pallas.attention import flash_attention

    q, k, v, bias = _qkv(batch=2, seq=128, heads=4, depth=64)
    base = flash_attention(q, k, v, bias=bias)
    # Exercise BOTH PRNG impls: rbg key data duplicates its halves
    # ([t0,t1,t0,t1]), which once collapsed a naive xor-fold seed to 0.
    for impl in ("threefry2x32", "rbg"):
        with jax.default_prng_impl(impl):
            key = jax.random.PRNGKey(7)
            d1 = flash_attention(q, k, v, bias=bias, dropout_rate=0.1,
                                 dropout_rng=key)
            d2 = flash_attention(q, k, v, bias=bias, dropout_rate=0.1,
                                 dropout_rng=key)
            d3 = flash_attention(q, k, v, bias=bias, dropout_rate=0.1,
                                 dropout_rng=jax.random.PRNGKey(8))
            s1, s2 = jax.random.split(key)
            e1 = flash_attention(q, k, v, bias=bias, dropout_rate=0.1,
                                 dropout_rng=s1)
            e2 = flash_attention(q, k, v, bias=bias, dropout_rate=0.1,
                                 dropout_rng=s2)
            assert bool(jnp.all(d1 == d2)), impl  # same key -> same masks
            assert bool(jnp.any(d1 != d3)), impl  # fresh keys differ
            assert bool(jnp.any(e1 != e2)), impl  # split keys differ
            assert bool(jnp.any(d1 != base)), impl
    # E[dropout(out)] -> out: mean over seeds approaches the dense result
    acc = sum(
        flash_attention(q, k, v, bias=bias, dropout_rate=0.1,
                        dropout_rng=jax.random.PRNGKey(i))
        for i in range(32)
    )
    rel = float(jnp.abs(acc / 32 - base).mean() / jnp.abs(base).mean())
    assert rel < 0.1
