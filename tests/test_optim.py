"""Optimizer and schedule tests against independent numpy references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu import optim


def test_poly_schedule_values():
    # BERT phase-1 recipe shape: warmup fraction then (1-p)^0.5 decay.
    sched = optim.warmup_poly_schedule(6e-3, warmup=0.2843, total_steps=7038)
    # step 0 -> last_epoch 1 -> lr = base * (1/7038)/0.2843
    got = float(sched(jnp.asarray(0)))
    want = 6e-3 * (1 / 7038) / 0.2843
    assert np.isclose(got, want, rtol=1e-6)
    # past warmup: poly decay
    t = 5000
    got = float(sched(jnp.asarray(t)))
    want = 6e-3 * (1.0 - (t + 1) / 7038) ** 0.5
    assert np.isclose(got, want, rtol=1e-6)
    # end of schedule: lr ~ 0, never negative
    assert float(sched(jnp.asarray(7037))) == 0.0
    assert float(sched(jnp.asarray(8000))) == 0.0


def test_linear_schedule_values():
    sched = optim.warmup_linear_schedule(4e-4, warmup=0.06, total_steps=1000)
    t = 500
    progress = (t + 1) / 1000
    want = 4e-4 * (progress - 1.0) / (0.06 - 1.0)
    assert np.isclose(float(sched(jnp.asarray(t))), want, rtol=1e-6)


def test_make_schedule_rejects_unknown():
    with pytest.raises(ValueError):
        optim.make_schedule("exponential", 1e-3, 0.1, 100)


def _numpy_lamb_step(p, g, m, v, t, lr, b1, b2, eps, wd):
    """Independent LAMB reference (bias-corrected, trust ratio)."""
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    m_hat = m / (1 - b1**t)
    v_hat = v / (1 - b2**t)
    upd = m_hat / (np.sqrt(v_hat) + eps) + wd * p
    p_norm = np.linalg.norm(p)
    u_norm = np.linalg.norm(upd)
    ratio = p_norm / u_norm if p_norm > 0 and u_norm > 0 else 1.0
    return p - lr * ratio * upd, m, v


def test_lamb_matches_numpy_reference():
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(4, 3)).astype(np.float32)
    params = {"w": jnp.asarray(p0)}
    tx = optim.lamb(1e-2, max_grad_norm=None, weight_decay=0.01)
    state = tx.init(params)

    p_np, m_np, v_np = p0.copy(), np.zeros_like(p0), np.zeros_like(p0)
    for t in range(1, 4):
        g = rng.normal(size=(4, 3)).astype(np.float32)
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        p_np, m_np, v_np = _numpy_lamb_step(
            p_np, g, m_np, v_np, t, 1e-2, 0.9, 0.999, 1e-6, 0.01
        )
        np.testing.assert_allclose(np.asarray(params["w"]), p_np, rtol=2e-5, atol=1e-7)


def test_lamb_grad_clipping_is_global():
    params = {"a": jnp.ones((2,)), "b": jnp.ones((2,))}
    tx = optim.lamb(1e-2, max_grad_norm=1.0)
    state = tx.init(params)
    huge = {"a": jnp.full((2,), 100.0), "b": jnp.full((2,), 100.0)}
    updates1, s1 = tx.update(huge, state, params)
    scaled = jax.tree_util.tree_map(lambda g: g / 200.0, huge)
    updates2, _ = tx.update(scaled, state, params)
    # after global clipping to norm 1, both give the same moments direction
    gnorm = float(np.sqrt(4 * 100.0**2))
    expect_scale = 1.0 / gnorm
    # the clipped grads equal huge * expect_scale; just check updates finite & equal-ish
    for k in ("a", "b"):
        np.testing.assert_allclose(
            np.asarray(updates1[k]),
            np.asarray(updates2[k] / (0.5 / (100.0 * expect_scale))),
            rtol=1e-3,
        )


def test_weight_decay_mask_routes_decay():
    params = {
        "dense": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))},
        "layer_norm": {"scale": jnp.ones((2,)), "bias": jnp.zeros((2,))},
    }
    mask = optim.no_decay_mask(params)
    assert mask["dense"]["kernel"] is True
    assert mask["dense"]["bias"] is False
    assert mask["layer_norm"]["scale"] is False
    assert mask["layer_norm"]["bias"] is False


def test_bert_adam_no_bias_correction_and_schedule():
    """BertAdam semantics (optimization.py:113-174): no bias correction,
    schedule evaluated at pre-update count."""
    p0 = np.full((3,), 2.0, np.float32)
    g = np.full((3,), 0.5, np.float32)
    lr, warmup, t_total = 1e-2, 0.5, 10
    tx = optim.bert_adam(
        lr, schedule="warmup_linear", warmup=warmup, t_total=t_total,
        weight_decay=0.0, max_grad_norm=-1,
    )
    params = {"w": jnp.asarray(p0)}
    state = tx.init(params)
    updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
    # step count 0 -> progress 0 -> lr_scheduled = 0 => first update is zero.
    np.testing.assert_allclose(np.asarray(updates["w"]), np.zeros(3), atol=1e-12)
    # second step: count=1, progress=0.1 < warmup -> lr*0.1/0.5
    updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
    m = 0.1 * 0.5 * (1 - 0.9) + 0.9 * (0.5 * (1 - 0.9))  # b1 EMA after 2 identical grads
    m = (1 - 0.9) * 0.5 + 0.9 * ((1 - 0.9) * 0.5)
    v = (1 - 0.999) * 0.25 + 0.999 * ((1 - 0.999) * 0.25)
    want = -(lr * (0.1 / 0.5)) * (m / (np.sqrt(v) + 1e-6))
    np.testing.assert_allclose(np.asarray(updates["w"]), np.full(3, want), rtol=1e-5)


def test_reset_count_phase_surgery():
    params = {"w": jnp.ones((2,))}
    tx = optim.lamb(1e-2)
    state = tx.init(params)
    for _ in range(5):
        _, state = tx.update({"w": jnp.ones((2,))}, state, params)
    assert int(state.count) == 5
    state2 = optim.reset_count(state, 0)
    assert int(state2.count) == 0
    np.testing.assert_allclose(np.asarray(state2.mu["w"]), np.asarray(state.mu["w"]))


def test_adamw_converges_quadratic():
    # sanity: minimize ||x - 3||^2
    tx = optim.adamw(0.1, weight_decay=0.0)
    params = {"x": jnp.zeros((2,))}
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        grads = jax.grad(lambda p: jnp.sum((p["x"] - 3.0) ** 2))(params)
        updates, state = tx.update(grads, state, params)
        return jax.tree_util.tree_map(lambda p, u: p + u, params, updates), state

    for _ in range(200):
        params, state = step(params, state)
    np.testing.assert_allclose(np.asarray(params["x"]), np.full(2, 3.0), atol=0.05)
