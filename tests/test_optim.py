"""Optimizer and schedule tests against independent numpy references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu import optim


def test_poly_schedule_values():
    # BERT phase-1 recipe shape: warmup fraction then (1-p)^0.5 decay.
    sched = optim.warmup_poly_schedule(6e-3, warmup=0.2843, total_steps=7038)
    # step 0 -> last_epoch 1 -> lr = base * (1/7038)/0.2843
    got = float(sched(jnp.asarray(0)))
    want = 6e-3 * (1 / 7038) / 0.2843
    assert np.isclose(got, want, rtol=1e-6)
    # past warmup: poly decay
    t = 5000
    got = float(sched(jnp.asarray(t)))
    want = 6e-3 * (1.0 - (t + 1) / 7038) ** 0.5
    assert np.isclose(got, want, rtol=1e-6)
    # end of schedule: lr ~ 0, never negative
    assert float(sched(jnp.asarray(7037))) == 0.0
    assert float(sched(jnp.asarray(8000))) == 0.0


def test_linear_schedule_values():
    sched = optim.warmup_linear_schedule(4e-4, warmup=0.06, total_steps=1000)
    t = 500
    progress = (t + 1) / 1000
    want = 4e-4 * (progress - 1.0) / (0.06 - 1.0)
    assert np.isclose(float(sched(jnp.asarray(t))), want, rtol=1e-6)


def test_make_schedule_rejects_unknown():
    with pytest.raises(ValueError):
        optim.make_schedule("exponential", 1e-3, 0.1, 100)


def test_cosine_schedule_values():
    # Reference formula (schedulers.py:66): past warmup the decay is
    # 0.5*(1+cos(pi + progress)) — pi ADDED to progress, a reference quirk
    # kept verbatim for parity.
    import math

    sched = optim.warmup_cosine_schedule(1e-3, warmup=0.1, total_steps=1000)
    # warmup region: linear ramp progress/warmup with the +1 offset
    t = 49
    want = 1e-3 * ((t + 1) / 1000) / 0.1
    assert np.isclose(float(sched(jnp.asarray(t))), want, rtol=1e-6)
    # decay region
    t = 600
    progress = (t + 1) / 1000
    want = 1e-3 * 0.5 * (1.0 + math.cos(math.pi + progress))
    assert np.isclose(float(sched(jnp.asarray(t))), want, rtol=1e-5)


def test_constant_schedule_values():
    sched = optim.warmup_constant_schedule(2e-5, warmup=0.2, total_steps=500)
    t = 59  # progress 0.12 < warmup
    want = 2e-5 * ((t + 1) / 500) / 0.2
    assert np.isclose(float(sched(jnp.asarray(t))), want, rtol=1e-6)
    # past warmup: exactly base_lr, forever
    for t in (100, 499, 5000):
        assert np.isclose(float(sched(jnp.asarray(t))), 2e-5, rtol=1e-6)


def test_exp_decay_exp_schedule_values():
    # Reference warmup_exp_decay_exp (schedulers.py:144-158): NO +1 offset
    # (driven with the raw global step), degree-2 polynomial warmup, then
    # decay_rate**((step - warmup_end)/decay_steps).
    sched = optim.warmup_exp_decay_exp_schedule(
        1e-3, decay_rate=0.5, decay_steps=100, total_steps=1000,
        warmup=0.01, degree=2.0)
    t = 5  # x = 0.005 < warmup
    want = 1e-3 * (0.005 / 0.01) ** 2.0
    assert np.isclose(float(sched(jnp.asarray(t))), want, rtol=1e-6)
    t = 300
    want = 1e-3 * 0.5 ** ((300 - 10) / 100)
    assert np.isclose(float(sched(jnp.asarray(t))), want, rtol=1e-5)
    # warmup == 0 short-circuits to base_lr (reference returns 1.0)
    flat = optim.warmup_exp_decay_exp_schedule(
        1e-3, decay_rate=0.5, decay_steps=100, total_steps=1000, warmup=0.0)
    assert np.isclose(float(flat(jnp.asarray(123))), 1e-3, rtol=1e-6)


def _numpy_lamb_step(p, g, m, v, t, lr, b1, b2, eps, wd):
    """Independent LAMB reference (bias-corrected, trust ratio)."""
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    m_hat = m / (1 - b1**t)
    v_hat = v / (1 - b2**t)
    upd = m_hat / (np.sqrt(v_hat) + eps) + wd * p
    p_norm = np.linalg.norm(p)
    u_norm = np.linalg.norm(upd)
    ratio = p_norm / u_norm if p_norm > 0 and u_norm > 0 else 1.0
    return p - lr * ratio * upd, m, v


def test_lamb_matches_numpy_reference():
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(4, 3)).astype(np.float32)
    params = {"w": jnp.asarray(p0)}
    tx = optim.lamb(1e-2, max_grad_norm=None, weight_decay=0.01)
    state = tx.init(params)

    p_np, m_np, v_np = p0.copy(), np.zeros_like(p0), np.zeros_like(p0)
    for t in range(1, 4):
        g = rng.normal(size=(4, 3)).astype(np.float32)
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        p_np, m_np, v_np = _numpy_lamb_step(
            p_np, g, m_np, v_np, t, 1e-2, 0.9, 0.999, 1e-6, 0.01
        )
        np.testing.assert_allclose(np.asarray(params["w"]), p_np, rtol=2e-5, atol=1e-7)


def test_lamb_grad_clipping_is_global():
    params = {"a": jnp.ones((2,)), "b": jnp.ones((2,))}
    tx = optim.lamb(1e-2, max_grad_norm=1.0)
    state = tx.init(params)
    huge = {"a": jnp.full((2,), 100.0), "b": jnp.full((2,), 100.0)}
    updates1, s1 = tx.update(huge, state, params)
    scaled = jax.tree_util.tree_map(lambda g: g / 200.0, huge)
    updates2, _ = tx.update(scaled, state, params)
    # after global clipping to norm 1, both give the same moments direction
    gnorm = float(np.sqrt(4 * 100.0**2))
    expect_scale = 1.0 / gnorm
    # the clipped grads equal huge * expect_scale; just check updates finite & equal-ish
    for k in ("a", "b"):
        np.testing.assert_allclose(
            np.asarray(updates1[k]),
            np.asarray(updates2[k] / (0.5 / (100.0 * expect_scale))),
            rtol=1e-3,
        )


def test_weight_decay_mask_routes_decay():
    params = {
        "dense": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))},
        "layer_norm": {"scale": jnp.ones((2,)), "bias": jnp.zeros((2,))},
    }
    mask = optim.no_decay_mask(params)
    assert mask["dense"]["kernel"] is True
    assert mask["dense"]["bias"] is False
    assert mask["layer_norm"]["scale"] is False
    assert mask["layer_norm"]["bias"] is False


def test_bert_adam_no_bias_correction_and_schedule():
    """BertAdam semantics (optimization.py:113-174): no bias correction,
    schedule evaluated at pre-update count."""
    p0 = np.full((3,), 2.0, np.float32)
    g = np.full((3,), 0.5, np.float32)
    lr, warmup, t_total = 1e-2, 0.5, 10
    tx = optim.bert_adam(
        lr, schedule="warmup_linear", warmup=warmup, t_total=t_total,
        weight_decay=0.0, max_grad_norm=-1,
    )
    params = {"w": jnp.asarray(p0)}
    state = tx.init(params)
    updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
    # step count 0 -> progress 0 -> lr_scheduled = 0 => first update is zero.
    np.testing.assert_allclose(np.asarray(updates["w"]), np.zeros(3), atol=1e-12)
    # second step: count=1, progress=0.1 < warmup -> lr*0.1/0.5
    updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
    m = 0.1 * 0.5 * (1 - 0.9) + 0.9 * (0.5 * (1 - 0.9))  # b1 EMA after 2 identical grads
    m = (1 - 0.9) * 0.5 + 0.9 * ((1 - 0.9) * 0.5)
    v = (1 - 0.999) * 0.25 + 0.999 * ((1 - 0.999) * 0.25)
    want = -(lr * (0.1 / 0.5)) * (m / (np.sqrt(v) + 1e-6))
    np.testing.assert_allclose(np.asarray(updates["w"]), np.full(3, want), rtol=1e-5)


def test_reset_count_phase_surgery():
    params = {"w": jnp.ones((2,))}
    tx = optim.lamb(1e-2)
    state = tx.init(params)
    for _ in range(5):
        _, state = tx.update({"w": jnp.ones((2,))}, state, params)
    assert int(state.count) == 5
    state2 = optim.reset_count(state, 0)
    assert int(state2.count) == 0
    np.testing.assert_allclose(np.asarray(state2.mu["w"]), np.asarray(state.mu["w"]))


def test_adamw_converges_quadratic():
    # sanity: minimize ||x - 3||^2
    tx = optim.adamw(0.1, weight_decay=0.0)
    params = {"x": jnp.zeros((2,))}
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        grads = jax.grad(lambda p: jnp.sum((p["x"] - 3.0) ** 2))(params)
        updates, state = tx.update(grads, state, params)
        return jax.tree_util.tree_map(lambda p, u: p + u, params, updates), state

    for _ in range(200):
        params, state = step(params, state)
    np.testing.assert_allclose(np.asarray(params["x"]), np.full(2, 3.0), atol=0.05)


# ---------------------------------------------------------------------------
# LAMB cross-validation against an INDEPENDENT implementation (optax.lamb)
# — not the in-repo numpy re-derivation — plus the trust-ratio edge cases
# where large-batch runs go wrong (apex FusedLAMB semantics,
# reference run_pretraining.py:295).
# ---------------------------------------------------------------------------


def _lamb_tree():
    rng = np.random.default_rng(3)
    params = {
        "dense": {
            "kernel": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32),
            "bias": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
        },
        "zero_init": {"kernel": jnp.zeros((4, 4), jnp.float32)},
        "layer_norm": {"scale": jnp.ones((4,), jnp.float32)},
    }
    def grads_for(step):
        g = np.random.default_rng(100 + step)
        return jax.tree_util.tree_map(
            lambda p: jnp.asarray(g.normal(size=p.shape), jnp.float32), params)
    return params, grads_for


def test_lamb_matches_optax_lamb_multi_step():
    """Same updates as optax.lamb (independent implementation: its own
    scale_by_adam / add_decayed_weights / scale_by_trust_ratio chain) over
    several steps, including a zero-initialized param and a masked
    (no-decay) LayerNorm scale."""
    import optax as ox

    from bert_pytorch_tpu import optim

    params, grads_for = _lamb_tree()
    mask = optim.no_decay_mask(params)
    wd, lr = 0.01, 3e-3

    ours = optim.lamb(lr, weight_decay=wd, weight_decay_mask=mask,
                      max_grad_norm=None)
    theirs = ox.lamb(lr, weight_decay=wd, mask=mask)

    p_a, p_b = params, params
    s_a, s_b = ours.init(params), theirs.init(params)
    for step in range(5):
        g = grads_for(step)
        u_a, s_a = ours.update(g, s_a, p_a)
        u_b, s_b = theirs.update(g, s_b, p_b)
        for path_a, path_b in zip(
                jax.tree_util.tree_leaves_with_path(u_a),
                jax.tree_util.tree_leaves_with_path(u_b)):
            np.testing.assert_allclose(
                path_a[1], path_b[1], rtol=2e-5, atol=1e-7,
                err_msg=f"step {step} {path_a[0]}")
        p_a = ox.apply_updates(p_a, u_a)
        p_b = ox.apply_updates(p_b, u_b)


def test_lamb_trust_ratio_zero_param_norm():
    """A zero-initialized parameter has ||p||=0: the trust ratio must be 1
    (not 0, which would freeze the parameter forever)."""
    from bert_pytorch_tpu import optim

    params = {"w": jnp.zeros((4,), jnp.float32)}
    grads = {"w": jnp.ones((4,), jnp.float32)}
    tx = optim.lamb(1.0, weight_decay=0.0, max_grad_norm=None,
                    bias_correction=True)
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    # step 1, bias-corrected adam of constant grad = g/(|g|+eps) ~= sign(g);
    # ratio 1 => update = -lr * 1 * ~1
    np.testing.assert_allclose(updates["w"], -np.ones(4), rtol=1e-4)


def test_lamb_trust_ratio_zero_update_norm():
    """Zero gradient + zero moments + no decay => zero update norm: ratio
    must be 1 and the update exactly zero (no NaN from 0/0)."""
    from bert_pytorch_tpu import optim

    params = {"w": jnp.ones((4,), jnp.float32)}
    grads = {"w": jnp.zeros((4,), jnp.float32)}
    tx = optim.lamb(1.0, weight_decay=0.0, max_grad_norm=None)
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    assert np.all(np.isfinite(updates["w"]))
    np.testing.assert_array_equal(updates["w"], np.zeros(4))


def test_lamb_excluded_group_gets_no_decay():
    """The no-decay group (bias/LayerNorm) must see pure Adam+trust-ratio:
    with zero grads, a decayed param moves and an excluded one does not."""
    from bert_pytorch_tpu import optim

    params = {"dense": {"kernel": jnp.ones((3,), jnp.float32),
                        "bias": jnp.ones((3,), jnp.float32)}}
    grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    tx = optim.lamb(1e-2, weight_decay=0.1,
                    weight_decay_mask=optim.no_decay_mask(params),
                    max_grad_norm=None)
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    assert np.all(updates["dense"]["kernel"] != 0)  # wd-driven update
    np.testing.assert_array_equal(updates["dense"]["bias"], np.zeros(3))


def test_lamb_global_norm_clip_scales_to_max():
    from bert_pytorch_tpu import optim
    from bert_pytorch_tpu.ops.grad_utils import global_norm

    params = {"w": jnp.ones((16,), jnp.float32)}
    grads = {"w": jnp.full((16,), 100.0, jnp.float32)}  # norm 400
    tx = optim.lamb(1e-3, max_grad_norm=1.0, weight_decay=0.0)
    state = tx.init(params)
    _, new_state = tx.update(grads, state, params)
    # the clipped gradient (norm 1.0) is what enters the moments:
    # mu = (1-b1) * g_clipped => ||mu|| = 0.1 * 1.0
    np.testing.assert_allclose(
        float(global_norm(new_state.mu)), 0.1, rtol=1e-4)
