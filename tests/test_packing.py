"""Sequence packing (ISSUE 3): packer, packed data formats, block-diagonal
attention parity (XLA and Pallas), packed-vs-unpacked model/loss parity,
and padding-aware telemetry — including the CPU smoke acceptance run
(packed padding_efficiency >= 1.5x unpacked, lower wall per real token).
"""

import json
import os

import numpy as np
import pytest

from bert_pytorch_tpu.data import (
    DataLoader,
    DistributedSampler,
    PackedPretrainingDataset,
    ShardedPretrainingDataset,
    first_fit_decreasing,
    pack_features,
    write_packed_shard,
)
from bert_pytorch_tpu.telemetry import schema as tschema
from bert_pytorch_tpu.telemetry.step_timer import StepTimer
from bert_pytorch_tpu.tools.make_synthetic_data import make_shard


# -- packer ---------------------------------------------------------------


def test_ffd_respects_capacity_and_pack_limit():
    lengths = [100, 60, 50, 40, 30, 20, 10, 5]
    packs = first_fit_decreasing(lengths, 128, 3)
    seen = sorted(i for p in packs for i in p)
    assert seen == list(range(len(lengths)))  # every sample placed once
    for p in packs:
        assert sum(lengths[i] for i in p) <= 128
        assert 1 <= len(p) <= 3


def test_ffd_overlong_sample_gets_singleton():
    packs = first_fit_decreasing([300, 10], 128, 8)
    assert [sorted(p) for p in sorted(packs, key=min)] == [[0], [1]]


def test_ffd_is_deterministic_and_orders_by_first_member():
    lengths = list(np.random.default_rng(0).integers(5, 120, 50))
    a = first_fit_decreasing(lengths, 128, 8)
    b = first_fit_decreasing(lengths, 128, 8)
    assert a == b
    firsts = [min(p) for p in a]
    assert firsts == sorted(firsts)


def test_pack_features_layout():
    def sample(n, nsp, base):
        ids = np.arange(base, base + n, dtype=np.int32)
        seg = np.zeros(16, np.int32)
        mask = np.zeros(16, np.int32)
        mask[:n] = 1
        labs = np.full(16, -1, np.int32)
        labs[1] = 7
        row = np.zeros(16, np.int32)
        row[:n] = ids
        return [row, seg, mask, labs, np.int32(nsp)]

    row = pack_features([sample(5, 1, 10), sample(7, 0, 50)], 16, 4)
    ids, seg, mask, labs, nsp, seq_ids, cls = row
    assert list(seq_ids) == [1] * 5 + [2] * 7 + [0] * 4
    assert list(mask) == [1] * 12 + [0] * 4
    assert list(nsp) == [1, 0, -1, -1]
    assert list(cls) == [0, 5, 0, 0]
    assert ids[5] == 50 and labs[1] == 7 and labs[6] == 7


# -- datasets -------------------------------------------------------------


@pytest.fixture()
def mixed_shard_dir(tmp_path):
    d = tmp_path / "data"
    d.mkdir()
    for i in range(2):
        make_shard(str(d / f"s{i}.hdf5"), 48, 64, 500, seed=i,
                   mixed_lengths=True)
    return str(d)


def test_on_the_fly_packing_dataset(mixed_shard_dir):
    import glob

    files = sorted(glob.glob(os.path.join(mixed_shard_dir, "*.hdf5")))
    base = ShardedPretrainingDataset(files, 4, 10, 0.15, vocab_size=500,
                                     seed=1)
    packed = PackedPretrainingDataset(base, max_sequences_per_pack=4)
    assert len(packed) < len(base)  # something actually packed
    assert packed.occupancy > 0.75
    for i in (0, len(packed) // 2, len(packed) - 1):
        ids, seg, mask, labs, nsp, seq_ids, cls = packed[i]
        assert (mask == (seq_ids > 0).astype(np.int32)).all()
        valid = nsp != -1
        assert valid.any()
        # every packed sequence starts with [CLS] (id 2 in synthetic data)
        assert (ids[cls[valid]] == 2).all()
        # MLM labels only on real tokens
        assert (labs[seq_ids == 0] == -1).all()
        # ids within a pack ascend contiguously 1..n
        present = sorted(set(seq_ids[seq_ids > 0]))
        assert present == list(range(1, len(present) + 1))

    # loader collation: packed keys appear, NSP becomes [B, K]
    loader = DataLoader(
        packed, DistributedSampler(packed, num_replicas=1, rank=0),
        batch_size=4)
    batch = next(iter(loader))
    assert batch["next_sentence_labels"].shape == (4, 4)
    assert batch["sequence_ids"].shape == (4, 64)
    assert batch["cls_positions"].shape == (4, 4)


def test_offline_packed_shard_roundtrip(tmp_path):
    path = str(tmp_path / "packed.hdf5")
    make_shard(path, 48, 64, 500, seed=0, mixed_lengths=True, packed=True,
               max_sequences_per_pack=4)
    ds = ShardedPretrainingDataset(path, 4, 10, 0.15, vocab_size=500, seed=1)
    assert ds.packed and ds.max_sequences_per_pack == 4
    assert len(ds) < 48
    ids, seg, mask, labs, nsp, seq_ids, cls = ds[0]
    assert (mask == (seq_ids > 0).astype(np.int32)).all()
    valid = nsp != -1
    assert (ids[cls[valid]] == 2).all()
    assert (labs != -1).sum() > 0  # dynamic masking ran per member
    # masked positions never hit specials or pads
    masked = np.nonzero(labs != -1)[0]
    assert (seq_ids[masked] > 0).all()


def test_encode_data_packed_writer(tmp_path):
    """tools/encode_data.py --pack_sequences path: TrainingSample ->
    FFD-packed shard in the data/packing.py layout, loadable by the
    runtime dataset."""
    from bert_pytorch_tpu.tools.encode_data import (
        TrainingSample, write_packed_samples_to_hdf5)

    class FakeTok:
        def token_to_id(self, t):
            return {"[CLS]": 2, "[SEP]": 3}.get(t, 5 + hash(t) % 100)

    rng = np.random.default_rng(0)
    samples = [
        TrainingSample([f"w{rng.integers(1000)}"
                        for _ in range(int(rng.integers(4, 24)))],
                       next_seq_tokens=[f"w{rng.integers(1000)}"
                                        for _ in range(5)],
                       is_random_next=bool(i % 2))
        for i in range(12)
    ]
    path = str(tmp_path / "enc_packed.hdf5")
    n = write_packed_samples_to_hdf5(path, samples, FakeTok(), 64, 4)
    assert 0 < n < len(samples)  # packing actually combined rows
    ds = ShardedPretrainingDataset(path, 4, 10, 0.15, vocab_size=500, seed=0)
    assert ds.packed and len(ds) == n
    ids, _seg, mask, _labs, nsp, seq_ids, cls = ds[0]
    assert (ids[cls[nsp != -1]] == 2).all()  # members start with [CLS]
    assert (mask == (seq_ids > 0).astype(np.int32)).all()


def test_mixed_packed_and_unpacked_shards_rejected(tmp_path):
    a = str(tmp_path / "a.hdf5")
    b = str(tmp_path / "b.hdf5")
    make_shard(a, 8, 64, 500, seed=0)
    make_shard(b, 8, 64, 500, seed=1, mixed_lengths=True, packed=True)
    with pytest.raises(ValueError, match="mix packed and unpacked"):
        ShardedPretrainingDataset([a, b], 4, 10, 0.15, vocab_size=500)


# -- attention: block-diagonal XLA vs Pallas(interpret) -------------------


def _packed_qkv(seed=0, batch=2, seq=64, heads=4, depth=16):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    q, k, v = (jnp.asarray(rng.standard_normal((batch, seq, heads, depth)),
                           jnp.float32) for _ in range(3))
    seq_ids = np.zeros((batch, seq), np.int32)
    seq_ids[0, :20] = 1
    seq_ids[0, 20:45] = 2
    seq_ids[0, 45:60] = 3
    seq_ids[1, :30] = 1
    seq_ids[1, 30:50] = 2
    return q, k, v, jnp.asarray(seq_ids)


def test_block_diagonal_bias_masks_cross_sequence():
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops.attention import make_attention_bias

    seq_ids = jnp.asarray([[1, 1, 2, 0]], jnp.int32)
    bias = np.asarray(make_attention_bias(None, sequence_ids=seq_ids))[0, 0]
    assert bias.shape == (4, 4)
    allowed = bias == 0.0
    expected = np.array([
        [1, 1, 0, 0],
        [1, 1, 0, 0],
        [0, 0, 1, 0],
        [0, 0, 0, 0],  # pad row: everything masked
    ], bool)
    assert (allowed == expected).all()


def test_flash_attention_packed_matches_xla_forward():
    from bert_pytorch_tpu.ops.attention import (dot_product_attention,
                                                make_attention_bias)
    from bert_pytorch_tpu.ops.pallas.attention import flash_attention

    q, k, v, seq_ids = _packed_qkv()
    bias = make_attention_bias(None, sequence_ids=seq_ids)
    ref = dot_product_attention(q, k, v, bias=bias, backend="xla")
    out = flash_attention(q, k, v, sequence_ids=seq_ids)
    real = np.asarray(seq_ids) > 0
    np.testing.assert_allclose(
        np.asarray(out)[real], np.asarray(ref)[real], atol=2e-5)


def test_flash_attention_packed_grads_match_xla():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops.attention import (dot_product_attention,
                                                make_attention_bias)
    from bert_pytorch_tpu.ops.pallas.attention import flash_attention

    q, k, v, seq_ids = _packed_qkv()
    bias = make_attention_bias(None, sequence_ids=seq_ids)
    real = jnp.asarray(np.asarray(seq_ids) > 0)[:, :, None, None]

    def loss(fn):
        def f(q, k, v):
            return jnp.sum(jnp.where(real, fn(q, k, v), 0.0) ** 2)
        return f

    g_ref = jax.grad(
        loss(lambda q, k, v: dot_product_attention(
            q, k, v, bias=bias, backend="xla")), argnums=(0, 1, 2))(q, k, v)
    g_pal = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, sequence_ids=seq_ids)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_packing_rejected_on_ring_backend():
    from bert_pytorch_tpu.ops.attention import dot_product_attention

    q, k, v, seq_ids = _packed_qkv()
    with pytest.raises(ValueError, match="ring"):
        dot_product_attention(q, k, v, backend="ring",
                              sequence_ids=seq_ids)


# -- model parity: packed row == separate rows ----------------------------


def _tiny_model(next_sentence=True):
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining

    cfg = BertConfig(
        vocab_size=200, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        next_sentence=next_sentence)
    return BertForPreTraining(cfg, dtype=jnp.float32), cfg


def test_packed_forward_and_loss_match_unpacked():
    """ISSUE 3 acceptance: the same documents packed into one row vs run
    as separate rows produce identical per-token encoder outputs and
    identical total MLM+NSP loss (fp32, XLA path)."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models.losses import pretraining_loss

    model, _ = _tiny_model()
    rng = np.random.default_rng(0)
    S, l1, l2 = 64, 22, 31
    ids1 = rng.integers(5, 200, l1).astype(np.int32)
    ids2 = rng.integers(5, 200, l2).astype(np.int32)

    up = {
        "ids": np.zeros((2, S), np.int32),
        "seg": np.zeros((2, S), np.int32),
        "mask": np.zeros((2, S), np.int32),
        "labs": np.full((2, S), -1, np.int32),
        "nsp": np.array([1, 0], np.int32),
    }
    up["ids"][0, :l1] = ids1
    up["ids"][1, :l2] = ids2
    up["seg"][0, l1 // 2:l1] = 1
    up["seg"][1, l2 // 2:l2] = 1
    up["mask"][0, :l1] = 1
    up["mask"][1, :l2] = 1
    up["labs"][0, 3] = ids1[3]
    up["labs"][1, 5] = ids2[5]
    up["labs"][1, 9] = ids2[9]

    pk_ids = np.zeros((1, S), np.int32)
    pk_ids[0, :l1] = ids1
    pk_ids[0, l1:l1 + l2] = ids2
    pk_seg = np.concatenate([up["seg"][0, :l1], up["seg"][1, :l2],
                             np.zeros(S - l1 - l2, np.int32)])[None]
    pk_mask = np.zeros((1, S), np.int32)
    pk_mask[0, :l1 + l2] = 1
    pk_labs = np.full((1, S), -1, np.int32)
    pk_labs[0, 3] = ids1[3]
    pk_labs[0, l1 + 5] = ids2[5]
    pk_labs[0, l1 + 9] = ids2[9]
    seq_ids = np.zeros((1, S), np.int32)
    seq_ids[0, :l1] = 1
    seq_ids[0, l1:l1 + l2] = 2
    cls = np.array([[0, l1, 0]], np.int32)
    pk_nsp = np.array([[1, 0, -1]], np.int32)

    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, S), jnp.int32),
        jnp.zeros((1, S), jnp.int32), jnp.zeros((1, S), jnp.int32))

    bert = lambda m, *a: m.bert(*a)
    seq_u, pooled_u = model.apply(
        params, up["ids"], up["seg"], up["mask"], True, method=bert)
    mlm_u, nsp_u = model.apply(params, up["ids"], up["seg"], up["mask"], True)
    loss_u = pretraining_loss(mlm_u, nsp_u, up["labs"], up["nsp"])

    seq_p, pooled_p = model.apply(
        params, pk_ids, pk_seg, pk_mask, True,
        jnp.asarray(seq_ids), jnp.asarray(cls), method=bert)
    mlm_p, nsp_p = model.apply(
        params, pk_ids, pk_seg, pk_mask, True, None,
        jnp.asarray(seq_ids), jnp.asarray(cls))
    loss_p = pretraining_loss(mlm_p, nsp_p, pk_labs, pk_nsp)

    # identical per-token encoder outputs at each member's positions
    np.testing.assert_allclose(
        np.asarray(seq_p)[0, :l1], np.asarray(seq_u)[0, :l1], atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(seq_p)[0, l1:l1 + l2], np.asarray(seq_u)[1, :l2],
        atol=1e-5)
    # identical pooled vectors per packed sequence
    np.testing.assert_allclose(
        np.asarray(pooled_p)[0, :2], np.asarray(pooled_u), atol=1e-5)
    # identical TOTAL MLM+NSP loss
    assert float(loss_p) == pytest.approx(float(loss_u), abs=1e-5)


def test_packed_parity_holds_on_pallas_interpret_path():
    """The Pallas interpret-mode kernel gives the same packed encoder
    outputs as the XLA block-diagonal path, through the full model."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining

    cfg = BertConfig(
        vocab_size=200, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2, next_sentence=True)
    rng = np.random.default_rng(1)
    S = 64
    ids = rng.integers(5, 200, (2, S)).astype(np.int32)
    seq_ids = np.zeros((2, S), np.int32)
    seq_ids[0, :40] = 1
    seq_ids[0, 40:56] = 2
    seq_ids[1, :64] = 1
    mask = (seq_ids > 0).astype(np.int32)
    cls = np.array([[0, 40], [0, 0]], np.int32)

    outs = {}
    for backend in ("xla", "pallas"):
        model = BertForPreTraining(
            cfg, dtype=jnp.float32, attention_backend=backend)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, S), jnp.int32),
            jnp.zeros((1, S), jnp.int32), jnp.zeros((1, S), jnp.int32))
        outs[backend], _ = model.apply(
            params, ids, np.zeros_like(ids), mask, True,
            jnp.asarray(seq_ids), jnp.asarray(cls),
            method=lambda m, *a: m.bert(*a))
    real = seq_ids > 0
    np.testing.assert_allclose(
        np.asarray(outs["pallas"])[real], np.asarray(outs["xla"])[real],
        atol=2e-5)


# -- padding-aware telemetry ---------------------------------------------


def test_step_timer_padding_fields():
    t = [0.0]

    def clock():
        t[0] += 0.05
        return t[0]

    timer = StepTimer(window=2, sync_every=1, clock=clock, seq_per_step=4,
                      tokens_per_step=400)
    for step in (1, 2):
        timer.data_start()
        timer.data_end()
        timer.dispatch_end()
        timer._t_device1 = clock()
        timer.note_tokens(200.0)
        rec = timer.step_done(step)
    assert rec is not None
    assert rec["padding_efficiency"] == pytest.approx(0.5)
    assert rec["tokens_per_s_basis"] == "real"
    assert rec["tokens_per_s"] > 0
    assert timer.run_padding_efficiency() == pytest.approx(0.5)
    assert tschema.validate_record(
        {**rec, "schema": tschema.SCHEMA_VERSION, "ts": 0}) == []


def test_step_timer_tokens_all_basis_when_unsynced():
    timer = StepTimer(window=1, sync_every=0, tokens_per_step=400)
    timer.data_start()
    timer.data_end()
    timer.dispatch_end()
    rec = timer.step_done(1)
    assert rec["tokens_per_s_basis"] == "all"
    assert "padding_efficiency" not in rec


def test_schema_rejects_inconsistent_token_fields():
    base = {"schema": tschema.SCHEMA_VERSION, "ts": 0.0,
            "kind": "step_window", "step": 1, "window_steps": 1,
            "data_wait_p50_s": 0, "data_wait_p95_s": 0, "data_wait_max_s": 0,
            "host_p50_s": 0, "host_p95_s": 0, "host_max_s": 0,
            "device_p50_s": 0, "device_p95_s": 0, "device_max_s": 0,
            "step_p50_s": 0, "steps_per_sec": 1.0, "mfu": 0.0}
    assert tschema.validate_record(base) == []
    assert tschema.validate_record({**base, "tokens_per_s": 5.0})
    assert tschema.validate_record(
        {**base, "tokens_per_s": 5.0, "tokens_per_s_basis": "bogus"})
    assert tschema.validate_record(
        {**base, "tokens_per_s": 5.0, "tokens_per_s_basis": "real"})
    assert tschema.validate_record(
        {**base, "tokens_per_s": 5.0, "tokens_per_s_basis": "real",
         "padding_efficiency": 0.8}) == []
    assert tschema.validate_record({**base, "padding_efficiency": 1.7})
    assert tschema.validate_record({**base, "mfu_real_tokens": 0.1})


# -- acceptance: packed vs unpacked CPU smoke ----------------------------


def _smoke_run(tmp_path, tag, pack):
    import run_pretraining

    data_dir = tmp_path / f"data_{tag}"
    data_dir.mkdir()
    for i in range(2):
        make_shard(str(data_dir / f"s{i}.hdf5"), 96, 128, 1000, seed=i,
                   mixed_lengths=True)
    model_config = {
        "vocab_size": 1000, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 128, "type_vocab_size": 2,
        "next_sentence": True, "mask_token_id": 4,
    }
    config_path = tmp_path / f"model_{tag}.json"
    config_path.write_text(json.dumps(model_config))
    out = str(tmp_path / f"out_{tag}")
    argv = [
        "--input_dir", str(data_dir), "--output_dir", out,
        "--model_config_file", str(config_path),
        "--global_batch_size", "8", "--local_batch_size", "1",
        "--max_steps", "6", "--steps", "6", "--dtype", "float32",
        "--learning_rate", "1e-3", "--num_steps_per_checkpoint", "100",
        "--skip_final_checkpoint",
        "--telemetry_window", "3", "--telemetry_sync_every", "1",
        "--seed", "11",
    ]
    if pack:
        argv += ["--pack_sequences", "--max_sequences_per_pack", "8"]
    args = run_pretraining.parse_arguments(argv)
    result = run_pretraining.main(args)
    assert result["global_step"] == 6
    jsonl = os.path.join(out, "pretraining_telemetry.jsonl")
    assert tschema.validate_file(jsonl) == []
    summary = None
    windows = []
    for line in open(jsonl):
        rec = json.loads(line)
        if rec.get("kind") == "run_summary":
            summary = rec
        elif rec.get("kind") == "step_window":
            windows.append(rec)
    return jsonl, summary, windows


def test_packed_smoke_padding_efficiency_acceptance(tmp_path):
    """ISSUE 3 acceptance: on a mixed-length synthetic shard (seq 128) a
    packed CPU run reports padding_efficiency >= 1.5x the unpacked run's
    and lower wall-clock per real token, in the telemetry JSONL and the
    telemetry-report summary."""
    from bert_pytorch_tpu.telemetry.report import summarize_file

    _, sum_u, win_u = _smoke_run(tmp_path, "unpacked", pack=False)
    jsonl_p, sum_p, win_p = _smoke_run(tmp_path, "packed", pack=True)

    eff_u = sum_u["padding_efficiency"]
    eff_p = sum_p["padding_efficiency"]
    assert 0 < eff_u < 0.75  # mixed lengths leave real padding
    assert eff_p >= 1.5 * eff_u, (eff_p, eff_u)
    # lower wall-clock per REAL token == higher real-token throughput
    assert (sum_p["real_tokens_per_sec"]
            > 1.2 * sum_u["real_tokens_per_sec"]), (sum_p, sum_u)
    # windows carry the padding-aware fields with the real basis
    assert all(w["tokens_per_s_basis"] == "real" for w in win_p)
    assert all(0 < w["padding_efficiency"] <= 1 for w in win_p)
    # telemetry-report summarizes them
    report = summarize_file(jsonl_p)
    assert report["padding_efficiency"] == pytest.approx(eff_p, abs=0.1)
    assert report["tokens_per_s"] > 0
    assert report["real_tokens_per_sec"] == sum_p["real_tokens_per_sec"]
