"""Pipeline-parallelism tests: the GPipe engine (parallel/pipeline.py) and
the pp train step (pretrain.make_pp_train_step) against the plain dp path.

Strategy equivalence is the invariant: pp is an execution schedule, not a
different model, so loss/params after a step must match the dp train step on
the same params and data (up to fp32 reduction-order noise). Runs on the
virtual 8-device CPU mesh (conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu import optim, pretrain
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.parallel import (
    MeshConfig,
    create_mesh,
    gpipe,
    logical_axis_rules,
)

# Heavyweight, and the gpipe engine needs the jax>=0.5 shard_map/pcast typing
# (parallel/pipeline.py shim): on jax 0.4.x the legacy partial-auto shard_map
# hits XLA's "PartitionId is not supported for SPMD partitioning". Outside
# the tier-1 budget; run explicitly with `-m slow` on a current jax.
pytestmark = pytest.mark.slow


def _batch(rng, n_mb, b, seq, vocab):
    return {
        "input_ids": rng.integers(0, vocab, (n_mb, b, seq)).astype(np.int32),
        "segment_ids": rng.integers(0, 2, (n_mb, b, seq)).astype(np.int32),
        "input_mask": np.ones((n_mb, b, seq), np.int32),
        "masked_lm_labels": np.where(
            rng.random((n_mb, b, seq)) < 0.2,
            rng.integers(0, vocab, (n_mb, b, seq)),
            -1,
        ).astype(np.int32),
        "next_sentence_labels": rng.integers(0, 2, (n_mb, b)).astype(np.int32),
    }


def test_gpipe_engine_matches_sequential(devices):
    """The engine alone: y = fn(...fn(x)) layer chain, pipelined == serial."""
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    n_layers, n_mb, b, d = 8, 4, 4, 16
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(n_layers, d, d)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.normal(size=(n_mb, b, d)), jnp.float32)
    scale = jnp.asarray(rng.normal(size=(n_mb, b, 1)), jnp.float32)

    def layer(w_j, h):
        return jnp.tanh(h @ w_j)

    def stage_fn(local_w, h, scale_mb, _rep, stage, mb):
        def body(carry, w_j):
            return layer(w_j, carry), None

        h, _ = jax.lax.scan(body, h, local_w)
        return h * scale_mb

    with mesh:
        out = gpipe(stage_fn, w, x, scale, mesh)

    # serial reference: full chain per microbatch, scale applied per stage
    n_stages, per = 4, n_layers // 4
    ref = x
    for s in range(n_stages):
        h = ref
        for j in range(s * per, (s + 1) * per):
            h = layer(w[j], h)
        ref = h * scale
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_gpipe_grads_match_sequential(devices):
    mesh = create_mesh(MeshConfig(data=1, pipe=2), devices=jax.devices()[:2])
    n_layers, n_mb, b, d = 4, 4, 2, 8
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(n_layers, d, d)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.normal(size=(n_mb, b, d)), jnp.float32)
    ones = jnp.ones((n_mb, b, 1), jnp.float32)

    def stage_fn(local_w, h, _c, _rep, stage, mb):
        def body(carry, w_j):
            return jnp.tanh(carry @ w_j), None

        h, _ = jax.lax.scan(body, h, local_w)
        return h

    def loss_pp(w):
        with mesh:
            return jnp.sum(gpipe(stage_fn, w, x, ones, mesh) ** 2)

    def loss_ref(w):
        h = x
        for j in range(n_layers):
            h = jnp.tanh(h @ w[j])
        return jnp.sum(h**2)

    l_pp, g_pp = jax.value_and_grad(loss_pp)(w)
    l_ref, g_ref = jax.value_and_grad(loss_ref)(w)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_ref), atol=1e-4)


def test_gpipe_rejects_bad_shapes(devices):
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    w = jnp.zeros((8, 4, 4))
    with pytest.raises(ValueError, match="at least as many microbatches"):
        with mesh:
            gpipe(lambda *a: a[1], w, jnp.zeros((2, 2, 4)), None, mesh)


def test_pp_no_nsp_and_remat(tiny_config, devices):
    """The RoBERTa path (next_sentence=False: no pooler/NSP head) and
    remat='dots' inside pipeline stages both work under pp."""
    from bert_pytorch_tpu.config import BertConfig

    cfg_dict = tiny_config.to_dict()
    cfg_dict["next_sentence"] = False
    cfg = BertConfig.from_dict(cfg_dict)
    model = BertForPreTraining(cfg, dtype=jnp.float32, remat="dots")
    schedule = optim.warmup_poly_schedule(1e-3, 0.25, 100)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    seq, b, n_mb = 32, 2, 4
    sample = (jnp.zeros((1, seq), jnp.int32),) * 3
    host = _batch(np.random.default_rng(3), n_mb, b, seq, cfg.vocab_size)
    mesh = create_mesh(MeshConfig(data=2, pipe=2), devices=jax.devices()[:4])
    rules = logical_axis_rules("pp")
    with mesh:
        shardings = pretrain.state_shardings(mesh, model, rules, sample)
        b_shardings = pretrain.batch_shardings(
            mesh,
            {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
             "masked_lm_labels": 3, "next_sentence_labels": 2},
        )
        state = pretrain.make_init_fn(model, tx, sample, shardings)(
            jax.random.PRNGKey(6)
        )
        step = pretrain.make_pp_train_step(
            model, tx, mesh, schedule=schedule, next_sentence=False,
            shardings=shardings, batch_shardings_=b_shardings,
            max_pred_per_seq=8)
        batch = pretrain.put_batch(host, b_shardings)
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        # second step exercises donated-state reuse
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


def test_pp_sp_bf16_dropout_step(tiny_config, devices):
    """pp x sp in bf16 with dropout ON: one step runs and is finite.

    Regression coverage for two things the fp32 equivalence test cannot
    see: (1) the XLA CPU AllReducePromotion crash on bf16 all-reduces in
    the pipeline region (parallel/pipeline.py promotes the boundary and
    the param pvary to f32 on CPU), and (2) the ring_manual dropout path
    with its per-seq-shard rng folding."""
    model = BertForPreTraining(tiny_config, dtype=jnp.bfloat16)
    schedule = optim.warmup_poly_schedule(1e-3, 0.25, 100)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    seq, b, n_mb = 32, 2, 4
    sample = (jnp.zeros((1, seq), jnp.int32),) * 3
    host = _batch(np.random.default_rng(7), n_mb, b, seq,
                  tiny_config.vocab_size)
    mesh = create_mesh(MeshConfig(data=1, pipe=2, seq=2),
                       devices=jax.devices()[:4])
    rules = logical_axis_rules("pp")
    with mesh:
        shardings = pretrain.state_shardings(mesh, model, rules, sample)
        b_shardings = pretrain.batch_shardings(
            mesh,
            {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
             "masked_lm_labels": 3, "next_sentence_labels": 2},
            seq_sharded=True,
        )
        state = pretrain.make_init_fn(model, tx, sample, shardings)(
            jax.random.PRNGKey(8)
        )
        step = pretrain.make_pp_train_step(
            model, tx, mesh, schedule=schedule, next_sentence=True,
            shardings=shardings, batch_shardings_=b_shardings,
            max_pred_per_seq=8)
        batch = pretrain.put_batch(host, b_shardings)
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


def test_pp_runner_end_to_end(tmp_path, devices):
    """run_pretraining with --parallel_strategy pp: smoke + resume compat
    (pp and dp share one parameter tree, so the checkpoint layout is
    strategy-independent)."""
    import json

    import run_pretraining
    from bert_pytorch_tpu.tools.make_synthetic_data import make_shard

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    make_shard(str(data_dir / "shard_0.hdf5"), 64, 32, 96, seed=0)
    model_config = {
        "vocab_size": 96, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 32, "type_vocab_size": 2,
        "next_sentence": True, "mask_token_id": 4,
    }
    cfg_path = tmp_path / "model.json"
    cfg_path.write_text(json.dumps(model_config))
    argv = [
        "--input_dir", str(data_dir),
        "--output_dir", str(tmp_path / "out"),
        "--model_config_file", str(cfg_path),
        "--global_batch_size", "16",
        "--local_batch_size", "2",
        "--max_steps", "4",
        "--steps", "2",
        "--learning_rate", "1e-3",
        "--warmup_proportion", "0.25",
        "--dtype", "float32",
        "--parallel_strategy", "pp",
        "--mesh_pipe", "2",
        "--log_prefix", str(tmp_path / "log"),
    ]
    result = run_pretraining.main(run_pretraining.parse_arguments(argv))
    assert np.isfinite(result["loss"])
    # resume under plain dp from the pp checkpoint
    argv_dp = [a for a in argv]
    argv_dp[argv_dp.index("pp")] = "dp"
    argv_dp[argv_dp.index("--mesh_pipe") + 1] = "1"
    result2 = run_pretraining.main(
        run_pretraining.parse_arguments(argv_dp + ["--steps", "2"]))
    assert result2["global_step"] == 4
    assert np.isfinite(result2["loss"])
    # pp x sp through the CLI glue: --mesh_seq composes with pp (the
    # runner seq-shards the batch and the pp step runs the manual ring
    # region); fresh output dir so it starts from step 0.
    argv_sp = [a for a in argv]
    argv_sp[argv_sp.index(str(tmp_path / "out"))] = str(tmp_path / "out_sp")
    result3 = run_pretraining.main(run_pretraining.parse_arguments(
        argv_sp + ["--mesh_seq", "2", "--mesh_data", "2"]))
    assert np.isfinite(result3["loss"])


def test_pp_train_step_matches_dp(tiny_config, devices):
    """One optimizer step under pp(2 stages)x dp(2) == plain dp: same loss,
    same updated params, from the same initial state and batch. Dropout off:
    the two paths fold the step PRNG differently, so only the deterministic
    computation is comparable."""
    from bert_pytorch_tpu.config import BertConfig

    cfg_dict = tiny_config.to_dict()
    cfg_dict["hidden_dropout_prob"] = 0.0
    cfg_dict["attention_probs_dropout_prob"] = 0.0
    cfg = BertConfig.from_dict(cfg_dict)
    vocab, b, seq, n_mb = cfg.vocab_size, 4, 32, 4
    model = BertForPreTraining(cfg, dtype=jnp.float32)
    schedule = optim.warmup_poly_schedule(1e-3, 0.25, 100)
    sample = (jnp.zeros((1, seq), jnp.int32),) * 3
    host = _batch(np.random.default_rng(2), n_mb, b, seq, vocab)

    results = {}
    for name, meshcfg, strategy, seq_sharded, n_dev in [
        ("dp", MeshConfig(data=4), "dp", False, 4),
        ("pp", MeshConfig(data=2, pipe=2), "pp", False, 4),
        # pipeline x tensor parallel: 'pipe' manual, 'model' automatic
        # (each stage's matmuls split over 2 model shards)
        ("pp_tp", MeshConfig(data=1, pipe=2, model=2), "pp_tp", False, 4),
        # pipeline x sequence parallel: ONE shard_map manual over
        # {pipe, seq}, attention runs the manual ring body inside it
        # (parallel/pipeline.py gpipe(seq_axis=...)); activations are
        # sequence-sharded end to end
        ("pp_sp", MeshConfig(data=1, pipe=2, seq=2), "pp", True, 4),
        # all three composed in one step: {pipe, seq} manual, 'model'
        # automatic (GSPMD shards each stage's matmuls)
        ("pp_sp_tp", MeshConfig(data=1, pipe=2, seq=2, model=2),
         "pp_tp", True, 8),
    ]:
        mesh = create_mesh(meshcfg, devices=jax.devices()[:n_dev])
        rules = logical_axis_rules(strategy)
        tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
        with mesh:
            shardings = pretrain.state_shardings(mesh, model, rules, sample)
            b_shardings = pretrain.batch_shardings(
                mesh,
                {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
                 "masked_lm_labels": 3, "next_sentence_labels": 2},
                seq_sharded=seq_sharded,
            )
            state = pretrain.make_init_fn(model, tx, sample, shardings)(
                jax.random.PRNGKey(5)
            )
            if name.startswith("pp"):
                step = pretrain.make_pp_train_step(
                    model, tx, mesh, schedule=schedule, next_sentence=True,
                    shardings=shardings, batch_shardings_=b_shardings,
                    max_pred_per_seq=8)
            else:
                step = pretrain.make_train_step(
                    model, tx, schedule=schedule, next_sentence=True,
                    shardings=shardings, batch_shardings_=b_shardings,
                    max_pred_per_seq=8)
            batch = pretrain.put_batch(host, b_shardings)
            new_state, metrics = step(state, batch)
            results[name] = (
                float(metrics["loss"]),
                jax.device_get(new_state.params),
            )

    loss_dp, params_dp = results["dp"]
    flat_dp = jax.tree_util.tree_leaves_with_path(params_dp)
    # Dropout draws differ between the paths (different rng folding), so
    # compare with dropout effectively disabled via the config used here:
    for name in ("pp", "pp_tp", "pp_sp", "pp_sp_tp"):
        loss_x, params_x = results[name]
        np.testing.assert_allclose(loss_x, loss_dp, rtol=1e-5, err_msg=name)
        flat_x = dict(
            (jax.tree_util.keystr(kp), leaf)
            for kp, leaf in jax.tree_util.tree_leaves_with_path(params_x)
        )
        for kp, leaf in flat_dp:
            np.testing.assert_allclose(
                np.asarray(flat_x[jax.tree_util.keystr(kp)]),
                np.asarray(leaf),
                atol=2e-5,
                err_msg=f"{name} {jax.tree_util.keystr(kp)}",
            )
