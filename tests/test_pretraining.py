"""End-to-end pretraining runner tests on the virtual 8-device CPU mesh.

The TPU-world analog of the reference's Gloo CPU harness (SURVEY.md §4):
full config -> data -> model -> LAMB -> checkpoint -> logging flow, plus the
resume and phase-switch behaviors of SURVEY §5.4.
"""

import json
import os

import numpy as np
import pytest

import run_pretraining
from bert_pytorch_tpu.tools.make_synthetic_data import make_shard
from bert_pytorch_tpu.utils import checkpoint as ckpt

# End-to-end runner tests (compile + train on the virtual 8-device mesh, many
# minutes on a throttled CPU host): outside the tier-1 wallclock budget. Run
# explicitly with `-m slow`; tier-1 keeps the telemetry CPU smoke run
# (tests/test_telemetry.py) as the fast end-to-end pretraining guard.
pytestmark = pytest.mark.slow

VOCAB = 1000


@pytest.fixture()
def workdir(tmp_path):
    data_dir = tmp_path / "data"
    out_dir = tmp_path / "out"
    data_dir.mkdir()
    for i in range(2):
        make_shard(str(data_dir / f"shard_{i}.hdf5"), 64, 32, VOCAB, seed=i)
    model_config = {
        "vocab_size": VOCAB,
        "hidden_size": 32,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "intermediate_size": 64,
        "max_position_embeddings": 32,
        "type_vocab_size": 2,
        "next_sentence": True,
        "mask_token_id": 4,
    }
    config_path = tmp_path / "model.json"
    config_path.write_text(json.dumps(model_config))
    return {"data": str(data_dir), "out": str(out_dir), "model": str(config_path)}


def _args(workdir, **overrides):
    argv = [
        "--input_dir", workdir["data"],
        "--output_dir", workdir["out"],
        "--model_config_file", workdir["model"],
        "--global_batch_size", "32",
        "--local_batch_size", "2",
        "--max_steps", "8",
        "--steps", "3",
        "--learning_rate", "1e-3",
        "--warmup_proportion", "0.25",
        "--num_steps_per_checkpoint", "100",
        "--dtype", "float32",
        "--seed", "7",
    ]
    for key, value in overrides.items():
        if value is True:  # bare store_true flag
            argv += [f"--{key}"]
        else:
            argv += [f"--{key}", str(value)]
    return run_pretraining.parse_arguments(argv)


def test_smoke_train_with_accumulation(workdir):
    # 8 data shards x local_bs 2 = global microbatch 16; gbs 32 -> accum 2.
    result = run_pretraining.main(_args(workdir))
    assert result["global_step"] == 3
    assert np.isfinite(result["loss"])
    # loss should be near ln(vocab) + ln(2) at start
    assert 4.0 < result["loss"] < 10.0
    # final checkpoint written
    assert ckpt.find_resume_step(os.path.join(workdir["out"], "pretrain_ckpts")) == 3
    # log sinks exist
    assert os.path.exists(os.path.join(workdir["out"], "pretraining.txt"))
    assert os.path.exists(os.path.join(workdir["out"], "pretraining_metrics.csv"))


def test_compile_cache_populates_and_restart_resumes(workdir, tmp_path,
                                                    monkeypatch):
    """--compile_cache_dir wires JAX's persistent cache into the runner:
    the train-step executable lands in the directory (threshold dropped to
    0 here — tiny-model compiles are under the production 10s bar) and a
    restarted run against the same cache resumes cleanly."""
    import jax

    from bert_pytorch_tpu.utils import compile_cache

    monkeypatch.setattr(compile_cache, "MIN_COMPILE_TIME_SECS", 0.0)
    cache = tmp_path / "xla_cache"
    before_dir = jax.config.jax_compilation_cache_dir
    before_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        run_pretraining.main(
            _args(workdir, compile_cache_dir=str(cache)))
        entries = list(cache.iterdir())
        assert entries, "no executables were persisted"
        result = run_pretraining.main(
            _args(workdir, steps=2, compile_cache_dir=str(cache)))
        assert result["global_step"] == 5
    finally:
        jax.config.update("jax_compilation_cache_dir", before_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", before_min)


def test_resume_continues_and_losses_drop(workdir):
    run_pretraining.main(_args(workdir))
    result2 = run_pretraining.main(_args(workdir, steps=2))
    assert result2["global_step"] == 5
    out_dir = os.path.join(workdir["out"], "pretrain_ckpts")
    assert ckpt.find_resume_step(out_dir) == 5


def test_checkpoint_sampler_index_matches_trained_samples(workdir):
    """The saved sampler position must equal the samples actually TRAINED,
    not the loader's read-ahead position: the DataLoader queue plus
    device_prefetch stage batches ahead of the train step, and saving the
    live index would skip that buffered-but-untrained data on resume (a
    latent defect of the reference, whose DataLoader workers run ahead of
    its checkpoints the same way, reference src/dataset.py:401-425)."""
    run_pretraining.main(_args(workdir, steps=3))
    out = os.path.join(workdir["out"], "pretrain_ckpts")
    step = ckpt.find_resume_step(out)
    saved = ckpt.load_checkpoint(ckpt.checkpoint_path(out, step))
    # dataset: 128 samples; 3 steps x global_batch 32 trained = 96 < 128,
    # while the pipelines have buffered well past 96 by save time.
    assert int(saved["sampler"]["index"]) == 3 * 32


def test_phase_switch_resets_optimizer_count(workdir):
    run_pretraining.main(_args(workdir, steps=4, max_steps=4))
    out_dir = os.path.join(workdir["out"], "pretrain_ckpts")
    assert ckpt.find_resume_step(out_dir) == 4
    # Phase 2: new schedule, previous_phase_end_step=4.
    result = run_pretraining.main(
        _args(workdir, steps=2, max_steps=4, previous_phase_end_step=4,
              learning_rate="2e-3", warmup_proportion="0.5"))
    # global step restarts from 0 within phase 2 and runs 2 steps
    assert result["global_step"] == 2
    # checkpoint names continue the global numbering (4 + 2)
    assert ckpt.find_resume_step(out_dir) == 6


def test_checkpoint_retention(workdir):
    run_pretraining.main(
        _args(workdir, steps=6, max_steps=8, num_steps_per_checkpoint=1,
              keep_checkpoints=3))
    out_dir = os.path.join(workdir["out"], "pretrain_ckpts")
    files = sorted(f for f in os.listdir(out_dir) if f.endswith(".msgpack"))
    assert len(files) == 3
    assert ckpt.find_resume_step(out_dir) == 6


def test_masked_position_head_matches_full_head():
    """The masked-positions MLM path (decoder on [B,P] gathered positions)
    must give the same loss as the full [B,S,V] path."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining, pretraining_loss

    cfg = BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32)
    model = BertForPreTraining(cfg, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    B, S, P = 4, 16, 5
    ids = jnp.asarray(rng.integers(0, 128, (B, S), dtype=np.int32))
    types = jnp.zeros((B, S), jnp.int32)
    mask = jnp.ones((B, S), jnp.int32)
    labels = np.full((B, S), -1, np.int32)
    for b in range(B):
        pos = rng.choice(S, size=rng.integers(1, P), replace=False)
        labels[b, pos] = rng.integers(0, 128, len(pos))
    labels = jnp.asarray(labels)
    nsp = jnp.asarray(rng.integers(0, 2, (B,), dtype=np.int32))

    variables = model.init(jax.random.PRNGKey(0), ids, types, mask)
    full_logits, nsp_logits = model.apply(variables, ids, types, mask)
    full_loss = pretraining_loss(full_logits, nsp_logits, labels, nsp)

    is_masked = (labels != -1).astype(jnp.int32)
    _, positions = jax.lax.top_k(is_masked, P)
    glabels = jnp.take_along_axis(labels, positions, axis=1)
    m_logits, nsp_logits2 = model.apply(
        variables, ids, types, mask, True, positions)
    m_loss = pretraining_loss(m_logits, nsp_logits2, glabels, nsp)
    assert m_logits.shape == (B, P, 128)
    np.testing.assert_allclose(float(m_loss), float(full_loss), rtol=1e-5)


def test_kfac_end_to_end(workdir):
    """Runner with --kfac: preconditioned steps, preconditioner in the
    checkpoint, and resume restoring it (reference run_pretraining.py:320-355,
    519-520)."""
    argv = [
        "--input_dir", workdir["data"],
        "--output_dir", workdir["out"],
        "--model_config_file", workdir["model"],
        "--global_batch_size", "32",
        "--local_batch_size", "2",
        "--max_steps", "8",
        "--steps", "3",
        "--learning_rate", "1e-3",
        "--warmup_proportion", "0.25",
        "--num_steps_per_checkpoint", "100",
        "--dtype", "float32",
        "--seed", "7",
        "--kfac",
        "--kfac_factor_interval", "1",
        "--kfac_inv_interval", "2",
    ]
    result = run_pretraining.main(run_pretraining.parse_arguments(argv))
    assert result["global_step"] == 3
    assert np.isfinite(result["loss"])
    ckpt_dir = os.path.join(workdir["out"], "pretrain_ckpts")
    loaded = ckpt.load_checkpoint(ckpt.checkpoint_path(ckpt_dir, 3))
    assert "preconditioner" in loaded
    assert int(loaded["preconditioner"]["count"]) == 3
    # resume picks the preconditioner back up and keeps training
    result2 = run_pretraining.main(
        run_pretraining.parse_arguments(argv + ["--steps", "2"]))
    assert result2["global_step"] == 5
    assert np.isfinite(result2["loss"])


def test_roberta_path_no_nsp(workdir, tmp_path):
    """next_sentence=False (the RoBERTa config path,
    configs/roberta_pretraining_config.json): no token-type embeddings, no
    pooler/NSP head, MLM-only loss."""
    model_config = json.loads(open(workdir["model"]).read())
    model_config["next_sentence"] = False
    config_path = tmp_path / "roberta.json"
    config_path.write_text(json.dumps(model_config))
    args = _args({**workdir, "model": str(config_path)},
                 lr_decay="linear", warmup_proportion="0.06")
    result = run_pretraining.main(args)
    assert result["global_step"] == 3
    assert np.isfinite(result["loss"])
    # NSP-free loss is pure MLM cross-entropy: ~ln(vocab)
    assert 4.0 < result["loss"] < 9.0
    loaded = ckpt.load_checkpoint(ckpt.checkpoint_path(
        os.path.join(workdir["out"], "pretrain_ckpts"), 3))
    assert "seq_relationship" not in loaded["model"]
    assert "token_type_embeddings" not in loaded["model"]["bert"]["embeddings"]
    assert "pooler" not in loaded["model"]["bert"]


def test_convergence_memorization():
    """End-to-end learning signal: LAMB + schedule + masking + model memorize
    a fixed batch to ~100% MLM accuracy — catches optimizer/loss/labeling
    plumbing bugs no smoke test sees."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu import optim, pretrain
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.parallel import (
        MeshConfig, create_mesh, logical_axis_rules)

    config = BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, next_sentence=True)
    model = BertForPreTraining(config, dtype=jnp.float32)
    mesh = create_mesh(MeshConfig(data=-1))
    rules = logical_axis_rules("dp")
    schedule = optim.warmup_poly_schedule(8e-3, 0.05, 300)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    S, B = 16, 16
    sample = (jnp.zeros((1, S), jnp.int32),) * 3
    rng = np.random.default_rng(0)
    host = {
        "input_ids": rng.integers(5, 128, (B, S)).astype(np.int32),
        "segment_ids": np.zeros((B, S), np.int32),
        "input_mask": np.ones((B, S), np.int32),
        "next_sentence_labels": rng.integers(0, 2, (B,)).astype(np.int32),
    }
    host["masked_lm_labels"] = np.where(
        rng.random((B, S)) < 0.3, host["input_ids"], -1).astype(np.int32)
    with mesh:
        shardings = pretrain.state_shardings(mesh, model, rules, sample)
        b_shardings = pretrain.batch_shardings(
            mesh, {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
                   "masked_lm_labels": 3, "next_sentence_labels": 2})
        state = pretrain.make_init_fn(model, tx, sample, shardings)(
            jax.random.PRNGKey(0))
        step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            shardings=shardings, batch_shardings_=b_shardings)
        batch = pretrain.put_batch(
            pretrain.stack_microbatches(host, 1), b_shardings)
        for i in range(300):
            state, metrics = step(state, batch)
            if i % 25 == 0:  # periodic sync: keep the CPU in-process
                float(metrics["loss"])  # collective queue shallow
    assert float(metrics["mlm_accuracy"]) > 0.95
    assert float(metrics["loss"]) < 1.0


def test_validation_pass(workdir, tmp_path):
    """--val_input_dir runs a held-out MLM eval at the configured cadence
    and logs tag=val records (beyond the reference, which never evaluates
    during pretraining)."""
    val_dir = tmp_path / "valdata"
    val_dir.mkdir()
    make_shard(str(val_dir / "val_0.hdf5"), 32, 32, VOCAB, seed=99)
    log_prefix = str(tmp_path / "vallog")
    result = run_pretraining.main(_args(
        workdir, steps=2, val_input_dir=str(val_dir),
        num_steps_per_eval=1, eval_batches=2, log_prefix=log_prefix))
    assert np.isfinite(result["loss"])
    text = open(log_prefix + ".txt").read()
    assert "tag: val" in text
    assert "mlm_accuracy" in text


@pytest.mark.slow  # ~90s subprocess; the cross-process half is also
# covered by the chaos harness (tier-1) and the in-process term-injection
# test (tests/test_fault_tolerance.py) — run with -m slow
def test_sigterm_graceful_checkpoint(workdir):
    """Preemption handling (beyond the reference's die-and-resubmit fault
    model): SIGTERM mid-run makes the runner stop at the next
    term-check step, write the normal final checkpoint, and exit with
    the distinct EXIT_PREEMPTED code (75: "checkpointed cleanly,
    resubmit me" — docs/fault_tolerance.md) — and the checkpoint
    resumes."""
    import signal
    import subprocess
    import sys
    import time as _time

    # Drop PYTHONPATH: the axon sitecustomize on it force-selects the TPU
    # platform at interpreter startup, overriding JAX_PLATFORMS (see
    # tests/conftest.py, which solves this in-process via jax.config).
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "PYTHONPATH")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    argv = [
        sys.executable,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "run_pretraining.py"),
        "--input_dir", workdir["data"],
        "--output_dir", workdir["out"],
        "--model_config_file", workdir["model"],
        "--global_batch_size", "4", "--local_batch_size", "4",
        "--max_steps", "100000", "--steps", "100000",
        "--learning_rate", "1e-3", "--warmup_proportion", "0.25",
        "--num_steps_per_checkpoint", "100000",
        "--term_check_steps", "1", "--log_steps", "1",
        "--dtype", "float32", "--seed", "7",
    ]
    proc = subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    log_path = os.path.join(workdir["out"], "pretraining_metrics.csv")
    deadline = _time.monotonic() + 240
    try:
        # Wait until a couple of steps have actually trained.
        while _time.monotonic() < deadline:
            if os.path.exists(log_path) and sum(
                    1 for _ in open(log_path)) >= 3:
                break
            _time.sleep(1.0)
        else:
            raise AssertionError("runner never reached step 2")
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    from bert_pytorch_tpu.utils.preemption import EXIT_PREEMPTED

    assert proc.returncode == EXIT_PREEMPTED, (proc.returncode, out[-2000:])
    assert "termination signal" in out, out[-2000:]
    ckpt_dir = os.path.join(workdir["out"], "pretrain_ckpts")
    stopped_at = ckpt.find_resume_step(ckpt_dir)
    assert stopped_at is not None and 1 <= stopped_at < 100000
    # The checkpoint is a normal one: a resume run continues from it.
    result = run_pretraining.main(_args(
        workdir, steps=1, max_steps=100000, term_check_steps=0))
    assert result["global_step"] == stopped_at + 1
    assert not result["terminated_by_signal"]


def test_check_batch_process_locality(monkeypatch):
    """Batch shards whose pipe/model replicas span processes must be
    rejected: the per-process loaders would feed the same global rows
    different data (silent cross-rank divergence)."""
    import dataclasses

    import jax

    from bert_pytorch_tpu import pretrain

    @dataclasses.dataclass(frozen=True)
    class Dev:
        process_index: int

    def mesh_of(proc_grid):
        # proc_grid: nested list shaped [data, fsdp, pipe, seq, model]
        class FakeMesh:
            pass
        m = FakeMesh()
        m.devices = np.vectorize(Dev)(np.asarray(proc_grid))
        return m

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # 2 hosts, pipe intra-host: data axis splits hosts -> OK
    ok = [[[[[0]], [[0]]]], [[[[1]], [[1]]]]]  # [2,1,2,1,1]
    pretrain.check_batch_process_locality(mesh_of(ok))
    # pipe spans hosts: shard (0,0) replicated on processes 0 and 1 -> raise
    bad = [[[[[0]], [[1]]]], [[[[0]], [[1]]]]]
    with pytest.raises(ValueError, match="conflicting data"):
        pretrain.check_batch_process_locality(mesh_of(bad))
    # single process: never raises
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    pretrain.check_batch_process_locality(mesh_of(bad))
