"""Profiling-plane tests (ISSUE 17, docs/observability.md "Profiling
plane" + docs/telemetry.md "Perf ledger"): the stdlib host thread
sampler (bounded, self-excluding), the arm-at-boundary capture
controller (idle -> armed -> active -> idle, the double-arm 409 guard),
the process-wide trace latch, ``POST /profilez`` on BOTH HTTP planes
(trainer introspection hub + serving replica) with live-server status
codes, the collector's coordinated fleet-wide trigger, the longitudinal
perf ledger (append/read/drift direction-awareness, the CLI, the
telemetry-report "perf ledger drift" gate, ``--format json``), the
router heartbeat, and the schema fixtures for both new record kinds.

The jax-trace-artifact proof (real ``jax.profiler`` trace directory
with nonzero bytes) is slow-gated at the bottom."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from bert_pytorch_tpu.telemetry import profiler, schema
from bert_pytorch_tpu.telemetry import ledger as ledger_mod
from bert_pytorch_tpu.telemetry.collector import FleetCollector, Target
from bert_pytorch_tpu.telemetry.introspect import (IntrospectionHub,
                                                   start_debug_server)
from bert_pytorch_tpu.telemetry.sampler import (CaptureController,
                                                ThreadSampler)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "fixtures", "telemetry")
REPORT_TOOL = os.path.join(REPO_ROOT, "tools", "telemetry_report.py")
LEDGER_TOOL = os.path.join(REPO_ROOT, "tools", "perf_ledger.py")
TOOLS_DIR = os.path.join(REPO_ROOT, "tools")


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _busy_thread(stop: threading.Event) -> threading.Thread:
    """A named worker the sampler is guaranteed to catch mid-frame."""

    def spin():
        while not stop.is_set():
            sum(i * i for i in range(200))
            time.sleep(0.001)

    t = threading.Thread(target=spin, name="busy-worker", daemon=True)
    t.start()
    return t


def _post(url: str, body: dict, timeout: float = 5.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read().decode("utf-8") or "{}")


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, json.loads(resp.read().decode("utf-8"))


def _stamped(rec: dict) -> dict:
    """What the JSONL sink would add before writing."""
    out = dict(rec)
    out.setdefault("schema", 1)
    out.setdefault("ts", 1754600000.0)
    return out


# ---------------------------------------------------------------------------
# telemetry/sampler.py: ThreadSampler


def test_sampler_attributes_busy_thread_and_is_bounded():
    stop = threading.Event()
    _busy_thread(stop)
    try:
        sampler = ThreadSampler(interval_s=0.002, max_samples=500,
                                max_duration_s=5.0)
        sampler.start()
        time.sleep(0.15)
        sampler.stop()
        folded = sampler.result(top_k=10)
    finally:
        stop.set()
    assert 0 < folded["samples"] <= 500
    assert folded["top_frames"], "a live process must yield frames"
    share_sum = 0.0
    for row in folded["top_frames"]:
        assert row["samples"] >= 1
        assert row["samples"] <= folded["samples"]
        assert 0 < row["share"] <= 1
        assert row["frame"] and row["stack"]
        share_sum += row["share"]
    assert share_sum <= 1.0 + 1e-6
    # The sampler never profiles itself.
    assert all("telemetry-sampler" not in row["frame"]
               for row in folded["top_frames"])
    assert any(t for t in folded["threads"])


def test_sampler_max_samples_bound_and_one_shot():
    sampler = ThreadSampler(interval_s=0.001, max_samples=3,
                            max_duration_s=5.0)
    sampler.start()
    time.sleep(0.1)
    sampler.stop()
    assert sampler.result()["samples"] <= 3
    with pytest.raises(RuntimeError):
        sampler.start()


# ---------------------------------------------------------------------------
# telemetry/sampler.py: CaptureController state machine


def test_controller_full_cycle_emits_schema_clean_record():
    clock = FakeClock()
    emitted = []
    ctrl = CaptureController(source="trainer", covered_unit="steps",
                             emit=emitted.append, clock=clock)
    assert ctrl.status()["phase"] == "idle"
    ok, payload = ctrl.arm(duration_s=0.2, sample_interval_s=0.002)
    assert ok and payload["armed"] and payload["source"] == "trainer"
    assert ctrl.status()["phase"] == "armed"

    stop = threading.Event()
    _busy_thread(stop)
    try:
        assert ctrl.tick(100) is None          # armed -> active
        assert ctrl.status()["phase"] == "active"
        assert ctrl.tick(105) is None          # not expired yet
        time.sleep(0.1)                        # real time for the sampler
        clock.advance(0.5)                     # past the deadline
        record = ctrl.tick(112)
    finally:
        stop.set()
    assert record is not None and emitted == [record]
    assert record["kind"] == "profile_window"
    assert record["trigger"] == "ondemand"
    assert record["covered"] == 12 and record["covered_unit"] == "steps"
    assert record["samples"] > 0 and record["top_frames"]
    assert record["trace_path"] == "" and record["trace_bytes"] == 0
    assert schema.validate_record(_stamped(record)) == []
    status = ctrl.status()
    assert status["phase"] == "idle" and status["captures"] == 1
    assert status["last"]["top_frame"]
    # The plane is reusable: a second arm from idle succeeds.
    ok, _ = ctrl.arm(duration_s=0.1)
    assert ok


def test_controller_double_arm_refused_with_phase_bad_params_without():
    """The 409 discriminator: a busy refusal carries the blocking phase,
    a bad parameter does not — the HTTP planes map exactly on that."""
    ctrl = CaptureController(source="replica", covered_unit="requests",
                             clock=FakeClock())
    ok, _ = ctrl.arm(duration_s=0.5)
    assert ok
    ok, payload = ctrl.arm(duration_s=0.5)
    assert not ok and payload["phase"] == "armed"
    ctrl.tick(0)
    ok, payload = ctrl.arm(duration_s=0.5)
    assert not ok and payload["phase"] == "active"
    # Parameter refusals: no "phase" key.
    for kwargs in ({"duration_s": "soon"}, {"duration_s": -1.0},
                   {"max_samples": "lots"}):
        ok, payload = ctrl.arm(**kwargs)
        assert not ok and "error" in payload and "phase" not in payload


def test_controller_caps_runaway_duration():
    ctrl = CaptureController(source="trainer", clock=FakeClock())
    ok, payload = ctrl.arm(duration_s=1e9)
    assert ok
    from bert_pytorch_tpu.telemetry.sampler import MAX_DURATION_S
    assert payload["duration_s"] == MAX_DURATION_S


# ---------------------------------------------------------------------------
# telemetry/profiler.py: the process-wide trace latch


def test_trace_latch_is_exclusive_and_releases():
    assert not profiler.trace_active()
    assert profiler._acquire_trace()
    try:
        assert profiler.trace_active()
        assert not profiler._acquire_trace()  # refused, not raised
    finally:
        profiler._release_trace()
    assert not profiler.trace_active()
    assert profiler._acquire_trace()
    profiler._release_trace()


# ---------------------------------------------------------------------------
# POST /profilez on the trainer introspection plane (live server)


def test_profilez_live_trainer_debug_server(tmp_path):
    emitted = []
    hub = IntrospectionHub(process="unit")
    hub.capture = CaptureController(source="trainer", covered_unit="steps",
                                    emit=emitted.append)
    server = start_debug_server(hub, port=0)
    stop = threading.Event()
    _busy_thread(stop)
    try:
        host, port = server.server_address[:2]
        base = f"http://{host}:{port}"
        code, body = _post(f"{base}/profilez",
                           {"duration_s": 0.15, "sample_interval_s": 0.002})
        assert code == 200 and body["armed"]
        # Second arm while armed: 409, naming the blocking phase.
        code, body = _post(f"{base}/profilez", {"duration_s": 0.15})
        assert code == 409 and body["phase"] == "armed"
        # /statsz shows the capture status sub-object.
        code, stats = _get(f"{base}/statsz")
        assert code == 200 and stats["profile"]["phase"] == "armed"
        # Bad parameter: 400, not 409.
        code, body = _post(f"{base}/profilez", {"duration_s": "soon"})
        assert code == 400 and "error" in body
        # Drive the boundary like the train loop does.
        hub.capture.tick(7)
        time.sleep(0.3)
        record = hub.capture.tick(19)
        assert record is not None and record["covered"] == 12
        assert record["top_frames"], "host-frame table must be non-empty"
        assert schema.validate_record(_stamped(record)) == []
        code, stats = _get(f"{base}/statsz")
        assert stats["profile"]["phase"] == "idle"
        assert stats["profile"]["captures"] == 1
        # Idle again: a new arm succeeds.
        code, body = _post(f"{base}/profilez", {"duration_s": 0.1})
        assert code == 200
    finally:
        stop.set()
        server.shutdown()
        server.server_close()
    assert len(emitted) == 1


def test_profilez_404_when_no_controller_attached():
    hub = IntrospectionHub(process="bare")
    server = start_debug_server(hub, port=0)
    try:
        host, port = server.server_address[:2]
        code, body = _post(f"http://{host}:{port}/profilez",
                           {"duration_s": 0.1})
        assert code == 404 and "error" in body
    finally:
        server.shutdown()
        server.server_close()


def test_train_telemetry_wires_capture_to_hub_and_ticks_it(tmp_path):
    """TrainTelemetry builds the controller, attaches it to the hub, and
    ticks it at every step boundary — armed captures complete through
    the normal step loop and land in the run's JSONL sink."""
    from bert_pytorch_tpu.telemetry.runner import TrainTelemetry

    jsonl = tmp_path / "train_telemetry.jsonl"
    hub = IntrospectionHub(process="unit")
    tele = TrainTelemetry(jsonl_path=str(jsonl), window=10, sync_every=1,
                          introspect=hub)
    try:
        assert hub.capture is tele.capture
        ok, _ = tele.capture.arm(duration_s=0.1, sample_interval_s=0.002)
        assert ok
        for step in (1, 2):
            tele.timer.data_start()
            tele.timer.data_end()
            tele.dispatch_done()
            if step == 2:
                time.sleep(0.2)
            tele.step_done(step, {"loss": 2.0})
    finally:
        tele.close()
    records = [json.loads(line) for line in open(jsonl)]
    windows = [r for r in records if r.get("kind") == "profile_window"]
    assert len(windows) == 1
    assert windows[0]["source"] == "trainer"
    assert windows[0]["covered_unit"] == "steps"
    assert schema.validate_file(str(jsonl)) == []


# ---------------------------------------------------------------------------
# POST /profilez on a serving replica (live HTTP server, no engine work)


def test_profilez_live_replica_http_server(tmp_path):
    from bert_pytorch_tpu.serve import (Batcher, ServeTelemetry,
                                        ServingService, make_server)

    emitted = []
    capture = CaptureController(source="replica", covered_unit="requests",
                                emit=emitted.append)
    # __init__ never touches the engine; the capture plane needs only
    # the HTTP front end + the telemetry counters.
    service = ServingService(object(), Batcher(max_batch_size=2),
                             ServeTelemetry(), capture=capture)
    server = make_server(service, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    stop = threading.Event()
    _busy_thread(stop)
    try:
        port = server.server_address[1]
        base = f"http://127.0.0.1:{port}"
        code, body = _post(f"{base}/profilez",
                           {"duration_s": 0.15, "sample_interval_s": 0.002,
                            "trigger": "fleet"})
        assert code == 200 and body["covered_unit"] == "requests"
        code, body = _post(f"{base}/profilez", {"duration_s": 0.1})
        assert code == 409 and body["phase"] == "armed"
        code, stats = _get(f"{base}/statsz")
        assert code == 200 and stats["profile"]["phase"] == "armed"
        code, body = _post(f"{base}/profilez", {"duration_s": []})
        assert code == 400
        # Drive the dispatch boundary the way the service loops do.
        service._capture_tick()
        time.sleep(0.3)
        service._capture_tick()
    finally:
        stop.set()
        server.shutdown()
        server.server_close()
    assert len(emitted) == 1
    record = emitted[0]
    assert record["source"] == "replica" and record["trigger"] == "fleet"
    assert record["top_frames"]
    assert schema.validate_record(_stamped(record)) == []


# ---------------------------------------------------------------------------
# telemetry/collector.py: the coordinated fleet-wide trigger


def test_collector_trigger_profile_hits_every_capture_plane(tmp_path):
    out = tmp_path / "timeline.jsonl"
    targets = [Target("pretrain", "trainer", "http://t:9100"),
               Target("r0", "replica", "http://r0:8001"),
               Target("r1", "replica", "http://r1:8002"),
               Target("front", "router", "http://front:8100")]
    coll = FleetCollector(targets, out_path=str(out))
    calls = []

    def post(url, path, body, timeout_s):
        calls.append((url, path, dict(body)))
        if "r1" in url:
            raise OSError("connection refused")
        return 200, json.dumps({"armed": True,
                                "duration_s": body["duration_s"]})

    records = coll.trigger_profile(duration_s=1.5, post=post)
    coll.close()
    # Routers have no capture plane: three posts, not four.
    assert len(calls) == 3
    assert all(path == "/profilez" for _, path, _ in calls)
    assert all(body["duration_s"] == 1.5 and body["trigger"] == "fleet"
               for _, _, body in calls)
    by_target = {r["target"]: r for r in records}
    assert set(by_target) == {"pretrain", "r0", "r1"}
    assert by_target["pretrain"]["ok"] and by_target["r0"]["ok"]
    assert not by_target["r1"]["ok"] and by_target["r1"]["error"]
    assert all(r["probe"] == "profilez" for r in records)
    # The trigger records land in the timeline, schema-clean.
    assert schema.validate_file(str(out)) == []
    written = [json.loads(line) for line in open(out)]
    assert sum(1 for r in written if r.get("probe") == "profilez") == 3


def test_obs_collect_cli_profile_flag(tmp_path):
    """--profile arms the fleet before the pass loop; an unreachable
    target is reported, the trigger record still lands, the timeline
    still lints."""
    out = tmp_path / "timeline.jsonl"
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS_DIR, "obs_collect.py"),
         "--target", "replica:r0=http://127.0.0.1:9",
         "--out", str(out), "--passes", "1", "--interval_s", "0.05",
         "--scrape_timeout_s", "0.2",
         "--profile", "--profile_duration_s", "0.5"],
        capture_output=True, text=True, timeout=60, cwd=TOOLS_DIR)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "profile: armed 0/1" in proc.stdout
    assert "r0" in proc.stderr
    written = [json.loads(line) for line in open(out)]
    triggers = [r for r in written if r.get("probe") == "profilez"]
    assert len(triggers) == 1 and triggers[0]["ok"] is False
    assert schema.validate_file(str(out)) == []


# ---------------------------------------------------------------------------
# telemetry/ledger.py: the longitudinal perf ledger


def test_ledger_append_read_roundtrip_and_digest_stability(tmp_path):
    path = tmp_path / "ledger.jsonl"
    cfg = {"seq_len": "128", "batch": "256"}
    a = ledger_mod.append_entry(str(path), "train",
                                {"step_ms_p50": 41.0, "mfu": 0.38},
                                config=cfg, ts=1.0)
    b = ledger_mod.append_entry(str(path), "train",
                                {"step_ms_p50": 42.0, "mfu": 0.38},
                                config=dict(cfg), ts=2.0)
    other = ledger_mod.append_entry(str(path), "train",
                                    {"step_ms_p50": 39.0},
                                    config={"seq_len": "512"}, ts=3.0)
    assert a["config_digest"] == b["config_digest"]
    assert other["config_digest"] != a["config_digest"]
    entries = ledger_mod.read_entries(str(path))
    assert [e["metrics"]["step_ms_p50"] for e in entries] == \
        [41.0, 42.0, 39.0]
    assert ledger_mod.read_entries(str(path), leg="serve") == []
    assert schema.validate_file(str(path)) == []
    # Non-finite / negative metrics are dropped, never written.
    bad = ledger_mod.append_entry(str(path), "train",
                                  {"step_ms_p50": float("nan"),
                                   "mfu": -0.5}, ts=4.0)
    assert bad is None
    assert len(ledger_mod.read_entries(str(path))) == 3


def test_ledger_drift_is_direction_aware(tmp_path):
    path = tmp_path / "ledger.jsonl"
    for i, p50 in enumerate((40.0, 41.0, 40.0, 39.0)):
        ledger_mod.append_entry(str(path), "train",
                                {"step_ms_p50": p50, "mfu": 0.40},
                                ts=float(i))
    entries = ledger_mod.read_entries(str(path))
    assert ledger_mod.check_drift(entries) == []  # steady: clean
    # Latency UP is drift...
    ledger_mod.append_entry(str(path), "train",
                            {"step_ms_p50": 60.0, "mfu": 0.40}, ts=10.0)
    findings = ledger_mod.check_drift(ledger_mod.read_entries(str(path)))
    assert [f["metric"] for f in findings] == ["step_ms_p50"]
    assert findings[0]["change"] > 0.25 and findings[0]["leg"] == "train"
    # ...latency DOWN is an improvement, not drift.
    path2 = tmp_path / "faster.jsonl"
    for i, p50 in enumerate((40.0, 41.0, 40.0, 20.0)):
        ledger_mod.append_entry(str(path2), "train",
                                {"step_ms_p50": p50}, ts=float(i))
    assert ledger_mod.check_drift(
        ledger_mod.read_entries(str(path2))) == []
    # mfu is inverted: DOWN is the regression.
    path3 = tmp_path / "mfu.jsonl"
    for i, mfu in enumerate((0.40, 0.41, 0.40, 0.20)):
        ledger_mod.append_entry(str(path3), "train", {"mfu": mfu},
                                ts=float(i))
    findings = ledger_mod.check_drift(ledger_mod.read_entries(str(path3)))
    assert [f["metric"] for f in findings] == ["mfu"]


def test_ledger_needs_history_before_gating(tmp_path):
    path = tmp_path / "ledger.jsonl"
    for i, p50 in enumerate((40.0, 80.0, 160.0)):  # wild, but < min history
        ledger_mod.append_entry(str(path), "train",
                                {"step_ms_p50": p50}, ts=float(i))
    assert ledger_mod.check_drift(ledger_mod.read_entries(str(path))) == []


def test_ledger_metrics_from_summary_maps_and_scales():
    metrics = ledger_mod.metrics_from_summary(
        {"step_p50_s": 0.1, "step_p95_s": 0.15, "mfu": 0.4,
         "serve_latency_p99_ms": 33.0, "steps": 30,
         "name": "run", "peak_bytes_in_use": None})
    assert metrics == {"step_ms_p50": pytest.approx(100.0),
                       "step_ms_p95": pytest.approx(150.0),
                       "mfu": pytest.approx(0.4),
                       "serve_p99_ms": pytest.approx(33.0)}


def test_perf_ledger_cli_show_append_check(tmp_path):
    path = str(tmp_path / "ledger.jsonl")

    def run(*args):
        return subprocess.run(
            [sys.executable, LEDGER_TOOL, *args],
            capture_output=True, text=True, timeout=60, cwd=TOOLS_DIR)

    for p50 in ("41.0", "40.5", "41.2", "40.8"):
        proc = run("append", path, "--leg", "train",
                   "--metric", f"step_ms_p50={p50}",
                   "--config", "seq_len=128")
        assert proc.returncode == 0, proc.stderr
        assert "appended train" in proc.stdout
    proc = run("check", path)
    assert proc.returncode == 0 and "no drift" in proc.stdout
    proc = run("show", path, "--leg", "train")
    assert proc.returncode == 0 and "step_ms_p50=41" in proc.stdout
    # Doctor one slow entry onto the trajectory: named drift, exit 1.
    proc = run("append", path, "--leg", "train",
               "--metric", "step_ms_p50=70.0", "--config", "seq_len=128")
    assert proc.returncode == 0
    proc = run("check", path)
    assert proc.returncode == 1
    assert "REGRESSION perf ledger drift: train/step_ms_p50" in proc.stdout
    # Bad input is 2, not a traceback.
    proc = run("append", path, "--leg", "train", "--metric", "nonsense")
    assert proc.returncode == 2
    proc = run("check", str(tmp_path / "missing.jsonl"))
    assert proc.returncode == 2


# ---------------------------------------------------------------------------
# telemetry-report: the "perf ledger drift" gate + --format json


def _window(step, p50, mfu=0.4):
    rec = {"schema": 1, "ts": 0.0, "kind": "step_window",
           "tag": "telemetry", "step": step, "window_steps": 10,
           "synced_steps": 10, "steps_per_sec": round(1.0 / p50, 4),
           "mfu": mfu, "mfu_basis": "device"}
    for prefix in ("data_wait", "host", "device", "step"):
        base = p50 if prefix == "step" else p50 / 10
        rec[f"{prefix}_p50_s"] = base
        rec[f"{prefix}_p95_s"] = base * 1.5
        rec[f"{prefix}_max_s"] = base * 2
    return rec


def _run_artifact(path, p50=0.1, mfu=0.4):
    records = [_window(10, p50, mfu), _window(20, p50, mfu),
               _window(30, p50, mfu),
               {"schema": 1, "ts": 0.0, "kind": "run_summary",
                "tag": "telemetry", "step": 30, "steps": 30,
                "training_seq_per_sec": round(8 / p50, 2), "mfu": mfu}]
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def _report(*args):
    return subprocess.run(
        [sys.executable, REPORT_TOOL, *args],
        capture_output=True, text=True, timeout=60, cwd=TOOLS_DIR)


def test_report_ledger_gate_names_drift_and_self_diffs_green(tmp_path):
    """The acceptance property: a clean trajectory stays green run after
    run; ONE doctored slow entry makes the report exit 1 naming 'perf
    ledger drift'."""
    clean = _run_artifact(tmp_path / "clean.jsonl", p50=0.1)
    slow = _run_artifact(tmp_path / "slow.jsonl", p50=0.14)
    ledger = str(tmp_path / "ledger.jsonl")
    for _ in range(4):
        proc = _report(clean, "--ledger", ledger)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "perf ledger" in proc.stdout
    assert len(ledger_mod.read_entries(ledger)) == 4
    assert schema.validate_file(ledger) == []
    proc = _report(slow, "--ledger", ledger)
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    assert "REGRESSION perf ledger drift" in proc.stdout
    assert "step_ms_p50" in proc.stdout
    # Bare drift check (no run artifact): same verdict off the ledger.
    proc = _report("--ledger", ledger)
    assert proc.returncode == 1
    assert "perf ledger drift" in proc.stdout
    # The doctored entry is history now; do NOT append the probe run.
    proc = _report(clean, "--ledger", ledger, "--no-ledger-append")
    assert len(ledger_mod.read_entries(ledger)) == 5


def test_report_format_json_stable_contract(tmp_path):
    """--format json prints the check_all contract: one versioned object
    with rc both inside and as the exit code."""
    clean = _run_artifact(tmp_path / "clean.jsonl", p50=0.1)
    ledger = str(tmp_path / "ledger.jsonl")
    proc = _report(clean, "--ledger", ledger, "--format", "json")
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    obj = json.loads(proc.stdout)
    assert obj["version"] == 1
    assert obj["rc"] == proc.returncode == 0
    assert obj["verdict"] == "ok"
    assert obj["regressions"] == []
    assert isinstance(obj["checks"], list)
    assert obj["ledger"]["entries"] >= 1
    # Drift flows into the same shape with rc=1.
    for _ in range(3):
        _report(clean, "--ledger", ledger)
    slow = _run_artifact(tmp_path / "slow.jsonl", p50=0.14)
    proc = _report(slow, "--ledger", ledger, "--format", "json")
    obj = json.loads(proc.stdout)
    assert proc.returncode == 1 and obj["rc"] == 1
    assert any(r["label"] == "perf ledger drift"
               for r in obj["regressions"])


def test_report_profile_section_joins_host_and_device(tmp_path):
    """The report names the dominant host frame and the heaviest
    compiled fn out of profile_window + compile_cost records."""
    from bert_pytorch_tpu.telemetry import report

    path = tmp_path / "run.jsonl"
    records = [
        _window(10, 0.1),
        {"schema": 1, "ts": 1.0, "kind": "profile_window",
         "tag": "profile", "source": "trainer", "trigger": "ondemand",
         "covered": 12, "covered_unit": "steps", "duration_s": 2.0,
         "sample_interval_s": 0.01, "samples": 100,
         "top_frames": [
             {"frame": "MainThread:train_loop.py:step", "samples": 60,
              "share": 0.6, "stack": "x"},
             {"frame": "writer:runner.py:write_record", "samples": 20,
              "share": 0.2, "stack": "y"}],
         "trace_path": "out/profile/ondemand_1", "trace_bytes": 4096},
        {"schema": 1, "ts": 2.0, "kind": "compile_cost",
         "tag": "telemetry", "fn": "train_step", "shapes_digest": "abc",
         "analysis": "jaxpr", "flops": 9e12, "bytes_accessed": 1e9},
        {"schema": 1, "ts": 3.0, "kind": "compile_cost",
         "tag": "telemetry", "fn": "eval_step", "shapes_digest": "def",
         "analysis": "jaxpr", "flops": 1e10, "bytes_accessed": 1e8},
    ]
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    summary = report.summarize_file(str(path))
    assert summary["profile_windows"] == 1
    assert summary["profile_samples"] == 100
    assert summary["profile_trace_bytes"] == 4096
    assert summary["profile_critical_host"] == \
        "MainThread:train_loop.py:step"
    assert summary["profile_critical_device"] == "train_step"
    text = report.format_summary(summary)
    assert "MainThread:train_loop.py:step" in text


# ---------------------------------------------------------------------------
# schema fixtures for both new kinds


def test_profile_window_fixtures_lint_as_expected():
    good = os.path.join(FIXTURES, "profile_window_good.jsonl")
    bad = os.path.join(FIXTURES, "profile_window_bad.jsonl")
    assert schema.validate_file(good) == []
    errors = schema.validate_file(bad)
    assert len(errors) >= 10
    text = " ".join(err for _, err in errors)
    assert "trigger must be one of" in text
    assert "covered_unit must be one of" in text
    assert "exceeds the capture's total samples" in text
    assert "shares sum to" in text
    assert "trace_path must be a string" in text
    proc = subprocess.run(
        [sys.executable,
         os.path.join(TOOLS_DIR, "check_telemetry_schema.py"), good, bad],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "profile_window_good.jsonl: ok" in proc.stdout
    assert "trigger must be one of" in proc.stdout


def test_ledger_fixtures_lint_as_expected():
    good = os.path.join(FIXTURES, "ledger_good.jsonl")
    bad = os.path.join(FIXTURES, "ledger_bad.jsonl")
    assert schema.validate_file(good) == []
    errors = schema.validate_file(bad)
    assert len(errors) >= 7
    text = " ".join(err for _, err in errors)
    assert "leg must be a non-empty string" in text
    assert "percentiles must be ordered" in text
    assert "ratio in [0, 1]" in text
    assert "metrics must be a non-empty object" in text
    proc = subprocess.run(
        [sys.executable,
         os.path.join(TOOLS_DIR, "check_telemetry_schema.py"), good, bad],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "ledger_good.jsonl: ok" in proc.stdout
    assert "percentiles must be ordered" in proc.stdout


# ---------------------------------------------------------------------------
# serve/router.py: the router heartbeat


def test_router_writes_resumable_heartbeat_with_routed_requests(tmp_path):
    from bert_pytorch_tpu.serve import Router
    from bert_pytorch_tpu.telemetry.sentinels import Heartbeat

    hb = tmp_path / "router_heartbeat.json"

    def mk_router():
        return Router(
            ["http://127.0.0.1:1"],
            scrape=lambda url: {"dispatch_alive": True, "queue_depth": 0},
            transport=lambda url, task, payload, deadline_s: (200, {}),
            heartbeat_file=str(hb))

    router = mk_router()
    router.scrape_once()
    status, _, _ = router.handle("classify", {"text": "x"})
    assert status == 200
    assert router._maybe_beat(0.0) > 0.0  # interval elapsed: beats
    payload = Heartbeat.read(str(hb))
    assert payload["step"] == 1 and payload["counter"] == 1
    router.stop()  # final flush beats again
    payload = Heartbeat.read(str(hb))
    assert payload["counter"] == 2
    # Resumable: a restarted router continues the counter, never resets.
    router2 = mk_router()
    router2.stop()
    payload = Heartbeat.read(str(hb))
    assert payload["counter"] == 3 and payload["step"] == 0


# ---------------------------------------------------------------------------
# bench.py: automatic ledger append (jax-free parent path)


def test_bench_append_ledger_maps_result_keys(tmp_path, monkeypatch):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_bench_under_test", os.path.join(REPO_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    path = str(tmp_path / "ledger.jsonl")
    monkeypatch.setattr(bench, "LEDGER_PATH", path)
    bench._append_ledger({"metric": "serve_p99_latency_ms", "value": 30.0,
                          "latency_p50_ms": 12.0, "latency_p99_ms": 30.0,
                          "cold_start_s": 2.5})
    entries = ledger_mod.read_entries(path)
    assert len(entries) == 1
    entry = entries[0]
    assert entry["leg"] == "train"  # no serve/kernels env flags set
    assert entry["metrics"]["serve_p50_ms"] == 12.0
    assert entry["metrics"]["serve_p99_ms"] == 30.0
    assert entry["metrics"]["cold_start_s"] == 2.5
    assert entry["metrics"]["headline"] == 30.0
    assert entry["config_digest"] == bench._config_digest()
    assert entry["metric"] == "serve_p99_latency_ms"  # extras merge flat
    assert schema.validate_file(path) == []
    # Error results and a disabled ledger never append.
    bench._append_ledger({"error": "no backend"})
    monkeypatch.setattr(bench, "LEDGER_PATH", "")
    bench._append_ledger({"value": 1.0})
    assert len(ledger_mod.read_entries(path)) == 1


# ---------------------------------------------------------------------------
# slow-gated: a real jax.profiler trace artifact on disk


@pytest.mark.slow
def test_ondemand_capture_writes_real_trace_artifact(tmp_path):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.telemetry.profiler import ProfilerWindow

    trace_root = str(tmp_path / "profile")
    emitted = []
    ctrl = CaptureController(
        source="trainer", covered_unit="steps",
        window=ProfilerWindow(None, trace_root, enabled=True),
        trace_dir=trace_root, emit=emitted.append)
    ok, _ = ctrl.arm(duration_s=0.5, sample_interval_s=0.005)
    assert ok
    x = jnp.ones((256, 256))
    assert ctrl.tick(0) is None
    deadline = time.time() + 10.0
    step = 0
    record = None
    while record is None and time.time() < deadline:
        for _ in range(5):
            x = jnp.tanh(x @ x.T / 256.0)
        x.block_until_ready()
        step += 1
        record = ctrl.tick(step, sync_target=x)
    assert record is not None, "capture never completed"
    assert record["trace_path"].startswith(trace_root)
    assert os.path.isdir(record["trace_path"])
    assert record["trace_bytes"] > 0
    assert record["samples"] > 0
    assert schema.validate_record(_stamped(record)) == []
    # The latch is released: a fresh window can begin again.
    assert profiler._acquire_trace()
    profiler._release_trace()
