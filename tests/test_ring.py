"""Ring attention / context parallelism tests (ops/ring.py).

The reference has no long-context support (SURVEY.md §5.7); these tests pin
the sequence-parallel design the TPU framework adds: ring attention must be
numerically identical to dense attention (forward and gradients), compose
with the model, and train end-to-end on an 'sp' mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu import optim, pretrain
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.ops.attention import dot_product_attention, make_attention_bias
from bert_pytorch_tpu.ops.ring import ring_attention
from bert_pytorch_tpu.parallel import MeshConfig, create_mesh, logical_axis_rules

# Heavyweight (ring-attention grad comparisons + end-to-end sp-mesh training):
# outside the tier-1 wallclock budget on a throttled CPU host. Run explicitly
# with `-m slow`.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    B, S, H, D = 4, 32, 4, 8
    mk = lambda: jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    mask = np.ones((B, S), np.int32)
    mask[:, -5:] = 0  # padding tail
    return mk(), mk(), mk(), make_attention_bias(jnp.asarray(mask))


def test_ring_matches_dense_forward(qkv, devices):
    q, k, v, bias = qkv
    dense = dot_product_attention(q, k, v, bias=bias)
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    with mesh:
        ring = jax.jit(lambda *a: ring_attention(*a, bias=bias))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-6)


def test_ring_matches_dense_grads(qkv, devices):
    q, k, v, bias = qkv
    mesh = create_mesh(MeshConfig(seq=8))

    def loss_d(q, k, v):
        return (dot_product_attention(q, k, v, bias=bias) ** 2).sum()

    def loss_r(q, k, v):
        return (ring_attention(q, k, v, bias=bias) ** 2).sum()

    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    with mesh:
        gr = jax.jit(jax.grad(loss_r, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gd, gr):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-5, atol=5e-6)


def test_ring_backend_falls_back_without_seq_axis(qkv, devices):
    """backend='ring' on a seq=1 mesh silently uses the dense path — the
    fused-or-fallback policy (reference modeling.py:327-335 analog)."""
    q, k, v, bias = qkv
    dense = dot_product_attention(q, k, v, bias=bias)
    with create_mesh(MeshConfig(data=-1)):
        out = dot_product_attention(q, k, v, bias=bias, backend="ring")
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), rtol=1e-6)


def test_ring_dropout_statistics(qkv, devices):
    """Dropped-prob semantics match dense attention dropout: output mean is
    preserved (unbiased), and deterministic mode ignores the rng."""
    q, k, v, bias = qkv
    mesh = create_mesh(MeshConfig(seq=4, data=2))
    dense = dot_product_attention(q, k, v, bias=bias)
    with mesh:
        fn = jax.jit(lambda q, k, v, r: ring_attention(
            q, k, v, bias=bias, dropout_rng=r, dropout_rate=0.1))
        outs = [np.asarray(fn(q, k, v, jax.random.PRNGKey(i)))
                for i in range(16)]
        avg = np.mean(outs, axis=0)
    # dropout is unbiased; with 16 samples the mean is loosely close
    np.testing.assert_allclose(avg, np.asarray(dense), rtol=0.5, atol=0.15)
    assert not np.allclose(outs[0], np.asarray(dense))


def test_model_forward_ring_vs_xla(tiny_config, devices):
    """Full BertForPreTraining forward identical under the ring backend."""
    model_x = BertForPreTraining(tiny_config, dtype=jnp.float32)
    model_r = BertForPreTraining(
        tiny_config, dtype=jnp.float32, attention_backend="ring")
    rng = np.random.default_rng(1)
    B, S = 8, 32
    ids = jnp.asarray(rng.integers(0, tiny_config.vocab_size, (B, S)), jnp.int32)
    types = jnp.zeros((B, S), jnp.int32)
    mask = jnp.asarray((rng.random((B, S)) < 0.9).astype(np.int32))
    variables = model_x.init(jax.random.PRNGKey(0), ids, types, mask)
    mlm_x, nsp_x = model_x.apply(variables, ids, types, mask)
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    with mesh:
        mlm_r, nsp_r = jax.jit(
            lambda v, a, b, c: model_r.apply(v, a, b, c))(variables, ids, types, mask)
    np.testing.assert_allclose(
        np.asarray(mlm_r), np.asarray(mlm_x), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(nsp_r), np.asarray(nsp_x), rtol=2e-4, atol=2e-4)


def test_train_step_sp_strategy(tiny_config, devices):
    """End-to-end sharded train step on an sp mesh (seq-sharded batch +
    ring attention): runs, loss finite and decreasing."""
    model = BertForPreTraining(
        tiny_config, dtype=jnp.float32, attention_backend="ring")
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    rules = logical_axis_rules("sp")
    schedule = optim.warmup_poly_schedule(1e-3, 0.1, 100)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    S = 32
    sample = (jnp.zeros((1, S), jnp.int32),) * 3
    rng = np.random.default_rng(2)
    B = 8
    host = {
        "input_ids": rng.integers(
            0, tiny_config.vocab_size, (B, S)).astype(np.int32),
        "segment_ids": np.zeros((B, S), np.int32),
        "input_mask": np.ones((B, S), np.int32),
        "masked_lm_labels": np.where(
            rng.random((B, S)) < 0.15,
            rng.integers(0, tiny_config.vocab_size, (B, S)), -1).astype(np.int32),
        "next_sentence_labels": rng.integers(0, 2, (B,)).astype(np.int32),
    }
    with mesh:
        shardings = pretrain.state_shardings(mesh, model, rules, sample)
        b_shardings = pretrain.batch_shardings(
            mesh, {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
                   "masked_lm_labels": 3, "next_sentence_labels": 2},
            seq_sharded=True)
        state = pretrain.make_init_fn(model, tx, sample, shardings)(
            jax.random.PRNGKey(0))
        step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            shardings=shardings, batch_shardings_=b_shardings)
        batch = pretrain.put_batch(
            pretrain.stack_microbatches(host, 1), b_shardings)
        losses = []
        for _ in range(4):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_long_sequence_beyond_reference(devices):
    """Sequence length past the reference's 512 ceiling (its
    max_position_embeddings bound, SURVEY §5.7) — the point of CP."""
    config = BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=2048, next_sentence=False)
    model = BertForPreTraining(
        config, dtype=jnp.float32, attention_backend="ring")
    rng = np.random.default_rng(3)
    B, S = 2, 2048
    ids = jnp.asarray(rng.integers(0, 128, (B, S)), jnp.int32)
    mask = jnp.ones((B, S), jnp.int32)
    mesh = create_mesh(MeshConfig(seq=8))
    with mesh:
        variables = model.init(jax.random.PRNGKey(0), ids, None, mask)
        mlm, _ = jax.jit(
            lambda v, a, b: model.apply(v, a, None, b))(variables, ids, mask)
    assert mlm.shape == (B, S, 128)
    assert bool(jnp.isfinite(mlm).all())


def test_ring_raises_on_nondivisible_seq(devices):
    """Active seq mesh + non-divisible S must error, not silently densify."""
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((2, 30, 2, 4)), jnp.float32)
    with create_mesh(MeshConfig(seq=4, data=2)):
        with pytest.raises(ValueError, match="not.*divisible|divisible"):
            dot_product_attention(q, q, q, backend="ring")


def test_ring_dropout_decorrelated_across_batch_shards(devices):
    """Each data shard's dropout mask must differ (the dense path gives every
    batch element independent noise; sharding must not correlate it)."""
    rng = np.random.default_rng(5)
    B, S, H, D = 4, 16, 2, 4
    # identical rows: without dropout all outputs equal; with dropout,
    # correlated masks across batch shards would keep shard outputs equal.
    row = rng.standard_normal((1, S, H, D))
    q = jnp.asarray(np.repeat(row, B, axis=0), jnp.float32)
    with create_mesh(MeshConfig(seq=4, data=2)):
        out = jax.jit(lambda q, r: ring_attention(
            q, q, q, dropout_rng=r, dropout_rate=0.3))(q, jax.random.PRNGKey(0))
    first_shard = np.asarray(out)[:2]
    second_shard = np.asarray(out)[2:]
    assert not np.allclose(first_shard, second_shard)


def test_ring_requires_seq_axis(qkv, devices):
    q, k, v, bias = qkv
    # no active mesh at all
    with pytest.raises(ValueError, match="needs an active mesh"):
        ring_attention(q, k, v, bias=bias)
    # active mesh without a real seq axis
    mesh = create_mesh(MeshConfig(data=8))
    with mesh:
        with pytest.raises(ValueError, match="'seq' axis"):
            ring_attention(q, k, v, bias=bias)


def test_ring_rejects_indivisible_sequence(qkv, devices):
    q, k, v, bias = qkv
    mesh = create_mesh(MeshConfig(seq=8))
    # seq length 5 not divisible by the 8-way seq axis
    q5, k5, v5 = (x[:, :5] for x in (q, k, v))
    with mesh:
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q5, k5, v5, bias=bias[..., :5])
