"""Shell-script syntax checks: every launcher/capture script must at least
pass ``bash -n`` (the cluster scripts themselves cannot execute here —
SURVEY §2.1 #20)."""

import glob
import os
import subprocess


def test_shell_scripts_parse():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    scripts = [p for pat in ("scripts/*.sh", "scripts/*.slurm",
                             "scripts/*.cobalt")
               for p in glob.glob(os.path.join(root, pat))]
    assert len(scripts) >= 10, scripts
    for path in scripts:
        res = subprocess.run(["bash", "-n", path], capture_output=True,
                             text=True)
        assert res.returncode == 0, f"{path}: {res.stderr}"
