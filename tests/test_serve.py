"""Online inference subsystem tests (serve/, docs/serving.md).

Covers the ISSUE-4 acceptance surface on CPU: bucket-selection
boundaries, batcher flush on size vs deadline under an injected fake
clock, packed-batch response demultiplexing, per-task served-vs-direct
output parity (1e-5 fp32), the >=32-concurrent-request HTTP smoke with
zero post-warmup compiles + schema-clean serve telemetry +
telemetry-report summary, and the >=1.5x packed-vs-unpacked batch
occupancy acceptance on a short-biased trace.

One module-scoped engine (tiny config, buckets (16, 32), batch 4,
pack K=4) amortizes the AOT warmup compiles across every test.
"""

import json
import threading

import numpy as np
import pytest

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.serve.batcher import Batcher, Request

ATOL = 1e-5
BUCKETS = (16, 32)
BATCH = 4
PACK_K = 4


# ---------------------------------------------------------------------------
# fixtures


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    from bert_pytorch_tpu.tools.make_synthetic_data import write_trace_vocab

    d = tmp_path_factory.mktemp("serve_vocab")
    return write_trace_vocab(str(d / "vocab.txt"))


@pytest.fixture(scope="module")
def tokenizer(vocab_file):
    from bert_pytorch_tpu.data.tokenization import BertTokenizer

    return BertTokenizer(vocab_file, do_lower_case=True)


@pytest.fixture(scope="module")
def config():
    from bert_pytorch_tpu.tools.make_synthetic_data import TRACE_WORDS

    vocab = 5 + len(TRACE_WORDS)
    vocab += (8 - vocab % 8) % 8
    return BertConfig(
        vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2, next_sentence=True,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)


NER_LABELS = ["O", "B-LOC", "B-PER", "I-PER"]
CLS_LABELS = ["neg", "pos"]


@pytest.fixture(scope="module")
def engine(config, tokenizer):
    import jax.numpy as jnp

    from bert_pytorch_tpu.serve import InferenceEngine

    eng = InferenceEngine(
        config, tokenizer,
        tasks={"fill_mask": {}, "classify": {"labels": CLS_LABELS},
               "squad": {}, "ner": {"labels": NER_LABELS}},
        buckets=BUCKETS, max_batch_size=BATCH,
        max_requests_per_pack=PACK_K, dtype=jnp.float32, seed=7)
    eng.warmup()
    eng.warm_events = len(eng.monitor.events)
    return eng


def _payloads():
    """Mixed-task payloads over the trace vocabulary, varied lengths."""
    return [
        ("fill_mask", {"text": "the capital of [MASK] is paris"}),
        ("fill_mask", {"text": "paris is [MASK]"}),
        ("fill_mask", {"text": "william shakespeare wrote [MASK] in "
                               "london england where the river runs"}),
        ("classify", {"text": "paris is big"}),
        ("classify", {"text": "the river runs through london",
                      "text_pair": "england is old"}),
        ("squad", {"question": "what is the capital of france",
                   "context": "the capital of france is paris"}),
        ("squad", {"question": "who wrote hamlet",
                   "context": "hamlet was wrote by william shakespeare "
                              "in london"}),
        # short enough (7 tokens) that two share even the 16 bucket
        ("squad", {"question": "who wrote hamlet",
                   "context": "shakespeare"}),
        ("ner", {"text": "paris is big"}),
        ("ner", {"text": "william shakespeare wrote hamlet in london "
                         "england by the river"}),
    ]


# ---------------------------------------------------------------------------
# bucket selection


def test_select_bucket_boundaries(engine):
    assert engine.select_bucket(1) == 16
    assert engine.select_bucket(16) == 16
    assert engine.select_bucket(17) == 32
    assert engine.select_bucket(32) == 32
    # over-long falls back to the largest bucket (prepare() truncated).
    assert engine.select_bucket(33) == 32
    assert engine.max_len() == 32


def test_prepare_truncates_to_largest_bucket(engine):
    spec = engine.tasks["ner"]
    long_text = " ".join(["london"] * 100)
    features = spec.handler.prepare({"text": long_text}, engine.max_len())
    assert len(features["input_ids"]) <= engine.max_len()
    assert len(features["words"]) == len(features["word_starts"])


def test_fill_mask_windows_around_late_mask(engine):
    """An over-long text truncates AROUND the mask, never away from it."""
    spec = engine.tasks["fill_mask"]
    text = " ".join(["london"] * 80) + " [MASK] paris"
    features = spec.handler.prepare({"text": text}, engine.max_len())
    assert len(features["input_ids"]) <= engine.max_len()
    assert features["mask_positions"]  # mask survived the windowing


# ---------------------------------------------------------------------------
# batcher: size vs deadline flush under a fake clock


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def _req(task="classify", n=6):
    return Request(task, {"input_ids": list(range(2, 2 + n)),
                          "segment_ids": [0] * n}, {})


def test_batcher_flushes_on_deadline(monkeypatch):
    clk = FakeClock()
    b = Batcher(max_batch_size=4, max_wait_ms=10.0, clock=clk)
    r = _req()
    b.submit(r)
    assert b.poll() is None           # fresh: under both thresholds
    clk.t += 0.009
    assert b.poll() is None           # 9ms < 10ms deadline
    clk.t += 0.002
    batch = b.poll()                  # 11ms: oldest request is due
    assert batch == [r]
    assert b.depth() == 0


def test_batcher_flushes_on_size_before_deadline():
    clk = FakeClock()
    b = Batcher(max_batch_size=4, max_wait_ms=1000.0, clock=clk)
    reqs = [_req() for _ in range(5)]
    for r in reqs[:3]:
        b.submit(r)
    assert b.poll() is None           # 3 < 4, deadline far away
    for r in reqs[3:]:
        b.submit(r)
    batch = b.poll()                  # 5 pending >= 4: flush a full batch
    assert batch == reqs[:4]
    assert b.depth() == 1             # the 5th waits for its own flush
    # packed batcher flushes at max_batch_size * K
    bp = Batcher(max_batch_size=2, max_wait_ms=1000.0,
                 max_requests_per_pack=3, clock=clk)
    for _ in range(5):
        bp.submit(_req())
    assert bp.poll() is None          # 5 < 2*3
    bp.submit(_req())
    assert len(bp.poll()) == 6


def test_batcher_sheds_load_at_max_pending():
    from bert_pytorch_tpu.serve.batcher import BatcherFull

    b = Batcher(max_batch_size=4, max_wait_ms=1000.0, max_pending=3,
                clock=FakeClock())
    for _ in range(3):
        b.submit(_req())
    with pytest.raises(BatcherFull):
        b.submit(_req())
    assert b.depth() == 3


def test_dispatch_skips_abandoned_requests(engine):
    """A timed-out submitter marks its request abandoned; the dispatch
    path must not spend a forward on it (and must not count it)."""
    from bert_pytorch_tpu.serve import (Batcher, ServeTelemetry,
                                        ServingService)

    telemetry = ServeTelemetry()
    service = ServingService(engine, Batcher(max_batch_size=4), telemetry)
    spec = engine.tasks["classify"]
    live = Request("classify",
                   spec.handler.prepare({"text": "paris is big"},
                                        engine.max_len()),
                   {"text": "paris is big"})
    dead = Request("classify",
                   spec.handler.prepare({"text": "london is old"},
                                        engine.max_len()),
                   {"text": "london is old"})
    dead.abandoned = True
    service.process_batch([live, dead])
    assert live.result is not None
    assert dead.result is None and dead.error is None
    assert telemetry.total_requests == 1
    service.process_batch([dead])  # all-abandoned batch is a no-op
    assert telemetry.total_batches == 1


def test_wrap_pair_truncation_is_balanced(engine):
    """Sentence-pair truncation pops from the LONGER side (the BERT
    convention, data/glue.py) instead of sacrificing text_a whole."""
    handler = engine.tasks["classify"].handler
    text = " ".join(["paris"] * 20)
    pair = " ".join(["london"] * 20)
    features = handler.prepare({"text": text, "text_pair": pair}, 32)
    n_a = sum(1 for s in features["segment_ids"] if s == 0) - 2  # CLS,SEP
    n_b = sum(1 for s in features["segment_ids"] if s == 1) - 1  # SEP
    assert len(features["input_ids"]) <= 32
    assert abs(n_a - n_b) <= 1, (n_a, n_b)


def test_batcher_groups_by_head_task(monkeypatch):
    clk = FakeClock()
    b = Batcher(max_batch_size=4, max_wait_ms=10.0, clock=clk)
    c1, n1, c2 = _req("classify"), _req("ner"), _req("classify")
    for r in (c1, n1, c2):
        b.submit(r)
    clk.t += 0.05                     # everyone past the deadline
    assert b.poll() == [c1, c2]       # head task drained together...
    assert b.poll() == [n1]           # ...other task keeps arrival order
    # requeue_front restores FIFO position
    b.submit(c1)
    b.requeue_front([c2])
    clk.t += 0.05
    assert b.poll() == [c2, c1]


# ---------------------------------------------------------------------------
# batch planning


def test_plan_batch_unpacked_picks_smallest_bucket(engine):
    short = [_req(n=5) for _ in range(3)]
    plan = engine.plan_batch(short, packed=False)
    assert plan.bucket == 16 and not plan.leftover
    assert [len(row) for row in plan.rows] == [1, 1, 1]
    mixed = short + [_req(n=20)]
    plan = engine.plan_batch(mixed, packed=False)
    assert plan.bucket == 32          # one long request forces the bucket
    over = [_req(n=5) for _ in range(BATCH + 2)]
    plan = engine.plan_batch(over, packed=False)
    assert len(plan.rows) == BATCH and len(plan.leftover) == 2


def test_plan_batch_packed_rows_and_leftover(engine):
    # 8 x 7 tokens: bucket 16 fits 2/row -> 4 rows == BATCH; smallest
    # bucket whose packing fits wins.
    reqs = [_req(n=7) for _ in range(8)]
    plan = engine.plan_batch(reqs, packed=True)
    assert plan.bucket == 16
    assert len(plan.rows) <= BATCH
    assert sum(len(row) for row in plan.rows) == 8
    for row in plan.rows:
        assert sum(r.length for r in row) <= plan.bucket
        assert len(row) <= PACK_K
    # Overflow: more tokens than BATCH rows of the largest bucket hold.
    many = [_req(n=30) for _ in range(BATCH + 3)]
    plan = engine.plan_batch(many, packed=True)
    assert len(plan.rows) == BATCH
    assert len(plan.leftover) == 3


# ---------------------------------------------------------------------------
# packed demultiplexing + parity


def _direct_forward(engine, task, features):
    """Unbatched, unjitted reference forward for one request."""
    spec = engine.tasks[task]
    n = len(features["input_ids"])
    S = engine.select_bucket(n)
    ids = np.zeros((1, S), np.int32)
    seg = np.zeros((1, S), np.int32)
    mask = np.zeros((1, S), np.int32)
    ids[0, :n] = features["input_ids"]
    seg[0, :n] = features["segment_ids"]
    mask[0, :n] = 1
    out = spec.model.apply({"params": spec.params}, ids, seg, mask)
    if spec.handler.output_kind == "span":
        return (np.asarray(out[0], np.float32)[0, :n],
                np.asarray(out[1], np.float32)[0, :n])
    if spec.handler.output_kind == "pooled":
        return np.asarray(out, np.float32)[0]
    return np.asarray(out, np.float32)[0, :n]


def _assert_outputs_close(a, b, atol=ATOL):
    if isinstance(a, tuple):
        for x, y in zip(a, b):
            np.testing.assert_allclose(x, y, atol=atol, rtol=0)
    else:
        np.testing.assert_allclose(a, b, atol=atol, rtol=0)


@pytest.mark.parametrize("task", ["fill_mask", "classify", "squad", "ner"])
def test_packed_demux_matches_unpacked_and_direct(engine, task):
    """Acceptance: a packed batch's demultiplexed per-request outputs
    match both the unpacked batched path and a direct (unjitted,
    unbatched) forward to 1e-5 fp32."""
    spec = engine.tasks[task]
    payloads = [p for t, p in _payloads() if t == task] * 4  # 8-12 requests
    requests = [Request(task, spec.handler.prepare(p, engine.max_len()), p)
                for p in payloads]

    todo = list(requests)
    by_id_packed = {}
    shared = False
    while todo:
        plan = engine.plan_batch(todo, packed=True)
        shared = shared or any(len(row) > 1 for row in plan.rows)
        outs, info = engine.execute(task, plan)
        assert info["packed"]
        for r, o in zip(plan.requests, outs):
            by_id_packed[r.id] = o
        todo = plan.leftover
    assert shared, "test payloads must actually share rows"

    todo = list(requests)
    by_id_unpacked = {}
    while todo:
        plan = engine.plan_batch(todo, packed=False)
        outs, info = engine.execute(task, plan)
        assert not info["packed"]
        for r, o in zip(plan.requests, outs):
            by_id_unpacked[r.id] = o
        todo = plan.leftover

    for req in requests:
        _assert_outputs_close(by_id_packed[req.id], by_id_unpacked[req.id])
        _assert_outputs_close(by_id_packed[req.id],
                              _direct_forward(engine, task, req.features))


def test_postprocess_shapes(engine):
    """Task handlers produce their documented JSON shapes end to end."""
    out = engine.run_direct(
        "fill_mask", {"text": "paris is [MASK]", "top_k": 3})
    assert len(out["masks"]) == 1 and len(out["masks"][0]) == 3
    assert {"token", "id", "score"} <= set(out["masks"][0][0])

    out = engine.run_direct("classify", {"text": "paris is big"})
    assert out["label"] in CLS_LABELS
    assert abs(sum(out["scores"].values()) - 1.0) < 1e-6

    out = engine.run_direct(
        "squad", {"question": "what is the capital of france",
                  "context": "the capital of france is paris"})
    assert "answer" in out and isinstance(out["n_best"], list)

    out = engine.run_direct("ner", {"text": "paris is big"})
    assert [e["word"] for e in out["entities"]] == ["paris", "is", "big"]
    assert all(e["tag"] in NER_LABELS + ["O"] for e in out["entities"])


# ---------------------------------------------------------------------------
# CPU smoke acceptance: concurrent HTTP traffic, zero post-warmup compiles,
# schema-clean serve telemetry, telemetry-report summary


def _approx_equal_json(a, b, atol=ATOL):
    if isinstance(a, dict):
        return (isinstance(b, dict) and set(a) == set(b)
                and all(_approx_equal_json(a[k], b[k], atol) for k in a))
    if isinstance(a, list):
        return (isinstance(b, list) and len(a) == len(b)
                and all(_approx_equal_json(x, y, atol)
                        for x, y in zip(a, b)))
    if isinstance(a, float) or isinstance(b, float):
        return abs(float(a) - float(b)) <= atol
    return a == b


def test_http_smoke_concurrent_requests(engine, tmp_path):
    import http.client

    from bert_pytorch_tpu.serve import (Batcher, ServeTelemetry,
                                        ServingService, make_server)
    from bert_pytorch_tpu.telemetry.schema import validate_file
    from bert_pytorch_tpu.tools.make_synthetic_data import (
        make_request_trace)
    from bert_pytorch_tpu.utils.logging import JSONLHandler

    trace_path = make_request_trace(
        str(tmp_path / "requests.jsonl"), 32, seed=11, max_words=20,
        rate_rps=0.0)
    lines = [json.loads(line) for line in open(trace_path)]
    assert len(lines) >= 32 and len({l["task"] for l in lines}) == 4

    jsonl = str(tmp_path / "serve_telemetry.jsonl")
    sink = JSONLHandler(jsonl, overwrite=True)
    telemetry = ServeTelemetry(emit=sink.write_record, window=16)
    # The smoke serves the UNPACKED path so responses are comparable to
    # run_direct exactly; the packed path has its own acceptance below.
    engine.pack = False
    service = ServingService(
        engine, Batcher(max_batch_size=BATCH, max_wait_ms=10.0),
        telemetry)
    events_before = len(engine.monitor.events)
    service.start()
    server = make_server(service, port=0, request_timeout_s=60.0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    responses = [None] * len(lines)

    def fire(i, line):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            conn.request("POST", f"/v1/{line['task']}",
                         json.dumps(line["payload"]),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            responses[i] = (resp.status, json.loads(resp.read()))
        finally:
            conn.close()

    threads = [threading.Thread(target=fire, args=(i, line))
               for i, line in enumerate(lines)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)

        # /statsz + /healthz answer alongside the traffic
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["status"] == "ok" and health["warmed"]
        conn.request("GET", "/statsz")
        stats = json.loads(conn.getresponse().read())
        conn.close()
    finally:
        server.shutdown()
        service.stop()
        sink.close()
        engine.pack = True

    assert all(r is not None and r[0] == 200 for r in responses), [
        r for r in responses if r is None or r[0] != 200][:3]
    # outputs match the direct forward through the same engine
    for line, (_, result) in zip(lines, responses):
        direct = engine.run_direct(line["task"], line["payload"])
        assert _approx_equal_json(result, direct), (line, result, direct)
    assert stats["requests"] >= 32 and stats["errors"] == 0

    # zero NEW compiles across the whole smoke (warmup covered them all)
    new_compiles = [e for e in engine.monitor.events[events_before:]
                    if e.get("kind") == "compile"]
    assert not new_compiles, new_compiles

    # serve telemetry lints clean against schema v1
    assert validate_file(jsonl) == []
    records = [json.loads(line) for line in open(jsonl)]
    kinds = {r.get("kind") for r in records}
    assert "serve_window" in kinds and "serve_summary" in kinds

    # telemetry-report summarizes the artifact (and its serve section)
    from bert_pytorch_tpu.telemetry import report

    summary = report.summarize_file(jsonl)
    assert summary["serve_requests"] >= 32
    assert summary["serve_compiles"] == 0
    text = report.format_summary(summary)
    assert "serve_latency_p95_ms" in text and "serve_occupancy" in text
    # and the p95-latency regression gate trips on a slowed-down run
    slow = dict(summary, serve_latency_p95_ms=(
        summary["serve_latency_p95_ms"] * 10 + 100))
    regressions, _ = report.compare(summary, slow)
    assert any(r["metric"] == "serve_latency_p95_ms" for r in regressions)


# ---------------------------------------------------------------------------
# packing occupancy acceptance


def _replay(engine, requests, packed, flush_size):
    """Drive the engine the way the dispatch loop would: fixed-size
    flushes, leftovers requeued at the front. Returns (outputs by request
    id, real token total, dispatched budget total)."""
    outputs, real, budget = {}, 0, 0
    queue = list(requests)
    while queue:
        group, queue = queue[:flush_size], queue[flush_size:]
        while group:
            plan = engine.plan_batch(group, packed=packed)
            outs, info = engine.execute(group[0].task, plan)
            for r, o in zip(plan.requests, outs):
                outputs[r.id] = o
            real += info["real_tokens"]
            budget += info["rows"] * info["bucket"]
            group = plan.leftover
    return outputs, real, budget


def test_packing_improves_occupancy_1p5x(engine):
    """Acceptance: on a short-biased trace the packed batcher's occupancy
    (real tokens / dispatched slot budget) beats the unpacked batcher by
    >= 1.5x on the SAME trace, with per-request outputs unchanged."""
    from bert_pytorch_tpu.tools.make_synthetic_data import (
        make_request_trace)
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        trace = make_request_trace(
            d + "/requests.jsonl", 48, seed=5, max_words=24, rate_rps=0.0)
        lines = [json.loads(line) for line in open(trace)]

    by_task = {}
    for line in lines:
        spec = engine.tasks[line["task"]]
        features = spec.handler.prepare(line["payload"], engine.max_len())
        by_task.setdefault(line["task"], []).append(
            Request(line["task"], features, line["payload"]))

    real_u = budget_u = real_p = budget_p = 0
    for task, requests in by_task.items():
        out_u, ru, bu = _replay(engine, requests, packed=False,
                                flush_size=BATCH)
        out_p, rp, bp = _replay(engine, requests, packed=True,
                                flush_size=BATCH * PACK_K)
        real_u += ru; budget_u += bu; real_p += rp; budget_p += bp
        for req in requests:  # outputs unchanged under packing
            _assert_outputs_close(out_p[req.id], out_u[req.id])

    occ_unpacked = real_u / budget_u
    occ_packed = real_p / budget_p
    assert real_u == real_p  # same trace, same tokens
    assert occ_packed >= 1.5 * occ_unpacked, (occ_packed, occ_unpacked)
