"""Continuous batching: the pipelined serve dispatch plane (ISSUE 13,
serve/service.py, docs/serving.md "Continuous batching").

Covers the acceptance surface with a DETERMINISTIC fake engine (pure
host-side sleeps per stage — no jax, no device; the real-engine serving
path is exercised end to end by tests/test_serve.py and
tests/test_serve_tracing.py, which now run pipelined by default):

* the same concurrent burst driven through ``dispatch_mode="serial"``
  and ``"pipelined"``: pipelined shows late-admitted requests
  (``admitted_late > 0``), a LOWER executor-gap (device idle) share,
  and p99 no worse; span invariants (sum(spans) <= total,
  queue_wait <= total) hold on every trace in both modes;
* a paced (open-loop) burst through both modes: the slowest-decile
  critical path shifts OFF ``assembly`` — the demux host conversion
  that serial dispatch charges to the assembly span runs on the
  completion stage in pipelined mode, off the device thread's path;
* drain correctness across the pipeline: ``stop()`` fails-or-flushes
  requests stranded in the forming batch, the staged handoff, a wedged
  executor, and a wedged completion stage deterministically — every
  blocked submitter wakes with a definite answer;
* the admission-window API (``Batcher.admit_into_forming``) under a
  fake clock;
* the under-reporting load gauge fix: ``bert_serve_unfinished``
  (pending + in-flight) exported next to ``queue_depth``, a mid-batch
  replica no longer scraping as idle, and the router's least-loaded
  score and brownout admission preferring it;
* the "serve device idle share" telemetry-report gate (fixture pair,
  wired like the PR 9 SLO gates).
"""

import os
import threading
import time

import pytest

from bert_pytorch_tpu.serve.batcher import Batcher, Request
from bert_pytorch_tpu.serve.engine import BatchPlan, StagedBatch
from bert_pytorch_tpu.serve.service import ServingService
from bert_pytorch_tpu.serve.stats import ServeTelemetry
from bert_pytorch_tpu.serve.tracing import TraceCollector
from bert_pytorch_tpu.telemetry import report
from bert_pytorch_tpu.telemetry.schema import validate_file, validate_record

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "telemetry")


# ---------------------------------------------------------------------------
# deterministic fake engine: per-stage costs are injected sleeps


class _Handler:
    output_kind = "pooled"

    def __init__(self, engine):
        self._engine = engine

    def prepare(self, payload, max_len):
        n = min(max_len, int(payload.get("n", 6)))
        return {"input_ids": list(range(2, 2 + n)), "segment_ids": [0] * n}

    def postprocess(self, features, out, payload):
        eng = self._engine
        if payload.get("block") and eng.post_hold is not None \
                and not eng.post_hold.is_set():
            eng.post_entered.set()
            eng.post_hold.wait(10.0)
        if eng.post_s:
            time.sleep(eng.post_s)
        return {"ok": True, "n": len(features["input_ids"])}


class _Spec:
    def __init__(self, handler):
        self.handler = handler


class FakeEngine:
    """Host-only engine stand-in with deterministic per-stage costs.

    ``stage_s``/``execute_s``/``demux_s``/``post_s`` are sleeps, so the
    A/B between serial and pipelined dispatch is a property of the
    dispatch plane alone. ``exec_gate``/``post_hold`` (when set by a
    test) block the executor / completion stage — the wedge shapes the
    drain tests strand requests behind."""

    def __init__(self, stage_s=0.0, execute_s=0.0, demux_s=0.0,
                 post_s=0.0, max_batch_size=4):
        self.stage_s = stage_s
        self.execute_s = execute_s
        self.demux_s = demux_s
        self.post_s = post_s
        self.max_batch_size = max_batch_size
        self.pack = False
        self.warmed = True
        self.startup = None
        self.exec_gate = None       # unset Event = executor blocks
        self.post_hold = None       # unset Event = postprocess blocks
        self.post_entered = threading.Event()
        self.tasks = {"classify": _Spec(_Handler(self))}

    def max_len(self):
        return 32

    def warmup(self):
        return 0

    def plan_batch(self, requests, packed=None):
        take = requests[: self.max_batch_size]
        leftover = requests[self.max_batch_size:]
        return BatchPlan(16, [[r] for r in take], leftover, False)

    def stage(self, task, plan):
        if self.stage_s:
            time.sleep(self.stage_s)
        return StagedBatch(task, plan, (), {}, pack_s=self.stage_s)

    def execute_staged(self, staged):
        if self.exec_gate is not None:
            self.exec_gate.wait(10.0)
        t0 = time.monotonic()
        if self.execute_s:
            time.sleep(self.execute_s)
        device_s = time.monotonic() - t0
        n = len(staged.plan.requests)
        info = {"bucket": staged.plan.bucket, "rows": self.max_batch_size,
                "real_tokens": sum(r.length for r in staged.plan.requests),
                "device_s": device_s, "pack_s": staged.pack_s,
                "compiles": 0, "packed": False}
        return [None] * n, info

    def demux(self, staged, out):
        if self.demux_s:
            time.sleep(self.demux_s)
        return list(out)

    def execute(self, task, plan):
        staged = self.stage(task, plan)
        out, info = self.execute_staged(staged)
        return self.demux(staged, out), info


def _req(n=6, payload=None, task="classify"):
    return Request(task, {"input_ids": list(range(2, 2 + n)),
                          "segment_ids": [0] * n}, payload or {})


def _service(engine, mode, max_batch_size=4, max_wait_ms=2.0,
             tracer=None):
    return ServingService(
        engine, Batcher(max_batch_size=max_batch_size,
                        max_wait_ms=max_wait_ms),
        ServeTelemetry(window=64), tracer=tracer, dispatch_mode=mode)


# ---------------------------------------------------------------------------
# acceptance: the same concurrent burst, serial vs pipelined


def _saturation_leg(mode, n_workers=4, per_worker=7):
    """Closed-loop staggered burst: enough concurrency that batches
    overlap with arrivals — the shape continuous batching exists for."""
    records = []
    tracer = TraceCollector(emit=records.append, sample_rate=1.0,
                            window=64)
    engine = FakeEngine(stage_s=0.004, execute_s=0.025, demux_s=0.008,
                        post_s=0.001)
    service = _service(engine, mode, tracer=tracer)
    service.start()
    errors = []

    def worker(i):
        time.sleep(0.003 * i)  # desynchronize the closed loops
        for k in range(per_worker):
            try:
                service.submit("classify", {"n": 6}, timeout=30.0)
            except Exception as exc:  # pragma: no cover - the assert
                errors.append(exc)
            time.sleep(0.002 * ((i + k) % 3))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    service.stop()
    snap = service.telemetry.snapshot(include_phases=False)
    traces = [r for r in records if r.get("kind") == "serve_trace"]
    return snap, traces, errors


def test_pipelined_vs_serial_saturation_acceptance():
    snap_s, traces_s, err_s = _saturation_leg("serial")
    snap_p, traces_p, err_p = _saturation_leg("pipelined")
    assert not err_s and not err_p
    assert snap_s["requests"] == snap_p["requests"] == 28
    assert snap_s["errors"] == snap_p["errors"] == 0

    # Late admission exists only in the pipelined plane: requests that
    # arrived while a batch executed joined the NEXT forming batch.
    assert snap_p["admitted_late"] > 0
    assert snap_s["admitted_late"] == 0
    assert any(t["admitted_late"] for t in traces_p)
    assert not any(t["admitted_late"] for t in traces_s)

    # The device idles less: back-to-back forwards from the depth-1
    # staged handoff vs serial's assemble/demux/decode gaps.
    assert snap_s["device_idle_share"] > 0
    assert snap_p["device_idle_share"] <= snap_s["device_idle_share"] * 0.8

    # Tail latency is no worse under the pipeline (it should be better:
    # the same batches, minus the serialized host work between them).
    assert snap_p["latency_p99_ms"] <= snap_s["latency_p99_ms"] * 1.25

    # Span invariants hold by construction on EVERY trace, both modes —
    # and the records lint against schema v1 (admitted_late is a real
    # boolean, staged_wait_ms non-negative).
    for t in traces_s + traces_p:
        dur_sum = sum(s["dur_ms"] for s in t["spans"])
        assert dur_sum <= t["total_ms"] + 0.01, t
        assert t["queue_wait_ms"] <= t["total_ms"] + 0.01, t
        assert validate_record(dict(t, schema=1, ts=0.0)) == []
    # Pipelined traces carry the staged-handoff wait as context.
    assert all("staged_wait_ms" in t for t in traces_p)
    assert all("staged_wait_ms" not in t for t in traces_s)


def _paced_leg(mode, n_requests=10, interval_s=0.11):
    """Open-loop paced burst (arrival interval > the serial cycle): no
    queueing in either mode, so per-trace span attribution — not
    backlog — decides the critical path."""
    records = []
    tracer = TraceCollector(emit=records.append, sample_rate=1.0,
                            window=64)
    engine = FakeEngine(stage_s=0.004, execute_s=0.02, demux_s=0.06,
                        post_s=0.001)
    service = _service(engine, mode)
    service.tracer = tracer
    service.telemetry.attach_tracer(tracer)
    service.start()
    errors = []

    def one():
        try:
            service.submit("classify", {"n": 6}, timeout=30.0)
        except Exception as exc:  # pragma: no cover - the assert
            errors.append(exc)

    threads = []
    for _ in range(n_requests):
        t = threading.Thread(target=one)
        threads.append(t)
        t.start()
        time.sleep(interval_s)
    for t in threads:
        t.join(timeout=60)
    service.stop()
    assert not errors
    return [r for r in records if r.get("kind") == "serve_trace"]


def test_critical_path_shifts_off_assembly():
    """Serial dispatch charges the demux host conversion to the
    ``assembly`` span (it happens on the dispatch thread between pop
    and fulfilment); the pipelined completion stage runs it off the
    device path, so the slowest-decile critical path
    (telemetry-report's tail attribution) moves off ``assembly``."""
    traces_serial = _paced_leg("serial")
    traces_pipe = _paced_leg("pipelined")
    cp_serial = report.summarize_records(
        traces_serial, name="serial")["serve_critical_path"]
    cp_pipe = report.summarize_records(
        traces_pipe, name="pipelined")["serve_critical_path"]
    assert max(cp_serial, key=cp_serial.get) == "assembly", cp_serial
    assert max(cp_pipe, key=cp_pipe.get) != "assembly", cp_pipe


# ---------------------------------------------------------------------------
# drain correctness: fail-or-flush across every pipeline stage


def test_stop_fails_stranded_forming_staged_and_executing():
    """A wedged executor strands batches in every stage: the executing
    batch, the staged handoff, the forming batch, and the pending
    queue. stop() must give EVERY request a deterministic error — no
    blocked submitter left waiting for its client-side timeout."""
    engine = FakeEngine(max_batch_size=2)
    engine.exec_gate = threading.Event()  # executor blocks until set
    service = _service(engine, "pipelined", max_batch_size=2,
                       max_wait_ms=1.0)
    service.start()
    try:
        reqs = [_req() for _ in range(8)]
        for r in reqs:
            service.batcher.submit(r)
        # Pipeline fills: b1 executing (blocked), b2 in the handoff,
        # b3 forming, r7/r8 pending.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            health = service.health()
            if health["forming_depth"] == 2 and health["queue_depth"] == 2:
                break
            time.sleep(0.01)
        assert service.batcher.unfinished() == 8
        service.stop(drain_s=0.1, join_s=0.3)
    finally:
        engine.exec_gate.set()  # unwedge for thread cleanup
    for r in reqs:
        assert r.error is not None, r.id
    messages = " | ".join(r.error for r in reqs)
    assert "executing" in messages
    assert "staged but unexecuted" in messages
    assert "before this request was dispatched" in messages
    assert service.batcher.unfinished() == 0
    assert service.telemetry.snapshot()["errors"] == 8


def test_stop_flushes_executed_and_fails_wedged_completion():
    """Batches the executor already finished are FLUSHED at stop (their
    answers exist); the batch a wedged completion stage holds is failed
    deterministically."""
    engine = FakeEngine(max_batch_size=2)
    engine.post_hold = threading.Event()  # postprocess blocks until set
    service = _service(engine, "pipelined", max_batch_size=2,
                       max_wait_ms=1.0)
    service.start()
    try:
        blocked = [_req(payload={"block": True}) for _ in range(2)]
        for r in blocked:
            service.batcher.submit(r)
        # The completion stage is now wedged inside b1's postprocess.
        assert engine.post_entered.wait(5.0)
        flushed = [_req() for _ in range(2)]
        for r in flushed:
            service.batcher.submit(r)
        # b2 executes and parks in the completion queue (nobody drains).
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                service._completed_q.qsize() < 1:
            time.sleep(0.01)
        assert service._completed_q.qsize() >= 1
        service.stop(drain_s=0.1, join_s=0.3)
    finally:
        engine.post_hold.set()  # unwedge for thread cleanup
    # Executed-but-undelivered b2: flushed — real results.
    for r in flushed:
        assert r.result is not None and r.result["ok"], r.id
    # The wedged b1: failed deterministically.
    for r in blocked:
        assert r.error is not None and "completion stage" in r.error, r.id
    assert service.batcher.unfinished() == 0


def test_serial_mode_unchanged_by_stop():
    """The serial plane still drains as before (no pipeline queues to
    sweep): accepted requests are served, late pending ones failed."""
    engine = FakeEngine(execute_s=0.005, max_batch_size=2)
    service = _service(engine, "serial", max_batch_size=2,
                       max_wait_ms=1.0)
    service.start()
    r = _req()
    service.batcher.submit(r)
    assert r.wait(5.0) and r.result is not None
    service.stop()
    assert service.batcher.unfinished() == 0


def test_admission_window_closes_on_unplaceable_leftover():
    """When the re-plan cannot place admitted requests (plan capacity
    below the flush budget — the packed-rows-full shape), the overflow
    bounces back to pending with its admitted_late marker CLEARED and
    the window CLOSES: exactly one re-plan happens, not an
    admit/replan/re-stage spin that burns the assembler until the
    executor goes hungry. Driven deterministically: the handoff is
    pre-parked (executor 'busy', never hungry) and _form_and_hand_off
    runs on the test thread until a timed stop."""
    engine = FakeEngine(max_batch_size=2)
    calls = {"plan": 0}
    orig_plan = engine.plan_batch

    def counting_plan(requests, packed=None):
        calls["plan"] += 1
        return orig_plan(requests, packed)

    engine.plan_batch = counting_plan
    batcher = Batcher(max_batch_size=2, max_wait_ms=1.0,
                      max_requests_per_pack=2)  # flush budget 4 > rows 2
    service = ServingService(engine, batcher, ServeTelemetry(),
                             dispatch_mode="pipelined")
    service._handoff.put(object())  # park: the window can never hand off
    stopper = threading.Timer(0.25, service._stop.set)
    stopper.start()
    live = [_req() for _ in range(4)]
    try:
        service._form_and_hand_off(live)
    finally:
        stopper.cancel()
        service._stop.set()
    # Initial plan + exactly ONE replan for the admitted pair — the
    # unfixed loop replans every ~2ms poll for the whole window.
    assert calls["plan"] == 2, calls
    # The unplaceable pair bounced to pending unmarked; the stop path
    # requeued the forming pair — nobody is stranded, nobody is "late".
    assert batcher.depth() == 4
    assert all(not r.admitted_late for r in live)
    assert all(r.error is None for r in live)


# ---------------------------------------------------------------------------
# the admission-window API under a fake clock


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_admit_into_forming_fake_clock():
    clk = FakeClock()
    b = Batcher(max_batch_size=4, max_wait_ms=10.0, clock=clk)
    classify = [_req() for _ in range(3)]
    other = _req(task="ner")
    for r in (classify[0], other, classify[1], classify[2]):
        b.submit(r)
    clk.t += 0.5
    admitted = b.admit_into_forming("classify", 2)
    # Task-filtered, FIFO-ordered, capped at the limit.
    assert admitted == classify[:2]
    for r in admitted:
        assert r.admitted_late and r.dequeued_at == clk.t
    # They moved pending -> in-flight: unfinished never dipped.
    assert b.depth() == 2 and b.inflight() == 2 and b.unfinished() == 4
    # The remainder keeps arrival order (other task untouched).
    assert b.admit_into_forming("classify", 5) == [classify[2]]
    assert b.depth() == 1
    # limit <= 0 admits nothing; a closed (draining) batcher refuses.
    assert b.admit_into_forming("ner", 0) == []
    b.close()
    assert b.admit_into_forming("ner", 5) == []
    # Flush-path requests are NOT marked late.
    assert not other.admitted_late


# ---------------------------------------------------------------------------
# the under-reporting load gauge fix (bert_serve_unfinished)


def test_mid_batch_replica_no_longer_scrapes_as_idle():
    """queue_depth reads 0 the instant a batch pops; the new
    bert_serve_unfinished gauge (pending + in-flight) keeps reporting
    the requests the replica still owes — on /metricsz AND /healthz."""
    clk = FakeClock()
    b = Batcher(max_batch_size=4, max_wait_ms=1.0, clock=clk)
    for _ in range(3):
        b.submit(_req())
    clk.t += 1.0
    batch = b.poll()  # the whole queue pops: "mid-batch" state
    assert len(batch) == 3
    assert b.depth() == 0 and b.unfinished() == 3
    service = ServingService(
        FakeEngine(), b, ServeTelemetry(),
        tracer=TraceCollector(sample_rate=0.0))
    text = service.metrics_text()
    assert "bert_serve_queue_depth 0" in text
    assert "bert_serve_unfinished 3" in text
    assert "bert_serve_forming_depth 0" in text
    health = service.health()
    assert health["queue_depth"] == 0 and health["unfinished"] == 3


def test_router_prefers_unfinished_and_brownouts_on_it():
    from bert_pytorch_tpu.serve.router import Router

    calls = []

    def transport(url, task, payload, timeout_s):
        calls.append(url)
        return 200, {"ok": True}

    scrapes = {
        # Mid-batch replica: empty queue but 9 unfinished requests.
        "http://a": {"dispatch_alive": True, "draining": False,
                     "queue_depth": 0, "unfinished": 9},
        # Deeper queue but nearly drained pipeline: the honest choice.
        "http://b": {"dispatch_alive": True, "draining": False,
                     "queue_depth": 5, "unfinished": 1},
    }
    router = Router(
        ["http://a", "http://b"], transport=transport,
        scrape=lambda url: scrapes[url.rstrip("/")],
        hedge_pctl=0.0, sleep=lambda s: None)
    router.scrape_once()
    status, _, _ = router.handle("classify", {"text": "x"})
    assert status == 200
    assert calls == ["http://b"]  # least UNFINISHED wins, not queue_depth

    # Brownout admission keys on unfinished too: queue_depth scrapes 0
    # everywhere, yet the fleet is saturated mid-pipeline.
    for s in scrapes.values():
        s["unfinished"] = 500
        s["queue_depth"] = 0
    router2 = Router(
        ["http://a", "http://b"], transport=transport,
        scrape=lambda url: scrapes[url.rstrip("/")],
        hedge_pctl=0.0, brownout_queue_depth=100, sleep=lambda s: None)
    router2.scrape_once()
    status, body, headers = router2.handle("classify", {"text": "x"})
    assert status == 503
    assert "Retry-After" in headers
    assert "brownout" in body["error"]

    # Replicas without the gauge fall back to queue_depth (the pre-gauge
    # scrape shape keeps working).
    old = {"http://a": {"dispatch_alive": True, "draining": False,
                        "queue_depth": 7},
           "http://b": {"dispatch_alive": True, "draining": False,
                        "queue_depth": 2}}
    calls.clear()
    router3 = Router(
        ["http://a", "http://b"], transport=transport,
        scrape=lambda url: old[url.rstrip("/")],
        hedge_pctl=0.0, sleep=lambda s: None)
    router3.scrape_once()
    status, _, _ = router3.handle("classify", {"text": "x"})
    assert status == 200 and calls == ["http://b"]


# ---------------------------------------------------------------------------
# the "serve device idle share" report gate (fixture pair)


def test_device_idle_share_gate_names_regression(capsys):
    base = os.path.join(FIXTURES, "serve_idle_base.jsonl")
    regressed = os.path.join(FIXTURES, "serve_idle_regressed.jsonl")
    assert validate_file(base) == []
    assert validate_file(regressed) == []
    summary = report.summarize_file(regressed)
    assert summary["serve_device_idle_share"] == pytest.approx(0.55)
    assert summary["serve_admitted_late"] == 8
    rc = report.main([regressed, base])
    assert rc == 1
    out = capsys.readouterr().out
    assert "serve device idle share" in out
    assert "REGRESSION" in out
    # The same artifact against itself stays clean.
    assert report.main([base, base]) == 0
