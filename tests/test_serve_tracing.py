"""Request-level tracing, the /metricsz export plane, and the SLO gates
(serve/tracing.py, docs/serving.md "Request tracing & metrics").

Covers the ISSUE-9 acceptance surface on CPU:

* >= 32 concurrent HTTP requests with sampling ON: every sampled trace's
  span tree is complete (the four-phase taxonomy, additive invariants)
  and the artifact lints schema-clean; /metricsz parses as Prometheus
  text with per-task phase histograms CONSISTENT with /statsz, and its
  counters are monotonic across scrapes;
* telemetry-report exits nonzero NAMING "serve SLO p99" when the same
  trace replays against a baseline with an injected queue-delay
  regression;
* the always-sample-slow rule (over-SLO requests traced at rate 0);
* a tracing-off overhead guard (tracer-None p50 within noise of the
  traced path);
* the serve heartbeat satellite (resumable liveness file from the
  dispatch loop);
* fixture-backed schema-lint coverage for the new record kinds.

One module-scoped TWO-task engine (classify + ner, tiny config, buckets
(16, 32), batch 4) keeps the AOT warmup cost down — the tracing layer is
task-generic, and test_serve.py already exercises all four heads.
"""

import json
import re
import subprocess
import sys
import threading
import time

import pytest

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.serve.batcher import Batcher, Request
from bert_pytorch_tpu.serve.tracing import (HIST_BUCKETS_MS, PHASES,
                                            TraceCollector)
from bert_pytorch_tpu.telemetry import report
from bert_pytorch_tpu.telemetry.schema import validate_file, validate_record

BUCKETS = (16, 32)
BATCH = 4
NER_LABELS = ["O", "B-LOC", "B-PER"]
CLS_LABELS = ["neg", "pos"]


# ---------------------------------------------------------------------------
# fixtures


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    from bert_pytorch_tpu.tools.make_synthetic_data import write_trace_vocab

    d = tmp_path_factory.mktemp("trace_vocab")
    return write_trace_vocab(str(d / "vocab.txt"))


@pytest.fixture(scope="module")
def tokenizer(vocab_file):
    from bert_pytorch_tpu.data.tokenization import BertTokenizer

    return BertTokenizer(vocab_file, do_lower_case=True)


@pytest.fixture(scope="module")
def config():
    from bert_pytorch_tpu.tools.make_synthetic_data import TRACE_WORDS

    vocab = 5 + len(TRACE_WORDS)
    vocab += (8 - vocab % 8) % 8
    return BertConfig(
        vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2, next_sentence=True,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)


@pytest.fixture(scope="module")
def engine(config, tokenizer):
    import jax.numpy as jnp

    from bert_pytorch_tpu.serve import InferenceEngine

    eng = InferenceEngine(
        config, tokenizer,
        tasks={"classify": {"labels": CLS_LABELS},
               "ner": {"labels": NER_LABELS}},
        buckets=BUCKETS, max_batch_size=BATCH, dtype=jnp.float32, seed=3)
    eng.warmup()
    return eng


def _payloads(n):
    """n mixed classify/ner payloads over the trace vocabulary."""
    texts = [
        "paris is big",
        "the river runs through london",
        "william shakespeare wrote hamlet in london england",
        "england is old",
        "the capital of france is paris",
    ]
    out = []
    for i in range(n):
        task = "classify" if i % 2 == 0 else "ner"
        out.append((task, {"text": texts[i % len(texts)]}))
    return out


def _serve(engine, sink=None, tracer=None, max_wait_ms=5.0,
           batcher_batch=BATCH, heartbeat=None):
    from bert_pytorch_tpu.serve import ServeTelemetry, ServingService

    telemetry = ServeTelemetry(
        emit=sink.write_record if sink else None, window=16)
    service = ServingService(
        engine, Batcher(max_batch_size=batcher_batch,
                        max_wait_ms=max_wait_ms),
        telemetry, tracer=tracer, heartbeat=heartbeat,
        heartbeat_interval_s=0.0)
    return service


# ---------------------------------------------------------------------------
# collector units (no engine, no jax)


def _phases(queue=0.002, assembly=0.001, execute=0.010, postprocess=0.001):
    return {"queue": queue, "assembly": assembly, "execute": execute,
            "postprocess": postprocess}


def test_head_sampling_is_deterministic_and_rate_bounded():
    records = []
    tc = TraceCollector(emit=records.append, sample_rate=0.5, window=1000)
    for i in range(200):
        tc.observe("classify", i, _phases(), total_s=0.02)
    first = [r["trace_id"] for r in records if r["kind"] == "serve_trace"]
    assert 40 < len(first) < 160  # ~half, hash-dependent but bounded
    # Same ids -> the SAME sampling decisions (replay determinism).
    records2 = []
    tc2 = TraceCollector(emit=records2.append, sample_rate=0.5, window=1000)
    for i in range(200):
        tc2.observe("classify", i, _phases(), total_s=0.02)
    second = [r["trace_id"] for r in records2
              if r["kind"] == "serve_trace"]
    assert [t.split("-")[1] for t in first] == \
        [t.split("-")[1] for t in second]


def test_always_sample_slow_rule_at_rate_zero():
    records = []
    tc = TraceCollector(emit=records.append, sample_rate=0.0,
                        slo_p99_ms=50.0, window=1000)
    tc.observe("classify", 1, _phases(), total_s=0.02)   # under SLO
    tc.observe("classify", 2, _phases(queue=0.2), total_s=0.21)  # over
    traces = [r for r in records if r["kind"] == "serve_trace"]
    assert len(traces) == 1
    assert traces[0]["sampled"] is False
    assert traces[0]["sample_reason"] == "slow"
    assert traces[0]["total_ms"] > 50.0
    # No SLO configured -> rate 0 emits nothing at all.
    silent = []
    tc2 = TraceCollector(emit=silent.append, sample_rate=0.0, window=1000)
    tc2.observe("classify", 2, _phases(queue=0.2), total_s=0.21)
    assert not [r for r in silent if r["kind"] == "serve_trace"]


def test_slow_reason_outranks_head_and_forced_exports_are_capped():
    from bert_pytorch_tpu.serve.tracing import SLOW_TRACE_WINDOW_CAP

    # A head-sampled request that was ALSO over the SLO reports "slow" —
    # the report's serve_traces_slow tail-attribution count keys on the
    # reason, and at rate 1.0 every over-SLO trace would otherwise hide
    # behind "head". `sampled` still records head-sampledness.
    records = []
    tc = TraceCollector(emit=records.append, sample_rate=1.0,
                        slo_p99_ms=50.0, window=1000)
    tc.observe("classify", 1, _phases(), total_s=0.02)            # under
    tc.observe("classify", 2, _phases(queue=0.2), total_s=0.21)   # over
    traces = [r for r in records if r["kind"] == "serve_trace"]
    assert [t["sample_reason"] for t in traces] == ["head", "slow"]
    assert all(t["sampled"] is True for t in traces)

    # Everything-is-slow incident at rate 0: forced exports stop at the
    # per-window budget; the over-SLO counters are never capped.
    slow = []
    tc2 = TraceCollector(emit=slow.append, sample_rate=0.0,
                         slo_p99_ms=50.0, window=1000)
    n = SLOW_TRACE_WINDOW_CAP + 24
    for i in range(n):
        tc2.observe("classify", i, _phases(queue=0.2), total_s=0.21)
    traces = [r for r in slow if r["kind"] == "serve_trace"]
    assert len(traces) == SLOW_TRACE_WINDOW_CAP
    snap = tc2.phase_snapshot()
    assert snap["over_slo"] == n and snap["sampled_traces"] == len(traces)


def test_direct_process_batch_anchors_unstamped_requests(engine):
    """Requests handed straight to process_batch (offline scoring, the
    docstring-invited deterministic-test path) never met Batcher.submit:
    their life must anchor at batch entry, not at the monotonic clock's
    origin — which would register as hours of latency and force-trace
    every one as over-SLO."""
    records = []
    tracer = TraceCollector(emit=records.append, sample_rate=1.0,
                            slo_p99_ms=30000.0, window=1000)
    service = _serve(engine, tracer=tracer)
    spec = engine.tasks["classify"]
    req = Request("classify",
                  spec.handler.prepare({"text": "paris is big"},
                                       engine.max_len()),
                  {"text": "paris is big"})
    assert req.enqueued_at is None  # the unstamped sentinel
    service.process_batch([req])
    assert req.error is None and req.result is not None
    (trace,) = [r for r in records if r["kind"] == "serve_trace"]
    assert trace["sample_reason"] == "head"  # not force-sampled slow
    assert trace["queue_wait_ms"] == 0.0
    # Seconds of real work, not uptime: generous bound for the 2-core box.
    assert trace["total_ms"] < 30000.0


def test_phase_windows_and_snapshot_shape():
    records = []
    tc = TraceCollector(emit=records.append, sample_rate=1.0,
                        slo_p99_ms=100.0, window=4)
    for i in range(9):
        tc.observe("ner", i, _phases(), total_s=0.015)
    windows = [r for r in records if r["kind"] == "serve_phase"]
    assert len(windows) == 2 and all(
        w["window_requests"] == 4 for w in windows)
    tc.finish()  # flushes the 1-request partial window
    windows = [r for r in records if r["kind"] == "serve_phase"]
    assert len(windows) == 3 and windows[-1]["window_requests"] == 1
    for w in windows:
        assert validate_record(dict(w, schema=1, ts=0.0)) == []
        assert 0 <= w["queue_wait_share"] <= 1
    snap = tc.phase_snapshot()
    assert snap["requests"] == 9 and snap["over_slo"] == 0
    assert {"queue_wait_share", "queue_p95_ms", "execute_p95_ms",
            "slo_budget_burn"} <= set(snap)


def test_metrics_text_prometheus_shape():
    tc = TraceCollector(sample_rate=0.0, slo_p99_ms=100.0, window=64)
    for i in range(7):
        tc.observe("classify", i, _phases(), total_s=0.015)
    tc.observe_error("classify")
    text = tc.metrics_text()
    assert 'bert_serve_requests_total{task="classify"} 7' in text
    assert 'bert_serve_errors_total{task="classify"} 1' in text
    assert "bert_serve_slo_p99_target_ms 100" in text
    # Histogram: cumulative over le, _count equals the +Inf bucket.
    for phase in PHASES + ("total",):
        pat = (r'bert_serve_phase_latency_ms_bucket\{task="classify",'
               rf'phase="{phase}",le="([^"]+)"\}} (\d+)')
        buckets = re.findall(pat, text)
        assert len(buckets) == len(HIST_BUCKETS_MS) + 1
        counts = [int(c) for _, c in buckets]
        assert counts == sorted(counts) and counts[-1] == 7
        assert buckets[-1][0] == "+Inf"


# ---------------------------------------------------------------------------
# schema-lint fixtures (the check_telemetry_schema satellite)


def test_trace_schema_fixtures_lint():
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    good = os.path.join(here, "fixtures", "telemetry",
                        "serve_trace_good.jsonl")
    bad = os.path.join(here, "fixtures", "telemetry",
                       "serve_trace_bad.jsonl")
    assert validate_file(good) == []
    errors = validate_file(bad)
    text = " | ".join(err for _, err in errors)
    assert "dur_ms must be a non-negative number" in text
    assert "queue_wait_ms (9.0) exceeds total_ms" in text
    assert "'sampled' must be a boolean" in text
    assert "sum of span durations" in text
    assert "queue_wait_share must be in [0, 1]" in text
    assert "total percentiles not ordered" in text
    assert "over_slo (12) exceeds window_requests (8)" in text
    # continuous-batching field lints (docs/serving.md)
    assert "'admitted_late' must be a boolean" in text
    assert "staged_wait_ms must be a non-negative number" in text
    assert "device_idle_share must be in [0, 1]" in text
    assert "admitted_late (99) exceeds window_requests (8)" in text
    # And the repo tool (jax-free, file-path bootstrap) agrees end to end.
    proc = subprocess.run(
        [sys.executable, "tools/check_telemetry_schema.py", good, bad],
        capture_output=True, text=True,
        cwd=os.path.dirname(here))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "serve_trace_good.jsonl: ok" in proc.stdout
    assert "serve_trace_bad" in proc.stdout


# ---------------------------------------------------------------------------
# report: trace section + the two named gates


def _phase_rec(task="classify", n=16, share=0.2, p99=20.0, over=0,
               target=100.0):
    rec = {"schema": 1, "ts": 0.0, "kind": "serve_phase", "tag": "serve",
           "task": task, "window_requests": n, "queue_wait_share": share,
           "total_p50_ms": p99 * 0.5, "total_p95_ms": p99 * 0.9,
           "total_p99_ms": p99, "slo_target_ms": target,
           "slo_budget": 0.01, "over_slo": over}
    for phase in PHASES:
        rec[f"{phase}_p50_ms"] = 1.0
        rec[f"{phase}_p95_ms"] = 2.0
    return rec


def _trace_rec(total=20.0, dominant="execute"):
    spans = []
    start = 0.0
    for name in PHASES:
        dur = total * 0.7 if name == dominant else total * 0.05
        spans.append({"name": name, "start_ms": start, "dur_ms": dur})
        start += dur
    return {"schema": 1, "ts": 0.0, "kind": "serve_trace", "tag": "serve",
            "trace_id": f"t-{int(total)}", "task": "classify",
            "total_ms": total, "queue_wait_ms": spans[0]["dur_ms"],
            "sampled": True, "sample_reason": "head", "spans": spans}


def test_report_trace_section_and_slo_verdict():
    recs = [_phase_rec(n=16, share=0.2, p99=20.0),
            _phase_rec(task="ner", n=16, share=0.4, p99=30.0)]
    recs += [_trace_rec(total=5.0 + i, dominant="execute")
             for i in range(19)]
    recs.append(_trace_rec(total=500.0, dominant="queue"))
    summary = report.summarize_records(recs, name="t")
    assert summary["serve_queue_wait_share"] == pytest.approx(0.3)
    assert summary["serve_slo_p99_ms"] == 30.0
    assert summary["serve_slo_verdict"] == "ok"
    assert summary["serve_traces"] == 20
    # slowest decile = 2 traces; the 500ms queue-dominated one leads.
    assert summary["serve_critical_path"]["queue"] == 1
    text = report.format_summary(summary)
    assert "serve_queue_wait_share" in text
    assert "serve_critical_path" in text
    # Budget burn past 1.0 (or p99 over target) flips the verdict.
    breach = report.summarize_records(
        [_phase_rec(n=16, share=0.2, p99=150.0, over=8)])
    assert breach["serve_slo_verdict"] == "breach"
    assert breach["serve_slo_budget_burn"] > 1.0


def test_slo_gates_trip_by_name():
    base = report.summarize_records([_phase_rec(share=0.2, p99=20.0)])
    slow = report.summarize_records([_phase_rec(share=0.5, p99=80.0)])
    regressions, _ = report.compare(base, slow)
    labels = [r["label"] for r in regressions]
    assert "serve queue-wait share" in labels
    assert "serve SLO p99" in labels
    # Within tolerance: neither gate fires.
    near = report.summarize_records([_phase_rec(share=0.21, p99=21.0)])
    regressions, checks = report.compare(base, near)
    assert not regressions
    assert {"serve_queue_wait_share", "serve_slo_p99_ms"} <= {
        c["metric"] for c in checks}


# ---------------------------------------------------------------------------
# ISSUE-9 acceptance: concurrent HTTP with sampling on, /metricsz vs
# /statsz consistency, counter monotonicity, and the named SLO gate on an
# injected queue-delay regression


def _http_get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8")
    finally:
        conn.close()


def _fire_concurrent(port, payloads):
    import http.client

    responses = [None] * len(payloads)

    def fire(i, task, payload):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            conn.request("POST", f"/v1/{task}", json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            responses[i] = (resp.status, json.loads(resp.read()))
        finally:
            conn.close()

    threads = [threading.Thread(target=fire, args=(i, task, payload))
               for i, (task, payload) in enumerate(payloads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    return responses


def _parse_prom_counters(text, name):
    out = {}
    for task, value in re.findall(
            rf'{name}\{{task="([a-z_]+)"\}} (\d+)', text):
        out[task] = int(value)
    return out


def _replay_to_artifact(engine, tmp_path, name, payloads, max_wait_ms,
                        batcher_batch, slo_p99_ms):
    """One traced replay -> (jsonl path, statsz snapshot)."""
    from bert_pytorch_tpu.utils.logging import JSONLHandler

    jsonl = str(tmp_path / name)
    sink = JSONLHandler(jsonl, overwrite=True)
    tracer = TraceCollector(emit=sink.write_record, sample_rate=1.0,
                            slo_p99_ms=slo_p99_ms, window=8)
    service = _serve(engine, sink=sink, tracer=tracer,
                     max_wait_ms=max_wait_ms, batcher_batch=batcher_batch)
    from bert_pytorch_tpu.serve import make_server

    service.start()
    server = make_server(service, port=0, request_timeout_s=60.0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        responses = _fire_concurrent(port, payloads)
        assert all(r is not None and r[0] == 200 for r in responses), [
            r for r in responses if r is None or r[0] != 200][:3]
        _, stats_body = _http_get(port, "/statsz")
        stats = json.loads(stats_body)
    finally:
        server.shutdown()
        service.stop()
        sink.close()
    return jsonl, stats


def test_http_tracing_acceptance(engine, tmp_path, capsys):
    from bert_pytorch_tpu.serve import make_server
    from bert_pytorch_tpu.utils.logging import JSONLHandler

    payloads = _payloads(32)
    jsonl = str(tmp_path / "serve_traced.jsonl")
    sink = JSONLHandler(jsonl, overwrite=True)
    tracer = TraceCollector(emit=sink.write_record, sample_rate=1.0,
                            slo_p99_ms=30000.0, window=8)
    service = _serve(engine, sink=sink, tracer=tracer)
    service.start()
    server = make_server(service, port=0, request_timeout_s=60.0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        responses = _fire_concurrent(port, payloads)
        assert all(r is not None and r[0] == 200 for r in responses), [
            r for r in responses if r is None or r[0] != 200][:3]

        # -- /statsz carries the phase rollup; /metricsz is consistent
        _, stats_body = _http_get(port, "/statsz")
        stats = json.loads(stats_body)
        assert stats["requests"] == 32 and stats["errors"] == 0
        phases = stats["phases"]
        assert phases["requests"] == 32
        assert 0 <= phases["queue_wait_share"] <= 1

        status, metrics1 = _http_get(port, "/metricsz")
        assert status == 200
        counts1 = _parse_prom_counters(metrics1,
                                       "bert_serve_requests_total")
        assert sum(counts1.values()) == stats["requests"] == 32
        assert set(counts1) == {"classify", "ner"}
        # Per-task phase histograms: every phase's +Inf count equals the
        # task's request counter (each request contributes one sample).
        for task, n in counts1.items():
            for phase in PHASES + ("total",):
                pat = (r'bert_serve_phase_latency_ms_bucket\{'
                       rf'task="{task}",phase="{phase}",le="\+Inf"\}} '
                       r"(\d+)")
                (inf_count,) = re.findall(pat, metrics1)
                assert int(inf_count) == n, (task, phase)
        assert "bert_serve_queue_depth" in metrics1
        assert "bert_serve_dispatch_alive 1" in metrics1

        # -- counter monotonicity across scrapes under more traffic
        more = _fire_concurrent(port, _payloads(4))
        assert all(r is not None and r[0] == 200 for r in more)
        _, metrics2 = _http_get(port, "/metricsz")
        counts2 = _parse_prom_counters(metrics2,
                                       "bert_serve_requests_total")
        assert sum(counts2.values()) == 36
        for task in counts1:
            assert counts2[task] >= counts1[task]
    finally:
        server.shutdown()
        service.stop()
        sink.close()

    # -- every sampled trace's span tree is complete and schema-clean
    assert validate_file(jsonl) == []
    records = [json.loads(line) for line in open(jsonl)]
    traces = [r for r in records if r.get("kind") == "serve_trace"]
    assert len(traces) == 36  # rate 1.0: every request traced
    for t in traces:
        assert [s["name"] for s in t["spans"]] == list(PHASES)
        dur_sum = sum(s["dur_ms"] for s in t["spans"])
        assert dur_sum <= t["total_ms"] + 0.01
        assert t["queue_wait_ms"] <= t["total_ms"] + 0.01
        assert t["sampled"] is True and t["sample_reason"] == "head"
        assert t["bucket"] in BUCKETS and t["batch_requests"] >= 1
        # host-cost context rides the record (pre-queue prepare; the
        # engine's array-fill share of assembly)
        assert t["prepare_ms"] >= 0 and t["pack_ms"] >= 0
        # span offsets chain: each span starts where the previous ended
        for prev, cur in zip(t["spans"], t["spans"][1:]):
            assert cur["start_ms"] == pytest.approx(
                prev["start_ms"] + prev["dur_ms"], abs=0.01)
    phase_windows = [r for r in records if r.get("kind") == "serve_phase"]
    assert {w["task"] for w in phase_windows} == {"classify", "ner"}

    # -- the named SLO gate: replay the SAME payloads with an injected
    # queue-delay regression (a 64-wide flush that only ever fires on
    # the 1.5s oldest-request deadline parks every request in the
    # queue), then report run-vs-baseline: nonzero exit naming
    # "serve SLO p99".
    slow_jsonl, slow_stats = _replay_to_artifact(
        engine, tmp_path, "serve_slow.jsonl", payloads,
        max_wait_ms=1500.0, batcher_batch=64, slo_p99_ms=30000.0)
    assert slow_stats["phases"]["queue_p95_ms"] >= 1000.0
    rc = report.main([slow_jsonl, jsonl])
    assert rc == 1
    out = capsys.readouterr().out
    assert "serve SLO p99" in out
    assert "REGRESSION" in out
    # The queue-delay regression is attributed to the queue phase: the
    # slow run's critical path is queue-dominated.
    slow_summary = report.summarize_file(slow_jsonl)
    assert set(slow_summary["serve_critical_path"]) == {"queue"}


# ---------------------------------------------------------------------------
# slow-rule end to end + overhead guard + heartbeat


def test_slow_requests_traced_at_rate_zero_end_to_end(engine, tmp_path):
    """An over-SLO request is exported even with head sampling OFF —
    the always-sample-slow rule on the real dispatch path (SLO set below
    the deadline-flush latency so every request counts as slow)."""
    from bert_pytorch_tpu.utils.logging import JSONLHandler

    jsonl = str(tmp_path / "slow_only.jsonl")
    sink = JSONLHandler(jsonl, overwrite=True)
    tracer = TraceCollector(emit=sink.write_record, sample_rate=0.0,
                            slo_p99_ms=0.1, window=8)
    service = _serve(engine, sink=sink, tracer=tracer, max_wait_ms=50.0)
    service.start()
    try:
        for task, payload in _payloads(3):
            service.submit(task, payload, timeout=30.0)
    finally:
        service.stop()
        sink.close()
    assert validate_file(jsonl) == []
    traces = [json.loads(line) for line in open(jsonl)
              if '"serve_trace"' in line]
    assert len(traces) == 3
    assert all(t["sampled"] is False and t["sample_reason"] == "slow"
               for t in traces)
    snap = tracer.phase_snapshot()
    assert snap["over_slo"] == 3 and snap["slo_budget_burn"] > 1.0


def test_tracing_overhead_guard(engine):
    """Tracing off (tracer=None) must serve at the same p50 as the fully
    traced path — the per-request bookkeeping is a few clock reads and
    one locked dict update. Generous bound: this box is 2 throttled
    cores and the absolute latencies are milliseconds."""
    def median_latency(tracer):
        service = _serve(engine, tracer=tracer, max_wait_ms=1.0)
        service.start()
        try:
            for task, payload in _payloads(6):  # warm the path
                service.submit(task, payload, timeout=30.0)
            t_samples = []
            for task, payload in _payloads(18):
                t0 = time.perf_counter()
                service.submit(task, payload, timeout=30.0)
                t_samples.append(time.perf_counter() - t0)
        finally:
            service.stop()
        return sorted(t_samples)[len(t_samples) // 2]

    untraced = median_latency(None)
    traced = median_latency(
        TraceCollector(sample_rate=1.0, slo_p99_ms=1000.0, window=8))
    assert traced <= untraced * 2.5 + 0.02, (traced, untraced)


def test_http_trace_context_adoption_and_echo(engine, tmp_path):
    """The ISSUE-16 replica half of trace propagation over REAL HTTP
    (docs/observability.md "Trace propagation"): an inbound
    ``X-Bert-Trace`` header is adopted — the ROUTER'S sampling decision
    replaces the local head hash, so a replica at rate 0 still traces a
    sampled=1 request — and the trace id is ECHOED on every response
    (200s, 400s, context-free requests get no echo), which is what the
    chaos harness's per-request correlation check rides on."""
    import http.client

    from bert_pytorch_tpu.serve import make_server
    from bert_pytorch_tpu.serve.tracing import (TRACE_HEADER,
                                                TRACE_ID_RESPONSE_HEADER)
    from bert_pytorch_tpu.utils.logging import JSONLHandler

    def post(port, task, payload, headers=None):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            hdrs = {"Content-Type": "application/json"}
            hdrs.update(headers or {})
            conn.request("POST", f"/v1/{task}", json.dumps(payload), hdrs)
            resp = conn.getresponse()
            body = resp.read()
            return resp.status, body, dict(resp.getheaders())
        finally:
            conn.close()

    jsonl = str(tmp_path / "ctx_adoption.jsonl")
    sink = JSONLHandler(jsonl, overwrite=True)
    # Rate 0, no SLO: left alone, this tracer NEVER emits a trace — every
    # serve_trace below exists only because the router context said so.
    tracer = TraceCollector(emit=sink.write_record, sample_rate=0.0,
                            window=8)
    service = _serve(engine, sink=sink, tracer=tracer)
    service.start()
    server = make_server(service, port=0, request_timeout_s=60.0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        # Router-sampled request: traced despite local rate 0, echoed.
        status, body, headers = post(
            port, "classify", {"text": "paris is big"},
            {TRACE_HEADER: "rt-cafe0001-1;attempt=2;sampled=1"})
        assert status == 200 and json.loads(body)["label"] in CLS_LABELS
        assert headers.get(TRACE_ID_RESPONSE_HEADER) == "rt-cafe0001-1"
        # Router said NOT sampled: echoed anyway, but no trace emitted.
        status, _, headers = post(
            port, "classify", {"text": "england is old"},
            {TRACE_HEADER: "rt-cafe0001-2;attempt=1;sampled=0"})
        assert status == 200
        assert headers.get(TRACE_ID_RESPONSE_HEADER) == "rt-cafe0001-2"
        # No context: no echo header at all (nothing to correlate with).
        status, _, headers = post(port, "classify", {"text": "paris"})
        assert status == 200
        assert TRACE_ID_RESPONSE_HEADER not in headers
        # Error paths echo too — correlation must survive failures.
        status, _, headers = post(
            port, "nosuchtask", {"text": "x"},
            {TRACE_HEADER: "rt-cafe0001-3;attempt=1;sampled=1"})
        assert status == 404
        assert headers.get(TRACE_ID_RESPONSE_HEADER) == "rt-cafe0001-3"
    finally:
        server.shutdown()
        service.stop()
        sink.close()
    assert validate_file(jsonl) == []
    traces = [json.loads(line) for line in open(jsonl)
              if '"serve_trace"' in line]
    # Exactly ONE: the sampled=1 request. The sampled=0 request obeyed
    # the router both ways; the context-free one fell back to rate 0.
    assert len(traces) == 1
    t = traces[0]
    assert t["parent_trace_id"] == "rt-cafe0001-1"
    assert t["attempt"] == 2
    assert t["sampled"] is True and t["sample_reason"] == "head"


def test_serve_heartbeat_is_written_and_resumable(engine, tmp_path):
    from bert_pytorch_tpu.telemetry.sentinels import Heartbeat

    path = str(tmp_path / "heartbeat.json")
    service = _serve(engine, heartbeat=Heartbeat(path))
    service.start()
    try:
        for task, payload in _payloads(2):
            service.submit(task, payload, timeout=30.0)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            beat = Heartbeat.read(path)
            if beat and beat["step"] >= 2:
                break
            time.sleep(0.05)
    finally:
        service.stop()
    beat = Heartbeat.read(path)
    assert beat is not None
    assert beat["step"] == 2          # step = requests served
    assert beat["counter"] >= 2       # start beat + loop/stop beats
    # Resumable: a restarted server continues the counter monotonically
    # (the liveness check is "did counter advance", across restarts too).
    resumed = Heartbeat(path)
    assert resumed.counter == beat["counter"]
    resumed.beat(5)
    assert Heartbeat.read(path)["counter"] == beat["counter"] + 1
