"""SQuAD v2.0 offline oracle (scripts/squad_evaluate_v20.py) + v2
synthetic data generation.

The reference evaluates v2.0 runs by shelling out to the official
evaluate-v2.0.py it downloads alongside the dataset (reference
run_squad.py:1197-1204, utils/download.py:119-120); this environment has
zero egress, so the repo carries a fresh implementation of the published
algorithm. These tests pin its semantics: empty-string handling for
unanswerable questions, HasAns/NoAns breakdowns, threshold application,
and the best-threshold sweep.
"""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "squad_evaluate_v20.py")

spec = importlib.util.spec_from_file_location("squad_evaluate_v20", SCRIPT)
v20 = importlib.util.module_from_spec(spec)
spec.loader.exec_module(v20)


def _dataset():
    def qa(qid, question, answers, impossible=False):
        return {"id": qid, "question": question, "answers": answers,
                "is_impossible": impossible}

    ctx = "the capital of france is paris"
    return [{"title": "t", "paragraphs": [{"context": ctx, "qas": [
        qa("has1", "capital of france?",
           [{"text": "paris", "answer_start": ctx.index("paris")}]),
        qa("has2", "capital of what is paris?",
           [{"text": "france", "answer_start": ctx.index("france")}]),
        qa("no1", "who wrote hamlet?", [], impossible=True),
        qa("no2", "longest river?", [], impossible=True),
    ]}]}]


class TestRawMetric:
    def test_all_correct(self):
        out = v20.evaluate(_dataset(), {
            "has1": "Paris", "has2": "France", "no1": "", "no2": ""})
        assert out["exact"] == 100.0 and out["f1"] == 100.0
        assert out["exact_match"] == out["exact"]  # runner-summary key
        assert out["HasAns_total"] == 2 and out["NoAns_total"] == 2
        assert out["HasAns_exact"] == 100.0 and out["NoAns_exact"] == 100.0

    def test_wrong_text_on_unanswerable_scores_zero(self):
        out = v20.evaluate(_dataset(), {
            "has1": "Paris", "has2": "France", "no1": "shakespeare",
            "no2": ""})
        assert out["NoAns_exact"] == 50.0
        assert out["exact"] == 75.0

    def test_f1_partial_credit_only_for_answerable(self):
        out = v20.evaluate(_dataset(), {
            "has1": "is paris", "has2": "France", "no1": "", "no2": ""})
        # token F1 for 'is paris' vs 'paris': normalize drops nothing
        # here; P=1/2, R=1/1 -> F1 = 2/3
        assert abs(out["f1"] - 100.0 * (2 / 3 + 1 + 1 + 1) / 4) < 1e-9
        assert out["exact"] == 75.0

    def test_normalization_articles_punct_case(self):
        assert v20.compute_exact("The Paris!", "paris") == 1
        assert v20.compute_f1("", "") == 1.0
        assert v20.compute_f1("paris", "") == 0.0

    def test_missing_prediction_dropped_from_denominator(self, capsys):
        out = v20.evaluate(_dataset(), {
            "has1": "paris", "has2": "france", "no1": ""})
        assert out["total"] == 3


class TestThreshold:
    def _na(self, **kw):
        # score-diff style: higher = more likely unanswerable
        base = {"has1": -8.0, "has2": -6.0, "no1": 5.0, "no2": 7.0}
        base.update(kw)
        return base

    def test_threshold_flips_predictions_to_null(self):
        # raw predictions answer EVERYTHING with text; na_probs above the
        # threshold convert them to no-answer at scoring time
        preds = {"has1": "paris", "has2": "france",
                 "no1": "shakespeare", "no2": "nile"}
        out = v20.evaluate(_dataset(), preds, self._na(), na_prob_thresh=0.0)
        assert out["exact"] == 100.0  # no-ans qids crossed the threshold
        out_hi = v20.evaluate(_dataset(), preds, self._na(),
                              na_prob_thresh=10.0)
        assert out_hi["NoAns_exact"] == 0.0

    def test_best_thresh_search_finds_separator(self):
        preds = {"has1": "paris", "has2": "france",
                 "no1": "shakespeare", "no2": "nile"}
        out = v20.evaluate(_dataset(), preds, self._na(),
                           na_prob_thresh=100.0)  # terrible fixed thresh
        assert out["exact"] == 50.0
        # ... but the sweep finds a separating threshold in [-6, 5)
        assert out["best_exact"] == 100.0
        assert -6.0 <= out["best_exact_thresh"] < 5.0
        assert out["best_f1"] == 100.0

    def test_best_thresh_prefers_all_null_when_preds_bad(self):
        # predictions wrong everywhere; best strategy = call everything
        # unanswerable => score = #no-answer questions
        preds = {"has1": "lyon", "has2": "lyon",
                 "no1": "shakespeare", "no2": "nile"}
        out = v20.evaluate(_dataset(), preds, self._na(), na_prob_thresh=0.0)
        assert out["best_exact"] == 50.0


class TestCli:
    def test_cli_contract(self, tmp_path):
        data = tmp_path / "d.json"
        data.write_text(json.dumps({"version": "v2.0", "data": _dataset()}))
        preds = tmp_path / "p.json"
        preds.write_text(json.dumps({
            "has1": "paris", "has2": "france", "no1": "", "no2": ""}))
        odds = tmp_path / "o.json"
        odds.write_text(json.dumps({
            "has1": -8.0, "has2": -6.0, "no1": 5.0, "no2": 7.0}))
        out = json.loads(subprocess.run(
            [sys.executable, SCRIPT, str(data), str(preds),
             "--na-prob-file", str(odds), "--na-prob-thresh", "0.0"],
            capture_output=True, text=True, check=True).stdout)
        assert out["exact_match"] == 100.0
        assert out["best_exact"] == 100.0

    def test_cli_without_na_probs(self, tmp_path):
        data = tmp_path / "d.json"
        data.write_text(json.dumps({"version": "v2.0", "data": _dataset()}))
        preds = tmp_path / "p.json"
        preds.write_text(json.dumps({
            "has1": "paris", "has2": "berlin", "no1": "", "no2": "x"}))
        out = json.loads(subprocess.run(
            [sys.executable, SCRIPT, str(data), str(preds)],
            capture_output=True, text=True, check=True).stdout)
        assert out["exact"] == 50.0
        assert "best_exact" not in out


class TestSyntheticV2:
    def test_generator_marks_impossible_and_version(self, tmp_path):
        from bert_pytorch_tpu.tools import make_synthetic_text as mst

        path = tmp_path / "v2.json"
        mst.write_squad(str(path), n_paragraphs=20, qas_per_paragraph=3,
                        seed=5, fact_seed=0, impossible_frac=0.5)
        data = json.load(open(path))
        assert data["version"] == "v2.0"
        n_imp = n_ans = 0
        for art in data["data"]:
            for para in art["paragraphs"]:
                ctx = para["context"]
                for qa in para["qas"]:
                    if qa["is_impossible"]:
                        n_imp += 1
                        assert qa["answers"] == []
                    else:
                        n_ans += 1
                        a = qa["answers"][0]
                        s = a["answer_start"]
                        assert ctx[s:s + len(a["text"])] == a["text"]
        # frac 0.5 over ~60 questions: both classes well represented
        assert n_imp >= 10 and n_ans >= 10

    def test_v1_output_unchanged(self, tmp_path):
        from bert_pytorch_tpu.tools import make_synthetic_text as mst

        path = tmp_path / "v1.json"
        mst.write_squad(str(path), n_paragraphs=3, qas_per_paragraph=2,
                        seed=5, fact_seed=0)
        data = json.load(open(path))
        assert data["version"] == "1.1"
        for art in data["data"]:
            for para in art["paragraphs"]:
                for qa in para["qas"]:
                    assert "is_impossible" not in qa
                    assert len(qa["answers"]) == 1

    def test_impossible_question_not_answerable_from_context(self, tmp_path):
        import re

        from bert_pytorch_tpu.tools import make_synthetic_text as mst

        path = tmp_path / "v2.json"
        mst.write_squad(str(path), n_paragraphs=30, qas_per_paragraph=3,
                        seed=7, fact_seed=0, impossible_frac=0.4)
        data = json.load(open(path))
        checked = 0
        for art in data["data"]:
            for para in art["paragraphs"]:
                for qa in para["qas"]:
                    if not qa["is_impossible"]:
                        continue
                    checked += 1
                    # identify (relation, entity) from the question, then
                    # assert the relation's fact STATEMENT (for that
                    # entity, any value) never occurs in the context — the
                    # question's fact is genuinely absent, not reworded
                    matched = False
                    for _rel, stmt_tpl, q_tpl in mst.RELATIONS:
                        m = re.fullmatch(
                            re.escape(q_tpl).replace(r"\{a\}", r"(\w+)"),
                            qa["question"])
                        if not m:
                            continue
                        matched = True
                        stmt_re = (re.escape(stmt_tpl)
                                   .replace(r"\{a\}", re.escape(m.group(1)))
                                   .replace(r"\{b\}", r"\w+"))
                        assert not re.search(stmt_re, para["context"])
                    assert matched
        assert checked > 5
