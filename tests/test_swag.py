"""SWAG multiple-choice: reading, featurization, and a tiny e2e finetune."""

import json

import numpy as np
import pytest

VOCAB_TOKENS = (
    ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    + ["the", "chef", "cook", "##s", "a", "meal", "eats", "it", "burns",
       "kitchen", "sings", "loudly", "quietly", "then", "and"]
)


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    path.write_text("\n".join(VOCAB_TOKENS) + "\n")
    return str(path)


@pytest.fixture(scope="module")
def tokenizer(vocab_file):
    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer

    return get_wordpiece_tokenizer(vocab_file)


@pytest.fixture(scope="module")
def swag_csv(tmp_path_factory):
    """Learnable toy task: the correct ending repeats a context word."""
    import csv

    path = tmp_path_factory.mktemp("swag") / "train.csv"
    header = ["video-id", "fold-ind", "startphrase", "sent1", "sent2",
              "gold-source", "ending0", "ending1", "ending2", "ending3"]
    rows = []
    for i in range(16):
        label = i % 4
        endings = ["sings loudly", "burns it", "eats quietly", "cooks a meal"]
        # rotate so the gold ending is 'cooks a meal' at index `label`
        rotated = endings[-label:] + endings[:-label] if label else endings
        gold_at = rotated.index("cooks a meal")
        rows.append([f"v{i}", i, "x", "the chef cooks a meal", "then",
                     "gold"] + rotated + [gold_at])
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(header + ["label"])
        w.writerows(rows)
    return str(path)


def test_read_swag_examples(swag_csv):
    from bert_pytorch_tpu.data import swag

    examples = swag.read_swag_examples(swag_csv)
    assert len(examples) == 16
    ex = examples[0]
    assert ex.context == "the chef cooks a meal"
    assert ex.start == "then"
    assert len(ex.endings) == 4
    assert ex.endings[ex.label] == "cooks a meal"


def test_read_swag_missing_columns(tmp_path):
    from bert_pytorch_tpu.data import swag

    bad = tmp_path / "bad.csv"
    bad.write_text("a,b\n1,2\n")
    with pytest.raises(ValueError, match="missing SWAG columns"):
        swag.read_swag_examples(str(bad))


def test_swag_featurization_layout(swag_csv, tokenizer):
    from bert_pytorch_tpu.data import swag

    examples = swag.read_swag_examples(swag_csv)
    arrays = swag.convert_examples_to_arrays(examples, tokenizer, 24)
    assert arrays["input_ids"].shape == (16, 4, 24)
    cls_id = tokenizer.token_to_id("[CLS]")
    sep_id = tokenizer.token_to_id("[SEP]")
    ids = arrays["input_ids"][0, 0]
    seg = arrays["segment_ids"][0, 0]
    mask = arrays["input_mask"][0, 0]
    assert ids[0] == cls_id
    seps = np.flatnonzero(ids == sep_id)
    assert len(seps) == 2
    assert seg[seps[0]] == 0 and seg[seps[1]] == 1  # pair segments
    assert mask[seps[1]] == 1 and mask[seps[1] + 1 :].sum() == 0
    # choices share the context but differ in the ending
    assert (arrays["input_ids"][0, 0] != arrays["input_ids"][0, 1]).any()


def test_swag_end_to_end_tiny(tmp_path, swag_csv, vocab_file):
    import run_swag

    model_config = {
        "vocab_size": len(VOCAB_TOKENS), "hidden_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 64, "max_position_embeddings": 32,
        "type_vocab_size": 2, "next_sentence": True,
        "vocab_file": vocab_file, "tokenizer": "wordpiece",
    }
    config_path = tmp_path / "model.json"
    config_path.write_text(json.dumps(model_config))
    args = run_swag.parse_arguments([
        "--train_file", swag_csv, "--val_file", swag_csv,
        "--model_config_file", str(config_path),
        "--output_dir", str(tmp_path / "out"),
        "--epochs", "8", "--batch_size", "8", "--max_seq_len", "24",
        "--lr", "3e-3", "--dtype", "float32",
    ])
    results = run_swag.main(args)
    # 'pick the ending echoing the context' is learnable by a 2-layer model
    assert results["accuracy"] >= 0.5
    assert (tmp_path / "out" / "eval_results_swag.json").exists()


def test_swag_unlabeled_rejected(swag_csv, tokenizer):
    from bert_pytorch_tpu.data import swag

    examples = swag.read_swag_examples(swag_csv, has_label=False)
    assert all(e.label is None for e in examples)
    with pytest.raises(ValueError, match="no label"):
        swag.convert_examples_to_arrays(examples, tokenizer, 24)
