"""Telemetry layer tests (docs/telemetry.md, ISSUE 1).

Covers the five telemetry pieces in isolation — JSONL sink round-trip +
schema pin, StepTimer decomposition under a fake clock, sentinel
abort-after-K, compile-event emission on a forced persistent-cache miss,
heartbeat advance/resume — the logging satellites (CSV widening,
is_primary vs verbose, stepless TensorBoard records, init closing
handlers), the schema lint over the committed bench artifacts, and the
acceptance CPU smoke: a >=20-step synthetic pretraining run whose JSONL
stream must hold the step-time decomposition, MFU, a compile event with
cache status, and an advancing heartbeat.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bert_pytorch_tpu import telemetry
from bert_pytorch_tpu.telemetry import schema as tschema
from bert_pytorch_tpu.telemetry.profiler import parse_profile_spec
from bert_pytorch_tpu.telemetry.sentinels import (FailureSentinel, Heartbeat,
                                                  NonFiniteError)
from bert_pytorch_tpu.telemetry.step_timer import StepTimer
from bert_pytorch_tpu.utils import logging as logging_util

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    """Manually-advanced clock for deterministic timer tests."""

    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# -- JSONL sink + schema ------------------------------------------------


def test_schema_version_pinned():
    # Consumers dispatch on this; bump KNOWN_VERSIONS when it changes.
    assert tschema.SCHEMA_VERSION == 1
    assert tschema.SCHEMA_VERSION in tschema.KNOWN_VERSIONS


def test_jsonl_sink_round_trip(tmp_path):
    path = str(tmp_path / "t.jsonl")
    sink = logging_util.JSONLHandler(path)
    sink.write_record({"kind": "run_summary", "tag": "telemetry",
                       "step": 3, "steps": 3, "note": "hi"})
    sink.write_record({"tag": "train", "step": 4, "loss": 1.25})
    sink.close()
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    for rec in lines:
        assert rec["schema"] == tschema.SCHEMA_VERSION
        assert "ts" in rec
    assert lines[0]["note"] == "hi"
    assert lines[1]["loss"] == 1.25
    assert tschema.validate_file(path) == []


def test_jsonl_sink_nonfinite_becomes_null(tmp_path):
    path = str(tmp_path / "nan.jsonl")
    sink = logging_util.JSONLHandler(path)
    sink.write_record({"tag": "train", "step": 1, "loss": float("nan"),
                       "grad_norm": float("inf")})
    sink.close()
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    rec = json.loads(raw)
    assert rec["loss"] is None and rec["grad_norm"] is None
    assert tschema.validate_file(path) == []


def test_schema_rejects_bad_records(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"schema": 999, "ts": 0}) + "\n")
        f.write(json.dumps({"schema": 1, "ts": 0, "kind": "mystery"}) + "\n")
        f.write(json.dumps({"schema": 1, "ts": 0, "kind": "sentinel"}) + "\n")
        f.write('{"loss": NaN}\n')
        f.write("not json at all\n")
        window = {"schema": 1, "ts": 0, "kind": "step_window", "step": 1,
                  "window_steps": 1, "synced_steps": 1, "steps_per_sec": 1.0,
                  "mfu": 0.0}
        window.update({f"{p}_{s}_s": 0.0 for p in
                       ("data_wait", "host", "device", "step")
                       for s in ("p50", "p95", "max")})
        f.write(json.dumps({**window, "loader": {"batches": 1}}) + "\n")
    errors = tschema.validate_file(path)
    linenos = [lineno for lineno, _ in errors]
    assert 1 in linenos  # unknown version
    assert 2 in linenos  # unknown kind
    assert 3 in linenos  # missing required keys
    assert 4 in linenos  # NaN spelling
    assert 5 in linenos  # invalid JSON
    assert 6 in linenos  # malformed nested loader gauges


def test_check_telemetry_schema_tool(tmp_path):
    """The tier-1 lint: committed artifacts pass; a malformed file fails."""
    tool = os.path.join(REPO_ROOT, "tools", "check_telemetry_schema.py")
    proc = subprocess.run([sys.executable, tool], capture_output=True,
                          text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    bad = tmp_path / "BROKEN_r99.jsonl"
    bad.write_text('{"metric": "x", "value": NaN}\n')
    proc = subprocess.run([sys.executable, tool, str(bad)],
                          capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1
    assert "non-finite" in proc.stdout


# -- logging satellites -------------------------------------------------


def test_csv_handler_widens_on_new_keys(tmp_path):
    path = str(tmp_path / "m.csv")
    h = logging_util.CSVHandler(path)
    h.write_record({"tag": "train", "step": 1, "loss": 1.0})
    h.write_record({"tag": "train", "step": 2, "loss": 0.9, "mfu": 0.41})
    h.write_record({"tag": "eval", "step": 2, "eval_loss": 2.0})
    h.close()
    import csv

    rows = list(csv.DictReader(open(path)))
    assert set(rows[0].keys()) == {"tag", "step", "loss", "mfu", "eval_loss"}
    assert rows[0]["loss"] == "1.0" and rows[0]["mfu"] == ""  # blank-filled
    assert rows[1]["mfu"] == "0.41"
    assert rows[2]["eval_loss"] == "2.0" and rows[2]["loss"] == ""


def test_csv_handler_append_resume_keeps_prior_header(tmp_path):
    """A resumed (append-mode) session must treat the FILE's header as the
    base column set: widening may not demote the old header to a data row
    or zip prior rows against the wrong columns."""
    path = str(tmp_path / "m.csv")
    h = logging_util.CSVHandler(path)
    h.write_record({"tag": "train", "step": 1, "loss": 1.0})
    h.close()

    h2 = logging_util.CSVHandler(path)  # restart: different first record
    h2.write_record({"tag": "train", "step": 2, "loss": 0.8, "mfu": 0.3})
    h2.close()
    import csv

    rows = list(csv.DictReader(open(path)))
    assert set(rows[0].keys()) == {"tag", "step", "loss", "mfu"}
    assert [r["step"] for r in rows] == ["1", "2"]  # no header-as-data row
    assert rows[0]["loss"] == "1.0" and rows[0]["mfu"] == ""
    assert rows[1]["mfu"] == "0.3"


def test_is_primary_separate_from_verbose(tmp_path, capsys):
    """A quiet (verbose=False) rank-0 run still writes its file artifacts;
    a non-primary rank writes none even when verbose."""
    quiet_path = str(tmp_path / "quiet.txt")
    h = logging_util.FileHandler(quiet_path, verbose=False, is_primary=True)
    h.write_message("kept")
    h.close()
    assert open(quiet_path).read().strip() == "kept"

    nonprimary_path = str(tmp_path / "nonprimary.txt")
    h = logging_util.FileHandler(nonprimary_path, verbose=True,
                                 is_primary=False)
    h.write_message("dropped")
    h.close()
    assert not os.path.exists(nonprimary_path)

    stream = logging_util.StreamHandler(verbose=False, is_primary=True)
    stream.write_message("silent")
    assert capsys.readouterr().out == ""

    # Backward compatibility: is_primary defaults to verbose.
    legacy = logging_util.FileHandler(str(tmp_path / "legacy.txt"),
                                      verbose=False)
    assert legacy._f is None


def test_logger_init_closes_replaced_handlers(tmp_path):
    lg = logging_util.Logger()
    f = logging_util.FileHandler(str(tmp_path / "a.txt"))
    lg.init([f])
    assert f._f is not None
    lg.init([logging_util.StreamHandler(verbose=False)])
    assert f._f is None  # closed by re-init, not leaked
    lg.close()


def test_tensorboard_handler_skips_stepless_records(recwarn):
    h = logging_util.TensorBoardHandler.__new__(logging_util.TensorBoardHandler)
    logging_util.Handler.__init__(h, verbose=True, is_primary=True)
    h._warned_stepless = False

    class FakeWriter:
        def __init__(self):
            self.scalars = []

        def add_scalar(self, tag, value, step):
            self.scalars.append((tag, value, step))

        def flush(self):
            pass

    h._writer = FakeWriter()
    h.write_record({"tag": "train", "loss": 1.0})  # stepless: skipped
    assert h._writer.scalars == []
    assert any("without 'step'" in str(w.message) for w in recwarn.list)
    h.write_record({"tag": "train", "step": 7, "loss": 1.0})
    assert h._writer.scalars == [("train/loss", 1.0, 7)]


# -- step timer ---------------------------------------------------------


def test_step_timer_decomposition_fake_clock():
    clock = FakeClock()
    timer = StepTimer(window=3, sync_every=1, clock=clock)
    for _ in range(2):
        for _ in range(3):
            timer.data_start()
            clock.advance(0.10)  # data wait
            timer.data_end()
            clock.advance(0.02)  # host dispatch
            timer.dispatch_end()
            assert timer.should_sync()
            clock.advance(0.30)  # device tail
            timer._t_device1 = clock()  # what device_sync records
            record = timer.step_done(step=timer._step_index + 1)
        assert record is not None, "window must close every 3rd step"
        assert record["window_steps"] == 3
        assert record["synced_steps"] == 3
        assert record["data_wait_p50_s"] == pytest.approx(0.10)
        assert record["host_p50_s"] == pytest.approx(0.02)
        assert record["device_p50_s"] == pytest.approx(0.30)
        # Monotonicity: the step total equals the component sum (each
        # component is a difference of successive clock reads).
        assert record["step_p50_s"] == pytest.approx(0.42)
        assert record["step_max_s"] >= record["step_p50_s"]
        assert record["steps_per_sec"] == pytest.approx(1 / 0.42, rel=1e-3)


def test_step_timer_unsynced_steps_have_no_device_sample():
    clock = FakeClock()
    timer = StepTimer(window=4, sync_every=2, clock=clock)
    for _ in range(4):
        timer.data_start()
        clock.advance(0.01)
        timer.data_end()
        clock.advance(0.01)
        timer.dispatch_end()
        if timer.should_sync():
            clock.advance(0.5)
            timer._t_device1 = clock()
        record = timer.step_done(step=timer._step_index + 1)
    assert record["window_steps"] == 4
    assert record["synced_steps"] == 2  # steps 0 and 2 per the cadence
    # Sampled cadence: each device sample is a multi-step backlog, so MFU
    # must fall back to the wall basis instead of deflating by the cadence.
    timer2 = StepTimer(window=2, sync_every=2, clock=clock, seq_per_step=8,
                       flops_per_seq=1e12, device_kind="TPU v4")
    for _ in range(2):
        timer2.data_start()
        timer2.data_end()
        clock.advance(1.0)  # 1 s of wall per step, in the host segment
        timer2.dispatch_end()
        if timer2.should_sync():
            timer2._t_device1 = clock()
        record2 = timer2.step_done(step=timer2._step_index + 1)
    assert record2["mfu_basis"] == "wall"
    # 2 steps * 8 seq over 2 s wall on a 275 Tflop/s chip.
    assert record2["mfu"] == pytest.approx(8e12 / 275e12, rel=1e-3)


def test_step_timer_mfu_from_device_time():
    clock = FakeClock()
    # 8 seq per step, 1e12 flops/seq, 1 s device time per step on a chip
    # with 275 Tflop/s peak (v4): MFU = 8e12 / 275e12 per step.
    timer = StepTimer(window=2, sync_every=1, clock=clock, seq_per_step=8,
                      flops_per_seq=1e12, device_kind="TPU v4", n_devices=1)
    for _ in range(2):
        timer.data_start()
        timer.data_end()
        timer.dispatch_end()
        clock.advance(1.0)
        timer._t_device1 = clock()
        record = timer.step_done(step=timer._step_index + 1)
    assert record["mfu"] == pytest.approx(8e12 / 275e12, rel=1e-3)
    assert record["mfu_basis"] == "device"  # every step synced
    # CPU (unknown peak) reports 0.0, never a bogus number.
    cpu_timer = StepTimer(window=1, clock=clock, seq_per_step=8,
                          flops_per_seq=1e12, device_kind="cpu")
    cpu_timer.data_start()
    cpu_timer.data_end()
    cpu_timer.dispatch_end()
    clock.advance(1.0)
    cpu_timer._t_device1 = clock()
    assert cpu_timer.step_done(1)["mfu"] == 0.0


def test_step_timer_flush_partial_window():
    clock = FakeClock()
    timer = StepTimer(window=100, clock=clock)
    timer.data_start()
    clock.advance(0.1)
    timer.data_end()
    timer.dispatch_end()
    assert timer.step_done(1) is None  # window not full
    record = timer.flush(1)
    assert record is not None and record["window_steps"] == 1
    assert timer.flush(1) is None  # nothing left


# -- sentinels + heartbeat ----------------------------------------------


def test_sentinel_abort_after_k_consecutive():
    emitted = []
    s = FailureSentinel(policy="abort", patience=3, emit=emitted.append)
    assert s.observe(1, finite=1.0)
    assert not s.observe(2, finite=0.0, loss=float("nan"))
    assert s.observe(3, finite=1.0)  # recovery resets the streak
    s.observe(4, finite=0.0)
    s.observe(5, finite=0.0)
    with pytest.raises(NonFiniteError):
        s.observe(6, finite=0.0)
    assert s.total_nonfinite == 4
    assert [r["consecutive_nonfinite"] for r in emitted] == [1, 1, 2, 3]
    assert all(r["kind"] == "sentinel" for r in emitted)


def test_sentinel_continue_never_raises():
    emitted = []
    s = FailureSentinel(policy="continue", patience=1, emit=emitted.append)
    for step in range(5):
        s.observe(step, finite=0.0)
    assert len(emitted) == 5


def test_sentinel_rejects_unknown_policy():
    with pytest.raises(ValueError):
        FailureSentinel(policy="explode")


def test_heartbeat_advances_and_resumes(tmp_path):
    path = str(tmp_path / "hb.json")
    hb = Heartbeat(path)
    hb.beat(1, last_loss=2.5)
    first = Heartbeat.read(path)
    hb.beat(2)  # no loss this beat: last known loss is retained
    second = Heartbeat.read(path)
    assert (first["counter"], second["counter"]) == (1, 2)
    assert second["step"] == 2 and second["last_loss"] == 2.5
    assert second["wallclock"] >= first["wallclock"]

    # A restarted run resumes the monotonic counter from the file.
    hb2 = Heartbeat(path)
    hb2.beat(3)
    assert Heartbeat.read(path)["counter"] == 3

    assert Heartbeat.read(str(tmp_path / "absent.json")) is None
    assert Heartbeat(None).path is None  # disabled: beat() is a no-op
    Heartbeat(None).beat(1)
    # Non-primary ranks never write.
    assert Heartbeat(str(tmp_path / "np.json"), is_primary=False).path is None


# -- profiler spec ------------------------------------------------------


def test_parse_profile_spec():
    assert parse_profile_spec(None) is None
    assert parse_profile_spec("") is None
    assert parse_profile_spec("0") is None
    assert parse_profile_spec(0) is None
    assert parse_profile_spec("5") == (2, 7)  # legacy steady-state window
    assert parse_profile_spec(5) == (2, 7)
    assert parse_profile_spec("3:10") == (3, 10)
    for bad in ("0:5", "7:3", "4:4"):
        with pytest.raises(ValueError):
            parse_profile_spec(bad)


# -- compile events -----------------------------------------------------


@pytest.fixture()
def persistent_cache(tmp_path):
    import jax
    from jax._src import compilation_cache as cc

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # jax latches cache-enablement on the first compile of the process
    # (_cache_used); any earlier test that compiled with no cache dir would
    # leave the persistent cache permanently off without this reset.
    cc.reset_cache()
    try:
        yield
    finally:
        cc.reset_cache()
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min)


def test_compile_event_on_forced_cache_miss(persistent_cache):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.telemetry.compile_events import CompileMonitor

    emitted = []
    monitor = CompileMonitor(emit=emitted.append)
    # A fresh (never-jitted) program against an empty persistent cache:
    # a real XLA compile plus a cache miss must be attributed to the call.
    fn = monitor.instrument(jax.jit(lambda x: x * 3.5 + x ** 2), "probe")
    out = fn(jnp.arange(7, dtype=jnp.float32))
    assert out.shape == (7,)
    assert len(emitted) == 1
    rec = emitted[0]
    assert rec["kind"] == "compile" and rec["fn"] == "probe"
    assert rec["cache"] == "miss"
    assert rec["compile_s"] > 0
    assert rec["backend_compile_s"] > 0
    assert len(rec["shapes_digest"]) == 12
    assert tschema.validate_record(
        {"schema": tschema.SCHEMA_VERSION, "ts": 0.0, **rec}) == []

    # Same shapes again: the in-process executable serves it — no event.
    fn(jnp.arange(7, dtype=jnp.float32))
    assert len(emitted) == 1

    # New shapes: new digest, new event.
    fn(jnp.arange(9, dtype=jnp.float32))
    assert len(emitted) == 2
    assert emitted[1]["shapes_digest"] != emitted[0]["shapes_digest"]


def test_compile_event_cache_hit(persistent_cache):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.telemetry.compile_events import CompileMonitor

    emitted = []
    monitor = CompileMonitor(emit=emitted.append)

    # Two DISTINCT function objects with identical programs: the second
    # can't reuse the in-process executable (different jit cache key) but
    # lowers to the same HLO, so it hits the persistent cache instead of
    # compiling — the warm-start path the runners rely on. Lambdas, not
    # defs: the cache key covers the HLO module, whose name comes from the
    # Python function name, and both lambdas lower as "jit__lambda_".
    monitor.instrument(
        jax.jit(lambda x: jnp.sin(x) * 2.0 + jnp.cos(x)), "cold")(
            jnp.ones((5,)))
    monitor.instrument(
        jax.jit(lambda x: jnp.sin(x) * 2.0 + jnp.cos(x)), "warm")(
            jnp.ones((5,)))
    assert [r["fn"] for r in emitted] == ["cold", "warm"]
    assert emitted[0]["cache"] == "miss"
    # The hit call may still compile tiny auxiliary modules (constant
    # conversions), so backend_compile_s isn't asserted to be zero — the
    # cache counters, not the durations, carry the warm/cold verdict.
    assert emitted[1]["cache"] == "hit"


def test_shapes_digest_stability():
    import jax.numpy as jnp

    from bert_pytorch_tpu.telemetry.compile_events import shapes_digest

    a = shapes_digest(((jnp.ones((2, 3)),), {"n": 4}))
    b = shapes_digest(((jnp.zeros((2, 3)),), {"n": 4}))  # values don't matter
    c = shapes_digest(((jnp.ones((2, 4)),), {"n": 4}))  # shapes do
    d = shapes_digest(((jnp.ones((2, 3)),), {"n": 5}))  # static args do
    assert a == b
    assert a != c and a != d


# -- TrainTelemetry facade ----------------------------------------------


def test_train_telemetry_loop_protocol(tmp_path):
    import jax.numpy as jnp

    path = str(tmp_path / "tele.jsonl")
    clock = FakeClock()
    tele = telemetry.TrainTelemetry(
        jsonl_path=path, window=2, clock=clock,
        heartbeat_path=str(tmp_path / "hb.json"), sentinel_policy="continue")
    batches = iter([jnp.ones((2,)), jnp.ones((2,)), jnp.ones((2,))])
    step = 0
    for batch in tele.timed(batches):
        step += 1
        clock.advance(0.01)
        tele.dispatch_done()
        loss = jnp.asarray(1.0 if step < 3 else float("nan"))
        tele.step_done(step, {"loss": loss})
    tele.finish(step, summary={"note": "done"})
    tele.close()

    kinds = {}
    for line in open(path):
        rec = json.loads(line)
        kinds.setdefault(rec["kind"], []).append(rec)
    assert len(kinds["step_window"]) == 2  # one full window + the flush
    assert kinds["step_window"][0]["window_steps"] == 2
    # Step 3's NaN loss trips the host-side fallback sentinel.
    assert kinds["sentinel"][0]["step"] == 3
    assert kinds["run_summary"][0]["note"] == "done"
    hb = Heartbeat.read(str(tmp_path / "hb.json"))
    assert hb["step"] == 3 and hb["counter"] == 4  # 3 steps + finish
    assert tschema.validate_file(path) == []


# -- acceptance: CPU smoke pretraining run ------------------------------


@pytest.fixture()
def pretrain_workdir(tmp_path):
    from bert_pytorch_tpu.tools.make_synthetic_data import make_shard

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    for i in range(2):
        make_shard(str(data_dir / f"shard_{i}.hdf5"), 64, 32, 1000, seed=i)
    model_config = {
        "vocab_size": 1000, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 32, "type_vocab_size": 2,
        "next_sentence": True, "mask_token_id": 4,
    }
    config_path = tmp_path / "model.json"
    config_path.write_text(json.dumps(model_config))
    return {"data": str(data_dir), "out": str(tmp_path / "out"),
            "model": str(config_path)}


@pytest.mark.slow
def test_pretraining_smoke_emits_telemetry(pretrain_workdir):
    """ISSUE 1 acceptance: >=20 synthetic CPU steps must leave a JSONL
    stream holding the per-window step-time decomposition, MFU, a compile
    event with cache status, and a heartbeat file that advanced.

    Slow-gated (ISSUE 14 budget fix; ~47-100s on the throttled box: a
    full runner compile+run): the key invariant — the telemetry facade
    leaves a SCHEMA-CLEAN artifact with step_window/sentinel/
    run_summary records and an advancing heartbeat — is carried tier-1
    by the cheap in-process ``test_train_telemetry_loop_protocol``
    above (fake clock, no jit); this E2E additionally proves
    run_pretraining.py plumbs it and runs under ``-m slow``."""
    import run_pretraining

    args = run_pretraining.parse_arguments([
        "--input_dir", pretrain_workdir["data"],
        "--output_dir", pretrain_workdir["out"],
        "--model_config_file", pretrain_workdir["model"],
        "--global_batch_size", "16", "--local_batch_size", "2",
        "--max_steps", "22", "--steps", "22",
        "--learning_rate", "1e-3", "--warmup_proportion", "0.25",
        "--num_steps_per_checkpoint", "100", "--dtype", "float32",
        "--seed", "7", "--telemetry_window", "10",
        "--telemetry_sync_every", "1",
    ])
    result = run_pretraining.main(args)
    assert result["global_step"] == 22

    jsonl = os.path.join(pretrain_workdir["out"],
                         "pretraining_telemetry.jsonl")
    assert tschema.validate_file(jsonl) == []
    kinds = {}
    for line in open(jsonl):
        rec = json.loads(line)
        kinds.setdefault(rec.get("kind", "metric"), []).append(rec)

    windows = kinds["step_window"]
    assert len(windows) >= 2  # 22 steps / window 10
    for w in windows:
        for key in ("data_wait_p50_s", "data_wait_p95_s", "data_wait_max_s",
                    "host_p50_s", "host_p95_s", "host_max_s",
                    "device_p50_s", "device_p95_s", "device_max_s",
                    "step_p50_s", "steps_per_sec", "mfu"):
            assert key in w, f"window record missing {key}"
        assert w["synced_steps"] == w["window_steps"]  # --telemetry_sync_every 1
    assert windows[0]["mfu"] == 0.0  # CPU: unknown peak, never bogus
    # The device-prefetch loader feeds its queue gauges into the windows.
    assert any("loader" in w for w in windows)

    compiles = kinds["compile"]
    assert any(r["fn"] == "train_step" for r in compiles)
    assert all(r["cache"] in ("hit", "miss", "uncached", "jit")
               for r in compiles)
    # The step-0 compile dominates; it must be visible, not folded into
    # step time.
    assert max(r["compile_s"] for r in compiles) > 0

    # ISSUE 2: in-jit grad-health on the sync cadence (1 here, so every
    # step), with per-layer-group norms and the stacked-encoder
    # per-layer vector.
    health = kinds["grad_health"]
    assert len(health) >= 20
    for rec in health[:3]:
        assert rec["grad_norm"] > 0 and rec["param_norm"] > 0
        assert 0 < rec["update_ratio"] < 1
        assert "bert/encoder" in rec["groups"]
        assert "bert/embeddings" in rec["groups"]
        for vals in rec["groups"].values():
            assert set(vals) == {"grad_norm", "param_norm", "update_ratio"}
        assert len(rec["per_layer_grad_norm"]) == 2  # num_hidden_layers
    # The in-jit global grad norm must agree with the step's own metric.
    train_recs = [r for r in kinds["metric"] if r.get("tag") == "train"]
    by_step = {r["step"]: r for r in train_recs}
    probe = health[5]
    assert probe["grad_norm"] == pytest.approx(
        by_step[probe["step"]]["grad_norm"], rel=1e-4)

    # ISSUE 2: memory observability on CPU = exactly ONE unsupported
    # note (never a per-step storm), and one-shot static cost
    # attribution joined to the compile event's digest.
    mem = kinds["memory"]
    assert len(mem) == 1 and mem[0]["memory_supported"] is False
    costs = kinds["compile_cost"]
    assert any(r["fn"] == "train_step" for r in costs)
    cost = next(r for r in costs if r["fn"] == "train_step")
    assert cost["shapes_digest"] in {c["shapes_digest"] for c in compiles}
    assert cost["flops"] > 0
    assert cost["analysis"] == "compiled"  # CPU: the extra compile is cheap
    assert cost["temp_bytes"] >= 0 and cost["argument_bytes"] > 0
    # No divergence warnings on a healthy run.
    assert "divergence" not in kinds

    hb = Heartbeat.read(
        os.path.join(pretrain_workdir["out"], "heartbeat.json"))
    assert hb is not None
    assert hb["step"] == 22
    assert hb["counter"] >= 22  # advanced across (at least) every step
    assert np.isfinite(hb["last_loss"])

    assert kinds["run_summary"][0]["steps"] == 22

    # The ordinary train records share the sink (tag/step/loss... records
    # with no "kind"): the artifact is single-file parseable.
    assert any(r.get("tag") == "train" for r in kinds["metric"])


@pytest.mark.slow
def test_pretraining_resume_keeps_grad_health_cadence(pretrain_workdir):
    """A checkpoint-resumed run whose resume step is NOT a multiple of
    the sampled sync cadence must still emit grad_health records: the
    in-jit due gate is rebased on the run-start optimizer count
    (stats_phase), matching the host's run-local sync index.

    Slow-gated (~36s: two full pretraining runs): the rebasing invariant
    itself is tier-1-covered at the step level by
    tests/test_model_stats.py (phase-offset due-gate cases); this E2E
    proves the runner plumbs the run-start count through and runs under
    ``-m slow``."""
    import run_pretraining

    def run(steps):
        args = run_pretraining.parse_arguments([
            "--input_dir", pretrain_workdir["data"],
            "--output_dir", pretrain_workdir["out"],
            "--model_config_file", pretrain_workdir["model"],
            "--global_batch_size", "16", "--local_batch_size", "2",
            "--max_steps", "20", "--steps", str(steps),
            "--num_steps_per_checkpoint", "100", "--dtype", "float32",
            "--seed", "7", "--telemetry_window", "5",
            "--telemetry_sync_every", "4",  # sampled cadence
        ])
        return run_pretraining.main(args)

    assert run(6)["global_step"] == 6   # final checkpoint at step 6
    assert run(6)["global_step"] == 12  # resumes; 6 % 4 != 0
    jsonl = os.path.join(pretrain_workdir["out"],
                         "pretraining_telemetry.jsonl")
    health = [json.loads(line) for line in open(jsonl)]
    health = [r for r in health if r.get("kind") == "grad_health"]
    first = [r for r in health if r["step"] <= 6]
    resumed = [r for r in health if r["step"] > 6]
    assert first, "fresh run emitted no grad_health"
    assert resumed, ("resumed run emitted no grad_health — the due gate "
                     "drifted off the run-local sync cadence")


@pytest.mark.slow
def test_pretraining_sentinel_abort_flag(pretrain_workdir):
    """--sentinel_policy abort is accepted and a healthy run completes.

    Slow-gated (~24s for a full compile+run that asserts only flag
    acceptance): the sentinel abort BEHAVIOR is tier-1-covered by the
    FailureSentinel unit tests above and the fault-tolerance in-process
    injection tests; runs under ``-m slow``."""
    import run_pretraining

    args = run_pretraining.parse_arguments([
        "--input_dir", pretrain_workdir["data"],
        "--output_dir", pretrain_workdir["out"],
        "--model_config_file", pretrain_workdir["model"],
        "--global_batch_size", "16", "--local_batch_size", "2",
        "--max_steps", "2", "--steps", "2",
        "--num_steps_per_checkpoint", "100", "--dtype", "float32",
        "--sentinel_policy", "abort", "--sentinel_patience", "1",
    ])
    result = run_pretraining.main(args)
    assert result["global_step"] == 2
