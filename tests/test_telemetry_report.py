"""Offline telemetry report + regression gate (ISSUE 2 acceptance,
docs/telemetry.md): summary aggregation over synthetic artifacts, the
baseline-diff verdict (including the injected +25% step-time regression
that must exit nonzero and NAME the regression), and the CLI surface."""

import json
import os
import subprocess
import sys

import pytest

from bert_pytorch_tpu.telemetry import report

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO_ROOT, "tools", "telemetry_report.py")


def _window(step, p50, p95=None, steps=10, sps=None, mfu=0.4):
    rec = {"schema": 1, "ts": 0.0, "kind": "step_window", "tag": "telemetry",
           "step": step, "window_steps": steps, "synced_steps": steps,
           "steps_per_sec": sps if sps is not None else round(1.0 / p50, 4),
           "mfu": mfu, "mfu_basis": "device"}
    for prefix in ("data_wait", "host", "device", "step"):
        base = p50 if prefix == "step" else p50 / 10
        rec[f"{prefix}_p50_s"] = base
        rec[f"{prefix}_p95_s"] = p95 if (p95 and prefix == "step") \
            else base * 1.5
        rec[f"{prefix}_max_s"] = base * 2
    return rec


def _artifact(path, p50=0.1, mfu=0.4, peak=1000, grad_max=1.5,
              divergences=0, nonfinite=0):
    records = [
        _window(10, p50 * 1.2, p95=p50 * 30, mfu=mfu),  # cold: compile tail
        _window(20, p50, mfu=mfu),
        _window(30, p50, mfu=mfu),
        {"schema": 1, "ts": 0.0, "kind": "compile", "tag": "telemetry",
         "fn": "train_step", "shapes_digest": "abc123", "compile_s": 3.0,
         "backend_compile_s": 2.5, "cache": "miss"},
        {"schema": 1, "ts": 0.0, "kind": "memory", "tag": "telemetry",
         "step": 30, "memory_supported": True, "samples": 3, "n_devices": 1,
         "bytes_in_use": peak - 100, "bytes_in_use_max": peak - 50,
         "peak_bytes_in_use": peak, "bytes_limit": 4000},
        {"schema": 1, "ts": 0.0, "kind": "grad_health", "tag": "telemetry",
         "step": 30, "grad_norm": grad_max, "param_norm": 10.0,
         "update_ratio": 0.002, "groups": {}},
        {"schema": 1, "ts": 0.0, "kind": "run_summary", "tag": "telemetry",
         "step": 30, "steps": 30, "training_seq_per_sec": round(8 / p50, 2),
         "mfu": mfu},
    ]
    for i in range(divergences):
        records.append({"schema": 1, "ts": 0.0, "kind": "divergence",
                        "tag": "telemetry", "step": 25 + i,
                        "reason": "grad_norm_spike", "value": 99.0,
                        "threshold": 9.0, "policy": "continue"})
    for i in range(nonfinite):
        records.append({"schema": 1, "ts": 0.0, "kind": "sentinel",
                        "tag": "telemetry", "step": 28 + i, "finite": 0,
                        "loss": None, "consecutive_nonfinite": i + 1,
                        "policy": "continue"})
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def test_summarize_aggregates(tmp_path):
    summary = report.summarize_file(_artifact(tmp_path / "a.jsonl", p50=0.1))
    assert summary["steps"] == 30
    assert summary["windows"] == 3
    # weighted median over window p50s: two steady windows dominate
    assert summary["step_p50_s"] == pytest.approx(0.1)
    # p95 excludes the first (compile-tail) window
    assert summary["step_p95_s"] == pytest.approx(0.15)
    assert summary["mfu"] == pytest.approx(0.4)
    assert summary["compiles"] == 1 and summary["cold_start"] is True
    assert summary["peak_bytes_in_use"] == 1000
    assert summary["grad_norm_max"] == pytest.approx(1.5)
    assert summary["training_seq_per_sec"] == pytest.approx(80.0)
    assert summary["nonfinite_steps"] == 0
    assert summary["divergence_warnings"] == 0


def test_summarize_mfu_excludes_cold_window(tmp_path):
    """Like p95, the MFU aggregate must skip the first window: a cold
    run's step-0 compile halves that window's wall-basis MFU and would
    read as a regression against a warm baseline."""
    path = tmp_path / "cold.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps(_window(10, 0.2, mfu=0.2)) + "\n")  # cold
        f.write(json.dumps(_window(20, 0.1, mfu=0.4)) + "\n")
        f.write(json.dumps(_window(30, 0.1, mfu=0.4)) + "\n")
    summary = report.summarize_file(str(path))
    assert summary["mfu"] == pytest.approx(0.4)


def test_last_run_trims_append_mode_artifact(tmp_path):
    """Append-mode artifacts accumulate runs (capture legs, retries);
    --last-run must score only the segment after the penultimate
    run_summary, so one leg's windows can't poison another's verdict."""
    def _summary(metric):
        return {"schema": 1, "ts": 0.0, "kind": "run_summary",
                "tag": "telemetry", "step": 30, "steps": 30,
                "metric": metric}

    path = tmp_path / "accumulated.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps(_window(10, 0.1)) + "\n")   # fast leg
        f.write(json.dumps(_summary("phase1")) + "\n")
        f.write(json.dumps(_window(10, 0.5)) + "\n")   # slow leg
        f.write(json.dumps(_summary("seq2048")) + "\n")
    last = report.summarize_file(str(path), last_run=True)
    assert last["metric"] == "seq2048"
    assert last["step_p50_s"] == pytest.approx(0.5)
    blended = report.summarize_file(str(path))
    assert blended["step_p50_s"] != pytest.approx(0.5)  # why --last-run exists
    # fewer than two run_summary records: nothing to trim
    single = _artifact(tmp_path / "single.jsonl", p50=0.1)
    assert report.summarize_file(single, last_run=True)["steps"] == 30


def test_compare_clean_runs_pass(tmp_path):
    base = report.summarize_file(_artifact(tmp_path / "b.jsonl", p50=0.1))
    new = report.summarize_file(_artifact(tmp_path / "n.jsonl", p50=0.104))
    regressions, checks = report.compare(base, new)
    assert regressions == []
    assert any(c["verdict"] == "ok" for c in checks)


def test_compare_catches_each_axis(tmp_path):
    base = report.summarize_file(_artifact(tmp_path / "b.jsonl"))
    cases = {
        "step_p50_s": dict(p50=0.125),            # +25% step time
        "mfu": dict(mfu=0.3),                     # -25% MFU
        "peak_bytes_in_use": dict(peak=1200),     # +20% peak memory
        "grad_norm_max": dict(grad_max=4.0),      # >2x grad envelope
        "divergence_warnings": dict(divergences=2),
        "nonfinite_steps": dict(nonfinite=1),
    }
    for metric, kwargs in cases.items():
        new = report.summarize_file(
            _artifact(tmp_path / f"{metric}.jsonl", **kwargs))
        regressions, _ = report.compare(base, new)
        assert metric in [r["metric"] for r in regressions], metric


def test_cli_summary_and_missing_file(tmp_path, capsys):
    path = _artifact(tmp_path / "a.jsonl")
    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "steps_per_sec" in out
    assert report.main([str(tmp_path / "absent.jsonl")]) == 2


def test_cli_injected_step_time_regression_exits_nonzero(tmp_path):
    """The ISSUE 2 acceptance shape: a +25% step-time copy of the same
    run must exit nonzero with the regression NAMED, via the repo-root
    tool in a fresh process (no jax import needed)."""
    base = _artifact(tmp_path / "base.jsonl", p50=0.1)
    slow = _artifact(tmp_path / "slow.jsonl", p50=0.125)
    proc = subprocess.run(
        [sys.executable, TOOL, slow, base],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "REGRESSION" in proc.stdout
    assert "step-time p50" in proc.stdout
    # same artifact against itself: clean exit
    proc = subprocess.run(
        [sys.executable, TOOL, base, base],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_verdict(tmp_path, capsys):
    base = _artifact(tmp_path / "base.jsonl", p50=0.1)
    slow = _artifact(tmp_path / "slow.jsonl", p50=0.2)
    assert report.main([slow, base, "--json"]) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["verdict"] == "regression"
    assert "step_p50_s" in [r["metric"] for r in verdict["regressions"]]


def test_cli_tolerance_knobs(tmp_path):
    base = _artifact(tmp_path / "base.jsonl", p50=0.1)
    mild = _artifact(tmp_path / "mild.jsonl", p50=0.115)  # +15%
    assert report.main([mild, base]) == 1                 # default 10%
    assert report.main([mild, base, "--step-tol", "0.2"]) == 0


def test_bench_attach_regression_gate(tmp_path, monkeypatch):
    """bench.py's parent attaches the report verdict to its result JSON
    when a committed baseline exists — and never fails the bench."""
    import bench

    base = _artifact(tmp_path / "base.jsonl", p50=0.1)
    slow = _artifact(tmp_path / "slow.jsonl", p50=0.2)
    monkeypatch.setattr(bench, "TELEMETRY_JSONL", slow)
    monkeypatch.setattr(bench, "TELEMETRY_BASELINE", base)
    result = bench._attach_regression({"metric": "m", "value": 1.0})
    assert result["regression"]["verdict"] == "regression"
    assert "step_p50_s" in [
        r["metric"] for r in result["regression"]["regressions"]]
    assert result["regression"]["baseline"] == "base.jsonl"
    # clean pair: verdict ok, still attached for the artifact trail
    monkeypatch.setattr(
        bench, "TELEMETRY_JSONL", _artifact(tmp_path / "same.jsonl", p50=0.1))
    assert bench._attach_regression({})["regression"]["verdict"] == "ok"
    # no baseline on disk: result passes through untouched
    monkeypatch.setattr(
        bench, "TELEMETRY_BASELINE", str(tmp_path / "absent.jsonl"))
    assert "regression" not in bench._attach_regression({"metric": "m"})


def test_bench_gate_refuses_mismatched_configs(tmp_path, monkeypatch):
    """Different bench legs (phase2, seq2048, degraded) share the default
    baseline path; the gate must refuse to diff incomparable configs
    instead of flagging a bogus regression."""
    import bench

    def _stamped(path, metric, p50):
        art = _artifact(tmp_path / path, p50=p50)
        with open(art, "a") as f:
            f.write(json.dumps({
                "schema": 1, "ts": 0.0, "kind": "run_summary", "tag":
                "telemetry", "step": 30, "steps": 30, "metric": metric,
            }) + "\n")
        return art

    base = _stamped("base.jsonl", "bert_large_phase1_seq_per_sec", 0.1)
    other = _stamped("other.jsonl", "bert_large_phase2_seq_per_sec", 0.5)
    monkeypatch.setattr(bench, "TELEMETRY_JSONL", other)
    monkeypatch.setattr(bench, "TELEMETRY_BASELINE", base)
    verdict = bench._attach_regression({})["regression"]
    assert verdict["verdict"] == "n/a"
    assert "not comparable" in verdict["note"]
