"""Tokenizer tests: C++ core vs the pure-Python spec vs HF Rust tokenizers.

The pure-Python BasicTokenizer/WordpieceTokenizer
(bert_pytorch_tpu/data/tokenization.py, parity with reference
src/tokenization.py:60-229) is the behavioral specification; the C++ core
and the HF fast tokenizer must both agree with it (SQuAD answer alignment
depends on it, SURVEY.md §7 'tokenizer bit-parity').
"""

import os

import pytest

from bert_pytorch_tpu.data.tokenization import (
    BasicTokenizer,
    BertTokenizer,
    WordpieceTokenizer,
    load_vocab,
)

VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##s", "##ed", "##ing",
    "over", "lazy", "dog", "un", "##believ", "##able", "hello", "world",
    "cafe", "resume", "2023", "!", ",", ".", "'", "don", "t", "中", "文",
    "dvorak", "eric", "##son",
]

SENTENCES = [
    "The quick brown fox jumps over the lazy dog.",
    "Hello, world!",
    "unbelievable",
    "Café résumé 2023",          # accents fold away when lowercasing
    "Dvořák Ēricson Łódź",       # Latin Extended-A folds (ř/Ē/ź; ł kept)
    "don't",
    "hello 中文 world",           # CJK isolation
    "  weird\tspacing\n here ",
    "UNKNOWNWORDXYZ",
]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("tok") / "vocab.txt"
    path.write_text("\n".join(VOCAB) + "\n")
    return str(path)


@pytest.fixture(scope="module")
def cpp_tok(vocab_file):
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    return CppWordPieceTokenizer(vocab_file, lowercase=True)


@pytest.fixture(scope="module")
def py_tok(vocab_file):
    return BertTokenizer(vocab_file, do_lower_case=True)


def test_basic_tokenizer_spec():
    bt = BasicTokenizer(do_lower_case=True)
    assert bt.tokenize("Hello, World!") == ["hello", ",", "world", "!"]
    assert bt.tokenize("Café") == ["cafe"]
    assert bt.tokenize("中文ab") == ["中", "文", "ab"]
    assert bt.tokenize(" don't ") == ["don", "'", "t"]


def test_wordpiece_greedy_longest_match(vocab_file):
    wp = WordpieceTokenizer(load_vocab(vocab_file))
    assert wp.tokenize("unbelievable") == ["un", "##believ", "##able"]
    assert wp.tokenize("jumps") == ["jump", "##s"]
    assert wp.tokenize("zzzqqq") == ["[UNK]"]


def test_cpp_matches_python_spec(cpp_tok, py_tok):
    for sentence in SENTENCES:
        py_tokens = py_tok.tokenize(sentence)
        py_ids = py_tok.convert_tokens_to_ids(py_tokens)
        enc = cpp_tok.encode(sentence)
        assert enc.tokens == py_tokens, sentence
        assert enc.ids == py_ids, sentence


def test_cpp_matches_hf_fast(vocab_file, cpp_tok):
    tokenizers = pytest.importorskip("tokenizers")
    hf = tokenizers.BertWordPieceTokenizer(
        vocab_file, lowercase=True, strip_accents=True,
        handle_chinese_chars=True, clean_text=True)
    for sentence in SENTENCES:
        hf_enc = hf.encode(sentence, add_special_tokens=False)
        enc = cpp_tok.encode(sentence)
        assert enc.tokens == hf_enc.tokens, sentence
        assert enc.ids == hf_enc.ids, sentence


def test_cpp_special_token_api(cpp_tok):
    assert cpp_tok.token_to_id("[MASK]") == 4
    assert cpp_tok.id_to_token(4) == "[MASK]"
    assert cpp_tok.token_to_id("notavocabword") is None
    enc = cpp_tok.encode("hello world", add_special_tokens=True)
    assert enc.tokens[0] == "[CLS]" and enc.tokens[-1] == "[SEP]"


def test_cpp_uppercase_mode(vocab_file, tmp_path):
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    cased_vocab = tmp_path / "cased.txt"
    cased_vocab.write_text("\n".join(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "Hello", "hello"]) + "\n")
    tok = CppWordPieceTokenizer(str(cased_vocab), lowercase=False)
    assert tok.encode("Hello").tokens == ["Hello"]
    assert tok.encode("hello").tokens == ["hello"]


def test_vocab_trainer_roundtrip(tmp_path):
    from bert_pytorch_tpu.tools.tokenizer_cpp import (
        CppWordPieceTokenizer,
        train_wordpiece_vocab,
    )

    corpus = tmp_path / "corpus.txt"
    corpus.write_text(
        "the cat sat on the mat\n" * 50
        + "the cats sat on the mats\n" * 30
        + "a dog ran in the park\n" * 40
    )
    out = str(tmp_path / "trained_vocab.txt")
    train_wordpiece_vocab([str(corpus)], vocab_size=60, out_path=out)
    lines = [l for l in open(out).read().splitlines() if l]
    # specials first, [PAD] at 0 (reference utils/build_vocab.py:64-75)
    assert lines[0] == "[PAD]"
    assert lines[1:5] == ["[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    tok = CppWordPieceTokenizer(out, lowercase=True)
    enc = tok.encode("the cat sat")
    # frequent words must be single tokens after merging
    assert "the" in enc.tokens and "cat" in enc.tokens
    assert tok.token_to_id("[UNK]") == 1


def test_get_wordpiece_tokenizer_prefers_cpp(vocab_file):
    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    tok = get_wordpiece_tokenizer(vocab_file)
    assert isinstance(tok, CppWordPieceTokenizer)
