"""Tokenizer tests: C++ core vs the pure-Python spec vs HF Rust tokenizers.

The pure-Python BasicTokenizer/WordpieceTokenizer
(bert_pytorch_tpu/data/tokenization.py, parity with reference
src/tokenization.py:60-229) is the behavioral specification; the C++ core
and the HF fast tokenizer must both agree with it (SQuAD answer alignment
depends on it, SURVEY.md §7 'tokenizer bit-parity').
"""

import os

import pytest

from bert_pytorch_tpu.data.tokenization import (
    BasicTokenizer,
    BertTokenizer,
    WordpieceTokenizer,
    load_vocab,
)

VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##s", "##ed", "##ing",
    "over", "lazy", "dog", "un", "##believ", "##able", "hello", "world",
    "cafe", "resume", "2023", "!", ",", ".", "'", "don", "t", "中", "文",
    "dvorak", "eric", "##son",
]

SENTENCES = [
    "The quick brown fox jumps over the lazy dog.",
    "Hello, world!",
    "unbelievable",
    "Café résumé 2023",          # accents fold away when lowercasing
    "Dvořák Ēricson Łódź",       # Latin Extended-A folds (ř/Ē/ź; ł kept)
    "don't",
    "hello 中文 world",           # CJK isolation
    "  weird\tspacing\n here ",
    "UNKNOWNWORDXYZ",
]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("tok") / "vocab.txt"
    path.write_text("\n".join(VOCAB) + "\n")
    return str(path)


@pytest.fixture(scope="module")
def cpp_tok(vocab_file):
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    return CppWordPieceTokenizer(vocab_file, lowercase=True)


@pytest.fixture(scope="module")
def py_tok(vocab_file):
    return BertTokenizer(vocab_file, do_lower_case=True)


def test_basic_tokenizer_spec():
    bt = BasicTokenizer(do_lower_case=True)
    assert bt.tokenize("Hello, World!") == ["hello", ",", "world", "!"]
    assert bt.tokenize("Café") == ["cafe"]
    assert bt.tokenize("中文ab") == ["中", "文", "ab"]
    assert bt.tokenize(" don't ") == ["don", "'", "t"]


def test_wordpiece_greedy_longest_match(vocab_file):
    wp = WordpieceTokenizer(load_vocab(vocab_file))
    assert wp.tokenize("unbelievable") == ["un", "##believ", "##able"]
    assert wp.tokenize("jumps") == ["jump", "##s"]
    assert wp.tokenize("zzzqqq") == ["[UNK]"]


def test_cpp_matches_python_spec(cpp_tok, py_tok):
    for sentence in SENTENCES:
        py_tokens = py_tok.tokenize(sentence)
        py_ids = py_tok.convert_tokens_to_ids(py_tokens)
        enc = cpp_tok.encode(sentence)
        assert enc.tokens == py_tokens, sentence
        assert enc.ids == py_ids, sentence


def test_cpp_matches_hf_fast(vocab_file, cpp_tok):
    tokenizers = pytest.importorskip("tokenizers")
    hf = tokenizers.BertWordPieceTokenizer(
        vocab_file, lowercase=True, strip_accents=True,
        handle_chinese_chars=True, clean_text=True)
    for sentence in SENTENCES:
        hf_enc = hf.encode(sentence, add_special_tokens=False)
        enc = cpp_tok.encode(sentence)
        assert enc.tokens == hf_enc.tokens, sentence
        assert enc.ids == hf_enc.ids, sentence


def test_cpp_special_token_api(cpp_tok):
    assert cpp_tok.token_to_id("[MASK]") == 4
    assert cpp_tok.id_to_token(4) == "[MASK]"
    assert cpp_tok.token_to_id("notavocabword") is None
    enc = cpp_tok.encode("hello world", add_special_tokens=True)
    assert enc.tokens[0] == "[CLS]" and enc.tokens[-1] == "[SEP]"


def test_cpp_uppercase_mode(vocab_file, tmp_path):
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    cased_vocab = tmp_path / "cased.txt"
    cased_vocab.write_text("\n".join(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "Hello", "hello"]) + "\n")
    tok = CppWordPieceTokenizer(str(cased_vocab), lowercase=False)
    assert tok.encode("Hello").tokens == ["Hello"]
    assert tok.encode("hello").tokens == ["hello"]


def test_vocab_trainer_roundtrip(tmp_path):
    from bert_pytorch_tpu.tools.tokenizer_cpp import (
        CppWordPieceTokenizer,
        train_wordpiece_vocab,
    )

    corpus = tmp_path / "corpus.txt"
    corpus.write_text(
        "the cat sat on the mat\n" * 50
        + "the cats sat on the mats\n" * 30
        + "a dog ran in the park\n" * 40
    )
    out = str(tmp_path / "trained_vocab.txt")
    train_wordpiece_vocab([str(corpus)], vocab_size=60, out_path=out)
    lines = [l for l in open(out).read().splitlines() if l]
    # specials first, [PAD] at 0 (reference utils/build_vocab.py:64-75)
    assert lines[0] == "[PAD]"
    assert lines[1:5] == ["[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    tok = CppWordPieceTokenizer(out, lowercase=True)
    enc = tok.encode("the cat sat")
    # frequent words must be single tokens after merging
    assert "the" in enc.tokens and "cat" in enc.tokens
    assert tok.token_to_id("[UNK]") == 1


def test_get_wordpiece_tokenizer_prefers_cpp(vocab_file):
    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    tok = get_wordpiece_tokenizer(vocab_file)
    assert isinstance(tok, CppWordPieceTokenizer)


# ---------------------------------------------------------------------------
# Byte-level BPE (C++ core vs HF ByteLevelBPETokenizer)
# ---------------------------------------------------------------------------

BPE_SENTENCES = [
    "Hello world",
    "hello world",
    "The quick brown fox jumps over 1234 lazy dogs!",
    "  leading and   multiple  spaces ",
    "don't stop, we'll go; they've said I'm he'd 're",
    "tabs\tand\nnewlines\n\nhere",
    "punctuation!!! (parens) [brackets] {braces} #hash @at",
    "numbers 007 3.14159 1,000,000",
    "unicode café naïve über straße",
    "mixed CJK 中文 text",
    "emoji \U0001f600 ok",
    "",
    "a",
    " ",
    "trailing space ",
]


@pytest.fixture(scope="module")
def bpe_files(tmp_path_factory):
    """Train a small byte-level BPE with HF (the oracle) on sample text."""
    tokenizers = pytest.importorskip("tokenizers")
    d = tmp_path_factory.mktemp("bpe")
    corpus = d / "corpus.txt"
    corpus.write_text("\n".join(BPE_SENTENCES * 8) + "\n")
    tok = tokenizers.ByteLevelBPETokenizer()
    tok.train([str(corpus)], vocab_size=400, min_frequency=1,
              special_tokens=["<s>", "<pad>", "</s>", "<unk>", "<mask>"])
    tok.save_model(str(d))
    return str(d / "vocab.json"), str(d / "merges.txt")


def test_cpp_bpe_matches_hf(bpe_files):
    """Bit parity of the C++ byte-level BPE against the HF Rust oracle:
    same pre-tokenization (GPT-2 regex incl. contractions and the
    whitespace-lookahead rule), same ranked merges, same ids."""
    tokenizers = pytest.importorskip("tokenizers")
    vocab_json, merges_txt = bpe_files
    hf = tokenizers.ByteLevelBPETokenizer(vocab_json, merges_txt)
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppByteLevelBPETokenizer

    cpp = CppByteLevelBPETokenizer(vocab_json, merges_txt)
    assert cpp.get_vocab_size() == hf.get_vocab_size()
    for sentence in BPE_SENTENCES:
        hf_enc = hf.encode(sentence)
        enc = cpp.encode(sentence)
        assert enc.tokens == hf_enc.tokens, repr(sentence)
        assert enc.ids == hf_enc.ids, repr(sentence)


def test_cpp_bpe_lowercase_mode(bpe_files):
    tokenizers = pytest.importorskip("tokenizers")
    vocab_json, merges_txt = bpe_files
    hf = tokenizers.ByteLevelBPETokenizer(vocab_json, merges_txt,
                                          lowercase=True)
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppByteLevelBPETokenizer

    cpp = CppByteLevelBPETokenizer(vocab_json, merges_txt, lowercase=True)
    for sentence in ["Hello World", "ALL CAPS 123", "MiXeD CaSe!"]:
        assert cpp.encode(sentence).ids == hf.encode(sentence).ids, sentence


def test_get_bpe_tokenizer_routes_to_cpp(bpe_files):
    from bert_pytorch_tpu.data.tokenization import get_bpe_tokenizer
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppByteLevelBPETokenizer

    tok = get_bpe_tokenizer(bpe_files[0], uppercase=True, backend="cpp")
    assert isinstance(tok, CppByteLevelBPETokenizer)
    assert tok.encode("hello world").ids


def test_cpp_bpe_hash_merges_and_scripts(tmp_path):
    """Review-hardened corner cases: merges whose left symbol begins with
    '#' (only the '#version' header is a comment), the katakana interpunct
    (punctuation inside the kana block, excluded from \\p{L}), and Latin
    Extended-A lowercase where the upper/lower pairing parity flips."""
    import json

    tokenizers = pytest.importorskip("tokenizers")
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppByteLevelBPETokenizer

    alphabet = [chr(c) for c in range(33, 127)] + ["Ġ"]
    vocab = {t: i for i, t in enumerate(alphabet)}
    vocab["##"] = len(vocab)
    vocab["Ġ#"] = len(vocab)
    vj, mt = str(tmp_path / "vocab.json"), str(tmp_path / "merges.txt")
    json.dump(vocab, open(vj, "w"))
    open(mt, "w").write("#version: 0.2\n# #\nĠ #\n")
    hf = tokenizers.ByteLevelBPETokenizer(vj, mt)
    cpp = CppByteLevelBPETokenizer(vj, mt)
    for s in ["##", "# ##x", "a ## b", "####"]:
        assert cpp.encode(s).ids == hf.encode(s).ids, s

    d = tmp_path / "trained"
    d.mkdir()
    corpus = d / "c.txt"
    corpus.write_text("łódź ľahko デ・ニーロ ĽAHKO test\n" * 40)
    tok = tokenizers.ByteLevelBPETokenizer()
    tok.train([str(corpus)], vocab_size=400, min_frequency=1)
    tok.save_model(str(d))
    vj2, mt2 = str(d / "vocab.json"), str(d / "merges.txt")
    hf2 = tokenizers.ByteLevelBPETokenizer(vj2, mt2)
    cpp2 = CppByteLevelBPETokenizer(vj2, mt2)
    hf_low = tokenizers.ByteLevelBPETokenizer(vj2, mt2, lowercase=True)
    cpp_low = CppByteLevelBPETokenizer(vj2, mt2, lowercase=True)
    for s in ["デ・ニーロ", "カタカナー", "łódź ĽAHKO"]:
        assert cpp2.encode(s).ids == hf2.encode(s).ids, s
    for s in ["ŁÓDŹ Ľahko Ĺ", "Ÿ ŶĵĶ", "Źle Žba Ŵ", "ĿL ŊAname"]:
        assert cpp_low.encode(s).ids == hf_low.encode(s).ids, s


def test_cpp_bpe_trainer_roundtrip(tmp_path):
    """The C++ BPE trainer's vocab.json/merges.txt load interchangeably
    into HF and the C++ encoder, and both encode the training corpus
    identically (training tie-breaks may differ from HF's trainer, but the
    artifact format and encode semantics are the contract)."""
    tokenizers = pytest.importorskip("tokenizers")
    from bert_pytorch_tpu.tools.tokenizer_cpp import (
        CppByteLevelBPETokenizer,
        train_bpe_vocab,
    )

    corpus = tmp_path / "c.txt"
    text = "the quick brown fox jumps over the lazy dog 123 don't\n" * 30
    corpus.write_text(text)
    out = tmp_path / "bpe"
    vocab_json = train_bpe_vocab([str(corpus)], 330, str(out),
                                 min_frequency=1)
    merges_txt = str(out / "merges.txt")
    hf = tokenizers.ByteLevelBPETokenizer(vocab_json, merges_txt)
    cpp = CppByteLevelBPETokenizer(vocab_json, merges_txt)
    assert cpp.get_vocab_size() == hf.get_vocab_size() > 261  # merges happened
    for s in ["the quick brown fox", "don't jump 123", "unseen words here"]:
        hf_enc, enc = hf.encode(s), cpp.encode(s)
        assert enc.ids == hf_enc.ids, s
        assert enc.tokens == hf_enc.tokens, s
    # merges actually compress: fewer tokens than bytes
    assert len(cpp.encode("the quick brown fox").ids) < len("the quick brown fox")
    # specials sit at the front, [PAD] first (reference build_vocab.py:64-75)
    assert cpp.token_to_id("[PAD]") == 0 and cpp.token_to_id("[MASK]") == 4


def test_cpp_bpe_oov_dropped_and_cyrillic_greek_lower(tmp_path):
    """Two oracle-verified regressions: (1) symbols missing from a partial
    vocab are DROPPED like HF (byte-level BPE has no unk token), not
    substituted; (2) lowercase covers accented Greek capitals and the
    Cyrillic U+0400-040F row (Ё et al.)."""
    import json

    tokenizers = pytest.importorskip("tokenizers")
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppByteLevelBPETokenizer

    alphabet = [chr(c) for c in range(33, 127)] + ["Ġ"]
    vj = str(tmp_path / "vocab.json")
    mt = str(tmp_path / "merges.txt")
    json.dump({t: i for i, t in enumerate(alphabet)}, open(vj, "w"))
    open(mt, "w").write("#version: 0.2\n")
    hf = tokenizers.ByteLevelBPETokenizer(vj, mt)
    cpp = CppByteLevelBPETokenizer(vj, mt)
    for s in ["aéb", "héllo wörld", "ascii only"]:
        assert cpp.encode(s).ids == hf.encode(s).ids, s

    d = tmp_path / "cyr"
    d.mkdir()
    corpus = d / "c.txt"
    corpus.write_text("Ёлка ёлка Άθήνα αθήνα Ђуро Џак ЀЍ test\n" * 40)
    tok = tokenizers.ByteLevelBPETokenizer()
    tok.train([str(corpus)], vocab_size=450, min_frequency=1)
    tok.save_model(str(d))
    vj2, mt2 = str(d / "vocab.json"), str(d / "merges.txt")
    hf_low = tokenizers.ByteLevelBPETokenizer(vj2, mt2, lowercase=True)
    cpp_low = CppByteLevelBPETokenizer(vj2, mt2, lowercase=True)
    for s in ["Ёлка", "Άθήνα", "Ђуро Џак", "ЀЍЉЊ", "Ϊ Ϋ Ό Ύ Ώ Έ Ή Ί"]:
        assert cpp_low.encode(s).ids == hf_low.encode(s).ids, s


# ---------------------------------------------------------------------------
# Adversarial Unicode parity: the C++ core vs the pure-Python spec
# (reference src/tokenization.py:60-229) on text far outside BERT's
# English comfort zone. The generated range/fold tables
# (native/gen_unicode_tables.py) must make these byte-identical.
# ---------------------------------------------------------------------------

ADVERSARIAL_TEXTS = [
    "Élan naïve façade CAFÉ Ångström søster œuvre",   # Latin accents
    "ΒΑΣ σαλάμι Σ ΚΟΣΜΟΣ ΑΣΦΑΛΗΣ ΣΣ",                 # Greek + Final_Sigma
    "Ο'Σ ΟΣ́Α אΣ Α.Σ. Σ' ΑΣ:",  # Final_Sigma with case-ignorables/uncased
    "Привет МИР Ёлка ЙОД",                             # Cyrillic (Ё->е, Й->и)
    "한국어 조선말 한",                                  # Hangul (NFD decomposes)
    "Tiếng Việt Đà-Nẵng ở đâu",                        # stacked accents
    "中文 and 日本語テキストです",                       # CJK + kana mix
    "[MASK] [CLS] x [SEP] x[MASK]y ([MASK]) [PAD]. [UNK]",  # never_split
    "İstanbul DİYARBAKIR ʼn ǅungla ẞ groß",            # multi-char lower()
    "“curly” — em…dash ¡olé! ¿qué? «guillemets» ׳״",   # Unicode punct
    "zero​width­shy écombining ́alone",
    "�replacement \x00nul\x07bell tab\tsplit",
    "⁠⁢invisible \U0001D400math \U0001F600emoji",
]


def test_cpp_matches_python_spec_adversarial(vocab_file, tmp_path):
    """Byte-identical tokens on adversarial Unicode, on a vocab built to
    exercise real subword splits for these scripts."""
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    spec = BasicTokenizer(do_lower_case=True)
    pieces = dict.fromkeys(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"])
    for text in ADVERSARIAL_TEXTS:
        for word in spec.tokenize(text):
            # whole word, first char, and every continuation char: gives the
            # greedy matcher both one-shot and char-by-char paths.
            chars = list(word)
            pieces.setdefault(word)
            pieces.setdefault(chars[0])
            for c in chars[1:]:
                pieces.setdefault("##" + c)
    vocab_path = tmp_path / "adv_vocab.txt"
    vocab_path.write_text("\n".join(pieces) + "\n")

    py = BertTokenizer(str(vocab_path), do_lower_case=True)
    cpp = CppWordPieceTokenizer(str(vocab_path), lowercase=True)
    for text in ADVERSARIAL_TEXTS:
        py_tokens = py.tokenize(text)
        enc = cpp.encode(text)
        assert enc.tokens == py_tokens, (text, enc.tokens, py_tokens)
        assert enc.ids == py.convert_tokens_to_ids(py_tokens), text


def test_never_split_special_tokens():
    """Reference tokenization.py:64-75,106-108: special tokens pass through
    basic tokenization verbatim — no lowercase, no punct split."""
    bt = BasicTokenizer(do_lower_case=True)
    assert bt.tokenize("a [MASK] b") == ["a", "[MASK]", "b"]
    assert bt.tokenize("[CLS] Hi [SEP]") == ["[CLS]", "hi", "[SEP]"]
    # Attached punctuation means the whitespace token is NOT the special
    # token, so it splits like any other text (reference behavior).
    assert bt.tokenize("([MASK])") == ["(", "[", "mask", "]", ")"]
    assert bt.tokenize("x[MASK]y") == ["x", "[", "mask", "]", "y"]


def test_max_input_chars_per_word_is_100_codepoints(tmp_path):
    """Reference tokenization.py:181: words over 100 CHARS (not bytes)
    become [UNK]."""
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    vocab_path = tmp_path / "v.txt"
    vocab_path.write_text(
        "\n".join(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
                   "a", "##a", "é", "##é"]) + "\n")
    wp = WordpieceTokenizer(load_vocab(str(vocab_path)))
    assert wp.max_input_chars_per_word == 100
    assert wp.tokenize("a" * 100) == ["a"] + ["##a"] * 99
    assert wp.tokenize("a" * 101) == ["[UNK]"]
    cpp = CppWordPieceTokenizer(str(vocab_path), lowercase=True)
    assert cpp.encode("a" * 100).tokens == ["a"] + ["##a"] * 99
    assert cpp.encode("a" * 101).tokens == ["[UNK]"]
    # 100 codepoints of 'é' is 200 UTF-8 bytes — still under the limit
    # (uppercase mode so the accent survives and 'é' stays in-vocab).
    cpp_u = CppWordPieceTokenizer(str(vocab_path), lowercase=False)
    assert cpp_u.encode("é" * 100).tokens == ["é"] + ["##é"] * 99
    assert cpp_u.encode("é" * 101).tokens == ["[UNK]"]


def test_final_sigma_matches_cpython_lower(vocab_file, tmp_path):
    """CPython str.lower() maps trailing capital sigma to the final form;
    the C++ fold must agree (SQuAD's get_final_text realigns on it)."""
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    words = ["ΚΟΣΜΟΣ", "Σ", "ΑΣ", "ΣΑ", "ΟΔΥΣΣΕΑΣ"]
    spec = BasicTokenizer(do_lower_case=True)
    pieces = dict.fromkeys(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"])
    for w in words:
        for t in spec.tokenize(w):
            pieces.setdefault(t)
    vocab_path = tmp_path / "sigma.txt"
    vocab_path.write_text("\n".join(pieces) + "\n")
    cpp = CppWordPieceTokenizer(str(vocab_path), lowercase=True)
    for w in words:
        assert cpp.encode(w).tokens == spec.tokenize(w) == [w.lower()], w


def test_unicode_tables_match_runtime_unidata_version():
    """The C++ range/fold tables are frozen at the unidata version of the
    Python that generated them; the parity contract only holds when the
    runtime's unicodedata agrees. Regenerate on mismatch:
    cd native && make unicode_tables.inc && make."""
    import re
    import unicodedata

    inc = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native", "unicode_tables.inc")
    with open(inc) as f:
        head = f.read(4096)
    m = re.search(r'kUnidataVersion\[\] = "([^"]+)"', head)
    assert m, "unicode_tables.inc missing kUnidataVersion"
    assert m.group(1) == unicodedata.unidata_version, (
        f"tables generated for unidata {m.group(1)} but runtime has "
        f"{unicodedata.unidata_version}; regenerate (see docstring)")


def test_concurrent_encode_matches_serial(cpp_tok, py_tok):
    """Thread-safety audit contract (serving worker threads,
    data/tokenization.py module docstring): concurrent encodes through one
    SHARED tokenizer instance must be identical to serial encoding — the
    C++ backend's per-handle result buffers are serialized by its
    _encode_lock; the pure-Python tokenizer is read-only state."""
    import concurrent.futures

    texts = [SENTENCES[i % len(SENTENCES)] + f" tail{i}" for i in range(64)]

    serial_cpp = [cpp_tok.encode(t).ids for t in texts]
    serial_py = [py_tok.tokenize(t) for t in texts]

    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
        concurrent_cpp = list(pool.map(lambda t: cpp_tok.encode(t).ids,
                                       texts))
        concurrent_py = list(pool.map(py_tok.tokenize, texts))

    assert concurrent_cpp == serial_cpp
    assert concurrent_py == serial_py
