"""Offline pipeline integration: format -> shard -> vocab -> encode -> load.

The TPU-framework analog of the reference's scripts/create_datasets.sh flow
(SURVEY.md §3.5), run end-to-end on a synthetic corpus and consumed back
through the runtime dataset.
"""

import os
import random

import numpy as np
import pytest

CORPUS_SENTENCES = [
    "the cat sat on the mat",
    "a dog ran in the park",
    "the quick brown fox jumps over the lazy dog",
    "hello world this is a test sentence",
    "the mat was soft and warm",
    "dogs and cats are animals",
]


@pytest.fixture(scope="module")
def pipeline(tmp_path_factory):
    """Run the full offline pipeline once; return its artifacts."""
    root = tmp_path_factory.mktemp("pipeline")

    # 1. raw "books" corpus files (paragraph text)
    raw_dir = root / "raw"
    raw_dir.mkdir()
    rng = random.Random(0)
    for i in range(4):
        sentences = [rng.choice(CORPUS_SENTENCES) for _ in range(30)]
        (raw_dir / f"book_{i}.txt").write_text(". ".join(sentences) + ".")

    # 2. format -> one sentence per line
    from bert_pytorch_tpu.tools.format import format_corpus

    fmt_dir = root / "formatted"
    outs = format_corpus(
        [str(p) for p in raw_dir.iterdir()], str(fmt_dir), "books",
        num_outputs=2, processes=1)
    assert len(outs) == 2

    # 3. shard
    from bert_pytorch_tpu.tools.shard import shard

    # Each shard must hold >=2 documents for NSP's random-next draw, so use
    # a shard size that keeps all articles together.
    shard_dir = root / "sharded"
    shards = shard(outs, str(shard_dir), max_bytes=10**6)
    assert len(shards) >= 1

    # 4. vocab (C++ WordPiece trainer)
    from bert_pytorch_tpu.tools.build_vocab import build_wordpiece_vocab

    vocab_path = str(root / "vocab.txt")
    build_wordpiece_vocab(shards, vocab_path, vocab_size=120)

    # 5. encode to HDF5 (with NSP)
    from bert_pytorch_tpu.tools import encode_data

    out_dir = root / "encoded"
    encode_data.main([
        "--input_dir", str(shard_dir), "--output_dir", str(out_dir),
        "--vocab_file", vocab_path, "--max_seq_len", "64",
        "--next_seq_prob", "0.5", "--short_seq_prob", "0.1",
        "--processes", "1",
    ])
    enc_dir = out_dir / "sequences_lowercase_max_seq_len_64_next_seq_task_true"
    hdf5_files = sorted(str(p) for p in enc_dir.glob("*.hdf5"))
    assert hdf5_files
    return {"vocab": vocab_path, "hdf5": hdf5_files, "root": root}


def test_encoded_shards_have_expected_format(pipeline):
    import h5py

    with h5py.File(pipeline["hdf5"][0], "r") as f:
        assert set(f.keys()) == {
            "input_ids", "special_token_positions", "next_sentence_labels"}
        n = len(f["input_ids"])
        assert n > 0
        assert f["input_ids"].shape[1] == 64
        labels = np.asarray(f["next_sentence_labels"][:])
        assert set(np.unique(labels)) <= {0, 1}
        specials = f["special_token_positions"][0]
        assert len(specials) == 3  # NSP -> [CLS], mid [SEP], end [SEP]
        assert specials[0] == 0


def test_samples_wrap_with_cls_sep(pipeline):
    import h5py

    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    tok = CppWordPieceTokenizer(pipeline["vocab"])
    cls_id, sep_id = tok.token_to_id("[CLS]"), tok.token_to_id("[SEP]")
    with h5py.File(pipeline["hdf5"][0], "r") as f:
        ids = np.asarray(f["input_ids"][0])
        specials = np.asarray(f["special_token_positions"][0])
    assert ids[specials[0]] == cls_id
    assert ids[specials[1]] == sep_id
    assert ids[specials[2]] == sep_id


def test_encoded_data_trains_end_to_end(pipeline):
    """The offline pipeline's output feeds the runtime dataset + a train
    step — the full create_datasets -> run_pretraining contract."""
    from bert_pytorch_tpu.data import DataLoader, DistributedSampler, \
        ShardedPretrainingDataset
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    tok = CppWordPieceTokenizer(pipeline["vocab"])
    ds = ShardedPretrainingDataset(
        pipeline["hdf5"], tok.token_to_id("[MASK]"), 10, 0.15,
        vocab_size=tok.get_vocab_size(), seed=0)
    sampler = DistributedSampler(ds, 1, 0)
    loader = DataLoader(ds, sampler, batch_size=4)
    batch = next(iter(loader))
    assert batch["input_ids"].shape == (4, 64)
    assert (batch["masked_lm_labels"] != -1).sum() > 0


def test_shard_respects_article_boundaries(tmp_path):
    from bert_pytorch_tpu.tools.shard import iter_articles, shard

    src = tmp_path / "in.txt"
    src.write_text("a1 s1\na1 s2\n\nb1 s1\n\nc1 s1\nc1 s2\nc1 s3\n")
    articles = list(iter_articles([str(src)]))
    assert [len(a) for a in articles] == [2, 1, 3]
    outs = shard([str(src)], str(tmp_path / "out"), max_bytes=10)
    # every output shard starts at an article boundary
    total = []
    for o in outs:
        arts = list(iter_articles([o]))
        total.extend(arts)
    assert [len(a) for a in total] == [2, 1, 3]


def test_shard_sentence_sampling(tmp_path):
    from bert_pytorch_tpu.tools.shard import iter_articles, shard

    src = tmp_path / "in.txt"
    src.write_text("\n".join(f"article{i} sentence" for i in range(50)) + "\n")
    outs = shard([str(src)], str(tmp_path / "out"), max_bytes=10**6,
                 sample_sentences=10)
    sentences = [s for o in outs for a in iter_articles([o]) for s in a]
    assert len(sentences) == 10


def test_parse_value_as_int():
    from bert_pytorch_tpu.tools.shard import parse_value_as_int

    assert parse_value_as_int("250M") == 250_000_000
    assert parse_value_as_int("1k") == 1000
    assert parse_value_as_int("42") == 42


def test_sha256_verification(tmp_path):
    from bert_pytorch_tpu.tools.download import sha256_file, verify_sha256

    p = tmp_path / "f.bin"
    p.write_bytes(b"hello")
    digest = sha256_file(str(p))
    verify_sha256(str(p), digest)
    with pytest.raises(ValueError, match="SHA256 mismatch"):
        verify_sha256(str(p), "0" * 64)


def test_bz2_extraction(tmp_path):
    import bz2 as bz2mod

    from bert_pytorch_tpu.tools.download import extract_bz2

    src = tmp_path / "x.bz2"
    src.write_bytes(bz2mod.compress(b"wiki dump contents"))
    out = extract_bz2(str(src), str(tmp_path / "x.xml"))
    assert open(out, "rb").read() == b"wiki dump contents"


def test_format_multiprocess(tmp_path):
    """mp.Pool path (the reference's Pool.starmap, format.py:62-63) — job
    functions must be picklable."""
    from bert_pytorch_tpu.tools.format import format_corpus

    raw = tmp_path / "raw"
    raw.mkdir()
    for i in range(4):
        (raw / f"b{i}.txt").write_text("one sentence. and another one.")
    outs = format_corpus(
        [str(p) for p in raw.iterdir()], str(tmp_path / "fmt"), "books",
        num_outputs=2, processes=2)
    assert len(outs) == 2
    text = "".join(open(o).read() for o in outs)
    assert "one sentence." in text and "and another one." in text


def test_encode_keeps_last_sentence():
    """The closing sentence of each document lands in a sample, and
    1-sentence documents produce a sample (deliberate fix over the
    reference's flush-before-append loop, encode_data.py:92-96)."""
    from bert_pytorch_tpu.tools.encode_data import create_samples_from_document

    rng = random.Random(0)
    docs = [
        [["alpha", "beta"], ["gamma", "delta"], ["FINAL", "WORD"]],
        [["other", "doc", "filler"]],
    ]
    all_tokens = set()
    for _ in range(20):  # over rng draws
        for sample in create_samples_from_document(
                0, docs, 16, next_seq_prob=0.5, short_seq_prob=0.0, rng=rng):
            all_tokens.update(sample.sequence)
    assert "FINAL" in all_tokens and "WORD" in all_tokens

    single = create_samples_from_document(
        1, docs, 16, next_seq_prob=0.5, short_seq_prob=0.0, rng=rng)
    assert single, "single-sentence document must yield a sample"


def test_encode_single_segment_chunk_forces_random_next():
    """A 1-segment chunk cannot provide an 'actual next' pair — canonical
    BERT forces is_random_next (no empty-segment-B samples)."""
    from bert_pytorch_tpu.tools.encode_data import create_samples_from_document

    rng = random.Random(1)
    docs = [
        [["a"] * 20],  # one long sentence: every chunk is single-segment
        [["rand", "next", "tokens"]],
    ]
    for _ in range(10):
        for sample in create_samples_from_document(
                0, docs, 16, next_seq_prob=0.5, short_seq_prob=0.0, rng=rng):
            assert sample.is_random_next
            assert sample.next_seq_tokens, "segment B must be non-empty"


def test_encode_samples_respect_max_seq_len():
    from bert_pytorch_tpu.tools.encode_data import create_samples_from_document

    rng = random.Random(2)
    docs = [
        [["w%d" % i for i in range(j, j + 9)] for j in range(0, 90, 9)],
        [["other", "document"]],
    ]
    for _ in range(10):
        for sample in create_samples_from_document(
                0, docs, 24, next_seq_prob=0.5, short_seq_prob=0.3, rng=rng):
            assert len(sample.sequence) <= 24


def test_weights_sha_verify(tmp_path):
    """WeightsDownloader.verify checks extracted files against the SHA table
    (reference utils/download.py:203-216)."""
    from bert_pytorch_tpu.tools import download

    d = tmp_path / "model" / "nested"
    d.mkdir(parents=True)
    (d / "bert_config.json").write_bytes(b"fake config")
    sha = download.sha256_file(str(d / "bert_config.json"))
    download.WEIGHTS_SHA["__test__"] = {"bert_config.json": sha}
    try:
        download.WeightsDownloader.verify(str(tmp_path / "model"), "__test__")
        download.WEIGHTS_SHA["__test__"] = {"bert_config.json": "0" * 64}
        with pytest.raises(ValueError, match="SHA256 mismatch"):
            download.WeightsDownloader.verify(
                str(tmp_path / "model"), "__test__")
        with pytest.raises(FileNotFoundError):
            download.WEIGHTS_SHA["__test__"] = {"missing.bin": sha}
            download.WeightsDownloader.verify(
                str(tmp_path / "model"), "__test__")
    finally:
        del download.WEIGHTS_SHA["__test__"]


def test_bench_tokenizer_smoke():
    """The tokenizer throughput harness runs end to end and reports the
    same token count for both backends (identical work — the fairness
    property the ratio depends on)."""
    import json
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-m", "bert_pytorch_tpu.tools.bench_tokenizer",
         "--lines", "200", "--repeat", "1"],
        capture_output=True, text=True, check=True, timeout=300).stdout
    recs = [json.loads(l) for l in out.splitlines() if l.strip()]
    by_backend = {r["backend"]: r for r in recs if "backend" in r}
    assert by_backend["cpp"]["value"] > 0
    if "skipped" not in by_backend.get("hf_rust", {"skipped": 1}):
        assert by_backend["cpp"]["tokens"] == by_backend["hf_rust"]["tokens"]
