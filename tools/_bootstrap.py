"""Shared bootstrap for the repo-root tools: load a stdlib-only engine
module from the package by FILE PATH, without executing the
``bert_pytorch_tpu/__init__`` chain (which imports jax) — the property
that lets these tools run on machines without the accelerator stack
(pre-commit hooks, CI boxes). Scripts in this directory can import it
directly: Python puts the script's own directory on ``sys.path``.
"""

from __future__ import annotations

import importlib.util
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_by_path(name: str, *relpath: str):
    """Load ``<REPO_ROOT>/<relpath...>`` as module ``name`` (no package
    __init__ execution; the module must be stdlib-only)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO_ROOT, *relpath))
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module
