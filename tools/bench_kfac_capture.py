"""Measure the K-FAC factor-capture cost at factor_interval=1.

The reference's hooks harvest Kronecker factors from the training backward
pass for free (reference run_pretraining.py:320-355); round-3's design
paid a separate stats forward/backward per factor update instead
(VERDICT r3 missing #3: a structural, not just evidence, gap). This tool
measures the fix — fused in-train capture
(pretrain.make_train_step(kfac_capture_model=...)) — against both the old
stats-pass mode and the first-order baseline, at the reference's
operating point (factors EVERY step):

    python tools/bench_kfac_capture.py [--out KFAC_CAPTURE_BENCH.jsonl]

Emits one JSON line per leg:
{"leg": "lamb|kfac_stats|kfac_stats_full|kfac_fused", "sec_per_step": N,
"cost_vs_lamb": N, ...}. The headline is the fused leg's
``fused_vs_stats_equal_rows``: fused capture vs a decoupled stats pass of
the SAME statistical quality (full microbatch rows — what the reference's
hooks harvest). ``fused_vs_stats`` compares against the runner's cheap
16-row subsampled pass instead, a quality-vs-cost trade, not
like-for-like. Runs on whatever backend JAX selects (CPU gives an
architecture-honest FLOP-cost proxy but over-prices the factor einsums
relative to a TPU's MXU; the capture harness runs the BERT-large shape on
the chip).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Runnable as `python tools/bench_kfac_capture.py` from the repo root
# without touching PYTHONPATH (which must keep any TPU-plugin site dir).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def build(args):
    import flax.linen as nn

    from bert_pytorch_tpu import optim, pretrain
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining

    config = BertConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_hidden_layers=args.layers, num_attention_heads=args.heads,
        intermediate_size=4 * args.hidden,
        max_position_embeddings=args.seq, next_sentence=True)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    model = BertForPreTraining(config, dtype=dtype, remat=args.remat)
    tapped = BertForPreTraining(config, dtype=dtype, remat=args.remat,
                                kfac_tap=True)
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), *(jnp.zeros((1, args.seq), jnp.int32),) * 3)
    )["params"]
    schedule = optim.warmup_poly_schedule(1e-3, 0.1, 1000)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    state = pretrain.TrainState(
        params=params, opt_state=tx.init(params), rng=jax.random.PRNGKey(1))

    rng = np.random.default_rng(0)
    A, B, S = args.accum, args.batch, args.seq
    batch = {
        "input_ids": rng.integers(
            0, args.vocab, (A, B, S)).astype(np.int32),
        "segment_ids": np.zeros((A, B, S), np.int32),
        "input_mask": np.ones((A, B, S), np.int32),
        "masked_lm_labels": np.where(
            rng.random((A, B, S)) < 0.15,
            rng.integers(0, args.vocab, (A, B, S)), -1).astype(np.int32),
        "next_sentence_labels": rng.integers(0, 2, (A, B)).astype(np.int32),
    }
    apply_loss, tap_shape_fn = pretrain.make_kfac_fns(
        tapped, True, max_pred_per_seq=args.max_pred)
    kfac = optim.KFAC(apply_loss, tap_shape_fn)
    mb0 = {k: v[0] for k, v in batch.items()}
    kstate = kfac.init(params, mb0)
    return (model, tapped, tx, schedule, kfac, kstate, state, batch, mb0,
            config)


def timed(fn, warmup, steps):
    for _ in range(warmup):
        out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--accum", type=int, default=1)
    ap.add_argument("--max_pred", type=int, default=20)
    ap.add_argument("--remat", type=str, default="none")
    ap.add_argument("--dtype", type=str, default="float32")
    ap.add_argument("--stats_batch", type=int, default=16,
                    help="rows for the stats-pass leg (the runner default)")
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    from bert_pytorch_tpu import optim, pretrain

    (model, tapped, tx, schedule, kfac, kstate, state, batch, mb0, config
     ) = build(args)
    copy = lambda t: jax.tree_util.tree_map(jnp.array, t)

    meta = {
        "backend": jax.devices()[0].platform,
        "hidden": args.hidden, "layers": args.layers, "seq": args.seq,
        "batch": args.batch, "accum": args.accum, "dtype": args.dtype,
        "factor_interval": 1, "stats_batch": args.stats_batch,
    }
    results = []

    # Leg 1: first-order baseline.
    plain = pretrain.make_train_step(
        model, tx, schedule=schedule, next_sentence=True,
        max_pred_per_seq=args.max_pred)

    def run_plain(st=[copy(state)]):
        st[0], m = plain(st[0], batch)
        return m["loss"]

    t_lamb = timed(run_plain, args.warmup, args.steps)
    results.append({"leg": "lamb", **meta,
                    "sec_per_step": round(t_lamb, 5), "cost_vs_lamb": 1.0})

    # Legs 2a/2b: K-FAC, decoupled stats pass every step (the round-3
    # design at the reference operating point — pays a second
    # forward/backward). 2a subsamples --stats_batch rows (the runner's
    # cheap default: LESS statistical quality than the reference's
    # full-batch hooks); 2b runs the stats pass on the FULL microbatch —
    # the equal-statistics comparison the fused capture must beat.
    kstep = pretrain.make_train_step(
        model, tx, schedule=schedule, next_sentence=True,
        max_pred_per_seq=args.max_pred, kfac=kfac)

    def stats_runner(stats_mb):
        def run(st=[copy(state)], ks=[copy(kstate)], n=[0]):
            ks[0] = kfac.update_factors(
                ks[0], st[0].params, stats_mb,
                jax.random.fold_in(jax.random.PRNGKey(17), n[0]))
            n[0] += 1
            st[0], m = kstep(st[0], batch, ks[0])
            return m["loss"]
        return run

    stats_rows = min(args.stats_batch, args.batch)
    stride = max(1, args.batch // stats_rows)
    t_stats = timed(
        stats_runner({k: v[::stride][:stats_rows] for k, v in mb0.items()}),
        args.warmup, args.steps)
    results.append({"leg": "kfac_stats", **meta,
                    "rows": stats_rows,
                    "sec_per_step": round(t_stats, 5),
                    "cost_vs_lamb": round(t_stats / t_lamb, 4)})

    t_stats_full = t_stats
    if stats_rows < args.batch:
        t_stats_full = timed(stats_runner(mb0), args.warmup, args.steps)
        results.append({"leg": "kfac_stats_full", **meta,
                        "rows": args.batch,
                        "sec_per_step": round(t_stats_full, 5),
                        "cost_vs_lamb": round(t_stats_full / t_lamb, 4)})

    # Leg 3: K-FAC, fused in-train capture (this round's structural fix).
    fstep = pretrain.make_train_step(
        model, tx, schedule=schedule, next_sentence=True,
        max_pred_per_seq=args.max_pred, kfac=kfac,
        kfac_capture_model=tapped, kfac_factor_interval=1)

    def run_fused(st=[copy(state)], ks=[copy(kstate)]):
        st[0], m, ks[0] = fstep(st[0], batch, ks[0])
        return m["loss"]

    t_fused = timed(run_fused, args.warmup, args.steps)
    results.append({"leg": "kfac_fused", **meta,
                    "rows": args.batch,
                    "sec_per_step": round(t_fused, 5),
                    "cost_vs_lamb": round(t_fused / t_lamb, 4),
                    "fused_vs_stats": round(t_fused / t_stats, 4),
                    "fused_vs_stats_equal_rows": round(
                        t_fused / t_stats_full, 4)})

    for r in results:
        print(json.dumps(r))
    if args.out:
        with open(args.out, "a") as f:
            for r in results:
                f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
