"""Strategy-scaling table on the virtual CPU mesh -> SCALING_r03.json.

Real multi-chip scaling needs a pod; this harness produces what one host
CAN honestly measure (VERDICT r2 weak #5): for each parallelism strategy
(dp, fsdp, tp, sp/ring, pp) at 1/2/4/8 virtual CPU devices
(``--xla_force_host_platform_device_count``), the same fixed global-batch
training step — correctness (finite, dp-consistent loss) plus the
step-time ratio against the unsharded baseline. CPU step times do NOT
predict TPU throughput (no MXU, no ICI; XLA:CPU collectives are memcpys);
what the table evidences is that every strategy composes into one jitted
step at every width with consistent losses, and what sharding overhead
each strategy adds. NB on the ideal: the N virtual devices SHARE the
host's cores, so with the global batch fixed the total compute per step
is constant and the ideal step time is ~= the 1-device baseline
(overhead_factor 1.0); overhead_factor above 1 quantifies the
partitioning/collective cost the strategy introduces at that width.

  python tools/bench_scaling_cpu.py [out.json]

Reference point: the reference's only strategy is DDP data parallelism
(run_pretraining.py:270); everything beyond dp here is beyond-parity
surface from SURVEY.md §2.2's TPU-native plan.
"""

from __future__ import annotations

import json
import os
import sys
import time

N_DEVICES = 8
GLOBAL_BATCH = 32
SEQ = 128
WARMUP, MEASURE = 2, 5


def _force_cpu(n):
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(out_path="SCALING_r03.json"):
    _force_cpu(N_DEVICES)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bert_pytorch_tpu import optim, pretrain
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.parallel import (MeshConfig, create_mesh,
                                           logical_axis_rules)

    # bert_small geometry, 8 layers so pipeline splits 2/4/8 ways, small
    # vocab for CPU speed.
    config = BertConfig(
        vocab_size=8192, hidden_size=256, num_hidden_layers=8,
        num_attention_heads=4, intermediate_size=1024,
        max_position_embeddings=SEQ, next_sentence=True)
    schedule = optim.warmup_poly_schedule(1e-3, 0.1, 1000)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    sample = (jnp.zeros((1, SEQ), jnp.int32),) * 3
    rng = np.random.default_rng(0)
    host = {
        "input_ids": rng.integers(
            0, config.vocab_size, (GLOBAL_BATCH, SEQ)).astype(np.int32),
        "segment_ids": rng.integers(0, 2, (GLOBAL_BATCH, SEQ)).astype(np.int32),
        "input_mask": np.ones((GLOBAL_BATCH, SEQ), np.int32),
        "masked_lm_labels": np.where(
            rng.random((GLOBAL_BATCH, SEQ)) < 0.15,
            rng.integers(0, config.vocab_size, (GLOBAL_BATCH, SEQ)),
            -1).astype(np.int32),
        "next_sentence_labels": rng.integers(
            0, 2, (GLOBAL_BATCH,)).astype(np.int32),
    }

    def run_point(strategy, n):
        axes = {"dp": dict(data=n), "fsdp": dict(data=1, fsdp=n),
                "tp": dict(data=1, model=n), "sp": dict(data=1, seq=n),
                "pp": dict(data=1, pipe=n)}[strategy]
        mesh = create_mesh(MeshConfig(**axes), devices=jax.devices()[:n])
        rules = logical_axis_rules(strategy if n > 1 else "dp")
        backend = "ring" if strategy == "sp" and n > 1 else "xla"
        model = BertForPreTraining(config, dtype=jnp.float32,
                                   attention_backend=backend)
        accum = n if strategy == "pp" and n > 1 else 1
        with mesh:
            shardings = pretrain.state_shardings(mesh, model, rules, sample)
            b_shardings = pretrain.batch_shardings(
                mesh, {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
                       "masked_lm_labels": 3, "next_sentence_labels": 2},
                seq_sharded=backend == "ring")
            state = pretrain.make_init_fn(model, tx, sample, shardings)(
                jax.random.PRNGKey(0))
            if strategy == "pp" and n > 1:
                step = pretrain.make_pp_train_step(
                    model, tx, mesh, schedule=schedule, next_sentence=True,
                    shardings=shardings, batch_shardings_=b_shardings)
            else:
                step = pretrain.make_train_step(
                    model, tx, schedule=schedule, next_sentence=True,
                    shardings=shardings, batch_shardings_=b_shardings)
            batch = pretrain.put_batch(
                pretrain.stack_microbatches(host, accum), b_shardings)
            first_loss = None
            for _ in range(WARMUP):
                state, metrics = step(state, batch)
                loss = float(metrics["loss"])
                if first_loss is None:
                    first_loss = loss
            t0 = time.perf_counter()
            for _ in range(MEASURE):
                state, metrics = step(state, batch)
            _ = float(metrics["loss"])
            dt = (time.perf_counter() - t0) / MEASURE
        assert np.isfinite(first_loss), f"{strategy}@{n}: loss {first_loss}"
        return {"strategy": strategy, "n_devices": n,
                "step_time_ms": round(dt * 1000, 1),
                "first_step_loss": round(first_loss, 4)}

    points = []
    base = run_point("dp", 1)
    base_ms, base_loss = base["step_time_ms"], base["first_step_loss"]
    base["overhead_factor"] = 1.0
    points.append(base)
    print(json.dumps(base))
    widths = {"dp": (2, 4, 8), "fsdp": (2, 4, 8), "sp": (2, 4, 8),
              "pp": (2, 4, 8),
              # tensor parallelism splits the 4 attention heads
              "tp": (2, 4)}
    for strategy in ("dp", "fsdp", "tp", "sp", "pp"):
        for n in widths[strategy]:
            rec = run_point(strategy, n)
            rec["overhead_factor"] = round(rec["step_time_ms"] / base_ms, 3)
            # all strategies run the SAME global batch from the same init
            # seed; first-step losses must agree (dropout streams differ
            # by sharding layout, so exact equality is not expected —
            # strict step-equivalence lives in tests/test_pipeline.py)
            rec["loss_delta_vs_base"] = round(
                rec["first_step_loss"] - base_loss, 4)
            assert abs(rec["loss_delta_vs_base"]) < 0.05, rec
            points.append(rec)
            print(json.dumps(rec))
    out = {
        "meta": {
            "harness": "virtual 8-device CPU mesh (global batch fixed at "
                       f"{GLOBAL_BATCH}, seq {SEQ}, 8-layer bert_small "
                       "geometry); devices share the host's cores, so "
                       "overhead_factor ~1.0 is ideal and the excess is "
                       "the strategy's partitioning/collective cost — "
                       "NOT a TPU throughput prediction",
            "correctness": "all points run the same global batch from the "
                           "same init; first_step_loss must agree with "
                           "the baseline (asserted within 0.05)",
        },
        "baseline_step_time_ms": base_ms,
        "points": points,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {out_path} ({len(points)} points)")


if __name__ == "__main__":
    main(*sys.argv[1:2])
