#!/usr/bin/env python
"""Chaos harness: kill, corrupt, resume — and PROVE the recovery was
exact (docs/fault_tolerance.md).

The fault-tolerance subsystem's acceptance gate. One invocation:

1. **reference** — an uninterrupted CPU pretraining run on synthetic
   data (tiny fp32 config, dropout 0, per-step telemetry) records the
   ground-truth per-step loss trajectory;
2. **chaos** — an identical run armed with ``--fault_spec die@K`` is
   SIGKILLed mid-run (the hard-preemption model: no handlers, no
   flushing), after transient injected shard-read errors exercised the
   data-path retry;
3. **corrupt** — the newest checkpoint the dead run left behind is
   damaged in place (``--corrupt_mode truncate|flip``; the manifest
   sidecar is left stale so only integrity verification can catch
   ``flip``);
4. **resume** — the same command reruns with no faults armed. It must
   walk back past the corrupt checkpoint to the previous verified one,
   emit a schema-clean ``resume`` record naming what it skipped, finish
   the remaining steps, and reproduce the reference trajectory from the
   resume step on (``--loss_rtol``, default 1e-6 — fp32 CPU reruns of
   the same compiled step are deterministic; resume-exactness holds
   because masking derives from (seed, epoch, index), data/dataset.py).

Both telemetry artifacts are then linted against the record schema.
Verdict is one JSON line on stdout; exit 0 = every assertion held.

``--smoke`` is the documented one-command local gate (small step counts,
tier-1-budget-friendly: three lean child processes, ~45 s total on a
throttled 2-core CPU)::

    python tools/chaos_run.py --smoke

The parent is deliberately jax-free (``tools/_bootstrap.py`` file-path
imports): a hung accelerator runtime can hang a CHILD, which the
per-child ``--child_timeout_s`` kills — never the harness itself.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import shutil
import subprocess
import sys
import tempfile

from _bootstrap import REPO_ROOT, load_by_path

faults = load_by_path(
    "_chaos_faults", "bert_pytorch_tpu", "testing", "faults.py")
integrity = load_by_path(
    "_chaos_integrity", "bert_pytorch_tpu", "utils", "integrity.py")
schema = load_by_path(
    "_chaos_schema", "bert_pytorch_tpu", "telemetry", "schema.py")
synth = load_by_path(
    "_chaos_synth", "bert_pytorch_tpu", "tools", "make_synthetic_data.py")

# Tiny fp32 model, dropout 0: deterministic across kill/resume (the
# dropout rng chain is NOT checkpointed — with it enabled, resumed draws
# would legitimately differ and the trajectory comparison would be
# meaningless noise instead of a recovery proof). Sized at the floor
# that still exercises the full step (encoder + MLM + NSP): each of the
# three children pays the train-step compile, which dominates the
# harness's wall-clock inside the tier-1 budget.
MODEL_CONFIG = {
    "vocab_size": 1000, "hidden_size": 16, "num_hidden_layers": 1,
    "num_attention_heads": 2, "intermediate_size": 32,
    "max_position_embeddings": 32, "type_vocab_size": 2,
    "next_sentence": True, "mask_token_id": 4,
    "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
}


class ChaosFailure(AssertionError):
    pass


def check(cond, what):
    if not cond:
        raise ChaosFailure(what)


def make_data(data_dir: str, seq_len: int, n_per_shard: int = 64) -> None:
    os.makedirs(data_dir, exist_ok=True)
    for i in range(2):
        synth.make_shard(os.path.join(data_dir, f"shard_{i}.hdf5"),
                         n_per_shard, seq_len,
                         MODEL_CONFIG["vocab_size"], seed=i)


def child_cmd(args, out_dir: str, fault_spec: str = "") -> list:
    cmd = [
        sys.executable, os.path.join(REPO_ROOT, "run_pretraining.py"),
        "--input_dir", args.data_dir, "--output_dir", out_dir,
        "--model_config_file", args.config_path,
        "--global_batch_size", "16", "--local_batch_size", "16",
        "--max_steps", str(args.steps), "--steps", str(args.steps),
        "--learning_rate", "1e-3", "--warmup_proportion", "0.25",
        "--num_steps_per_checkpoint", str(args.ckpt_every),
        "--keep_checkpoints", "3",
        "--dtype", "float32", "--seed", str(args.seed),
        "--log_steps", "1", "--telemetry_sync_every", "1",
        "--telemetry_window", "5", "--term_check_steps", "1",
        # Keep the children lean — the gate's evidence is the loss
        # trajectory + fault/resume records, so skip the sinks/extras
        # with heavy fixed costs: the TensorBoard backend import (torch,
        # ~25s/child on a throttled CPU), the cost-analysis extra
        # compile, the in-jit grad stats. Wall-clock is tier-1 budget
        # (tests/test_fault_tolerance.py runs this harness).
        "--disable_tensorboard",
        "--telemetry_cost_analysis", "off", "--grad_stats_every", "0",
        # SYNCHRONOUS checkpoint writes, deliberately overriding the
        # async default (PR 6): die@N fires right after step N's
        # checkpoint block, and with async writes the SIGKILL can land
        # before the background writer commits the newest manifest —
        # leaving a TORN pair (blob, no sidecar) that verify_checkpoint
        # reports as no_manifest, so the harness's "corrupt the newest
        # VERIFIED checkpoint and walk back" setup becomes a coin flip
        # on a loaded box (observed flaking tier-1). Losing the newest
        # checkpoint to a kill mid-async-write is BY-DESIGN durability
        # behavior with its own PR 6 tests
        # (test_preemption_joins_inflight_async_save etc.); this gate
        # tests corruption recovery, which needs a deterministic,
        # durably-manifested checkpoint layout at kill time.
        "--checkpoint_write", "sync",
    ]
    if fault_spec:
        cmd += ["--fault_spec", fault_spec]
    return cmd


def run_child(args, out_dir: str, fault_spec: str = "") -> int:
    env = dict(os.environ)
    # The chaos proof is a single-device CPU-determinism gate; never let
    # a TPU plugin, the test harness's virtual 8-device mesh flag, or a
    # fault spec leaked from an outer environment change that.
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop(faults.FAULTS_ENV, None)
    xla_flags = " ".join(
        flag for flag in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in flag)
    if xla_flags:
        env["XLA_FLAGS"] = xla_flags
    else:
        env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        child_cmd(args, out_dir, fault_spec), env=env,
        timeout=args.child_timeout_s,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    if args.verbose:
        sys.stderr.write(proc.stdout[-4000:] + "\n")
    return proc.returncode


def telemetry_records(out_dir: str) -> list:
    path = os.path.join(out_dir, "pretraining_telemetry.jsonl")
    records = []
    with open(path) as f:
        for line in f:
            if line.strip():
                records.append(json.loads(line))
    return records


def train_losses(records) -> dict:
    return {int(r["step"]): float(r["step_loss"]) for r in records
            if r.get("tag") == "train" and r.get("step_loss") is not None}


def lint(out_dir: str) -> None:
    path = os.path.join(out_dir, "pretraining_telemetry.jsonl")
    errors = schema.validate_file(path)
    check(errors == [], f"schema lint failed for {path}: {errors[:3]}")


def compare_trajectories(ref: dict, new: dict, steps, rtol: float,
                         what: str) -> None:
    for step in steps:
        check(step in ref, f"{what}: reference has no step {step}")
        check(step in new, f"{what}: run has no step {step}")
        check(math.isclose(ref[step], new[step], rel_tol=rtol),
              f"{what}: loss diverged at step {step}: "
              f"reference {ref[step]!r} vs {new[step]!r} (rtol {rtol})")


def ckpt_steps(out_dir: str) -> list:
    d = os.path.join(out_dir, "pretrain_ckpts")
    steps = []
    for name in os.listdir(d):
        if name.startswith("ckpt_") and name.endswith(".msgpack"):
            steps.append(int(name[len("ckpt_"):-len(".msgpack")]))
    return sorted(steps)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="kill->corrupt->resume chaos harness")
    parser.add_argument("--smoke", action="store_true",
                        help="the one-command local gate: small step "
                             "counts sized for a laptop CPU / the tier-1 "
                             "budget")
    parser.add_argument("--steps", type=int, default=None,
                        help="total optimizer steps (default 20; 8 "
                             "under --smoke)")
    parser.add_argument("--die_at", type=int, default=None,
                        help="SIGKILL the chaos child at this step "
                             "(default: steps - 3)")
    parser.add_argument("--ckpt_every", type=int, default=2)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--seq_len", type=int, default=32)
    parser.add_argument("--shard_errors", type=int, default=2,
                        help="transient injected shard-read errors in the "
                             "chaos run (0 disables)")
    parser.add_argument("--corrupt_mode", type=str, default="truncate",
                        choices=["truncate", "flip"])
    parser.add_argument("--loss_rtol", type=float, default=1e-6)
    parser.add_argument("--child_timeout_s", type=float, default=300.0)
    parser.add_argument("--workdir", type=str, default="",
                        help="keep artifacts here (default: a fresh "
                             "temp dir, removed on success)")
    parser.add_argument("--verbose", action="store_true",
                        help="echo child output")
    args = parser.parse_args(argv)

    args.steps = args.steps or (8 if args.smoke else 20)
    args.die_at = args.die_at or max(3, args.steps - 3)
    check(args.die_at < args.steps, "--die_at must be before --steps")

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_run_")
    os.makedirs(workdir, exist_ok=True)
    args.data_dir = os.path.join(workdir, "data")
    args.config_path = os.path.join(workdir, "model.json")
    ref_dir = os.path.join(workdir, "reference")
    chaos_dir = os.path.join(workdir, "chaos")
    verdict = {"metric": "chaos_kill_corrupt_resume", "workdir": workdir,
               "steps": args.steps, "die_at": args.die_at,
               "corrupt_mode": args.corrupt_mode}
    try:
        make_data(args.data_dir, args.seq_len)
        with open(args.config_path, "w") as f:
            json.dump(MODEL_CONFIG, f)

        # 1. reference trajectory (uninterrupted)
        rc = run_child(args, ref_dir)
        check(rc == 0, f"reference run failed (rc {rc})")
        ref = train_losses(telemetry_records(ref_dir))
        check(len(ref) == args.steps,
              f"reference logged {len(ref)} steps, wanted {args.steps}")

        # 2. chaos run: transient shard errors early, SIGKILL at die_at
        spec = f"die@{args.die_at}"
        if args.shard_errors:
            spec += f",shard_errorx{args.shard_errors}"
        rc = run_child(args, chaos_dir, fault_spec=spec)
        check(rc in (-9, 137),
              f"chaos child should die by SIGKILL, got rc {rc}")
        chaos_records = telemetry_records(chaos_dir)
        chaos = train_losses(chaos_records)
        fault_kinds = {r.get("fault") for r in chaos_records
                       if r.get("kind") == "fault"}
        check("injected_die" in fault_kinds,
              f"no injected_die fault record (saw {sorted(fault_kinds)})")
        if args.shard_errors:
            check("injected_shard_error" in fault_kinds,
                  "no injected_shard_error fault record")
            check("shard_read_retry" in fault_kinds,
                  "retry wrapper emitted no shard_read_retry record")
        compare_trajectories(
            ref, chaos, range(1, args.die_at), args.loss_rtol,
            "pre-kill prefix (shard retries must not change the data)")

        # 3. corrupt the newest surviving checkpoint
        steps = ckpt_steps(chaos_dir)
        check(len(steps) >= 2,
              f"need >=2 retained checkpoints to corrupt+walk back, "
              f"have {steps}")
        newest, expect_resume = steps[-1], steps[-2]
        newest_path = os.path.join(
            chaos_dir, "pretrain_ckpts", f"ckpt_{newest}.msgpack")
        faults.corrupt_checkpoint(newest_path, args.corrupt_mode)
        status, detail = integrity.verify_checkpoint(newest_path)
        check(status == integrity.CORRUPT,
              f"corruption undetected: {status} ({detail})")
        verdict.update(corrupted_step=newest, resume_step=expect_resume)

        # 4. resume: walk back past the corruption, finish, match
        rc = run_child(args, chaos_dir)
        check(rc == 0, f"resume run failed (rc {rc})")
        records = telemetry_records(chaos_dir)
        resumes = [r for r in records if r.get("kind") == "resume"]
        check(resumes, "resume run emitted no resume record")
        resume = resumes[-1]
        check(int(resume["step"]) == expect_resume,
              f"resumed from step {resume['step']}, expected "
              f"{expect_resume} (walk-back past corrupt {newest})")
        skipped_steps = [int(e["step"]) for e in resume["skipped"]]
        check(newest in skipped_steps,
              f"resume record does not name corrupt step {newest} "
              f"(skipped: {skipped_steps})")
        resumed = train_losses(records)
        compare_trajectories(
            ref, resumed, range(expect_resume + 1, args.steps + 1),
            args.loss_rtol, "post-resume trajectory")

        # 5. both artifacts schema-clean
        lint(ref_dir)
        lint(chaos_dir)

        verdict.update(ok=True, skipped=resume["skipped"],
                       compared_steps=args.steps - expect_resume)
        print(json.dumps(verdict))
        if not args.workdir:
            shutil.rmtree(workdir, ignore_errors=True)
        return 0
    except (ChaosFailure, subprocess.TimeoutExpired, OSError,
            ValueError, KeyError) as exc:
        verdict.update(ok=False, error=f"{type(exc).__name__}: {exc}")
        print(json.dumps(verdict))
        print(f"chaos_run: FAILED — artifacts kept in {workdir}",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
