#!/usr/bin/env python
"""Fleet chaos harness: kill, wedge, and drain-kill serving replicas
under live traffic — and PROVE no client ever saw it
(docs/serving.md "Fleet tier", docs/fault_tolerance.md "Serve failover").

The serving resilience layer's acceptance gate, the serve analog of
``tools/chaos_run.py``. One invocation stands up the real fleet — a
:class:`Supervisor` owning N ``run_server.py`` replica subprocesses
(each warmed from one shared persistent AOT compile cache) behind a
:class:`Router` front tier — then drives a closed-loop client burst
through the router while injecting, in sequence:

1. **SIGKILL inside the admission window** — replica 0 is armed with
   ``admit_hold@N`` (testing/faults.py): its pipelined assembler emits
   an injection record and then HOLDS its forming batch open inside
   the admission window; the harness waits for that record and kills
   the replica while requests are provably captive in the forming
   batch (the continuous-batching stage a flush-then-wait server never
   had). The router's transport failures fail over to a different
   replica inside the retry budget; the supervisor reaps the exit and
   respawns with crash backoff; the restarted replica must report
   ``compiles_cold == 0`` (PR 8's warm-restart property is what makes
   seconds-scale recovery real);
2. **wedged dispatch** — a replica armed with ``BERT_FAULTS=wedge@N``
   hangs its dispatch thread while ``/healthz`` keeps answering 200.
   Only the supervisor's heartbeat watchdog can catch this; meanwhile
   the router's hedged requests keep the stuck replica's traffic inside
   the latency budget until the watchdog kills it;
3. **kill during drain** — SIGTERM (graceful drain) followed by SIGKILL
   mid-drain. Requests the dying replica never answered are retried
   elsewhere; the supervisor classifies the exit as a crash;
4. **SIGKILL mid-swap** (docs/serving.md "Model registry & canary
   rollouts") — the fleet's own init checkpoint is published into a
   model registry, a replica is armed with ``swap_hold@1`` (the fault
   holds the hot-swap open between the new params finishing their load
   and the atomic flip), and the harness SIGKILLs it inside that held
   window under load. The kill must be invisible: zero client
   failures, the respawned replica boots the baseline version (a
   half-applied swap is structurally impossible — the flip either
   happened or it did not), and ``torn_serves`` stays 0 everywhere.
   Then the whole fleet converges onto the published version via the
   supervisor's ``/swapz`` control calls with ZERO cold compiles — a
   same-geometry swap reuses the already-jitted executables, proven by
   the CompileMonitor's cache-counter events, never wall clock.

Acceptance, asserted per phase and overall: ZERO client-visible
failures (every request answers 2xx, except explicit brownout sheds —
503 carrying ``Retry-After``); failover latency p95 within
``--failover_tolerance_ms`` (the same number telemetry-report's
"router failover" gate regresses on); the supervisor's restart within
the backoff budget; and every artifact (router/fleet events + each
replica's serve telemetry) schema-clean.

End-to-end tracing acceptance (docs/observability.md "Trace
propagation") rides the same run: the router samples EVERY request
(``trace_sample_rate=1``) while the replicas keep their local head
sampling at 0 — so every serve_trace that appears proves the router's
decision won fleet-wide — and every response (including a replica
probed directly with an unsampled context) must echo
``X-Bert-Trace-Id``. Post-hoc, a :class:`FleetCollector` stitches the
router + replica sinks into one timeline and the harness asserts:
every sampled client request resolves to exactly ONE stitched trace
tree, zero orphan stitches, every complete stitch's decomposition is
``consistent`` (client_total >= router overhead + replica time), the
phase-A failover request's tree shows attempt 1 on the killed replica
chaining to the surviving replica's serve_trace on attempt 2, and
``tools/obs_collect.py --trace <id>`` prints that tree. Finally the
report gates are proven live: a copy of the timeline doctored with a
router-side delay makes ``telemetry-report`` exit 1 naming "router
overhead share" while the clean timeline self-diffs green.

Verdict is one JSON line on stdout; exit 0 = every assertion held.

``--smoke`` is the documented one-command local gate (2 replicas, small
bursts, sized for a throttled tier-1 CPU box)::

    python tools/chaos_serve.py --smoke

``--canary`` runs the deployment-plane E2E instead of the kill/wedge
phases: a 2-replica fleet serving version v1, a new version published
into the registry and rolled out 1% -> 50% -> 100% by a live
:class:`RolloutController` (real router splits, real ``/swapz`` hot
swaps, SLO verdicts from the canary cohort's own outcome windows, zero
client-visible failures), followed by a deliberately DEGRADED version
whose first canary window breaches its latency SLO and must
auto-rollback — and the report gate is proven live: the artifact
carrying the breach makes ``telemetry-report`` exit 1 naming "rollout
canary SLO" against the pre-breach baseline, while the baseline
self-diffs green.

``--surge`` runs the elasticity-plane E2E (docs/serving.md "Elastic
fleet"): a 1-replica fleet behind a live :class:`AutoscalerController`,
a closed-loop burst ramping past the replica's brownout ceiling ->
warm scale-up (``compiles_cold == 0`` from the shared AOT cache) ->
sheds stop and p99 recovers at the same offered load; a SIGKILL lands
mid-surge and is absorbed as the SAME capacity (respawn, not growth);
load drops -> green windows + the down cooldown drain the elastic
replica through the SIGTERM -> rc-75 contract with zero stranded
requests — and the "autoscaler thrash" / "surge client-visible errors"
gates are proven to fire on a seeded artifact.

The parent is deliberately jax-free: supervisor/router/schema load by
FILE PATH (tools/_bootstrap.py), so a hung accelerator runtime can hang
a REPLICA — which the watchdog kills — never the harness itself.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse

from _bootstrap import REPO_ROOT, load_by_path

schema = load_by_path(
    "_fleet_schema", "bert_pytorch_tpu", "telemetry", "schema.py")
supervisor_mod = load_by_path(
    "_fleet_supervisor", "bert_pytorch_tpu", "serve", "supervisor.py")
router_mod = load_by_path(
    "_fleet_router", "bert_pytorch_tpu", "serve", "router.py")
collector_mod = load_by_path(
    "_fleet_collector", "bert_pytorch_tpu", "telemetry", "collector.py")
faults = load_by_path(
    "_fleet_faults", "bert_pytorch_tpu", "testing", "faults.py")
synth = load_by_path(
    "_fleet_synth", "bert_pytorch_tpu", "tools", "make_synthetic_data.py")
registry_mod = load_by_path(
    "_fleet_registry", "bert_pytorch_tpu", "serve", "registry.py")
rollout_mod = load_by_path(
    "_fleet_rollout", "bert_pytorch_tpu", "serve", "rollout.py")
autoscaler_mod = load_by_path(
    "_fleet_autoscaler", "bert_pytorch_tpu", "serve", "autoscaler.py")

# Tiny fp32 model over the trace vocabulary: the gate's evidence is
# request outcomes and fleet/router records, not model quality — sized
# at the floor that still exercises the full serve path (tokenize ->
# batch -> jitted forward -> postprocess) so replica warmup stays
# seconds, not minutes, on a throttled CPU.
def model_config() -> dict:
    vocab = 5 + len(synth.TRACE_WORDS)
    vocab += (8 - vocab % 8) % 8
    return {
        "vocab_size": vocab, "hidden_size": 16, "num_hidden_layers": 1,
        "num_attention_heads": 2, "intermediate_size": 32,
        "max_position_embeddings": 32, "type_vocab_size": 2,
        "next_sentence": True, "mask_token_id": 4,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
    }


PHRASES = (
    "paris is big", "the river runs through london",
    "william shakespeare wrote hamlet", "england is old",
    "the capital of france is paris", "hamlet was wrote in london",
)


class ChaosFailure(AssertionError):
    pass


def check(cond, what):
    if not cond:
        raise ChaosFailure(what)


class Sink:
    """Thread-safe schema-v1 JSONL sink + in-memory event index.

    The supervisor's monitor thread and every router request thread emit
    through ``write``; the harness polls ``count`` to sequence phases
    (e.g. "burst until the watchdog's wedged_kill lands"). Deliberately
    local: the package JSONLHandler imports the package chain on first
    write, which would drag jax into this jax-free parent.
    """

    def __init__(self, path: str):
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")
        self.records = []

    def write(self, record: dict) -> None:
        rec = {"schema": schema.SCHEMA_VERSION, "ts": round(time.time(), 3)}
        rec.update(record)
        with self._lock:
            self.records.append(rec)
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()

    def count(self, event: str) -> int:
        with self._lock:
            return sum(1 for r in self.records if r.get("event") == event)

    def close(self) -> None:
        with self._lock:
            self._f.close()


def make_spawn(log_dir: str):
    """A Popen factory that pins replicas to CPU jax, strips the test
    harness's virtual-device flag and any leaked fault spec from the
    inherited environment (spec.env re-arms faults deliberately), and
    tees replica output to a per-replica log for post-mortems."""

    def spawn(spec):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop(faults.FAULTS_ENV, None)
        xla = " ".join(
            flag for flag in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in flag)
        if xla:
            env["XLA_FLAGS"] = xla
        else:
            env.pop("XLA_FLAGS", None)
        if spec.env:
            env.update(spec.env)
        log = open(os.path.join(log_dir, f"replica_{spec.index}.log"), "ab")
        return subprocess.Popen(spec.cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT)

    return spawn


# -- the closed-loop client --------------------------------------------------

def post(url: str, task: str, payload: dict, timeout_s: float,
         extra_headers: dict = None):
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=timeout_s)
    headers = {"Content-Type": "application/json"}
    headers.update(extra_headers or {})
    try:
        conn.request("POST", f"/v1/{task}",
                     body=json.dumps(payload).encode("utf-8"),
                     headers=headers)
        resp = conn.getresponse()
        resp.read()
        return resp.status, dict(resp.getheaders())
    finally:
        conn.close()


def header(headers: dict, name: str):
    """Case-insensitive response-header lookup (http.client preserves
    whatever case the server sent)."""
    lower = name.lower()
    for key, value in headers.items():
        if key.lower() == lower:
            return value
    return None


def get_json(url: str, path: str, timeout_s: float = 5.0) -> dict:
    """GET an introspection endpoint (/statsz, /healthz) as JSON."""
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        check(resp.status == 200, f"GET {path} on {url} -> {resp.status}")
        return json.loads(body)
    finally:
        conn.close()


def get_text(url: str, path: str, timeout_s: float = 5.0) -> str:
    """GET a text endpoint (/metricsz)."""
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read().decode("utf-8", "replace")
        check(resp.status == 200, f"GET {path} on {url} -> {resp.status}")
        return body
    finally:
        conn.close()


def run_burst(url: str, total: int, workers: int, timeout_s: float,
              outcomes: list, should_stop=None, mid=None) -> None:
    """Closed-loop burst: ``workers`` threads issue requests until
    ``total`` have been sent (or ``should_stop()`` says enough — the
    wedge phase stops on the watchdog's event, not a count). Each
    outcome is appended to the shared ``outcomes`` list.

    ``mid=(count, callback)`` fires ``callback`` exactly once, from
    whichever worker completes outcome number ``count`` — the fault
    injection is sequenced INSIDE the burst, so it lands mid-flight no
    matter how fast the box drains the request quota."""
    lock = threading.Lock()
    issued = [0]
    mid_fired = [False]

    def worker() -> None:
        while True:
            if should_stop is not None and should_stop():
                return
            with lock:
                if issued[0] >= total:
                    return
                issued[0] += 1
                seq = issued[0]
            payload = {"text": PHRASES[seq % len(PHRASES)]}
            t0 = time.monotonic()
            try:
                status, headers = post(url, "classify", payload, timeout_s)
            except Exception as exc:
                status, headers = None, {
                    "error": f"{type(exc).__name__}: {exc}"}
            fire = False
            with lock:
                outcomes.append({
                    "status": status,
                    "retry_after": headers.get("Retry-After"),
                    # The router's minted trace id, echoed on EVERY
                    # response (sampled or not) — the correlation handle
                    # the post-hoc stitch assertions join on.
                    "trace_id": header(headers, "X-Bert-Trace-Id"),
                    "latency_s": round(time.monotonic() - t0, 4),
                })
                if (mid is not None and not mid_fired[0]
                        and len(outcomes) >= mid[0]):
                    mid_fired[0] = True
                    fire = True
            if fire:
                mid[1]()

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def classify_outcomes(outcomes: list) -> dict:
    """ok / shed / failure decomposition of one burst. A shed is an
    EXPLICIT admission-control answer — 503 carrying Retry-After;
    everything else non-2xx (including the router's own deadline 503,
    which has no Retry-After) is a client-visible failure."""
    ok = shed = 0
    failures = []
    for o in outcomes:
        if o["status"] is not None and 200 <= o["status"] < 300:
            ok += 1
        elif o["status"] == 503 and o.get("retry_after"):
            shed += 1
        else:
            failures.append(o)
    return {"requests": len(outcomes), "ok": ok, "sheds": shed,
            "failures": len(failures), "failure_samples": failures[:5],
            "traced": sum(1 for o in outcomes if o.get("trace_id"))}


def check_traced(outcomes: list, phase: str) -> None:
    """Every ANSWERED request — ok or shed, sampled or not — must carry
    the router's echoed trace id (the correlation contract): the only
    excusable blanks are transport-level failures that never produced a
    response at all."""
    untraced = [o for o in outcomes
                if o["status"] is not None and not o.get("trace_id")]
    check(not untraced,
          f"{phase}: {len(untraced)} answered requests carried no "
          f"X-Bert-Trace-Id response header: {untraced[:3]}")


def wait_until(pred, timeout_s: float, what: str, poll_s: float = 0.25):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise ChaosFailure(f"timed out after {timeout_s:g}s waiting for {what}")


def cold_start_records(out_dir: str) -> list:
    path = os.path.join(out_dir, "serve_telemetry.jsonl")
    records = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
                    if rec.get("kind") == "serve_cold_start":
                        records.append(rec)
    return records


def lint(path: str) -> None:
    errors = schema.validate_file(path)
    check(errors == [], f"schema lint failed for {path}: {errors[:3]}")


# -- the canary-rollout scenario ---------------------------------------------

def plan_burst(share: float, need: int, next_seq: int,
               minimum: int = 12) -> int:
    """Burst size whose canary-cohort membership yields at least
    ``need`` canary requests starting at router seq ``next_seq``.

    Cohort assignment is DETERMINISTIC — the router hashes its monotone
    request seq (serve/router.py ``_split_hash``) — so the harness can
    size each observation window exactly instead of waiting on luck for
    a 1% cohort to fill it."""
    n = 0
    hits = 0
    seq = next_seq
    while hits < need or n < minimum:
        if router_mod._split_hash(seq) < share:
            hits += 1
        n += 1
        seq += 1
        if n > 50000:
            raise ChaosFailure(
                f"no burst size under 50000 yields {need} canary "
                f"requests at share {share}")
    return n


def run_canary(args) -> int:
    """The deployment-plane E2E: registry publish -> canary swap ->
    SLO-gated 1% -> 50% -> 100% rollout -> promote, then a degraded
    version that must auto-rollback on its first full canary window —
    with zero client-visible failures throughout and the "rollout
    canary SLO" report gate proven to fire on the breach artifact."""
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_canary_")
    os.makedirs(workdir, exist_ok=True)
    cache_dir = os.path.join(workdir, "compile_cache")
    vocab_path = synth.write_trace_vocab(os.path.join(workdir, "vocab.txt"))
    config_path = os.path.join(workdir, "model.json")
    with open(config_path, "w") as f:
        json.dump(model_config(), f)

    shared_args = [
        "--model_config_file", config_path, "--vocab_file", vocab_path,
        "--tasks", "classify", "--classify_labels", "neg,pos",
        "--buckets", "16", "--max_batch_size", "4", "--max_wait_ms", "5",
        "--dtype", "float32", "--compile_cache_dir", cache_dir,
        "--trace_sample_rate", "0", "--telemetry_window", "16",
        "--request_timeout_s", "10", "--serving_version", "v1",
    ]
    template = supervisor_mod.ReplicaTemplate(shared_args, workdir)
    specs = []
    for i in range(args.replicas):
        extra_args = []
        if i == 0:
            extra_args = ["--save_init_checkpoint",
                          os.path.join(workdir, "init_ckpt")]
        specs.append(template.make_spec(i, extra_args=extra_args))

    fleet_jsonl = os.path.join(workdir, "fleet_telemetry.jsonl")
    sink = Sink(fleet_jsonl)
    sup = supervisor_mod.Supervisor(
        specs, emit=sink.write, spawn=make_spawn(workdir),
        policy=supervisor_mod.RetryPolicy(
            attempts=5, base_delay_s=0.4, max_delay_s=3.0,
            full_jitter=True),
        heartbeat_timeout_s=5.0,
        startup_grace_s=args.warmup_timeout_s,
        stable_reset_s=15.0, poll_interval_s=0.25, drain_grace_s=15.0)
    router = router_mod.Router(
        [s.url for s in specs], emit=sink.write, window=32,
        scrape_interval_s=0.25,
        deadline_s=args.router_deadline_s,
        retry_policy=router_mod.RetryPolicy(
            attempts=3, base_delay_s=0.05, max_delay_s=0.5,
            full_jitter=True),
        hedge_pctl=0.95, hedge_min_ms=30.0, hedge_min_samples=24,
        brownout_queue_depth=64, shed_retry_after_s=0.5,
        trace_sample_rate=1.0)
    router_server = router_mod.make_router_server(router, port=0)
    router_url = "http://%s:%d" % router_server.server_address[:2]

    t_start = time.monotonic()
    verdict = {"metric": "chaos_serve_canary_rollout",
               "workdir": workdir, "replicas": args.replicas,
               "router_url": router_url}
    canary_idx = args.replicas - 1

    def next_seq() -> int:
        # The router is in-process and quiescent between bursts, and
        # _mint_trace hands out the CURRENT counter value before
        # post-incrementing — so _trace_seq is exactly the next
        # request's cohort-hash input.
        return router._trace_seq

    def scrape_torn() -> int:
        total = 0
        for s in specs:
            try:
                total += int(get_json(s.url, "/statsz")
                             .get("torn_serves", 0))
            except (OSError, ValueError, ChaosFailure):
                pass
        return total

    def router_sees(idx: int, version: str) -> bool:
        return any(r["url"].endswith(f":{specs[idx].port}")
                   and r.get("version") == version and r["healthy"]
                   for r in router.snapshot()["replica_states"])

    def burst(n: int) -> dict:
        outcomes: list = []
        run_burst(router_url, n, args.burst_workers,
                  args.client_timeout_s, outcomes)
        summary = classify_outcomes(outcomes)
        check(summary["failures"] == 0,
              f"canary-mode burst saw client-visible failures: "
              f"{summary}")
        check_traced(outcomes, "canary burst")
        return summary

    try:
        sup.start()
        router.start()
        threading.Thread(target=router_server.serve_forever,
                         daemon=True).start()
        wait_until(lambda: router.healthy_count() == args.replicas,
                   args.warmup_timeout_s,
                   f"all {args.replicas} replicas healthy")

        # -- publish: the fleet's own init params become the registry's
        # versions (same geometry — the zero-compile swap property is
        # part of what this scenario proves).
        reg = registry_mod.ModelRegistry(
            os.path.join(workdir, "registry"), emit=sink.write)
        ckpt_src = os.path.join(workdir, "init_ckpt", "ckpt_0.msgpack")
        check(os.path.isfile(ckpt_src),
              "replica 0 wrote no init checkpoint "
              "(--save_init_checkpoint)")

        def publish(version: str) -> str:
            path = os.path.join(workdir, f"published_{version}.msgpack")
            shutil.copyfile(ckpt_src, path)
            reg.publish(version, task="classify", checkpoint=path,
                        geometry=registry_mod.geometry_from_config(
                            model_config()))
            return path

        publish("v1")
        reg.begin_canary("v1")
        reg.promote("v1")   # the audit trail starts at the booted truth
        ckpt_v2 = publish("v2")

        # -- happy path: v2 rolls 1% -> 50% -> 100% ---------------------
        info = sup.swap_replica(canary_idx, "classify", ckpt_v2, "v2")
        check(info.get("compiles_cold") == 0,
              f"canary-replica swap recompiled: {info}")
        wait_until(lambda: router_sees(canary_idx, "v2"), 15.0,
                   "router scrape to learn the canary replica's version")

        min_window = 3
        promoted = {"swapped": False}

        def on_promote() -> None:
            infos = sup.swap_all("classify", ckpt_v2, "v2",
                                 skip_indices=(canary_idx,))
            for i in infos:
                check(i.get("compiles_cold") == 0,
                      f"promote-swap recompiled: {i}")
            promoted["swapped"] = True

        ctrl = rollout_mod.RolloutController(
            router, reg, "classify", "v2",
            stages=(0.01, 0.50, 1.0),
            min_window_requests=min_window,
            green_windows_to_advance=1,
            error_budget=0.02,
            emit=sink.write, on_promote=on_promote,
            scrape_torn=scrape_torn)
        ctrl.start()
        windows = []
        for _ in range(8):
            status = ctrl.status()
            if status["state"] != "canary":
                break
            burst(plan_burst(status["share"], min_window, next_seq()))
            rec = ctrl.observe()
            windows.append({k: rec.get(k) for k in (
                "stage", "canary_share", "window_requests", "ok",
                "errors", "slo_ok", "action")})
            check(rec["action"] != "rollback",
                  f"happy-path rollout rolled back: {rec}")
        verdict["happy_windows"] = windows
        check(ctrl.status()["state"] == "promoted",
              f"rollout never promoted: {ctrl.status()} "
              f"(windows: {windows})")
        check(promoted["swapped"],
              "promotion never swapped the rest of the fleet")
        check(reg.get("v2")["state"] == "live",
              f"v2 not live after promote: {reg.get('v2')['state']}")
        check(reg.get("v1")["state"] == "retired",
              f"promote did not retire v1: {reg.get('v1')['state']}")
        for i in range(args.replicas):
            st = get_json(specs[i].url, "/statsz")
            check(st.get("version") == "v2",
                  f"replica {i} did not converge onto v2: "
                  f"{st.get('version')!r}")
        check(router.split_window() is None,
              "the split survived the promotion")

        # -- per-version counters: /metricsz and /statsz must render
        # the same snapshot (the no-drift contract).
        snap = router.snapshot()
        vreq = snap.get("version_requests") or {}
        check(vreq.get("v2", 0) > 0,
              f"router counted no v2 requests: {vreq}")
        metrics = get_text(router_url, "/metricsz")
        for version, count in sorted(vreq.items()):
            line = (f'bert_router_version_requests'
                    f'{{version="{version}"}} {count}')
            check(line in metrics,
                  f"/metricsz disagrees with the snapshot: missing "
                  f"{line!r}")
        stats = get_json(router_url, "/statsz")
        check(stats.get("version_requests") == vreq,
              f"/statsz version counters drifted from the snapshot: "
              f"{stats.get('version_requests')} != {vreq}")
        verdict["version_requests"] = vreq

        # -- degraded leg: v3 must breach and auto-rollback -------------
        ckpt_v3 = publish("v3")
        sup.swap_replica(canary_idx, "classify", ckpt_v3, "v3")
        wait_until(lambda: router_sees(canary_idx, "v3"), 15.0,
                   "router scrape to learn the degraded version")
        # The report gate's comparison point: everything up to (not
        # including) the breach.
        baseline_jsonl = os.path.join(
            workdir, "fleet_telemetry.baseline.jsonl")
        shutil.copyfile(fleet_jsonl, baseline_jsonl)

        rolled = {"reason": None}

        def on_rollback(reason: str) -> None:
            rolled["reason"] = reason
            sup.swap_replica(canary_idx, "classify", ckpt_v2, "v2")

        ctrl2 = rollout_mod.RolloutController(
            router, reg, "classify", "v3",
            stages=(0.01, 0.50, 1.0),
            min_window_requests=2, green_windows_to_advance=1,
            # An unmeetable latency SLO stands in for a degraded model:
            # the first full canary window MUST breach.
            slo_p95_ms=0.001, error_budget=0.5,
            emit=sink.write, on_rollback=on_rollback,
            scrape_torn=scrape_torn)
        ctrl2.start()
        burst(plan_burst(0.01, 2, next_seq()))
        rec = ctrl2.observe()
        verdict["degraded_window"] = {k: rec.get(k) for k in (
            "action", "slo_ok", "reason", "window_requests")}
        check(rec["action"] == "rollback" and rec["slo_ok"] is False,
              f"degraded canary did not roll back: {rec}")
        check("p95" in (rec.get("reason") or ""),
              f"rollback reason does not name the breached SLO: {rec}")
        check(ctrl2.status()["state"] == "rolled_back",
              f"controller not terminal after rollback: "
              f"{ctrl2.status()}")
        check(rolled["reason"], "on_rollback never fired")
        check(reg.get("v3")["state"] == "staged",
              f"v3 not rolled back to staged: {reg.get('v3')['state']}")
        check(router.split_window() is None,
              "the split survived the rollback")
        wait_until(lambda: router_sees(canary_idx, "v2"), 15.0,
                   "canary replica swapped back to v2 after rollback")
        burst(12)   # the fleet still serves, on the old version
        torn = scrape_torn()
        check(torn == 0, f"torn-model serves recorded: {torn}")
        verdict["torn_serves"] = torn

        # -- teardown + artifacts ---------------------------------------
        drain = sup.stop()
        router_server.shutdown()
        router.stop()
        check(drain["drain_killed"] == 0,
              f"a replica ignored the drain SIGTERM: {drain}")
        sink.close()
        lint(fleet_jsonl)
        lint(baseline_jsonl)
        for i in range(args.replicas):
            lint(os.path.join(workdir, f"replica_{i}",
                              "serve_telemetry.jsonl"))

        # -- the report gate, proven live -------------------------------
        # The artifact carrying the breach must trip "rollout canary
        # SLO" against the pre-breach baseline; the baseline self-diffs
        # green (the gate is proven to FIRE, not just to exist).
        report_tool = os.path.join(REPO_ROOT, "tools",
                                   "telemetry_report.py")
        bad = subprocess.run(
            [sys.executable, report_tool, fleet_jsonl, baseline_jsonl],
            capture_output=True, text=True)
        check(bad.returncode == 1
              and "rollout canary SLO" in bad.stdout,
              f"the canary breach did not trip the 'rollout canary "
              f"SLO' gate (rc {bad.returncode}):\n{bad.stdout}")
        clean = subprocess.run(
            [sys.executable, report_tool, baseline_jsonl,
             baseline_jsonl],
            capture_output=True, text=True)
        check(clean.returncode == 0,
              f"pre-breach baseline failed its own self-diff (rc "
              f"{clean.returncode}):\n{clean.stdout}")
        verdict["report_gate"] = {"breach_rc": bad.returncode,
                                  "clean_rc": clean.returncode}

        verdict.update(ok=True,
                       wall_s=round(time.monotonic() - t_start, 1))
        print(json.dumps(verdict))
        if not args.workdir:
            shutil.rmtree(workdir, ignore_errors=True)
        return 0
    except (ChaosFailure, OSError, ValueError, KeyError,
            RuntimeError) as exc:
        verdict.update(ok=False, error=f"{type(exc).__name__}: {exc}")
        try:
            sup.stop()
            router_server.shutdown()
            router.stop()
        except Exception:
            pass
        print(json.dumps(verdict))
        print(f"chaos_serve --canary: FAILED — artifacts kept in "
              f"{workdir}", file=sys.stderr)
        return 1


# -- the surge (elastic capacity) scenario -----------------------------------

def run_surge(args) -> int:
    """The elasticity-plane E2E (docs/serving.md "Elastic fleet"): a
    1-replica fleet behind the router, driven by a live
    :class:`AutoscalerController`.

    Sequence: a closed-loop burst ramps past the seed replica's
    capacity (a deliberately LOW brownout ceiling makes "past capacity"
    mean explicit sheds, deterministically, on any box) -> the
    controller's red windows accumulate and it scales up -> the elastic
    replica warms from the shared AOT cache (``compiles_cold == 0``,
    cache counter events are the authority) -> sheds stop and p99
    recovers at the SAME offered load. A SIGKILL lands mid-surge on the
    seed replica: its respawn is the same capacity, never growth (the
    membership chain lint would catch a double-count) and must not
    block correctness. Load then drops to a trickle -> green windows +
    the down cooldown -> scale-down drains the ELASTIC replica through
    the SIGTERM -> rc-75 contract (reaped without respawn, router
    target removed only after the supervisor confirms) with the trickle
    still being answered — zero stranded requests. Zero client-visible
    failures across every phase, and both elasticity report gates
    ("autoscaler thrash", "surge client-visible errors") are proven to
    FIRE on a seeded artifact while the real one self-diffs green.

    The harness drives ``ctrl.tick()`` itself instead of ``start()`` —
    phase boundaries stay deterministic, and every verdict lands in the
    same sink the lint replays."""
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_surge_")
    os.makedirs(workdir, exist_ok=True)
    cache_dir = os.path.join(workdir, "compile_cache")
    vocab_path = synth.write_trace_vocab(os.path.join(workdir, "vocab.txt"))
    config_path = os.path.join(workdir, "model.json")
    with open(config_path, "w") as f:
        json.dump(model_config(), f)

    shared_args = [
        "--model_config_file", config_path, "--vocab_file", vocab_path,
        "--tasks", "classify", "--classify_labels", "neg,pos",
        "--buckets", "16", "--max_batch_size", "2", "--max_wait_ms", "5",
        "--dtype", "float32", "--compile_cache_dir", cache_dir,
        "--trace_sample_rate", "0", "--telemetry_window", "16",
        "--request_timeout_s", "10", "--serving_version", "v1",
    ]
    template = supervisor_mod.ReplicaTemplate(shared_args, workdir)
    specs = [template.make_spec(0)]

    fleet_jsonl = os.path.join(workdir, "fleet_telemetry.jsonl")
    sink = Sink(fleet_jsonl)
    sup = supervisor_mod.Supervisor(
        specs, emit=sink.write, spawn=make_spawn(workdir),
        policy=supervisor_mod.RetryPolicy(
            attempts=5, base_delay_s=0.4, max_delay_s=3.0,
            full_jitter=True),
        heartbeat_timeout_s=5.0,
        startup_grace_s=args.warmup_timeout_s,
        stable_reset_s=15.0, poll_interval_s=0.25, drain_grace_s=15.0)
    router = router_mod.Router(
        [s.url for s in specs], emit=sink.write, window=32,
        scrape_interval_s=0.2,
        deadline_s=args.router_deadline_s,
        retry_policy=router_mod.RetryPolicy(
            attempts=3, base_delay_s=0.05, max_delay_s=0.5,
            full_jitter=True),
        # Hedging off (unreachable sample floor): hedges ADD load, and
        # this scenario needs the offered load to be exactly what the
        # burst issues so "past one replica's capacity" is the
        # brownout ceiling, nothing else.
        hedge_pctl=0.95, hedge_min_ms=30.0, hedge_min_samples=10**6,
        brownout_queue_depth=args.surge_brownout_depth,
        shed_retry_after_s=0.2,
        trace_sample_rate=1.0)
    router_server = router_mod.make_router_server(router, port=0)
    router_url = "http://%s:%d" % router_server.server_address[:2]

    # The control loop under test. Signals are the router's own
    # windowed deltas (sheds/errors/requests + the scraped unfinished
    # gauge); the /statsz phases probe (queue-wait share, budget burn)
    # is a RUN-LEVEL rollup — cumulative, so a post-surge fleet would
    # never read "idle" again — and is exercised by the fake-fleet
    # units instead.
    fleet = autoscaler_mod.ElasticFleet(sup, router, template)
    signals = autoscaler_mod.RouterSignals(router)
    ctrl = autoscaler_mod.AutoscalerController(
        fleet, signals,
        min_replicas=1, max_replicas=2,
        red_windows_to_scale_up=2,
        green_windows_to_scale_down=4,
        up_cooldown_s=2.0, down_cooldown_s=args.surge_down_cooldown_s,
        min_window_requests=4,
        unfinished_high_per_replica=float(args.surge_brownout_depth),
        unfinished_low_per_replica=2.0,
        emit=sink.write)

    t_start = time.monotonic()
    verdict = {"metric": "chaos_serve_surge", "workdir": workdir,
               "router_url": router_url}

    def tick_until(pred, timeout_s: float, what: str,
                   tick_s: float = 0.3) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            ctrl.tick()
            if pred():
                return
            time.sleep(tick_s)
        raise ChaosFailure(
            f"timed out after {timeout_s:g}s waiting for {what} "
            f"(controller: {ctrl.status()})")

    def p99_ok_latency(outcomes: list):
        oks = sorted(o["latency_s"] for o in outcomes
                     if o["status"] is not None
                     and 200 <= o["status"] < 300)
        if not oks:
            return None
        return oks[min(len(oks) - 1, int(0.99 * len(oks)))]

    try:
        sup.start()
        router.start()
        threading.Thread(target=router_server.serve_forever,
                         daemon=True).start()
        wait_until(lambda: router.healthy_count() == 1,
                   args.warmup_timeout_s, "the seed replica healthy")

        # -- phase 1: surge past one replica's capacity -> scale up -----
        surge_stop = {"flag": False}
        outcomes_surge: list = []
        burst_thread = threading.Thread(
            target=run_burst,
            args=(router_url, 10**9, args.surge_workers,
                  args.client_timeout_s, outcomes_surge),
            kwargs={"should_stop": lambda: surge_stop["flag"]},
            daemon=True)
        burst_thread.start()
        tick_until(lambda: ctrl.status()["scale_ups"] >= 1,
                   args.recover_timeout_s,
                   "the controller to scale up under the surge")
        tick_until(lambda: router.healthy_count() == 2,
                   args.recover_timeout_s,
                   "the elastic replica healthy behind the router")
        elastic_idx = max(st["replica"] for st in sup.status())
        check(elastic_idx >= 1,
              f"scale-up minted no fresh replica index: {sup.status()}")

        # The warm-elasticity acceptance: the elastic replica booted
        # from the shared AOT cache with ZERO cold compiles — the cache
        # counter events are the authority, never wall clock. This is
        # the property that makes reactive scaling viable at all.
        colds = cold_start_records(
            os.path.join(workdir, f"replica_{elastic_idx}"))
        check(colds, f"elastic replica {elastic_idx} emitted no "
                     f"serve_cold_start record")
        verdict["elastic_compiles_cold"] = colds[-1]["compiles_cold"]
        check(colds[-1]["compiles_cold"] == 0,
              f"elastic replica compiled cold: {colds[-1]}")

        # -- phase 2: SIGKILL mid-surge — same capacity, never growth ---
        seed_pid = sup.status()[0]["pid"]
        check(seed_pid, "seed replica has no pid mid-surge")
        os.kill(seed_pid, signal.SIGKILL)
        tick_until(
            lambda: sup.status()[0]["state"] == supervisor_mod.RUNNING
            and router.healthy_count() == 2,
            args.recover_timeout_s,
            "the SIGKILLed seed replica respawned and healthy")
        check(ctrl.status()["scale_downs"] == 0,
              "the mid-surge SIGKILL triggered a scale-down: "
              f"{ctrl.status()}")

        surge_stop["flag"] = True
        burst_thread.join(timeout=60.0)
        check(not burst_thread.is_alive(), "surge burst never drained")
        phase_surge = classify_outcomes(outcomes_surge)
        verdict["phase_surge"] = phase_surge
        check(phase_surge["failures"] == 0,
              f"surge phase: client-visible failures: {phase_surge}")
        check(phase_surge["sheds"] > 0,
              "the surge never shed — the burst did not ramp past one "
              "replica's capacity (lower --surge_brownout_depth or "
              "raise --surge_workers)")
        check_traced(outcomes_surge, "surge")
        p99_surge = p99_ok_latency(outcomes_surge)

        # -- phase 3: same offered load, doubled capacity ---------------
        outcomes_post: list = []
        post_thread = threading.Thread(
            target=run_burst,
            args=(router_url, args.surge_recovery_requests,
                  args.surge_workers, args.client_timeout_s,
                  outcomes_post),
            daemon=True)
        post_thread.start()
        while post_thread.is_alive():
            ctrl.tick()     # the loop keeps running; no thrash allowed
            time.sleep(0.3)
        post_thread.join()
        phase_post = classify_outcomes(outcomes_post)
        verdict["phase_post"] = phase_post
        check(phase_post["failures"] == 0,
              f"post-scale phase: client-visible failures: {phase_post}")
        check(phase_post["sheds"] == 0,
              f"sheds did not stop after the scale-up: {phase_post}")
        check_traced(outcomes_post, "post-scale")
        p99_post = p99_ok_latency(outcomes_post)
        verdict["p99_surge_s"] = p99_surge
        verdict["p99_post_s"] = p99_post
        check(p99_surge is not None and p99_post is not None,
              "no ok-latency percentile to compare")
        check(p99_post < p99_surge,
              f"p99 did not recover after the scale-up: "
              f"{p99_post:.3f}s >= {p99_surge:.3f}s")

        # -- phase 4: load drops -> graceful scale-down under traffic ---
        trickle_stop = {"flag": False}
        outcomes_trickle: list = []
        trickle_thread = threading.Thread(
            target=run_burst,
            args=(router_url, 10**9, 1, args.client_timeout_s,
                  outcomes_trickle),
            kwargs={"should_stop": lambda: trickle_stop["flag"]},
            daemon=True)
        trickle_thread.start()
        tick_until(lambda: ctrl.status()["scale_downs"] >= 1,
                   args.recover_timeout_s,
                   "green windows + down cooldown to trigger scale-down")
        tick_until(lambda: router.replica_count() == 1,
                   args.recover_timeout_s,
                   "the drain to complete and the router target removed")
        trickle_stop["flag"] = True
        trickle_thread.join(timeout=60.0)
        phase_trickle = classify_outcomes(outcomes_trickle)
        verdict["phase_trickle"] = phase_trickle
        check(phase_trickle["failures"] == 0,
              f"scale-down stranded requests (client-visible failures "
              f"during the drain): {phase_trickle}")
        check_traced(outcomes_trickle, "trickle")

        # The drain contract: the ELASTIC replica (highest index) exits
        # EXIT_PREEMPTED on SIGTERM, is reaped WITHOUT respawn, and its
        # slot stays retired.
        drains = [r for r in sink.records
                  if r.get("event") == "drain_complete"]
        check(drains, "no drain_complete fleet_event recorded")
        check(drains[-1].get("replica") == elastic_idx,
              f"scale-down drained the wrong replica: {drains[-1]} "
              f"(expected the elastic replica {elastic_idx})")
        check(drains[-1].get("rc") == supervisor_mod.EXIT_PREEMPTED,
              f"drained replica did not exit EXIT_PREEMPTED: "
              f"{drains[-1]} (the run_server preemption contract)")
        st = next(s for s in sup.status()
                  if s["replica"] == elastic_idx)
        check(st["state"] == supervisor_mod.STOPPED and st["draining"],
              f"drained replica not reaped as a retired slot: {st}")

        # -- the membership + hysteresis verdicts -----------------------
        ctrl_status = ctrl.status()
        verdict["controller"] = ctrl_status
        check(ctrl_status["thrash"] == 0,
              f"autoscaler thrash recorded: {ctrl_status}")
        check(ctrl_status["scale_ups"] == 1
              and ctrl_status["scale_downs"] == 1,
              f"expected exactly one scale-up and one scale-down: "
              f"{ctrl_status}")
        scale_events = [r for r in sink.records
                        if r.get("kind") == "scale_event"]
        check(scale_events, "the controller emitted no scale_event")
        check(max(int(r["replicas_after"]) for r in scale_events) <= 2,
              "a scale_event reports capacity above the band — the "
              f"SIGKILL respawn was double-counted: {scale_events}")
        check(all(int(r.get("exogenous", 0)) == 0
                  for r in scale_events),
              "unexplained exogenous membership drift — the SIGKILL "
              "respawn was double-counted as capacity change: "
              f"{[r for r in scale_events if r.get('exogenous')]}")

        # -- teardown + artifacts ---------------------------------------
        drain = sup.stop()
        router_server.shutdown()
        router.stop()
        check(drain["drain_killed"] == 0,
              f"a replica ignored the drain SIGTERM: {drain}")
        sink.close()
        # validate_file replays the scale_event membership chain — the
        # "reconstructible from the event stream" acceptance rides this
        # lint, not just the in-memory asserts above.
        lint(fleet_jsonl)
        for idx in sorted({s["replica"] for s in sup.status()}):
            lint(os.path.join(workdir, f"replica_{idx}",
                              "serve_telemetry.jsonl"))

        # -- both elasticity report gates, proven live ------------------
        # A copy of the artifact seeded with one impossible record — a
        # direction flip inside its cooldown window that also carries
        # client-visible errors — must make telemetry-report exit 1
        # naming BOTH gates, while the clean artifact self-diffs green.
        breach_path = os.path.join(
            workdir, "fleet_telemetry.breach.jsonl")
        shutil.copyfile(fleet_jsonl, breach_path)
        with open(breach_path, "a", encoding="utf-8") as f:
            f.write(json.dumps({
                "schema": schema.SCHEMA_VERSION,
                "ts": round(time.time(), 3),
                "kind": "scale_event", "tag": "autoscale",
                "decision": "scale_up",
                "reason": "red_windows:sheds=3",
                "replicas_before": 1, "replicas_after": 2,
                "exogenous": 0, "healthy": 1, "reds": 2, "greens": 0,
                "window_requests": 9, "window_errors": 3,
                "window_sheds": 3,
                "cooldown_s": 2.0, "since_last_scale_s": 0.1}) + "\n")
        report_tool = os.path.join(REPO_ROOT, "tools",
                                   "telemetry_report.py")
        bad = subprocess.run(
            [sys.executable, report_tool, breach_path, fleet_jsonl],
            capture_output=True, text=True)
        check(bad.returncode == 1
              and "autoscaler thrash" in bad.stdout
              and "surge client-visible errors" in bad.stdout,
              f"the seeded violation did not trip both elasticity "
              f"gates (rc {bad.returncode}):\n{bad.stdout}")
        clean = subprocess.run(
            [sys.executable, report_tool, fleet_jsonl, fleet_jsonl],
            capture_output=True, text=True)
        check(clean.returncode == 0,
              f"clean surge artifact failed its own self-diff (rc "
              f"{clean.returncode}):\n{clean.stdout}")
        verdict["report_gate"] = {"breach_rc": bad.returncode,
                                  "clean_rc": clean.returncode}

        verdict.update(ok=True,
                       wall_s=round(time.monotonic() - t_start, 1))
        print(json.dumps(verdict))
        if not args.workdir:
            shutil.rmtree(workdir, ignore_errors=True)
        return 0
    except (ChaosFailure, OSError, ValueError, KeyError,
            RuntimeError) as exc:
        verdict.update(ok=False, error=f"{type(exc).__name__}: {exc}")
        try:
            sup.stop()
            router_server.shutdown()
            router.stop()
        except Exception:
            pass
        print(json.dumps(verdict))
        print(f"chaos_serve --surge: FAILED — artifacts kept in "
              f"{workdir}", file=sys.stderr)
        return 1


# -- the scenario ------------------------------------------------------------

def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="replica kill/wedge/drain-kill chaos harness for the "
                    "serving fleet tier")
    parser.add_argument("--smoke", action="store_true",
                        help="the one-command local gate: 2 replicas, "
                             "small bursts, tier-1-budget-sized")
    parser.add_argument("--canary", action="store_true",
                        help="run the deployment-plane E2E (registry "
                             "publish + SLO-gated 1%%->50%%->100%% "
                             "rollout + degraded-version auto-rollback) "
                             "instead of the kill/wedge phases")
    parser.add_argument("--surge", action="store_true",
                        help="run the elasticity-plane E2E (autoscaler "
                             "scale-up under a shedding surge, SIGKILL "
                             "mid-surge, graceful rc-75 scale-down) "
                             "instead of the kill/wedge phases")
    parser.add_argument("--surge_workers", type=int, default=10,
                        help="closed-loop client threads for the surge "
                             "burst (must overwhelm ONE replica's "
                             "brownout ceiling, not two)")
    parser.add_argument("--surge_brownout_depth", type=int, default=6,
                        help="router brownout queue ceiling per replica "
                             "in surge mode — the definition of one "
                             "replica's capacity")
    parser.add_argument("--surge_recovery_requests", type=int, default=60,
                        help="burst size for the post-scale-up recovery "
                             "phase (same worker count as the surge)")
    parser.add_argument("--surge_down_cooldown_s", type=float, default=6.0,
                        help="the controller's scale-down cooldown in "
                             "surge mode (the slow, cautious direction)")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--burst_workers", type=int, default=4)
    parser.add_argument("--phase_a_requests", type=int, default=None,
                        help="burst size for the SIGKILL phase "
                             "(default 60; 50 under --smoke)")
    parser.add_argument("--phase_c_requests", type=int, default=30)
    parser.add_argument("--phase_d_requests", type=int, default=24,
                        help="burst size for the SIGKILL-mid-swap phase")
    parser.add_argument("--wedge_at", type=int, default=100,
                        help="requests the wedge replica serves before "
                             "its dispatch thread hangs (BERT_FAULTS "
                             "wedge@N; must exceed its phase-A share)")
    parser.add_argument("--wedge_cap_requests", type=int, default=600,
                        help="phase-B safety cap: the wedge MUST fire "
                             "before this many burst requests")
    parser.add_argument("--router_deadline_s", type=float, default=8.0)
    parser.add_argument("--failover_tolerance_ms", type=float, default=8000.0,
                        help="failover-latency p95 budget — the same "
                             "tolerance telemetry-report's 'router "
                             "failover' gate regresses on")
    parser.add_argument("--warmup_timeout_s", type=float, default=240.0)
    parser.add_argument("--recover_timeout_s", type=float, default=120.0,
                        help="budget for a killed replica to be respawned "
                             "AND healthy again (backoff + warm start)")
    parser.add_argument("--client_timeout_s", type=float, default=15.0)
    parser.add_argument("--workdir", type=str, default="",
                        help="keep artifacts here (default: a fresh temp "
                             "dir, removed on success)")
    args = parser.parse_args(argv)
    args.phase_a_requests = args.phase_a_requests or (
        50 if args.smoke else 60)
    if args.canary:
        return run_canary(args)
    if args.surge:
        return run_surge(args)

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_serve_")
    os.makedirs(workdir, exist_ok=True)
    cache_dir = os.path.join(workdir, "compile_cache")
    vocab_path = synth.write_trace_vocab(os.path.join(workdir, "vocab.txt"))
    config_path = os.path.join(workdir, "model.json")
    with open(config_path, "w") as f:
        json.dump(model_config(), f)

    # One ReplicaSpec per replica: shared model/cache flags, its own
    # port + output dir (telemetry JSONL and the heartbeat file the
    # supervisor watches live under it). The LAST replica is armed with
    # the wedge fault — it hangs only after serving --wedge_at requests,
    # so phases A (SIGKILL) and B (wedge) stay sequenced. Replica 0 is
    # armed with admit_hold@2x6: on its SECOND formed batch the
    # assembler emits the injection record and holds the admission
    # window open for 6s — the cue (and the window) for phase A's
    # SIGKILL-with-requests-in-the-forming-batch.
    shared_args = [
        "--model_config_file", config_path, "--vocab_file", vocab_path,
        "--tasks", "classify", "--classify_labels", "neg,pos",
        "--buckets", "16", "--max_batch_size", "4", "--max_wait_ms", "5",
        "--dtype", "float32", "--compile_cache_dir", cache_dir,
        "--trace_sample_rate", "0", "--telemetry_window", "16",
        "--request_timeout_s", "10", "--serving_version", "v1",
    ]
    template = supervisor_mod.ReplicaTemplate(shared_args, workdir)
    specs = []
    for i in range(args.replicas):
        env = {}
        extra_args = []
        if i == args.replicas - 1:
            env[faults.FAULTS_ENV] = f"wedge@{args.wedge_at}"
        elif i == 0:
            env[faults.FAULTS_ENV] = "admit_hold@2x6"
        if i == 0:
            # Replica 0 writes its freshly-initialized params as a real
            # msgpack checkpoint before serving — the blob phase D
            # publishes into the registry and swaps the fleet to (the
            # jax-free parent can't produce one itself).
            extra_args = ["--save_init_checkpoint",
                          os.path.join(workdir, "init_ckpt")]
        specs.append(template.make_spec(i, extra_args=extra_args, env=env))

    sink = Sink(os.path.join(workdir, "fleet_telemetry.jsonl"))
    sup = supervisor_mod.Supervisor(
        specs, emit=sink.write, spawn=make_spawn(workdir),
        policy=supervisor_mod.RetryPolicy(
            attempts=5, base_delay_s=0.4, max_delay_s=3.0,
            full_jitter=True),
        heartbeat_timeout_s=5.0,
        startup_grace_s=args.warmup_timeout_s,
        stable_reset_s=15.0, poll_interval_s=0.25, drain_grace_s=15.0)
    router = router_mod.Router(
        [s.url for s in specs], emit=sink.write, window=32,
        scrape_interval_s=0.25,
        deadline_s=args.router_deadline_s,
        retry_policy=router_mod.RetryPolicy(
            attempts=3, base_delay_s=0.05, max_delay_s=0.5,
            full_jitter=True),
        hedge_pctl=0.95, hedge_min_ms=30.0, hedge_min_samples=24,
        brownout_queue_depth=64, shed_retry_after_s=0.5,
        # Sample EVERYTHING at the router while the replicas keep their
        # local head rate at 0 (shared_args): every serve_trace that
        # shows up proves the router's sampling decision won fleet-wide,
        # and every client request gets a stitchable trace tree.
        trace_sample_rate=1.0)
    router_server = router_mod.make_router_server(router, port=0)
    router_url = "http://%s:%d" % router_server.server_address[:2]

    t_start = time.monotonic()
    verdict = {"metric": "chaos_serve_fleet_failover", "workdir": workdir,
               "replicas": args.replicas, "router_url": router_url}
    wedge_idx = args.replicas - 1

    def state_of(idx):
        return sup.status()[idx]

    def healthy(idx):
        st = state_of(idx)
        return (st["state"] == supervisor_mod.RUNNING
                and router.healthy_count() >= 1
                and any(r["healthy"] and r["url"].endswith(
                    f":{specs[idx].port}")
                        for r in router.snapshot()["replica_states"]))

    try:
        sup.start()
        router.start()
        threading.Thread(target=router_server.serve_forever,
                         daemon=True).start()
        wait_until(lambda: router.healthy_count() == args.replicas,
                   args.warmup_timeout_s,
                   f"all {args.replicas} replicas healthy")

        # Replica-side echo, decoupled from sampling: probe a replica
        # DIRECTLY with an unsampled trace context. The response must
        # echo the trace id even though sampled=0 means no serve_trace
        # will be exported for it — correlation must never depend on
        # the sampling decision.
        st, hdrs = post(specs[0].url, "classify",
                        {"text": PHRASES[0]}, args.client_timeout_s,
                        extra_headers={
                            "X-Bert-Trace": "chaos-probe-1;attempt=1;"
                                            "sampled=0"})
        check(st == 200, f"direct replica probe failed: {st}")
        check(header(hdrs, "X-Bert-Trace-Id") == "chaos-probe-1",
              "replica did not echo X-Bert-Trace-Id for an UNSAMPLED "
              f"context (got {header(hdrs, 'X-Bert-Trace-Id')!r}): the "
              "echo must not depend on the sampling decision")

        # -- phase A: SIGKILL inside the admission window ----------------
        # Replica 0's armed admit_hold@2x6 emits its injection record
        # and then HOLDS the forming batch open; the kill callback waits
        # for the record and kills during the hold, so the process dies
        # with requests captive in the admission window — the stranded
        # shape that only exists under pipelined (continuous-batching)
        # dispatch. Those requests' clients must still see answers
        # (failover), like every other phase.
        outcomes_a: list = []
        kill_at = {"t": None, "admit_hold_observed": False}
        replica0_jsonl = os.path.join(
            workdir, "replica_0", "serve_telemetry.jsonl")

        def admit_hold_recorded() -> bool:
            try:
                with open(replica0_jsonl) as f:
                    return any('"injected_admit_hold"' in line for line in f)
            except OSError:
                return False

        def kill_replica_0() -> None:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if admit_hold_recorded():
                    kill_at["admit_hold_observed"] = True
                    break
                time.sleep(0.2)
            pid = state_of(0)["pid"]
            kill_at["t"] = time.monotonic()
            if pid:
                os.kill(pid, signal.SIGKILL)
            # The respawned replica must not re-arm the hold: spec.env
            # re-arms deliberately (the wedge depends on it), but a
            # second 6s hold would just add tail latency to phases B/C.
            specs[0].env.pop(faults.FAULTS_ENV, None)

        run_burst(router_url, args.phase_a_requests, args.burst_workers,
                  args.client_timeout_s, outcomes_a,
                  mid=(2, kill_replica_0))
        t_kill = kill_at["t"]
        check(t_kill is not None, "phase-A kill never fired")
        phase_a = classify_outcomes(outcomes_a)
        phase_a["admit_hold_observed"] = kill_at["admit_hold_observed"]
        verdict["phase_a"] = phase_a
        check(phase_a["admit_hold_observed"],
              "phase A: the admit_hold injection record never appeared — "
              "the SIGKILL cannot be placed inside the admission window "
              "(is replica 0 running --dispatch_mode pipelined?)")
        check(phase_a["failures"] == 0,
              f"phase A (SIGKILL): client-visible failures: {phase_a}")
        check_traced(outcomes_a, "phase A")
        wait_until(lambda: healthy(0), args.recover_timeout_s,
                   "killed replica respawned and healthy")
        verdict["phase_a"]["recovery_s"] = round(
            time.monotonic() - t_kill, 2)
        check(sink.count("spawn") >= args.replicas + 1,
              "no respawn fleet_event after the SIGKILL")
        crash_restarts = [
            r for r in sink.records
            if r.get("event") == "restart_scheduled" and r.get("crash")]
        check(crash_restarts, "SIGKILL was not classified as a crash")
        check(crash_restarts[0]["backoff_s"] <= sup.policy.max_delay_s,
              f"restart backoff {crash_restarts[0]['backoff_s']} exceeds "
              "the policy ceiling")

        # The warm-restart acceptance: the respawned replica warmed from
        # the shared AOT cache — zero cold compiles, by the cache
        # counter events (the authority, per PR 8).
        colds = cold_start_records(os.path.join(workdir, "replica_0"))
        check(len(colds) >= 2,
              f"expected >=2 serve_cold_start records (initial + "
              f"restart), found {len(colds)}")
        verdict["restart_compiles_cold"] = colds[-1]["compiles_cold"]
        check(colds[-1]["compiles_cold"] == 0,
              f"restarted replica recompiled: {colds[-1]}")

        # -- phase B: wedged dispatch, caught only by the watchdog ------
        outcomes_b: list = []
        run_burst(router_url, args.wedge_cap_requests, args.burst_workers,
                  args.client_timeout_s, outcomes_b,
                  should_stop=lambda: sink.count("wedged_kill") > 0)
        # The burst's only job is to push the wedge replica past
        # --wedge_at served requests; the watchdog then needs its OWN
        # detection window — heartbeat_timeout_s of staleness plus a
        # poll tick — measured from the instant the dispatch thread
        # hung. A fast burst drains its remaining requests through the
        # surviving replica in less than that, so the kill is awaited
        # here rather than required to land mid-burst.
        wait_until(lambda: sink.count("wedged_kill") > 0,
                   args.recover_timeout_s,
                   "watchdog kill of the wedged replica (if the wedge "
                   f"never armed, raise --wedge_cap_requests "
                   f"[{args.wedge_cap_requests}] or lower --wedge_at "
                   f"[{args.wedge_at}])")
        phase_b = classify_outcomes(outcomes_b)
        verdict["phase_b"] = phase_b
        check(phase_b["failures"] == 0,
              f"phase B (wedge): client-visible failures: {phase_b}")
        check_traced(outcomes_b, "phase B")
        wait_until(lambda: healthy(wedge_idx), args.recover_timeout_s,
                   "wedged replica respawned and healthy")

        # -- phase C: SIGKILL mid-drain ---------------------------------
        outcomes_c: list = []

        def kill_during_drain() -> None:
            pid = state_of(wedge_idx)["pid"]
            if not pid:
                verdict["phase_c_kill"] = "no_pid"
                return
            os.kill(pid, signal.SIGTERM)   # graceful drain begins
            time.sleep(0.3)
            try:
                os.kill(pid, signal.SIGKILL)   # ... and is cut short
                verdict["phase_c_kill"] = "mid_drain"
            except ProcessLookupError:
                verdict["phase_c_kill"] = "drained_first"

        run_burst(router_url, args.phase_c_requests, args.burst_workers,
                  args.client_timeout_s, outcomes_c,
                  mid=(args.phase_c_requests // 4, kill_during_drain))
        check(verdict.get("phase_c_kill") in ("mid_drain",
                                              "drained_first"),
              f"phase-C kill did not fire: {verdict.get('phase_c_kill')}")
        phase_c = classify_outcomes(outcomes_c)
        verdict["phase_c"] = phase_c
        check(phase_c["failures"] == 0,
              f"phase C (kill-during-drain): client-visible failures: "
              f"{phase_c}")
        check_traced(outcomes_c, "phase C")
        wait_until(
            lambda: any(r.get("event") == "exit"
                        and r.get("replica") == wedge_idx
                        for r in sink.records[-20:]),
            30.0, "supervisor to reap the drain-killed replica")
        wait_until(lambda: healthy(wedge_idx), args.recover_timeout_s,
                   "drain-killed replica respawned and healthy")

        # -- phase D: SIGKILL mid-swap ----------------------------------
        # The deployment-plane chaos proof (docs/serving.md "Model
        # registry & canary rollouts"): publish the fleet's own init
        # checkpoint as a new version, hold a hot-swap open on replica
        # 0 (swap_hold@1 — new params loaded, flip not yet taken),
        # SIGKILL inside the held window under load, then converge the
        # whole fleet with zero cold compiles and zero torn serves.
        reg = registry_mod.ModelRegistry(
            os.path.join(workdir, "registry"), emit=sink.write)
        ckpt_src = os.path.join(workdir, "init_ckpt", "ckpt_0.msgpack")
        check(os.path.isfile(ckpt_src),
              "replica 0 wrote no init checkpoint "
              "(--save_init_checkpoint)")
        # Published bytes must be immutable: every replica-0 respawn
        # rewrites the init checkpoint, so the registry binds a private
        # copy.
        ckpt_pub = os.path.join(workdir, "published_v2.msgpack")
        shutil.copyfile(ckpt_src, ckpt_pub)
        reg.publish("v2-swap", task="classify", checkpoint=ckpt_pub,
                    geometry=registry_mod.geometry_from_config(
                        model_config()))
        reg_ok, reg_detail = reg.verify("v2-swap")
        check(reg_ok, f"published version failed verify: {reg_detail}")

        # Faults arm at spawn: restart replica 0 with swap_hold armed.
        specs[0].env[faults.FAULTS_ENV] = "swap_hold@1x6"
        spawns_before = sink.count("spawn")
        pid = state_of(0)["pid"]
        check(pid, "replica 0 has no pid before the swap phase")
        os.kill(pid, signal.SIGKILL)
        wait_until(lambda: sink.count("spawn") > spawns_before
                   and healthy(0),
                   args.recover_timeout_s,
                   "replica 0 respawned with swap_hold armed")

        swap_attempt = {"resp": None, "exc": None}

        def call_swapz() -> None:
            try:
                swap_attempt["resp"] = sup.swap_replica(
                    0, "classify", ckpt_pub, "v2-swap", timeout_s=60.0)
            except (RuntimeError, OSError) as exc:
                swap_attempt["exc"] = f"{type(exc).__name__}: {exc}"

        def swap_hold_recorded() -> bool:
            try:
                with open(replica0_jsonl) as f:
                    return any('"injected_swap_hold"' in line
                               for line in f)
            except OSError:
                return False

        kill_d = {"hold_observed": False}
        spawns_before_kill = sink.count("spawn")

        def kill_mid_swap() -> None:
            # Start the /swapz call (it loads the new params, then the
            # armed fault emits its record and holds the window open),
            # wait for the cue, and kill with BOTH param trees in
            # memory and the flip not yet taken.
            threading.Thread(target=call_swapz, daemon=True).start()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if swap_hold_recorded():
                    kill_d["hold_observed"] = True
                    break
                time.sleep(0.2)
            pid = state_of(0)["pid"]
            if pid:
                os.kill(pid, signal.SIGKILL)
            # The respawn must come back unarmed: a second held swap
            # would only slow the convergence assertions below.
            specs[0].env.pop(faults.FAULTS_ENV, None)

        outcomes_d: list = []
        run_burst(router_url, args.phase_d_requests, args.burst_workers,
                  args.client_timeout_s, outcomes_d,
                  mid=(2, kill_mid_swap))
        phase_d = classify_outcomes(outcomes_d)
        phase_d["swap_hold_observed"] = kill_d["hold_observed"]
        verdict["phase_d"] = phase_d
        check(kill_d["hold_observed"],
              "phase D: the swap_hold injection record never appeared — "
              "the SIGKILL cannot be placed inside the swap window")
        check(phase_d["failures"] == 0,
              f"phase D (SIGKILL mid-swap): client-visible failures: "
              f"{phase_d}")
        check_traced(outcomes_d, "phase D")
        wait_until(lambda: sink.count("spawn") > spawns_before_kill
                   and healthy(0),
                   args.recover_timeout_s,
                   "mid-swap-killed replica respawned and healthy")
        # The interrupted control call must surface as a failure, never
        # a silent 200 for a swap that did not happen.
        wait_until(lambda: swap_attempt["exc"] is not None
                   or swap_attempt["resp"] is not None,
                   30.0, "the interrupted /swapz call to fail")
        check(swap_attempt["resp"] is None,
              f"/swapz answered ok for a swap the SIGKILL interrupted: "
              f"{swap_attempt}")
        # A half-applied swap is structurally impossible: the respawned
        # replica boots the configured baseline version, and nothing
        # ever served torn params.
        stats0 = get_json(specs[0].url, "/statsz")
        check(stats0.get("version") == "v1",
              f"replica respawned after a mid-swap SIGKILL must serve "
              f"the baseline version v1, got {stats0.get('version')!r}")
        check(int(stats0.get("torn_serves", 0)) == 0,
              f"torn serves recorded on the killed replica: {stats0}")

        # Converge: the supervisor swaps the whole fleet onto the
        # published version — sequentially, zero cold compiles (same
        # geometry hits the already-jitted executables; the cache
        # counter events are the authority, never wall clock).
        swap_infos = sup.swap_all("classify", ckpt_pub, "v2-swap",
                                  timeout_s=120.0)
        check(len(swap_infos) == args.replicas,
              f"swap_all answered for {len(swap_infos)} of "
              f"{args.replicas} replicas")
        for info in swap_infos:
            check(info.get("compiles_cold") == 0,
                  f"same-geometry hot-swap recompiled: {info}")
        torn_total = 0
        for i in range(args.replicas):
            st = get_json(specs[i].url, "/statsz")
            check(st.get("version") == "v2-swap",
                  f"replica {i} did not converge onto v2-swap: "
                  f"{st.get('version')!r}")
            torn_total += int(st.get("torn_serves", 0))
        check(torn_total == 0,
              f"torn-model serves after fleet convergence: {torn_total}")
        phase_d["torn_serves"] = torn_total
        phase_d["swap_compiles_cold"] = max(
            i.get("compiles_cold", 0) for i in swap_infos)
        phase_d["swap_load_s"] = max(
            i.get("load_s", 0.0) for i in swap_infos)
        # And the converged fleet still serves.
        outcomes_d2: list = []
        run_burst(router_url, 12, args.burst_workers,
                  args.client_timeout_s, outcomes_d2)
        post_swap = classify_outcomes(outcomes_d2)
        check(post_swap["failures"] == 0,
              f"post-swap burst saw failures: {post_swap}")
        check_traced(outcomes_d2, "phase D post-swap")

        # -- teardown + fleet-level assertions --------------------------
        drain = sup.stop()
        router_server.shutdown()
        router.stop()
        snapshot = router.snapshot()
        verdict["drain"] = {"rcs": {str(k): v for k, v
                                    in drain["rcs"].items()},
                            "drain_killed": drain["drain_killed"]}
        check(drain["drain_killed"] == 0,
              "a live replica ignored the drain SIGTERM and needed "
              f"SIGKILL at stop: {drain}")
        check(drain["rcs"][0] == supervisor_mod.EXIT_PREEMPTED,
              f"replica 0 should exit EXIT_PREEMPTED on drain, got "
              f"{drain['rcs'][0]} (the run_server preemption contract)")
        verdict["router"] = {
            k: snapshot.get(k) for k in
            ("requests", "ok", "sheds", "errors", "retries", "hedges",
             "hedge_wins", "failovers", "latency_p95_ms",
             "failover_p95_ms")}
        check(snapshot["errors"] == 0,
              f"router recorded client-visible errors: {snapshot}")
        check(snapshot["failovers"] >= 1,
              "no failover was recorded — the kill phases did not "
              "exercise the retry path")
        failover_p95 = snapshot.get("failover_p95_ms")
        check(failover_p95 is not None,
              "router snapshot carries no failover percentile")
        check(failover_p95 <= args.failover_tolerance_ms,
              f"failover p95 {failover_p95}ms exceeds the "
              f"{args.failover_tolerance_ms:g}ms tolerance — the "
              "telemetry-report 'router failover' gate would trip")

        # -- every artifact schema-clean --------------------------------
        sink.close()
        lint(os.path.join(workdir, "fleet_telemetry.jsonl"))
        for i in range(args.replicas):
            lint(os.path.join(workdir, f"replica_{i}",
                              "serve_telemetry.jsonl"))

        # -- end-to-end trace stitching ---------------------------------
        # Post-hoc FleetCollector pass over the router's sink + every
        # replica's serve telemetry: one ordered timeline with one
        # trace_stitch per sampled client request. Everything is already
        # on disk, so one pass joins both sides and close() force-drains
        # anything one-sided into an orphan record.
        timeline_path = os.path.join(workdir, "fleet_timeline.jsonl")
        timeline: list = []
        tails = [collector_mod.JsonlTailer(
            os.path.join(workdir, "fleet_telemetry.jsonl"), "fleet")]
        for i in range(args.replicas):
            tails.append(collector_mod.JsonlTailer(
                os.path.join(workdir, f"replica_{i}",
                             "serve_telemetry.jsonl"), f"replica-{i}"))
        coll = collector_mod.FleetCollector([], tails=tails,
                                            out_path=timeline_path,
                                            emit=timeline.append)
        coll.collect_once()
        coll.close()
        lint(timeline_path)
        router_traces = {r["trace_id"]: r for r in timeline
                         if r.get("kind") == "router_trace"}
        stitches = [r for r in timeline
                    if r.get("kind") == "trace_stitch"]
        check(router_traces, "router sampled at 1.0 but emitted no "
                             "router_trace records")
        stitch_ids = [s["trace_id"] for s in stitches]
        check(len(stitch_ids) == len(set(stitch_ids)),
              "a trace id stitched more than once: every sampled client "
              "request must resolve to exactly ONE stitched tree")
        check(set(stitch_ids) == set(router_traces),
              f"stitch/trace mismatch: {len(stitches)} stitches for "
              f"{len(router_traces)} router traces")
        orphans = [s for s in stitches if s.get("orphan")]
        check(not orphans,
              f"{len(orphans)} orphan stitches on a fully-sampled run "
              f"(first: {orphans[:2]}): a span went missing between "
              "tiers")
        complete = [s for s in stitches
                    if s.get("router_overhead_ms") is not None]
        check(complete, "no complete stitch decompositions")
        bad_decomp = [s for s in complete if not s.get("consistent")]
        check(not bad_decomp,
              f"inconsistent stitch decomposition (client_total < "
              f"router overhead + replica time): {bad_decomp[:2]}")
        # Every 2xx client outcome's echoed trace id names a stitch.
        ok_ids = {o["trace_id"]
                  for o in (outcomes_a + outcomes_b + outcomes_c
                            + outcomes_d + outcomes_d2)
                  if o["status"] is not None and 200 <= o["status"] < 300}
        missing = ok_ids - set(stitch_ids)
        check(not missing,
              f"{len(missing)} answered requests never resolved to a "
              f"stitched tree: {sorted(missing)[:5]}")
        # The phase-A failover tree: attempt 1 on the SIGKILLed replica
        # 0, winning attempt 2+ chaining to a surviving replica's
        # serve_trace.
        failover_stitch = None
        for s in complete:
            if s.get("winning_attempt", 1) < 2:
                continue
            rt = router_traces[s["trace_id"]]
            first = next((sp for sp in rt["spans"]
                          if sp.get("name") == "attempt"
                          and sp.get("attempt") == 1), None)
            if first and first["replica"] == specs[0].url \
                    and first.get("outcome") == "transport_error":
                failover_stitch = s
                break
        check(failover_stitch is not None,
              "no stitched trace shows attempt 1 dying on the killed "
              "replica (transport_error) and failing over to a winning "
              "attempt 2+")
        rt = router_traces[failover_stitch["trace_id"]]
        win_span = next(sp for sp in rt["spans"]
                        if sp.get("name") == "attempt"
                        and sp.get("attempt")
                        == failover_stitch["winning_attempt"])
        check(win_span["replica"] != specs[0].url,
              f"winning attempt stayed on the killed replica: {win_span}")
        check(failover_stitch.get("winning_trace_id"),
              "failover stitch does not chain to a replica serve_trace")
        verdict["trace"] = {
            "router_traces": len(router_traces),
            "stitches": len(stitches),
            "orphans": len(orphans),
            "complete": len(complete),
        }
        verdict["failover_trace"] = {
            "trace_id": failover_stitch["trace_id"],
            "attempts": failover_stitch.get("attempts"),
            "winning_attempt": failover_stitch["winning_attempt"],
            "attempt_1_replica": specs[0].url,
            "winning_replica": win_span["replica"],
            "winning_trace_id": failover_stitch["winning_trace_id"],
            "winning_source": failover_stitch.get("winning_source"),
        }
        # The operator drill-down path: obs_collect --trace prints the
        # stitched tree for the failover request out of the timeline.
        tree_proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "obs_collect.py"),
             "--out", timeline_path,
             "--trace", failover_stitch["trace_id"]],
            capture_output=True, text=True)
        check(tree_proc.returncode == 0,
              f"obs_collect --trace failed: {tree_proc.stdout}"
              f"{tree_proc.stderr}")
        check(specs[0].url in tree_proc.stdout
              and win_span["replica"] in tree_proc.stdout
              and "stitch:" in tree_proc.stdout,
              f"obs_collect --trace tree missing expected spans:\n"
              f"{tree_proc.stdout}")

        # -- report gates, proven live ----------------------------------
        # A copy of the timeline doctored with one router-delay-dominated
        # stitch must make telemetry-report exit 1 naming the gate, while
        # the clean timeline self-diffs green (the observatory E2E
        # discipline: the gate is proven to FIRE, not just to exist).
        doctored_path = timeline_path + ".doctored"
        shutil.copyfile(timeline_path, doctored_path)
        with open(doctored_path, "a", encoding="utf-8") as f:
            f.write(json.dumps({
                "schema": schema.SCHEMA_VERSION,
                "ts": round(time.time(), 3),
                "kind": "trace_stitch", "tag": "obs",
                "trace_id": "rt-injected-router-delay", "orphan": False,
                "router_spans": 2, "replica_spans": 1, "status": 200,
                "task": "classify", "attempts": 1, "hedges": 0,
                "hedge_wasted_ms": 0.0,
                "client_total_ms": 60000.0,
                "router_overhead_ms": 59900.0,
                "network_gap_ms": 50.0, "replica_ms": 50.0,
                "consistent": True, "winning_attempt": 1}) + "\n")
        report_tool = os.path.join(REPO_ROOT, "tools",
                                   "telemetry_report.py")
        bad = subprocess.run(
            [sys.executable, report_tool, doctored_path, timeline_path],
            capture_output=True, text=True)
        check(bad.returncode == 1
              and "router overhead share" in bad.stdout,
              f"injected router delay did not trip the 'router overhead "
              f"share' gate (rc {bad.returncode}):\n{bad.stdout}")
        clean = subprocess.run(
            [sys.executable, report_tool, timeline_path, timeline_path],
            capture_output=True, text=True)
        check(clean.returncode == 0,
              f"clean timeline failed its own self-diff (rc "
              f"{clean.returncode}):\n{clean.stdout}")
        verdict["report_gate"] = {"doctored_rc": bad.returncode,
                                  "clean_rc": clean.returncode}
        os.remove(doctored_path)

        verdict.update(ok=True, wall_s=round(time.monotonic() - t_start, 1))
        print(json.dumps(verdict))
        if not args.workdir:
            shutil.rmtree(workdir, ignore_errors=True)
        return 0
    except (ChaosFailure, OSError, ValueError, KeyError) as exc:
        verdict.update(ok=False, error=f"{type(exc).__name__}: {exc}")
        try:
            sup.stop()
            router_server.shutdown()
            router.stop()
        except Exception:
            pass
        print(json.dumps(verdict))
        print(f"chaos_serve: FAILED — artifacts kept in {workdir}",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
