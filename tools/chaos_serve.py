#!/usr/bin/env python
"""Fleet chaos harness: kill, wedge, and drain-kill serving replicas
under live traffic — and PROVE no client ever saw it
(docs/serving.md "Fleet tier", docs/fault_tolerance.md "Serve failover").

The serving resilience layer's acceptance gate, the serve analog of
``tools/chaos_run.py``. One invocation stands up the real fleet — a
:class:`Supervisor` owning N ``run_server.py`` replica subprocesses
(each warmed from one shared persistent AOT compile cache) behind a
:class:`Router` front tier — then drives a closed-loop client burst
through the router while injecting, in sequence:

1. **SIGKILL inside the admission window** — replica 0 is armed with
   ``admit_hold@N`` (testing/faults.py): its pipelined assembler emits
   an injection record and then HOLDS its forming batch open inside
   the admission window; the harness waits for that record and kills
   the replica while requests are provably captive in the forming
   batch (the continuous-batching stage a flush-then-wait server never
   had). The router's transport failures fail over to a different
   replica inside the retry budget; the supervisor reaps the exit and
   respawns with crash backoff; the restarted replica must report
   ``compiles_cold == 0`` (PR 8's warm-restart property is what makes
   seconds-scale recovery real);
2. **wedged dispatch** — a replica armed with ``BERT_FAULTS=wedge@N``
   hangs its dispatch thread while ``/healthz`` keeps answering 200.
   Only the supervisor's heartbeat watchdog can catch this; meanwhile
   the router's hedged requests keep the stuck replica's traffic inside
   the latency budget until the watchdog kills it;
3. **kill during drain** — SIGTERM (graceful drain) followed by SIGKILL
   mid-drain. Requests the dying replica never answered are retried
   elsewhere; the supervisor classifies the exit as a crash.

Acceptance, asserted per phase and overall: ZERO client-visible
failures (every request answers 2xx, except explicit brownout sheds —
503 carrying ``Retry-After``); failover latency p95 within
``--failover_tolerance_ms`` (the same number telemetry-report's
"router failover" gate regresses on); the supervisor's restart within
the backoff budget; and every artifact (router/fleet events + each
replica's serve telemetry) schema-clean.

Verdict is one JSON line on stdout; exit 0 = every assertion held.

``--smoke`` is the documented one-command local gate (2 replicas, small
bursts, sized for a throttled tier-1 CPU box)::

    python tools/chaos_serve.py --smoke

The parent is deliberately jax-free: supervisor/router/schema load by
FILE PATH (tools/_bootstrap.py), so a hung accelerator runtime can hang
a REPLICA — which the watchdog kills — never the harness itself.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse

from _bootstrap import REPO_ROOT, load_by_path

schema = load_by_path(
    "_fleet_schema", "bert_pytorch_tpu", "telemetry", "schema.py")
supervisor_mod = load_by_path(
    "_fleet_supervisor", "bert_pytorch_tpu", "serve", "supervisor.py")
router_mod = load_by_path(
    "_fleet_router", "bert_pytorch_tpu", "serve", "router.py")
faults = load_by_path(
    "_fleet_faults", "bert_pytorch_tpu", "testing", "faults.py")
synth = load_by_path(
    "_fleet_synth", "bert_pytorch_tpu", "tools", "make_synthetic_data.py")

# Tiny fp32 model over the trace vocabulary: the gate's evidence is
# request outcomes and fleet/router records, not model quality — sized
# at the floor that still exercises the full serve path (tokenize ->
# batch -> jitted forward -> postprocess) so replica warmup stays
# seconds, not minutes, on a throttled CPU.
def model_config() -> dict:
    vocab = 5 + len(synth.TRACE_WORDS)
    vocab += (8 - vocab % 8) % 8
    return {
        "vocab_size": vocab, "hidden_size": 16, "num_hidden_layers": 1,
        "num_attention_heads": 2, "intermediate_size": 32,
        "max_position_embeddings": 32, "type_vocab_size": 2,
        "next_sentence": True, "mask_token_id": 4,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
    }


PHRASES = (
    "paris is big", "the river runs through london",
    "william shakespeare wrote hamlet", "england is old",
    "the capital of france is paris", "hamlet was wrote in london",
)


class ChaosFailure(AssertionError):
    pass


def check(cond, what):
    if not cond:
        raise ChaosFailure(what)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Sink:
    """Thread-safe schema-v1 JSONL sink + in-memory event index.

    The supervisor's monitor thread and every router request thread emit
    through ``write``; the harness polls ``count`` to sequence phases
    (e.g. "burst until the watchdog's wedged_kill lands"). Deliberately
    local: the package JSONLHandler imports the package chain on first
    write, which would drag jax into this jax-free parent.
    """

    def __init__(self, path: str):
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")
        self.records = []

    def write(self, record: dict) -> None:
        rec = {"schema": schema.SCHEMA_VERSION, "ts": round(time.time(), 3)}
        rec.update(record)
        with self._lock:
            self.records.append(rec)
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()

    def count(self, event: str) -> int:
        with self._lock:
            return sum(1 for r in self.records if r.get("event") == event)

    def close(self) -> None:
        with self._lock:
            self._f.close()


def make_spawn(log_dir: str):
    """A Popen factory that pins replicas to CPU jax, strips the test
    harness's virtual-device flag and any leaked fault spec from the
    inherited environment (spec.env re-arms faults deliberately), and
    tees replica output to a per-replica log for post-mortems."""

    def spawn(spec):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop(faults.FAULTS_ENV, None)
        xla = " ".join(
            flag for flag in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in flag)
        if xla:
            env["XLA_FLAGS"] = xla
        else:
            env.pop("XLA_FLAGS", None)
        if spec.env:
            env.update(spec.env)
        log = open(os.path.join(log_dir, f"replica_{spec.index}.log"), "ab")
        return subprocess.Popen(spec.cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT)

    return spawn


# -- the closed-loop client --------------------------------------------------

def post(url: str, task: str, payload: dict, timeout_s: float):
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=timeout_s)
    try:
        conn.request("POST", f"/v1/{task}",
                     body=json.dumps(payload).encode("utf-8"),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        return resp.status, dict(resp.getheaders())
    finally:
        conn.close()


def run_burst(url: str, total: int, workers: int, timeout_s: float,
              outcomes: list, should_stop=None, mid=None) -> None:
    """Closed-loop burst: ``workers`` threads issue requests until
    ``total`` have been sent (or ``should_stop()`` says enough — the
    wedge phase stops on the watchdog's event, not a count). Each
    outcome is appended to the shared ``outcomes`` list.

    ``mid=(count, callback)`` fires ``callback`` exactly once, from
    whichever worker completes outcome number ``count`` — the fault
    injection is sequenced INSIDE the burst, so it lands mid-flight no
    matter how fast the box drains the request quota."""
    lock = threading.Lock()
    issued = [0]
    mid_fired = [False]

    def worker() -> None:
        while True:
            if should_stop is not None and should_stop():
                return
            with lock:
                if issued[0] >= total:
                    return
                issued[0] += 1
                seq = issued[0]
            payload = {"text": PHRASES[seq % len(PHRASES)]}
            t0 = time.monotonic()
            try:
                status, headers = post(url, "classify", payload, timeout_s)
            except Exception as exc:
                status, headers = None, {
                    "error": f"{type(exc).__name__}: {exc}"}
            fire = False
            with lock:
                outcomes.append({
                    "status": status,
                    "retry_after": headers.get("Retry-After"),
                    "latency_s": round(time.monotonic() - t0, 4),
                })
                if (mid is not None and not mid_fired[0]
                        and len(outcomes) >= mid[0]):
                    mid_fired[0] = True
                    fire = True
            if fire:
                mid[1]()

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def classify_outcomes(outcomes: list) -> dict:
    """ok / shed / failure decomposition of one burst. A shed is an
    EXPLICIT admission-control answer — 503 carrying Retry-After;
    everything else non-2xx (including the router's own deadline 503,
    which has no Retry-After) is a client-visible failure."""
    ok = shed = 0
    failures = []
    for o in outcomes:
        if o["status"] is not None and 200 <= o["status"] < 300:
            ok += 1
        elif o["status"] == 503 and o.get("retry_after"):
            shed += 1
        else:
            failures.append(o)
    return {"requests": len(outcomes), "ok": ok, "sheds": shed,
            "failures": len(failures), "failure_samples": failures[:5]}


def wait_until(pred, timeout_s: float, what: str, poll_s: float = 0.25):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise ChaosFailure(f"timed out after {timeout_s:g}s waiting for {what}")


def cold_start_records(out_dir: str) -> list:
    path = os.path.join(out_dir, "serve_telemetry.jsonl")
    records = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
                    if rec.get("kind") == "serve_cold_start":
                        records.append(rec)
    return records


def lint(path: str) -> None:
    errors = schema.validate_file(path)
    check(errors == [], f"schema lint failed for {path}: {errors[:3]}")


# -- the scenario ------------------------------------------------------------

def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="replica kill/wedge/drain-kill chaos harness for the "
                    "serving fleet tier")
    parser.add_argument("--smoke", action="store_true",
                        help="the one-command local gate: 2 replicas, "
                             "small bursts, tier-1-budget-sized")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--burst_workers", type=int, default=4)
    parser.add_argument("--phase_a_requests", type=int, default=None,
                        help="burst size for the SIGKILL phase "
                             "(default 60; 50 under --smoke)")
    parser.add_argument("--phase_c_requests", type=int, default=30)
    parser.add_argument("--wedge_at", type=int, default=100,
                        help="requests the wedge replica serves before "
                             "its dispatch thread hangs (BERT_FAULTS "
                             "wedge@N; must exceed its phase-A share)")
    parser.add_argument("--wedge_cap_requests", type=int, default=600,
                        help="phase-B safety cap: the wedge MUST fire "
                             "before this many burst requests")
    parser.add_argument("--router_deadline_s", type=float, default=8.0)
    parser.add_argument("--failover_tolerance_ms", type=float, default=8000.0,
                        help="failover-latency p95 budget — the same "
                             "tolerance telemetry-report's 'router "
                             "failover' gate regresses on")
    parser.add_argument("--warmup_timeout_s", type=float, default=240.0)
    parser.add_argument("--recover_timeout_s", type=float, default=120.0,
                        help="budget for a killed replica to be respawned "
                             "AND healthy again (backoff + warm start)")
    parser.add_argument("--client_timeout_s", type=float, default=15.0)
    parser.add_argument("--workdir", type=str, default="",
                        help="keep artifacts here (default: a fresh temp "
                             "dir, removed on success)")
    args = parser.parse_args(argv)
    args.phase_a_requests = args.phase_a_requests or (
        50 if args.smoke else 60)

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_serve_")
    os.makedirs(workdir, exist_ok=True)
    cache_dir = os.path.join(workdir, "compile_cache")
    vocab_path = synth.write_trace_vocab(os.path.join(workdir, "vocab.txt"))
    config_path = os.path.join(workdir, "model.json")
    with open(config_path, "w") as f:
        json.dump(model_config(), f)

    # One ReplicaSpec per replica: shared model/cache flags, its own
    # port + output dir (telemetry JSONL and the heartbeat file the
    # supervisor watches live under it). The LAST replica is armed with
    # the wedge fault — it hangs only after serving --wedge_at requests,
    # so phases A (SIGKILL) and B (wedge) stay sequenced. Replica 0 is
    # armed with admit_hold@2x6: on its SECOND formed batch the
    # assembler emits the injection record and holds the admission
    # window open for 6s — the cue (and the window) for phase A's
    # SIGKILL-with-requests-in-the-forming-batch.
    shared_args = [
        "--model_config_file", config_path, "--vocab_file", vocab_path,
        "--tasks", "classify", "--classify_labels", "neg,pos",
        "--buckets", "16", "--max_batch_size", "4", "--max_wait_ms", "5",
        "--dtype", "float32", "--compile_cache_dir", cache_dir,
        "--trace_sample_rate", "0", "--telemetry_window", "16",
        "--request_timeout_s", "10",
    ]
    specs = []
    for i in range(args.replicas):
        out_dir = os.path.join(workdir, f"replica_{i}")
        os.makedirs(out_dir, exist_ok=True)
        env = {}
        if i == args.replicas - 1:
            env[faults.FAULTS_ENV] = f"wedge@{args.wedge_at}"
        elif i == 0:
            env[faults.FAULTS_ENV] = "admit_hold@2x6"
        port = free_port()
        specs.append(supervisor_mod.ReplicaSpec(
            index=i, port=port,
            cmd=supervisor_mod.run_server_command(port, out_dir,
                                                  shared_args),
            heartbeat_file=os.path.join(out_dir, "heartbeat.json"),
            env=env))

    sink = Sink(os.path.join(workdir, "fleet_telemetry.jsonl"))
    sup = supervisor_mod.Supervisor(
        specs, emit=sink.write, spawn=make_spawn(workdir),
        policy=supervisor_mod.RetryPolicy(
            attempts=5, base_delay_s=0.4, max_delay_s=3.0,
            full_jitter=True),
        heartbeat_timeout_s=5.0,
        startup_grace_s=args.warmup_timeout_s,
        stable_reset_s=15.0, poll_interval_s=0.25, drain_grace_s=15.0)
    router = router_mod.Router(
        [s.url for s in specs], emit=sink.write, window=32,
        scrape_interval_s=0.25,
        deadline_s=args.router_deadline_s,
        retry_policy=router_mod.RetryPolicy(
            attempts=3, base_delay_s=0.05, max_delay_s=0.5,
            full_jitter=True),
        hedge_pctl=0.95, hedge_min_ms=30.0, hedge_min_samples=24,
        brownout_queue_depth=64, shed_retry_after_s=0.5)
    router_server = router_mod.make_router_server(router, port=0)
    router_url = "http://%s:%d" % router_server.server_address[:2]

    t_start = time.monotonic()
    verdict = {"metric": "chaos_serve_fleet_failover", "workdir": workdir,
               "replicas": args.replicas, "router_url": router_url}
    wedge_idx = args.replicas - 1

    def state_of(idx):
        return sup.status()[idx]

    def healthy(idx):
        st = state_of(idx)
        return (st["state"] == supervisor_mod.RUNNING
                and router.healthy_count() >= 1
                and any(r["healthy"] and r["url"].endswith(
                    f":{specs[idx].port}")
                        for r in router.snapshot()["replica_states"]))

    try:
        sup.start()
        router.start()
        threading.Thread(target=router_server.serve_forever,
                         daemon=True).start()
        wait_until(lambda: router.healthy_count() == args.replicas,
                   args.warmup_timeout_s,
                   f"all {args.replicas} replicas healthy")

        # -- phase A: SIGKILL inside the admission window ----------------
        # Replica 0's armed admit_hold@2x6 emits its injection record
        # and then HOLDS the forming batch open; the kill callback waits
        # for the record and kills during the hold, so the process dies
        # with requests captive in the admission window — the stranded
        # shape that only exists under pipelined (continuous-batching)
        # dispatch. Those requests' clients must still see answers
        # (failover), like every other phase.
        outcomes_a: list = []
        kill_at = {"t": None, "admit_hold_observed": False}
        replica0_jsonl = os.path.join(
            workdir, "replica_0", "serve_telemetry.jsonl")

        def admit_hold_recorded() -> bool:
            try:
                with open(replica0_jsonl) as f:
                    return any('"injected_admit_hold"' in line for line in f)
            except OSError:
                return False

        def kill_replica_0() -> None:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if admit_hold_recorded():
                    kill_at["admit_hold_observed"] = True
                    break
                time.sleep(0.2)
            pid = state_of(0)["pid"]
            kill_at["t"] = time.monotonic()
            if pid:
                os.kill(pid, signal.SIGKILL)
            # The respawned replica must not re-arm the hold: spec.env
            # re-arms deliberately (the wedge depends on it), but a
            # second 6s hold would just add tail latency to phases B/C.
            specs[0].env.pop(faults.FAULTS_ENV, None)

        run_burst(router_url, args.phase_a_requests, args.burst_workers,
                  args.client_timeout_s, outcomes_a,
                  mid=(2, kill_replica_0))
        t_kill = kill_at["t"]
        check(t_kill is not None, "phase-A kill never fired")
        phase_a = classify_outcomes(outcomes_a)
        phase_a["admit_hold_observed"] = kill_at["admit_hold_observed"]
        verdict["phase_a"] = phase_a
        check(phase_a["admit_hold_observed"],
              "phase A: the admit_hold injection record never appeared — "
              "the SIGKILL cannot be placed inside the admission window "
              "(is replica 0 running --dispatch_mode pipelined?)")
        check(phase_a["failures"] == 0,
              f"phase A (SIGKILL): client-visible failures: {phase_a}")
        wait_until(lambda: healthy(0), args.recover_timeout_s,
                   "killed replica respawned and healthy")
        verdict["phase_a"]["recovery_s"] = round(
            time.monotonic() - t_kill, 2)
        check(sink.count("spawn") >= args.replicas + 1,
              "no respawn fleet_event after the SIGKILL")
        crash_restarts = [
            r for r in sink.records
            if r.get("event") == "restart_scheduled" and r.get("crash")]
        check(crash_restarts, "SIGKILL was not classified as a crash")
        check(crash_restarts[0]["backoff_s"] <= sup.policy.max_delay_s,
              f"restart backoff {crash_restarts[0]['backoff_s']} exceeds "
              "the policy ceiling")

        # The warm-restart acceptance: the respawned replica warmed from
        # the shared AOT cache — zero cold compiles, by the cache
        # counter events (the authority, per PR 8).
        colds = cold_start_records(os.path.join(workdir, "replica_0"))
        check(len(colds) >= 2,
              f"expected >=2 serve_cold_start records (initial + "
              f"restart), found {len(colds)}")
        verdict["restart_compiles_cold"] = colds[-1]["compiles_cold"]
        check(colds[-1]["compiles_cold"] == 0,
              f"restarted replica recompiled: {colds[-1]}")

        # -- phase B: wedged dispatch, caught only by the watchdog ------
        outcomes_b: list = []
        run_burst(router_url, args.wedge_cap_requests, args.burst_workers,
                  args.client_timeout_s, outcomes_b,
                  should_stop=lambda: sink.count("wedged_kill") > 0)
        # The burst's only job is to push the wedge replica past
        # --wedge_at served requests; the watchdog then needs its OWN
        # detection window — heartbeat_timeout_s of staleness plus a
        # poll tick — measured from the instant the dispatch thread
        # hung. A fast burst drains its remaining requests through the
        # surviving replica in less than that, so the kill is awaited
        # here rather than required to land mid-burst.
        wait_until(lambda: sink.count("wedged_kill") > 0,
                   args.recover_timeout_s,
                   "watchdog kill of the wedged replica (if the wedge "
                   f"never armed, raise --wedge_cap_requests "
                   f"[{args.wedge_cap_requests}] or lower --wedge_at "
                   f"[{args.wedge_at}])")
        phase_b = classify_outcomes(outcomes_b)
        verdict["phase_b"] = phase_b
        check(phase_b["failures"] == 0,
              f"phase B (wedge): client-visible failures: {phase_b}")
        wait_until(lambda: healthy(wedge_idx), args.recover_timeout_s,
                   "wedged replica respawned and healthy")

        # -- phase C: SIGKILL mid-drain ---------------------------------
        outcomes_c: list = []

        def kill_during_drain() -> None:
            pid = state_of(wedge_idx)["pid"]
            if not pid:
                verdict["phase_c_kill"] = "no_pid"
                return
            os.kill(pid, signal.SIGTERM)   # graceful drain begins
            time.sleep(0.3)
            try:
                os.kill(pid, signal.SIGKILL)   # ... and is cut short
                verdict["phase_c_kill"] = "mid_drain"
            except ProcessLookupError:
                verdict["phase_c_kill"] = "drained_first"

        run_burst(router_url, args.phase_c_requests, args.burst_workers,
                  args.client_timeout_s, outcomes_c,
                  mid=(args.phase_c_requests // 4, kill_during_drain))
        check(verdict.get("phase_c_kill") in ("mid_drain",
                                              "drained_first"),
              f"phase-C kill did not fire: {verdict.get('phase_c_kill')}")
        phase_c = classify_outcomes(outcomes_c)
        verdict["phase_c"] = phase_c
        check(phase_c["failures"] == 0,
              f"phase C (kill-during-drain): client-visible failures: "
              f"{phase_c}")
        wait_until(
            lambda: any(r.get("event") == "exit"
                        and r.get("replica") == wedge_idx
                        for r in sink.records[-20:]),
            30.0, "supervisor to reap the drain-killed replica")

        # -- teardown + fleet-level assertions --------------------------
        drain = sup.stop()
        router_server.shutdown()
        router.stop()
        snapshot = router.snapshot()
        verdict["drain"] = {"rcs": {str(k): v for k, v
                                    in drain["rcs"].items()},
                            "drain_killed": drain["drain_killed"]}
        check(drain["drain_killed"] == 0,
              "a live replica ignored the drain SIGTERM and needed "
              f"SIGKILL at stop: {drain}")
        check(drain["rcs"][0] == supervisor_mod.EXIT_PREEMPTED,
              f"replica 0 should exit EXIT_PREEMPTED on drain, got "
              f"{drain['rcs'][0]} (the run_server preemption contract)")
        verdict["router"] = {
            k: snapshot.get(k) for k in
            ("requests", "ok", "sheds", "errors", "retries", "hedges",
             "hedge_wins", "failovers", "latency_p95_ms",
             "failover_p95_ms")}
        check(snapshot["errors"] == 0,
              f"router recorded client-visible errors: {snapshot}")
        check(snapshot["failovers"] >= 1,
              "no failover was recorded — the kill phases did not "
              "exercise the retry path")
        failover_p95 = snapshot.get("failover_p95_ms")
        check(failover_p95 is not None,
              "router snapshot carries no failover percentile")
        check(failover_p95 <= args.failover_tolerance_ms,
              f"failover p95 {failover_p95}ms exceeds the "
              f"{args.failover_tolerance_ms:g}ms tolerance — the "
              "telemetry-report 'router failover' gate would trip")

        # -- every artifact schema-clean --------------------------------
        sink.close()
        lint(os.path.join(workdir, "fleet_telemetry.jsonl"))
        for i in range(args.replicas):
            lint(os.path.join(workdir, f"replica_{i}",
                              "serve_telemetry.jsonl"))

        verdict.update(ok=True, wall_s=round(time.monotonic() - t_start, 1))
        print(json.dumps(verdict))
        if not args.workdir:
            shutil.rmtree(workdir, ignore_errors=True)
        return 0
    except (ChaosFailure, OSError, ValueError, KeyError) as exc:
        verdict.update(ok=False, error=f"{type(exc).__name__}: {exc}")
        try:
            sup.stop()
            router_server.shutdown()
            router.stop()
        except Exception:
            pass
        print(json.dumps(verdict))
        print(f"chaos_serve: FAILED — artifacts kept in {workdir}",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
