#!/usr/bin/env python
"""Repo-root wrapper for the unified lint gate (``bert-lint``): jaxlint
over the package + runners + tools, then the telemetry record schema
over JSONL artifacts. One command for tier-1, the capture harness's
``commit_artifacts``, and pre-commit hooks::

    python tools/check_all.py                 # lint code + all repo JSONLs
    python tools/check_all.py CAPTURE.jsonl   # code + just this artifact
    python tools/check_all.py --skip-jaxlint CAPTURE.jsonl

jax-free — see bert_pytorch_tpu/analysis/check_all.py.
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from bert_pytorch_tpu.analysis.check_all import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
