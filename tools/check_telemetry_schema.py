#!/usr/bin/env python
"""Lint JSONL metric artifacts against the telemetry record schema.

Invoked from the tier-1 suite (tests/test_telemetry.py) over EVERY
committed ``*.jsonl`` artifact in the repo root — bench artifacts,
telemetry captures, sweep logs — so a future round cannot commit
malformed metrics (invalid JSON lines, NaN/Infinity spellings, records
claiming a schema version whose required keys are missing). The capture
harness (scripts/retry_capture_r04.sh) also runs it over any ``*.jsonl``
it is about to auto-commit. Legacy artifacts written before the schema
existed carry no ``schema`` key and are held to the universal rules only
(bert_pytorch_tpu/telemetry/schema.py). The ``serve`` record family
(``serve_window``/``serve_summary``, serve/stats.py) is linted with its
consistency rules — latency percentiles ordered p50 <= p95 <= p99,
``batch_occupancy`` in (0, 1]. The request-tracing kinds
(``serve_trace``/``serve_phase``, serve/tracing.py) are held to their
decomposition invariants: span durations non-negative and summing to no
more than ``total_ms``, ``queue_wait_ms <= total_ms``, a boolean
``sampled`` flag, ``queue_wait_share`` in [0, 1], ordered total
percentiles, and ``over_slo`` bounded by the window with a positive
``slo_target_ms`` — and the fault-tolerance family
(``fault``/``resume``, docs/fault_tolerance.md) with its own: a real
boolean ``injected`` marker, and every ``resume.skipped`` entry naming
step/path/reason. The async-hot-path step_window fields are held to
their invariants too: ``h2d_wait_*`` must be numeric and never exceed
the ``data_wait_*`` it is a sub-phase of, and ``ckpt_step_*``
percentiles require a positive ``ckpt_steps`` checkpoint-step flag
(docs/telemetry.md "Checkpoint-step p95"). The fleet-tier kinds
(``fleet_event``/``router_window``/``router_summary``,
serve/supervisor.py + serve/router.py) carry their own rules: the
ok/shed/error triple must decompose the window exactly, hedge wins are
bounded by hedges fired, healthy replicas by the fleet size, and the
latency/failover percentiles must be ordered. The fleet-observatory
kinds (``obs_scrape``/``obs_fleet_window``, telemetry/collector.py —
the fleet-timeline JSONLs ``tools/obs_collect.py`` writes and self-
lints by default) carry theirs: a non-empty target of a known kind
(trainer/replica/router), a boolean ``ok``, non-negative staleness/
latency/rate aggregates, and healthy counts bounded by totals. The
cross-tier tracing kinds (docs/observability.md "Trace propagation")
have the strictest rules of all: a ``router_trace`` must carry a
non-empty trace id, a span list restricted to the router taxonomy
(admission/attempt/backoff) where every span fits inside ``total_ms``
(spans may OVERLAP — hedged attempts race — so the serve_trace
sum-of-durations rule does NOT apply), every attempt span names its
1-based attempt index, target replica, and outcome, the ``attempts``
counter equals the attempt-span count, ``winning_attempt`` is bounded
by it, and ``hedge_wasted_ms`` needs at least one hedge fired; a
``trace_stitch`` must mark itself ``orphan`` when it has no router
parent, and when it carries the full decomposition,
``router_overhead_ms + network_gap_ms + replica_ms`` must equal
``client_total_ms`` within epsilon with a ``consistent`` verdict that
may only be true when the gap is non-negative (minus clock-noise
epsilon). The profiling-plane kinds (docs/observability.md "Profiling
plane") carry theirs: a ``profile_window`` must name its source, a
known trigger (startup/ondemand/fleet) and covered unit
(steps/requests), carry non-negative covered/samples/duration/
trace-byte counts and a string ``trace_path`` (empty = trace skipped),
and its host-frame table must be internally consistent — every frame a
positive sample count bounded by the capture's total, shares in (0, 1]
summing to no more than 1 (a frame over the total would mean two
captures folded together — the double-arm race the 409 guard
prevents); a ``ledger_entry`` (telemetry/ledger.py, the longitudinal
perf ledger) must name its leg and config digest and carry a non-empty
metrics object of non-negative numbers with ordered percentiles and
ratio metrics (mfu/padding_efficiency) in [0, 1]. The deployment-plane
kinds (docs/serving.md "Model registry & canary rollouts") carry
theirs: a ``registry_event`` must name its version, a non-empty event,
and a legal lifecycle state (staged/canary/live/retired), with
``state_change`` events restricted to the registry's legal edges and
every canary -> staged rollback carrying a ``reason``; a
``rollout_window`` must carry a ``canary_share`` in (0, 1], a
non-negative stage, an ok/errors pair bounded by ``window_requests``,
an action from the rollout vocabulary (hold/advance/promote/rollback)
— where a rollback names its ``reason`` — ordered latency percentiles
when present, and a non-negative ``torn_serves``; and across records
in one artifact, each (task, version) rollout's share sequence must be
monotone non-decreasing unless a rollback resets it. The
elasticity-plane kind (``scale_event``, serve/autoscaler.py —
docs/serving.md "Elastic fleet") carries its own: a decision from the
scale vocabulary (scale_up/scale_down/hold), a non-empty ``reason``,
non-negative integer ``replicas_before``/``replicas_after`` whose delta
matches the decision (+1 for scale_up, -1 for scale_down, 0 for hold),
an integer ``exogenous`` drift declaration, non-negative
window/streak/health counters and signal shares (``queue_wait_share``
in [0, 1]) when present — and across records per tag, the fleet's
membership must be RECONSTRUCTIBLE from the stream: each event's
``replicas_before`` must equal the previous event's ``replicas_after``
plus its declared ``exogenous`` drift. The chaos harnesses
(tools/chaos_run.py, tools/chaos_serve.py) lint their artifacts
through this same module.

Usage::

    python tools/check_telemetry_schema.py [paths...]

With no paths, lints ``<repo_root>/*.jsonl``. Exit 0 = all valid,
1 = violations (one ``path:line: error`` per finding), 2 = a named path
is missing. Imports only the schema module — no jax — so it runs
anywhere, including pre-commit hooks on machines without the accelerator
stack.
"""

from __future__ import annotations

import glob
import os
import sys

from _bootstrap import REPO_ROOT, load_by_path

validate_file = load_by_path(
    "_telemetry_schema", "bert_pytorch_tpu", "telemetry", "schema.py"
).validate_file


def main(argv=None) -> int:
    paths = list(argv if argv is not None else sys.argv[1:])
    if not paths:
        paths = sorted(glob.glob(os.path.join(REPO_ROOT, "*.jsonl")))
        if not paths:
            print("check_telemetry_schema: no *.jsonl artifacts found")
            return 0
    failed = False
    for path in paths:
        if not os.path.exists(path):
            print(f"check_telemetry_schema: {path}: no such file")
            return 2
        errors = validate_file(path)
        rel = os.path.relpath(path, REPO_ROOT)
        if errors:
            failed = True
            for lineno, err in errors:
                print(f"{rel}:{lineno}: {err}")
        else:
            print(f"{rel}: ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
