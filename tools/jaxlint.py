#!/usr/bin/env python
"""Repo-root jaxlint wrapper — the command the acceptance gate, tier-1
test, and pre-commit hook run::

    python tools/jaxlint.py bert_pytorch_tpu run_*.py serve tools

Pure-AST TPU-hazard linter (docs/static_analysis.md): host-sync,
recompile, RNG, tracer-leak, and lock-discipline checks. The analysis
package and the ``bert_pytorch_tpu`` package ``__init__`` chain are
stdlib-only, so this runs in milliseconds with NO jax import — on
pre-commit hooks, CI boxes, and the 2-core tier-1 box alike (the tier-1
test asserts the no-jax property).
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from bert_pytorch_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
