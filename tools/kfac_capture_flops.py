"""FLOP accounting for the K-FAC capture designs at real model geometry.

Compiles (never executes) the plain train step, the fused-capture step,
and the decoupled stats pass with XLA, then reads the post-optimization
``cost_analysis()`` FLOP counts. This is architecture-neutral evidence
the wallclock proxies cannot give: exact program FLOPs at the REAL
BERT-large bench shape, independent of host load or chip availability —
the compiled-program analog of the reference's "hooks are free" claim.

    python tools/kfac_capture_flops.py [--preset bert_large|small] \
        [--out KFAC_CAPTURE_FLOPS.json]

Reported ratios (factor_interval=1, the reference operating point):
  fused_overhead      = (fused_step - plain_step) / plain_step
  stats16_overhead    = stats_pass(16 rows)  / plain_step
  stats_full_overhead = stats_pass(batch rows) / plain_step
The fused capture replaces an entire extra forward/backward with just
the in-backward outer products; these numbers quantify exactly that.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def flops_of(jitted, *args):
    cost = jitted.lower(*args).compile().cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0]
    return float(cost["flops"])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="bert_large",
                    choices=["bert_large", "small"])
    ap.add_argument("--batch", type=int, default=0,
                    help="0 = preset default (bench shape)")
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--max_pred", type=int, default=20)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    import flax.linen as nn

    from bert_pytorch_tpu import optim, pretrain
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.preset == "bert_large":
        config = BertConfig.from_json_file(os.path.join(
            repo, "configs", "bert_large_uncased_config.json"))
        if config.vocab_size % 8 != 0:
            config.vocab_size += 8 - (config.vocab_size % 8)
        batch_n = args.batch or 56  # the bench's phase-1 single-chip shape
        dtype, remat = jnp.bfloat16, "dots"
    else:
        config = BertConfig(
            vocab_size=8192, hidden_size=256, num_hidden_layers=4,
            num_attention_heads=4, intermediate_size=1024,
            max_position_embeddings=args.seq, next_sentence=True)
        batch_n = args.batch or 16
        dtype, remat = jnp.float32, "none"

    model = BertForPreTraining(config, dtype=dtype, remat=remat)
    tapped = BertForPreTraining(config, dtype=dtype, remat=remat,
                                kfac_tap=True)
    S = args.seq
    params = jax.eval_shape(
        lambda r: nn.unbox(model.init(r, *(jnp.zeros((1, S), jnp.int32),) * 3)
                           )["params"],
        jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), params)
    schedule = optim.warmup_poly_schedule(1e-3, 0.1, 1000)
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
    state = pretrain.TrainState(
        params=params, opt_state=tx.init(params), rng=jax.random.PRNGKey(1))

    rng = np.random.default_rng(0)
    A, B = 1, batch_n
    batch = {
        "input_ids": rng.integers(
            0, config.vocab_size, (A, B, S)).astype(np.int32),
        "segment_ids": np.zeros((A, B, S), np.int32),
        "input_mask": np.ones((A, B, S), np.int32),
        "masked_lm_labels": np.where(
            rng.random((A, B, S)) < 0.15,
            rng.integers(0, config.vocab_size, (A, B, S)), -1
        ).astype(np.int32),
        "next_sentence_labels": rng.integers(0, 2, (A, B)).astype(np.int32),
    }
    mb0 = {k: v[0] for k, v in batch.items()}
    apply_loss, tap_shape_fn = pretrain.make_kfac_fns(
        tapped, True, max_pred_per_seq=args.max_pred)
    kfac = optim.KFAC(apply_loss, tap_shape_fn)
    kstate = kfac.init(params, mb0)

    plain = pretrain.make_train_step(
        model, tx, schedule=schedule, next_sentence=True,
        max_pred_per_seq=args.max_pred, kfac=kfac)
    fused = pretrain.make_train_step(
        model, tx, schedule=schedule, next_sentence=True,
        max_pred_per_seq=args.max_pred, kfac=kfac,
        kfac_capture_model=tapped, kfac_factor_interval=1)

    print("compiling plain step...", file=sys.stderr)
    f_plain = flops_of(plain, state, batch, kstate)
    print("compiling fused step...", file=sys.stderr)
    f_fused = flops_of(fused, state, batch, kstate)

    # The decoupled stats pass the fused capture replaces, at both the
    # runner's 16-row default and equal statistics (full microbatch).
    def stats_flops(rows):
        smb = {k: v[:rows] for k, v in mb0.items()}
        abstract = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                    for k, v in smb.items()}
        tap_shapes, _ = tap_shape_fn(
            jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params),
            abstract, jax.random.PRNGKey(0))
        impl = jax.jit(kfac._build_update_impl(tap_shapes))
        return flops_of(impl, kstate, params, smb, jax.random.PRNGKey(3))

    print("compiling stats pass (16 rows)...", file=sys.stderr)
    f_stats16 = stats_flops(min(16, B))
    print("compiling stats pass (full microbatch)...", file=sys.stderr)
    f_statsfull = stats_flops(B)

    out = {
        "preset": args.preset,
        "geometry": {"hidden": config.hidden_size,
                     "layers": config.num_hidden_layers,
                     "seq": S, "batch": B, "max_pred": args.max_pred,
                     "dtype": str(dtype.__name__), "remat": remat},
        "flops": {
            "plain_step": f_plain,
            "fused_step": f_fused,
            "stats_pass_16rows": f_stats16,
            "stats_pass_full_mb": f_statsfull,
        },
        "ratios_at_factor_interval_1": {
            "fused_capture_overhead": round((f_fused - f_plain) / f_plain, 4),
            "stats16_overhead": round(f_stats16 / f_plain, 4),
            "stats_full_overhead": round(f_statsfull / f_plain, 4),
            "fused_vs_stats_full_total": round(
                f_fused / (f_plain + f_statsfull), 4),
            "fused_vs_stats16_total": round(
                f_fused / (f_plain + f_stats16), 4),
        },
        "note": ("post-optimization XLA cost_analysis flops; compiled, "
                 "never executed — independent of host load and backend "
                 "availability"),
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f)


if __name__ == "__main__":
    main()
