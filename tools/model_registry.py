#!/usr/bin/env python
"""Model-registry CLI: publish, inspect, verify, and promote model
versions without an accelerator runtime (docs/serving.md "Model
registry & canary rollouts").

The registry itself is serve/registry.py — one directory per version,
one ``manifest.json`` each (checkpoint path + sha256 + quant level +
geometry + lifecycle state), written tmp+rename so a SIGKILL mid-write
never leaves a half-manifest. This tool is the operator's (and CI's)
surface over it::

    python tools/model_registry.py --root runs/registry \
        publish v2 --task classify --checkpoint out/ckpt_9000.msgpack \
        --quantize int8 --config configs/bert_base_config.json
    python tools/model_registry.py --root runs/registry list
    python tools/model_registry.py --root runs/registry verify v2
    python tools/model_registry.py --root runs/registry canary v2
    python tools/model_registry.py --root runs/registry promote v2
    python tools/model_registry.py --root runs/registry \
        rollback v2 --reason "canary p95 breach"

``publish --config`` records the model geometry from the config JSON so
``verify`` (and tools/verify_checkpoint.py --registry) can flag a
version whose checkpoint was trained at a different shape than the
fleet serves — the drift that otherwise surfaces as a shape error at
swap time on a live replica.

With ``--telemetry_jsonl`` every state change appends a schema-v1
``registry_event`` record (the audit trail telemetry-report
summarizes). Exit codes: 0 ok, 1 verification/state failure, 2 usage.

jax-free by construction: serve/registry.py and its integrity/schema
dependencies are stdlib-only and loaded by file path (tools/
_bootstrap.py), so this runs on any checkout.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from _bootstrap import load_by_path

registry_mod = load_by_path(
    "_registry_cli", "bert_pytorch_tpu", "serve", "registry.py")
schema = load_by_path(
    "_registry_schema", "bert_pytorch_tpu", "telemetry", "schema.py")


def make_emit(path):
    """Append-mode schema-v1 JSONL emitter (the registry emits bare
    records; the envelope — schema tag + timestamp — is stamped here,
    the same shape every sink in the repo writes)."""
    if not path:
        return None

    def emit(record: dict) -> None:
        rec = {"schema": schema.SCHEMA_VERSION, "ts": round(time.time(), 3)}
        rec.update(record)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")

    return emit


def cmd_publish(reg, args) -> int:
    geometry = None
    if args.config:
        with open(args.config, "r", encoding="utf-8") as f:
            geometry = registry_mod.geometry_from_config(json.load(f))
    manifest = reg.publish(args.version, task=args.task,
                           checkpoint=args.checkpoint,
                           quantize=args.quantize, geometry=geometry)
    print(f"published {manifest['version']} (task {manifest['task']}, "
          f"sha256 {manifest['sha256'][:12]}..., "
          f"{manifest['size_bytes']} bytes, "
          f"quantize {manifest['quantize']}, state {manifest['state']})")
    return 0


def cmd_list(reg, args) -> int:
    versions = reg.list_versions()
    if args.task:
        versions = [m for m in versions if m.get("task") == args.task]
    if not versions:
        print("(empty registry)")
        return 0
    for m in versions:
        geo = m.get("geometry") or {}
        shape = (f"L{geo['num_hidden_layers']}/H{geo['hidden_size']}"
                 if geo else "-")
        print(f"{m['version']:>12}  {m['state']:>7}  task={m['task']}  "
              f"quant={m['quantize']}  geometry={shape}  "
              f"sha256={m['sha256'][:12]}...")
    return 0


def cmd_verify(reg, args) -> int:
    rc = 0
    versions = ([args.version] if args.version
                else [m["version"] for m in reg.list_versions()])
    if not versions:
        print("(empty registry)")
        return 0
    for version in versions:
        ok, detail = reg.verify(version)
        print(f"{version}: {'OK' if ok else 'FAIL'} ({detail})")
        if not ok:
            rc = 1
    return rc


def cmd_canary(reg, args) -> int:
    manifest = reg.begin_canary(args.version)
    print(f"{manifest['version']}: staged -> canary")
    return 0


def cmd_promote(reg, args) -> int:
    manifest = reg.promote(args.version)
    print(f"{manifest['version']}: canary -> live "
          f"(task {manifest['task']})")
    return 0


def cmd_rollback(reg, args) -> int:
    manifest = reg.rollback(args.version, args.reason)
    print(f"{manifest['version']}: canary -> staged "
          f"(reason: {args.reason})")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="model-registry",
        description="versioned model registry over serve/registry.py "
                    "(docs/serving.md)")
    parser.add_argument("--root", required=True,
                        help="registry root directory (one subdir per "
                             "version)")
    parser.add_argument("--telemetry_jsonl", default="",
                        help="append registry_event records here "
                             "(schema v1 audit trail)")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("publish", help="register a checkpoint as a new "
                                       "staged version")
    p.add_argument("version")
    p.add_argument("--task", required=True)
    p.add_argument("--checkpoint", required=True,
                   help="ckpt_*.msgpack file the serving hosts can read")
    p.add_argument("--quantize", default=None,
                   help="quant level the version serves at (e.g. int8)")
    p.add_argument("--config", default="",
                   help="model config JSON; records the geometry so "
                        "verify can flag shape drift vs the fleet")

    p = sub.add_parser("list", help="list versions, newest last")
    p.add_argument("--task", default="")

    p = sub.add_parser("verify", help="re-hash checkpoints against the "
                                      "manifests (exit 1 on mismatch)")
    p.add_argument("version", nargs="?", default=None,
                   help="one version (default: every version)")

    p = sub.add_parser("canary", help="staged -> canary")
    p.add_argument("version")

    p = sub.add_parser("promote", help="canary -> live (retires the "
                                       "task's previous live version)")
    p.add_argument("version")

    p = sub.add_parser("rollback", help="canary -> staged, with a "
                                        "recorded reason")
    p.add_argument("version")
    p.add_argument("--reason", required=True,
                   help="why (lands on the registry_event and the "
                        "manifest history)")

    args = parser.parse_args(argv)
    reg = registry_mod.ModelRegistry(
        args.root, emit=make_emit(args.telemetry_jsonl))
    commands = {"publish": cmd_publish, "list": cmd_list,
                "verify": cmd_verify, "canary": cmd_canary,
                "promote": cmd_promote, "rollback": cmd_rollback}
    try:
        return commands[args.command](reg, args)
    except (registry_mod.RegistryError, FileNotFoundError) as exc:
        print(f"model-registry: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
