#!/usr/bin/env python
"""Fleet observatory collector CLI (docs/observability.md).

Scrapes every registered endpoint — trainer debug planes
(``--debug_port``, telemetry/introspect.py), serving replicas'
``/metricsz``, the router's ``/statsz`` — and tails their JSONL sinks,
merging everything into ONE ordered fleet-timeline JSONL with schema-v1
``obs_scrape`` (per-target sample + staleness) and ``obs_fleet_window``
(healthy/total counts, fleet req/s, worst-replica p99, trainer step
rate, error-budget burn) records. ``telemetry-report`` summarizes the
timeline and gates on "fleet scrape staleness" and "fleet worst-replica
p99" by name.

Usage::

    python tools/obs_collect.py \
        --target trainer:pretrain=http://127.0.0.1:9100 \
        --target replica:r0=http://127.0.0.1:8001 \
        --target router:front=http://127.0.0.1:8100 \
        --tail trainer=out/pretrain_telemetry.jsonl \
        --tail fleet=out/fleet_telemetry.jsonl \
        --out fleet_timeline.jsonl --interval_s 1 --duration_s 60

``--target`` is ``kind:name=url`` with kind in trainer/replica/router;
``--tail`` is ``name=path``. Scrape targets named with ``--target`` are
static; under an elastic fleet (serve/autoscaler.py) add
``--fleet fleet=out/fleet_telemetry.jsonl`` and membership follows the
supervisor's own event stream instead — replicas spawned mid-run join
the scrape set, drained ones leave it rather than counting as stale
scrape failures forever. Bounded by ``--duration_s`` or
``--passes`` (whichever lands first; Ctrl-C stops cleanly either way).
``--trace <id>`` skips collecting entirely and prints the stitched
span tree of one trace id out of an existing timeline (``--out`` names
the file to read): the router's admission/attempt/backoff spans, each
attempt's replica phases nested under it, and the stitch verdict.
``--profile`` fires ONE coordinated fleet-wide capture before the pass
loop: ``POST /profilez`` to every trainer/replica target concurrently
(the windows align on the same wall-clock slice), one trigger
``obs_scrape`` record per target in the timeline; the resulting
``profile_window`` records arrive through the tailed sinks
(docs/observability.md "Profiling plane").
The output is schema-linted by default at exit (exit 1 on violations) —
the collector's own artifact is held to the same bar as everything it
collects; ``--no-lint`` skips that.

jax-free like every tool here: the collector engine loads by FILE PATH
(tools/_bootstrap.py), so this process keeps collecting even while the
accelerator processes it watches are hung.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from _bootstrap import REPO_ROOT, load_by_path

collector_mod = load_by_path(
    "_obs_collector", "bert_pytorch_tpu", "telemetry", "collector.py")
schema = load_by_path(
    "_obs_schema", "bert_pytorch_tpu", "telemetry", "schema.py")


def parse_target(spec: str):
    kind, sep, rest = spec.partition(":")
    name, sep2, url = rest.partition("=")
    if not sep or not sep2 or not name or not url:
        raise argparse.ArgumentTypeError(
            f"--target wants kind:name=url, got {spec!r}")
    if kind not in schema.OBS_TARGET_KINDS:
        raise argparse.ArgumentTypeError(
            f"target kind must be one of {schema.OBS_TARGET_KINDS}, "
            f"got {kind!r}")
    return kind, name, url


def parse_tail(spec: str):
    name, sep, path = spec.partition("=")
    if not sep or not name or not path:
        raise argparse.ArgumentTypeError(
            f"--tail wants name=path, got {spec!r}")
    return name, path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="obs-collect",
        description="scrape the fleet's endpoints + tail its JSONL "
                    "sinks into one ordered timeline "
                    "(docs/observability.md)")
    parser.add_argument("--target", action="append", default=[],
                        type=parse_target, metavar="KIND:NAME=URL",
                        help="scrape target (trainer/replica/router); "
                             "repeatable")
    parser.add_argument("--tail", action="append", default=[],
                        type=parse_tail, metavar="NAME=PATH",
                        help="JSONL sink to tail into the timeline; "
                             "repeatable")
    parser.add_argument("--fleet", type=parse_tail, default=None,
                        metavar="NAME=PATH",
                        help="supervisor fleet-telemetry JSONL to read "
                             "fleet MEMBERSHIP from: replicas the "
                             "autoscaler spawns mid-run join the scrape "
                             "set as NAME-<index> targets, drained or "
                             "gave-up replicas leave it (instead of "
                             "counting as stale scrape failures "
                             "forever)")
    parser.add_argument("--fleet_host", type=str, default="127.0.0.1",
                        help="host the replicas announced by --fleet "
                             "events are scraped at")
    parser.add_argument("--out", type=str, default="fleet_timeline.jsonl",
                        help="timeline output JSONL (appended)")
    parser.add_argument("--interval_s", type=float, default=1.0,
                        help="seconds between collector passes")
    parser.add_argument("--duration_s", type=float, default=0.0,
                        help="stop after this much wall time "
                             "(0 = unbounded; Ctrl-C always stops "
                             "cleanly)")
    parser.add_argument("--passes", type=int, default=0,
                        help="stop after this many passes (0 = unbounded)")
    parser.add_argument("--scrape_timeout_s", type=float, default=2.0,
                        help="per-target scrape transport timeout")
    parser.add_argument("--slo_error_budget", type=float, default=0.01,
                        help="over-SLO fraction allowed before the "
                             "fleet error-budget burn exceeds 1")
    parser.add_argument("--no-lint", action="store_true",
                        help="skip schema-linting the timeline at exit")
    parser.add_argument("--profile", action="store_true",
                        help="fire one coordinated fleet-wide capture "
                             "(POST /profilez to every trainer/replica "
                             "target) before the pass loop; keep "
                             "collecting past the capture duration so "
                             "the profile_window records reach the "
                             "timeline through the tailed sinks")
    parser.add_argument("--profile_duration_s", type=float, default=2.0,
                        help="bounded capture window per target for "
                             "--profile")
    parser.add_argument("--trace", type=str, default=None,
                        metavar="TRACE_ID",
                        help="print the stitched span tree of one trace "
                             "id from the existing --out timeline and "
                             "exit (no collecting)")
    args = parser.parse_args(argv)

    if args.trace:
        # Read-only mode: render one stitched trace out of an already
        # collected timeline (the chaos harness / operator drill-down).
        if not os.path.exists(args.out):
            print(f"obs-collect: {args.out}: no such timeline",
                  file=sys.stderr)
            return 2
        records = []
        with open(args.out, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
        tree = collector_mod.stitch_tree(records, args.trace)
        print(tree)
        return 0 if "not found" not in tree.splitlines()[0] else 1

    if not args.target and not args.tail and not args.fleet:
        parser.error("need at least one --target, --tail, or --fleet")
    targets = [collector_mod.Target(name, kind, url,
                                    timeout_s=args.scrape_timeout_s)
               for kind, name, url in args.target]
    tails = [collector_mod.JsonlTailer(path, name)
             for name, path in args.tail]
    coll = collector_mod.FleetCollector(
        targets, tails=tails, out_path=args.out,
        interval_s=args.interval_s,
        slo_error_budget=args.slo_error_budget)
    membership = None
    if args.fleet:
        # Membership rides the supervisor's OWN event stream (spawn /
        # drain_complete / gave_up) on a dedicated tailer — independent
        # offset from any --tail of the same file, which keeps tailing
        # those records into the timeline too.
        fleet_name, fleet_path = args.fleet
        membership = collector_mod.FleetMembership(
            coll, collector_mod.JsonlTailer(fleet_path, fleet_name),
            host=args.fleet_host, prefix=fleet_name,
            timeout_s=args.scrape_timeout_s)
    deadline = (time.monotonic() + args.duration_s
                if args.duration_s > 0 else None)
    if args.profile:
        triggers = coll.trigger_profile(
            duration_s=args.profile_duration_s)
        armed = sum(1 for t in triggers if t["ok"])
        print(f"profile: armed {armed}/{len(triggers)} targets "
              f"({args.profile_duration_s:g}s window)")
        for t in triggers:
            if not t["ok"]:
                print(f"profile: {t['target']}: "
                      f"{t.get('error', 'unreachable')}", file=sys.stderr)
    done = 0
    try:
        while True:
            if membership is not None:
                delta = membership.sync()
                for name in delta["joined"]:
                    print(f"fleet: {name} joined the scrape set")
                for name in delta["left"]:
                    print(f"fleet: {name} left the scrape set")
            window = coll.collect_once()
            done += 1
            if window is not None:
                print(f"pass {done}: healthy "
                      f"{window['targets_healthy']}/"
                      f"{window['targets_total']}, max staleness "
                      f"{window['max_staleness_s']:.1f}s")
            if args.passes and done >= args.passes:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(args.interval_s)
    except KeyboardInterrupt:
        pass
    finally:
        # close(), not stop(): this loop already ran its final pass —
        # stop()'s drain pass (background-thread mode) would append an
        # uncounted extra round, blocking on any dead target again.
        coll.close()
    if args.no_lint:
        return 0
    # The collector's own artifact is held to the schema bar by default
    # (the check_all/check_telemetry_schema contract): a timeline that
    # fails its own lint must not exit 0.
    errors = schema.validate_file(args.out)
    rel = os.path.relpath(args.out, REPO_ROOT) \
        if args.out.startswith(REPO_ROOT) else args.out
    if errors:
        for lineno, err in errors[:20]:
            print(f"{rel}:{lineno}: {err}", file=sys.stderr)
        print(f"obs-collect: timeline FAILED schema lint "
              f"({len(errors)} errors)", file=sys.stderr)
        return 1
    print(f"obs-collect: {rel}: ok ({done} passes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
