#!/usr/bin/env python
"""Longitudinal perf-ledger CLI (telemetry/ledger.py, docs/telemetry.md
"Perf ledger").

The ledger is an append-mode, schema-linted JSONL trajectory of
headline perf numbers — one ``ledger_entry`` per bench leg /
telemetry-report run, keyed by (leg, config digest). This tool is the
standalone surface over it; ``bench.py`` appends automatically and
``tools/telemetry_report.py --ledger`` appends + gates in one run.

Usage::

    python tools/perf_ledger.py show   PERF_LEDGER.jsonl [--leg serve]
    python tools/perf_ledger.py append PERF_LEDGER.jsonl --leg train \
        --metric step_ms_p50=41.2 --metric mfu=0.38 [--config seq_len=128]
    python tools/perf_ledger.py check  PERF_LEDGER.jsonl \
        [--window 8] [--tol 0.25]

``check`` compares the NEWEST entry of every (leg, config) trajectory
against the rolling median of its history and exits 1 on drift, naming
"perf ledger drift" — the regression a single hand-picked baseline can
never catch. Exit 0 = clean, 1 = drift, 2 = missing file / bad input.

jax-free like every tool here: the ledger engine loads by FILE PATH
(tools/_bootstrap.py).
"""

from __future__ import annotations

import argparse
import os
import sys

from _bootstrap import load_by_path

ledger = load_by_path(
    "_perf_ledger_engine", "bert_pytorch_tpu", "telemetry", "ledger.py")


def _parse_kv(pairs, cast):
    out = {}
    for item in pairs or ():
        key, sep, value = item.partition("=")
        if not sep or not key:
            raise argparse.ArgumentTypeError(
                f"want key=value, got {item!r}")
        out[key] = cast(value)
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="perf-ledger",
        description="show / append / drift-check the longitudinal perf "
                    "ledger (docs/telemetry.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_show = sub.add_parser("show", help="render the trajectory")
    p_show.add_argument("path")
    p_show.add_argument("--leg", default=None,
                        help="only this leg's entries")

    p_append = sub.add_parser("append", help="append one entry")
    p_append.add_argument("path")
    p_append.add_argument("--leg", required=True, help="leg name")
    p_append.add_argument("--metric", action="append", default=[],
                          metavar="KEY=VALUE",
                          help="one metric (repeatable); known "
                               "directions: "
                               + ", ".join(sorted(
                                   ledger.METRIC_DIRECTIONS)))
    p_append.add_argument("--config", action="append", default=[],
                          metavar="KEY=VALUE",
                          help="config knob folded into the "
                               "comparability digest (repeatable)")

    p_check = sub.add_parser("check", help="rolling-median drift gate")
    p_check.add_argument("path")
    p_check.add_argument("--leg", default=None,
                         help="only gate this leg's trajectories")
    p_check.add_argument("--window", type=int,
                         default=ledger.DEFAULT_WINDOW,
                         help="history depth (default %(default)s)")
    p_check.add_argument("--tol", type=float,
                         default=ledger.DEFAULT_TOLERANCE,
                         help="relative drift tolerance "
                              "(default %(default)s)")

    args = parser.parse_args(argv)

    if args.cmd == "append":
        try:
            metrics = _parse_kv(args.metric, float)
            config = _parse_kv(args.config, str) or None
        except (argparse.ArgumentTypeError, ValueError) as exc:
            print(f"perf-ledger: {exc}", file=sys.stderr)
            return 2
        if not metrics:
            print("perf-ledger: append wants at least one --metric",
                  file=sys.stderr)
            return 2
        rec = ledger.append_entry(args.path, args.leg, metrics,
                                  config=config)
        if rec is None:
            print("perf-ledger: no metric survived cleaning (non-finite "
                  "or negative values are dropped)", file=sys.stderr)
            return 2
        print(f"perf-ledger: appended {args.leg} "
              f"[{rec['config_digest']}]: "
              + " ".join(f"{k}={v:g}"
                         for k, v in sorted(rec["metrics"].items())))
        return 0

    if not os.path.exists(args.path):
        print(f"perf-ledger: {args.path}: no such ledger", file=sys.stderr)
        return 2
    entries = ledger.read_entries(args.path,
                                  leg=getattr(args, "leg", None))
    if args.cmd == "show":
        print(ledger.format_trajectory(entries))
        return 0

    # check
    findings = ledger.check_drift(entries, window=args.window,
                                  tolerance=args.tol)
    if not findings:
        print(f"perf-ledger: {args.path}: ok "
              f"({len(entries)} entries, no drift)")
        return 0
    for f in findings:
        print(f"perf-ledger: REGRESSION perf ledger drift: "
              f"{f['leg']}/{f['metric']} [{f['digest']}]: "
              f"median {f['median']:g} -> {f['latest']:g} "
              f"({f['change']:+.1%}, tolerance {f['tolerance']:.0%}, "
              f"window {f['window']})")
    return 1


if __name__ == "__main__":
    sys.exit(main())
