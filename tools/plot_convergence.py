#!/usr/bin/env python
"""Render a CONVERGENCE_r*.csv (scripts/convergence_r02.sh output) to a PNG
loss-curve figure for the architecture notes.

  python tools/plot_convergence.py CONVERGENCE_r02.csv docs/convergence.png

One line per optimizer leg. Styling follows the repo-external dataviz
conventions: thin 2px lines, categorical hues in fixed slot order
(blue, orange — a validated colorblind-safe adjacent pair), recessive
grid/axes, text in neutral ink, direct labels at line ends plus a legend
when there is more than one series.
"""

from __future__ import annotations

import csv
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

SERIES_COLORS = ["#2a78d6", "#eb6834"]  # categorical slots 1-2, light mode
INK = "#3d3d3a"
MUTED = "#8a8a85"
GRID = "#e7e7e4"


def main(csv_path: str, out_path: str, title: str | None = None) -> None:
    legs: dict[str, list[tuple[int, float]]] = {}
    with open(csv_path) as f:
        for rec in csv.DictReader(f):
            legs.setdefault(rec["optimizer"], []).append(
                (int(rec["step"]), float(rec["loss"]))
            )
    if title is None:
        # Derived, claim-free default: hardware/recipe claims belong to the
        # caller that knows them (a default asserting "one v5e chip" would
        # mislabel CPU sanity CSVs run through the same tool).
        import os
        title = (f"{os.path.basename(csv_path)} — pretraining loss "
                 f"({', '.join(sorted(legs))})")

    fig, ax = plt.subplots(figsize=(7.0, 4.0), dpi=160)
    for i, (name, rows) in enumerate(legs.items()):
        rows.sort()
        steps = [s for s, _ in rows]
        losses = [l for _, l in rows]
        color = SERIES_COLORS[i % len(SERIES_COLORS)]
        ax.plot(steps, losses, color=color, linewidth=2.0,
                label=name.upper(), solid_capstyle="round")
        # direct label at the line end
        ax.annotate(
            f" {name.upper()} {losses[-1]:.2f}", (steps[-1], losses[-1]),
            color=INK, fontsize=9, va="center")

    ax.set_xlabel("optimizer step", color=INK, fontsize=10)
    ax.set_ylabel("MLM+NSP loss", color=INK, fontsize=10)
    ax.set_title(title, color=INK, fontsize=11, loc="left")
    ax.grid(axis="y", color=GRID, linewidth=0.8)
    ax.set_axisbelow(True)
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(MUTED)
    ax.tick_params(colors=MUTED, labelsize=9)
    ax.margins(x=0.12)  # room for the direct labels
    if len(legs) > 1:
        ax.legend(frameon=False, fontsize=9, labelcolor=INK)
    fig.tight_layout()
    fig.savefig(out_path, facecolor="white")
    print(f"wrote {out_path} ({', '.join(legs)})")


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2], *sys.argv[3:4])
