#!/usr/bin/env python
"""Summarize a CONVERGENCE_r*.csv (scripts/convergence_r0N.sh output).

Prints one JSON object with, per optimizer leg: loss/accuracy at step
milestones and the end of the run, plus K-FAC-vs-LAMB loss deltas at
equal STEPS and — when the CSV carries samples_per_second — at equal
WALLCLOCK. Both comparisons matter: the reference wires K-FAC for
quality-per-step (run_pretraining.py:320-355), but the preconditioner
only pays for itself if the per-step cost doesn't erase the advantage in
wall-clock terms (BASELINE.md north star is loss @ step).

  python tools/summarize_convergence.py CONVERGENCE_r03.csv
"""

from __future__ import annotations

import csv
import json
import sys


def _elapsed_proxy(row) -> float | None:
    """Per-row cumulative elapsed time, up to the (constant) global-batch
    factor: the runner logs samples_per_second = samples_seen / elapsed
    and samples_seen = step * gbs, so step / sps == elapsed / gbs — a
    time scale that is comparable ACROSS legs of the same capture."""
    sps = row.get("samples_per_second")
    if not sps:
        return None
    try:
        return int(row["step"]) / float(sps)
    except (ValueError, ZeroDivisionError):
        return None


def summarize(path: str) -> dict:
    legs: dict[str, list[dict]] = {}
    with open(path) as f:
        for rec in csv.DictReader(f):
            legs.setdefault(rec["optimizer"], []).append(rec)

    out: dict = {"file": path, "legs": {}}
    for name, rows in legs.items():
        rows.sort(key=lambda r: int(r["step"]))
        by_step = {int(r["step"]): r for r in rows}
        last = rows[-1]
        milestones = {}
        for s in (10, 25, 50, 100, 150, 200, 500, 1000, 2000, 5000):
            if s in by_step:
                milestones[str(s)] = round(float(by_step[s]["loss"]), 4)
        out["legs"][name] = {
            "steps": int(last["step"]),
            "first_loss": round(float(rows[0]["loss"]), 4),
            "final_loss": round(float(last["loss"]), 4),
            "final_mlm_accuracy": round(float(last["mlm_accuracy"]), 4),
            "loss_at_step": milestones,
        }
    kfac_legs = [k for k in legs if k.startswith("kfac")]
    if "lamb" in legs and kfac_legs:
        out["kfac_vs_lamb"] = {}
        lamb = legs["lamb"]
        lamb_t = [_elapsed_proxy(r) for r in lamb]
        for kname in kfac_legs:
            kf = legs[kname]
            n = min(int(lamb[-1]["step"]), int(kf[-1]["step"]))
            l_loss = next(float(r["loss"]) for r in lamb
                          if int(r["step"]) == n)
            k_loss = next(float(r["loss"]) for r in kf
                          if int(r["step"]) == n)
            cmp = {
                "equal_step": n,
                "lamb_loss": round(l_loss, 4),
                "kfac_loss": round(k_loss, 4),
                # positive = K-FAC is ahead (lower loss) at equal steps
                "kfac_advantage": round(l_loss - k_loss, 4),
            }
            kf_t = [_elapsed_proxy(r) for r in kf]
            # Equal wallclock: compare each leg's loss at the largest
            # elapsed time BOTH legs reached. Rows without a usable proxy
            # (no samples_per_second column, or the step-1 row where the
            # runner logs 0 before its timer starts) are ignored; skipped
            # entirely when either leg has no usable row in the horizon.
            lamb_v = [(i, t) for i, t in enumerate(lamb_t) if t is not None]
            kf_v = [(i, t) for i, t in enumerate(kf_t) if t is not None]
            horizon = (min(lamb_v[-1][1], kf_v[-1][1])
                       if lamb_v and kf_v else None)
            l_in = [i for i, t in lamb_v
                    if horizon is not None and t <= horizon]
            k_in = [i for i, t in kf_v
                    if horizon is not None and t <= horizon]
            if l_in and k_in:
                l_i, k_i = max(l_in), max(k_in)
                l_wc = float(lamb[l_i]["loss"])
                k_wc = float(kf[k_i]["loss"])
                cmp["equal_wallclock"] = {
                    "lamb_step": int(lamb[l_i]["step"]),
                    "kfac_step": int(kf[k_i]["step"]),
                    "lamb_loss": round(l_wc, 4),
                    "kfac_loss": round(k_wc, 4),
                    # positive = K-FAC ahead per unit wall-clock
                    "kfac_advantage": round(l_wc - k_wc, 4),
                    # K-FAC per-step cost relative to LAMB
                    "step_cost_ratio": round(
                        (kf_v[-1][1] / int(kf[kf_v[-1][0]]["step"]))
                        / (lamb_v[-1][1] / int(lamb[lamb_v[-1][0]]["step"])),
                        3),
                }
            out["kfac_vs_lamb"][kname] = cmp
    return out


if __name__ == "__main__":
    print(json.dumps(summarize(sys.argv[1])))
