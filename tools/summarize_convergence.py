#!/usr/bin/env python
"""Summarize a CONVERGENCE_r*.csv (scripts/convergence_r02.sh output).

Prints one JSON object with, per optimizer leg: loss/accuracy at step
milestones and the end of the run, plus the K-FAC-vs-LAMB loss delta at
equal steps — the quality-per-step comparison that justifies K-FAC's
per-step cost (reference wires K-FAC for exactly this trade,
run_pretraining.py:320-355; BASELINE.md north star is loss @ step).

  python tools/summarize_convergence.py CONVERGENCE_r02.csv
"""

from __future__ import annotations

import csv
import json
import sys


def summarize(path: str) -> dict:
    legs: dict[str, list[dict]] = {}
    with open(path) as f:
        for rec in csv.DictReader(f):
            legs.setdefault(rec["optimizer"], []).append(rec)

    out: dict = {"file": path, "legs": {}}
    for name, rows in legs.items():
        rows.sort(key=lambda r: int(r["step"]))
        by_step = {int(r["step"]): r for r in rows}
        last = rows[-1]
        milestones = {}
        for s in (10, 25, 50, 100, 150, 200):
            if s in by_step:
                milestones[str(s)] = round(float(by_step[s]["loss"]), 4)
        out["legs"][name] = {
            "steps": int(last["step"]),
            "first_loss": round(float(rows[0]["loss"]), 4),
            "final_loss": round(float(last["loss"]), 4),
            "final_mlm_accuracy": round(float(last["mlm_accuracy"]), 4),
            "loss_at_step": milestones,
        }
    if {"lamb", "kfac"} <= set(legs):
        n = min(int(legs["lamb"][-1]["step"]), int(legs["kfac"][-1]["step"]))
        l_loss = next(float(r["loss"]) for r in legs["lamb"]
                      if int(r["step"]) == n)
        k_loss = next(float(r["loss"]) for r in legs["kfac"]
                      if int(r["step"]) == n)
        out["kfac_vs_lamb"] = {
            "equal_step": n,
            "lamb_loss": round(l_loss, 4),
            "kfac_loss": round(k_loss, 4),
            # positive = K-FAC is ahead (lower loss) at equal steps
            "kfac_advantage": round(l_loss - k_loss, 4),
        }
    return out


if __name__ == "__main__":
    print(json.dumps(summarize(sys.argv[1])))
