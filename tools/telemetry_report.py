#!/usr/bin/env python
"""Offline telemetry summary + baseline-diff regression verdict.

Thin CLI shim over :mod:`bert_pytorch_tpu.telemetry.report` (also
installed as the ``telemetry-report`` console script) so the tool runs
straight from a checkout. Imports only stdlib + the report/schema
modules — no jax — so it works anywhere, including CI boxes without the
accelerator stack.

Usage::

    python tools/telemetry_report.py RUN.jsonl              # summary
    python tools/telemetry_report.py RUN.jsonl BASE.jsonl   # diff + verdict
    python tools/telemetry_report.py RUN.jsonl --ledger PERF_LEDGER.jsonl
    python tools/telemetry_report.py --ledger PERF_LEDGER.jsonl  # drift only

Exit 0 = no regression, 1 = regression (named in the output),
2 = missing file. ``--format json`` prints one stable versioned object
(``{"version": 1, ..., "rc": N}`` — the tools/check_all.py contract);
``--json`` is the legacy machine shape bench.py parses. ``--ledger``
appends the run to the longitudinal perf ledger and gates its rolling
median — "perf ledger drift" by name (telemetry/ledger.py,
docs/telemetry.md). Tolerance knobs: ``--step-tol --p95-tol --mfu-tol
--mem-tol --grad-tol --ledger-tol`` (docs/telemetry.md has a worked
example).
"""

from __future__ import annotations

import sys

from _bootstrap import load_by_path

_report = load_by_path(
    "_telemetry_report_engine", "bert_pytorch_tpu", "telemetry", "report.py")

if __name__ == "__main__":
    sys.exit(_report.main())
