#!/usr/bin/env python
"""Offline telemetry summary + baseline-diff regression verdict.

Thin CLI shim over :mod:`bert_pytorch_tpu.telemetry.report` (also
installed as the ``telemetry-report`` console script) so the tool runs
straight from a checkout. Imports only stdlib + the report/schema
modules — no jax — so it works anywhere, including CI boxes without the
accelerator stack.

Usage::

    python tools/telemetry_report.py RUN.jsonl              # summary
    python tools/telemetry_report.py RUN.jsonl BASE.jsonl   # diff + verdict

Exit 0 = no regression, 1 = regression (named in the output),
2 = missing file. ``--json`` prints the machine-readable verdict;
tolerance knobs: ``--step-tol --p95-tol --mfu-tol --mem-tol --grad-tol``
(docs/telemetry.md has a worked example).
"""

from __future__ import annotations

import sys

from _bootstrap import load_by_path

_report = load_by_path(
    "_telemetry_report_engine", "bert_pytorch_tpu", "telemetry", "report.py")

if __name__ == "__main__":
    sys.exit(_report.main())
