#!/usr/bin/env python
"""Verify checkpoint integrity manifests offline (docs/fault_tolerance.md).

For every ``ckpt_*.msgpack`` named (or found under a named directory),
check its sidecar manifest (``utils/integrity.py``: size, then sha256)
and print one ``path: status (detail)`` line. Statuses:

* ``verified``    — manifest present, bytes match;
* ``no_manifest`` — loadable but unverifiable (pre-manifest legacy
  checkpoint, or a write torn between the blob and sidecar renames);
* ``corrupt``     — size/sha mismatch or unreadable manifest. The resume
  walk-back (``utils/checkpoint.py``) will skip these.

Usage::

    python tools/verify_checkpoint.py out/pretrain_ckpts [more paths...]
    python tools/verify_checkpoint.py --strict out/   # no_manifest fails too

Checkpoints saved by the one-mesh runner carry a ``mesh_spec`` manifest
field (the topology they were saved under) and, for sharded layouts, the
shard-file list; both are printed, and under ``--strict`` the spec is
validated against the shard layout (``integrity.validate_mesh_spec`` —
concrete positive axis sizes, device product divisible by the process
shard count). Shard files verify against their OWN sidecars and are
chased from the index's manifest, so pointing this tool at the index
covers the whole step.

Exit 0 = nothing corrupt (``--strict``: everything verified), 1 =
corruption found (or unverified under ``--strict``), 2 = a named path is
missing. Imports only the stdlib integrity module — no jax — so it runs
anywhere, including cron health checks on storage-only machines.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

from _bootstrap import load_by_path

integrity = load_by_path(
    "_ckpt_integrity", "bert_pytorch_tpu", "utils", "integrity.py")


def expand(paths):
    """Named files, plus every ckpt_*.msgpack under named directories."""
    out = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(sorted(
                glob.glob(os.path.join(path, "**", "ckpt_*.msgpack"),
                          recursive=True)))
        else:
            out.append(path)
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="verify checkpoint integrity manifests")
    parser.add_argument("paths", nargs="+",
                        help="checkpoint files or directories to scan")
    parser.add_argument("--strict", action="store_true",
                        help="treat no_manifest (unverifiable) as failure")
    args = parser.parse_args(argv)

    for path in args.paths:
        if not os.path.exists(path):
            print(f"verify_checkpoint: {path}: no such file or directory")
            return 2
    ckpts = expand(args.paths)
    if not ckpts:
        print("verify_checkpoint: no ckpt_*.msgpack files found")
        return 2

    failed = False
    for path in ckpts:
        status, detail = integrity.verify_checkpoint(path)
        print(f"{path}: {status} ({detail})")
        if status == integrity.CORRUPT or (
                args.strict and status != integrity.VERIFIED):
            failed = True
        manifest = integrity.read_manifest(path)
        if manifest and "mesh_spec" in manifest:
            spec = ",".join(f"{k}={v}"
                            for k, v in sorted(manifest["mesh_spec"].items()))
            layout = manifest.get("layout")
            suffix = f" (layout={layout})" if layout else ""
            print(f"{path}: mesh_spec {spec}{suffix}")
            ok, reason = integrity.validate_mesh_spec(manifest)
            if not ok:
                print(f"{path}: mesh_spec INVALID ({reason})")
                if args.strict:
                    failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
