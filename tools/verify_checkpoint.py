#!/usr/bin/env python
"""Verify checkpoint integrity manifests offline (docs/fault_tolerance.md).

For every ``ckpt_*.msgpack`` named (or found under a named directory),
check its sidecar manifest (``utils/integrity.py``: size, then sha256)
and print one ``path: status (detail)`` line. Statuses:

* ``verified``    — manifest present, bytes match;
* ``no_manifest`` — loadable but unverifiable (pre-manifest legacy
  checkpoint, or a write torn between the blob and sidecar renames);
* ``corrupt``     — size/sha mismatch or unreadable manifest. The resume
  walk-back (``utils/checkpoint.py``) will skip these.

Usage::

    python tools/verify_checkpoint.py out/pretrain_ckpts [more paths...]
    python tools/verify_checkpoint.py --strict out/   # no_manifest fails too

Checkpoints saved by the one-mesh runner carry a ``mesh_spec`` manifest
field (the topology they were saved under) and, for sharded layouts, the
shard-file list; both are printed, and under ``--strict`` the spec is
validated against the shard layout (``integrity.validate_mesh_spec`` —
concrete positive axis sizes, device product divisible by the process
shard count). Shard files verify against their OWN sidecars and are
chased from the index's manifest, so pointing this tool at the index
covers the whole step.

With ``--registry`` the named paths are model-registry roots
(serve/registry.py, one manifest.json per version) instead of raw
checkpoint trees: every version's checkpoint is re-hashed against its
registry manifest digest, and with ``--config <model.json>`` each
version's recorded geometry is diffed against the config — a version
trained at a different shape than the fleet serves FAILs here instead
of as a shape error at swap time on a live replica. Versions published
without geometry are reported ``no_geometry`` (fails under
``--strict`` only).

Exit 0 = nothing corrupt (``--strict``: everything verified), 1 =
corruption found (or unverified under ``--strict``), 2 = a named path is
missing. Imports only the stdlib integrity module — no jax — so it runs
anywhere, including cron health checks on storage-only machines.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

from _bootstrap import load_by_path

integrity = load_by_path(
    "_ckpt_integrity", "bert_pytorch_tpu", "utils", "integrity.py")


def expand(paths):
    """Named files, plus every ckpt_*.msgpack under named directories."""
    out = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(sorted(
                glob.glob(os.path.join(path, "**", "ckpt_*.msgpack"),
                          recursive=True)))
        else:
            out.append(path)
    return out


def verify_registry(root: str, config: dict, strict: bool) -> int:
    """Registry mode: re-hash every version in a serve/registry.py root
    against its manifest digest, plus geometry drift vs ``config``."""
    registry_mod = load_by_path(
        "_ckpt_registry", "bert_pytorch_tpu", "serve", "registry.py")
    reg = registry_mod.ModelRegistry(root)
    versions = reg.list_versions()
    if not versions:
        print(f"verify_checkpoint: no registry versions under {root}")
        return 2
    failed = False
    for manifest in versions:
        version = manifest["version"]
        ok, detail = reg.verify(version)
        status = "verified" if ok else "corrupt"
        print(f"{root}:{version}: {status} ({detail}) "
              f"[state={manifest.get('state')} task={manifest.get('task')}]")
        if not ok:
            failed = True
        if config is not None:
            if not manifest.get("geometry"):
                print(f"{root}:{version}: no_geometry "
                      "(published without --config; nothing to diff)")
                if strict:
                    failed = True
            else:
                gok, gdetail = reg.verify_geometry(version, config)
                if not gok:
                    print(f"{root}:{version}: geometry DRIFT ({gdetail})")
                    failed = True
                else:
                    print(f"{root}:{version}: geometry ok ({gdetail})")
    return 1 if failed else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="verify checkpoint integrity manifests")
    parser.add_argument("paths", nargs="+",
                        help="checkpoint files or directories to scan "
                             "(--registry: registry roots)")
    parser.add_argument("--strict", action="store_true",
                        help="treat no_manifest (unverifiable) as failure")
    parser.add_argument("--registry", action="store_true",
                        help="paths are model-registry roots "
                             "(serve/registry.py); verify every "
                             "version's manifest digest")
    parser.add_argument("--config", default="",
                        help="model config JSON to diff each registry "
                             "version's recorded geometry against "
                             "(--registry only)")
    args = parser.parse_args(argv)

    if args.registry:
        import json
        config = None
        if args.config:
            with open(args.config, "r", encoding="utf-8") as f:
                config = json.load(f)
        for root in args.paths:
            if not os.path.isdir(root):
                print(f"verify_checkpoint: {root}: no such registry root")
                return 2
        rcs = [verify_registry(root, config, args.strict)
               for root in args.paths]
        return max(rcs)

    for path in args.paths:
        if not os.path.exists(path):
            print(f"verify_checkpoint: {path}: no such file or directory")
            return 2
    ckpts = expand(args.paths)
    if not ckpts:
        print("verify_checkpoint: no ckpt_*.msgpack files found")
        return 2

    failed = False
    for path in ckpts:
        status, detail = integrity.verify_checkpoint(path)
        print(f"{path}: {status} ({detail})")
        if status == integrity.CORRUPT or (
                args.strict and status != integrity.VERIFIED):
            failed = True
        manifest = integrity.read_manifest(path)
        if manifest and "mesh_spec" in manifest:
            spec = ",".join(f"{k}={v}"
                            for k, v in sorted(manifest["mesh_spec"].items()))
            layout = manifest.get("layout")
            suffix = f" (layout={layout})" if layout else ""
            print(f"{path}: mesh_spec {spec}{suffix}")
            ok, reason = integrity.validate_mesh_spec(manifest)
            if not ok:
                print(f"{path}: mesh_spec INVALID ({reason})")
                if args.strict:
                    failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
